"""fbtpu-locksmith: interprocedural lock-order & lockset analyzer for
the threaded control plane.

The paper's engine is one event loop; production growth added a
threaded control plane — collector threads and library callers append
under ``Engine._ingest_lock``, reload transactions serialize on
``_reload_lock``, the guard watchdog and QoS dispatch take their own
plane locks, DeviceLane workers and the fault registry run on worker
threads.  PR 7 needed six review rounds of hand-found races
(stop-vs-commit, retired-output reap, COW list swaps) to converge; this
pack catches that bug class mechanically at ``--all`` time.

Two cooperating analyses, both walking calls interprocedurally with
the same summary-fixpoint machinery:

**Lock acquisition-order graph.**  Every ``with <lock>:`` /
``.acquire()`` site contributes a node (a *canonical* lock id such as
``Engine._ingest_lock`` or ``device._lock`` — the same strings
``core.lockorder.make_lock`` records, so the tier-1 witness crosscheck
joins the static and dynamic worlds on them).  A site executed while
other locks are held contributes ``held -> acquired`` edges; calls
propagate the transitive acquire-set of the callee into the caller's
held context.  Cycles are reported as ``lock-order-cycle`` with a
witness site per edge.  Calls that cross the plugin boundary
(``self.plugin.*`` callbacks, ``sp.do``) cannot be resolved
name-by-name, so they contribute a declared *effect set* — the locks
any plugin callback may take (``PLUGIN_EFFECT``); metric instrument
calls (``self.m_*.inc``) contribute ``MetricsRegistry._lock``.

**Eraser-style lockset pass** against the guarded-by registry
(analysis/registry.py).  The lexical rule (analysis/locks.py) already
enforces ``with <lock>:`` around plain attribute *stores* and *reads*;
its blind spot is mutations that present the attribute in ``Load``
context — ``x.attr.pop(...)``, ``x.attr[k] = v``, ``del x.attr[k]`` —
which is exactly where ``writes_only`` entries leak.  Locksmith owns
that layer: ``guarded-field-unlocked`` fires on a Load-context
mutation of a registered ``writes_only`` field when the owning lock is
provably not held — neither lexically nor on every interprocedural
path into the function (a must-hold entry-lockset fixpoint over
observed call sites).  ``guarded-by-missing`` is the registry-gap
detector: a field mutated from ≥2 functions with *inconsistent*
locking (the classic Eraser signal: lockset intersection empty while
some site did lock) and no registry entry; its ``global`` arm flags a
module-level cache rebound via ``global`` in a module that owns a lock
but never registered the cache.  ``atomicity-check-then-act`` finds
the PR-7 stop/commit race shape: a guarded read whose lock is released
and re-acquired around a dependent write.  ``lock-held-across-dispatch``
extends PR 1's await-under-lock to the device/flush boundary: an
engine lock held (directly or through resolved calls) across a
DeviceLane launch or an output flush.  ``cow-swap-aliasing`` enforces
the copy-on-write discipline on the engine instance lists: readers
iterate ``engine.inputs``/``filters``/``outputs`` lock-free, so the
lists are replaced, never mutated in place.

Suppress any rule with ``# fbtpu-lint: allow(<rule>)`` + justification
(``guarded-field-unlocked`` also honors ``allow(guarded-by)`` — same
contract, different layer).  Shipped debt gates through the committed
``analysis/lock_baseline.json`` (the PR-3 ``(path, rule, message)``
key scheme); every entry is justified in ANALYSIS.md.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from . import Finding, Module, Rule
from .registry import GUARDS, GuardEntry

__all__ = [
    "LocksmithRules", "build_lock_graph", "lock_graph_to_dot",
    "static_order_edges", "graph_cycle_findings", "collect_modules",
]

#: Planes the order graph is built over (the threaded control plane:
#: engine/guard/qos/scheduler, the device attach controller and the
#: fault domain).  codec/ and native/ loader locks are deliberately
#: out of scope: leaf double-checked singletons, never nested.
ORDER_SCOPES = ("fluentbit_tpu/core/", "fluentbit_tpu/ops/",
                "fluentbit_tpu/flux/")

#: The lockset pass additionally covers the analyzer's own caches.
LOCKSET_SCOPES = ORDER_SCOPES + ("fluentbit_tpu/analysis/",)

#: Canonical lock ids constructed reentrant (RLock): a self-edge
#: through these is a re-entry, not a deadlock.
REENTRANT = frozenset({
    "Engine._ingest_lock", "InputInstance.ingest_lock",
    "MetricsRegistry._lock",
})

#: Lock attribute names unique to one home class/module: resolves
#: ``engine._ingest_lock`` / ``ins.ingest_lock`` seen from any module
#: without needing the receiver.  Keep in sync with the
#: ``core.lockorder.make_lock`` construction names — the tier-1
#: witness crosscheck fails on drift.
LOCK_HOMES = {
    "_ingest_lock": "Engine",
    "_reload_lock": "Engine",
    "_event_queue_lock": "Engine",
    "ingest_lock": "InputInstance",
    "_registry_lock": "fault",
    "_listener_lock": "fault",
}

#: Receiver variable name -> class, for ``<recv>._lock`` and
#: ``<recv>.method()`` resolution (the tree's naming conventions).
RECEIVER_CLASSES = {
    "engine": "Engine", "guard": "Guard", "qos": "Qos",
    "br": "CircuitBreaker", "breaker": "CircuitBreaker",
    "bucket": "TokenBucket", "lane": "DeviceLane",
    "metrics": "MetricsRegistry", "registry": "MetricsRegistry",
    "ins": "InputInstance", "src": "InputInstance",
    "inp": "InputInstance", "out": "OutputInstance",
}

#: Classes whose ``self._lock`` IS another class's lock (the metric
#: instruments share ``registry._lock``, core/metrics.py).
CLASS_CANON = {
    "_Metric": "MetricsRegistry", "Counter": "MetricsRegistry",
    "Gauge": "MetricsRegistry", "Histogram": "MetricsRegistry",
}

#: In-place mutator method names (present the receiver in Load ctx —
#: the lexical rule's blind spot).
MUTATORS = frozenset({
    "append", "extend", "add", "remove", "discard", "pop", "popleft",
    "clear", "update", "setdefault", "insert", "appendleft",
})

#: Locks a plugin callback (pause/resume/flush/cb_collect, ``sp.do``)
#: may transitively take.  Deliberately EXCLUDES ``Engine._ingest_lock``:
#: plugin callbacks never re-enter the engine append path holding it
#: (the parallel raw path takes only the input's own lock).
PLUGIN_EFFECT = frozenset({
    "InputInstance.ingest_lock", "Qos._lock", "TokenBucket._lock",
    "MetricsRegistry._lock", "DeviceLane._lock", "CircuitBreaker._lock",
    "fault._listener_lock", "fault._registry_lock", "device._lock",
})

#: ``self.m_*.inc/set/observe/set_max`` -> the metrics registry lock.
METRIC_TERMINALS = frozenset({"inc", "set", "observe", "set_max"})
METRIC_EFFECT = frozenset({"MetricsRegistry._lock"})

#: Engine locks that must never be held across a device dispatch or
#: an output flush (the watched-worker handoff can block on a device).
ENGINE_DISPATCH_LOCKS = frozenset({"Engine._ingest_lock",
                                   "Engine._reload_lock"})

#: COW instance lists: replaced, never mutated in place.  ``self.*``
#: counts only inside the classes that own the live lists (the plugin
#: Registry's same-named dicts are import-time state, not COW).
COW_ATTRS = frozenset({"inputs", "filters", "outputs"})
COW_SELF_CLASSES = frozenset({"Engine", "ReloadTxn"})

_SEVERITY = {
    "lock-order-cycle": "error",
    "guarded-field-unlocked": "error",
    "guarded-by-missing": "warning",
    "atomicity-check-then-act": "warning",
    "lock-held-across-dispatch": "warning",
    "cow-swap-aliasing": "error",
}

_CTOR_NAMES = frozenset({"__init__", "__new__"})


def _canon_path(path: str) -> str:
    p = path.replace(os.sep, "/")
    i = p.rfind("fluentbit_tpu/")
    return p[i:] if i >= 0 else p


def _module_stem(path: str) -> str:
    p = _canon_path(path)
    base = os.path.basename(p)
    if base == "__init__.py":
        parent = os.path.dirname(p)
        return os.path.basename(parent) or "module"
    return base[:-3] if base.endswith(".py") else base


def _chain_names(node: ast.AST) -> List[str]:
    """Names along an Attribute/Call chain, root first:
    ``self.qos.admit(x)`` -> ``["self", "qos", "admit"]``."""
    names: List[str] = []
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Name):
            names.append(node.id)
            break
        else:
            break
    return list(reversed(names))


def _walk_no_nested(body: List[ast.stmt]):
    """Walk statements/expressions without descending into nested
    function/lambda bodies (those get their own scope/scan)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                stack.append(child)


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


class _WithRec:
    """One ``with <lock>:`` block, for the check-then-act pairing."""

    __slots__ = ("locks", "line", "end_line", "loads", "stores",
                 "bound", "refs")

    def __init__(self, locks: FrozenSet[str], line: int, end_line: int):
        self.locks = locks
        self.line = line
        self.end_line = end_line
        self.loads: Set[str] = set()     # registered attrs read
        self.stores: Set[str] = set()    # registered attrs written/mutated
        self.bound: Set[str] = set()     # local names assigned inside
        self.refs: Set[str] = set()      # local names read inside


class _FnInfo:
    """Per-function summary: everything the fixpoints consume."""

    __slots__ = ("key", "mod", "cls", "name", "is_ctor", "lineno",
                 "acquires", "edges", "calls", "dispatches", "mutations",
                 "withrecs", "global_decls", "exit_lines")

    def __init__(self, key, mod, cls, name, lineno):
        self.key = key
        self.mod = mod
        self.cls = cls                       # canonical class or None
        self.name = name
        self.is_ctor = name in _CTOR_NAMES
        self.lineno = lineno
        #: canonical locks acquired directly in this body
        self.acquires: Set[str] = set()
        #: (held_lock, acquired_lock, line) — direct nesting
        self.edges: List[Tuple[str, str, int]] = []
        #: (callee_ref, frozenset(held), line); refs are
        #: ("local", key) / ("method", cls, name) / ("func", name) /
        #: ("effect", frozenset(locks), label)
        self.calls: List[Tuple[tuple, FrozenSet[str], int]] = []
        #: (line, frozenset(held), what) — lane launch / output flush
        self.dispatches: List[Tuple[int, FrozenSet[str], str]] = []
        #: (mutkind, scope, attr, recv_root, line, frozenset(held))
        #: mutkind: "store" (lexical rule's territory) | "loadmut"
        self.mutations: List[
            Tuple[str, str, str, str, int, FrozenSet[str]]] = []
        self.withrecs: List[_WithRec] = []
        self.global_decls: Set[str] = set()
        #: lines of return/raise statements (an exit between two with
        #: blocks means they sit in alternative branches, not in a
        #: released-and-reacquired sequence)
        self.exit_lines: Set[int] = set()


class _ModInfo:
    __slots__ = ("module", "canon", "stem", "tree", "fns", "classes",
                 "funcs", "has_lock_with", "top_lock_globals",
                 "top_globals", "registered")

    def __init__(self, module: Module):
        self.module = module
        self.canon = _canon_path(module.path)
        self.stem = _module_stem(module.path)
        self.tree = ast.parse(module.source)
        self.fns: Dict[tuple, _FnInfo] = {}
        self.classes: Dict[str, Dict[str, tuple]] = {}
        self.funcs: Dict[str, tuple] = {}
        self.has_lock_with = False
        #: module-level names bound to a lock CONSTRUCTION
        #: (threading.Lock()/RLock()/make_lock(...))
        self.top_lock_globals: Set[str] = set()
        #: every module-level bound name (the global-arm universe —
        #: a bare-name mutation inside a function is a *global*
        #: mutation only if the name actually lives at module level)
        self.top_globals: Set[str] = set()
        self.registered: Set[str] = set()
        for stmt in self.tree.body:
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target] if isinstance(stmt, ast.AnnAssign) \
                else []
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                self.top_globals.add(t.id)
                v = stmt.value
                if isinstance(v, ast.Call) and _terminal(v.func) \
                        in ("Lock", "RLock", "make_lock"):
                    self.top_lock_globals.add(t.id)


class _FnScan:
    """Lexical walk of one function body: tracks the held lock set
    through ``with`` nesting, records acquisition edges, call sites
    with held context, dispatch sites, and mutations."""

    def __init__(self, analyzer: "_Analyzer", mod: _ModInfo,
                 info: _FnInfo, aliases: Dict[str, FrozenSet[str]],
                 plugin_aliases: Set[str], local_defs: Dict[str, tuple]):
        self.a = analyzer
        self.mod = mod
        self.info = info
        self.aliases = dict(aliases)
        self.plugin_aliases = set(plugin_aliases)
        self.local_defs = dict(local_defs)
        self.local_names: Set[str] = set()

    # -- lock canonicalization ----------------------------------------

    def canon_lock(self, expr: ast.AST) -> FrozenSet[str]:
        if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
            attr = expr.attr
            if attr in LOCK_HOMES:
                return frozenset({f"{LOCK_HOMES[attr]}.{attr}"})
            recv = _chain_names(expr.value)
            if recv:
                t = recv[-1]
                if t == "self" and self.info.cls:
                    return frozenset({f"{self.info.cls}.{attr}"})
                if t in RECEIVER_CLASSES:
                    return frozenset({f"{RECEIVER_CLASSES[t]}.{attr}"})
            return frozenset()
        if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
            if expr.id in self.aliases:
                return self.aliases[expr.id]
            if expr.id in LOCK_HOMES:
                return frozenset({f"{LOCK_HOMES[expr.id]}.{expr.id}"})
            if expr.id.startswith("_"):
                return frozenset({f"{self.mod.stem}.{expr.id}"})
        return frozenset()

    def _lock_refs_in(self, expr: ast.AST) -> FrozenSet[str]:
        out: Set[str] = set()
        for n in ast.walk(expr):
            if isinstance(n, (ast.Attribute, ast.Name)):
                out |= self.canon_lock(n)
        return frozenset(out)

    # -- prepasses -----------------------------------------------------

    def prepass(self, body: List[ast.stmt]) -> None:
        """Alias + plugin-alias discovery (function-scoped, flow
        insensitive: an if/else alias carries both candidates)."""
        for node in _walk_no_nested(body):
            if isinstance(node, ast.Global):
                self.info.global_decls.update(node.names)
            if not isinstance(node, ast.Assign):
                continue
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if not targets:
                continue
            refs = self._lock_refs_in(node.value)
            if refs:
                for t in targets:
                    self.aliases[t] = self.aliases.get(
                        t, frozenset()) | refs
            chain = {n for sub in ast.walk(node.value)
                     for n in ([sub.attr] if isinstance(sub, ast.Attribute)
                               else [sub.id] if isinstance(sub, ast.Name)
                               else [])}
            if "plugin" in chain:
                self.plugin_aliases.update(targets)
        # plain-name Store targets (locals unless declared global)
        for node in _walk_no_nested(body):
            if isinstance(node, ast.Name) and \
                    not isinstance(node.ctx, ast.Load) and \
                    node.id not in self.info.global_decls:
                self.local_names.add(node.id)

    # -- statement walk ------------------------------------------------

    def run(self, body: List[ast.stmt]) -> None:
        self.prepass(body)
        self._stmts(body, frozenset())

    def _stmts(self, body: List[ast.stmt], held: FrozenSet[str]) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            locks: Set[str] = set()
            for item in stmt.items:
                self._expr(item.context_expr, new_held)
                canon = self.canon_lock(item.context_expr)
                if canon:
                    self.mod.has_lock_with = True
                    self.info.acquires |= canon
                    for h in new_held:
                        for b in canon:
                            if b != h:
                                self.info.edges.append(
                                    (h, b, stmt.lineno))
                            elif b not in REENTRANT:
                                self.a.self_deadlocks.append(
                                    (self.mod, stmt.lineno, b,
                                     f"{self.info.name}()"))
                    new_held = new_held | canon
                    locks |= canon
            if locks:
                rec = _WithRec(frozenset(locks), stmt.lineno,
                               getattr(stmt, "end_lineno", stmt.lineno))
                self._fill_withrec(rec, stmt.body)
                self.info.withrecs.append(rec)
            self._stmts(stmt.body, new_held)
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._target(stmt.target, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.While):
            self._expr(stmt.test, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, held)
            for h in stmt.handlers:
                self._stmts(h.body, held)
            self._stmts(stmt.orelse, held)
            self._stmts(stmt.finalbody, held)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.a.scan_function(
                self.mod, stmt, self.info.cls,
                qual=f"{self.info.name}.{stmt.name}",
                aliases=self.aliases,
                plugin_aliases=self.plugin_aliases)
            self.local_defs[stmt.name] = (
                self.mod.canon, self.info.cls,
                f"{self.info.name}.{stmt.name}")
        elif isinstance(stmt, ast.Assign):
            self._expr(stmt.value, held)
            for t in stmt.targets:
                self._target(t, held)
        elif isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, held)
            self._target(stmt.target, held)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, held)
            self._target(stmt.target, held)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._target(t, held, deleting=True)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if isinstance(stmt, ast.Return):
                self.info.exit_lines.add(stmt.lineno)
            if stmt.value is not None:
                self._expr(stmt.value, held)
        elif isinstance(stmt, ast.Assert):
            self._expr(stmt.test, held)
        elif isinstance(stmt, ast.Raise):
            self.info.exit_lines.add(stmt.lineno)
            if stmt.exc is not None:
                self._expr(stmt.exc, held)
        elif isinstance(stmt, ast.Global):
            self.info.global_decls.update(stmt.names)
        # Pass/Break/Continue/Import/Nonlocal: nothing to track

    def _target(self, t: ast.AST, held: FrozenSet[str],
                deleting: bool = False) -> None:
        """Assignment/del target: classify the mutation."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e, held, deleting)
        elif isinstance(t, ast.Starred):
            self._target(t.value, held, deleting)
        elif isinstance(t, ast.Attribute):
            recv = _chain_names(t.value)
            root = recv[-1] if recv else ""
            self._mutation("store", "attr", t.attr, root,
                           t.lineno, held)
        elif isinstance(t, ast.Subscript):
            self._expr(t.slice, held)
            base = t.value
            if isinstance(base, ast.Attribute):
                recv = _chain_names(base.value)
                self._mutation("loadmut", "attr", base.attr,
                               recv[-1] if recv else "",
                               t.lineno, held)
            elif isinstance(base, ast.Name) and self._is_global(base.id):
                self._mutation("loadmut", "global", base.id, "",
                               t.lineno, held)
            else:
                self._expr(base, held)
        elif isinstance(t, ast.Name):
            if t.id in self.info.global_decls:
                self._mutation("store", "global", t.id, "",
                               t.lineno, held)

    def _is_global(self, name: str) -> bool:
        """A bare-name mutation is a *module-global* mutation only if
        the name is declared ``global`` here or bound at module level
        (locals shadow: a local rebinding hides the module name)."""
        return name in self.info.global_decls or (
            name in self.mod.top_globals and
            name not in self.local_names)

    def _mutation(self, mutkind: str, scope: str, name: str,
                  recv_root: str, line: int,
                  held: FrozenSet[str]) -> None:
        self.info.mutations.append(
            (mutkind, scope, name, recv_root, line, held))

    def _expr(self, expr: ast.AST, held: FrozenSet[str]) -> None:
        """Expression walk: record call sites / dispatches / mutator
        calls with the current held set."""
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            t = _terminal(node.func)
            if t is None:
                continue
            chain = _chain_names(node.func)
            line = node.lineno
            if t == "acquire" and isinstance(node.func, ast.Attribute):
                canon = self.canon_lock(node.func.value)
                if canon:
                    self.info.acquires |= canon
                    for h in held:
                        for b in canon:
                            if b != h:
                                self.info.edges.append((h, b, line))
                            elif b not in REENTRANT:
                                self.a.self_deadlocks.append(
                                    (self.mod, line, b,
                                     f"{self.info.name}()"))
                    continue
            # dispatch boundary: DeviceLane launch / output flush
            if t in ("run", "begin") and any(
                    "lane" in n.lower() for n in chain[:-1]):
                self.info.dispatches.append((line, held, f"lane.{t}"))
            elif t == "flush" and any(
                    n == "out" or n.startswith("out")
                    for n in chain[:-1]):
                self.info.dispatches.append((line, held, "output.flush"))
            # mutator-method call: x.attr.pop(...) — Load-ctx mutation
            if t in MUTATORS and isinstance(node.func, ast.Attribute):
                base = node.func.value
                if isinstance(base, ast.Attribute):
                    recv = _chain_names(base.value)
                    self._mutation("loadmut", "attr", base.attr,
                                   recv[-1] if recv else "",
                                   line, held)
                elif isinstance(base, ast.Name) and \
                        self._is_global(base.id):
                    self._mutation("loadmut", "global", base.id, "",
                                   line, held)
            # callee resolution
            ref = self._callee_ref(node.func, chain)
            if ref is not None:
                self.info.calls.append((ref, held, line))

    def _callee_ref(self, func: ast.AST,
                    chain: List[str]) -> Optional[tuple]:
        t = _terminal(func)
        # metric instruments: self.m_foo.inc(...) et al.
        if t in METRIC_TERMINALS and any(
                n.startswith("m_") for n in chain[:-1]):
            return ("effect", METRIC_EFFECT, "metric")
        # plugin boundary: unresolvable by name -> declared effect set
        if len(chain) > 1 and (
                "plugin" in chain[:-1] or "sp" in chain[:-1]
                or chain[0] in self.plugin_aliases):
            return ("effect", PLUGIN_EFFECT, "plugin")
        if isinstance(func, ast.Name):
            if func.id in self.local_defs:
                return ("local", self.local_defs[func.id])
            if func.id in self.plugin_aliases:
                return ("effect", PLUGIN_EFFECT, "plugin")
            return ("func", func.id)
        if isinstance(func, ast.Attribute) and len(chain) >= 2:
            prev = chain[-2]
            if prev == "self" and self.info.cls:
                return ("method", self.info.cls, t)
            if prev in RECEIVER_CLASSES:
                return ("method", RECEIVER_CLASSES[prev], t)
        return None

    # -- check-then-act bookkeeping -----------------------------------

    def _fill_withrec(self, rec: _WithRec,
                      body: List[ast.stmt]) -> None:
        registered = self.mod.registered
        wrapper = ast.Module(body=body, type_ignores=[])
        for node in ast.walk(wrapper):
            if isinstance(node, ast.Attribute):
                if node.attr in registered:
                    if isinstance(node.ctx, ast.Load):
                        rec.loads.add(node.attr)
                    else:
                        rec.stores.add(node.attr)
            elif isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    rec.refs.add(node.id)
                else:
                    rec.bound.add(node.id)
            elif isinstance(node, ast.Call):
                t = _terminal(node.func)
                if t in MUTATORS and isinstance(node.func, ast.Attribute):
                    base = node.func.value
                    if isinstance(base, ast.Attribute) and \
                            base.attr in registered:
                        rec.stores.add(base.attr)
            elif isinstance(node, ast.Subscript) and \
                    not isinstance(node.ctx, ast.Load):
                if isinstance(node.value, ast.Attribute) and \
                        node.value.attr in registered:
                    rec.stores.add(node.value.attr)


class _Analyzer:
    """Whole-program (or single-module) lock analysis over a module
    set: builds per-function summaries, runs the acquire-set /
    dispatch / must-hold fixpoints, generates the order graph, and
    emits findings."""

    def __init__(self, modules: Iterable[Module],
                 guards: Tuple[GuardEntry, ...] = GUARDS):
        self.guards = guards
        self.mods: List[_ModInfo] = []
        self.fns: Dict[tuple, _FnInfo] = {}
        #: canonical class name -> {method -> fn key}
        self.class_index: Dict[str, Dict[str, tuple]] = {}
        self.self_deadlocks: List[Tuple[_ModInfo, int, str, str]] = []
        for m in modules:
            mi = _ModInfo(m)
            mi.registered = {
                a for e in guards if mi.canon.endswith(e.module)
                for a in e.attrs
            }
            self.mods.append(mi)
            self._scan_module(mi)
        self._fix_acquires()
        self._fix_dispatches()
        self._fix_must_entry()
        self._find_call_self_deadlocks()

    # -- scanning ------------------------------------------------------

    def _scan_module(self, mi: _ModInfo) -> None:
        for stmt in mi.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = self.scan_function(mi, stmt, None, stmt.name)
                mi.funcs[stmt.name] = key
            elif isinstance(stmt, ast.ClassDef):
                canon = CLASS_CANON.get(stmt.name, stmt.name)
                methods = self.class_index.setdefault(canon, {})
                mi.classes.setdefault(canon, {})
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        key = self.scan_function(
                            mi, sub, canon, f"{stmt.name}.{sub.name}")
                        methods[sub.name] = key
                        mi.classes[canon][sub.name] = key

    def scan_function(self, mi: _ModInfo, node, cls: Optional[str],
                      qual: str, aliases=None,
                      plugin_aliases=None) -> tuple:
        key = (mi.canon, cls, qual)
        info = _FnInfo(key, mi, cls, node.name, node.lineno)
        mi.fns[key] = info
        self.fns[key] = info
        scan = _FnScan(self, mi, info, aliases or {},
                       plugin_aliases or set(), {})
        args = node.args
        for a in (list(getattr(args, "posonlyargs", [])) + args.args
                  + args.kwonlyargs + [args.vararg, args.kwarg]):
            if a is not None:
                scan.local_names.add(a.arg)
        scan.run(node.body)
        return key

    # -- resolution ----------------------------------------------------

    def resolve(self, ref: tuple, fn: _FnInfo) -> Optional[tuple]:
        kind = ref[0]
        if kind == "local":
            return ref[1] if ref[1] in self.fns else None
        if kind == "method":
            _, cls, name = ref
            return self.class_index.get(cls, {}).get(name)
        if kind == "func":
            return fn.mod.funcs.get(ref[1])
        return None

    # -- fixpoints -----------------------------------------------------

    def _fix_acquires(self) -> None:
        self.AC: Dict[tuple, Set[str]] = {
            k: set(f.acquires) for k, f in self.fns.items()}
        changed = True
        while changed:
            changed = False
            for k, f in self.fns.items():
                s = self.AC[k]
                before = len(s)
                for ref, _held, _line in f.calls:
                    if ref[0] == "effect":
                        s |= ref[1]
                    else:
                        g = self.resolve(ref, f)
                        if g is not None:
                            s |= self.AC[g]
                if len(s) != before:
                    changed = True

    def _fix_dispatches(self) -> None:
        """dispatches*(f): does f (transitively, via RESOLVED calls
        only — not effect sets) reach a dispatch boundary?"""
        self.DISP: Dict[tuple, bool] = {
            k: bool(f.dispatches) for k, f in self.fns.items()}
        changed = True
        while changed:
            changed = False
            for k, f in self.fns.items():
                if self.DISP[k]:
                    continue
                for ref, _held, _line in f.calls:
                    if ref[0] == "effect":
                        continue
                    g = self.resolve(ref, f)
                    if g is not None and self.DISP[g]:
                        self.DISP[k] = True
                        changed = True
                        break

    def _fix_must_entry(self) -> None:
        """must_entry(f): locks held on EVERY observed interprocedural
        path into f.  Public names are roots (empty set: anyone may
        call them bare); private names intersect over observed call
        sites.  Private with no observed site -> empty (conservative)."""
        callers: Dict[tuple, List[Tuple[tuple, FrozenSet[str]]]] = {}
        for k, f in self.fns.items():
            for ref, held, _line in f.calls:
                if ref[0] == "effect":
                    continue
                g = self.resolve(ref, f)
                if g is not None:
                    callers.setdefault(g, []).append((k, held))
        TOP = None  # lattice top: unknown-yet
        self.ME: Dict[tuple, Optional[FrozenSet[str]]] = {}
        for k, f in self.fns.items():
            leaf = f.name.split(".")[-1]
            if not leaf.startswith("_") or leaf.startswith("__") or \
                    k not in callers:
                self.ME[k] = frozenset()
            else:
                self.ME[k] = TOP
        changed = True
        while changed:
            changed = False
            for k in self.fns:
                if self.ME[k] == frozenset():
                    continue
                acc: Optional[FrozenSet[str]] = TOP
                for caller, held in callers.get(k, ()):
                    me = self.ME.get(caller)
                    site = held | me if me is not None else None
                    if site is None:
                        continue  # unknown caller: no constraint yet
                    acc = site if acc is None else (acc & site)
                if acc is not None and acc != self.ME[k]:
                    self.ME[k] = acc
                    changed = True
        for k, v in self.ME.items():
            if v is None:
                self.ME[k] = frozenset()

    def must_held(self, f: _FnInfo,
                  held: FrozenSet[str]) -> FrozenSet[str]:
        return held | self.ME.get(f.key, frozenset())

    def _find_call_self_deadlocks(self) -> None:
        """Interprocedural self-reacquire: a call made while holding a
        non-reentrant lock whose (transitive) callee may acquire that
        same lock.  The lexical case is caught at scan time; this pass
        closes the gap where the re-acquire hides behind a call."""
        for f in self.fns.values():
            for ref, held, line in f.calls:
                if not held:
                    continue
                if ref[0] == "effect":
                    acq, via = ref[1], ref[2]
                else:
                    g = self.resolve(ref, f)
                    if g is None:
                        continue
                    acq, via = self.AC[g], self.fns[g].name
                for h in held:
                    if h in acq and h not in REENTRANT:
                        self.self_deadlocks.append(
                            (f.mod, line, h,
                             f"{f.name}() via {via}()"))

    # -- order graph ---------------------------------------------------

    def order_edges(self) -> Dict[Tuple[str, str],
                                  List[Tuple[str, int, str]]]:
        """(held, acquired) -> witness list [(module, line, via)]."""
        edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}

        def add(a, b, mod, line, via):
            edges.setdefault((a, b), []).append((mod, line, via))

        for f in self.fns.values():
            for a, b, line in f.edges:
                add(a, b, f.mod.canon, line, f.name)
            for ref, held, line in f.calls:
                if not held:
                    continue
                if ref[0] == "effect":
                    acq, via = ref[1], ref[2]
                else:
                    g = self.resolve(ref, f)
                    if g is None:
                        continue
                    acq = self.AC[g]
                    via = self.fns[g].name
                for h in held:
                    for b in acq:
                        if b != h:
                            add(h, b, f.mod.canon, line, via)
        return edges

    def order_nodes(self) -> Set[str]:
        nodes: Set[str] = set()
        for f in self.fns.values():
            nodes |= f.acquires
        for (a, b) in self.order_edges():
            nodes.add(a)
            nodes.add(b)
        return nodes

    def cycles(self) -> List[List[str]]:
        """Elementary cycles via SCC decomposition (each non-trivial
        SCC reported once, as a deterministic closed walk)."""
        edges = self.order_edges()
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        sccs = _tarjan(adj)
        out: List[List[str]] = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            comp_sorted = sorted(comp)
            # deterministic closed walk: follow in-component edges
            walk = [comp_sorted[0]]
            cur = comp_sorted[0]
            seen = {cur}
            while True:
                nxts = sorted(n for n in adj.get(cur, ())
                              if n in comp and n not in seen)
                back = [n for n in adj.get(cur, ()) if n == walk[0]]
                if nxts:
                    cur = nxts[0]
                    seen.add(cur)
                    walk.append(cur)
                elif back or len(seen) == len(comp):
                    break
                else:
                    break
            walk.append(walk[0])
            out.append(walk)
        return out

    # -- findings ------------------------------------------------------

    def findings(self, cycle_mode: str = "all",
                 only_cycles: bool = False) -> List[Finding]:
        """``cycle_mode``: which order cycles to report — "all",
        "intra" (single-module), or "cross" (spanning modules, the
        whole-program complement of the per-module rule pass)."""
        out: List[Finding] = []
        flagged: Set[Tuple[str, int, str]] = set()

        def emit(mod: _ModInfo, line: int, rule: str, msg: str,
                 also_allow: Tuple[str, ...] = ()) -> None:
            if (mod.canon, line, rule) in flagged:
                return
            for r in (rule,) + also_allow:
                if mod.module.allowed(r, line):
                    return
            flagged.add((mod.canon, line, rule))
            out.append(Finding(mod.module.path, line, 0, rule, msg,
                               _SEVERITY[rule]))

        self._cycle_findings(out, emit, cycle_mode)
        if not only_cycles:
            self._lockset_findings(emit)
            self._missing_findings(emit)
            self._atomicity_findings(emit)
            self._dispatch_findings(emit)
            self._cow_findings(emit)
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out

    def _mod_by_canon(self, canon: str) -> Optional[_ModInfo]:
        for m in self.mods:
            if m.canon == canon:
                return m
        return None

    def _cycle_findings(self, out, emit, cycle_mode: str) -> None:
        edges = self.order_edges()
        if cycle_mode != "cross":
            # self-deadlocks are reported from the holding module
            for mod, line, lock, fname in self.self_deadlocks:
                emit(mod, line, "lock-order-cycle",
                     f"non-reentrant lock {lock} re-acquired while "
                     f"already held in {fname} — self-deadlock")
        for walk in self.cycles():
            wit = []
            mods_involved = set()
            for a, b in zip(walk, walk[1:]):
                w = edges.get((a, b))
                if w:
                    m, ln, via = w[0]
                    wit.append(f"{a} -> {b} ({m.split('/')[-1]}:{ln} "
                               f"via {via})")
                    mods_involved.add(m)
                else:
                    wit.append(f"{a} -> {b}")
            first = None
            for a, b in zip(walk, walk[1:]):
                if edges.get((a, b)):
                    first = edges[(a, b)][0]
                    break
            if first is None:
                continue
            mod = self._mod_by_canon(first[0])
            if mod is None:
                continue
            intra = len(mods_involved) <= 1
            if cycle_mode == "all" or \
                    (cycle_mode == "cross") != intra:
                emit(mod, first[1], "lock-order-cycle",
                     "lock acquisition order cycle: " + "; ".join(wit))

    def _entry_for(self, mod: _ModInfo, name: str,
                   kind: str) -> Optional[GuardEntry]:
        for e in self.guards:
            if mod.canon.endswith(e.module) and e.kind == kind and \
                    name in e.attrs:
                return e
        return None

    def _lockset_findings(self, emit) -> None:
        """guarded-field-unlocked: Load-context mutation of a
        registered writes_only field, owning lock not held lexically
        nor on every interprocedural path in."""
        for f in self.fns.values():
            if f.is_ctor:
                continue
            for mutkind, scope, name, _root, line, held in f.mutations:
                if mutkind != "loadmut":
                    continue
                kind = "attr" if scope == "attr" else "global"
                e = self._entry_for(f.mod, name, kind)
                if e is None or not e.writes_only:
                    continue
                names_held = {h.split(".")[-1]
                              for h in self.must_held(f, held)}
                if e.lock not in names_held:
                    what = "global" if kind == "global" else "field"
                    emit(f.mod, line, "guarded-field-unlocked",
                         f"{what} {name!r} mutated in place without "
                         f"holding {e.lock!r} (registered "
                         f"writes_only; in-place mutation IS a write)"
                         + (f" — {e.note}" if e.note else ""),
                         also_allow=("guarded-by",))

    def _missing_findings(self, emit) -> None:
        """guarded-by-missing: Eraser registry-gap detection."""
        for mi in self.mods:
            # attr arm: inconsistent locking across >=2 functions
            per_attr: Dict[str, List[tuple]] = {}
            for f in mi.fns.values():
                if f.is_ctor:
                    continue
                for mutkind, scope, name, root, line, held in f.mutations:
                    if scope != "attr" or root not in ("self",) + \
                            tuple(RECEIVER_CLASSES):
                        continue
                    if name in mi.registered or name in COW_ATTRS or \
                            "lock" in name.lower() or \
                            name.startswith("m_") or \
                            name.startswith("__"):
                        continue
                    names_held = frozenset(
                        h.split(".")[-1]
                        for h in self.must_held(f, held))
                    per_attr.setdefault(name, []).append(
                        (f.key, line, names_held))
            if mi.has_lock_with:
                for name, sites in sorted(per_attr.items()):
                    fns = {k for k, _l, _h in sites}
                    if len(fns) < 2:
                        continue
                    locked = [h for _k, _l, h in sites if h]
                    inter = frozenset.intersection(
                        *[h for _k, _l, h in sites])
                    if locked and not inter:
                        k, line, h = min(
                            (s for s in sites if not s[2]),
                            default=sites[0], key=lambda s: s[1])
                        emit(mi, line, "guarded-by-missing",
                             f"field {name!r} mutated from "
                             f"{len(fns)} functions with inconsistent "
                             f"locking (lockset intersection empty) "
                             f"and no guarded-by registry entry")
            # global arm: module owns a lock, a function rebinds an
            # unregistered module global
            if not mi.top_lock_globals:
                continue
            for f in mi.fns.values():
                for mutkind, scope, name, _root, line, held in \
                        f.mutations:
                    if scope != "global" or name in mi.registered or \
                            "lock" in name.lower():
                        continue
                    emit(mi, line, "guarded-by-missing",
                         f"module global {name!r} rebound/mutated in "
                         f"{f.name}() but absent from the guarded-by "
                         f"registry (module owns "
                         f"{sorted(mi.top_lock_globals)[0]!r})")

    def _atomicity_findings(self, emit) -> None:
        """atomicity-check-then-act: guarded read, lock released, then
        a dependent guarded write under a fresh acquisition."""
        for f in self.fns.values():
            recs = f.withrecs
            for i, a in enumerate(recs):
                for b in recs[i + 1:]:
                    if b.line <= a.end_line:
                        continue  # nested, not sequential
                    if any(a.end_line < ln < b.line
                           for ln in f.exit_lines):
                        continue  # alternative branches, not a
                        # release-then-reacquire sequence
                    if not (a.locks & b.locks):
                        continue
                    fields = a.loads & b.stores
                    if not fields:
                        continue
                    if not (a.bound & b.refs):
                        continue  # no dataflow from check to act
                    if b.loads:
                        # the act re-reads guarded state under the
                        # re-acquired lock: a validated double-check
                        # (the current_mesh pattern), not a blind
                        # write from stale values
                        continue
                    lock = sorted(a.locks & b.locks)[0]
                    emit(f.mod, b.line, "atomicity-check-then-act",
                         f"check-then-act on {sorted(fields)[0]!r}: "
                         f"read under {lock} at line {a.line}, "
                         f"dependent write re-acquires it here — the "
                         f"state may have changed between the blocks")

    def _dispatch_findings(self, emit) -> None:
        for f in self.fns.values():
            for line, held, what in f.dispatches:
                bad = held & ENGINE_DISPATCH_LOCKS
                if bad:
                    emit(f.mod, line, "lock-held-across-dispatch",
                         f"{sorted(bad)[0]} held across {what} — the "
                         f"device/flush boundary can block; release "
                         f"before dispatching")
            for ref, held, line in f.calls:
                if ref[0] == "effect":
                    continue
                bad = held & ENGINE_DISPATCH_LOCKS
                if not bad:
                    continue
                g = self.resolve(ref, f)
                if g is not None and self.DISP[g]:
                    emit(f.mod, line, "lock-held-across-dispatch",
                         f"{sorted(bad)[0]} held across call to "
                         f"{self.fns[g].name}() which reaches a "
                         f"device/flush dispatch boundary")

    def _cow_findings(self, emit) -> None:
        for f in self.fns.values():
            if f.is_ctor:
                continue
            for mutkind, scope, name, root, line, held in f.mutations:
                if mutkind != "loadmut" or scope != "attr":
                    continue
                cow_recv = root == "engine" or (
                    root == "self" and f.cls in COW_SELF_CLASSES)
                if name in COW_ATTRS and cow_recv:
                    emit(f.mod, line, "cow-swap-aliasing",
                         f"COW list {name!r} mutated in place — "
                         f"lock-free readers iterate a stale alias; "
                         f"build a new list and replace the "
                         f"reference instead")


def _tarjan(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)
    return sccs


# -- whole-program entry points ---------------------------------------


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_modules(root: Optional[str] = None,
                    scopes: Tuple[str, ...] = ORDER_SCOPES
                    ) -> List[Module]:
    """Every scoped source module under the package root."""
    root = root or _package_root()
    mods: List[Module] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__",))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            canon = _canon_path(path)
            if not any(canon.startswith(s) for s in scopes):
                continue
            with open(path, "r", encoding="utf-8") as fh:
                mods.append(Module(path, fh.read()))
    return mods


def build_lock_graph(root: Optional[str] = None) -> Dict:
    """The whole-program lock acquisition-order graph (the ``--graph
    lock`` payload and the witness crosscheck's static side)."""
    a = _Analyzer(collect_modules(root))
    edges = a.order_edges()
    return {
        "version": 1,
        "nodes": sorted(a.order_nodes()),
        "edges": [
            {
                "from": e[0], "to": e[1],
                "witness": [
                    {"module": m, "line": ln, "via": via}
                    for m, ln, via in sorted(set(w))[:4]
                ],
            }
            for e, w in sorted(edges.items())
        ],
        "cycles": a.cycles(),
    }


def static_order_edges(root: Optional[str] = None
                       ) -> Set[Tuple[str, str]]:
    """The static edge set the dynamic witness must be a subset of."""
    g = build_lock_graph(root)
    return {(e["from"], e["to"]) for e in g["edges"]}


def graph_cycle_findings(root: Optional[str] = None) -> List[Finding]:
    """Whole-program CROSS-module cycle findings — the complement of
    the per-module rule pass (which sees intra-module cycles only),
    for ``--all``."""
    a = _Analyzer(collect_modules(root))
    return a.findings(cycle_mode="cross", only_cycles=True)


def lock_graph_to_dot(graph: Dict) -> str:
    lines = ["digraph lock_order {", "  rankdir=LR;",
             '  node [shape=box, fontname="monospace"];']
    cyc_nodes = {n for walk in graph.get("cycles", []) for n in walk}
    for n in graph["nodes"]:
        style = ', style=filled, fillcolor="#ffcccc"' \
            if n in cyc_nodes else ""
        lines.append(f'  "{n}" [label="{n}"{style}];')
    for e in graph["edges"]:
        w = e["witness"][0] if e["witness"] else None
        label = f'{w["module"].split("/")[-1]}:{w["line"]}' if w else ""
        lines.append(f'  "{e["from"]}" -> "{e["to"]}" '
                     f'[label="{label}", fontsize=8];')
    lines.append("}")
    return "\n".join(lines)


class LocksmithRules(Rule):
    """The concurrency pack: per-module lockset + intra-module order
    analysis (whole-program cycles ride ``--all`` via
    :func:`graph_cycle_findings`)."""

    RULE_NAMES = (
        "lock-order-cycle", "guarded-field-unlocked",
        "guarded-by-missing", "atomicity-check-then-act",
        "lock-held-across-dispatch", "cow-swap-aliasing",
    )
    name = RULE_NAMES
    description = ("interprocedural lock-order & Eraser-lockset "
                   "analysis over the threaded control plane")

    def __init__(self, guards: Optional[Tuple[GuardEntry, ...]] = None):
        self.guards = tuple(guards) if guards is not None else GUARDS

    def check(self, module: Module) -> List[Finding]:
        canon = _canon_path(module.path)
        if canon.startswith("fluentbit_tpu/") and not any(
                canon.startswith(s) for s in LOCKSET_SCOPES):
            return []
        try:
            a = _Analyzer([module], self.guards)
        except SyntaxError:
            return []
        # per-module pass: cycles here are intra-module by construction
        return a.findings(cycle_mode="all")
