"""Silent-failure rule.

``swallowed-error``: a broad ``except Exception: pass`` (or bare
``except:`` / ``except BaseException:``) on a data-path module turns
every future bug at that site into silently dropped telemetry — the
exact failure mode this pipeline exists to prevent. Narrow handlers
(``except OSError: pass`` on a close path) are deliberate and stay
legal; broad ones must either do something observable (log, metrics
increment — any non-trivial body passes) or carry a justified
``# fbtpu-lint: allow(swallowed-error)`` suppression.
"""

from __future__ import annotations

import ast
from typing import List

from . import Finding, Module, Rule

__all__ = ["SwallowedErrorRule"]

#: module path fragments that put a file on the data path
DATA_PATH_PREFIXES = (
    "fluentbit_tpu/core/",
    "fluentbit_tpu/codec/",
    "fluentbit_tpu/plugins/",
    "fluentbit_tpu/ops/",
    "fluentbit_tpu/native/",
)

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:  # bare except
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    return False


def _is_trivial(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


class SwallowedErrorRule(Rule):
    name = "swallowed-error"
    description = ("broad `except ...: pass` on a data-path module — "
                   "narrow the type, count it, or justify the swallow")

    def check(self, module: Module) -> List[Finding]:
        if not any(p in module.path for p in DATA_PATH_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type) or not _is_trivial(node.body):
                continue
            shown = (ast.unparse(node.type) if node.type is not None
                     else "")
            f = self.finding(
                module, node,
                f"broad `except {shown or 'bare'}: pass` swallows real "
                f"errors on the data path — narrow the exception type, "
                f"log it, or increment a metric",
                extra_lines=tuple(s.lineno for s in node.body[:1]))
            if f is not None:
                out.append(f)
        return out
