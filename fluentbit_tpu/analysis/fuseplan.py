"""fbtpu-fuseplan: the device-chain fusion planner and cross-launch
effect analyzer.

fbtpu-xray (analysis/launchgraph.py) made launches-per-segment visible
and gated; this module makes the *next move* reviewable: for every
device chain it reconstructs the launch sequence with the same
per-chain walker and classifies each **boundary between consecutive
launches** as FUSABLE or BLOCKED, with the pinpointed reason a fusion
PR must clear first:

- a host ``compact`` scatter between the launches (the verdict came
  home just to re-index bytes the next launch re-uploads) — BLOCKED,
  ``fusion-blocked-by-host-compact``;
- an intervening host mutation or effect — a metrics ``.inc()``/
  ``.observe()``, a qos ``admit``/``shed`` call, a lock acquisition
  (``.acquire()`` / ``with <lock>``) — a merged program would reorder
  it across the launch it used to follow, so the region is proposed
  but unsound: ``fused-effect-violation`` (error). The failpoint
  plane's ``fire`` is whitelisted: disarmed sites are inert by the
  tier-1 ``test_disabled_plane_adds_no_work`` contract;
- dtype/shape/PartitionSpec incompatibility of the two programs'
  shared input avals at the canonical ``BUDGET_PARAMS`` point
  (fbtpu-speccheck's lattice — a fused program stages each shared
  buffer once, so the two sides must agree on its aval exactly);
- re-staging of bytes already resident on device (an ``asarray``/
  ``stage_field`` between the launches over a buffer the producer
  already uploaded): not blocking — it is the cost the merge deletes —
  but reported as ``cross-launch-restage``;
- donation aliasing a merged program would preserve or break: a
  producer-donated input the consumer still re-reads with a different
  aval cannot alias in the merged program — BLOCKED,
  ``donation-break``.

A boundary with no blocking reason is FUSABLE and reports
``fusable-unfused-boundary`` — the planner then prices the *planned*
fused program (FUSABLE runs merged into one launch; shared h2d
buffers staged once) and the committed ``analysis/fusion_plan.json``
gates it the same way ``launch_budget.json`` gates the measured
graph: boundaries may only disappear, planned launches and planned
un-donated bytes may only shrink, a FUSABLE verdict may not silently
turn BLOCKED (``fusion-plan-regression``).

The first finding this planner produced is cashed in the same PR: the
flux 3-launch sketch/window chain (counts, per-field HLL, count-min)
is now ONE ``shard_map`` program (``flux/kernels.build_fused_absorb``)
— the shipped tree's plan therefore holds zero open boundaries, and
the file's job is to keep it that way.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from . import Finding, Module, Rule
from .launchgraph import (SCATTER_NAMES, SCOPES, TRANSFER_SHAPES,
                          _chain_names, _eval_bytes, _ModuleScan,
                          _terminal, canonical_env)

__all__ = [
    "FuseplanRules", "build_fusion_plan", "plan_snapshot",
    "compare_fusion_plan", "fusion_plan_to_dot", "classify_boundaries",
]

#: launch-site kind → shipped-program name in the fbtpu-speccheck
#: registry (the aval lattice the boundary compatibility check reads).
KIND_TO_PROGRAM = {
    "flux-segment-counts": "flux.counts",
    "flux-hll": "flux.hll",
    "flux-cms": "flux.cms",
    "flux-fused": "flux.fused",
    "grep-mesh": "grep.mesh[batch]",
    "grep-jit": "grep.jit",
}

#: Host-effect terminals a merged program would reorder: counter
#: bumps, qos admission verdicts, lock acquisitions.
_METRIC_EFFECTS = frozenset({"inc", "observe"})
_QOS_EFFECTS = frozenset({"admit", "shed"})
#: Inert-when-disarmed planes (failpoints) — never an effect hazard.
_EFFECT_WHITELIST = frozenset({"fire"})

#: Between-launch staging terminals (the restage detector).
_RESTAGE_NAMES = frozenset({"asarray", "ascontiguousarray",
                            "stage_field", "stage_field_into"})

_SEVERITY = {
    "fusable-unfused-boundary": "warning",
    "fusion-blocked-by-host-compact": "warning",
    "cross-launch-restage": "warning",
    "fused-effect-violation": "error",
    "fusion-plan-regression": "error",
}


# ----------------------------------------------------------------------
# boundary classification
# ----------------------------------------------------------------------

def _call_at(module: Module, line: int, what: str) -> Optional[ast.Call]:
    """The launch call a site row points at: same line, terminal name
    matching the site's ``what`` tail (``lane.run`` → ``run``,
    dispatch names verbatim); falls back to the first call on the
    line (sites serialize without their column)."""
    tail = what.split(".")[-1].lstrip("<")
    fallback = None
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and node.lineno == line:
            if fallback is None:
                fallback = node
            if _terminal(node.func) == tail:
                return node
    return fallback


def _arg_names(call: Optional[ast.Call]) -> Set[str]:
    """Name ids staged through a launch call (args + keywords,
    closures included — the lane idiom hands buffer-capturing defs)."""
    if call is None:
        return set()
    out: Set[str] = set()
    for arg in list(call.args) + [k.value for k in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
    return out


def _is_lockish(expr: ast.AST) -> bool:
    chain = " ".join(_chain_names(expr)).lower()
    return "lock" in chain or "mutex" in chain


def _scan_between(module: Module, lo: int, hi: int
                  ) -> Dict[str, List[Tuple[int, Any]]]:
    """Host activity on lines strictly between two launch sites:
    compacts, effects (metric/qos/lock), restage calls with the names
    they touch. Line-windowed rather than path-sensitive — the same
    approximation the launch walker itself makes for site ordering."""
    compacts: List[Tuple[int, Any]] = []
    effects: List[Tuple[int, Any]] = []
    restages: List[Tuple[int, Any]] = []
    for node in ast.walk(module.tree):
        ln = getattr(node, "lineno", None)
        if ln is None or not (lo < ln < hi):
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            if any(_is_lockish(i.context_expr) for i in node.items):
                effects.append((ln, "lock held (`with`)"))
            continue
        if not isinstance(node, ast.Call):
            continue
        t = _terminal(node.func)
        if t in _EFFECT_WHITELIST:
            continue
        if t in SCATTER_NAMES:
            compacts.append((ln, t))
        elif t in _METRIC_EFFECTS:
            effects.append((ln, f"metric `.{t}()`"))
        elif t in _QOS_EFFECTS \
                and "qos" in " ".join(_chain_names(node.func)).lower():
            effects.append((ln, f"qos `.{t}()`"))
        elif t == "acquire":
            effects.append((ln, "lock `.acquire()`"))
        elif t in _RESTAGE_NAMES:
            names = {s.id for a in node.args for s in ast.walk(a)
                     if isinstance(s, ast.Name)}
            restages.append((ln, names))
    return {"compacts": compacts, "effects": effects,
            "restages": restages}


def _program_avals(kind: str) -> Optional[Dict[str, Any]]:
    """The speccheck-lattice view of a launch kind: per-leaf
    (sharded shape, dtype, resolved spec) for inputs/outputs plus the
    declared donation set, at the program's canonical env. None when
    the kind has no shipped program or the registry cannot build
    (kernel-less host) — compatibility is then unknown, never a
    blocker."""
    name = KIND_TO_PROGRAM.get(kind)
    if name is None:
        return None
    try:
        from .speccheck import (_bound_rules, _resolved_spec,
                                program_env, sharded_shape,
                                shipped_programs)

        progs = {p.name: p for p in shipped_programs()}
        prog = progs.get(name)
        if prog is None:
            return None
        env = program_env(prog)
        rules = _bound_rules(prog)

        def leaf(a):
            spec = _resolved_spec(prog, a, rules)
            return (sharded_shape(a.shape, spec, prog.axes, env),
                    str(a.dtype), tuple(spec or ()))

        return {
            "inputs": {a.name: leaf(a) for a in prog.inputs},
            "outputs": {a.name: leaf(a) for a in prog.outputs},
            "donate": tuple(prog.donate),
        }
    except Exception:  # pragma: no cover - jax-less host
        return None


def classify_boundaries(module: Module, chain: Dict[str, Any]
                        ) -> List[Dict[str, Any]]:
    """Every boundary between consecutive launch sites of one chain →
    verdict + reasons + the host activity evidence."""
    sites = sorted(chain["sites"], key=lambda s: (s["line"],))
    out: List[Dict[str, Any]] = []
    for prod, cons in zip(sites, sites[1:]):
        lo, hi = prod["line"], cons["line"]
        seen = _scan_between(module, min(lo, hi), max(lo, hi))
        staged = _arg_names(_call_at(module, prod["line"],
                                     prod["what"]))
        reasons: List[Dict[str, Any]] = []
        for ln, what in seen["compacts"]:
            reasons.append({"kind": "host-compact", "line": ln,
                            "detail": f"host `{what}(...)` scatter "
                                      f"between the launches"})
        for ln, what in seen["effects"]:
            reasons.append({"kind": "host-effect", "line": ln,
                            "detail": what})
        restage_hits = []
        for ln, names in seen["restages"]:
            shared = sorted(names & staged)
            if shared:
                restage_hits.append({"line": ln, "buffers": shared})
        pa = _program_avals(prod["kind"])
        ca = _program_avals(cons["kind"])
        aval_compat: Optional[bool] = None
        donation: Dict[str, Any] = {"preserved": [], "broken": []}
        if pa is not None and ca is not None:
            aval_compat = True
            for nm in sorted(set(pa["inputs"]) & set(ca["inputs"])):
                if pa["inputs"][nm] != ca["inputs"][nm]:
                    aval_compat = False
                    reasons.append({
                        "kind": "aval-incompatible", "line": hi,
                        "detail": f"shared input `{nm}` differs at the "
                                  f"canonical point: "
                                  f"{pa['inputs'][nm]!r} vs "
                                  f"{ca['inputs'][nm]!r}"})
            for nm in pa["donate"]:
                if nm in ca["inputs"] and nm in pa["inputs"]:
                    if pa["inputs"][nm] == ca["inputs"][nm]:
                        donation["preserved"].append(nm)
                    else:
                        donation["broken"].append(nm)
                        reasons.append({
                            "kind": "donation-break", "line": hi,
                            "detail": f"producer donates `{nm}` but "
                                      f"the consumer re-reads it with "
                                      f"a different aval — the merged "
                                      f"program cannot alias it"})
        blocking = [r for r in reasons
                    if r["kind"] in ("host-compact", "host-effect",
                                     "aval-incompatible",
                                     "donation-break")]
        out.append({
            "producer": {"line": prod["line"], "kind": prod["kind"],
                         "what": prod["what"]},
            "consumer": {"line": cons["line"], "kind": cons["kind"],
                         "what": cons["what"]},
            "verdict": "BLOCKED" if blocking else "FUSABLE",
            "reasons": reasons,
            "restages": restage_hits,
            "aval_compat": aval_compat,
            "donation": donation,
        })
    return out


# ----------------------------------------------------------------------
# the planned fused program (symbolic pricing)
# ----------------------------------------------------------------------

def _planned_program(sites: List[Dict[str, Any]],
                     boundaries: List[Dict[str, Any]],
                     env: Dict[str, int]) -> Dict[str, Any]:
    """Merge FUSABLE runs into planned launches and price each: shared
    h2d buffers (same name + symbolic bytes) stage ONCE in the merged
    program; a buffer donated by any member stays donated."""
    groups: List[List[Dict[str, Any]]] = []
    if sites:
        cur = [sites[0]]
        for b, site in zip(boundaries, sites[1:]):
            if b["verdict"] == "FUSABLE":
                cur.append(site)
            else:
                groups.append(cur)
                cur = [site]
        groups.append(cur)
    h2d: List[Dict[str, Any]] = []
    seen: Set[Tuple[str, str]] = set()
    for grp in groups:
        for site in grp:
            shapes = TRANSFER_SHAPES.get(site["kind"])
            if shapes is None:
                continue
            for name, expr, dtype, donated in shapes["h2d"]:
                key = (name, expr)
                if key in seen:
                    continue
                seen.add(key)
                h2d.append({"buffer": name, "bytes": expr,
                            "dtype": dtype, "donated": donated})
    undonated = sum(_eval_bytes(r["bytes"], env) for r in h2d
                    if not r["donated"])
    return {
        "launches_per_segment": len(groups),
        "h2d": h2d,
        "h2d_bytes_canonical": sum(_eval_bytes(r["bytes"], env)
                                   for r in h2d),
        "undonated_h2d_bytes_canonical": undonated,
    }


# ----------------------------------------------------------------------
# the plan, its committed snapshot, and the regression gate
# ----------------------------------------------------------------------

def build_fusion_plan(root: Optional[str] = None,
                      params: Optional[Dict[str, int]] = None
                      ) -> Dict[str, Any]:
    """Scan the shipped device planes (the launch walker's scopes) and
    emit the per-chain fusion plan: boundary verdicts + the priced
    planned fused program."""
    import os

    from . import iter_py_files
    from .launchgraph import _package_root

    pkg = root or _package_root()
    env = canonical_env(params)
    chains: Dict[str, Any] = {}
    scopes = [os.path.join(pkg, "plugins"), os.path.join(pkg, "flux")]
    for scope in scopes:
        if not os.path.isdir(scope):
            continue
        for path in iter_py_files([scope]):
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            rel = os.path.relpath(path, os.path.dirname(pkg))
            module = Module(rel, source)
            if module.tree is None:
                continue
            for chain in _ModuleScan(module).chains():
                if chain["launches_per_segment"] == 0:
                    continue
                cid = f"{chain['module']}::{chain['cls']}." \
                      f"{chain['entry']}"
                sites = sorted(chain["sites"],
                               key=lambda s: (s["line"],))
                bounds = classify_boundaries(module, chain)
                chains[cid] = {
                    "launches_per_segment":
                        chain["launches_per_segment"],
                    "sites": [{"line": s["line"], "kind": s["kind"],
                               "what": s["what"]} for s in sites],
                    "boundaries": bounds,
                    "planned": _planned_program(sites, bounds, env),
                }
    return {"version": 1, "params": env,
            "chains": dict(sorted(chains.items()))}


def plan_snapshot(plan: Dict[str, Any]) -> Dict[str, Any]:
    """The regression-gated subset: per chain the boundary verdict
    vector and the planned fused program's launch count + un-donated
    h2d bytes. ``analysis/fusion_plan.json`` commits this — the fourth
    implicit baseline next to the launch, lock, and copy files."""
    chains = {}
    for cid, chain in plan["chains"].items():
        chains[cid] = {
            "boundaries": len(chain["boundaries"]),
            "blocked": sum(1 for b in chain["boundaries"]
                           if b["verdict"] == "BLOCKED"),
            "verdicts": [b["verdict"] for b in chain["boundaries"]],
            "planned_launches_per_segment":
                chain["planned"]["launches_per_segment"],
            "planned_undonated_h2d_bytes":
                chain["planned"]["undonated_h2d_bytes_canonical"],
        }
    return {"params": {k: int(v) for k, v in plan["params"].items()},
            "chains": chains}


def compare_fusion_plan(current: Dict[str, Any],
                        baseline: Dict[str, Any]
                        ) -> Tuple[List[str], List[str]]:
    """Current plan snapshot vs the committed one → (regressions,
    notes). Boundary growth, planned-launch growth, planned-byte
    growth, a chain the plan has never seen, or a FUSABLE verdict
    turning BLOCKED is a regression; shrinkage is a note (regenerate
    the plan file to claim it)."""
    regressions: List[str] = []
    notes: List[str] = []
    base_chains = baseline.get("chains", {})
    gate_keys = ("boundaries", "blocked", "planned_launches_per_segment",
                 "planned_undonated_h2d_bytes")
    for cid, cur in current.get("chains", {}).items():
        base = base_chains.get(cid)
        if base is None:
            regressions.append(
                f"{cid}: new device chain not in fusion_plan.json "
                f"({cur['boundaries']} boundary(ies)) — plan it "
                f"deliberately (--write-fusion-plan)")
            continue
        for key in gate_keys:
            b, c = int(base.get(key, 0)), int(cur.get(key, 0))
            if c > b:
                regressions.append(
                    f"{cid}: {key} grew {b} → {c} — a fusion plan "
                    f"only shrinks; re-plan deliberately "
                    f"(--write-fusion-plan)")
            elif c < b:
                notes.append(
                    f"{cid}: {key} improved {b} → {c}; regenerate "
                    f"fusion_plan.json (--write-fusion-plan) to "
                    f"claim it")
        bv = base.get("verdicts", [])
        cv = cur.get("verdicts", [])
        for i, (old, new) in enumerate(zip(bv, cv)):
            if old == "FUSABLE" and new == "BLOCKED":
                regressions.append(
                    f"{cid}: boundary {i} verdict regressed FUSABLE → "
                    f"BLOCKED — new host work landed between launches "
                    f"the plan had cleared for merging")
    for cid in base_chains:
        if cid not in current.get("chains", {}):
            notes.append(f"{cid}: chain left the device plane (fused "
                         f"or removed); regenerate fusion_plan.json")
    return regressions, notes


def fusion_plan_to_dot(plan: Dict[str, Any]) -> str:
    """Graphviz rendering: launch sites chained by boundary edges,
    green = FUSABLE (merge them), red = BLOCKED (labelled with the
    first reason)."""
    lines = ["digraph fuseplan {", "  rankdir=LR;",
             '  node [shape=box, fontname="monospace"];']
    for cid, chain in plan["chains"].items():
        prev = None
        for site in chain["sites"]:
            sid = f'"{cid}#L{site["line"]}"'
            lines.append(
                f'  {sid} [label="{site["what"]}\\n{site["kind"]}"];')
            prev = prev  # keep flake quiet; edges below
        for b in chain["boundaries"]:
            src = f'"{cid}#L{b["producer"]["line"]}"'
            dst = f'"{cid}#L{b["consumer"]["line"]}"'
            if b["verdict"] == "FUSABLE":
                lines.append(f'  {src} -> {dst} [color=green, '
                             f'label="FUSABLE"];')
            else:
                why = b["reasons"][0]["kind"] if b["reasons"] else "?"
                lines.append(f'  {src} -> {dst} [color=red, '
                             f'label="BLOCKED\\n{why}"];')
        planned = chain["planned"]["launches_per_segment"]
        lines.append(
            f'  "{cid}" [label="{cid}\\nplanned: {planned} '
            f'launch(es)/segment", style=bold];')
        if chain["sites"]:
            first = f'"{cid}#L{chain["sites"][0]["line"]}"'
            lines.append(f'  "{cid}" -> {first} [style=dotted];')
    lines.append("}")
    return "\n".join(lines)


# ----------------------------------------------------------------------
# the rule pack
# ----------------------------------------------------------------------

class FuseplanRules(Rule):
    name = "fuseplan"  # umbrella; findings carry precise rules
    description = ("fbtpu-fuseplan rules: boundary-level fusion "
                   "verdicts between consecutive device launches — "
                   "fusable-but-unfused boundaries, host-compact "
                   "blockers, cross-launch restages, host effects "
                   "inside proposed fused regions, and fusion-plan "
                   "regressions against analysis/fusion_plan.json")

    RULE_NAMES = ("fusable-unfused-boundary",
                  "fusion-blocked-by-host-compact",
                  "cross-launch-restage", "fused-effect-violation",
                  "fusion-plan-regression")

    def check(self, module: Module) -> List[Finding]:
        if not any(s in module.path for s in SCOPES):
            return []
        out: List[Finding] = []
        scan = _ModuleScan(module)
        flagged: Set[Tuple[int, str]] = set()

        def emit(line: int, rule: str, message: str) -> None:
            if (line, rule) in flagged or module.allowed(rule, line):
                return
            flagged.add((line, rule))
            out.append(Finding(module.path, line, 0, rule, message,
                               _SEVERITY[rule]))

        for chain in scan.chains():
            if chain["launches_per_segment"] < 2:
                continue
            ent = f"{chain['cls']}.{chain['entry']}"
            for b in classify_boundaries(module, chain):
                pk, ck = b["producer"]["kind"], b["consumer"]["kind"]
                if b["verdict"] == "FUSABLE":
                    emit(b["consumer"]["line"],
                         "fusable-unfused-boundary",
                         f"`{ent}`: the {pk} launch at line "
                         f"{b['producer']['line']} and this {ck} "
                         f"launch have no blocking host work between "
                         f"them — one merged program would stage the "
                         f"shared buffers once and pay one dispatch "
                         f"(see ANALYSIS.md \"Fusion pack\")")
                compact_blocked = False
                for r in b["reasons"]:
                    if r["kind"] == "host-compact":
                        compact_blocked = True
                        emit(r["line"], "fusion-blocked-by-host-compact",
                             f"`{ent}`: {r['detail']} — the "
                             f"{pk}→{ck} boundary cannot fuse until "
                             f"the scatter moves out (device-side "
                             f"compaction or verdict-on-device)")
                effect_reasons = [r for r in b["reasons"]
                                  if r["kind"] == "host-effect"]
                only_effects = effect_reasons and not compact_blocked \
                    and not any(r["kind"] in ("aval-incompatible",
                                              "donation-break")
                                for r in b["reasons"])
                if only_effects:
                    for r in effect_reasons:
                        emit(r["line"], "fused-effect-violation",
                             f"`{ent}`: {r['detail']} sits inside the "
                             f"proposed {pk}+{ck} fused region — a "
                             f"merged program would reorder this "
                             f"effect across the launch it follows; "
                             f"hoist it before or after the region")
                for hit in b["restages"]:
                    bufs = ", ".join(f"`{n}`" for n in hit["buffers"])
                    emit(hit["line"], "cross-launch-restage",
                         f"`{ent}`: {bufs} re-staged between the "
                         f"{pk} launch and the {ck} launch — those "
                         f"bytes are already device-resident; the "
                         f"fused program (or a device-side handle) "
                         f"deletes this upload")
        return out
