"""fbtpu-speccheck: abstract interpretation of the device plane's
sharding/shape/dtype contract.

ROADMAP item 1 collapses the filter stack into one fused shard_map
program and item 2 scales it to a 2-D mesh; both refactors fail in
ways that surface only at trace/lower time on an attached mesh — or as
a silent perf cliff the bench device path has never been able to
catch: a table leaf falling through to full replication, an axis the
mesh size does not divide, a donation that quietly stops aliasing, an
implicit reshard inside a fused body. This module proves the sharding
contract of every shipped device program statically, at lint time.

The lattice is ``(shape, dtype, PartitionSpec)`` triples: shapes are
symbolic dims (``"Bp"``, ``"R"``, ints) evaluated at the canonical
``registry.BUDGET_PARAMS`` point, dtypes are numpy names, and specs
are per-dim axis entries (axis name / ``None`` / unknown). Programs
are declared as :class:`ProgramSpec` records — the jit/pjit/shard_map
programs the PR-11 launch graph discovers (grep single-device + mesh
variants, the flux sketch/window kernels) — whose table pytrees
resolve their specs through the SAME declarative partition-rules
registry the builders consume (``ops.mesh.PARTITION_RULES``), so the
static prediction and the built program cannot drift apart by
construction. The tier-1 crosscheck (tests/test_speccheck.py) then
pins the abstraction to ground truth: every shipped program is lowered
on the simulated 8-device mesh and the predicted per-leaf
PartitionSpecs / donation set must equal the compiled module's actual
shardings and ``donation_report``.

Six rules (suppress with ``# fbtpu-lint: allow(<rule>)`` +
justification):

- ``shard-unmatched-leaf`` — a table-pytree leaf no explicit rule
  matches: ``match_partition_rules`` raises at trace time for the
  no-match case, and a catch-all match silently replicates — an error
  when the replicated per-device footprint exceeds
  ``REPLICATE_BUDGET``.
- ``shard-shadowed-rule`` — a partition rule that can never fire:
  every leaf it matches first-matches an earlier rule, or it matches
  no leaf at all (the dead-rule case ``match_partition_rules`` now
  also rejects at runtime). Plus a literal-tuple check for an earlier
  catch-all/duplicate pattern shadowing a later rule at any
  ``match_partition_rules`` call site.
- ``shard-indivisible-axis`` — a sharded dim not provably divisible by
  the mesh axis size. Discharged by an int dim the canonical axis size
  divides, a dim expression with the axis size as a literal factor, or
  a per-program discharge claim verified against the source: a
  ``pad_to_devices`` / ``bucket_size(..., multiple_of=)`` call in the
  named function (``("pad", fn)``), or a ``% ... == 0`` guard
  (``("guard", fn)`` — the 2-D ``R % n_dev`` case of ROADMAP item 2).
  A claim whose function no longer pads/guards is itself a finding.
- ``donation-aval-mismatch`` — a declared donated input whose abstract
  *sharded* (shape, dtype) aval matches no output aval: jax would fall
  back to a silent copy. This reproduces ``ops.mesh.
  aliasable_donations`` symbolically, without building a mesh.
- ``shard-implicit-reshard`` — an op inside a shard_map body combining
  operands whose inferred shardings disagree on a named mesh axis (the
  body-level interpreter propagates specs from literal ``in_specs``
  through element-wise ops, reductions, and collectives; ``psum``/
  ``pmax``-style merges clear the axis).
- ``jit-dynamic-shape-retrace`` — a parameter of a jit-boundary
  callable reaching a shape-constructor position (``jnp.zeros(n)``,
  ``reshape``, ``broadcast_to`` …) without ``static_argnums``/
  ``static_argnames``: a Python-value-derived dim at a jit boundary
  either retraces per distinct value or dies as a tracer. The
  sanctioned pattern — a closure-captured dim keyed into a
  compiled-fn cache (``flux.kernels.segment_counts``) — does not
  fire. Extends the purity pass's ``jax-retrace`` rule to shapes.

The per-program ``shardings`` block (:func:`shardings_snapshot`) rides
the launch graph (``--graph json``) and the committed
``analysis/launch_budget.json`` (``--write-budget``), and
``launch-budget-regression`` flags any leaf whose spec changed — the
fusion PR's sharding refactor is then diffable. See ANALYSIS.md
"speccheck pack".
"""

from __future__ import annotations

import ast
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import Finding, Module, Rule

__all__ = [
    "Aval", "ProgramSpec", "SpecCheckRules", "REPLICATE_BUDGET",
    "eval_dim", "leaf_spec", "sharded_shape", "predict_donations",
    "dim_divisible", "program_env", "shipped_programs",
    "program_shardings", "shardings_snapshot",
]

#: Implicit (catch-all / fallback) full replication above this
#: per-device byte footprint is an error. An explicit replicate rule is
#: always fine — the decision is declared and reviewable.
REPLICATE_BUDGET = 1 << 20

#: Patterns that match anything: a leaf landing on one of these is
#: implicitly replicated, not explicitly placed.
_CATCH_ALL = frozenset({"", ".*", ".+", "^.*$", "^.+$"})

_SEVERITY = {
    "shard-unmatched-leaf": "error",
    "shard-shadowed-rule": "warning",
    "shard-indivisible-axis": "error",
    "donation-aval-mismatch": "error",
    "shard-implicit-reshard": "error",
    "jit-dynamic-shape-retrace": "warning",
}


# ----------------------------------------------------------------------
# the lattice
# ----------------------------------------------------------------------

@dataclass
class Aval:
    """One abstract buffer: symbolic shape, dtype, PartitionSpec.

    ``spec`` entries are per-dim: an axis name, a tuple of axis names,
    or None (unsharded); a trailing-short spec leaves the remaining
    dims unsharded (PartitionSpec semantics). ``spec=None`` means the
    spec is RESOLVED through the program's partition-rule table by leaf
    name — the table-pytree case."""

    name: str
    shape: Tuple[Any, ...]
    dtype: str
    spec: Optional[Tuple[Any, ...]] = None
    donatable: bool = False


@dataclass
class ProgramSpec:
    """One device program's declared contract, evaluated at the
    canonical ``registry.BUDGET_PARAMS`` point (plus ``env``
    overrides — e.g. the rule-sharded grep variant models ``R=8``, the
    smallest R its own ``R % n_dev == 0`` gate admits on the canonical
    8-device mesh)."""

    name: str
    #: module path suffix findings anchor to (posix separators)
    module: str
    #: function/method name in that module findings anchor at (and
    #: where discharge claims default-verify)
    entry: str
    #: ((mesh axis name, size symbol), ...) — () for single-device jit
    axes: Tuple[Tuple[str, str], ...]
    #: key into ops.mesh.PARTITION_RULES for spec=None leaves
    rules_key: Optional[str]
    tables: Tuple[Aval, ...]
    inputs: Tuple[Aval, ...]
    outputs: Tuple[Aval, ...]
    #: input names the program declares donated (donate_argnums)
    donate: Tuple[str, ...] = ()
    #: dim symbol -> ("pad" | "guard", function name): the divisibility
    #: proof for that symbol, verified against the module source
    discharge: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: canonical-env overrides for this program
    env: Dict[str, int] = field(default_factory=dict)


def program_env(prog: Optional[ProgramSpec] = None) -> Dict[str, int]:
    """The canonical symbolic-evaluation point: the launch-graph env
    (``BUDGET_PARAMS`` + derived padded batch) plus the program's own
    overrides."""
    from .launchgraph import canonical_env

    env = canonical_env()
    if prog is not None:
        env.update(prog.env)
    return env


def eval_dim(dim: Any, env: Dict[str, int]) -> int:
    """A symbolic dim ("Bp", "8*n_dev", int) at the canonical env."""
    if isinstance(dim, (int, np.integer)):
        return int(dim)
    return int(eval(str(dim), {"__builtins__": {}}, dict(env)))  # noqa: S307


def _bound_rules(prog: ProgramSpec) -> Tuple[Tuple[str, Tuple], ...]:
    """The program's partition-rule rows with the axis placeholder
    bound to its first mesh axis — pure data (no jax import; lint must
    run without a backend)."""
    if not prog.rules_key:
        return ()
    from ..ops.mesh import AXIS, PARTITION_RULES

    axis = prog.axes[0][0] if prog.axes else None
    rows = PARTITION_RULES.get(prog.rules_key, ())
    return tuple(
        (rx, tuple(axis if t == AXIS else t for t in tmpl))
        for rx, tmpl in rows
    )


def leaf_spec(rules: Sequence[Tuple[str, Tuple]],
              name: str) -> Tuple[Optional[Tuple], Optional[int]]:
    """First-match resolution (the ``match_partition_rules``
    semantics) → (spec, rule index); (None, None) when nothing
    matches."""
    for i, (rx, spec) in enumerate(rules):
        if re.search(rx, name) is not None:
            return spec, i
    return None, None


def _resolved_spec(prog: ProgramSpec, aval: Aval,
                   rules: Sequence[Tuple[str, Tuple]]) -> Optional[Tuple]:
    if aval.spec is not None:
        return aval.spec
    spec, _ = leaf_spec(rules, aval.name)
    return spec


def sharded_shape(shape: Tuple[Any, ...], spec: Optional[Tuple],
                  axes: Tuple[Tuple[str, str], ...],
                  env: Dict[str, int]) -> Tuple[int, ...]:
    """Per-device shard shape — the symbolic twin of
    ``ops.mesh._sharded_shape`` (what jax's donation matcher compares)."""
    out = [eval_dim(d, env) for d in shape]
    sizes = {a: eval_dim(s, env) for a, s in axes}
    if spec:
        for i, ent in enumerate(spec[:len(out)]):
            if ent is None:
                continue
            for ax in (ent if isinstance(ent, tuple) else (ent,)):
                out[i] //= max(1, sizes.get(ax, 1))
    return tuple(out)


def predict_donations(prog: ProgramSpec,
                      env: Optional[Dict[str, int]] = None) -> List[str]:
    """The statically-aliasable donated-input set: donatable inputs
    whose sharded (shape, dtype) exactly matches an unclaimed output
    aval — ``ops.mesh.aliasable_donations`` reproduced symbolically,
    no mesh required."""
    env = env or program_env(prog)
    rules = _bound_rules(prog)
    outs: Dict[tuple, int] = {}
    for o in prog.outputs:
        key = (sharded_shape(o.shape, _resolved_spec(prog, o, rules),
                             prog.axes, env), np.dtype(o.dtype).name)
        outs[key] = outs.get(key, 0) + 1
    donated: List[str] = []
    for a in prog.inputs:
        if not a.donatable:
            continue
        key = (sharded_shape(a.shape, _resolved_spec(prog, a, rules),
                             prog.axes, env), np.dtype(a.dtype).name)
        if outs.get(key, 0) > 0:
            outs[key] -= 1
            donated.append(a.name)
    return donated


def dim_divisible(dim: Any, size_sym: str,
                  env: Dict[str, int]) -> Optional[bool]:
    """Static divisibility of a sharded dim by a mesh-axis size.

    Returns True (proven), False (proven indivisible — a concrete dim
    the canonical axis size does not divide), or None (unknown: a
    symbolic dim with no structural proof — the caller then requires a
    verified discharge claim). A symbolic dim is NEVER accepted on
    canonical-value luck: ``"B"`` evaluating to 4096 today proves
    nothing about tomorrow's segment."""
    if isinstance(dim, (int, np.integer)):
        return int(dim) % max(1, eval_dim(size_sym, env)) == 0
    expr = str(dim).replace(" ", "")
    if expr == size_sym:
        return True
    # a literal product with the axis size as a top-level factor
    if "*" in expr and size_sym in expr.split("*"):
        return True
    return None


# ----------------------------------------------------------------------
# discharge verification (source-level proofs)
# ----------------------------------------------------------------------

_PAD_FNS = frozenset({"pad_to_devices", "_pad_to_mesh"})


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _find_def(module: Module, name: str) -> Optional[ast.AST]:
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _verify_discharge(module: Module, claim: Tuple[str, str]) -> bool:
    """A discharge claim holds iff the named function still carries the
    proof: a pad helper call (``pad_to_devices`` /
    ``bucket_size(..., multiple_of=)``) for ``"pad"`` claims, a
    ``% ... == 0``-style modulo guard for ``"guard"`` claims."""
    kind, fn_name = claim
    fn = _find_def(module, fn_name)
    if fn is None:
        return False
    for sub in ast.walk(fn):
        if kind == "pad" and isinstance(sub, ast.Call):
            t = _terminal(sub.func)
            if t in _PAD_FNS:
                return True
            if t == "bucket_size" and any(kw.arg == "multiple_of"
                                          for kw in sub.keywords):
                return True
        elif kind == "guard" and isinstance(sub, ast.Compare):
            sides = [sub.left] + list(sub.comparators)
            has_mod = any(isinstance(s, ast.BinOp)
                          and isinstance(s.op, ast.Mod) for s in sides)
            against_zero = any(isinstance(s, ast.Constant)
                               and s.value == 0 for s in sides)
            if has_mod and against_zero:
                return True
    return False


# ----------------------------------------------------------------------
# the shipped-program registry (canonical BUDGET_PARAMS evaluation)
# ----------------------------------------------------------------------

_GREP_MODULE = "fluentbit_tpu/ops/grep.py"
_SKETCH_MODULE = "fluentbit_tpu/ops/sketch.py"
_KERNELS_MODULE = "fluentbit_tpu/flux/kernels.py"

_programs_cache: Optional[Tuple[ProgramSpec, ...]] = None
_cache_lock = threading.Lock()


def _grep_table_leaves(env: Dict[str, int]) -> Tuple[Aval, ...]:
    """The grep table pytree's leaves from a REAL canonical build
    (R copies of the apache2 worked example — one stride class, so the
    program never splits into per-k children), with the rule dim
    re-symbolized to ``"R"`` so both mesh variants share the leaves."""
    from .launchgraph import APACHE2
    from ..ops.grep import GrepProgram
    from ..regex.dfa import compile_dfa

    g = GrepProgram([compile_dfa(APACHE2)] * env["R"], max_len=env["L"])
    if g._np is None:  # pragma: no cover - homogeneous k never splits
        raise RuntimeError("canonical grep program split into children")
    return tuple(
        Aval(nm, ("R",) + tuple(int(s) for s in arr.shape[1:]),
             str(arr.dtype))
        for nm, arr in sorted(g._np.items()) if arr is not None
    )


def _build_shipped() -> Tuple[ProgramSpec, ...]:
    env = program_env()
    leaves = _grep_table_leaves(env)
    rep = tuple(Aval(a.name, a.shape, a.dtype, spec=())
                for a in leaves)

    from ..ops.sketch import CountMin, HyperLogLog

    hll = HyperLogLog(p=12)  # M_hll = 1 << 12, the FluxSpec default
    cms = CountMin()         # 4 × 16384 — M_cms
    hll_shape = tuple(int(s) for s in np.asarray(hll.registers).shape)
    hll_dtype = str(np.asarray(hll.registers).dtype)
    cms_shape = tuple(int(s) for s in np.asarray(cms.table).shape)
    cms_dtype = str(np.asarray(cms.table).dtype)

    from ..flux.kernels import _pad_segments

    n_pad = _pad_segments(env["G"])

    grep_jit = ProgramSpec(
        name="grep.jit", module=_GREP_MODULE, entry="_materialize",
        axes=(), rules_key=None, tables=rep,
        inputs=(Aval("batch", ("R", "B", "L"), "uint8", ()),
                Aval("lengths", ("R", "B"), "int32", ())),
        outputs=(Aval("mask", ("R", "B"), "bool", ()),),
    )
    grep_batch = ProgramSpec(
        name="grep.mesh[batch]", module=_GREP_MODULE,
        entry="dispatch_mesh",
        axes=(("batch", "n_dev"),), rules_key="grep-batch",
        tables=leaves,
        inputs=(Aval("batch", ("R", "Bp", "L"), "uint8",
                     (None, "batch", None), donatable=True),
                Aval("lengths", ("R", "Bp"), "int32",
                     (None, "batch"), donatable=True)),
        outputs=(Aval("mask", ("R", "Bp"), "int32", (None, "batch")),
                 Aval("counts", ("R",), "int32", ())),
        donate=("lengths",),
        discharge={"Bp": ("pad", "dispatch_mesh")},
    )
    grep_rules = ProgramSpec(
        name="grep.mesh[rules]", module=_GREP_MODULE,
        entry="dispatch_mesh",
        axes=(("batch", "n_dev"),), rules_key="grep-rules",
        tables=leaves,
        inputs=(Aval("batch", ("R", "Bp", "L"), "uint8",
                     ("batch", None, None), donatable=True),
                Aval("lengths", ("R", "Bp"), "int32",
                     ("batch", None), donatable=True)),
        outputs=(Aval("mask", ("R", "Bp"), "int32", ("batch", None)),
                 Aval("counts", ("R",), "int32", ("batch",))),
        donate=("lengths",),
        discharge={"R": ("guard", "mesh_variant"),
                   "Bp": ("pad", "dispatch_mesh")},
        # the smallest R the variant's own R % n_dev == 0 gate admits
        env={"R": env["n_dev"]},
    )
    flux_hll = ProgramSpec(
        name="flux.hll", module=_SKETCH_MODULE,
        entry="build_sharded_hll",
        axes=(("flux", "n_dev"),), rules_key="flux-hll",
        tables=(Aval("registers", hll_shape, hll_dtype),),
        inputs=(Aval("batch", ("Bp", "L"), "uint8", ("flux", None)),
                Aval("lengths", ("Bp",), "int32", ("flux",))),
        outputs=(Aval("registers_out", hll_shape, hll_dtype, ()),),
        discharge={"Bp": ("pad", "_pad_to_mesh")},
    )
    flux_cms = ProgramSpec(
        name="flux.cms", module=_SKETCH_MODULE,
        entry="build_sharded_cms",
        axes=(("flux", "n_dev"),), rules_key="flux-cms",
        tables=(Aval("table", cms_shape, cms_dtype),),
        inputs=(Aval("batch", ("Bp", "L"), "uint8", ("flux", None)),
                Aval("lengths", ("Bp",), "int32", ("flux",)),
                Aval("weights", ("Bp",), "int32", ("flux",))),
        outputs=(Aval("table_out", cms_shape, cms_dtype, ()),),
        discharge={"Bp": ("pad", "_pad_to_mesh")},
    )
    flux_counts = ProgramSpec(
        name="flux.counts", module=_KERNELS_MODULE,
        entry="build_sharded_counts",
        axes=(("flux", "n_dev"),), rules_key="flux-counts",
        tables=(),
        inputs=(Aval("seg", ("Bp",), "int32"),
                Aval("valid", ("Bp",), "int32")),
        outputs=(Aval("counts", (n_pad,), "int32", ()),),
        discharge={"Bp": ("pad", "sharded_segment_counts")},
    )
    flux_fused = ProgramSpec(
        # the 3-launch sketch/window chain merged into one program —
        # the first fusion the fuseplan analyzer cashed. Modeled at
        # F=1 string fields (the canonical single-distinct config);
        # registers is the [Gp, m] per-group stack, donated on
        # accelerator platforms only (the CPU path keeps the snapshot
        # for the lane fallback).
        name="flux.fused", module=_KERNELS_MODULE,
        entry="build_fused_absorb",
        axes=(("flux", "n_dev"),), rules_key="flux-fused",
        tables=(),
        inputs=(Aval("seg", ("Bp",), "int32"),
                Aval("valid", ("Bp",), "int32"),
                Aval("batch", ("Bp", "L"), "uint8"),
                Aval("lengths", ("Bp",), "int32"),
                Aval("registers", ("Gp", hll_shape[0]), hll_dtype,
                     donatable=True),
                Aval("comp", ("Bp", "L"), "uint8"),
                Aval("comp_len", ("Bp",), "int32"),
                Aval("table", cms_shape, cms_dtype)),
        outputs=(Aval("counts", ("Gp",), "int32", ()),
                 Aval("registers_out", ("Gp", hll_shape[0]),
                      hll_dtype, ()),
                 Aval("table_out", cms_shape, cms_dtype, ())),
        donate=("registers",),
        discharge={"Bp": ("pad", "_fused_call")},
    )
    return (grep_jit, grep_batch, grep_rules, flux_hll, flux_cms,
            flux_counts, flux_fused)


def shipped_programs(refresh: bool = False) -> Tuple[ProgramSpec, ...]:
    """The canonical shipped-program registry, built lazily (the grep
    leaves come from a real DFA compile). Returns () when the kernel
    deps are unavailable — the rest of the lint gate must still run on
    a jax-less host."""
    global _programs_cache
    if _programs_cache is not None and not refresh:
        return _programs_cache
    try:
        progs = _build_shipped()
    except Exception:
        progs = ()
    with _cache_lock:
        _programs_cache = progs
    return progs


# ----------------------------------------------------------------------
# the shardings snapshot (launch-budget plumbing)
# ----------------------------------------------------------------------

def _spec_json(spec: Optional[Tuple]) -> Optional[List]:
    if spec is None:
        return None
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def program_shardings(prog: ProgramSpec) -> Dict[str, Any]:
    """One program's predicted layout, JSON-shaped for the budget
    file: per-leaf specs (tables through the rule registry, inputs/
    outputs as declared) plus the predicted donation set."""
    env = program_env(prog)
    rules = _bound_rules(prog)

    def js(aval: Aval) -> Optional[List]:
        return _spec_json(_resolved_spec(prog, aval, rules))

    return {
        "module": prog.module,
        "axes": {a: eval_dim(s, env) for a, s in prog.axes},
        "tables": {a.name: js(a) for a in prog.tables},
        "inputs": {a.name: js(a) for a in prog.inputs},
        "outputs": {a.name: js(a) for a in prog.outputs},
        "donate": list(prog.donate),
        "donate_predicted": predict_donations(prog, env),
    }


def shardings_snapshot() -> Dict[str, Any]:
    """Every shipped program's predicted shardings — the block
    ``--graph json`` emits and ``--write-budget`` commits, gated by
    ``launch-budget-regression`` (a leaf whose spec changes fails until
    the budget file says so)."""
    return {p.name: program_shardings(p) for p in shipped_programs()}


# ----------------------------------------------------------------------
# the shard_map body interpreter (shard-implicit-reshard)
# ----------------------------------------------------------------------

#: entirely-unknown abstract value
_UNKNOWN = None

#: unknown single-dim entry (vs None = known-unsharded)
class _TopDim:
    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debug only
        return "TOP"


TOP = _TopDim()

_COLLECTIVES = frozenset({"psum", "pmax", "pmin", "pmean"})
_REDUCTIONS = frozenset({"sum", "max", "min", "prod", "mean", "any",
                         "all", "count_nonzero"})
_PASSTHROUGH_METHODS = frozenset({"astype", "clip", "copy", "round"})
_PASSTHROUGH_LIKE = frozenset({"zeros_like", "ones_like", "full_like",
                               "empty_like"})


class _SV:
    """Abstract sharding value: ``dims`` is a per-dim tuple of
    axis-name / None / TOP, or the value is wholly unknown (use
    ``_UNKNOWN`` i.e. None instead of an _SV)."""

    __slots__ = ("dims",)

    def __init__(self, dims: Tuple[Any, ...]):
        self.dims = dims


class _BodyInterp:
    """Best-effort abstract interpreter over one shard_map body:
    parameters seeded from literal ``in_specs``, element-wise ops
    combine operand specs (a definite named-axis disagreement on the
    same dim is the finding), reductions drop dims, collectives clear
    the merged axis. Anything unresolvable degrades to unknown — the
    rule only reports conflicts it can prove."""

    def __init__(self):
        self.conflicts: List[Tuple[ast.AST, str, str]] = []
        self._flagged: Set[int] = set()

    def run(self, fn: ast.AST, params: List[Optional[_SV]]) -> None:
        names = [a.arg for a in fn.args.args]
        env: Dict[str, Optional[_SV]] = {}
        for nm, sv in zip(names, params):
            env[nm] = sv
        if isinstance(fn, ast.Lambda):
            self._expr(fn.body, env)
            return
        self._stmts(fn.body, env)

    # -- statements ----------------------------------------------------

    def _stmts(self, stmts: List[ast.stmt],
               env: Dict[str, Optional[_SV]]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                val = self._expr(stmt.value, env)
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        env[tgt.id] = val
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        for e in tgt.elts:
                            if isinstance(e, ast.Name):
                                env[e.id] = _UNKNOWN
            elif isinstance(stmt, ast.AugAssign):
                val = self._expr(stmt.value, env)
                if isinstance(stmt.target, ast.Name):
                    cur = env.get(stmt.target.id)
                    env[stmt.target.id] = self._combine(cur, val, stmt)
            elif isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self._expr(stmt.value, env)
            elif isinstance(stmt, ast.If):
                self._expr(stmt.test, env)
                self._stmts(stmt.body, env)
                self._stmts(stmt.orelse, env)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr(stmt.iter, env)
                if isinstance(stmt.target, ast.Name):
                    env[stmt.target.id] = _UNKNOWN
                self._stmts(stmt.body, env)
            elif isinstance(stmt, ast.While):
                self._expr(stmt.test, env)
                self._stmts(stmt.body, env)
            elif isinstance(stmt, ast.Expr):
                self._expr(stmt.value, env)
            # nested defs/classes run under their own spec context

    # -- expressions ---------------------------------------------------

    def _expr(self, node: Optional[ast.AST],
              env: Dict[str, Optional[_SV]]) -> Optional[_SV]:
        if node is None:
            return _UNKNOWN
        if isinstance(node, ast.Constant):
            return _SV(())
        if isinstance(node, ast.Name):
            return env.get(node.id, _UNKNOWN)
        if isinstance(node, ast.BinOp):
            return self._combine(self._expr(node.left, env),
                                 self._expr(node.right, env), node)
        if isinstance(node, ast.UnaryOp):
            return self._expr(node.operand, env)
        if isinstance(node, ast.Compare):
            out = self._expr(node.left, env)
            for c in node.comparators:
                out = self._combine(out, self._expr(c, env), node)
            return out
        if isinstance(node, ast.BoolOp):
            out = _UNKNOWN
            for v in node.values:
                out = self._combine(out, self._expr(v, env), node)
            return out
        if isinstance(node, ast.IfExp):
            self._expr(node.test, env)
            return self._combine(self._expr(node.body, env),
                                 self._expr(node.orelse, env), node)
        if isinstance(node, ast.Call):
            return self._call(node, env)
        if isinstance(node, ast.Subscript):
            return self._subscript(node, env)
        if isinstance(node, ast.Attribute):
            self._expr(node.value, env)
            return _UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._expr(e, env)
            return _UNKNOWN
        return _UNKNOWN

    def _call(self, call: ast.Call,
              env: Dict[str, Optional[_SV]]) -> Optional[_SV]:
        t = _terminal(call.func)
        args = [self._expr(a, env) for a in call.args]
        for kw in call.keywords:
            if kw.arg not in ("axis", "axis_name"):
                self._expr(kw.value, env)
        if t in _COLLECTIVES:
            base = args[0] if args else _UNKNOWN
            if base is _UNKNOWN:
                return _UNKNOWN
            axis_name = None
            for kw in call.keywords:
                if kw.arg == "axis_name" and isinstance(kw.value,
                                                        ast.Constant):
                    axis_name = kw.value.value
            dims = tuple(
                None if (isinstance(d, str)
                         and (axis_name is None or d == axis_name))
                else d
                for d in base.dims)
            return _SV(dims)
        if t in _REDUCTIONS:
            base = args[0] if args else _UNKNOWN
            if isinstance(call.func, ast.Attribute) and not call.args:
                base = self._expr(call.func.value, env)
            axis_kw = next((kw.value for kw in call.keywords
                            if kw.arg == "axis"), None)
            if base is _UNKNOWN:
                return _UNKNOWN
            if axis_kw is None:
                return _SV(())
            if isinstance(axis_kw, ast.Constant) \
                    and isinstance(axis_kw.value, int):
                k = axis_kw.value
                n = len(base.dims)
                if -n <= k < n:
                    k %= n
                    return _SV(base.dims[:k] + base.dims[k + 1:])
            return _UNKNOWN
        if t == "where" and len(args) == 3:
            out = self._combine(args[1], args[2], call)
            return self._combine(out, args[0], call)
        if t in _PASSTHROUGH_METHODS \
                and isinstance(call.func, ast.Attribute):
            return self._expr(call.func.value, env)
        if t in _PASSTHROUGH_LIKE and args:
            return args[0]
        # x.at[idx].add(v) and friends: result layout is the base array
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("add", "set", "max", "min",
                                       "mul") \
                and isinstance(call.func.value, ast.Subscript) \
                and isinstance(call.func.value.value, ast.Attribute) \
                and call.func.value.value.attr == "at":
            return self._expr(call.func.value.value.value, env)
        return _UNKNOWN

    def _subscript(self, node: ast.Subscript,
                   env: Dict[str, Optional[_SV]]) -> Optional[_SV]:
        base = self._expr(node.value, env)
        self._index_exprs(node.slice, env)
        if base is _UNKNOWN:
            return _UNKNOWN
        elts = node.slice.elts if isinstance(node.slice, ast.Tuple) \
            else [node.slice]
        dims: List[Any] = []
        src = list(base.dims)
        for e in elts:
            if isinstance(e, ast.Constant) and e.value is None:
                dims.append(None)
            elif isinstance(e, ast.Slice):
                if src:
                    dims.append(src.pop(0))
            elif isinstance(e, type(Ellipsis)) or (
                    isinstance(e, ast.Constant)
                    and e.value is Ellipsis):
                keep = len(src) - sum(
                    1 for r in elts[elts.index(e) + 1:]
                    if not (isinstance(r, ast.Constant)
                            and r.value is None))
                while len(src) > max(0, len(src) - keep):
                    dims.append(src.pop(0))
            else:
                if src:
                    src.pop(0)  # integer/fancy index drops the dim
        dims.extend(src)
        return _SV(tuple(dims))

    def _index_exprs(self, node: ast.AST,
                     env: Dict[str, Optional[_SV]]) -> None:
        for e in (node.elts if isinstance(node, ast.Tuple) else [node]):
            if isinstance(e, ast.Slice):
                for part in (e.lower, e.upper, e.step):
                    if part is not None:
                        self._expr(part, env)
            elif not isinstance(e, ast.Constant):
                self._expr(e, env)

    def _combine(self, a: Optional[_SV], b: Optional[_SV],
                 node: ast.AST) -> Optional[_SV]:
        if a is _UNKNOWN or b is _UNKNOWN:
            return _UNKNOWN
        if len(a.dims) != len(b.dims):
            # rank mismatch = numpy broadcasting; a spec is left-
            # anchored, so alignment is ambiguous — stay sound, give up
            return _UNKNOWN
        out: List[Any] = []
        for da, db in zip(a.dims, b.dims):
            if isinstance(da, str) and isinstance(db, str) and da != db:
                if node.lineno not in self._flagged:
                    self._flagged.add(node.lineno)
                    self.conflicts.append((node, da, db))
                out.append(TOP)
            elif isinstance(da, str):
                out.append(da)
            elif isinstance(db, str):
                out.append(db)
            elif da is TOP or db is TOP:
                out.append(TOP)
            else:
                out.append(None)
        return _SV(tuple(out))


def _parse_spec_literal(node: ast.AST) -> Optional[Tuple]:
    """A literal ``P(...)``/``PartitionSpec(...)`` call → spec tuple
    (axis strings / None / TOP for unresolvable entries); None for
    anything else (unknown spec)."""
    if not (isinstance(node, ast.Call)
            and _terminal(node.func) in ("P", "PartitionSpec")):
        return None
    out: List[Any] = []
    for a in node.args:
        if isinstance(a, ast.Constant) and (a.value is None
                                            or isinstance(a.value, str)):
            out.append(a.value)
        else:
            out.append(TOP)
    return tuple(out)


# ----------------------------------------------------------------------
# jit-boundary shape scan (jit-dynamic-shape-retrace)
# ----------------------------------------------------------------------

#: shape-constructor terminals → (positional shape args, shape kwargs)
_SHAPE_CTORS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "zeros": ((0,), ("shape",)),
    "ones": ((0,), ("shape",)),
    "empty": ((0,), ("shape",)),
    "full": ((0,), ("shape",)),
    "arange": ((0, 1, 2), ()),
    "eye": ((0, 1), ()),
    "linspace": ((2,), ("num",)),
    "broadcast_to": ((1,), ("shape",)),
    "tile": ((1,), ("reps",)),
    "reshape": ((1, 2, 3), ("newshape", "shape")),
}

#: method form: x.reshape(...) — every argument is a shape
_SHAPE_METHODS = frozenset({"reshape"})

_JIT_NAMES = frozenset({"jit", "pjit"})


def _all_defs(module: Module) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _nearest_def(defs: Dict[str, List[ast.AST]], name: str,
                 line: int) -> Optional[ast.AST]:
    cands = defs.get(name)
    if not cands:
        return None
    return min(cands, key=lambda d: abs(d.lineno - line))


class _ShapeScan:
    """Which parameters of each function reach a shape-constructor
    position — directly or through a call into another local def
    (positional mapping, recursion memoized and cycle-guarded)."""

    def __init__(self, defs: Dict[str, List[ast.AST]]):
        self.defs = defs
        self._memo: Dict[int, Set[str]] = {}
        self._stack: Set[int] = set()

    def params(self, fn: ast.AST) -> List[str]:
        return [a.arg for a in fn.args.args if a.arg != "self"]

    def shape_params(self, fn: ast.AST) -> Set[str]:
        key = id(fn)
        if key in self._memo:
            return self._memo[key]
        if key in self._stack:
            return set()
        self._stack.add(key)
        try:
            params = set(self.params(fn))
            hits: Set[str] = set()
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                t = _terminal(sub.func)
                for tree in self._shape_arg_trees(sub, t):
                    for n in ast.walk(tree):
                        if isinstance(n, ast.Name) and n.id in params:
                            hits.add(n.id)
                # transitive: a param forwarded into a callee's shape
                # position is a shape param here too
                callee = None
                if isinstance(sub.func, ast.Name):
                    callee = _nearest_def(self.defs, sub.func.id,
                                          sub.lineno)
                elif isinstance(sub.func, ast.Attribute):
                    callee = _nearest_def(self.defs, sub.func.attr,
                                          sub.lineno)
                if callee is None or t in _SHAPE_CTORS:
                    continue
                cp = self.params(callee)
                ch = self.shape_params(callee)
                for pos, arg in enumerate(sub.args):
                    if isinstance(arg, ast.Name) and arg.id in params \
                            and pos < len(cp) and cp[pos] in ch:
                        hits.add(arg.id)
                for kw in sub.keywords:
                    if isinstance(kw.value, ast.Name) \
                            and kw.value.id in params \
                            and kw.arg in ch:
                        hits.add(kw.value.id)
            self._memo[key] = hits
            return hits
        finally:
            self._stack.discard(key)

    def _shape_arg_trees(self, call: ast.Call,
                         t: Optional[str]) -> List[ast.AST]:
        trees: List[ast.AST] = []
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _SHAPE_METHODS:
            return list(call.args)
        if t not in _SHAPE_CTORS:
            return trees
        pos, kws = _SHAPE_CTORS[t]
        for i in pos:
            if i < len(call.args):
                trees.append(call.args[i])
        for kw in call.keywords:
            if kw.arg in kws:
                trees.append(kw.value)
        return trees


def _static_names(call: ast.Call, params: List[str]) -> Set[str]:
    """Parameter names covered by static_argnums/static_argnames."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = []
            if isinstance(kw.value, ast.Constant):
                vals = [kw.value.value]
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                vals = [e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)]
            for v in vals:
                if isinstance(v, int) and 0 <= v < len(params):
                    out.add(params[v])
        elif kw.arg == "static_argnames":
            vals = []
            if isinstance(kw.value, ast.Constant):
                vals = [kw.value.value]
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                vals = [e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)]
            out |= {v for v in vals if isinstance(v, str)}
    return out


# ----------------------------------------------------------------------
# the rule pack
# ----------------------------------------------------------------------

class SpecCheckRules(Rule):
    name = "speccheck"  # umbrella; findings carry precise rule names
    description = ("fbtpu-speccheck abstract sharding/shape/dtype "
                   "interpreter: unmatched/shadowed partition rules, "
                   "axis divisibility proofs, symbolic donation-aval "
                   "matching, shard_map-body reshard conflicts, "
                   "jit-boundary dynamic shapes")

    RULE_NAMES = ("shard-unmatched-leaf", "shard-shadowed-rule",
                  "shard-indivisible-axis", "donation-aval-mismatch",
                  "shard-implicit-reshard", "jit-dynamic-shape-retrace")

    def __init__(self, programs: Optional[Sequence[ProgramSpec]] = None):
        #: None → the shipped registry (lazy); tests inject synthetic
        #: ProgramSpecs here, the GuardedByRule(guards) pattern
        self._programs = programs

    def programs(self) -> Sequence[ProgramSpec]:
        if self._programs is not None:
            return self._programs
        return shipped_programs()

    def check(self, module: Module) -> List[Finding]:
        out: List[Finding] = []
        flagged: Set[Tuple[int, str, str]] = set()

        def emit(line: int, col: int, rule: str, message: str) -> None:
            if (line, rule, message) in flagged \
                    or module.allowed(rule, line):
                return
            flagged.add((line, rule, message))
            out.append(Finding(module.path, line, col, rule, message,
                               _SEVERITY[rule]))

        src = module.source
        if "match_partition_rules" in src:
            self._literal_rule_tables(module, emit)
        if "shard_map" in src:
            self._shard_bodies(module, emit)
        if "jit" in src:
            self._jit_shapes(module, emit)
        for prog in self.programs():
            if module.path.endswith(prog.module):
                self._check_program(prog, module, emit)
        out.sort(key=lambda f: (f.line, f.col, f.rule))
        return out

    # -- registry-driven program checks -------------------------------

    def _check_program(self, prog: ProgramSpec, module: Module,
                       emit) -> None:
        env = program_env(prog)
        rules = _bound_rules(prog)
        entry = _find_def(module, prog.entry)
        line = entry.lineno if entry is not None else 1

        ruled = [a for a in list(prog.tables) + list(prog.inputs)
                 if a.spec is None]
        # 1. unmatched / implicitly replicated leaves
        first_match: Dict[str, Optional[int]] = {}
        for aval in ruled:
            spec, idx = leaf_spec(rules, aval.name)
            first_match[aval.name] = idx
            nbytes = int(np.prod([eval_dim(d, env)
                                  for d in aval.shape]) or 1) \
                * np.dtype(aval.dtype).itemsize
            if idx is None:
                emit(line, 0, "shard-unmatched-leaf",
                     f"[{prog.name}] leaf `{aval.name}` matches no "
                     f"partition rule in {prog.rules_key!r}: "
                     f"match_partition_rules raises at trace time — "
                     f"name the leaf explicitly in "
                     f"ops.mesh.PARTITION_RULES")
            elif rules[idx][0] in _CATCH_ALL \
                    and nbytes > REPLICATE_BUDGET:
                emit(line, 0, "shard-unmatched-leaf",
                     f"[{prog.name}] leaf `{aval.name}` "
                     f"({nbytes} B) rides the catch-all rule "
                     f"{rules[idx][0]!r}: implicit full replication "
                     f"above the {REPLICATE_BUDGET} B budget — give "
                     f"it an explicit rule (replicate deliberately or "
                     f"shard it)")

        # 2. shadowed / dead rules over the real leaf set
        if ruled and rules:
            for j, (rx, _spec) in enumerate(rules):
                matching = [a.name for a in ruled
                            if re.search(rx, a.name) is not None]
                if not matching:
                    emit(line, 0, "shard-shadowed-rule",
                         f"[{prog.name}] partition rule {rx!r} "
                         f"matches no leaf of the program's table "
                         f"pytree (dead rule): a renamed leaf lost "
                         f"its spec silently")
                elif all(first_match.get(nm) is not None
                         and first_match[nm] < j for nm in matching):
                    shadow = rules[max(first_match[nm]
                                       for nm in matching)][0]
                    emit(line, 0, "shard-shadowed-rule",
                         f"[{prog.name}] partition rule {rx!r} can "
                         f"never fire: every leaf it matches "
                         f"({', '.join(matching)}) first-matches the "
                         f"earlier rule {shadow!r}")

        # 3. axis divisibility obligations
        axis_sizes = dict(prog.axes)
        for aval in (tuple(ruled) + tuple(a for a in prog.inputs
                                          if a.spec is not None)
                     + prog.outputs):
            spec = _resolved_spec(prog, aval, rules)
            if not spec:
                continue
            for i, ent in enumerate(spec[:len(aval.shape)]):
                if ent is None:
                    continue
                for ax in (ent if isinstance(ent, tuple) else (ent,)):
                    size_sym = axis_sizes.get(ax)
                    if size_sym is None:
                        continue
                    dim = aval.shape[i]
                    ok = dim_divisible(dim, size_sym, env)
                    if ok is True:
                        continue
                    claim = prog.discharge.get(str(dim))
                    if ok is None and claim is not None \
                            and _verify_discharge(module, claim):
                        continue
                    why = (f"discharge claim {claim!r} no longer "
                           f"verifies in this module"
                           if claim is not None else
                           f"no pad_to_devices/bucket_size("
                           f"multiple_of=) or %-guard proof covers it")
                    emit(line, 0, "shard-indivisible-axis",
                         f"[{prog.name}] dim {dim!r} of "
                         f"`{aval.name}` is sharded over mesh axis "
                         f"{ax!r} (size {size_sym}="
                         f"{eval_dim(size_sym, env)}) but is not "
                         f"provably divisible: {why} — NamedSharding "
                         f"rejects the shape at trace time")

        # 4. donation aval matching
        predicted = predict_donations(prog, env)
        in_names = {a.name for a in prog.inputs}
        for nm in prog.donate:
            if nm not in in_names:
                emit(line, 0, "donation-aval-mismatch",
                     f"[{prog.name}] donate entry `{nm}` names no "
                     f"input of the program")
            elif nm not in predicted:
                emit(line, 0, "donation-aval-mismatch",
                     f"[{prog.name}] donated input `{nm}`'s sharded "
                     f"aval matches no output aval: jax falls back to "
                     f"a silent copy (\"donated buffer was not "
                     f"usable\") — donate exactly the aliasable set "
                     f"(ops.mesh.aliasable_donations)")

    # -- literal rule-table scan (shard-shadowed-rule, source level) --

    def _literal_rule_tables(self, module: Module, emit) -> None:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and _terminal(node.func) == "match_partition_rules"
                    and node.args):
                continue
            rules_arg = node.args[0]
            if not isinstance(rules_arg, (ast.Tuple, ast.List)):
                continue
            pats: List[Tuple[str, ast.AST]] = []
            for elt in rules_arg.elts:
                if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str):
                    pats.append((elt.elts[0].value, elt))
            for j in range(1, len(pats)):
                later, lnode = pats[j]
                for i in range(j):
                    earlier, _ = pats[i]
                    if earlier in _CATCH_ALL or earlier == later:
                        emit(lnode.lineno, lnode.col_offset,
                             "shard-shadowed-rule",
                             f"partition rule {later!r} can never "
                             f"fire: the earlier rule {earlier!r} "
                             f"matches every leaf first "
                             f"(first-match semantics)")
                        break

    # -- shard_map body interpretation (shard-implicit-reshard) -------

    def _shard_bodies(self, module: Module, emit) -> None:
        defs = _all_defs(module)
        seen: Set[Tuple[int, int]] = set()
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and _terminal(node.func) == "shard_map"):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen or not node.args:
                continue
            seen.add(key)
            target = node.args[0]
            fn: Optional[ast.AST] = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name):
                fn = _nearest_def(defs, target.id, node.lineno)
            if fn is None:
                continue
            in_specs = next((kw.value for kw in node.keywords
                             if kw.arg == "in_specs"), None)
            spec_nodes = (list(in_specs.elts)
                          if isinstance(in_specs, (ast.Tuple, ast.List))
                          else [in_specs] if in_specs is not None
                          else [])
            params: List[Optional[_SV]] = []
            for sn in spec_nodes:
                spec = _parse_spec_literal(sn)
                params.append(_SV(spec) if spec is not None
                              else _UNKNOWN)
            interp = _BodyInterp()
            try:
                interp.run(fn, params)
            except RecursionError:  # pragma: no cover - deep bodies
                continue
            for cnode, da, db in interp.conflicts:
                emit(cnode.lineno, cnode.col_offset,
                     "shard-implicit-reshard",
                     f"op combines operands sharded over different "
                     f"mesh axes on the same dim ({da!r} vs {db!r}) "
                     f"inside a shard_map body: the compiler inserts "
                     f"an implicit all-to-all reshard per launch — "
                     f"merge explicitly (psum/pmax/all_gather) or fix "
                     f"the in_specs")

    # -- jit boundary scan (jit-dynamic-shape-retrace) ----------------

    def _jit_shapes(self, module: Module, emit) -> None:
        defs = _all_defs(module)
        scan = _ShapeScan(defs)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and _terminal(node.func) in _JIT_NAMES
                    and node.args):
                continue
            target = node.args[0]
            fn: Optional[ast.AST] = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name):
                fn = _nearest_def(defs, target.id, node.lineno)
            elif isinstance(target, ast.Attribute):
                fn = _nearest_def(defs, target.attr, node.lineno)
            if fn is None:
                continue
            params = scan.params(fn)
            hot = scan.shape_params(fn) - _static_names(node, params)
            if not hot:
                continue
            what = ", ".join(f"`{p}`" for p in sorted(hot))
            fname = getattr(fn, "name", "<lambda>")
            emit(node.lineno, node.col_offset,
                 "jit-dynamic-shape-retrace",
                 f"parameter(s) {what} of jitted `{fname}` reach a "
                 f"shape-constructor position without static_argnums/"
                 f"static_argnames: a Python-value-derived dim at the "
                 f"jit boundary retraces per distinct value (or dies "
                 f"as a tracer) — mark it static, or close over it "
                 f"and key a compiled-fn cache by the dim "
                 f"(flux.kernels.segment_counts)")
