"""fbtpu-memscope: the host copy-census and buffer-escape analyzer.

The zero-copy ingest work (sidecar offset tables, mmap replay, the
``_buf_arg`` ctypes pass-through) only stays zero-copy if the tree can
*see* every host pass over record bytes. This module makes the host
memory plane reviewable the way fbtpu-xray made the PCIe plane
reviewable: it walks, from every ingest entry
(``input_log_append`` / ``input_event_append`` / ``_ingest_raw`` and
the backlog replay root ``_read_chunk_file``), the same-module call
closure and counts the **materialization passes** (``bytes()`` /
``bytearray()`` / ``b"".join`` / ``.copy()`` / re-encode) and **byte
walks** (msgpack ``Unpacker`` decode, ``native.scan_offsets`` /
``count_records``) each record pays, and it cross-references the
``core.copywitness`` instrumentation sites against a declared symbolic
byte budget evaluated at ``COPY_PARAMS`` (``registry.BUDGET_PARAMS``
plus the canonical record payload ``N``).

The census is kept honest two ways:

- **statically**: every ``copywitness.count("<site>", ...)`` call in
  the census modules must have a budget entry in ``WITNESS_SHAPES``
  (an unbudgeted site is a ``copy-budget-regression``), and every
  budget entry must still exist in source (stale entries surface too);
- **dynamically**: the ``FBTPU_COPY_WITNESS=1`` runtime witness
  accumulates (events, bytes) per site, and the tier-1 crosscheck
  asserts the static census is a superset of whatever the witness
  observed (``witness_crosscheck``).

On top of the census, four rules (suppress with
``# fbtpu-lint: allow(<rule>)`` + justification; shipped debt is
baselined in ``analysis/copy_budget.json`` under the
``(path, rule, message)`` key scheme):

- ``host-redundant-copy`` — the same pure expression is materialized
  twice (``bytes(x)`` … ``bytes(x)``) in one function with no rebind
  between: the second pass re-copies identical bytes.
- ``host-decode-then-restage`` — a value decoded from msgpack bytes
  (``Unpacker`` / ``unpackb``) flows into a re-encode
  (``packb`` / ``pack_event``) in the same function: the record was
  walked, heap-objectified, and re-serialized when a raw-byte slice
  (offset sidecar) carries it through untouched.
- ``host-mutable-view-escape`` — a view over the per-thread staging
  arena (``native.stage_field`` result, ``np.frombuffer`` /
  ``memoryview`` over an ``_arena`` / ``_tls`` buffer) escapes the
  function by return or attribute store without a ``bytes()``
  materialization: the next stage call rewrites those bytes under the
  caller.
- ``mmap-lifetime-escape`` — a view derived from ``mmap.mmap`` escapes
  by return / attribute store / container append without ``bytes()``:
  the buffer outlives the map and faults (or silently mutates) after
  close.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from . import Finding, Module, Rule
from .registry import BUDGET_PARAMS

__all__ = [
    "MemscopeRules", "build_copy_census", "census_snapshot",
    "compare_copy_budget", "canonical_copy_env", "witness_crosscheck",
    "COPY_PARAMS", "WITNESS_SHAPES", "INGEST_ENTRIES", "ELIMINATED",
]

#: Host-memory modules in census scope (also the rule scope): the
#: ingest/persistence data plane plus the ctypes boundary.
SCOPES = ("fluentbit_tpu/core/", "fluentbit_tpu/codec/",
          "fluentbit_tpu/native/")

#: Modules the census walks (ingest entries + witness sites live here).
CENSUS_MODULES = ("core/engine.py", "core/storage.py", "codec/chunk.py")

#: Copy-census walk roots: the three ingest entries plus the backlog
#: replay root (crash recovery re-pays host copies too).
INGEST_ENTRIES = ("input_log_append", "input_event_append",
                  "_ingest_raw", "_read_chunk_file")

#: registry.BUDGET_PARAMS plus the canonical record payload bytes the
#: symbolic per-record costs evaluate at. Kept memscope-local: the
#: launch-budget gate compares its own params and must not see N.
COPY_PARAMS: Dict[str, int] = dict(BUDGET_PARAMS, N=256)

#: Symbolic per-record byte cost of every copywitness site, split by
#: kind: a "copy" materializes record bytes into a new buffer, a
#: "walk" traverses them in place. The census cross-references this
#: table against the ``copywitness.count`` calls actually in source.
WITNESS_SHAPES: Dict[str, Tuple[str, str, str]] = {
    "engine.cond.materialize": (
        "N", "copy",
        "conditional-routing payload handed to the route splitter as "
        "one contiguous buffer (only when the pool returned parts)"),
    "engine.decoded.materialize": (
        "N", "copy",
        "decoded-ingest payload materialized once before "
        "write-through + routing (was twice before the census)"),
    "chunk.buf.materialize": (
        "N", "copy",
        "chunk.buf setter adopting a non-bytes payload (bytes "
        "payloads are adopted copy-free)"),
    "chunk.append.materialize": (
        "N", "copy",
        "chunk.append normalizing a non-bytes record (bytes records "
        "are appended copy-free)"),
    "storage.write.offset_scan": (
        "N", "walk",
        "native.scan_offsets pass building the sidecar offset table "
        "at write-through time (callers that already know the record "
        "ends skip it)"),
    "storage.replay.decode_walk": (
        "N", "walk",
        "full msgpack Unpacker walk of a replayed chunk — the "
        "fallback the sidecar fast path eliminates"),
    "storage.replay.validate_walk": (
        "N", "walk",
        "native.count_records validation of a non-FINAL sidecar "
        "before its offsets are trusted (C walk, no heap objects)"),
    "storage.replay.materialize": (
        "N", "copy",
        "mmap replay materializing the covered payload span into "
        "adoptable bytes before the map closes"),
}

#: The shipped copy passes this PR eliminated — the ledger the
#: committed copy_budget.json carries so the diff stays reviewable.
#: Each entry: (pass, where, bytes_per_record saved, how).
ELIMINATED: Tuple[Dict[str, str], ...] = (
    {"pass": "engine.decoded.double-materialize",
     "where": "core/engine.py input_log_append (decoded branch)",
     "bytes_per_record": "N",
     "how": "payload is materialized once and shared by write-through "
            "and routing instead of bytes(out) twice"},
    {"pass": "engine.cond.double-materialize",
     "where": "core/engine.py input_log_append (cond-routing branch)",
     "bytes_per_record": "N",
     "how": "conditional-routing buffer is materialized once; the "
            "route splitter slices raw bytes by sidecar offsets "
            "instead of re-packing decoded records"},
    {"pass": "storage.replay.double-copy",
     "where": "core/storage.py _read_chunk_file",
     "bytes_per_record": "N",
     "how": "replay adopts the payload bytes directly (chunk.buf "
            "setter no longer re-copies what the reader just built); "
            "untorn files skip the tail slice entirely"},
    {"pass": "native.ctypes.pre-copy",
     "where": "native/__init__.py _buf_arg",
     "bytes_per_record": "N",
     "how": "memoryview/mmap buffers cross the ctypes boundary "
            "zero-copy via np.frombuffer instead of bytes(buf) before "
            "every native call"},
)

_SEVERITY = {
    "host-redundant-copy": "warning",
    "host-decode-then-restage": "warning",
    "host-mutable-view-escape": "error",
    "mmap-lifetime-escape": "error",
}

#: Materialization terminals (each is one copy pass over its argument).
COPY_BUILTINS = frozenset({"bytes", "bytearray"})
ENCODE_CALLS = frozenset({"packb", "pack_event", "pack_events"})
DECODE_CALLS = frozenset({"unpackb", "Unpacker", "decode_events"})
NATIVE_WALKS = frozenset({"scan_offsets", "count_records"})

#: Arena-view taint: names whose chains mention these fragments hold
#: buffers the next native call rewrites.
ARENA_FRAGS = ("arena", "_tls")
ARENA_STAGERS = frozenset({"stage_field", "_arena"})


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _chain_names(node) -> Set[str]:
    out: Set[str] = set()
    while True:
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            break
    if isinstance(node, ast.Name):
        out.add(node.id)
    return out


def _walk_no_nested(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that stays out of nested defs/lambdas (they run later,
    under their own context)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _target_names(targets) -> Set[str]:
    names: Set[str] = set()
    for tgt in targets:
        if isinstance(tgt, ast.Name):
            names.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                if isinstance(e, ast.Name):
                    names.add(e.id)
    return names


def _is_pure_load(node: ast.AST) -> bool:
    """Name / attribute / constant-subscript chains — expressions whose
    second materialization is provably the same bytes (no call can have
    changed what they evaluate to between two adjacent reads)."""
    if isinstance(node, ast.Name):
        return True
    if isinstance(node, ast.Attribute):
        return _is_pure_load(node.value)
    if isinstance(node, ast.Subscript):
        return _is_pure_load(node.value)
    return False


def _is_witness_call(call: ast.Call) -> Optional[str]:
    """``copywitness.count("<site>", ...)`` / ``_cw.count(...)`` → the
    literal site id, else None."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "count"):
        return None
    chain = _chain_names(f.value)
    if not ({"_cw", "copywitness"} & chain):
        return None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


# ---------------------------------------------------------------------
# the copy-census walker (xray's _EntryWalk mold, host-memory terminals)
# ---------------------------------------------------------------------

class _Site:
    __slots__ = ("line", "col", "kind", "what", "in_loop")

    def __init__(self, line, col, kind, what, in_loop):
        self.line, self.col = line, col
        self.kind, self.what = kind, what
        self.in_loop = in_loop

    def as_dict(self) -> Dict[str, Any]:
        return {"line": self.line, "kind": self.kind, "what": self.what,
                "in_loop": self.in_loop}


class _CopyWalk:
    """One ingest entry's same-module closure walk: max-path copy/walk
    pass counts + site collection. Methods of the owning class and
    module-level functions inline by name (cycle-guarded,
    depth-capped — the launchgraph discipline)."""

    def __init__(self, methods: Dict[str, ast.FunctionDef],
                 functions: Dict[str, ast.FunctionDef]):
        self.methods = methods
        self.functions = functions
        self.sites: Dict[Tuple[int, int], _Site] = {}
        self._inlining: Set[str] = set()

    def run(self, fn: ast.FunctionDef) -> Tuple[int, int]:
        return self._fn_body(fn, in_loop=False, depth=0)

    def _fn_body(self, fn: ast.FunctionDef, in_loop: bool,
                 depth: int) -> Tuple[int, int]:
        return self._stmts(fn.body, in_loop, depth)[0:2]

    # right-to-left suffix counting: a branch that returns does not
    # chain into the statements after the if (launchgraph's _stmts,
    # carrying (copies, walks) pairs)

    def _stmts(self, stmts: List[ast.stmt], in_loop: bool,
               depth: int) -> Tuple[int, int, bool]:
        c_suf = w_suf = 0
        terminated = False
        for stmt in reversed(stmts):
            if isinstance(stmt, (ast.Return, ast.Raise)):
                val = stmt.value if isinstance(stmt, ast.Return) \
                    else getattr(stmt, "exc", None)
                c_suf, w_suf = self._expr(val, in_loop, depth) \
                    if val is not None else (0, 0)
                terminated = True
            elif isinstance(stmt, ast.If):
                tc, tw = self._expr(stmt.test, in_loop, depth)
                bc, bw, bt = self._stmts(stmt.body, in_loop, depth)
                ec, ew, et = self._stmts(stmt.orelse, in_loop, depth)
                tb_c = bc if bt else bc + c_suf
                tb_w = bw if bt else bw + w_suf
                te_c = ec if et else ec + c_suf
                te_w = ew if et else ew + w_suf
                # max over alternatives, coupled by total cost
                if tb_c + tb_w >= te_c + te_w:
                    c_suf, w_suf = tc + tb_c, tw + tb_w
                else:
                    c_suf, w_suf = tc + te_c, tw + te_w
                terminated = terminated or (bt and et)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                it = getattr(stmt, "iter", None) or stmt.test
                ic, iw = self._expr(it, in_loop, depth)
                bc, bw, _ = self._stmts(stmt.body, True, depth)
                oc, ow, _ = self._stmts(getattr(stmt, "orelse", []),
                                        in_loop, depth)
                c_suf += ic + bc + oc
                w_suf += iw + bw + ow
            elif isinstance(stmt, ast.Try):
                bc, bw, _ = self._stmts(stmt.body, in_loop, depth)
                hc = hw = 0
                for handler in stmt.handlers:
                    cc, cw, _ = self._stmts(handler.body, in_loop, depth)
                    if cc + cw > hc + hw:
                        hc, hw = cc, cw
                oc, ow, _ = self._stmts(stmt.orelse, in_loop, depth)
                fc, fw, _ = self._stmts(stmt.finalbody, in_loop, depth)
                c_suf += bc + hc + oc + fc
                w_suf += bw + hw + ow + fw
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                wc = ww = 0
                for i in stmt.items:
                    cc, cw = self._expr(i.context_expr, in_loop, depth)
                    wc, ww = wc + cc, ww + cw
                bc, bw, bt = self._stmts(stmt.body, in_loop, depth)
                c_suf += wc + bc
                w_suf += ww + bw
                terminated = terminated or bt
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # runs later, under its own call context
            else:
                cc, cw = self._expr(stmt, in_loop, depth)
                c_suf += cc
                w_suf += cw
        return c_suf, w_suf, terminated

    def _expr(self, node: Optional[ast.AST], in_loop: bool,
              depth: int) -> Tuple[int, int]:
        if node is None:
            return 0, 0
        copies = walks = 0
        for sub in _walk_no_nested(node):
            if isinstance(sub, ast.Call):
                c, w = self._call(sub, in_loop, depth)
                copies += c
                walks += w
        return copies, walks

    def _call(self, call: ast.Call, in_loop: bool,
              depth: int) -> Tuple[int, int]:
        t = _terminal(call.func)
        if _is_witness_call(call) is not None:
            return 0, 0  # instrumentation, not a pass of its own
        if t in COPY_BUILTINS and call.args:
            self._site(call, "copy", t, in_loop)
            return 1, 0
        if t == "join" and isinstance(call.func, ast.Attribute):
            self._site(call, "copy", "join", in_loop)
            return 1, 0
        if t == "copy" and isinstance(call.func, ast.Attribute) \
                and not call.args:
            self._site(call, "copy", ".copy()", in_loop)
            return 1, 0
        if t in ENCODE_CALLS:
            self._site(call, "copy", t, in_loop)
            return 1, 0
        if t in DECODE_CALLS or t in NATIVE_WALKS:
            self._site(call, "walk", t, in_loop)
            return 0, 1
        target = self._callee(call)
        if target is not None:
            ic, iw = self._inline(target, in_loop, depth)
            for a in call.args:
                c, w = self._expr(a, in_loop, depth)
                ic, iw = ic + c, iw + w
            return ic, iw
        c = w = 0
        for a in call.args:
            cc, cw = self._expr(a, in_loop, depth)
            c, w = c + cc, w + cw
        return c, w

    def _callee(self, call: ast.Call) -> Optional[ast.FunctionDef]:
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            return self.methods.get(f.attr)
        if isinstance(f, ast.Name):
            return self.functions.get(f.id)
        return None

    def _inline(self, fn: ast.FunctionDef, in_loop: bool,
                depth: int) -> Tuple[int, int]:
        if depth >= 6 or fn.name in self._inlining:
            return 0, 0
        self._inlining.add(fn.name)
        try:
            return self._fn_body(fn, in_loop, depth + 1)
        finally:
            self._inlining.discard(fn.name)

    def _site(self, call: ast.Call, kind: str, what: str,
              in_loop: bool) -> None:
        key = (call.lineno, call.col_offset)
        if key not in self.sites:
            self.sites[key] = _Site(call.lineno, call.col_offset, kind,
                                    what, in_loop)


class _ModuleScan:
    """All ingest entries + witness sites of one module."""

    def __init__(self, module: Module):
        self.module = module
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.classes: List[ast.ClassDef] = []
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes.append(node)

    def chains(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for cls in self.classes:
            methods = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for entry in INGEST_ENTRIES:
                fn = methods.get(entry)
                if fn is None:
                    continue
                walk = _CopyWalk(methods, self.functions)
                copies, walks = walk.run(fn)
                out.append({
                    "module": self.module.path,
                    "cls": cls.name,
                    "entry": entry,
                    "line": fn.lineno,
                    "copy_passes": copies,
                    "walk_passes": walks,
                    "sites": [s.as_dict() for s in
                              sorted(walk.sites.values(),
                                     key=lambda s: (s.line, s.col))],
                })
        return out

    def witness_sites(self) -> Dict[str, int]:
        """site id → first line of its ``copywitness.count`` call."""
        out: Dict[str, int] = {}
        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.Call):
                site = _is_witness_call(node)
                if site is not None and site not in out:
                    out[site] = node.lineno
        return out


# ---------------------------------------------------------------------
# the four rules
# ---------------------------------------------------------------------

class MemscopeRules(Rule):
    name = "memscope"  # umbrella; findings carry precise rules
    description = ("fbtpu-memscope host-memory rules: redundant "
                   "materializations, decode-then-restage round-trips, "
                   "arena-view and mmap-view lifetime escapes")

    RULE_NAMES = ("host-redundant-copy", "host-decode-then-restage",
                  "host-mutable-view-escape", "mmap-lifetime-escape")

    def check(self, module: Module) -> List[Finding]:
        if not any(s in module.path for s in SCOPES):
            return []
        out: List[Finding] = []
        flagged: Set[Tuple[int, str]] = set()

        def emit(line: int, col: int, rule: str, message: str) -> None:
            if (line, rule) in flagged or module.allowed(rule, line):
                return
            flagged.add((line, rule))
            out.append(Finding(module.path, line, col, rule, message,
                               _SEVERITY[rule]))

        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._redundant_copy(node, emit)
                self._decode_restage(node, emit)
                self._view_escape(node, emit)
        out.sort(key=lambda f: (f.line, f.col, f.rule))
        return out

    # -- host-redundant-copy ------------------------------------------

    def _redundant_copy(self, fn, emit) -> None:
        hits: Dict[str, List[ast.Call]] = {}
        for sub in _walk_no_nested(fn):
            if isinstance(sub, ast.Call) \
                    and _terminal(sub.func) in COPY_BUILTINS \
                    and len(sub.args) == 1 \
                    and _is_pure_load(sub.args[0]):
                hits.setdefault(ast.dump(sub.args[0]), []).append(sub)
        if not any(len(v) > 1 for v in hits.values()):
            return
        # sibling If arms are alternatives, not repeats
        arms: List[Tuple[Set[int], Set[int]]] = []
        for sub in _walk_no_nested(fn):
            if isinstance(sub, ast.If):
                body = {id(n) for s in sub.body for n in ast.walk(s)}
                els = {id(n) for s in sub.orelse for n in ast.walk(s)}
                arms.append((body, els))
        assigns = sorted(
            (s for s in _walk_no_nested(fn)
             if isinstance(s, (ast.Assign, ast.AugAssign))),
            key=lambda s: s.lineno)
        for key, calls in hits.items():
            if len(calls) < 2:
                continue
            calls.sort(key=lambda c: (c.lineno, c.col_offset))
            first, second = calls[0], calls[1]
            if any((id(first) in b and id(second) in e)
                   or (id(first) in e and id(second) in b)
                   for b, e in arms):
                continue
            names = {n.id for n in ast.walk(first.args[0])
                     if isinstance(n, ast.Name)}
            rebound = False
            for a in assigns:
                if first.lineno < a.lineno <= second.lineno:
                    tgts = _target_names(
                        a.targets if isinstance(a, ast.Assign)
                        else [a.target])
                    if tgts & names:
                        rebound = True
                        break
            if rebound:
                continue
            src = ast.unparse(first.args[0]) \
                if hasattr(ast, "unparse") else "the same buffer"
            emit(second.lineno, second.col_offset, "host-redundant-copy",
                 f"`{_terminal(second.func)}({src})` re-materializes "
                 f"bytes already copied at line {first.lineno} with no "
                 f"rebind between — hoist the first materialization and "
                 f"share it")

    # -- host-decode-then-restage -------------------------------------

    def _decode_restage(self, fn, emit) -> None:
        tainted: Set[str] = set()
        stmts = sorted(
            (s for s in _walk_no_nested(fn)
             if isinstance(s, (ast.Assign, ast.For))),
            key=lambda s: s.lineno)
        for s in stmts:
            if isinstance(s, ast.Assign):
                val = s.value
                if isinstance(val, ast.Call):
                    t = _terminal(val.func)
                    inner = (_terminal(val.args[0].func)
                             if val.args and isinstance(val.args[0],
                                                        ast.Call)
                             else None)
                    if t in DECODE_CALLS or inner in DECODE_CALLS:
                        tainted |= _target_names(s.targets)
                elif isinstance(val, ast.Name) and val.id in tainted:
                    tainted |= _target_names(s.targets)
            else:  # for rec in <tainted unpacker>:
                it_names = {n.id for n in ast.walk(s.iter)
                            if isinstance(n, ast.Name)}
                has_decode = any(
                    isinstance(n, ast.Call)
                    and _terminal(n.func) in DECODE_CALLS
                    for n in ast.walk(s.iter))
                if (it_names & tainted) or has_decode:
                    tainted |= _target_names([s.target])
        if not tainted:
            return
        for sub in _walk_no_nested(fn):
            if not (isinstance(sub, ast.Call)
                    and _terminal(sub.func) in ENCODE_CALLS):
                continue
            for arg in sub.args:
                names = {n.id for n in ast.walk(arg)
                         if isinstance(n, ast.Name)}
                if names & tainted:
                    emit(sub.lineno, sub.col_offset,
                         "host-decode-then-restage",
                         f"`{_terminal(sub.func)}` re-encodes "
                         f"`{sorted(names & tainted)[0]}`, which was "
                         f"decoded from msgpack bytes in this function: "
                         f"the record round-trips through heap objects "
                         f"— slice the raw bytes by record offsets "
                         f"(the sidecar table) instead")
                    break

    # -- host-mutable-view-escape + mmap-lifetime-escape --------------

    @staticmethod
    def _classify(val, arena: Set[str],
                  mmapped: Set[str]) -> Tuple[bool, bool]:
        """(aliases the staging arena, aliases an mmap) for a value
        expression — bytes()/tobytes() materializations break taint."""
        if isinstance(val, ast.Name):
            return val.id in arena, val.id in mmapped
        if isinstance(val, ast.Subscript):
            return MemscopeRules._classify(val.value, arena, mmapped)
        if isinstance(val, (ast.Tuple, ast.List)):
            is_a = is_m = False
            for e in val.elts:
                ca, cm = MemscopeRules._classify(e, arena, mmapped)
                is_a, is_m = is_a or ca, is_m or cm
            return is_a, is_m
        if isinstance(val, ast.Call):
            t = _terminal(val.func)
            if t in ("bytes", "tobytes"):
                return False, False
            if t in ARENA_STAGERS:
                return True, False
            if t in ("memoryview", "frombuffer"):
                is_a = is_m = False
                for arg in val.args:
                    chain = _chain_names(arg)
                    if any(frag in c for frag in ARENA_FRAGS
                           for c in chain):
                        is_a = True
                    ca, cm = MemscopeRules._classify(arg, arena, mmapped)
                    is_a, is_m = is_a or ca, is_m or cm
                return is_a, is_m
            if t == "mmap":
                return False, True
        return False, False

    def _view_escape(self, fn, emit) -> None:
        arena: Set[str] = set()
        mmapped: Set[str] = set()
        for s in sorted((s for s in _walk_no_nested(fn)
                         if isinstance(s, ast.Assign)),
                        key=lambda s: s.lineno):
            names = _target_names(s.targets)
            if not names:
                continue
            is_a, is_m = self._classify(s.value, arena, mmapped)
            if is_a:
                arena |= names
            if is_m:
                mmapped |= names
        for sub in _walk_no_nested(fn):
            if isinstance(sub, ast.Return) and sub.value is not None:
                self._escape_sink(sub.value, sub, "return", arena,
                                  mmapped, emit)
            elif isinstance(sub, ast.Assign):
                if any(isinstance(t, ast.Attribute) for t in sub.targets):
                    self._escape_sink(sub.value, sub, "attribute store",
                                      arena, mmapped, emit)
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "append" and sub.args:
                self._escape_sink(sub.args[0], sub, "container append",
                                  arena, mmapped, emit)

    def _escape_sink(self, value, node, how, arena, mmapped,
                     emit) -> None:
        is_a, is_m = self._classify(value, arena, mmapped)
        base = value
        while isinstance(base, ast.Subscript):
            base = base.value
        label = base.id if isinstance(base, ast.Name) else "the view"
        if is_m:
            emit(node.lineno, node.col_offset, "mmap-lifetime-escape",
                 f"{how} of `{label}` leaks a view into an mmap'd "
                 f"chunk file out of the function that owns the map — "
                 f"the buffer faults (or silently changes) after the "
                 f"map closes; materialize with bytes() first")
        elif is_a:
            emit(node.lineno, node.col_offset, "host-mutable-view-escape",
                 f"{how} of `{label}` leaks a mutable view of the "
                 f"per-thread staging arena — the next stage call "
                 f"rewrites these bytes under the caller; materialize "
                 f"with bytes() or stage into a caller buffer "
                 f"(stage_field_into)")


# ---------------------------------------------------------------------
# the census / budget API
# ---------------------------------------------------------------------

def _package_root() -> str:
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _eval_bytes(expr: str, env: Dict[str, int]) -> int:
    return int(eval(expr, {"__builtins__": {}}, dict(env)))  # noqa: S307


def canonical_copy_env(params: Optional[Dict[str, int]] = None
                       ) -> Dict[str, int]:
    """``COPY_PARAMS`` (+ overrides): the canonical evaluation point
    for the per-record copy costs — the committed copy_budget.json is
    evaluated here, so the gate compares like with like."""
    env = dict(COPY_PARAMS)
    if params:
        env.update(params)
    return env


def build_copy_census(root: Optional[str] = None,
                      params: Optional[Dict[str, int]] = None
                      ) -> Dict[str, Any]:
    """Scan the census modules and emit the host copy census: per
    ingest entry the max-path copy/walk pass counts with sites, and
    per copywitness site its symbolic + canonical per-record cost.
    Sites present in source with no ``WITNESS_SHAPES`` budget carry
    ``"unbudgeted": True`` (the gate turns them into regressions);
    budget entries no longer in source surface as stale."""
    import os

    pkg = root or _package_root()
    env = canonical_copy_env(params)
    chains: Dict[str, Any] = {}
    found_sites: Dict[str, Tuple[str, int]] = {}
    for rel in CENSUS_MODULES:
        path = os.path.join(pkg, *rel.split("/"))
        if not os.path.isfile(path):
            continue
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        mod_rel = "fluentbit_tpu/" + rel
        module = Module(mod_rel, source)
        scan = _ModuleScan(module)
        for chain in scan.chains():
            cid = f"{chain['module']}::{chain['cls']}.{chain['entry']}"
            chains[cid] = chain
        for site, line in scan.witness_sites().items():
            found_sites.setdefault(site, (mod_rel, line))
    sites: Dict[str, Any] = {}
    for site, (mod_rel, line) in sorted(found_sites.items()):
        shape = WITNESS_SHAPES.get(site)
        if shape is None:
            sites[site] = {"module": mod_rel, "line": line,
                           "unbudgeted": True}
            continue
        expr, kind, note = shape
        sites[site] = {
            "module": mod_rel, "line": line, "kind": kind,
            "bytes_per_record": expr,
            "bytes_canonical": _eval_bytes(expr, env),
            "note": note,
        }
    stale = sorted(set(WITNESS_SHAPES) - set(found_sites))
    return {
        "version": 1,
        "params": env,
        "chains": dict(sorted(chains.items())),
        "witness_sites": sites,
        "stale_shapes": stale,
    }


def census_snapshot(census: Dict[str, Any]) -> Dict[str, Any]:
    """The regression-gated subset of the census: per-entry pass counts
    and per-site canonical per-record bytes. The committed
    ``analysis/copy_budget.json`` holds this snapshot — the zero-copy
    work lands by SHRINKING it, and any PR that grows a number here
    fails the gate until the budget file says so."""
    chains = {
        cid: {"copy_passes": c["copy_passes"],
              "walk_passes": c["walk_passes"]}
        for cid, c in census["chains"].items()
    }
    sites = {}
    for site, d in census["witness_sites"].items():
        sites[site] = {
            "kind": d.get("kind", "?"),
            "bytes_per_record": int(d.get("bytes_canonical", -1)),
        }
    return {"params": {k: int(v) for k, v in census["params"].items()},
            "chains": chains, "witness_sites": sites}


def compare_copy_budget(current: Dict[str, Any],
                        baseline: Dict[str, Any]
                        ) -> Tuple[List[str], List[str]]:
    """Compare a census snapshot against the committed baseline →
    (regressions, notes). Growth in copy/walk passes per ingest entry,
    a new entry or witness site the baseline has never seen, or a
    per-record byte cost that grew is a regression; improvements are
    notes (regenerate the budget file to claim them)."""
    regressions: List[str] = []
    notes: List[str] = []
    base_chains = baseline.get("chains", {})
    for cid, cur in current.get("chains", {}).items():
        base = base_chains.get(cid)
        if base is None:
            regressions.append(
                f"{cid}: new ingest entry not in copy_budget.json "
                f"({cur['copy_passes']} copy pass(es)/record) — "
                f"baseline it deliberately (--write-copy-budget)")
            continue
        for key in ("copy_passes", "walk_passes"):
            b, c = int(base.get(key, 0)), int(cur.get(key, 0))
            if c > b:
                regressions.append(
                    f"{cid}: {key} grew {b} → {c} (the copy budget "
                    f"gates this — zero-copy PRs shrink it, nothing "
                    f"grows it silently)")
            elif c < b:
                notes.append(
                    f"{cid}: {key} improved {b} → {c}; regenerate "
                    f"copy_budget.json (--write-copy-budget) to claim "
                    f"it")
    for cid in base_chains:
        if cid not in current.get("chains", {}):
            notes.append(f"{cid}: ingest entry gone; regenerate "
                         f"copy_budget.json")
    base_sites = baseline.get("witness_sites", {})
    for site, cur in current.get("witness_sites", {}).items():
        if int(cur.get("bytes_per_record", -1)) < 0:
            regressions.append(
                f"witness site `{site}` has no WITNESS_SHAPES budget "
                f"entry — every copywitness.count site must declare "
                f"its symbolic per-record cost")
            continue
        base = base_sites.get(site)
        if base is None:
            regressions.append(
                f"witness site `{site}` is new — baseline its "
                f"per-record cost deliberately (--write-copy-budget)")
            continue
        b = int(base.get("bytes_per_record", 0))
        c = int(cur.get("bytes_per_record", 0))
        if c > b:
            regressions.append(
                f"witness site `{site}`: per-record bytes grew "
                f"{b} → {c}")
        elif c < b:
            notes.append(f"witness site `{site}`: per-record bytes "
                         f"improved {b} → {c}; regenerate "
                         f"copy_budget.json")
    for site in base_sites:
        if site not in current.get("witness_sites", {}):
            notes.append(f"witness site `{site}` left the source; "
                         f"regenerate copy_budget.json")
    return regressions, notes


def witness_crosscheck(counts: Dict[str, Tuple[int, int]],
                       census: Optional[Dict[str, Any]] = None
                       ) -> List[str]:
    """Static-census ⊇ dynamic-witness check: every site the
    ``FBTPU_COPY_WITNESS`` runtime observed must be a budgeted census
    site — a copy the static plane cannot see is exactly the bug class
    this analyzer exists for. Returns violation messages (empty =
    consistent)."""
    census = census or build_copy_census()
    sites = census["witness_sites"]
    out: List[str] = []
    for site, (events, nbytes) in sorted(counts.items()):
        d = sites.get(site)
        if d is None:
            out.append(
                f"dynamic witness site `{site}` ({events} events, "
                f"{nbytes} bytes) is not in the static census — "
                f"instrumented copy with no copywitness.count call in "
                f"a census module?")
        elif d.get("unbudgeted"):
            out.append(
                f"dynamic witness site `{site}` has no WITNESS_SHAPES "
                f"budget entry")
    return out
