"""Embedding API — the library mode.

Reference: src/flb_lib.c + include/fluent-bit/flb_lib.h:51-99
(flb_create / flb_input / flb_output / flb_filter / flb_*_set / flb_start /
flb_stop / flb_lib_push / flb_output_set_test). This is the test-harness
substrate: inject with in_lib, capture with out_lib callbacks or the
output test-formatter hook.

Usage::

    import fluentbit_tpu as flb
    ctx = flb.create(flush=0.1)
    in_ffd = ctx.input("lib")
    ctx.filter("grep", match="*", regex="log aa")
    out_ffd = ctx.output("lib", callback=cb)
    ctx.start()
    ctx.push(in_ffd, '{"log": "aa"}')
    ctx.stop()
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .core.config import ServiceConfig
from .core.engine import Engine
from .core.plugin import FilterInstance, InputInstance, OutputInstance

# ensure plugin registration
from . import plugins as _plugins  # noqa: F401


class FLBContext:
    """flb_ctx_t equivalent."""

    def __init__(self, **service_props):
        self.service = ServiceConfig()
        for k, v in service_props.items():
            self.service.set(k, v)
        self.engine = Engine(self.service)
        self._handles: list = []

    # -- configuration (returns integer handles like the C API's ffd) --

    def input(self, name: str, **props) -> int:
        ins = self.engine.input(name, **props)
        self._handles.append(ins)
        return len(self._handles) - 1

    def filter(self, name: str, **props) -> int:
        ins = self.engine.filter(name, **props)
        self._handles.append(ins)
        return len(self._handles) - 1

    def output(self, name: str, **props) -> int:
        ins = self.engine.output(name, **props)
        self._handles.append(ins)
        return len(self._handles) - 1

    def custom(self, name: str, **props) -> int:
        """flb_custom: control-plane plugins initialized before the
        pipeline (may create instances programmatically)."""
        ins = self.engine.custom(name, **props)
        self._handles.append(ins)
        return len(self._handles) - 1

    def parser(self, name: str, **props):
        """Create + register a named parser (flb_parser_create /
        parsers_file [PARSER] section equivalent)."""
        return self.engine.parser(name, **props)

    def ml_parser(self, name: str, rules=None, **kw):
        """Create + register a multiline parser ([MULTILINE_PARSER])."""
        return self.engine.ml_parser(name, rules, **kw)

    def sp_task(self, sql: str):
        """Register a stream-processor SQL query ([STREAM_TASK] Exec)."""
        return self.engine.sp_task(sql)

    def set(self, ffd: int, **props) -> None:
        """flb_input_set / flb_output_set / flb_filter_set."""
        ins = self._handles[ffd]
        for k, v in props.items():
            ins.set(k, v)

    def service_set(self, **props) -> None:
        for k, v in props.items():
            self.service.set(k, v)

    def output_set_test(self, ffd: int, mode: str, callback: Callable) -> None:
        """flb_output_set_test: 'formatter' bypasses delivery and hands the
        formatted payload to the test (src/flb_engine_dispatch.c:101-137)."""
        ins = self._handles[ffd]
        if not isinstance(ins, OutputInstance):
            raise TypeError("handle is not an output")
        if mode != "formatter":
            raise ValueError(f"unknown test mode {mode!r}")
        ins.test_formatter = callback

    # -- lifecycle --

    def start(self) -> None:
        self.engine.start()

    def stop(self) -> None:
        self.engine.stop()

    # -- data --

    def push(self, ffd: int, data) -> int:
        """flb_lib_push: inject JSON into an in_lib instance."""
        ins = self._handles[ffd]
        if not isinstance(ins, InputInstance):
            raise TypeError("handle is not an input")
        push = getattr(ins.plugin, "push", None)
        if push is None:
            raise TypeError(f"input {ins.name} does not accept pushes")
        return push(data)

    def flush_now(self) -> None:
        self.engine.flush_now()

    @property
    def metrics(self):
        return self.engine.metrics


def create(**service_props) -> FLBContext:
    """flb_create equivalent."""
    return FLBContext(**service_props)
