"""Batch assembly — variable-length records → fixed-shape device arrays.

The staging layer between msgpack chunks and the TPU kernels: field values
(or whole lines) become a ``[B, L] uint8`` padded matrix + ``lengths`` i32.
Records longer than L take the CPU fallback path (the same pattern the
reference uses for locked oversized chunks, src/flb_input_chunk.c:3135).

A C++ packer (native/staging.cpp) can replace the numpy loop; this is the
semantic reference implementation.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class Batch:
    """Fixed-shape batch of byte strings.

    batch   : uint8 [B, L]  padded with 0 (pad positions are identified by
              lengths, not by the pad byte value)
    lengths : int32 [B]     valid byte count per row; -1 marks an INVALID
              row (missing field) which must never match
    overflow: indices of source strings longer than L (CPU fallback)
    """

    __slots__ = ("batch", "lengths", "overflow", "n")

    def __init__(self, batch: np.ndarray, lengths: np.ndarray,
                 overflow: List[int], n: int):
        self.batch = batch
        self.lengths = lengths
        self.overflow = overflow
        self.n = n


def assemble(
    values: Sequence[Optional[bytes]],
    max_len: int = 512,
    pad_batch_to: Optional[int] = None,
) -> Batch:
    """Pack byte strings into a padded [B, L] uint8 matrix.

    ``None`` entries (missing record-accessor field) get length -1.
    Strings longer than ``max_len`` are recorded in ``overflow`` and get
    length -2 (kernel treats them as invalid; caller resolves on CPU).
    ``pad_batch_to`` rounds B up (to a multiple of the device count or a
    fixed bucket) so jit sees a stable shape and never recompiles.
    """
    n = len(values)
    B = pad_batch_to if pad_batch_to and pad_batch_to >= n else n
    batch = np.zeros((B, max_len), dtype=np.uint8)
    lengths = np.full((B,), -1, dtype=np.int32)
    overflow: List[int] = []
    for i, v in enumerate(values):
        if v is None:
            continue
        ln = len(v)
        if ln > max_len:
            overflow.append(i)
            lengths[i] = -2
            continue
        if ln:
            batch[i, :ln] = np.frombuffer(v, dtype=np.uint8)
        lengths[i] = ln
    return Batch(batch, lengths, overflow, n)


#: cap on bucket * max_len padding (bytes) — the top bucket (65536)
#: times a long-syslog row width (64 KiB max_len) would allocate 4 GiB
#: of mostly-pad staging per batch
_PAD_BYTE_BUDGET = 256 * 1024 * 1024


def bucket_size(n: int, buckets: Sequence[int] = (256, 1024, 4096, 16384, 65536),
                max_len: Optional[int] = None,
                byte_budget: int = _PAD_BYTE_BUDGET,
                multiple_of: Optional[int] = None) -> int:
    """Round a batch size up to a small set of jit-stable shapes.

    ``max_len`` (the per-row byte width the caller will allocate)
    clamps the rounding: the padded ``bucket * max_len`` staging matrix
    must stay inside ``byte_budget``. The smallest bucket ≥ n is also
    the cheapest one that fits n, so when IT overflows the budget no
    bucket can serve — long-record configurations then take minimal
    64-record-granularity padding instead of overflowing the pad
    allocation (regression test: tests/test_batch_filters.py; the
    shapes become chunk-size-dependent there, which is the acceptable
    cost of not allocating gigabytes of pad).

    ``multiple_of`` additionally aligns the result to the mesh size on
    the partitioned device path (NamedSharding requires the sharded
    batch dimension divisible by the device count; the power-of-two
    buckets already are for power-of-two meshes, but TPU slices come
    in non-power shapes too)."""
    pick = None
    for b in buckets:
        if n <= b:
            pick = b
            break
    if pick is None:
        pick = ((n + buckets[-1] - 1) // buckets[-1]) * buckets[-1]
    if max_len and pick * max_len > byte_budget:
        # minimal jit-stable padding (the n records must stage
        # regardless of what they cost)
        pick = ((n + 63) // 64) * 64
    if multiple_of and multiple_of > 1:
        pick = ((pick + multiple_of - 1) // multiple_of) * multiple_of
    return pick
