"""Batch assembly — variable-length records → fixed-shape device arrays.

The staging layer between msgpack chunks and the TPU kernels: field values
(or whole lines) become a ``[B, L] uint8`` padded matrix + ``lengths`` i32.
Records longer than L take the CPU fallback path (the same pattern the
reference uses for locked oversized chunks, src/flb_input_chunk.c:3135).

A C++ packer (native/staging.cpp) can replace the numpy loop; this is the
semantic reference implementation.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class Batch:
    """Fixed-shape batch of byte strings.

    batch   : uint8 [B, L]  padded with 0 (pad positions are identified by
              lengths, not by the pad byte value)
    lengths : int32 [B]     valid byte count per row; -1 marks an INVALID
              row (missing field) which must never match
    overflow: indices of source strings longer than L (CPU fallback)
    """

    __slots__ = ("batch", "lengths", "overflow", "n")

    def __init__(self, batch: np.ndarray, lengths: np.ndarray,
                 overflow: List[int], n: int):
        self.batch = batch
        self.lengths = lengths
        self.overflow = overflow
        self.n = n


def assemble(
    values: Sequence[Optional[bytes]],
    max_len: int = 512,
    pad_batch_to: Optional[int] = None,
) -> Batch:
    """Pack byte strings into a padded [B, L] uint8 matrix.

    ``None`` entries (missing record-accessor field) get length -1.
    Strings longer than ``max_len`` are recorded in ``overflow`` and get
    length -2 (kernel treats them as invalid; caller resolves on CPU).
    ``pad_batch_to`` rounds B up (to a multiple of the device count or a
    fixed bucket) so jit sees a stable shape and never recompiles.
    """
    n = len(values)
    B = pad_batch_to if pad_batch_to and pad_batch_to >= n else n
    batch = np.zeros((B, max_len), dtype=np.uint8)
    lengths = np.full((B,), -1, dtype=np.int32)
    overflow: List[int] = []
    for i, v in enumerate(values):
        if v is None:
            continue
        ln = len(v)
        if ln > max_len:
            overflow.append(i)
            lengths[i] = -2
            continue
        if ln:
            batch[i, :ln] = np.frombuffer(v, dtype=np.uint8)
        lengths[i] = ln
    return Batch(batch, lengths, overflow, n)


def bucket_size(n: int, buckets: Sequence[int] = (256, 1024, 4096, 16384, 65536)) -> int:
    """Round a batch size up to a small set of jit-stable shapes."""
    for b in buckets:
        if n <= b:
            return b
    return ((n + buckets[-1] - 1) // buckets[-1]) * buckets[-1]
