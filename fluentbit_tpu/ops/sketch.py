"""Device sketches — HyperLogLog + count-min, the psum/pmax showcase.

The north-star additions over the reference's filter_log_to_metrics
(BASELINE.md config 4: "count-min/HLL cardinality" — the reference
supports only counter/gauge/histogram). Batches of field values are
hashed ON DEVICE (FNV-1a over the padded ``[B, L] uint8`` staging
layout, masked by lengths — one fused jit with the register updates),
and sketch state lives as device arrays:

- HLL: 2^p registers of max-rank; multi-device merge is ``lax.pmax``
  over the mesh axis (register-wise max IS the union of sketches).
- Count-min: ``[d, w]`` counters via Kirsch-Mitzenmacher double
  hashing; multi-device merge is ``lax.psum`` (counter sum IS the
  union).

Both merges ride ICI on a real mesh — sketches are the rare aggregate
whose distributed reduction is exact, which is why they are the chosen
showcase for the metrics-reduction contract (SURVEY §2.4).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

try:
    import jax
    import jax.numpy as jnp
    from jax import lax

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

FNV_OFFSET = np.uint32(0x811C9DC5)
FNV_PRIME = np.uint32(0x01000193)


def _fnv1a_scan(batch, lengths):
    """FNV-1a 32-bit over valid bytes of each row: [B, L] u8 → [B] u32.

    Pad positions multiply by 1 (identity) so fixed shapes stay exact.
    """
    B, L = batch.shape
    pos = jnp.arange(L, dtype=jnp.int32)
    valid = pos[None, :] < lengths[:, None]  # [B, L]
    data = batch.astype(jnp.uint32)

    def step(h, xs):
        byte, ok = xs
        nh = (h ^ byte) * FNV_PRIME
        return jnp.where(ok, nh, h), None

    # ^ 0*lengths: ties the carry to the (possibly mesh-sharded) batch so
    # its varying-axes annotation matches the scan output under shard_map
    h0 = jnp.full((B,), FNV_OFFSET, dtype=jnp.uint32) ^ (
        lengths.astype(jnp.uint32) * 0
    )
    h, _ = lax.scan(step, h0, (data.T, valid.T))
    # FNV's high bits avalanche poorly; finalize so index bits (taken
    # from the top for HLL) are uniform
    return _mix(h)


def _mix(h):
    """murmur3 fmix32 — independent second hash for double hashing."""
    h = h ^ (h >> 16)
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def hll_index_rank(batch, lengths, p: int):
    """Per-row HLL (register index, rank) over a staged batch — the
    hash/rank half of :meth:`HyperLogLog._update_impl`, factored out so
    the fused flux absorb program (flux/kernels.build_fused_absorb) can
    scatter into a *per-group* [Gp, m] register stack with the exact
    same math. Invalid rows (length < 0) get rank 0, which every
    scatter-max treats as a no-op."""
    h = _fnv1a_scan(batch, lengths)
    idx = (h >> np.uint32(32 - p)).astype(jnp.int32)
    rest = h << np.uint32(p)
    # clz via bit-smear + popcount (integer-exact, TPU-friendly)
    x = rest
    for s in (1, 2, 4, 8, 16):
        x = x | (x >> np.uint32(s))
    nlz = 32 - lax.population_count(x).astype(jnp.int32)
    # rank = leading zeros of the remaining (32-p) bits + 1; rest==0
    # (nlz 32) saturates at the max rank for a (32-p)-bit suffix
    rank = jnp.minimum(nlz + 1, 32 - p + 1)
    valid = lengths >= 0
    return idx, jnp.where(valid, rank, 0)


class HyperLogLog:
    """HLL over 32-bit hashes; registers jnp int32 [2^p]."""

    def __init__(self, p: int = 14):
        if not HAVE_JAX:
            raise RuntimeError("jax is unavailable")
        self.p = p
        self.m = 1 << p
        # registers start host-side (numpy) and move to the device when
        # the backend attaches — constructing a sketch must never block
        # on backend init (see ops.device); add_cpu is bit-identical to
        # the device kernel, so pre-attach updates stay exact
        self.registers = np.zeros((self.m,), dtype=np.int32)
        self._update = None

    def _device_jit(self, wait: bool = False):
        """The update jit if the backend is attached (built once),
        WITHOUT touching register state — safe from the fbtpu-armor
        watched worker threads (the only race is a benign
        double-assignment of an equivalent jit)."""
        if self._update is None:
            from . import device

            ok = device.wait(max(60.0, device.default_wait())) if wait \
                else device.ready()
            if not ok:
                if not wait:
                    device.attach_async()
                return None
            self._update = jax.jit(self._update_impl)
        return self._update

    def _ensure_device(self, wait: bool = False) -> bool:
        if self._device_jit(wait) is None:
            return False
        if isinstance(self.registers, np.ndarray):
            self.registers = jnp.asarray(self.registers)
        return True

    def _update_impl(self, registers, batch, lengths):
        idx, rank = hll_index_rank(batch, lengths, self.p)
        return registers.at[idx].max(rank)

    def device_registers(self, batch: np.ndarray, lengths: np.ndarray,
                         wait: bool = False, registers=None):
        """Compute the post-update register set on the device WITHOUT
        committing it or mutating ANY sketch state (None when the
        backend isn't attached yet). The fbtpu-armor flux lane runs
        this inside its watched launch from an explicit pre-launch
        ``registers`` snapshot and commits on the caller thread only
        after the launch resolves — a soft-killed (abandoned) launch
        computes into a discarded local and can never clobber
        registers a fallback or later batch already advanced."""
        fn = self._device_jit(wait)
        if fn is None:
            return None
        regs = self.registers if registers is None else registers
        return fn(jnp.asarray(regs), jnp.asarray(batch),
                  jnp.asarray(lengths))

    def update(self, batch: np.ndarray, lengths: np.ndarray) -> None:
        """Absorb a staged [B, L] batch (rows with length<0 ignored).
        Falls back to the bit-identical host twins while the device
        backend is still attaching — the C batch kernel when the native
        plane is loaded (fbtpu_hll_update; the flux ingest-rate path),
        else the Python per-row loop."""
        if self._ensure_device():
            self.registers = self._update(
                self.registers, jnp.asarray(batch), jnp.asarray(lengths)
            )
            return
        self.host_update(batch, lengths)

    def host_update(self, batch: np.ndarray, lengths: np.ndarray) -> None:
        """Host-pinned batch update — never touches the device backend.
        The C batch kernel (fbtpu_hll_update) when the native plane is
        loaded and the registers are still host-side, else the
        bit-identical Python per-row loop. The flux plane uses this
        directly when the attached backend IS the host CPU (the jit
        round trip loses to the C walk there)."""
        from .. import native as _native

        if isinstance(self.registers, np.ndarray) and _native.hll_update(
                self.registers, batch, lengths, self.p):
            return
        for i in range(batch.shape[0]):
            ln = int(lengths[i])
            if ln >= 0:
                self.add_cpu(batch[i, :ln].tobytes())

    def add_cpu(self, value: bytes) -> None:
        """Host-side single-value update (overflow-row fallback) — same
        hash/rank math as the device kernel."""
        h = int(_hash32_cpu(value))
        idx = h >> (32 - self.p)
        rest = (h << self.p) & 0xFFFFFFFF
        nlz = 32 - rest.bit_length()
        rank = min(nlz + 1, 32 - self.p + 1)
        if isinstance(self.registers, np.ndarray):
            self.registers[idx] = max(int(self.registers[idx]), rank)
        else:
            self.registers = self.registers.at[idx].max(rank)

    def merge_registers(self, other) -> None:
        if isinstance(self.registers, np.ndarray):
            self.registers = np.maximum(self.registers, np.asarray(other))
        else:
            self.registers = jnp.maximum(self.registers, other)

    def estimate(self) -> float:
        """Standard HLL estimator with small/large range corrections."""
        regs = np.asarray(self.registers)
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        e = alpha * m * m / np.sum(np.exp2(-regs.astype(np.float64)))
        if e <= 2.5 * m:
            v = int(np.sum(regs == 0))
            if v > 0:
                e = m * np.log(m / v)
        elif e > (1 << 32) / 30.0:
            e = -(2.0 ** 32) * np.log(1.0 - e / 2.0 ** 32)
        return float(e)


class CountMin:
    """Count-min sketch [d, w]; conservative point queries via row-min."""

    def __init__(self, depth: int = 4, width: int = 16384):
        if not HAVE_JAX:
            raise RuntimeError("jax is unavailable")
        self.depth = depth
        self.width = width
        # host-side until the backend attaches (see HyperLogLog); the
        # dtype matches what the device table will use so the CPU-pinned
        # path keeps the same overflow envelope
        self._dtype = (np.int64 if jax.config.jax_enable_x64
                       else np.int32)
        self.table = np.zeros((depth, width), dtype=self._dtype)
        self._update = None
        self._row_ids = np.arange(depth, dtype=np.uint32)

    def _device_jit(self, wait: bool = False):
        """Non-mutating jit accessor (see HyperLogLog._device_jit)."""
        if self._update is None:
            from . import device

            ok = device.wait(max(60.0, device.default_wait())) if wait \
                else device.ready()
            if not ok:
                if not wait:
                    device.attach_async()
                return None
            self._update = jax.jit(self._update_impl)
        return self._update

    def _ensure_device(self, wait: bool = False) -> bool:
        if self._device_jit(wait) is None:
            return False
        if isinstance(self.table, np.ndarray):
            self.table = jnp.asarray(self.table, dtype=self._dtype)
        return True

    def _hashes(self, batch, lengths):
        h1 = _fnv1a_scan(batch, lengths)
        h2 = _mix(h1) | np.uint32(1)  # odd → full-period double hashing
        rows = jnp.asarray(self._row_ids)[:, None]  # [d, 1]
        cols = (h1[None, :] + rows * h2[None, :]) % np.uint32(self.width)
        return cols.astype(jnp.int32)  # [d, B]

    def _update_impl(self, table, batch, lengths, weights):
        cols = self._hashes(batch, lengths)  # [d, B]
        valid = (lengths >= 0).astype(table.dtype) * weights.astype(table.dtype)
        d = self.depth

        def body(r, tb):
            return tb.at[r, cols[r]].add(valid)

        return lax.fori_loop(0, d, body, table)

    def device_table(self, batch: np.ndarray, lengths: np.ndarray,
                     weights: Optional[np.ndarray] = None,
                     wait: bool = False, table=None):
        """Compute the post-update table on the device WITHOUT
        committing or mutating any sketch state (None until attached)
        — the same snapshot-in/commit-on-finish protocol as
        :meth:`HyperLogLog.device_registers`."""
        fn = self._device_jit(wait)
        if fn is None:
            return None
        if weights is None:
            weights = np.ones((batch.shape[0],), dtype=np.int32)
        tbl = self.table if table is None else table
        return fn(
            jnp.asarray(tbl, dtype=self._dtype), jnp.asarray(batch),
            jnp.asarray(lengths), jnp.asarray(weights),
        )

    def update(self, batch: np.ndarray, lengths: np.ndarray,
               weights: Optional[np.ndarray] = None) -> None:
        B = batch.shape[0]
        unit_weights = weights is None
        if weights is None:
            weights = np.ones((B,), dtype=np.int32)
        if self._ensure_device():
            self.table = self._update(
                self.table, jnp.asarray(batch), jnp.asarray(lengths),
                jnp.asarray(weights),
            )
            return
        self.host_update(batch, lengths, weights if not unit_weights
                         else None)

    def host_update(self, batch: np.ndarray, lengths: np.ndarray,
                    weights: Optional[np.ndarray] = None) -> None:
        """Host-pinned batch update (see HyperLogLog.host_update): the C
        batch twin for the weight-1 shape, else the Python loop."""
        from .. import native as _native

        if weights is None and isinstance(self.table, np.ndarray) \
                and _native.cms_update(self.table, batch, lengths):
            return
        B = batch.shape[0]
        if weights is None:
            weights = np.ones((B,), dtype=np.int32)
        for i in range(B):
            ln = int(lengths[i])
            if ln >= 0:
                self.add_cpu(batch[i, :ln].tobytes(), int(weights[i]))

    def merge_table(self, other) -> None:
        if isinstance(self.table, np.ndarray):
            self.table = self.table + np.asarray(other)
        else:
            self.table = self.table + other

    def _cols_cpu(self, value: bytes):
        """Column per row for one value — bit-identical to the device
        kernel (uint32 wrap BEFORE the modulo)."""
        h1 = int(_hash32_cpu(value))
        h2 = int(_mix_np(np.uint32(h1))) | 1
        return [((h1 + r * h2) & 0xFFFFFFFF) % self.width
                for r in range(self.depth)]

    def add_cpu(self, value: bytes, weight: int = 1) -> None:
        """Host-side single-value update (overflow-row fallback)."""
        cols = self._cols_cpu(value)
        rows = np.arange(self.depth)
        if isinstance(self.table, np.ndarray):
            self.table[rows, np.asarray(cols)] += weight
        else:
            self.table = self.table.at[rows, np.asarray(cols)].add(weight)

    def query(self, value: bytes) -> int:
        """Point estimate for one value (row-min)."""
        table = np.asarray(self.table)
        return int(min(
            int(table[r, c]) for r, c in enumerate(self._cols_cpu(value))
        ))

    def query_many(self, values) -> list:
        """Point estimates for many values with ONE device→host table
        copy (per-value query() would sync the device each time)."""
        table = np.asarray(self.table)
        out = []
        for v in values:
            out.append(int(min(
                int(table[r, c]) for r, c in enumerate(self._cols_cpu(v))
            )))
        return out


def _hash32_cpu(value: bytes) -> np.uint32:
    """Finalized FNV-1a — bit-identical to _fnv1a_scan on the device."""
    h = int(FNV_OFFSET)
    for b in value:
        h = ((h ^ b) * int(FNV_PRIME)) & 0xFFFFFFFF
    return _mix_np(np.uint32(h))


def _mix_np(h: np.uint32) -> np.uint32:
    h = np.uint32(h)
    with np.errstate(over="ignore"):
        h ^= h >> np.uint32(16)
        h = np.uint32((int(h) * 0x85EBCA6B) & 0xFFFFFFFF)
        h ^= h >> np.uint32(13)
        h = np.uint32((int(h) * 0xC2B2AE35) & 0xFFFFFFFF)
        h ^= h >> np.uint32(16)
    return h


# -- multi-device (SPMD) sketch update: batch sharded, state merged --

def _mesh_key(mesh) -> tuple:
    """Structural cache key: equal meshes (same axes + devices) share a
    compiled step; keying by id(mesh) would miss every freshly
    constructed-but-identical Mesh and pin dead meshes forever.
    (Shared helper in ops.mesh — same key the grep/flux caches use.)"""
    from .mesh import mesh_key

    return mesh_key(mesh)


def _pad_to_mesh(mesh, batch, lengths):
    """Pad the batch axis up to the mesh size through the one shared
    helper (``ops.mesh.pad_to_devices``) — the call fbtpu-speccheck
    recognizes as discharging the B-divisibility obligation of the
    sharded in_specs below. Pad rows carry length -1 (invalid), so they
    contribute nothing to any sketch."""
    from .mesh import pad_to_devices

    n_dev = mesh.devices.size
    B = batch.shape[0]
    Bp = pad_to_devices(B, n_dev)
    if Bp != B:
        batch = np.concatenate(
            [batch, np.zeros((Bp - B, batch.shape[1]), dtype=batch.dtype)]
        )
        lengths = np.concatenate(
            [lengths, np.full((Bp - B,), -1, dtype=lengths.dtype)]
        )
    return batch, lengths


def build_sharded_hll(hll: HyperLogLog, mesh):
    """Compile the mesh HLL-update program: each device absorbs its
    batch shard into a full local register set (the ``registers`` state
    leaf rides the declarative ``flux-hll`` partition rule — an
    explicit replicate, not the implicit fallback), merged with
    lax.pmax (union of HLLs). Factored out of the dispatch wrapper so
    the fbtpu-speccheck static==dynamic crosscheck can ``lower()`` the
    exact shipped program on the simulated mesh."""
    from jax.sharding import PartitionSpec as P

    from .device import shard_map_fn
    from .mesh import rule_spec

    shard_map = shard_map_fn()
    axis = mesh.axis_names[0]
    regs_spec = rule_spec("flux-hll", axis, "registers")

    def step(regs, b, ln):
        local = hll._update_impl(regs, b, ln)
        return lax.pmax(local, axis_name=axis)

    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(regs_spec, P(axis, None), P(axis)),
        out_specs=regs_spec,
    ))


def sharded_hll_registers(hll: HyperLogLog, mesh, batch: np.ndarray,
                          lengths: np.ndarray, registers=None):
    """Mesh update, WITHOUT committing or mutating any sketch state:
    runs the :func:`build_sharded_hll` program and returns the merged
    registers, computed from the explicit ``registers`` snapshot
    (default: the sketch's current set). The fbtpu-armor flux lane
    commits the result on the caller thread after the watched launch
    returns (see :meth:`HyperLogLog.device_registers`)."""
    from . import device

    if not device.wait(max(60.0, device.default_wait())):
        raise RuntimeError(
            f"device backend not attached: {device.status()}"
        )
    batch, lengths = _pad_to_mesh(mesh, batch, lengths)
    # cache the compiled step per mesh — a fresh jit(shard_map(...))
    # closure would recompile on every call
    cache = getattr(hll, "_sharded_cache", None)
    if cache is None:
        cache = hll._sharded_cache = {}
    fn = cache.get(_mesh_key(mesh))
    if fn is None:
        fn = build_sharded_hll(hll, mesh)
        cache[_mesh_key(mesh)] = fn
    regs = hll.registers if registers is None else registers
    return fn(jnp.asarray(regs), jnp.asarray(batch),
              jnp.asarray(lengths))


def sharded_hll_update(hll: HyperLogLog, mesh, batch: np.ndarray,
                       lengths: np.ndarray) -> None:
    """Compute-and-commit convenience over
    :func:`sharded_hll_registers` (bench / unguarded callers)."""
    merged = sharded_hll_registers(hll, mesh, batch, lengths)
    hll.registers = merged


def build_sharded_cms(cms: CountMin, mesh):
    """Compile the mesh count-min program: local scatter-adds over the
    batch shard, psum merge (the ``table`` state leaf rides the
    declarative ``flux-cms`` partition rule). Factored out of the
    dispatch wrapper for the fbtpu-speccheck lowering crosscheck, like
    :func:`build_sharded_hll`."""
    from jax.sharding import PartitionSpec as P

    from .device import shard_map_fn
    from .mesh import rule_spec

    shard_map = shard_map_fn()
    axis = mesh.axis_names[0]
    table_spec = rule_spec("flux-cms", axis, "table")

    def step(table, b, ln, w):
        # + 0*sum(w): ties the accumulator to the sharded batch so
        # the fori_loop carry's varying annotation stays consistent
        zero = jnp.zeros_like(table) + (0 * w.sum()).astype(table.dtype)
        local = cms._update_impl(zero, b, ln, w)
        return table + lax.psum(local, axis_name=axis)

    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(table_spec, P(axis, None), P(axis), P(axis)),
        out_specs=table_spec,
    ))


def sharded_cms_table(cms: CountMin, mesh, batch: np.ndarray,
                      lengths: np.ndarray, table=None):
    """Count-min over a mesh, WITHOUT committing or mutating any
    sketch state: runs the :func:`build_sharded_cms` program and
    returns the merged table, computed from the explicit ``table``
    snapshot (snapshot-in/commit-on-finish protocol — see
    :func:`sharded_hll_registers`)."""
    from . import device

    if not device.wait(max(60.0, device.default_wait())):
        raise RuntimeError(
            f"device backend not attached: {device.status()}"
        )
    batch, lengths = _pad_to_mesh(mesh, batch, lengths)
    weights = np.ones((batch.shape[0],), dtype=np.int32)
    cache = getattr(cms, "_sharded_cache", None)
    if cache is None:
        cache = cms._sharded_cache = {}
    fn = cache.get(_mesh_key(mesh))
    if fn is None:
        fn = build_sharded_cms(cms, mesh)
        cache[_mesh_key(mesh)] = fn
    tbl = cms.table if table is None else table
    return fn(jnp.asarray(tbl, dtype=cms._dtype), jnp.asarray(batch),
              jnp.asarray(lengths), jnp.asarray(weights))


def sharded_cms_update(cms: CountMin, mesh, batch: np.ndarray,
                       lengths: np.ndarray) -> None:
    """Compute-and-commit convenience over
    :func:`sharded_cms_table`."""
    merged = sharded_cms_table(cms, mesh, batch, lengths)
    cms.table = merged
