"""Mesh partitioning layer — declarative PartitionSpecs + shared mesh
helpers for every SPMD plane (grep DFA, sketches, flux kernels).

The device programs in this repo all shard the same way: one 1-D device
mesh, a batch-like axis split across chips, small lookup tables
replicated (or sharded over the rule axis when R is large). Before this
module each plane hand-wrote its specs inline; the partition decisions
now live in *rules* — ``(regex over the leaf name, PartitionSpec)``
pairs matched against a named table pytree, the ``match_partition_rules``
pattern of large-model training codebases (SNIPPETS.md [2]) — so a
reviewer can read the whole sharding layout of a program in one table,
and a new table added to a program picks up a spec by name instead of
by editing three call sites.

Also here:

- ``build_mesh`` / ``mesh_key`` / ``mesh_info`` — the one mesh
  constructor and cache-key/diagnostics helpers every plane shares
  (flux_mesh and ops.sketch used to carry private copies).
- donation helpers — compute the *aliasable* subset of staged input
  buffers (exact sharded shape+dtype match against the outputs, the
  same matching ``jax.jit`` itself performs) so donation never degrades
  into the silent "Some donated buffers were not usable" copy fallback,
  and report which aliases actually landed in the lowered HLO
  (``tf.aliasing_output``) for the bench RESULT and the tier-1
  donation test.

Everything degrades gracefully without jax: ``build_mesh`` returns
None and the callers stay on their host twins.
"""

from __future__ import annotations

import re
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

try:
    import jax

    HAVE_JAX = True
except Exception:  # pragma: no cover - jax absent: host twins only
    HAVE_JAX = False

__all__ = [
    "named_tree_map", "match_partition_rules", "build_mesh", "mesh_key",
    "mesh_info", "pad_to_devices", "aliasable_donations",
    "donation_report", "replicated_table_bytes",
    "AXIS", "PARTITION_RULES", "partition_rules", "rule_spec",
]

# -- the declarative partition-rules registry --------------------------
#
# One table per device program, naming EVERY leaf of its table pytree
# explicitly — anchored regexes, no catch-alls. The programs consume
# these through ``partition_rules``/``rule_spec`` (which bind the axis
# placeholder to the live mesh axis), and the fbtpu-speccheck abstract
# interpreter (analysis/speccheck.py) evaluates the same tables
# symbolically at lint time: a leaf that falls through to the implicit
# replicate fallback, a rule an earlier rule shadows, or a sharded dim
# with no divisibility proof is a finding BEFORE anything traces on a
# mesh. Spec templates are plain tuples (axis token / axis name / None
# per dim) so the registry imports without jax.

#: Placeholder resolved to the program's mesh axis name at build time.
AXIS = "@axis"

PARTITION_RULES: Dict[str, Tuple[Tuple[str, Tuple[Any, ...]], ...]] = {
    # grep DFA plane, batch variant: B shards across devices, every
    # table leaf replicated (the post-shrink matrices are small
    # relative to per-device memory — mesh_variant gates the flip)
    "grep-batch": (
        (r"^(trans_flat|class_maps|pair_maps|C|Ck|eol_cls|starts)$",
         ()),
    ),
    # grep rule-sharded variant: each device holds 1/n of the rules —
    # 2-D table leaves split on the rule axis, per-rule vectors too
    "grep-rules": (
        (r"^(trans_flat|class_maps|pair_maps)$", (AXIS, None)),
        (r"^(C|Ck|eol_cls|starts)$", (AXIS,)),
    ),
    # flux sketch state leaves: replicated snapshots — every device
    # absorbs its batch shard into a full local copy, merged by
    # pmax (HLL union) / psum (count-min sum) inside the program
    "flux-hll": ((r"^registers$", ()),),
    "flux-cms": ((r"^table$", ()),),
    # flux window/segment-count columns: batch-axis sharded inputs,
    # replicated counts out of the psum merge
    "flux-counts": ((r"^(seg|valid)$", (AXIS,)),),
    # ONE-launch fused flux absorb (counts + per-group HLL stack +
    # count-min in a single program — the fbtpu-fuseplan cashed merge):
    # every batch-axis column shards, all sketch state replicates (the
    # merges are pmax over the [Gp, m] register stack and psum over the
    # count-min table / segment counts, same exactness as the unfused
    # programs)
    "flux-fused": (
        (r"^(seg|valid|lengths|comp_len)$", (AXIS,)),
        (r"^(batch|comp)$", (AXIS, None)),
        (r"^(registers|table)$", ()),
    ),
}


def partition_rules(key: str, axis: str):
    """The ``(regex, PartitionSpec)`` rows of one registry table with
    the axis placeholder bound — what ``match_partition_rules`` and the
    program builders consume. Unknown keys raise: a renamed table must
    not silently build an unsharded program."""
    from jax.sharding import PartitionSpec as P

    try:
        rows = PARTITION_RULES[key]
    except KeyError:
        raise KeyError(
            f"unknown partition-rule table {key!r}; known: "
            f"{sorted(PARTITION_RULES)}") from None
    return tuple(
        (regex, P(*(axis if t == AXIS else t for t in tmpl)))
        for regex, tmpl in rows
    )


def rule_spec(key: str, axis: str, name: str):
    """The PartitionSpec a registry table assigns to the leaf ``name``
    (first-match, same semantics as ``match_partition_rules``) — the
    single-leaf convenience the flux kernel builders use."""
    for regex, spec in partition_rules(key, axis):
        if re.search(regex, name) is not None:
            return spec
    raise ValueError(
        f"partition-rule table {key!r} has no rule for leaf {name!r}")


def replicated_table_bytes(tables) -> int:
    """Total byte footprint of a program's table pytree (numpy dicts
    with possible None leaves, or device-array pytrees) — the number
    the batch-vs-rules partition decision weighs against
    ``FBTPU_MESH_TABLE_BUDGET``. Centralized here (rather than inline
    per program) so every plane sizes its replication the same way —
    the fbtpu-shrink pass changes these shapes per DFA, and the mesh
    variant choice must follow the REAL post-reduction footprint."""
    total = 0
    for v in (tables.values() if isinstance(tables, dict) else tables):
        if v is None:
            continue
        shape = getattr(v, "shape", None)
        if shape is None:
            continue
        itemsize = getattr(getattr(v, "dtype", None), "itemsize", 1)
        total += int(np.prod(shape)) * int(itemsize)
    return total


def named_tree_map(fn, tree, sep: str = "/"):
    """``tree_map`` with the leaf's /-joined key path as first argument
    (the naming layer ``match_partition_rules`` matches against)."""
    from jax.tree_util import keystr, tree_map_with_path

    def call(path, leaf):
        name = keystr(path)
        # keystr renders "['trans_flat']"; flatten to trans_flat/sub
        name = re.sub(r"\[['\"]?([^'\"\]]*)['\"]?\]", r"\1" + sep, name)
        return fn(name.rstrip(sep), leaf)

    return tree_map_with_path(call, tree)


def match_partition_rules(rules: Sequence[Tuple[str, Any]], tree,
                          *, scalars_replicate: bool = True,
                          dead_rules: str = "raise"):
    """Pytree of arrays → pytree of PartitionSpec via first-match regex
    rules over leaf names. Scalars (0-d / size-1 leaves) replicate
    unconditionally — there is nothing to split. A leaf no rule covers
    raises: an unsharded table sneaking into a partitioned program is a
    layout bug, not a default.

    A rule that fires on NO leaf across the whole pytree is equally a
    layout bug — a renamed table leaf silently reverts to whatever the
    later rules (or the unmatched-leaf error) decide while its spec
    rots in the table. ``dead_rules`` controls the response: ``"raise"``
    (default), ``"warn"``, or ``"ignore"`` (for rule tables shared by
    programs whose pytrees are legitimate subsets, e.g. an optional
    leaf). The fbtpu-speccheck lint rule ``shard-shadowed-rule`` makes
    the same check statically, before anything traces."""
    from jax.sharding import PartitionSpec as P

    used: set = set()

    def pick(name, leaf):
        shape = getattr(leaf, "shape", ())
        if scalars_replicate and (len(shape) == 0 or int(np.prod(shape)) == 1):
            return P()
        for i, (rule, spec) in enumerate(rules):
            if re.search(rule, name) is not None:
                used.add(i)
                return spec
        raise ValueError(f"no partition rule matches leaf {name!r}")

    out = named_tree_map(pick, tree)
    if dead_rules != "ignore":
        dead = [rules[i][0] for i in range(len(rules)) if i not in used]
        if dead:
            msg = (f"partition rule(s) matched no leaf: {dead!r} — "
                   f"a renamed table leaf no longer picks up its spec "
                   f"(dead_rules='ignore' if the subset is deliberate)")
            if dead_rules == "raise":
                raise ValueError(msg)
            warnings.warn(msg, stacklevel=2)
    return out


def build_mesh(n_devices: Optional[int] = None, axis: str = "batch"):
    """A 1-D mesh over the available devices. Under the simulated-mesh
    lane (``XLA_FLAGS=--xla_force_host_platform_device_count=8``, the
    tier-1 default — tests/conftest.py) these are 8 virtual CPU
    devices; on real hardware, the attached chips. Returns None when
    jax is unavailable or fewer than two devices exist (the mesh path
    would be pure overhead)."""
    if not HAVE_JAX:
        return None
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if len(devs) < 2:
        return None
    return Mesh(np.asarray(devs), (axis,))


def mesh_key(mesh) -> tuple:
    """Structural cache key: equal meshes share a compiled program
    (id() would recompile per Mesh object)."""
    return (tuple(mesh.axis_names),
            tuple(d.id for d in mesh.devices.flat))


def mesh_info(mesh) -> Dict[str, Any]:
    """Diagnostics block for RESULT JSON / health surfaces: shape,
    platform, and whether this is the simulated host-platform mesh."""
    import os

    if mesh is None:
        return {"devices": 1, "axis_names": [], "simulated": False,
                "platform": None}
    devs = list(mesh.devices.flat)
    plat = getattr(devs[0], "platform", None)
    flags = os.environ.get("XLA_FLAGS", "")
    simulated = (plat == "cpu"
                 and "xla_force_host_platform_device_count" in flags)
    return {
        "devices": len(devs),
        "axis_names": list(mesh.axis_names),
        "platform": plat,
        "simulated": simulated,
    }


def pad_to_devices(n: int, n_devices: int) -> int:
    """Smallest multiple of the device count ≥ n (NamedSharding requires
    the sharded dimension divisible by the mesh size)."""
    if n_devices <= 1:
        return n
    return ((n + n_devices - 1) // n_devices) * n_devices


# -- donation ----------------------------------------------------------

def _sharded_shape(shape, spec, mesh) -> tuple:
    """Per-device shard shape for an array of ``shape`` under ``spec``
    (what jax's donation matcher compares — aliasing is decided on the
    *sharded* avals)."""
    axes = {a: n for a, n in zip(mesh.axis_names,
                                 mesh.devices.shape)}
    out = list(shape)
    for i, s in enumerate(spec):
        if s is None:
            continue
        names = s if isinstance(s, tuple) else (s,)
        for nm in names:
            out[i] //= axes.get(nm, 1)
    return tuple(out)


def aliasable_donations(mesh, in_specs: Sequence[tuple],
                        out_specs: Sequence[tuple]) -> List[int]:
    """Indices of donatable inputs whose sharded (shape, dtype) exactly
    matches an output's — the subset jax can actually alias. Donating
    anything else is a silent no-op plus a compile-time warning (the
    "copy fallback" the mesh bench must never hide), so the mesh
    matcher donates exactly this set.

    ``in_specs``/``out_specs``: sequences of
    ``(shape, dtype, PartitionSpec, donatable: bool)`` /
    ``(shape, dtype, PartitionSpec)``.
    """
    outs: Dict[tuple, int] = {}
    for shape, dtype, spec in out_specs:
        key = (_sharded_shape(shape, spec, mesh), np.dtype(dtype))
        outs[key] = outs.get(key, 0) + 1
    donate: List[int] = []
    for i, (shape, dtype, spec, ok) in enumerate(in_specs):
        if not ok:
            continue
        key = (_sharded_shape(shape, spec, mesh), np.dtype(dtype))
        if outs.get(key, 0) > 0:
            outs[key] -= 1
            donate.append(i)
    return donate


def donation_report(lowered, donate_argnums: Sequence[int],
                    arg_names: Sequence[str]) -> Dict[str, Any]:
    """Inspect a ``jax.jit(...).lower(...)`` result for the
    input→output aliases donation promised. Returns
    ``{"declared": [...], "held": bool, "alias_count": int}`` where
    ``held`` means the lowered module carries at least one
    ``tf.aliasing_output`` annotation per declared arg — the
    compiled-module check the tier-1 donation test asserts (run-time
    proof is the donated buffer's ``is_deleted()`` flip)."""
    txt = lowered.as_text()
    n_alias = txt.count("tf.aliasing_output")
    declared = [arg_names[i] if i < len(arg_names) else str(i)
                for i in donate_argnums]
    return {
        "declared": declared,
        "alias_count": n_alias,
        "held": n_alias >= len(declared) and bool(declared),
    }
