"""Mesh partitioning layer — declarative PartitionSpecs + shared mesh
helpers for every SPMD plane (grep DFA, sketches, flux kernels).

The device programs in this repo all shard the same way: one 1-D device
mesh, a batch-like axis split across chips, small lookup tables
replicated (or sharded over the rule axis when R is large). Before this
module each plane hand-wrote its specs inline; the partition decisions
now live in *rules* — ``(regex over the leaf name, PartitionSpec)``
pairs matched against a named table pytree, the ``match_partition_rules``
pattern of large-model training codebases (SNIPPETS.md [2]) — so a
reviewer can read the whole sharding layout of a program in one table,
and a new table added to a program picks up a spec by name instead of
by editing three call sites.

Also here:

- ``build_mesh`` / ``mesh_key`` / ``mesh_info`` — the one mesh
  constructor and cache-key/diagnostics helpers every plane shares
  (flux_mesh and ops.sketch used to carry private copies).
- donation helpers — compute the *aliasable* subset of staged input
  buffers (exact sharded shape+dtype match against the outputs, the
  same matching ``jax.jit`` itself performs) so donation never degrades
  into the silent "Some donated buffers were not usable" copy fallback,
  and report which aliases actually landed in the lowered HLO
  (``tf.aliasing_output``) for the bench RESULT and the tier-1
  donation test.

Everything degrades gracefully without jax: ``build_mesh`` returns
None and the callers stay on their host twins.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

try:
    import jax

    HAVE_JAX = True
except Exception:  # pragma: no cover - jax absent: host twins only
    HAVE_JAX = False

__all__ = [
    "named_tree_map", "match_partition_rules", "build_mesh", "mesh_key",
    "mesh_info", "pad_to_devices", "aliasable_donations",
    "donation_report", "replicated_table_bytes",
]


def replicated_table_bytes(tables) -> int:
    """Total byte footprint of a program's table pytree (numpy dicts
    with possible None leaves, or device-array pytrees) — the number
    the batch-vs-rules partition decision weighs against
    ``FBTPU_MESH_TABLE_BUDGET``. Centralized here (rather than inline
    per program) so every plane sizes its replication the same way —
    the fbtpu-shrink pass changes these shapes per DFA, and the mesh
    variant choice must follow the REAL post-reduction footprint."""
    total = 0
    for v in (tables.values() if isinstance(tables, dict) else tables):
        if v is None:
            continue
        shape = getattr(v, "shape", None)
        if shape is None:
            continue
        itemsize = getattr(getattr(v, "dtype", None), "itemsize", 1)
        total += int(np.prod(shape)) * int(itemsize)
    return total


def named_tree_map(fn, tree, sep: str = "/"):
    """``tree_map`` with the leaf's /-joined key path as first argument
    (the naming layer ``match_partition_rules`` matches against)."""
    from jax.tree_util import keystr, tree_map_with_path

    def call(path, leaf):
        name = keystr(path)
        # keystr renders "['trans_flat']"; flatten to trans_flat/sub
        name = re.sub(r"\[['\"]?([^'\"\]]*)['\"]?\]", r"\1" + sep, name)
        return fn(name.rstrip(sep), leaf)

    return tree_map_with_path(call, tree)


def match_partition_rules(rules: Sequence[Tuple[str, Any]], tree,
                          *, scalars_replicate: bool = True):
    """Pytree of arrays → pytree of PartitionSpec via first-match regex
    rules over leaf names. Scalars (0-d / size-1 leaves) replicate
    unconditionally — there is nothing to split. A leaf no rule covers
    raises: an unsharded table sneaking into a partitioned program is a
    layout bug, not a default."""
    from jax.sharding import PartitionSpec as P

    def pick(name, leaf):
        shape = getattr(leaf, "shape", ())
        if scalars_replicate and (len(shape) == 0 or int(np.prod(shape)) == 1):
            return P()
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"no partition rule matches leaf {name!r}")

    return named_tree_map(pick, tree)


def build_mesh(n_devices: Optional[int] = None, axis: str = "batch"):
    """A 1-D mesh over the available devices. Under the simulated-mesh
    lane (``XLA_FLAGS=--xla_force_host_platform_device_count=8``, the
    tier-1 default — tests/conftest.py) these are 8 virtual CPU
    devices; on real hardware, the attached chips. Returns None when
    jax is unavailable or fewer than two devices exist (the mesh path
    would be pure overhead)."""
    if not HAVE_JAX:
        return None
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if len(devs) < 2:
        return None
    return Mesh(np.asarray(devs), (axis,))


def mesh_key(mesh) -> tuple:
    """Structural cache key: equal meshes share a compiled program
    (id() would recompile per Mesh object)."""
    return (tuple(mesh.axis_names),
            tuple(d.id for d in mesh.devices.flat))


def mesh_info(mesh) -> Dict[str, Any]:
    """Diagnostics block for RESULT JSON / health surfaces: shape,
    platform, and whether this is the simulated host-platform mesh."""
    import os

    if mesh is None:
        return {"devices": 1, "axis_names": [], "simulated": False,
                "platform": None}
    devs = list(mesh.devices.flat)
    plat = getattr(devs[0], "platform", None)
    flags = os.environ.get("XLA_FLAGS", "")
    simulated = (plat == "cpu"
                 and "xla_force_host_platform_device_count" in flags)
    return {
        "devices": len(devs),
        "axis_names": list(mesh.axis_names),
        "platform": plat,
        "simulated": simulated,
    }


def pad_to_devices(n: int, n_devices: int) -> int:
    """Smallest multiple of the device count ≥ n (NamedSharding requires
    the sharded dimension divisible by the mesh size)."""
    if n_devices <= 1:
        return n
    return ((n + n_devices - 1) // n_devices) * n_devices


# -- donation ----------------------------------------------------------

def _sharded_shape(shape, spec, mesh) -> tuple:
    """Per-device shard shape for an array of ``shape`` under ``spec``
    (what jax's donation matcher compares — aliasing is decided on the
    *sharded* avals)."""
    axes = {a: n for a, n in zip(mesh.axis_names,
                                 mesh.devices.shape)}
    out = list(shape)
    for i, s in enumerate(spec):
        if s is None:
            continue
        names = s if isinstance(s, tuple) else (s,)
        for nm in names:
            out[i] //= axes.get(nm, 1)
    return tuple(out)


def aliasable_donations(mesh, in_specs: Sequence[tuple],
                        out_specs: Sequence[tuple]) -> List[int]:
    """Indices of donatable inputs whose sharded (shape, dtype) exactly
    matches an output's — the subset jax can actually alias. Donating
    anything else is a silent no-op plus a compile-time warning (the
    "copy fallback" the mesh bench must never hide), so the mesh
    matcher donates exactly this set.

    ``in_specs``/``out_specs``: sequences of
    ``(shape, dtype, PartitionSpec, donatable: bool)`` /
    ``(shape, dtype, PartitionSpec)``.
    """
    outs: Dict[tuple, int] = {}
    for shape, dtype, spec in out_specs:
        key = (_sharded_shape(shape, spec, mesh), np.dtype(dtype))
        outs[key] = outs.get(key, 0) + 1
    donate: List[int] = []
    for i, (shape, dtype, spec, ok) in enumerate(in_specs):
        if not ok:
            continue
        key = (_sharded_shape(shape, spec, mesh), np.dtype(dtype))
        if outs.get(key, 0) > 0:
            outs[key] -= 1
            donate.append(i)
    return donate


def donation_report(lowered, donate_argnums: Sequence[int],
                    arg_names: Sequence[str]) -> Dict[str, Any]:
    """Inspect a ``jax.jit(...).lower(...)`` result for the
    input→output aliases donation promised. Returns
    ``{"declared": [...], "held": bool, "alias_count": int}`` where
    ``held`` means the lowered module carries at least one
    ``tf.aliasing_output`` annotation per declared arg — the
    compiled-module check the tier-1 donation test asserts (run-time
    proof is the donated buffer's ``is_deleted()`` flip)."""
    txt = lowered.as_text()
    n_alias = txt.count("tf.aliasing_output")
    declared = [arg_names[i] if i < len(arg_names) else str(i)
                for i in donate_argnums]
    return {
        "declared": declared,
        "alias_count": n_alias,
        "held": n_alias >= len(declared) and bool(declared),
    }
