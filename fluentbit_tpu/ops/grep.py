"""Device DFA execution — vectorized regex matching on TPU.

The Onigmo-replacement kernel (SURVEY §2.2: "the thing the TPU build must
re-express as a vectorized/compiled automaton kernel"). A compiled scan
DFA (fluentbit_tpu.regex.dfa) runs over a ``[B, L] uint8`` batch as a
``lax.scan`` of table gathers:

    state[b] = trans[state[b], class(byte[b, t])]        t = 0..L

- Multi-rule: R DFAs run in one kernel over ``[R, B, L]`` (each grep rule
  may address a different record field, hence per-rule batches). All R
  transition tables are fused into ONE flat gather per scan step
  (``trans_flat[R, max_flat]`` + per-rule radix), so the step cost does
  not grow a kernel launch per rule.
- k-byte super-steps: transition tables are pre-composed to ``C^k``
  columns (T2[s, c1*C+c2] = T[T[s,c1],c2]), cutting sequential scan steps
  by k at the cost of a larger (still VMEM-resident) table. k is chosen
  so the table stays under a size budget.
- Multi-stride symbol packing: for even k the per-byte class gathers are
  themselves fused two bytes at a time through a per-rule byte-PAIR
  class table (``pair_maps[R, 65536] = class(b0)*C + class(b1)``),
  halving the gather count of the super-symbol prepass — the same
  pair-table trick the native twin uses (native/fbtpu_native.cpp
  dfa_prepass_block).
- Padding positions map to the EOL symbol class, which is absorbing after
  the first step — fixed shapes stay exact, no masking in the inner loop.
- matched == (final_state == ACC): single comparison at scan end, no
  per-position accept reduction.
- Kernel selection: ``kernel="auto"`` (default) picks scan vs assoc per
  program shape at trace time — the sequential scan on host-CPU backends
  (where the log2-depth compose tree's S× extra work is pure overhead:
  BENCH_r05 measured it 300× slower there), the parallel-in-time assoc
  kernel on real accelerators when the state count is small enough for
  the extra parallel work to ride otherwise-idle vector lanes.

This module works on any JAX backend (tests force a CPU mesh); on TPU the
gathers vectorize across the batch dimension.
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("flb.grep")

try:
    import jax
    import jax.numpy as jnp
    from jax import lax

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

from ..regex.dfa import ACC, DFA, EOL

# table budget for k-byte super-stepping (bytes); C^k columns * S rows * 4
_TABLE_BUDGET = 4 * 1024 * 1024


#: byte-pair class tables cost R * 65536 * 4 bytes; skip beyond this
_PAIR_MAP_MAX_RULES = 32


def choose_k(n_states: int, n_classes: int, budget: int = _TABLE_BUDGET) -> int:
    """Largest stride whose composed table fits the budget (strides up
    to 6 — small alphabets with few states compose deep)."""
    k = 1
    while k < 6:
        cols = n_classes ** (k + 1)
        if n_states * cols * 4 > budget:
            break
        k += 1
    return k


def compose_table(trans: np.ndarray, k: int) -> np.ndarray:
    """Pre-compose a [S, C] table to k-byte super-steps: [S, C^k]
    (delegates to the shared composition in regex.dfa so the device and
    native tables stay bit-identical)."""
    from ..regex.dfa import compose_supersteps

    return compose_supersteps(trans, k)


class GrepProgram:
    """R compiled DFAs fused into one device program.

    Produces ``match(batch_u8[R,B,L], lengths[R,B]) -> bool[R,B]``.
    """

    def __init__(self, dfas: Sequence[DFA], max_len: int = 512,
                 kernel: Optional[str] = None, segment: int = 32):
        if not HAVE_JAX:
            raise RuntimeError("jax is unavailable")
        self.dfas = list(dfas)
        self.max_len = max_len
        # kernel variant: "scan" = sequential lax.scan of table gathers
        # (Lk serialized steps, minimal FLOPs); "assoc" = parallel-in-
        # time function composition (segments scanned as transition
        # FUNCTIONS over all states, then a log2-depth tree of
        # compositions) — sequential depth m + log2(Lk/m) instead of
        # Lk, trading S× more parallel work the TPU's lanes absorb;
        # "auto" = resolved per program shape + attached platform at
        # trace time (_resolve_kernel)
        import os as _os
        self.kernel = (kernel or
                       _os.environ.get("FBTPU_GREP_KERNEL", "auto"))
        if self.kernel not in ("scan", "assoc", "auto"):
            raise ValueError(f"unknown grep kernel {self.kernel!r}")
        self.kernel_resolved: Optional[str] = None
        self.segment = max(2, int(segment))
        R = len(self.dfas)

        # fbtpu-shrink: per-DFA stride selection. choose_k re-resolves
        # here against the MINIMIZED (S, C) — the whole point of the
        # compile-path reduction is that these numbers shrank. When the
        # rules disagree on k, the program splits into per-k child
        # programs (each a plain homogeneous GrepProgram) instead of
        # pinning the whole fleet to min(k): a literal rule's k=6 no
        # longer rides at a rich parser's k=3. The split is gated off
        # the rule-shard regime (large R wants ONE fused table set to
        # shard over the rule axis — ops/mesh.py) and `FBTPU_PER_DFA_K=0`.
        self.k_by_rule = [choose_k(d.n_states, d.n_classes)
                          for d in self.dfas]
        self._children: Optional[List["GrepProgram"]] = None
        self._inv_perm: Optional[np.ndarray] = None
        self._child_idxs: Optional[List[np.ndarray]] = None
        distinct_ks = sorted(set(self.k_by_rule))
        min_shard_r = int(_os.environ.get("FBTPU_MESH_RULE_SHARD_R", "64"))
        if (len(distinct_ks) > 1 and R < min_shard_r
                and _os.environ.get("FBTPU_PER_DFA_K", "1").lower()
                not in ("0", "off")):
            self._child_idxs = [
                np.asarray([i for i, kk in enumerate(self.k_by_rule)
                            if kk == k], dtype=np.int64)
                for k in distinct_ks
            ]
            self._children = [
                GrepProgram([self.dfas[int(i)] for i in idxs], max_len,
                            kernel=self.kernel, segment=segment)
                for idxs in self._child_idxs
            ]
            perm = np.concatenate(self._child_idxs)
            self._inv_perm = np.argsort(perm)
            self.k = distinct_ks[0]
            self.max_states = max(d.n_states for d in self.dfas)
            self._np = None
            self._jit = None
            self._mat_lock = threading.Lock()
            self._sharded_cache = {}
            self._mesh_cache = {}
            return

        # Table prep is pure numpy — cheap and safe at plugin init. The
        # jnp transfers + jit happen in _materialize(), gated on the
        # device-attach controller, so constructing a GrepProgram never
        # blocks on (possibly minutes-long) backend init.
        self.k = min(self.k_by_rule)
        tables = [compose_table(d.trans, self.k) for d in self.dfas]
        max_flat = max(t.shape[0] * t.shape[1] for t in tables)
        flat = np.zeros((R, max_flat), dtype=np.int32)
        for r, t in enumerate(tables):
            flat[r, : t.size] = t.reshape(-1)
        cmaps = np.zeros((R, 257), dtype=np.int32)
        for r, d in enumerate(self.dfas):
            cmaps[r] = d.class_map.astype(np.int32)
        self._np = {
            "trans_flat": flat,
            "C": np.asarray([d.n_classes for d in self.dfas],
                            dtype=np.int32),
            "Ck": np.asarray([d.n_classes ** self.k for d in self.dfas],
                             dtype=np.int32),
            "class_maps": cmaps,
            "eol_cls": np.asarray([d.eol_class for d in self.dfas],
                                  dtype=np.int32),
            "starts": np.asarray([d.start for d in self.dfas],
                                 dtype=np.int32),
        }
        # even strides classify through a byte-PAIR table: one gather
        # yields class(b0)*C + class(b1), halving the symbol-prep
        # gathers (the fused multi-stride packing)
        if self.k % 2 == 0 and R <= _PAIR_MAP_MAX_RULES:
            pair_maps = np.zeros((R, 65536), dtype=np.int32)
            w = np.arange(65536, dtype=np.int64)
            for r, d in enumerate(self.dfas):
                cm = d.class_map[:256].astype(np.int64)
                pair_maps[r] = (cm[w & 255] * d.n_classes
                                + cm[w >> 8]).astype(np.int32)
            self._np["pair_maps"] = pair_maps
        else:
            self._np["pair_maps"] = None
        self.max_states = max(d.n_states for d in self.dfas)
        self._jit = None
        self._mat_lock = threading.Lock()
        self._sharded_cache: dict = {}
        self._mesh_cache: dict = {}

    def _resolve_kernel(self) -> str:
        """Scan-vs-assoc per program shape, decided at trace time (the
        attached platform is known by then). The scan kernel's Lk
        serialized gathers are cheap on a host CPU where the assoc
        tree's S× parallel work is pure overhead (BENCH_r05: 300×
        slower there); assoc pays off only when idle vector lanes
        absorb that work — a real accelerator and a small state count."""
        if self.kernel != "auto":
            return self.kernel
        from . import device

        plat = device.platform()
        if plat in (None, "cpu"):
            return "scan"
        return "assoc" if self.max_states <= 64 else "scan"

    # -- fbtpu-shrink decision surface --

    def decision(self) -> dict:
        """The resolved compile/kernel decisions, per rule: S/C before →
        after the reduction pass (regex.dfa ShrinkStats), the chosen
        stride k, the k-group layout, and the scan/assoc resolution —
        what bench's `shrink` stage records and the unlock tests assert
        against. ``kernel_resolved`` is None until the program
        materializes on a backend (the resolution is a trace-time
        decision)."""
        rules = []
        for r, d in enumerate(self.dfas):
            st = d.shrink
            rules.append({
                "pattern": d.pattern,
                "s_raw": st.s_raw if st else None,
                "c_raw": st.c_raw if st else None,
                "s": d.n_states,
                "c": d.n_classes,
                "minimized": bool(st.minimized) if st else False,
                "approx_of": st.approx_of if st else None,
                "k": self.k_by_rule[r],
            })
        if self._children is not None:
            resolved = {c.kernel_resolved for c in self._children}
            kernel_resolved = (resolved.pop() if len(resolved) == 1
                               else "mixed")
            k_groups = [int(c.k) for c in self._children]
        else:
            kernel_resolved = self.kernel_resolved
            k_groups = [int(self.k)]
        return {
            "rules": rules,
            "k": int(self.k),
            "k_groups": k_groups,
            "max_states": int(self.max_states),
            "assoc_eligible": self.max_states <= 64,
            "kernel": self.kernel,
            "kernel_resolved": kernel_resolved,
        }

    def _merge_rule_axis(self, parts):
        """Reassemble per-child rule rows into the caller's order."""
        return jnp.concatenate(list(parts), axis=0)[self._inv_perm]

    def _materialize(self) -> None:
        """Transfer tables to the attached backend + build the jit.

        The tables live in ONE pytree (``self._tbl``) that the kernels
        take as an explicit first argument — the mesh matcher shards
        that same pytree by name through the partition-rules layer
        (ops.mesh.match_partition_rules), so the single-device and
        partitioned programs are the same code over the same tree."""
        with self._mat_lock:
            if self._jit is not None:
                return
            t = self._np
            self._tbl = {k: jnp.asarray(v) for k, v in t.items()
                         if v is not None}
            self.kernel_resolved = self._resolve_kernel()
            kern = (self._match_assoc_impl
                    if self.kernel_resolved == "assoc"
                    else self._match_impl)
            tbl = self._tbl

            def impl(batch, lengths):
                return kern(tbl, batch, lengths)

            self._impl = impl
            self._jit = jax.jit(impl)
            self._np = None  # tables now live on device; free host copy
            # the shrink/unlock audit line: S/C before→after, chosen
            # stride, resolved kernel — what bench + tests assert
            log.info("grep program materialized: %s", self.decision())

    def try_ready(self) -> bool:
        """Non-blocking: True iff the device path is usable now. Kicks
        background attach on first call; until ready, callers run their
        bit-exact CPU fallback."""
        if self._children is not None:
            ready = [c.try_ready() for c in self._children]
            return all(ready)
        if self._jit is not None:
            return True
        from . import device

        if not device.ready():
            device.attach_async()
            return False
        self._materialize()
        return True

    # -- the kernel --

    def _super_symbols(self, t: dict, batch: "jnp.ndarray",
                       lengths: "jnp.ndarray") -> "jnp.ndarray":
        """bytes → per-rule k-byte super-symbols: [R, B, Lk]. ``t`` is
        the table pytree (whole under single-device jit, this device's
        shard under the partitioned program — the kernels are uniform
        over the leading rule axis, so both read identically)."""
        if "pair_maps" in t:
            return self._super_symbols_pairs(t, batch, lengths)
        R, B, L = batch.shape
        k = self.k
        # byte → class, per rule
        cls = jax.vmap(lambda cm, bt: cm[bt])(t["class_maps"], batch)  # [R,B,L] i32
        pos = jnp.arange(L, dtype=jnp.int32)
        pad = pos[None, None, :] >= lengths[:, :, None]  # [R,B,L]
        cls = jnp.where(pad, t["eol_cls"][:, None, None], cls)
        # append EOL block: guarantees >=1 EOL and rounds L to multiple of k
        extra = (k - (L % k)) % k + k
        eol_block = jnp.broadcast_to(
            t["eol_cls"][:, None, None], (R, B, extra)
        )
        cls = jnp.concatenate([cls, eol_block], axis=2)
        Lk = cls.shape[2] // k
        cls = cls.reshape(R, B, Lk, k)
        # combine k classes into one super-symbol, per-rule radix C_r
        comb = cls[..., 0]
        for j in range(1, k):
            comb = comb * t["C"][:, None, None] + cls[..., j]
        return comb

    def _super_symbols_pairs(self, t: dict, batch: "jnp.ndarray",
                             lengths: "jnp.ndarray") -> "jnp.ndarray":
        """Even-stride symbol packing through the byte-pair class
        tables: one [R, 65536] gather per TWO bytes instead of one
        class gather per byte, then k/2 pair-symbols combine at radix
        C². Pad fix-up happens in pair space — fully-padded pairs
        become the absorbing EOL pair, and the single possibly-mixed
        pair at an odd length boundary is patched from the last valid
        byte's class. Bit-identical to the per-byte path
        (differentially tested in tests/test_ops_grep.py)."""
        R, B, L = batch.shape
        k = self.k
        if L % 2:
            batch = jnp.concatenate(
                [batch, jnp.zeros((R, B, 1), dtype=batch.dtype)], axis=2)
            L += 1
        idx = (batch[..., 0::2].astype(jnp.int32)
               + 256 * batch[..., 1::2].astype(jnp.int32))  # [R,B,L2]
        pcls = jax.vmap(lambda pm, ix: pm[ix])(t["pair_maps"], idx)
        L2 = L // 2
        t2 = jnp.arange(L2, dtype=jnp.int32) * 2
        eol_pair = t["eol_cls"] * t["C"] + t["eol_cls"]  # [R]
        # boundary pair (first byte valid, second padded):
        # class(last byte) * C + eol — one [R, B] gather, broadcast
        # into the single position it can occupy
        last_idx = jnp.clip(lengths - 1, 0)[..., None]       # [R,B,1]
        last_b = jnp.take_along_axis(batch, last_idx, axis=2)
        last_cls = jax.vmap(lambda cm, bt: cm[bt])(t["class_maps"],
                                                   last_b)  # [R,B,1]
        mixed = (last_cls * t["C"][:, None, None]
                 + t["eol_cls"][:, None, None])
        pcls = jnp.where(t2[None, None, :] + 1 == lengths[:, :, None],
                         mixed, pcls)
        pcls = jnp.where(t2[None, None, :] >= lengths[:, :, None],
                         eol_pair[:, None, None], pcls)
        # append EOL-pair block: >=1 full EOL super-symbol and rounds
        # L2 to a multiple of k/2 (same arithmetic as the byte path —
        # EOL is absorbing, extra tail symbols are no-ops)
        k2 = k // 2
        extra = (k2 - (L2 % k2)) % k2 + k2
        pcls = jnp.concatenate(
            [pcls, jnp.broadcast_to(eol_pair[:, None, None],
                                    (R, B, extra))], axis=2)
        Lk = pcls.shape[2] // k2
        pcls = pcls.reshape(R, B, Lk, k2)
        C2 = t["C"] * t["C"]
        comb = pcls[..., 0]
        for j in range(1, k2):
            comb = comb * C2[:, None, None] + pcls[..., j]
        return comb

    def _match_impl(self, t: dict, batch: "jnp.ndarray",
                    lengths: "jnp.ndarray"):
        R, B, L = batch.shape
        comb = self._super_symbols(t, batch, lengths)
        comb_t = jnp.moveaxis(comb, 2, 0)  # [Lk, R, B]

        # + 0*lengths: ties the carry to the (possibly mesh-sharded) batch
        # so its varying-axes annotation matches the scan output under
        # shard_map; a no-op single-device
        state0 = jnp.broadcast_to(t["starts"][:, None], (R, B)) + 0 * lengths

        def step(state, c_t):
            idx = state * t["Ck"][:, None] + c_t
            ns = jnp.take_along_axis(t["trans_flat"], idx, axis=1)
            return ns, None

        final, _ = lax.scan(step, state0, comb_t)
        return (final == ACC) & (lengths >= 0)

    def _match_assoc_impl(self, t: dict, batch: "jnp.ndarray",
                          lengths: "jnp.ndarray"):
        """Parallel-in-time DFA: the line's symbols are composed as
        transition FUNCTIONS instead of stepped as states.

        Each segment of m super-symbols is scanned once over ALL S
        states (m sequential steps on [R,B,G,S] gathers), producing a
        per-segment function table; segments then combine in a
        log2(G)-deep tree of compositions ``(f∘g)[s] = g[f[s]]``
        (take_along_axis over the state axis). Sequential depth drops
        from Lk to m + log2(G) — the S× extra parallel work is exactly
        what the TPU's vector lanes absorb, where the scan kernel's
        serialized gather chain leaves them idle. Bit-identical to
        _match_impl (differentially tested)."""
        R, B, L = batch.shape
        m = self.segment
        S = self.max_states
        comb = self._super_symbols(t, batch, lengths)  # [R, B, Lk]
        Lk = comb.shape[2]
        G = -(-Lk // m)
        # pad the segment grid to a power of two with all-EOL segments
        # (EOL is absorbing, so they compose as no-ops past the line)
        G2 = 1
        while G2 < G:
            G2 *= 2
        pad = G2 * m - Lk
        if pad:
            # super-symbol of k EOL classes: eol * (C^{k-1}+...+C+1)
            radix = jnp.ones_like(t["C"])
            eol_super = jnp.zeros_like(t["eol_cls"])
            for _ in range(self.k):
                eol_super = eol_super + t["eol_cls"] * radix
                radix = radix * t["C"]
            comb = jnp.concatenate(
                [comb, jnp.broadcast_to(eol_super[:, None, None],
                                        (R, B, pad))], axis=2)
        comb = comb.reshape(R, B, G2, m)

        def gather_rule(tf, idx):
            return tf[idx]

        states = jnp.arange(S, dtype=jnp.int32)
        idx0 = (states[None, None, None, :]
                * t["Ck"][:, None, None, None] + comb[..., 0:1])
        F = jax.vmap(gather_rule)(t["trans_flat"], idx0)  # [R,B,G2,S]

        def seg_step(F, c_j):  # c_j: [R, B, G2]
            idx = F * t["Ck"][:, None, None, None] + c_j[..., None]
            return jax.vmap(gather_rule)(t["trans_flat"], idx), None

        if m > 1:
            comb_j = jnp.moveaxis(comb[..., 1:], 3, 0)  # [m-1, R, B, G2]
            F, _ = lax.scan(seg_step, F, comb_j)
        g = G2
        while g > 1:  # static tree: g halves each round
            f_half = F[:, :, 0::2]
            g_half = F[:, :, 1::2]
            F = jnp.take_along_axis(g_half, f_half, axis=3)
            g //= 2
        final_fn = F[:, :, 0, :]  # [R, B, S]: whole-line function
        start_idx = jnp.broadcast_to(t["starts"][:, None, None], (R, B, 1))
        final = jnp.take_along_axis(final_fn, start_idx, axis=2)[..., 0]
        # + 0*lengths keeps the shard_map varying-axes annotation tied
        # to the batch, mirroring _match_impl's state0 trick
        return (final + 0 * lengths == ACC) & (lengths >= 0)

    def dispatch(self, batch: np.ndarray, lengths: np.ndarray):
        """Launch the kernel WITHOUT forcing the result (jax dispatch
        is asynchronous) — the launch half of the double-buffered
        staging pipeline (core.chunk_batch.double_buffered): the caller
        stages the next segment while this one's kernel is in flight,
        then forces with np.asarray one segment behind."""
        if self._children is not None:
            # per-k child programs: every child launches (async) before
            # the merge touches any result, so the k-groups overlap the
            # same way double-buffered segments do
            parts = [c.dispatch(batch[idx], lengths[idx])
                     for c, idx in zip(self._children, self._child_idxs)]
            return self._merge_rule_axis(parts)
        if self._jit is None:
            from . import device

            if not device.wait(60.0):
                raise RuntimeError(
                    f"device backend not attached: {device.status()}"
                )
            self._materialize()
        return self._jit(jnp.asarray(batch), jnp.asarray(lengths))

    def match(self, batch: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Run the kernel; returns bool [R, B] (numpy). Blocks up to the
        attach-wait deadline if the backend isn't up yet."""
        return np.asarray(self.dispatch(batch, lengths))

    # -- multi-device (SPMD over a 1-D device mesh) --

    def sharded_matcher(self, mesh, axis: str = "batch"):
        """Build the SPMD matcher for ``mesh``: the batch dimension is
        sharded across devices (the DP axis of SURVEY §2.4 — chunks →
        fixed-width arrays), the per-rule transition tables replicate, and
        global per-rule match counts reduce with ``lax.psum`` over ICI
        (the metrics-reduction contract of BASELINE/SURVEY §2.4).

        Returns ``fn(batch[R, B, L], lengths[R, B]) -> (mask[R, B],
        counts[R])`` with ``B`` divisible by the mesh size; ``counts`` is
        the global (all-device) per-rule match total.
        """
        from jax.sharding import PartitionSpec as P

        from .device import shard_map_fn

        shard_map = shard_map_fn()

        if self._jit is None:
            from . import device

            if not device.wait(60.0):
                raise RuntimeError(
                    f"device backend not attached: {device.status()}"
                )
            self._materialize()

        def step(batch, lengths):
            mask = self._impl(batch, lengths)
            counts = lax.psum(
                jnp.sum(mask.astype(jnp.int32), axis=1), axis_name=axis
            )
            return mask, counts

        return jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(P(None, axis, None), P(None, axis)),
                out_specs=(P(None, axis), P()),
            )
        )

    def match_sharded(self, mesh, batch: np.ndarray, lengths: np.ndarray):
        """Pad B up to the mesh size and run the SPMD matcher; returns
        (mask[R, B] numpy, counts[R] numpy, matcher-padded batch size)."""
        from .mesh import mesh_key, pad_to_devices

        if self._children is not None:
            masks, counts, bp = [], [], 0
            for c, idx in zip(self._children, self._child_idxs):
                m, ct, bp = c.match_sharded(mesh, batch[idx], lengths[idx])
                masks.append(m)
                counts.append(ct)
            inv = self._inv_perm
            return (np.concatenate(masks, axis=0)[inv],
                    np.concatenate(counts, axis=0)[inv], bp)

        R, B, L = batch.shape
        Bp = pad_to_devices(B, mesh.devices.size)
        if Bp != B:
            batch = np.concatenate(
                [batch, np.zeros((R, Bp - B, L), dtype=batch.dtype)], axis=1
            )
            lengths = np.concatenate(
                [lengths, np.full((R, Bp - B), -1, dtype=lengths.dtype)], axis=1
            )
        key = mesh_key(mesh)
        fn = self._sharded_cache.get(key)
        if fn is None:
            fn = self.sharded_matcher(mesh, axis=mesh.axis_names[0])
            self._sharded_cache[key] = fn
        mask, counts = fn(jnp.asarray(batch), jnp.asarray(lengths))
        return np.asarray(mask)[:, :B], np.asarray(counts), Bp

    # -- explicitly partitioned pjit program (the fbtpu-mesh plane) --

    def mesh_variant(self, mesh) -> str:
        """Which axis of the [R, B, L] program shards across the mesh.

        ``"batch"`` (default): B splits across devices, the transition/
        pair-class tables replicate — right whenever the tables are
        small relative to per-device memory. ``"rules"``: for large
        rule sets the replicated tables dominate (R × C^k rows + the
        R × 65536 pair maps), so the RULE axis shards instead — each
        device holds 1/n of the tables and matches the full batch
        against its own rules. Gated on the replicated-table footprint
        crossing ``FBTPU_MESH_TABLE_BUDGET`` (default 64 MiB) or R ≥
        ``FBTPU_MESH_RULE_SHARD_R`` (default 64), and on R dividing the
        mesh evenly (no rule padding — a dead-rule pad row would cost a
        full batch scan)."""
        import os as _os

        from .mesh import replicated_table_bytes

        if self._children is not None:
            # k-split programs never rule-shard (the split is gated off
            # the rule-shard regime in __init__); each child answers
            # for its own slice and they all land on "batch"
            return self._children[0].mesh_variant(mesh)
        n_dev = mesh.devices.size
        R = len(self.dfas)
        if R < 2 or R % n_dev != 0:
            return "batch"
        tbl = getattr(self, "_tbl", None)
        if tbl is None:
            table_bytes = replicated_table_bytes(self._np)
        else:
            table_bytes = replicated_table_bytes(tbl)
        budget = int(_os.environ.get("FBTPU_MESH_TABLE_BUDGET",
                                     str(64 * 1024 * 1024)))
        min_r = int(_os.environ.get("FBTPU_MESH_RULE_SHARD_R", "64"))
        if table_bytes * n_dev > budget or R >= min_r:
            return "rules"
        return "batch"

    def _mesh_handle(self, mesh, donate: str = "auto",
                     with_counts: bool = True):
        """Build (and cache per mesh structure) the explicitly
        partitioned matcher: a ``shard_map`` program under ``jax.jit``
        with declarative PartitionSpecs from the partition-rules layer,
        tables device_put once with their shardings, and staged input
        buffers donated where (and only where) they can alias an
        output.

        ``with_counts=False`` compiles the engine-dispatch variant
        WITHOUT the per-rule match totals: the counts are an O(R·B)
        reduction plus (batch variant) a cross-device ``psum`` — a
        sync point per segment launch — and the filter path never
        reads them. Only match_mesh/bench/metrics consumers pay for
        counts."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from . import device
        from .device import shard_map_fn
        from .mesh import (aliasable_donations, match_partition_rules,
                           mesh_key, partition_rules)

        if self._jit is None:
            if not device.wait(60.0):
                raise RuntimeError(
                    f"device backend not attached: {device.status()}"
                )
            self._materialize()
        key = (mesh_key(mesh), donate, with_counts)
        h = self._mesh_cache.get(key)
        if h is not None:
            return h

        axis = mesh.axis_names[0]
        variant = self.mesh_variant(mesh)
        R = len(self.dfas)
        # the whole sharding layout of the program lives in the
        # declarative registry (ops.mesh.PARTITION_RULES) — every table
        # leaf named explicitly, the same tables fbtpu-speccheck
        # evaluates statically; only the staged-input/output specs are
        # per-variant here
        if variant == "rules":
            table_rules = partition_rules("grep-rules", axis)
            spec_b, spec_l = P(axis, None, None), P(axis, None)
            spec_mask, spec_counts = P(axis, None), P(axis)
        else:
            table_rules = partition_rules("grep-batch", axis)
            spec_b, spec_l = P(None, axis, None), P(None, axis)
            spec_mask, spec_counts = P(None, axis), P()
        tspecs = match_partition_rules(table_rules, self._tbl)

        kern = (self._match_assoc_impl
                if self.kernel_resolved == "assoc" else self._match_impl)

        def step(t, batch, lengths):
            mask = kern(t, batch, lengths)
            # i32 mask (not bool): exactly matches the donated lengths
            # buffer's sharded aval, so XLA aliases the verdict into
            # the staging buffer instead of allocating a new one
            if not with_counts:
                return mask.astype(jnp.int32)
            counts = jnp.sum(mask.astype(jnp.int32), axis=1)
            if variant == "batch":
                # global per-rule totals over ICI; the rules variant
                # already sees the full batch per shard
                counts = lax.psum(counts, axis_name=axis)
            return mask.astype(jnp.int32), counts

        shard_map = shard_map_fn()
        out_specs = (spec_mask, spec_counts) if with_counts else spec_mask
        sm = shard_map(step, mesh=mesh,
                       in_specs=(tspecs, spec_b, spec_l),
                       out_specs=out_specs)
        tsh = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), tspecs)
        sh_b = NamedSharding(mesh, spec_b)
        sh_l = NamedSharding(mesh, spec_l)
        out_sh = (NamedSharding(mesh, spec_mask),
                  NamedSharding(mesh, spec_counts)) if with_counts \
            else NamedSharding(mesh, spec_mask)

        # donation: arg 1 (batch) and arg 2 (lengths) are per-segment
        # staging buffers; donate exactly the subset whose sharded
        # (shape, dtype) matches an output — jax silently falls back to
        # a copy (plus a warning) for anything else, which the mesh
        # bench must never report as donated. Shapes vary per call, so
        # the donate set is computed from dtypes on a canonical shape:
        # lengths i32 [R, B] ↔ mask i32 [R, B] always aliases; batch
        # u8 [R, B, L] never has an aliasable output.
        Bc = mesh.devices.size * 8  # canonical shape for the aval match
        Lc = self.max_len
        donate_idx: tuple = ()
        if donate != "off":
            outs = [((R, Bc), np.int32, spec_mask)]
            if with_counts:
                outs.append(((R,), np.int32, spec_counts))
            cand = aliasable_donations(
                mesh,
                in_specs=[
                    ((R, Bc, Lc), np.uint8, spec_b, True),
                    ((R, Bc), np.int32, spec_l, True),
                ],
                out_specs=outs,
            )
            if donate == "all":
                cand = [0, 1]
            donate_idx = tuple(i + 1 for i in cand)  # tables are arg 0

        fn = jax.jit(sm, in_shardings=(tsh, sh_b, sh_l),
                     out_shardings=out_sh, donate_argnums=donate_idx)
        tables_dev = jax.device_put(self._tbl, tsh)
        h = _MeshHandle(fn, tables_dev, sh_b, sh_l, variant,
                        int(mesh.devices.size), donate_idx, with_counts)
        self._mesh_cache[key] = h
        return h

    def dispatch_mesh(self, mesh, batch: np.ndarray, lengths: np.ndarray,
                      donate: str = "auto", with_counts: bool = True):
        """Launch the partitioned matcher WITHOUT forcing (the mesh half
        of the double-buffered pipeline). Pads B up to the mesh size
        (batch variant; the rules variant shards R and takes B as-is),
        transfers the staged buffers with their input shardings — each
        device receives only its own shard — and returns
        ``(mask_i32 dev[R, Bp], counts dev | None, B, Bp)``
        (``with_counts=False`` skips the per-rule totals and their
        cross-device psum — the engine filter path never reads them).
        The staged device buffers are CONSUMED when donation is on:
        re-reading them after dispatch raises instead of silently
        aliasing the verdict bytes."""
        from .mesh import pad_to_devices

        if self._children is not None:
            # per-k children: launch them all first (async), then merge
            # on the rule axis. Children may pad B differently (the
            # rules variant is gated off, but keep the contract local):
            # each part is sliced back to B lazily before the concat.
            B = batch.shape[1]
            parts, count_parts, bps = [], [], []
            for c, idx in zip(self._children, self._child_idxs):
                m, ct, _b, bp = c.dispatch_mesh(
                    mesh, batch[idx], lengths[idx], donate, with_counts)
                parts.append(m)
                count_parts.append(ct)
                bps.append(bp)
            if len(set(bps)) == 1:
                # the normal case: every child padded B identically
                # (same mesh, batch variant), so the merged mask keeps
                # the padded columns and Bp describes it — the same
                # contract as the unsplit program
                Bp = bps[0]
            else:
                # children disagree (a child crossed into the rules
                # variant): normalize to the unpadded batch
                parts = [p[:, :B] for p in parts]
                Bp = B
            mask = self._merge_rule_axis(parts)
            counts = (self._merge_rule_axis(count_parts)
                      if with_counts else None)
            return mask, counts, B, Bp

        h = self._mesh_handle(mesh, donate, with_counts)
        R, B, L = batch.shape
        Bp = pad_to_devices(B, h.n_devices) if h.variant == "batch" else B
        if Bp != B:
            batch = np.concatenate(
                [batch, np.zeros((R, Bp - B, L), dtype=batch.dtype)],
                axis=1)
            lengths = np.concatenate(
                [lengths, np.full((R, Bp - B), -1, dtype=lengths.dtype)],
                axis=1)
        bd = jax.device_put(np.ascontiguousarray(batch, dtype=np.uint8),
                            h.sh_b)
        ld = jax.device_put(np.ascontiguousarray(lengths, dtype=np.int32),
                            h.sh_l)
        if with_counts:
            mask_i32, counts = h.fn(h.tables, bd, ld)
        else:
            mask_i32, counts = h.fn(h.tables, bd, ld), None
        return mask_i32, counts, B, Bp

    def match_mesh(self, mesh, batch: np.ndarray, lengths: np.ndarray,
                   donate: str = "auto"):
        """Run the partitioned matcher and force: returns
        ``(mask[R, B] bool numpy, counts[R] numpy, Bp)`` — bit-exact
        with :meth:`match` and the CPU chain (tier-1 ``mesh`` tests)."""
        mask_i32, counts, B, Bp = self.dispatch_mesh(
            mesh, batch, lengths, donate)
        mask = np.asarray(mask_i32).astype(bool)[:, :B]
        return mask, np.asarray(counts), Bp

    def donation_info(self, mesh, B: int = 64,
                      donate: str = "auto") -> dict:
        """Compile-level donation status for the bench RESULT / tier-1
        donation test: which staged args are declared donated, whether
        the lowered module carries the input→output aliases
        (``tf.aliasing_output``), plus the variant and per-device batch
        share for a B-row segment."""
        from .mesh import donation_report, pad_to_devices

        if self._children is not None:
            rep = self._children[0].donation_info(mesh, B, donate)
            rep["k_groups"] = [int(c.k) for c in self._children]
            return rep

        h = self._mesh_handle(mesh, donate)
        R = len(self.dfas)
        Bp = pad_to_devices(B, h.n_devices) if h.variant == "batch" else B
        batch = np.zeros((R, Bp, self.max_len), dtype=np.uint8)
        lengths = np.full((R, Bp), -1, dtype=np.int32)
        bd = jax.device_put(batch, h.sh_b)
        ld = jax.device_put(lengths, h.sh_l)
        lowered = h.fn.lower(h.tables, bd, ld)
        names = ["tables", "batch", "lengths"]
        rep = donation_report(lowered, h.donate_idx, names)
        rep.update({
            "variant": h.variant,
            "devices": h.n_devices,
            "per_device_batch_share": (
                Bp // h.n_devices if h.variant == "batch" else Bp),
            "per_device_rule_share": (
                R // h.n_devices if h.variant == "rules" else R),
        })
        return rep


class _MeshHandle:
    """One mesh's compiled partitioned matcher + resident sharded
    tables (built once per mesh structure by ``_mesh_handle``)."""

    __slots__ = ("fn", "tables", "sh_b", "sh_l", "variant",
                 "n_devices", "donate_idx", "with_counts")

    def __init__(self, fn, tables, sh_b, sh_l, variant, n_devices,
                 donate_idx, with_counts=True):
        self.fn = fn
        self.tables = tables
        self.sh_b = sh_b
        self.sh_l = sh_l
        self.variant = variant
        self.n_devices = n_devices
        self.donate_idx = donate_idx
        self.with_counts = with_counts


@functools.lru_cache(maxsize=64)
def _cached_program(patterns: Tuple[str, ...], max_len: int,
                    minimize: bool) -> "GrepProgram":
    from ..regex.dfa import compile_dfa

    return GrepProgram([compile_dfa(p, minimize=minimize)
                        for p in patterns], max_len)


def program_for(patterns: Sequence[str], max_len: int = 512) -> "GrepProgram":
    """Compiled-program cache keyed by the pattern tuple (and the
    FBTPU_DFA_MIN toggle — the bench's minimization-off differential
    must never be served a cached minimized program, or vice versa)."""
    from ..regex.dfa import minimize_enabled

    return _cached_program(tuple(patterns), max_len, minimize_enabled())
