"""fbtpu-armor — the device fault domain (FAULTS.md "fbtpu-armor").

Every entry into the jit/pjit/shard_map plane goes through a
:class:`DeviceLane`: a per-plane wrapper that turns device failures into
bit-exact CPU fallbacks instead of lost records, stalled engine loops,
or a permanently pinned slow path. One lane exists per device plane
("grep" for the DFA filter matchers, "flux" for the sketch/window
kernels); lanes are process-global because the jax backend is.

What a lane guarantees per launch:

- **containment** — the launch runs on a watched worker thread; any
  exception (XlaRuntimeError, RESOURCE_EXHAUSTED, injected faults)
  resolves to the caller-supplied bit-exact host fallback. The verdict
  a caller commits comes from exactly ONE of {device result, fallback}
  — never both, never a partial.
- **launch deadline** — a launch that never returns (the wedged-device
  shape ``device.launch_hang`` injects) is soft-killed at
  ``FBTPU_LAUNCH_DEADLINE_S`` (default 120 s — first launches compile):
  the worker is abandoned (its eventual result is discarded, so a late
  completion can never commit a stale verdict) and the segment
  completes on the fallback. The fbtpu-guard watchdog pattern, applied
  to kernel launches.
- **re-staging on retry** — callers re-enter through their launch
  closure, which re-stages device buffers from host arrays on every
  attempt. A launch that consumed its donated staged buffers
  (``dispatch_mesh`` donates the lengths buffer) and THEN failed must
  never be retried against the deleted aval; the ``device.dispatch``
  failpoint fires at the post-launch boundary precisely to regression-
  test that hazard.
- **circuit breaking** — consecutive failures open a per-lane
  :class:`~fluentbit_tpu.core.guard.CircuitBreaker`
  (``FBTPU_DEVICE_BREAKER_FAILURES`` / ``_COOLDOWN``): while open,
  launches short-circuit straight to the fallback (no thread, no
  device touch); after the cooldown ONE probe launch re-tests the
  device, closing the breaker on success (and re-arming attach via
  ``device.reattach_async`` when the attach controller is exhausted).
- **mesh shrink/regrow** — a :class:`DeviceLostError` (real device
  loss, or the ``mesh.device_lost`` failpoint) shrinks the lane's mesh
  to the surviving devices (``ops.mesh.build_mesh(n_devices=...)``;
  per-``mesh_key`` handles recompile automatically, callers re-pad via
  ``pad_to_devices``) — bit-exact vs the full mesh. The mesh regrows
  to the full device set when the breaker re-closes, or — for a
  one-off loss that never opened the breaker — after
  ``FBTPU_DEVICE_REGROW_AFTER`` consecutive healthy launches on the
  survivors (a still-dead device just shrinks it back).

Observability: ``fluentbit_device_*`` metrics via the engine's
listener bridge (:func:`add_listener`), a ``"device"`` block in
``/api/v1/health`` (:func:`health_block`), and :func:`snapshot` for the
bench ``mesh.failover`` stats.

Cost model: each guarded launch runs on a fresh watched worker thread
(~50-100 µs spawn). That is a deliberate trade — it buys the deadline
+ hard-abandonment semantics with zero shared-worker state to wedge,
and it only applies to device paths, where a segment launch (thousands
of records through a compiled kernel) dwarfs the spawn; the 1-core CPU
bench hot path (native fused matcher, host sketch twins) never enters
a lane. If per-launch spawn ever shows up on a real-chip profile, a
persistent per-lane worker pair (keeping the depth-2 overlap) is the
upgrade path.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional

from ..core.lockorder import make_lock

log = logging.getLogger("flb.device.fault")

__all__ = [
    "DeviceLane", "DeviceLostError", "lane", "lanes", "reset",
    "snapshot", "health_block", "add_listener", "remove_listener",
    "notify",
]


class DeviceLostError(RuntimeError):
    """A launch failed because a device dropped out of the mesh (not a
    transient kernel error): the lane shrinks the mesh before the next
    launch instead of burning the breaker budget against a dead chip."""


#: Error-text signatures that mark a runtime failure as device LOSS
#: rather than a transient kernel error. Real losses surface as
#: XlaRuntimeError with a DEVICE_LOST-flavored message (PJRT's status
#: code name), not as our DeviceLostError — without this mapping the
#: shrink-to-survivors path would only ever engage under the
#: mesh.device_lost failpoint.
_DEVICE_LOST_SIGNATURES = ("device_lost", "device lost", "device is lost")


def is_device_loss(err: BaseException) -> bool:
    """Classify a launch failure as device loss (shrink the mesh) vs a
    transient error (fallback + breaker only)."""
    if isinstance(err, DeviceLostError):
        return True
    text = repr(err).lower()
    return any(sig in text for sig in _DEVICE_LOST_SIGNATURES)


def launch_deadline() -> float:
    try:
        return max(0.1, float(
            os.environ.get("FBTPU_LAUNCH_DEADLINE_S", "120")))
    except ValueError:
        return 120.0


def _breaker_failures() -> int:
    try:
        return max(1, int(
            os.environ.get("FBTPU_DEVICE_BREAKER_FAILURES", "3")))
    except ValueError:
        return 3


def _breaker_cooldown() -> float:
    try:
        return max(0.01, float(
            os.environ.get("FBTPU_DEVICE_BREAKER_COOLDOWN", "5")))
    except ValueError:
        return 5.0


def _regrow_after() -> int:
    try:
        return max(1, int(
            os.environ.get("FBTPU_DEVICE_REGROW_AFTER", "64")))
    except ValueError:
        return 64


# -- listener bridge (the engine wires fluentbit_device_* here) --------

_listener_lock = make_lock("fault._listener_lock")
_listeners: List[Callable[[str, str, object], None]] = []


def add_listener(cb: Callable[[str, str, object], None]) -> None:
    """Register ``cb(lane_name, event, value)``. Events: ``fallback``,
    ``timeout``, ``failure``, ``device_lost``, ``short_circuit``,
    ``breaker`` (value = new state name), ``mesh_devices`` (value =
    current device count), ``reattach`` (value = attach generation)."""
    with _listener_lock:
        if cb not in _listeners:
            _listeners.append(cb)


def remove_listener(cb: Callable[[str, str, object], None]) -> None:
    with _listener_lock:
        if cb in _listeners:
            _listeners.remove(cb)


def notify(lane_name: str, event: str, value: object = 1) -> None:
    with _listener_lock:
        cbs = list(_listeners)
    for cb in cbs:
        try:
            cb(lane_name, event, value)
        except Exception:
            log.exception("device fault listener failed")


# -- one guarded launch ------------------------------------------------


class _Flight:
    """One in-flight watched launch (the lane's begin/finish handle)."""

    __slots__ = ("launch", "fallback", "denied", "deadline", "done",
                 "box", "thread")

    def __init__(self, launch, fallback, denied: bool, deadline: float):
        self.launch = launch
        self.fallback = fallback
        self.denied = denied
        self.deadline = deadline
        self.done = threading.Event()
        self.box: dict = {}
        self.thread: Optional[threading.Thread] = None


class DeviceLane:
    """Fault domain for one device plane (see module docstring).

    ``begin``/``finish`` split the guarded launch so callers can keep
    their staging/kernel overlap (``double_buffered``): ``begin``
    starts the watched worker and returns immediately; ``finish``
    waits (bounded), applies breaker/fallback policy, and returns the
    final host-side result. ``run`` = begin + finish for unpipelined
    callers (the flux sketch updates).
    """

    def __init__(self, name: str, failures: Optional[int] = None,
                 cooldown: Optional[float] = None,
                 deadline: Optional[float] = None,
                 regrow_after: Optional[int] = None):
        from ..core.guard import CircuitBreaker

        self.name = name
        self.deadline = deadline if deadline is not None \
            else launch_deadline()
        self.regrow_after = regrow_after if regrow_after is not None \
            else _regrow_after()
        self.breaker = CircuitBreaker(
            f"device:{name}",
            failures=failures if failures is not None
            else _breaker_failures(),
            cooldown=cooldown if cooldown is not None
            else _breaker_cooldown(),
            on_transition=self._on_transition,
        )
        self._lock = make_lock("DeviceLane._lock")
        self._stats = {
            "launches": 0, "ok": 0, "failures": 0, "timeouts": 0,
            "fallback_segments": 0, "short_circuits": 0,
            "device_lost": 0, "breaker_trips": 0, "abandoned": 0,
        }
        self._lost = 0           # devices shrunk out of the mesh
        self._ok_since_shrink = 0  # healthy launches on the shrunk mesh
        self._mesh = None        # cached mesh for (_mesh_key)
        self._mesh_key = None    # (attach generation, lost, axis)

    # -- breaker transitions -------------------------------------------

    def _on_transition(self, _name: str, old: str, new: str) -> None:
        if new == "open":
            with self._lock:
                self._stats["breaker_trips"] += 1
        if new == "half-open":
            # the probe that would re-test a dead backend re-tests the
            # ATTACH when the controller is exhausted: success bumps
            # the generation and the mesh lane swaps back in live
            from . import device

            if device.failed():
                device.reattach_async()
        if old != "closed" and new == "closed":
            # recovery: regrow the mesh to the full device set
            with self._lock:
                self._lost = 0
                self._ok_since_shrink = 0
                self._mesh_key = None
        notify(self.name, "breaker", new)
        level = logging.WARNING if new != "closed" else logging.INFO
        log.log(level, "device lane %s: breaker %s -> %s",
                self.name, old, new)

    # -- mesh lifecycle ------------------------------------------------

    def current_mesh(self, axis: str = "batch"):
        """The mesh this lane launches over right now: the full device
        set normally; after device loss, the surviving devices (None
        when fewer than 2 survive — callers then run unsharded or on
        the host twin). Cached per (attach generation, lost, axis), so
        a re-attach or a shrink/regrow rebuilds exactly once."""
        from . import device
        from . import mesh as om

        gen = device.generation()
        with self._lock:
            lost = self._lost  # ONE read keys AND sizes the build: a
            # concurrent shrink between two reads must not cache a mesh
            # built over one device set under a key recording another
            key = (gen, lost, axis)
            if key == self._mesh_key:
                return self._mesh
        n = None
        if lost:
            n = max(0, device.device_count() - lost)
        mesh = om.build_mesh(n_devices=n, axis=axis)
        with self._lock:
            if self._lost == lost:  # loss state unchanged since keying
                self._mesh = mesh
                self._mesh_key = key
            # else: stale build — serve it once (the launch fails and
            # re-shrinks if it really is stale), never cache it
        notify(self.name, "mesh_devices",
               mesh.devices.size if mesh is not None else 1)
        return mesh

    def _device_lost(self) -> None:
        from . import device

        total = device.device_count()
        with self._lock:
            self._stats["device_lost"] += 1
            if self._lost < max(0, total - 1):
                self._lost += 1
            self._ok_since_shrink = 0
            self._mesh_key = None  # rebuild over the survivors
        notify(self.name, "device_lost", 1)
        log.warning("device lane %s: device lost — mesh shrinks to %d "
                    "device(s); regrows when the breaker re-closes or "
                    "after %d healthy launches",
                    self.name, max(1, total - self._lost),
                    self.regrow_after)

    # -- the guarded launch --------------------------------------------

    def _watched(self, flight: _Flight) -> None:
        """Worker-thread body: failpoint sites + the launch itself.
        ``device.launch_hang`` fires BEFORE the launch (a launch that
        never returns); ``mesh.device_lost`` marks the launch as device
        loss; ``device.dispatch`` fires at the POST-launch boundary —
        donated staged buffers are consumed by then, so a ``return``
        spec exercises exactly the re-stage-on-retry hazard."""
        from .. import failpoints as _fp

        try:
            if _fp.ACTIVE:
                _fp.fire("device.launch_hang")
                try:
                    _fp.fire("mesh.device_lost")
                except _fp.FailpointError as e:
                    raise DeviceLostError(str(e)) from None
            out = flight.launch()
            if _fp.ACTIVE:
                _fp.fire("device.dispatch")
            flight.box["result"] = out
        except BaseException as e:  # noqa: BLE001 - resolves to fallback
            flight.box["error"] = e
        finally:
            flight.done.set()

    def begin(self, launch, fallback,
              deadline: Optional[float] = None) -> _Flight:
        """Start one guarded launch. ``launch`` must run the device
        dispatch AND force the result to host (numpy) before returning
        — forcing inside the worker is what lets the deadline cover a
        wedged execution, and what keeps staging overlap alive when the
        caller pipelines begin/finish. ``fallback`` is the bit-exact
        host twin, called at ``finish`` time only."""
        with self._lock:
            self._stats["launches"] += 1
        if not self.breaker.allow():
            with self._lock:
                self._stats["short_circuits"] += 1
            notify(self.name, "short_circuit", 1)
            return _Flight(launch, fallback, denied=True, deadline=0.0)
        fl = _Flight(launch, fallback, denied=False,
                     deadline=self.deadline if deadline is None
                     else deadline)
        t = threading.Thread(target=self._watched, args=(fl,),
                             daemon=True,
                             name=f"flb-lane-{self.name}")
        fl.thread = t
        t.start()
        return fl

    def finish(self, flight: _Flight):
        """Resolve one guarded launch to its final host result: the
        device verdict on success, the bit-exact fallback on denial,
        failure, or deadline expiry. Nothing is committed until this
        returns — a soft-killed worker's late result is discarded."""
        if flight.denied:
            return self._fall_back(flight, record=False)
        if not flight.done.wait(flight.deadline):
            # wedged launch: abandon the worker (daemon thread; its
            # eventual result lands in a box nobody reads) and serve
            # the segment on the host twin
            with self._lock:
                self._stats["timeouts"] += 1
                self._stats["abandoned"] += 1
                self._ok_since_shrink = 0
            notify(self.name, "timeout", 1)
            log.warning(
                "device lane %s: launch exceeded its %.1fs deadline — "
                "soft-killed to the CPU fallback (worker abandoned)",
                self.name, flight.deadline)
            self.breaker.record_failure()
            return self._fall_back(flight)
        err = flight.box.get("error")
        if err is None:
            regrow = False
            with self._lock:
                self._stats["ok"] += 1
                if self._lost:
                    # regrow probe: a one-off loss must not pin a
                    # shrunk mesh forever when the breaker never
                    # opened — after enough healthy launches on the
                    # survivors, try the full device set again (a
                    # still-dead device just shrinks it back)
                    self._ok_since_shrink += 1
                    if self._ok_since_shrink >= self.regrow_after:
                        self._lost = 0
                        self._ok_since_shrink = 0
                        self._mesh_key = None
                        regrow = True
            if regrow:
                log.info("device lane %s: %d healthy launches on the "
                         "shrunk mesh — probing a regrow to the full "
                         "device set", self.name, self.regrow_after)
            self.breaker.record_ok()
            return flight.box["result"]
        if is_device_loss(err):
            self._device_lost()
        with self._lock:
            self._stats["failures"] += 1
            self._ok_since_shrink = 0
        notify(self.name, "failure", 1)
        log.warning("device lane %s: launch failed (%r) — segment "
                    "completes on the CPU fallback", self.name, err)
        self.breaker.record_failure()
        return self._fall_back(flight)

    def _fall_back(self, flight: _Flight, record: bool = True):
        with self._lock:
            self._stats["fallback_segments"] += 1
        if record:
            notify(self.name, "fallback", 1)
        return flight.fallback()

    def run(self, launch, fallback, deadline: Optional[float] = None):
        """begin + finish: one guarded, deadline-bounded launch."""
        return self.finish(self.begin(launch, fallback, deadline))

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["lost_devices"] = self._lost
        out["breaker"] = self.breaker.state_name()
        mesh = self._mesh
        out["mesh_devices"] = mesh.devices.size if mesh is not None \
            else None
        return out


# -- the process-global lane registry ----------------------------------

_registry_lock = make_lock("fault._registry_lock")
_lanes: Dict[str, DeviceLane] = {}


def lane(name: str) -> DeviceLane:
    """The named lane, created on first use (process-global — the jax
    backend the lanes guard is process-global too)."""
    with _registry_lock:
        ln = _lanes.get(name)
        if ln is None:
            ln = _lanes[name] = DeviceLane(name)
        return ln


def lanes() -> Dict[str, DeviceLane]:
    with _registry_lock:
        return dict(_lanes)


def reset() -> None:
    """Drop every lane (tests: breaker/shrink state must not leak
    between cases)."""
    with _registry_lock:
        _lanes.clear()


def snapshot() -> Dict[str, dict]:
    """Per-lane failover stats (the bench ``mesh.failover`` block)."""
    return {name: ln.stats() for name, ln in lanes().items()}


def health_block() -> dict:
    """The ``"device"`` block of ``/api/v1/health``: attach lifecycle
    (retry-world status) + every lane's breaker/failover state."""
    from . import device

    st = device.status()
    return {
        "attach": {k: st.get(k) for k in (
            "state", "platform", "attempts", "retries_max",
            "next_retry_eta_s", "generation", "error")},
        "lanes": snapshot(),
    }
