"""Device UTF-8 validation — the simdutf-connector equivalent.

Reference: src/simdutf/flb_simdutf_connector.cpp + src/flb_utf8.c (SIMD
Unicode validation; directly relevant to the
benchmarks/utf8_surrogate_bench_10k.ndjson corpus). The TPU re-design
runs a byte-class DFA over ``[B, L] uint8`` staged batches as a
``lax.scan`` of table gathers — the same execution model as the grep
kernel — validating a whole batch of records per dispatch.

The automaton is built from the RFC 3629 well-formedness table
(overlongs, UTF-16 surrogates ED A0..BF, and > U+10FFFF all rejected):

  classes: ASCII, 80-8F, 90-9F, A0-BF, C2-DF, E0, E1-EC|EE-EF, ED,
           F0, F1-F3, F4, invalid (C0-C1, F5-FF)
  states:  OK, C1 (one continuation), C2, C3, E0' (A0-BF then C1),
           ED' (80-9F then C1), F0' (90-BF then C2), F4' (80-8F then
           C2), DEAD
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import jax
    import jax.numpy as jnp
    from jax import lax

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

# byte classes
_ASCII, _80_8F, _90_9F, _A0_BF, _C2_DF, _E0, _E1_EC_EE_EF, _ED, _F0, \
    _F1_F3, _F4, _BAD = range(12)
N_CLASSES = 12

# states
OK, C1, C2, C3, E0S, EDS, F0S, F4S, DEAD = range(9)
N_STATES = 9


def _byte_classes() -> np.ndarray:
    cls = np.full(256, _BAD, dtype=np.int32)
    cls[0x00:0x80] = _ASCII
    cls[0x80:0x90] = _80_8F
    cls[0x90:0xA0] = _90_9F
    cls[0xA0:0xC0] = _A0_BF
    cls[0xC2:0xE0] = _C2_DF
    cls[0xE0] = _E0
    cls[0xE1:0xED] = _E1_EC_EE_EF
    cls[0xED] = _ED
    cls[0xEE:0xF0] = _E1_EC_EE_EF
    cls[0xF0] = _F0
    cls[0xF1:0xF4] = _F1_F3
    cls[0xF4] = _F4
    return cls


def _transitions() -> np.ndarray:
    t = np.full((N_STATES, N_CLASSES), DEAD, dtype=np.int32)
    cont = (_80_8F, _90_9F, _A0_BF)
    t[OK, _ASCII] = OK
    t[OK, _C2_DF] = C1
    t[OK, _E0] = E0S
    t[OK, _E1_EC_EE_EF] = C2
    t[OK, _ED] = EDS
    t[OK, _F0] = F0S
    t[OK, _F1_F3] = C3
    t[OK, _F4] = F4S
    for c in cont:
        t[C1, c] = OK
        t[C2, c] = C1
        t[C3, c] = C2
    t[E0S, _A0_BF] = C1            # E0: A0-BF only (no overlongs)
    t[EDS, _80_8F] = C1            # ED: 80-9F only (no surrogates)
    t[EDS, _90_9F] = C1
    t[F0S, _90_9F] = C2            # F0: 90-BF only (no overlongs)
    t[F0S, _A0_BF] = C2
    t[F4S, _80_8F] = C2            # F4: 80-8F only (<= U+10FFFF)
    return t


_CLS = _byte_classes()
_TRANS = _transitions()


def validate_bytes(data: bytes) -> bool:
    """CPU reference validator (the oracle the kernel must match)."""
    state = OK
    for b in data:
        state = _TRANS[state, _CLS[b]]
        if state == DEAD:
            return False
    return state == OK


class Utf8Validator:
    """Batched device validation: valid[b] per staged row."""

    def __init__(self):
        if not HAVE_JAX:
            raise RuntimeError("jax is unavailable")
        self._jit = None  # materialized when the backend attaches

    def _ensure_device(self) -> bool:
        if self._jit is not None:
            return True
        from . import device

        if not device.ready():
            device.attach_async()
            return False
        self._cls = jnp.asarray(_CLS)
        self._trans = jnp.asarray(_TRANS)
        self._jit = jax.jit(self._impl)
        return True

    def _impl(self, batch, lengths):
        B, L = batch.shape
        cls = self._cls[batch]  # [B, L]
        pos = jnp.arange(L, dtype=jnp.int32)
        pad = pos[None, :] >= lengths[:, None]
        # pad positions map to ASCII (identity for states OK/DEAD; a
        # sequence cut by the pad boundary stays in C*/E*/F* and fails
        # the final state == OK check exactly like a truncated string)
        cls = jnp.where(pad, _ASCII, cls)
        state0 = jnp.zeros((B,), dtype=jnp.int32) + 0 * lengths

        def step(state, c_t):
            return self._trans[state, c_t], None

        final, _ = lax.scan(step, state0, cls.T)
        return (final == OK) & (lengths >= 0)

    def validate(self, batch: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """bool [B] — row i's first lengths[i] bytes are well-formed
        UTF-8 (rows with negative length report False). Falls back to
        the host DFA while the backend is attaching."""
        if self._ensure_device():
            return np.asarray(self._jit(jnp.asarray(batch),
                                        jnp.asarray(lengths)))
        out = np.zeros((batch.shape[0],), dtype=bool)
        for i in range(batch.shape[0]):
            ln = int(lengths[i])
            if ln >= 0:
                out[i] = validate_bytes(bytes(batch[i, :ln]))
        return out


_validator: Optional[Utf8Validator] = None


def validator() -> Utf8Validator:
    global _validator
    if _validator is None:
        _validator = Utf8Validator()
    return _validator
