"""Device attach controller — jax backend init must never block the pipeline.

On some platforms (the axon TPU tunnel in particular) the first backend
touch — ``jax.devices()`` / the first ``jnp.asarray`` — can block in C
for minutes, during which Python signal handlers cannot run. The
reference never has this problem because its regex engine is host-side C
(Onigmo); our device kernels do, so every plugin that compiles a device
program routes its first backend touch through here:

- ``attach_async()`` starts backend init once, in a daemon thread.
- ``wait(timeout)`` joins it with a bounded, signal-interruptible wait.
- ``ready()`` is a cheap non-blocking probe.

Until ``ready()``, callers serve records on their (bit-exact) CPU
fallback path; when attach completes, compiled device programs
materialize lazily and the device path swaps in live. A failed attach
(no jax, broken platform) pins the CPU path permanently.

``FBTPU_ATTACH_WAIT_S`` tunes how long plugin init waits synchronously
for the device before proceeding on CPU (default 2 s — tests force the
CPU platform where attach is near-instant; the bench sets its own longer
deadline).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger("flb.device")

_lock = threading.Lock()
_state = "unattached"  # unattached | attaching | ready | failed
_error: Optional[str] = None
_thread: Optional[threading.Thread] = None
_attach_seconds: Optional[float] = None
_platform: Optional[str] = None


def default_wait() -> float:
    try:
        return float(os.environ.get("FBTPU_ATTACH_WAIT_S", "2"))
    except ValueError:
        return 2.0


def _attach_worker() -> None:
    global _state, _error, _attach_seconds, _platform
    t0 = time.time()
    try:
        from .. import failpoints as _fp

        if _fp.ACTIVE:
            # delay(ms) simulates the minutes-long axon attach stall;
            # return(err) pins the CPU fallback path (state=failed)
            _fp.fire("device.attach")
        import jax
        import jax.numpy as jnp

        n = len(jax.devices())  # the (possibly minutes-long) backend init
        # one trivial dispatch so the runtime is fully warm before the
        # first real kernel
        jnp.zeros((8,), dtype=jnp.int32).block_until_ready()
        with _lock:
            _attach_seconds = time.time() - t0
            _platform = jax.default_backend()
            _state = "ready"
        log.info("device backend attached: %d device(s) in %.1fs",
                 n, _attach_seconds)
    except Exception as e:  # pragma: no cover - platform-dependent
        with _lock:
            _error = repr(e)
            _state = "failed"
        log.warning("device attach failed (CPU path pinned): %r", e)


def attach_async() -> None:
    """Start backend init in the background (idempotent)."""
    global _state, _thread
    with _lock:
        if _state != "unattached":
            return
        _state = "attaching"
        _thread = threading.Thread(
            target=_attach_worker, daemon=True, name="flb-device-attach"
        )
        # start under the lock: wait() must never observe a created-but-
        # unstarted thread (is_alive False) and skip its join
        _thread.start()


def ready() -> bool:
    return _state == "ready"


def failed() -> bool:
    return _state == "failed"


def wait(timeout: Optional[float] = None) -> bool:
    """Ensure attach is running and wait up to ``timeout`` seconds for
    it (None = the FBTPU_ATTACH_WAIT_S default). Returns ready()."""
    attach_async()
    t = _thread
    if t is not None and t.is_alive():
        t.join(default_wait() if timeout is None else timeout)
    return ready()


def platform() -> Optional[str]:
    """Attached backend name ('tpu', 'cpu', ...); None until ready."""
    return _platform


def device_count() -> int:
    """Attached backend's device count; 0 until ready. Under the
    simulated-mesh lane (``--xla_force_host_platform_device_count=8``)
    this reports the virtual devices — the mesh planes (ops.mesh,
    ops.grep mesh matcher, flux kernels) treat those exactly like
    chips. Safe after ready(): the first (possibly minutes-long)
    backend touch already happened in the attach worker."""
    if not ready():
        return 0
    import jax

    return len(jax.devices())


def shard_map_fn():
    """Version-tolerant ``shard_map`` import: top-level in newer jax,
    ``jax.experimental.shard_map`` on 0.4.x.  Every SPMD builder (grep,
    sketches, flux kernels) routes through here so the simulated-mesh
    lane runs on whichever jax the image ships — the bare
    ``from jax import shard_map`` was exactly why the sharded tests sat
    in the pre-existing-failure bucket on 0.4.37."""
    try:
        from jax import shard_map  # type: ignore[attr-defined]

        return shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

        return shard_map


def status() -> dict:
    return {
        "state": _state,
        "error": _error,
        "platform": _platform,
        "attach_seconds": _attach_seconds,
    }
