"""Device attach controller — jax backend init must never block the pipeline.

On some platforms (the axon TPU tunnel in particular) the first backend
touch — ``jax.devices()`` / the first ``jnp.asarray`` — can block in C
for minutes, during which Python signal handlers cannot run. The
reference never has this problem because its regex engine is host-side C
(Onigmo); our device kernels do, so every plugin that compiles a device
program routes its first backend touch through here:

- ``attach_async()`` starts backend init once, in a daemon thread.
- ``wait(timeout)`` joins it with a bounded, signal-interruptible wait.
- ``ready()`` is a cheap non-blocking probe.

Until ``ready()``, callers serve records on their (bit-exact) CPU
fallback path; when attach completes, compiled device programs
materialize lazily and the device path swaps in live.

Attach is RETRIED (fbtpu-armor): a failed backend init no longer pins
the CPU path for the process lifetime. The worker makes up to
``FBTPU_ATTACH_RETRIES`` attempts with jittered exponential backoff
(base ``FBTPU_ATTACH_BACKOFF_S``); ``failed()`` means *exhausted*, not
"tried once". Each successful attach bumps the attach **generation** —
mesh-lane consumers key their resolution on it, so an attach that
succeeds after earlier refusals (or after :func:`reattach_async`) swaps
the device path in live instead of staying pinned. ``status()`` reports
the attempt count, per-attempt error history and the next retry ETA,
which the bench RESULT records on the fail-fast path.

``FBTPU_ATTACH_WAIT_S`` tunes how long plugin init waits synchronously
for the device before proceeding on CPU (default 2 s — tests force the
CPU platform where attach is near-instant; the bench sets its own longer
deadline).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import List, Optional

from ..core.lockorder import make_lock

log = logging.getLogger("flb.device")

_lock = make_lock("device._lock")
_state = "unattached"  # unattached | attaching | ready | failed
_error: Optional[str] = None
_thread: Optional[threading.Thread] = None
_attach_seconds: Optional[float] = None
_platform: Optional[str] = None
_attempts = 0
_retry_history: List[dict] = []
_next_retry_at: Optional[float] = None
_generation = 0  # successful attaches; consumers re-resolve on change

#: History is bounded to the most recent attempts: a permanently-absent
#: backend re-attached by the fault domain every breaker cooldown would
#: otherwise grow the list (and every health/status copy) forever.
_RETRY_HISTORY_MAX = 20


def default_wait() -> float:
    try:
        return float(os.environ.get("FBTPU_ATTACH_WAIT_S", "2"))
    except ValueError:
        return 2.0


def attach_retries() -> int:
    """Max attach attempts before ``failed()`` (exhausted)."""
    try:
        return max(1, int(os.environ.get("FBTPU_ATTACH_RETRIES", "3")))
    except ValueError:
        return 3


def attach_backoff() -> float:
    """Base backoff between attempts (doubles per attempt, ±25%
    jitter so a fleet of restarting workers never thunders in step)."""
    try:
        return max(0.0, float(
            os.environ.get("FBTPU_ATTACH_BACKOFF_S", "0.5")))
    except ValueError:
        return 0.5


def _attach_once(attempt: int) -> None:
    """One backend-init attempt; raises on failure."""
    global _attach_seconds, _platform
    t0 = time.time()
    from .. import failpoints as _fp

    if _fp.ACTIVE:
        # delay(ms) simulates the minutes-long axon attach stall;
        # return(err) fails THIS attempt (the retry loop decides
        # whether the CPU fallback pins)
        _fp.fire("device.attach")
    import jax
    import jax.numpy as jnp

    n = len(jax.devices())  # the (possibly minutes-long) backend init
    # one trivial dispatch so the runtime is fully warm before the
    # first real kernel
    jnp.zeros((8,), dtype=jnp.int32).block_until_ready()
    global _state, _generation
    with _lock:
        _attach_seconds = time.time() - t0
        _platform = jax.default_backend()
        _state = "ready"
        _generation += 1
        gen = _generation
    log.info("device backend attached: %d device(s) in %.1fs "
             "(attempt %d, generation %d)",
             n, _attach_seconds, attempt, gen)
    if gen > 1 or attempt > 1:
        # a late/re-attach: tell the fault domain so lanes can swap
        # the device path back in and the metric counts the event
        try:
            from . import fault as _fault

            _fault.notify("attach", "reattach", gen)
        except Exception:  # pragma: no cover - listener must not kill attach
            log.exception("reattach notification failed")


def _attach_worker() -> None:
    global _state, _error, _attempts, _next_retry_at
    retries = attach_retries()
    backoff = attach_backoff()
    for attempt in range(1, retries + 1):
        with _lock:
            _attempts = attempt
            _next_retry_at = None
        t0 = time.time()
        try:
            _attach_once(attempt)
            return
        except Exception as e:  # pragma: no cover - platform-dependent
            err = repr(e)
            with _lock:
                _error = err
                _retry_history.append({
                    "attempt": attempt,
                    "error": err,
                    "elapsed_s": round(time.time() - t0, 3),
                })
                del _retry_history[:-_RETRY_HISTORY_MAX]
            if attempt >= retries:
                break
            # jittered exponential backoff: base * 2^(attempt-1) ± 25%
            delay = backoff * (2.0 ** (attempt - 1))
            delay *= random.uniform(0.75, 1.25)
            with _lock:
                _next_retry_at = time.time() + delay
            log.warning("device attach attempt %d/%d failed (%r); "
                        "retrying in %.2fs", attempt, retries, e, delay)
            time.sleep(delay)
    with _lock:
        _state = "failed"
        _next_retry_at = None
    log.warning("device attach exhausted after %d attempt(s) "
                "(CPU path pinned until reattach_async): %s",
                retries, _error)


def attach_async() -> None:
    """Start backend init in the background (idempotent)."""
    global _state, _thread
    with _lock:
        if _state != "unattached":
            return
        _state = "attaching"
        _thread = threading.Thread(
            target=_attach_worker, daemon=True, name="flb-device-attach"
        )
        # start under the lock: wait() must never observe a created-but-
        # unstarted thread (is_alive False) and skip its join
        _thread.start()


def reattach_async() -> bool:
    """Re-arm attach after exhaustion (a new retry budget). True when a
    fresh attempt was started; False when attach is already running /
    ready. The fault domain calls this when a device-lane breaker
    half-opens against an exhausted attach — the probe that would
    otherwise test a dead backend instead re-tests the attach itself."""
    global _state, _thread
    with _lock:
        if _state != "failed":
            return False
        _state = "attaching"
        _thread = threading.Thread(
            target=_attach_worker, daemon=True,
            name="flb-device-reattach"
        )
        _thread.start()
    return True


def ready() -> bool:
    return _state == "ready"


def failed() -> bool:
    """True when attach EXHAUSTED its retry budget (terminal until
    :func:`reattach_async`) — a single failed attempt mid-retry-loop
    still reports attaching."""
    return _state == "failed"


def generation() -> int:
    """Successful-attach counter (0 until the first attach). Mesh-lane
    resolution is cached per generation: a bump means the device path
    must be re-probed (the PR-8 "resolution stays open until terminal"
    rule, extended to re-attach)."""
    return _generation


def wait(timeout: Optional[float] = None) -> bool:
    """Ensure attach is running and wait up to ``timeout`` seconds for
    it (None = the FBTPU_ATTACH_WAIT_S default). Returns ready()."""
    attach_async()
    t = _thread
    if t is not None and t.is_alive():
        t.join(default_wait() if timeout is None else timeout)
    return ready()


def platform() -> Optional[str]:
    """Attached backend name ('tpu', 'cpu', ...); None until ready."""
    return _platform


def device_count() -> int:
    """Attached backend's device count; 0 until ready. Under the
    simulated-mesh lane (``--xla_force_host_platform_device_count=8``)
    this reports the virtual devices — the mesh planes (ops.mesh,
    ops.grep mesh matcher, flux kernels) treat those exactly like
    chips. Safe after ready(): the first (possibly minutes-long)
    backend touch already happened in the attach worker."""
    if not ready():
        return 0
    import jax

    return len(jax.devices())


def shard_map_fn():
    """Version-tolerant ``shard_map`` import: top-level in newer jax,
    ``jax.experimental.shard_map`` on 0.4.x.  Every SPMD builder (grep,
    sketches, flux kernels) routes through here so the simulated-mesh
    lane runs on whichever jax the image ships — the bare
    ``from jax import shard_map`` was exactly why the sharded tests sat
    in the pre-existing-failure bucket on 0.4.37."""
    try:
        from jax import shard_map  # type: ignore[attr-defined]

        return shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

        return shard_map


def status() -> dict:
    """Attach state for diagnostics and the bench RESULT: retry-world
    fields (attempt count, per-attempt error history — the most recent
    ``_RETRY_HISTORY_MAX`` entries — next retry ETA, attach
    generation) ride along with the original block."""
    with _lock:
        eta = None
        if _next_retry_at is not None:
            eta = round(max(0.0, _next_retry_at - time.time()), 3)
        return {
            "state": _state,
            "error": _error,
            "platform": _platform,
            "attach_seconds": _attach_seconds,
            "attempts": _attempts,
            "retries_max": attach_retries(),
            "retry_history": list(_retry_history),
            "next_retry_eta_s": eta,
            "generation": _generation,
        }
