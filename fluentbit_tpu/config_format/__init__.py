"""Config formats — classic INI + YAML → a unified section AST.

Reference: src/config_format/flb_config_format.c (the unified flb_cf
AST), flb_cf_fluentbit.c (classic mode: ``[SECTION]`` + ``Key Value``
lines, ``@INCLUDE``/``@SET`` commands) and flb_cf_yaml.c (YAML with
``service:``/``pipeline:`` trees, per-instance ``processors:``,
includes). Environment interpolation (``${VAR}``, src/flb_env.c)
applies to both.

``load_config_file`` dispatches by extension (.yaml/.yml → YAML, else
classic), returning a ``ConfigFile`` of ordered sections that
``apply_to_context`` materializes onto an FLBContext.
"""

from __future__ import annotations

import glob as _glob
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}")


@dataclass
class Section:
    """One config section: name + ordered key/value properties."""

    name: str  # lowercased: service|input|filter|output|parser|custom...
    properties: List[Tuple[str, Any]] = field(default_factory=list)
    # per-instance processor pipelines (YAML only)
    processors: Dict[str, list] = field(default_factory=dict)

    def get(self, key: str, default=None):
        k = key.lower()
        for pk, v in self.properties:
            if pk.lower() == k:
                return v
        return default


@dataclass
class ConfigFile:
    sections: List[Section] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)  # @SET variables


def _interp(value: str, extra_env: Dict[str, str]) -> str:
    """${VAR} interpolation (flb_env semantics: environment wins over
    @SET definitions; unknown vars expand empty)."""

    def sub(m):
        name = m.group(1)
        return os.environ.get(name, extra_env.get(name, ""))

    return _ENV_RE.sub(sub, value)


# ---------------------------------------------------------------- classic

def parse_classic(text: str, base_dir: str = ".",
                  env: Optional[Dict[str, str]] = None) -> ConfigFile:
    """Classic fluent-bit INI mode (flb_cf_fluentbit.c)."""
    cf = ConfigFile(env=dict(env or {}))
    current: Optional[Section] = None
    for raw_line in text.splitlines():
        line = raw_line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("@"):
            parts = line.split(None, 1)
            cmd = parts[0].upper()
            arg = parts[1].strip() if len(parts) > 1 else ""
            if cmd == "@SET" and "=" in arg:
                k, v = arg.split("=", 1)
                cf.env[k.strip()] = v.strip()
            elif cmd == "@INCLUDE":
                pattern = arg if os.path.isabs(arg) else os.path.join(base_dir, arg)
                for path in sorted(_glob.glob(pattern)):
                    inc = load_config_file(path, env=cf.env)
                    cf.sections.extend(inc.sections)
                    cf.env.update(inc.env)
            continue
        if line.startswith("[") and line.endswith("]"):
            current = Section(line[1:-1].strip().lower())
            cf.sections.append(current)
            continue
        if current is None:
            raise ValueError(f"property outside any section: {line!r}")
        parts = line.split(None, 1)
        key = parts[0]
        value = _interp(parts[1].strip() if len(parts) > 1 else "", cf.env)
        current.properties.append((key, value))
    return cf


# ------------------------------------------------------------------- yaml

def parse_yaml(text: str, base_dir: str = ".",
               env: Optional[Dict[str, str]] = None) -> ConfigFile:
    """YAML mode (flb_cf_yaml.c): ``service:``, ``pipeline: {inputs,
    filters, outputs}``, ``parsers:``, ``includes:``, ``env:``,
    per-instance ``processors:``."""
    import yaml as _yaml

    cf = ConfigFile(env=dict(env or {}))
    doc = _yaml.safe_load(text) or {}
    if not isinstance(doc, dict):
        raise ValueError("YAML config root must be a mapping")

    for k, v in (doc.get("env") or {}).items():
        cf.env[str(k)] = str(v)

    def interp_val(v):
        return _interp(v, cf.env) if isinstance(v, str) else v

    def section_from(name: str, body: dict) -> Section:
        sec = Section(name)
        for k, v in body.items():
            if k == "processors" and isinstance(v, dict):
                sec.processors = v
                continue
            if isinstance(v, list):
                for item in v:
                    sec.properties.append((str(k), interp_val(item)))
            else:
                sec.properties.append((str(k), interp_val(v)))
        return sec

    for inc in doc.get("includes") or []:
        path = inc if os.path.isabs(inc) else os.path.join(base_dir, inc)
        for p in sorted(_glob.glob(path)):
            sub = load_config_file(p, env=cf.env)
            cf.sections.extend(sub.sections)
            cf.env.update(sub.env)

    if isinstance(doc.get("service"), dict):
        cf.sections.append(section_from("service", doc["service"]))

    for psec in doc.get("parsers") or []:
        cf.sections.append(section_from("parser", psec))

    for msec in doc.get("multiline_parsers") or []:
        sec = Section("multiline_parser")
        for k, v in msec.items():
            if k == "rules" and isinstance(v, list):
                # YAML rule form: {state: s, regex: r, next_state: n}
                for rule in v:
                    sec.properties.append((
                        "rule",
                        f'"{rule.get("state", "start_state")}" '
                        f'"{rule.get("regex", "")}" '
                        f'"{rule.get("next_state", "")}"',
                    ))
            else:
                sec.properties.append((str(k), interp_val(v)))
        cf.sections.append(sec)

    # top-level `plugins:` list of shared-object paths (the upstream
    # YAML schema for dynamic plugins, flb_cf_yaml.c plugins key)
    for p in doc.get("plugins") or []:
        sec = Section("plugins")
        sec.properties.append(("path", interp_val(p)))
        cf.sections.append(sec)

    pipeline = doc.get("pipeline") or {}
    for kind, sec_name in (("inputs", "input"), ("filters", "filter"),
                           ("outputs", "output")):
        for body in pipeline.get(kind) or []:
            if isinstance(body, dict):
                cf.sections.append(section_from(sec_name, body))
    for body in doc.get("customs") or []:
        cf.sections.append(section_from("custom", body))
    return cf


def load_config_file(path: str, env: Optional[Dict[str, str]] = None) -> ConfigFile:
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    base_dir = os.path.dirname(os.path.abspath(path))
    if path.endswith((".yaml", ".yml")):
        return parse_yaml(text, base_dir, env)
    return parse_classic(text, base_dir, env)


# -------------------------------------------------------------- apply

#: SERVICE keys that name parser definition files
_PARSER_FILE_KEYS = ("parsers_file", "parsers_files")


def _apply_dso_plugins(cf: "ConfigFile", base_dir: str) -> None:
    """[PLUGINS] sections: every `path` is dlopened + registered
    (flb_plugin_load_config_format, src/flb_plugin.c:356)."""
    for sec in cf.sections:
        if sec.name != "plugins":
            continue
        from ..core.dso import load_dso_plugin

        for key, value in sec.properties:
            if key.lower() != "path":
                raise ValueError(
                    f"[PLUGINS] supports only 'path' (got {key!r})")
            path = value if os.path.isabs(value) \
                else os.path.join(base_dir, value)
            load_dso_plugin(path)


def apply_to_context(ctx, cf: ConfigFile, base_dir: str = ".") -> None:
    """Materialize a parsed config onto an FLBContext (the flb_cf →
    flb_config translation the CLI performs)."""
    # service first (flush/grace/storage affect everything else)
    for sec in cf.sections:
        if sec.name != "service":
            continue
        for key, value in sec.properties:
            lk = key.lower()
            if lk in _PARSER_FILE_KEYS:
                path = value if os.path.isabs(value) \
                    else os.path.join(base_dir, value)
                pcf = load_config_file(path, env=cf.env)
                _apply_parsers(ctx, pcf)
            elif lk == "streams_file":
                path = value if os.path.isabs(value) \
                    else os.path.join(base_dir, value)
                _apply_streams(ctx, load_config_file(path, env=cf.env))
            elif lk == "plugins_file":
                # flb_plugin_load_config_file: a file whose [PLUGINS]
                # section lists shared objects to dlopen
                path = value if os.path.isabs(value) \
                    else os.path.join(base_dir, value)
                _apply_dso_plugins(load_config_file(path, env=cf.env),
                                   os.path.dirname(path))
            else:
                ctx.service_set(**{lk: value})
    _apply_dso_plugins(cf, base_dir)
    _apply_parsers(ctx, cf)
    _apply_streams(ctx, cf)
    for sec in cf.sections:
        if sec.name in ("service", "parser", "multiline_parser",
                        "stream_task", "plugins"):
            continue
        if sec.name not in ("input", "filter", "output", "custom"):
            raise ValueError(f"unknown config section [{sec.name}]")
        props = list(sec.properties)
        name = None
        rest = []
        for k, v in props:
            if k.lower() == "name":
                name = v
            else:
                rest.append((k, v))
        if name is None:
            raise ValueError(f"[{sec.name}] section without Name")
        if sec.name == "input":
            ffd = ctx.input(name)
        elif sec.name == "filter":
            ffd = ctx.filter(name)
        elif sec.name == "output":
            ffd = ctx.output(name)
        else:
            ffd = ctx.custom(name)
        for k, v in rest:
            ctx.set(ffd, **{k: v})
        if sec.processors:
            _apply_processors(ctx, ffd, sec.processors)


def _apply_processors(ctx, ffd, processors: Dict[str, list]) -> None:
    """YAML per-instance ``processors:`` → processor instances on the
    input/output (flb_cf_yaml.c is the only format exposing these)."""
    ins = ctx.engine.registry  # registry for creation
    target = ctx._handles[ffd]
    if not hasattr(target, "processors"):
        raise ValueError(
            f"processors are not supported on {target.kind} instances"
        )
    for signal_type, units in processors.items():
        if signal_type not in ("logs", "metrics", "traces"):
            raise ValueError(f"unknown processor signal {signal_type!r}")
        for unit in units or []:
            if not isinstance(unit, dict) or "name" not in unit:
                raise ValueError(f"processor unit needs a name: {unit!r}")
            proc = ins.create_processor(unit["name"])
            # which side of the pipeline this unit runs on — plugins
            # whose semantics are side-specific (tail sampling re-
            # injection) validate against it at init
            proc.side = target.kind
            for k, v in unit.items():
                if k in ("name", "condition"):
                    continue
                proc.set(k, v)
            if "condition" in unit:
                if signal_type != "logs":
                    # only the log pipeline evaluates per-record
                    # conditions; accepting one here would silently
                    # apply the processor unconditionally
                    raise ValueError(
                        "processor conditions are supported on logs "
                        "units only"
                    )
                from ..core.conditions import Condition

                proc.condition = Condition.from_config(unit["condition"])
            proc.configure()
            proc.plugin.init(proc, ctx.engine)
            target.processors.append(proc)


def _apply_parsers(ctx, cf: ConfigFile) -> None:
    for sec in cf.sections:
        if sec.name == "parser":
            props = {k: v for k, v in sec.properties}
            low = {k.lower(): v for k, v in props.items()}
            name = low.pop("name", None)
            if not name:
                raise ValueError("[PARSER] section without Name")
            props = {k: v for k, v in props.items() if k.lower() != "name"}
            ctx.parser(name, **props)
        elif sec.name == "multiline_parser":
            _apply_ml_parser(ctx, sec)


def _apply_streams(ctx, cf: ConfigFile) -> None:
    """[STREAM_TASK] sections (the reference's streams_file format:
    Name + Exec SQL)."""
    for sec in cf.sections:
        if sec.name != "stream_task":
            continue
        sql = sec.get("exec")
        if not sql:
            raise ValueError("[STREAM_TASK] section without Exec")
        ctx.sp_task(sql)


def _apply_ml_parser(ctx, sec: Section) -> None:
    """[MULTILINE_PARSER] → engine.ml_parser. Rule lines are
    '"state" "/regex/" "next_state"' (flb_ml_rule syntax)."""
    name = sec.get("name")
    if not name:
        raise ValueError("[MULTILINE_PARSER] section without Name")
    if (sec.get("type") or "regex").lower() != "regex":
        raise ValueError("multiline parser type must be 'regex'")
    rules = []
    for key, value in sec.properties:
        if key.lower() != "rule":
            continue
        parts = re.findall(r'"((?:[^"\\]|\\.)*)"', str(value))
        if len(parts) != 3:
            raise ValueError(f"invalid multiline rule {value!r}")
        state, pattern, nxt = parts
        if pattern.startswith("/") and pattern.endswith("/"):
            pattern = pattern[1:-1]
        rules.append((state, pattern, nxt))
    ctx.ml_parser(
        name, rules,
        flush_ms=int(sec.get("flush_timeout", 2000)),
        key_content=sec.get("key_content", "log"),
    )
