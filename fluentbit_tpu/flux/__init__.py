"""fbtpu-flux — the device-resident streaming analytics plane.

Unifies the streaming and analytical planes per FluxSieve (PAPERS.md,
2603.04937): per-tenant observability — unique users (HLL), hot keys
(count-min top-k), windowed error rates (count/sum/min/max/avg) — is
computed INSIDE the filter pass at ingest rate, on device-resident
state merged across chips with psum/pmax trees, instead of in a
downstream warehouse.

Layout:

- ``state``    — :class:`FluxState`: per-group sketches + window panes,
  snapshot/restore, the batched/per-record bit-identical absorb core;
- ``kernels``  — segment scatter-add count kernel + the mesh
  (``shard_map``/psum) lane, host twins bit-identical;
- ``plugin``   — ``filter_flux``: the stateful ``process_batch`` hook
  riding the native column stagers;
- ``query``    — sketch-eligibility + :class:`FluxBinding` for
  stream-processor SQL (``COUNT(DISTINCT ...)`` et al.);
- ``exporter`` — ``fluentbit_flux_*`` metrics families.

See FLUX.md for architecture, the exactness model, SQL eligibility
rules, and error bounds of the approximate path.
"""

from .state import FluxSpec, FluxState, WindowSpec  # noqa: F401
from .exporter import FluxExporter  # noqa: F401
from .query import FluxBinding, attach_flux, eligible  # noqa: F401

__all__ = ["FluxSpec", "FluxState", "WindowSpec", "FluxExporter",
           "FluxBinding", "attach_flux", "eligible"]
