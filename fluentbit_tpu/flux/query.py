"""Sketch-eligibility for stream-processor SQL — compile a query onto
flux state.

A query is **sketch-eligible** when its aggregation can be maintained
incrementally by the flux plane at ingest rate (FLUX.md has the full
rule table):

- ``CREATE STREAM ... AS SELECT`` over ``TAG:'pattern'`` (snapshots and
  stream-to-stream sources stay on the exact path),
- a ``WINDOW TUMBLING/HOPPING`` clause with aggregates,
- no ``WHERE`` (predicate pushdown to the DFA plane is future work),
- aggregate functions within {COUNT, COUNT(DISTINCT k), SUM, MIN, MAX,
  AVG} — ``TIMESERIES_FORECAST`` needs the raw series,
- not opted out per query via ``WITH (flux='off')``.

Eligible queries get a :class:`FluxBinding`: a hidden ``flux`` filter
instance on the query's tag route updates device-resident state inside
the filter pass (batched, no Python decode), and the SPTask becomes a
reader — its window tick renders rows straight from flux state in the
exact shape ``SPTask._rows_of`` would have produced.  Exact aggregates
(COUNT/SUM/MIN/MAX/AVG) are bit-identical to the Python evaluation
path; COUNT(DISTINCT) returns the HLL estimate within the documented
error bound.  Ineligible queries are untouched — the existing exact
path IS the fallback.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

from .state import FluxSpec, FluxState, WindowSpec

log = logging.getLogger("flb.flux")

__all__ = ["FluxBinding", "eligible", "attach_flux"]

#: aggregate functions the flux plane can maintain incrementally
_FLUX_FUNCS = {"count", "count_distinct", "sum", "min", "max", "avg"}


def eligible(query) -> bool:
    """Pure shape check (no side effects) — see module docstring."""
    if query.kind != "stream" or query.source_type != "tag":
        return False
    if query.where is not None or query.window is None:
        return False
    if not query.has_aggregates:
        return False
    if str(query.props.get("flux", "")).lower() in ("off", "false", "0"):
        return False
    for k in query.keys:
        if k.func is None:
            continue
        if k.func not in _FLUX_FUNCS:
            return False
        if k.func in ("sum", "min", "max", "avg", "count_distinct"):
            if k.name is None:
                return False
            if "." in k.name:
                # dotted names resolve through NESTED maps on the exact
                # path (_get_key splits on '.'); the flux stagers only
                # see literal top-level keys — silently-wrong results,
                # so nested accessors stay on the exact path
                # (ROADMAP item 3 follow-up)
                return False
    if any("." in g for g in query.group_by):
        return False
    return True


def _build_spec(query, mesh: bool) -> FluxSpec:
    distinct: List[str] = []
    numeric: List[str] = []
    for k in query.keys:
        if k.func == "count_distinct" and k.name not in distinct:
            distinct.append(k.name)
        elif k.func in ("sum", "min", "max", "avg") \
                and k.name not in numeric:
            numeric.append(k.name)
    kind, size, advance = query.window
    p = int(query.props.get("flux_precision", 12) or 12)
    return FluxSpec(
        name=query.stream_name or "sp",
        group_by=query.group_by,
        distinct=distinct,
        numeric=numeric,
        window=WindowSpec(kind, size, advance),
        hll_p=p,
        max_len=int(query.props.get("flux_max_len", 256) or 256),
        mesh=mesh,
    )


class FluxBinding:
    """One flux-backed SPTask's read side: renders window rows from
    flux state in the exact ``SPTask._rows_of`` shape."""

    def __init__(self, query, state: FluxState):
        self.query = query
        self.state = state

    def _rows(self, closed) -> List[dict]:
        q = self.query
        rows: List[dict] = []
        for key, g in closed:
            row: dict = {}
            for gname, part in zip(q.group_by, key):
                row[gname] = None if part is None \
                    else part.decode("utf-8", "replace")
            for k in q.keys:
                if k.func:
                    row[k.out_name] = self._agg_result(g, k)
                elif k.name is not None:
                    row.setdefault(k.out_name, None)
            rows.append(row)
        return rows

    @staticmethod
    def _agg_result(g, k):
        if k.func == "count":
            return g.count
        if k.func == "count_distinct":
            return int(round(g.hlls[k.name].estimate()))
        st = g.cols[k.name]
        if k.func == "sum":
            return st.sum if st.has else 0.0
        if k.func == "avg":
            return ((st.sum if st.has else 0.0) / g.count
                    if g.count else 0.0)
        if k.func == "min":
            return st.min_value()
        if k.func == "max":
            return st.max_value()
        return None

    def rows_on_tick(self, now: float) -> List[dict]:
        return self._rows(self.state.tick(now))

    def rows_on_drain(self) -> List[dict]:
        return self._rows(self.state.drain())


def sql_mesh_enabled() -> bool:
    """SQL-backed states shard across the mesh when the lane is opted
    in (FBTPU_FLUX_MESH=1; the per-shape jit compiles are not free on
    the 8-virtual-device CPU mesh, so it is explicit)."""
    return os.environ.get("FBTPU_FLUX_MESH", "") in ("1", "on", "true")


def attach_flux(engine, task) -> bool:
    """Bind a sketch-eligible SPTask to flux state: build the state,
    install the hidden flux filter on the query's tag route, and flip
    the task into reader mode.  False = not eligible (exact path)."""
    query = task.query
    if not eligible(query):
        return False
    state = FluxState(_build_spec(query, mesh=sql_mesh_enabled()))
    # align the window clock with the task's (differential harnesses
    # fake both through the same callable)
    state._now = task._now
    state._window_start = task._window_start
    ins = engine.registry.create_filter("flux")
    engine._number_instance(ins, engine.filters)
    ins.set("match", query.source)
    ins.set("alias", f"flux_sql_{query.stream_name or 'sp'}")
    ins.plugin._preset_state = state
    ins.plugin._sql_mode = True
    # keeps the hidden filter pinned to the chain TAIL (the SP's
    # post-filter position) even when user filters register later —
    # Engine.filter() inserts new filters before flagged instances
    ins._flux_sql_hidden = True
    ins.configure()
    ins.plugin.init(ins, engine)
    ins._initialized = True
    # COW swap: ingest iterates engine.filters lock-free — publish a
    # fresh list instead of mutating the shared alias
    with engine._ingest_lock:
        engine.filters = engine.filters + [ins]
    task.flux = FluxBinding(query, state)
    log.info(
        "stream task %s resolved against flux state (%s); NOTE: "
        "GROUP BY / COUNT(DISTINCT) fields must be string-typed at "
        "runtime (non-string values land in the null group — FLUX.md "
        "eligibility rules; pin the exact path with WITH (flux='off') "
        "if %s carries numeric labels)",
        query.stream_name or query.source,
        "mesh" if state.spec.mesh else "single",
        ", ".join(query.group_by) or "the query")
    return True
