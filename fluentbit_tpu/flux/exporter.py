"""fluentbit_flux_* metrics exporter — flux state → core.metrics.

Publishes the live flux plane into a :class:`MetricsRegistry` (the
engine's, normally — surfaces through /api/v1/metrics/prometheus and
the metrics pipeline like every other ``fluentbit_*`` family):

- ``fluentbit_flux_records_total{name}``        absorbed records
- ``fluentbit_flux_batches_total{name}``        absorbed chunks/appends
- ``fluentbit_flux_late_records_total{name}``   event-time late drops
- ``fluentbit_flux_window_emits_total{name}``   closed-window emissions
- ``fluentbit_flux_groups{name}``               open-pane group count
- ``fluentbit_flux_cardinality{name,group,field}``  HLL estimates
- ``fluentbit_flux_topk_estimate{name,group,value}`` CMS hot keys

Gauge families are refreshed wholesale (``clear()`` + set) so groups
that age out of the window do not linger in the exposition — the same
stale-series rule filter_log_to_metrics' frequency mode follows.
"""

from __future__ import annotations

from typing import Optional

from ..core.metrics import MetricsRegistry
from .state import FluxState

__all__ = ["FluxExporter"]


def _group_label(key: tuple) -> str:
    """Unambiguous label for a (possibly multi-field) group key:
    distinct keys must render distinct labels or two groups' series
    silently overwrite each other on refresh — so '/' inside a part is
    escaped and a missing (None) part renders differently from an
    empty string."""
    if not key:
        return ""
    return "/".join(
        "\\N" if part is None
        else part.decode("utf-8", "replace")
        .replace("\\", "\\\\").replace("/", "\\/")
        for part in key
    )


class FluxExporter:
    """One state's exporter; ``refresh()`` is cheap enough to run per
    window close and is additionally rate-limited for per-absorb calls
    (``min_interval`` seconds, 0 = always)."""

    def __init__(self, metrics: MetricsRegistry, state: FluxState,
                 min_interval: float = 0.0, now=None):
        import time as _time

        self.state = state
        self.name = state.spec.name
        self.min_interval = float(min_interval)
        self._now = now or _time.time
        self._last = 0.0
        m = metrics
        self.m_records = m.counter(
            "fluentbit", "flux", "records_total",
            "Records absorbed by the flux plane", ("name",))
        self.m_batches = m.counter(
            "fluentbit", "flux", "batches_total",
            "Chunks absorbed by the flux plane", ("name",))
        self.m_late = m.counter(
            "fluentbit", "flux", "late_records_total",
            "Event-time records behind the watermark", ("name",))
        self.m_emits = m.counter(
            "fluentbit", "flux", "window_emits_total",
            "Closed-window emissions", ("name",))
        self.m_groups = m.gauge(
            "fluentbit", "flux", "groups",
            "Open-pane group count", ("name",))
        self.m_cardinality = m.gauge(
            "fluentbit", "flux", "cardinality",
            "HLL distinct-value estimates", ("name", "group", "field"))
        self.m_topk = m.gauge(
            "fluentbit", "flux", "topk_estimate",
            "Count-min hot-key estimates", ("name", "group", "value"))
        # counters export deltas; these remember what was already added
        self._c_records = 0
        self._c_batches = 0
        self._c_late = 0
        self._c_emits = 0

    def refresh(self, force: bool = True) -> bool:
        """Publish the current state; ``force=False`` applies the
        rate limit (the per-absorb call site)."""
        now = self._now()
        if not force and self.min_interval > 0 \
                and now - self._last < self.min_interval:
            return False
        self._last = now
        st = self.state
        self._bump(self.m_records, "_c_records", st.records_total)
        self._bump(self.m_batches, "_c_batches", st.batches_total)
        self._bump(self.m_late, "_c_late", st.late_records_total)
        self._bump(self.m_emits, "_c_emits", st.window_emits_total)
        groups = st.live_groups()
        self.m_groups.set(float(len(groups)), (self.name,))
        # wholesale refresh of THIS state's series only: stale groups
        # must drop out of exposition, sibling exporters' series must
        # not (the families are shared engine-registry metrics)
        self.m_cardinality.remove_matching("name", self.name)
        self.m_topk.remove_matching("name", self.name)
        for key, g in groups:
            label = _group_label(key)
            for field, hll in g.hlls.items():
                self.m_cardinality.set(
                    hll.estimate(), (self.name, label, field))
        if st.cms is not None:
            # exposition covers LIVE groups only (same rule as the
            # cardinality family): refresh runs under the engine ingest
            # lock, and walking every state-lifetime candidate group
            # (up to _MAX_CANDIDATE_GROUPS × ~80 CMS point queries)
            # would stall ingestion — historical groups stay queryable
            # through FluxState.topk, they just leave the exposition
            # when they leave the window
            for key, _g in groups:
                label = _group_label(key)
                for est, value in st.topk(key):
                    self.m_topk.set(
                        float(est),
                        (self.name, label,
                         value.decode("utf-8", "replace")))
        return True

    def _bump(self, counter, attr: str, total: int) -> None:
        prev: int = getattr(self, attr)
        if total > prev:
            counter.inc(total - prev, (self.name,))
            setattr(self, attr, total)
