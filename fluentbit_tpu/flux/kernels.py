"""Device kernels for the flux plane — segment reductions + mesh merge.

The window-aggregate counterpart of ``ops/sketch.py``: per-batch group
counts run as a scatter-add kernel over the segment-id column, and the
multi-chip merge is ``lax.psum`` over the mesh axis (integer counter sum
IS the union, the same exactness argument as the count-min merge).
Counts are integers end to end, so the device/mesh result is
bit-identical to the host ``np.bincount`` twin — which is what lets the
simulated-mesh lane assert equality in tier-1 on every PR.

Float sums/mins/maxs deliberately do NOT run here: the exact Python
evaluation path accumulates IEEE doubles in record order, and the CPU
jax backend is float32 without ``jax_enable_x64`` — flux keeps those
host-side (``flux/state.py``) so sketch-eligible SQL stays bit-exact.
See FLUX.md "exactness model".
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import jax
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover - jax absent: host twins only
    HAVE_JAX = False

__all__ = ["flux_mesh", "segment_counts", "sharded_segment_counts",
           "host_segment_counts", "guarded_segment_counts",
           "build_sharded_counts"]

#: compiled-kernel caches, keyed by padded segment count (and mesh
#: structure for the sharded variant) — a fresh jit per call would
#: recompile every batch
_jit_cache: dict = {}
_shard_cache: dict = {}


def _pad_segments(n_seg: int) -> int:
    """Round the segment-table size to a power of two so jit sees a
    small set of stable shapes (same motivation as ops.batch.bucket_size).
    Host-only: n_seg is always a Python int computed BEFORE tracing (it
    becomes the jit-static output shape), never a tracer."""
    n = 8
    while n < n_seg:  # fbtpu-lint: allow(jax-retrace) host-side shape prep
        n *= 2
    return n


def flux_mesh(n_devices: Optional[int] = None):
    """A 1-D mesh over the available devices (axis ``flux``) — the
    shared constructor in ops.mesh, which also serves the grep DFA
    plane's partitioned matcher.  Under the simulated-mesh lane
    (``XLA_FLAGS=--xla_force_host_platform_device_count=8``, the tier-1
    default — tests/conftest.py) this is 8 virtual CPU devices; on real
    hardware it is the attached chips.  Returns None when jax is
    unavailable or only one device exists (the mesh path would be pure
    overhead)."""
    from ..ops.mesh import build_mesh

    return build_mesh(n_devices, axis="flux")


def host_segment_counts(seg: np.ndarray, valid: np.ndarray,
                        n_seg: int) -> np.ndarray:
    """Host twin: rows-per-segment over valid rows (int64 → int32-safe
    counts; a chunk has < 2^31 rows by construction)."""
    if n_seg <= 0:
        return np.zeros((0,), dtype=np.int32)
    return np.bincount(
        seg[valid.astype(bool)], minlength=n_seg
    ).astype(np.int32)[:n_seg]


def _counts_impl(seg, valid, n_pad):
    out = jnp.zeros((n_pad,), dtype=jnp.int32)
    return out.at[seg].add(valid.astype(jnp.int32))


def segment_counts(seg: np.ndarray, valid: np.ndarray,
                   n_seg: int) -> np.ndarray:
    """Device scatter-add group counts — bit-identical to
    :func:`host_segment_counts` (integers)."""
    if not HAVE_JAX:
        return host_segment_counts(seg, valid, n_seg)
    n_pad = _pad_segments(n_seg)
    fn = _jit_cache.get(n_pad)
    if fn is None:
        fn = _jit_cache[n_pad] = jax.jit(
            lambda s, v: _counts_impl(s, v, n_pad)
        )
    got = np.asarray(fn(jnp.asarray(seg.astype(np.int32)),
                        jnp.asarray(valid.astype(np.int32))))
    return got[:n_seg]


def _mesh_key(mesh) -> tuple:
    # structural key, not id(): equal meshes share a compiled step
    # (the shared helper in ops.mesh — also keys the grep/sketch caches)
    from ..ops.mesh import mesh_key

    return mesh_key(mesh)


def build_sharded_counts(mesh, n_pad: int):
    """Compile the mesh group-count program for an ``n_pad``-slot
    segment table: the ``seg``/``valid`` batch columns ride the
    declarative ``flux-counts`` partition rules (batch-axis sharded),
    each device scatter-adds its shard locally, and the merge is
    ``lax.psum`` over the mesh axis. Factored out of the dispatch
    wrapper so the fbtpu-speccheck static==dynamic crosscheck can
    ``lower()`` the exact shipped program on the simulated mesh."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..ops.device import shard_map_fn
    from ..ops.mesh import rule_spec

    shard_map = shard_map_fn()
    axis = mesh.axis_names[0]

    def step(s, v):
        local = _counts_impl(s, v, n_pad)
        return lax.psum(local, axis_name=axis)

    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(rule_spec("flux-counts", axis, "seg"),
                  rule_spec("flux-counts", axis, "valid")),
        out_specs=P(),
    ))


def sharded_segment_counts(mesh, seg: np.ndarray, valid: np.ndarray,
                           n_seg: int) -> np.ndarray:
    """Group counts over a mesh: the batch axis is sharded across
    devices, each device scatter-adds its shard locally, and the merge
    is ``lax.psum`` over the mesh axis — the psum-style tree reduction
    of the flux contract.  Bit-identical to the host twin (integer
    counters)."""
    if not HAVE_JAX or mesh is None:
        return host_segment_counts(seg, valid, n_seg)
    from ..ops.mesh import pad_to_devices

    n_dev = mesh.devices.size
    B = seg.shape[0]
    # pad_to_devices: the divisibility proof fbtpu-speccheck keys the
    # sharded batch axis on (pad rows are invalid → contribute zero)
    Bp = pad_to_devices(B, n_dev)
    seg32 = seg.astype(np.int32)
    valid32 = valid.astype(np.int32)
    if Bp != B:
        seg32 = np.concatenate(
            [seg32, np.zeros((Bp - B,), dtype=np.int32)])
        valid32 = np.concatenate(
            [valid32, np.zeros((Bp - B,), dtype=np.int32)])
    n_pad = _pad_segments(n_seg)
    key = (_mesh_key(mesh), n_pad)
    fn = _shard_cache.get(key)
    if fn is None:
        fn = _shard_cache[key] = build_sharded_counts(mesh, n_pad)
    got = np.asarray(fn(jnp.asarray(seg32), jnp.asarray(valid32)))
    return got[:n_seg]


def guarded_segment_counts(lane, seg: np.ndarray, valid: np.ndarray,
                           n_seg: int, axis: str = "flux") -> np.ndarray:
    """Group counts through the fbtpu-armor flux DeviceLane: the
    sharded scatter-add/psum launch runs on the lane's watched worker
    (deadline, breaker, ``flux.device_update`` failpoint), the mesh
    comes from the lane (shrinks on device loss, regrows on breaker
    re-close), and any failure resolves to the bit-identical host twin
    — integer counters, so the result is exact either way."""
    from .. import failpoints as _fp

    def launch():
        if _fp.ACTIVE:
            _fp.fire("flux.device_update")
        mesh = lane.current_mesh(axis=axis)
        if mesh is None:  # shrunk below 2 devices: host twin serves
            return host_segment_counts(seg, valid, n_seg)
        return sharded_segment_counts(mesh, seg, valid, n_seg)

    def fallback():
        return host_segment_counts(seg, valid, n_seg)

    return lane.run(launch, fallback)
