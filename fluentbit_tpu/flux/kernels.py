"""Device kernels for the flux plane — segment reductions + mesh merge.

The window-aggregate counterpart of ``ops/sketch.py``: per-batch group
counts run as a scatter-add kernel over the segment-id column, and the
multi-chip merge is ``lax.psum`` over the mesh axis (integer counter sum
IS the union, the same exactness argument as the count-min merge).
Counts are integers end to end, so the device/mesh result is
bit-identical to the host ``np.bincount`` twin — which is what lets the
simulated-mesh lane assert equality in tier-1 on every PR.

Float sums/mins/maxs deliberately do NOT run here: the exact Python
evaluation path accumulates IEEE doubles in record order, and the CPU
jax backend is float32 without ``jax_enable_x64`` — flux keeps those
host-side (``flux/state.py``) so sketch-eligible SQL stays bit-exact.
See FLUX.md "exactness model".
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import jax
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover - jax absent: host twins only
    HAVE_JAX = False

__all__ = ["flux_mesh", "segment_counts", "sharded_segment_counts",
           "host_segment_counts", "guarded_segment_counts",
           "build_sharded_counts", "build_fused_absorb",
           "sharded_fused_absorb", "fused_absorb"]

#: compiled-kernel caches, keyed by padded segment count (and mesh
#: structure for the sharded variant) — a fresh jit per call would
#: recompile every batch
_jit_cache: dict = {}
_shard_cache: dict = {}


def _pad_segments(n_seg: int) -> int:
    """Round the segment-table size to a power of two so jit sees a
    small set of stable shapes (same motivation as ops.batch.bucket_size).
    Host-only: n_seg is always a Python int computed BEFORE tracing (it
    becomes the jit-static output shape), never a tracer."""
    n = 8
    while n < n_seg:  # fbtpu-lint: allow(jax-retrace) host-side shape prep
        n *= 2
    return n


def flux_mesh(n_devices: Optional[int] = None):
    """A 1-D mesh over the available devices (axis ``flux``) — the
    shared constructor in ops.mesh, which also serves the grep DFA
    plane's partitioned matcher.  Under the simulated-mesh lane
    (``XLA_FLAGS=--xla_force_host_platform_device_count=8``, the tier-1
    default — tests/conftest.py) this is 8 virtual CPU devices; on real
    hardware it is the attached chips.  Returns None when jax is
    unavailable or only one device exists (the mesh path would be pure
    overhead)."""
    from ..ops.mesh import build_mesh

    return build_mesh(n_devices, axis="flux")


def host_segment_counts(seg: np.ndarray, valid: np.ndarray,
                        n_seg: int) -> np.ndarray:
    """Host twin: rows-per-segment over valid rows (int64 → int32-safe
    counts; a chunk has < 2^31 rows by construction)."""
    if n_seg <= 0:
        return np.zeros((0,), dtype=np.int32)
    return np.bincount(
        seg[valid.astype(bool)], minlength=n_seg
    ).astype(np.int32)[:n_seg]


def _counts_impl(seg, valid, n_pad):
    out = jnp.zeros((n_pad,), dtype=jnp.int32)
    return out.at[seg].add(valid.astype(jnp.int32))


def segment_counts(seg: np.ndarray, valid: np.ndarray,
                   n_seg: int) -> np.ndarray:
    """Device scatter-add group counts — bit-identical to
    :func:`host_segment_counts` (integers)."""
    if not HAVE_JAX:
        return host_segment_counts(seg, valid, n_seg)
    n_pad = _pad_segments(n_seg)
    fn = _jit_cache.get(n_pad)
    if fn is None:
        fn = _jit_cache[n_pad] = jax.jit(
            lambda s, v: _counts_impl(s, v, n_pad)
        )
    got = np.asarray(fn(jnp.asarray(seg.astype(np.int32)),
                        jnp.asarray(valid.astype(np.int32))))
    return got[:n_seg]


def _mesh_key(mesh) -> tuple:
    # structural key, not id(): equal meshes share a compiled step
    # (the shared helper in ops.mesh — also keys the grep/sketch caches)
    from ..ops.mesh import mesh_key

    return mesh_key(mesh)


def build_sharded_counts(mesh, n_pad: int):
    """Compile the mesh group-count program for an ``n_pad``-slot
    segment table: the ``seg``/``valid`` batch columns ride the
    declarative ``flux-counts`` partition rules (batch-axis sharded),
    each device scatter-adds its shard locally, and the merge is
    ``lax.psum`` over the mesh axis. Factored out of the dispatch
    wrapper so the fbtpu-speccheck static==dynamic crosscheck can
    ``lower()`` the exact shipped program on the simulated mesh."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..ops.device import shard_map_fn
    from ..ops.mesh import rule_spec

    shard_map = shard_map_fn()
    axis = mesh.axis_names[0]

    def step(s, v):
        local = _counts_impl(s, v, n_pad)
        return lax.psum(local, axis_name=axis)

    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(rule_spec("flux-counts", axis, "seg"),
                  rule_spec("flux-counts", axis, "valid")),
        out_specs=P(),
    ))


def sharded_segment_counts(mesh, seg: np.ndarray, valid: np.ndarray,
                           n_seg: int) -> np.ndarray:
    """Group counts over a mesh: the batch axis is sharded across
    devices, each device scatter-adds its shard locally, and the merge
    is ``lax.psum`` over the mesh axis — the psum-style tree reduction
    of the flux contract.  Bit-identical to the host twin (integer
    counters)."""
    if not HAVE_JAX or mesh is None:
        return host_segment_counts(seg, valid, n_seg)
    from ..ops.mesh import pad_to_devices

    n_dev = mesh.devices.size
    B = seg.shape[0]
    # pad_to_devices: the divisibility proof fbtpu-speccheck keys the
    # sharded batch axis on (pad rows are invalid → contribute zero)
    Bp = pad_to_devices(B, n_dev)
    seg32 = seg.astype(np.int32)
    valid32 = valid.astype(np.int32)
    if Bp != B:
        seg32 = np.concatenate(
            [seg32, np.zeros((Bp - B,), dtype=np.int32)])
        valid32 = np.concatenate(
            [valid32, np.zeros((Bp - B,), dtype=np.int32)])
    n_pad = _pad_segments(n_seg)
    key = (_mesh_key(mesh), n_pad)
    fn = _shard_cache.get(key)
    if fn is None:
        fn = _shard_cache[key] = build_sharded_counts(mesh, n_pad)
    got = np.asarray(fn(jnp.asarray(seg32), jnp.asarray(valid32)))
    return got[:n_seg]


# -- the fused absorb: counts + HLL stack + count-min, ONE launch ------
#
# The cashed fbtpu-fuseplan merge (ANALYSIS.md "Fusion pack"): the flux
# chain's three per-segment launches (guarded_segment_counts, the
# per-group HLL lane.run, the count-min lane.run) collapse into a
# single program. Legality is exactly what the planner proves: every
# constituent is a commutative integer scatter (add/max) from an
# explicit snapshot, no host effect or compact sits between them, and
# the producer/consumer avals are independent state leaves — so one
# program computing all three from the same staged batch is bit-exact
# vs both the unfused chain and the host twins.

#: compiled fused-absorb cache — keyed by mesh structure, segment-table
#: size, field count, HLL precision and CMS geometry (jit handles the
#: per-shape executables underneath the one wrapped callable)
_fused_cache: dict = {}


def build_fused_absorb(mesh, n_pad: int, n_fields: int, hll_p: int,
                       cms=None, donate: bool = False):
    """Compile the ONE-launch flux absorb program.

    Flat argument layout (``F = n_fields`` distinct columns)::

        seg [Bp] i32, valid [Bp] i32,
        (batch_f [Bp, L] u8, lengths_f [Bp] i32) × F,
        registers_f [n_pad, m] i32 × F,
        [table [d, w], comp [Bc, W] u8, comp_len [Bc] i32]   (cms only)

    Returns ``(counts [n_pad] i32, registers_f × F, [table])``.  On a
    mesh every batch-axis column shards per the declarative
    ``flux-fused`` partition rules; sketch state replicates and merges
    with pmax (HLL register stack) / psum (counts, count-min) — the
    same exact integer merges as the unfused programs.  ``mesh=None``
    compiles the plain single-device jit.  ``donate=True`` donates the
    register stacks (always freshly assembled inside the launch, so
    aliasing them is safe; the count-min table is NOT donated — the
    fallback path re-materializes host state from that snapshot).
    Factored out of the dispatch wrappers so the fbtpu-speccheck
    static==dynamic crosscheck can ``lower()`` the exact shipped
    program on the simulated mesh."""
    from jax import lax

    from ..ops.sketch import hll_index_rank

    axis = mesh.axis_names[0] if mesh is not None else None

    def step(seg, valid, *rest):
        counts = _counts_impl(seg, valid, n_pad)
        if axis is not None:
            counts = lax.psum(counts, axis_name=axis)
        outs = [counts]
        for f in range(n_fields):
            b, ln = rest[2 * f], rest[2 * f + 1]
            regs = rest[2 * n_fields + f]
            idx, rank = hll_index_rank(b, ln, hll_p)
            # 2-D scatter-max into the per-group register stack: row =
            # the row's segment id, column = the hash's register index.
            # Invalid rows carry rank 0 (a no-op under max), so pad
            # rows may scatter anywhere.
            local = regs.at[seg, idx].max(rank)
            outs.append(lax.pmax(local, axis_name=axis)
                        if axis is not None else local)
        if cms is not None:
            table, comp, comp_len = rest[3 * n_fields:]
            w = jnp.ones_like(comp_len)  # flux absorbs are weight-1
            # + 0*sum: ties the accumulator to the sharded batch so the
            # fori_loop carry's varying annotation stays consistent
            zero = jnp.zeros_like(table) + (
                0 * comp_len.sum()).astype(table.dtype)
            local = cms._update_impl(zero, comp, comp_len, w)
            outs.append(table + (lax.psum(local, axis_name=axis)
                                 if axis is not None else local))
        return tuple(outs)

    donate_idx: tuple = ()
    if donate:
        # the register stacks alias their outputs exactly (replicated
        # [n_pad, m] i32 in and out) — the one safely-donatable subset
        donate_idx = tuple(range(2 + 2 * n_fields, 2 + 3 * n_fields))
    if mesh is None:
        return jax.jit(step, donate_argnums=donate_idx)
    from jax.sharding import PartitionSpec as P

    from ..ops.device import shard_map_fn
    from ..ops.mesh import rule_spec

    shard_map = shard_map_fn()
    in_specs = [rule_spec("flux-fused", axis, "seg"),
                rule_spec("flux-fused", axis, "valid")]
    for _ in range(n_fields):
        in_specs.append(rule_spec("flux-fused", axis, "batch"))
        in_specs.append(rule_spec("flux-fused", axis, "lengths"))
    regs_spec = rule_spec("flux-fused", axis, "registers")
    in_specs.extend([regs_spec] * n_fields)
    out_specs = [P()] + [regs_spec] * n_fields
    if cms is not None:
        in_specs.extend([rule_spec("flux-fused", axis, "table"),
                         rule_spec("flux-fused", axis, "comp"),
                         rule_spec("flux-fused", axis, "comp_len")])
        out_specs.append(rule_spec("flux-fused", axis, "table"))
    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=tuple(in_specs), out_specs=tuple(out_specs),
    ), donate_argnums=donate_idx)


def _pad_rows_to(arr: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad the leading (batch) axis up to ``n`` rows with ``fill``."""
    if arr.shape[0] >= n:
        return arr
    pad_shape = (n - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill,
                                        dtype=arr.dtype)])


def _fused_call(mesh, seg, valid, fields, regs, comp, comp_len,
                table, hll_p: int, cms, n_seg: int):
    """Shared dispatch body of :func:`sharded_fused_absorb` /
    :func:`fused_absorb` — pads the batch axis to the mesh multiple
    (the divisibility proof fbtpu-speccheck keys the sharded in_specs
    on), stacks the per-group register snapshots to the padded segment
    table, and runs the cached compiled program."""
    from ..ops import device
    from ..ops.mesh import pad_to_devices

    if not device.wait(max(60.0, device.default_wait())):
        raise RuntimeError(
            f"device backend not attached: {device.status()}")
    n_dev = mesh.devices.size if mesh is not None else 1
    B = seg.shape[0]
    Bp = pad_to_devices(B, n_dev)
    args = [jnp.asarray(_pad_rows_to(seg.astype(np.int32), Bp, 0)),
            jnp.asarray(_pad_rows_to(valid.astype(np.int32), Bp, 0))]
    for b, ln in fields:
        args.append(jnp.asarray(_pad_rows_to(
            np.ascontiguousarray(b, dtype=np.uint8), Bp, 0)))
        args.append(jnp.asarray(_pad_rows_to(
            ln.astype(np.int32), Bp, -1)))
    n_pad = _pad_segments(n_seg)
    for group_regs in regs:
        # the per-group snapshot stack: ALWAYS freshly assembled here
        # (inside the watched launch), which is what makes donating it
        # safe — no caller holds a reference to the stacked buffer
        stack = jnp.stack([jnp.asarray(r) for r in group_regs])
        if n_pad > stack.shape[0]:
            stack = jnp.concatenate(
                [stack, jnp.zeros((n_pad - stack.shape[0],
                                   stack.shape[1]), stack.dtype)])
        args.append(stack)
    has_cms = cms is not None and comp is not None
    if has_cms:
        Bc = pad_to_devices(comp.shape[0], n_dev)
        args.append(jnp.asarray(table, dtype=cms._dtype))
        args.append(jnp.asarray(_pad_rows_to(
            np.ascontiguousarray(comp, dtype=np.uint8), Bc, 0)))
        args.append(jnp.asarray(_pad_rows_to(
            comp_len.astype(np.int32), Bc, -1)))
    plat = (list(mesh.devices.flat)[0].platform if mesh is not None
            else device.platform())
    donate = plat not in (None, "cpu")  # CPU never aliases: donating
    # there only buys the "donated buffers were not usable" warning
    key = (None if mesh is None else _mesh_key(mesh), n_pad,
           len(fields), hll_p,
           (cms.depth, cms.width) if has_cms else None, donate)
    fn = _fused_cache.get(key)
    if fn is None:
        fn = _fused_cache[key] = build_fused_absorb(
            mesh, n_pad, len(fields), hll_p,
            cms if has_cms else None, donate=donate)
    out = fn(*args)
    counts = out[0][:n_seg]
    regs_out = tuple(out[1:1 + len(fields)])
    table_out = out[1 + len(fields)] if has_cms else None
    return counts, regs_out, table_out


def sharded_fused_absorb(mesh, seg: np.ndarray, valid: np.ndarray,
                         fields, regs, comp=None, comp_len=None,
                         table=None, *, hll_p: int, cms=None,
                         n_seg: int):
    """Mesh dispatch of the fused absorb program, WITHOUT committing or
    mutating any sketch state: computes from the explicit per-group
    register snapshots in ``regs`` (sequence over distinct fields of
    sequences over groups) and the ``table`` snapshot, and returns
    ``(counts [:n_seg], register stacks × F, table-or-None)`` — the
    fbtpu-armor flux lane commits on the caller thread after the
    watched launch resolves (snapshot-in/commit-on-finish, see
    ops.sketch.sharded_hll_registers)."""
    return _fused_call(mesh, seg, valid, fields, regs, comp, comp_len,
                       table, hll_p, cms, n_seg)


def fused_absorb(seg: np.ndarray, valid: np.ndarray, fields, regs,
                 comp=None, comp_len=None, table=None, *, hll_p: int,
                 cms=None, n_seg: int):
    """Single-device twin of :func:`sharded_fused_absorb` (plain jit,
    no mesh) — the fused path when the lane's mesh has shrunk below
    two devices or the state was built without ``mesh``."""
    return _fused_call(None, seg, valid, fields, regs, comp, comp_len,
                       table, hll_p, cms, n_seg)


def guarded_segment_counts(lane, seg: np.ndarray, valid: np.ndarray,
                           n_seg: int, axis: str = "flux") -> np.ndarray:
    """Group counts through the fbtpu-armor flux DeviceLane: the
    sharded scatter-add/psum launch runs on the lane's watched worker
    (deadline, breaker, ``flux.device_update`` failpoint), the mesh
    comes from the lane (shrinks on device loss, regrows on breaker
    re-close), and any failure resolves to the bit-identical host twin
    — integer counters, so the result is exact either way."""
    from .. import failpoints as _fp

    def launch():
        if _fp.ACTIVE:
            _fp.fire("flux.device_update")
        mesh = lane.current_mesh(axis=axis)
        if mesh is None:  # shrunk below 2 devices: host twin serves
            return host_segment_counts(seg, valid, n_seg)
        return sharded_segment_counts(mesh, seg, valid, n_seg)

    def fallback():
        return host_segment_counts(seg, valid, n_seg)

    return lane.run(launch, fallback)
