"""filter_flux — the stateful batched analytics processor.

The flux plane's ingest hook: one configured instance maintains one
:class:`~fluentbit_tpu.flux.state.FluxState` (per-tenant sketches +
window aggregates) and rides the PR-2 ``process_batch`` fast path —
per tagged append, the needed columns are extracted straight from chunk
bytes by the native stagers (``stage_field`` / ``stage_field_f64`` /
``map_mask``) and absorbed in ONE batched commit; records pass through
untouched.  The per-record ``filter()`` twin runs the identical math on
decoded events, so a decline anywhere on the raw chain stays bit-exact.

Batch-exactness contract (machine-checked, ``analysis.batch``): every
decline (``return None``) is dominated by ZERO committed effects — all
staging happens first, the single ``absorb_batch`` commit last — and
the class declares ``stateful_batch = True`` so a downstream decline
takes the decoded-tail continuation instead of replaying the absorb.

Two creation modes:

- **configured** (``[FILTER] Name flux``): spec comes from properties
  (group_by/distinct_field/aggregate_field/topk_field/window...),
  window rows optionally re-enter the pipeline through a hidden
  emitter under ``tag``, snapshots persist to ``snapshot_path``;
- **SQL-backed** (``flux.query.attach_flux``): a sketch-eligible
  stream-processor query pre-builds the state and installs a hidden
  instance of this filter on the query's tag route; emission then
  belongs to the SPTask and records appended by the SP's own emitter
  are skipped (the ``flb_sp_do`` self-feed guard).
"""

from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np

from ..codec.events import encode_event, now_event_time
from ..core.config import ConfigMapEntry
from ..core.plugin import FilterPlugin, FilterResult, registry
from .exporter import FluxExporter
from .state import FluxSpec, FluxState, WindowSpec

log = logging.getLogger("flb.flux")


@registry.register
class FluxFilter(FilterPlugin):
    name = "flux"
    description = "device-resident streaming analytics (sketches + windows)"
    # the batched hook commits sketch/window state: a downstream decline
    # must take the decoded-tail continuation, never a chain restart
    stateful_batch = True
    config_map = [
        ConfigMapEntry("group_by", "str", multiple=True,
                       desc="tenant/group label fields (string-typed)"),
        ConfigMapEntry("distinct_field", "str", multiple=True,
                       desc="HLL cardinality columns"),
        ConfigMapEntry("aggregate_field", "str", multiple=True,
                       desc="numeric count/sum/min/max/avg columns"),
        ConfigMapEntry("topk_field", "str",
                       desc="count-min hot-key column"),
        ConfigMapEntry("topk", "int", default=10),
        ConfigMapEntry("window", "str",
                       desc="'tumbling N' | 'hopping N M' | 'none'"),
        ConfigMapEntry("window_time", "str", default="processing",
                       desc="processing|event (event: tumbling only, "
                            "per-record path)"),
        ConfigMapEntry("tag", "str",
                       desc="emit closed-window rows under this tag"),
        ConfigMapEntry("emitter_name", "str"),
        ConfigMapEntry("emitter_mem_buf_limit", "str", default="10M"),
        ConfigMapEntry("sketch_precision", "int", default=12),
        ConfigMapEntry("sketch_depth", "int", default=4),
        ConfigMapEntry("sketch_width", "int", default=16384),
        ConfigMapEntry("max_field_len", "int", default=256),
        ConfigMapEntry("mesh", "bool", default=False,
                       desc="shard sketch updates across the device "
                            "mesh (simulated-mesh lane in tier-1)"),
        ConfigMapEntry("snapshot_path", "str"),
        ConfigMapEntry("snapshot_interval_sec", "int", default=0),
        ConfigMapEntry("export_interval_sec", "str", default="1"),
        ConfigMapEntry("tick_interval_sec", "str", default="0.5"),
    ]

    #: SQL mode: state pre-built by flux.query.attach_flux before init
    _preset_state: Optional[FluxState] = None
    _sql_mode: bool = False

    def init(self, instance, engine) -> None:
        self._engine = engine
        self._emitter = None
        self._emitter_ins = None
        self._last_snapshot = 0.0
        if self._preset_state is not None:
            self.state = self._preset_state
        else:
            window = WindowSpec.parse(self.window)
            self.state = FluxState(FluxSpec(
                name=instance.display_name,
                group_by=self.group_by or (),
                distinct=self.distinct_field or (),
                numeric=self.aggregate_field or (),
                topk_field=self.topk_field,
                topk=self.topk,
                window=window,
                hll_p=self.sketch_precision,
                cms_depth=self.sketch_depth,
                cms_width=self.sketch_width,
                max_len=self.max_field_len,
                event_time=(self.window_time or "").lower() == "event",
                mesh=self.mesh,
            ))
            if self.snapshot_path:
                self.state.load(self.snapshot_path)
        metrics = engine.metrics if engine is not None else None
        if metrics is None:
            from ..core.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.exporter = FluxExporter(
            metrics, self.state,
            min_interval=float(self.export_interval_sec or 0)
            if self._preset_state is None else 1.0,
        )
        from .. import native as _native

        # probe the flux entry points ONCE: a stale prebuilt .so may
        # lack fbtpu_stage_field_f64, and discovering that per chunk
        # would stage every string column natively only to decline and
        # re-decode — permanent double work. Straight to the decoded
        # path instead.
        self._batch_ok = (
            _native.available() and not self.state.spec.event_time
            and (not self.state.spec.numeric
                 or _native.has_flux_stagers())
        )
        if self._preset_state is None and engine is not None \
                and (self.tag or self.state.spec.window.kind is not None
                     or self.state.spec.event_time
                     or (self.snapshot_path
                         and (self.snapshot_interval_sec or 0) > 0)):
            # the tick collector drives window close, gauge refresh AND
            # interval snapshots — an unwindowed state with
            # snapshot_interval_sec configured still needs the timer,
            # or the only persist would be exit() (and a crash is the
            # one scenario snapshots exist for)
            ename = self.emitter_name or \
                f"emitter_for_{instance.display_name}"
            ins = engine.hidden_input(
                "emitter", owner=instance, alias=ename,
                mem_buf_limit=self.emitter_mem_buf_limit,
            )
            self._emitter = ins.plugin
            self._emitter_ins = ins
            ins.plugin.collect_interval = float(
                self.tick_interval_sec or 0.5)
            ins.plugin.collect = self._on_tick

    # ------------------------------------------------------------- ticks

    def _on_tick(self, engine) -> None:
        """Window timer (rides the hidden emitter's collector, like the
        SP window tick): close expired windows, emit rows, refresh
        gauges, persist the snapshot.  The snapshot dict is built under
        the ingest lock (read-only copy) but pickled/fsynced OUTSIDE
        it — disk latency must not stall ingestion."""
        lock = getattr(engine, "_ingest_lock", None) \
            if engine is not None else None
        if lock is None:
            snap = self._tick_locked()
        else:
            with lock:
                snap = self._tick_locked()
        if snap is not None:
            import time as _time

            try:
                self.state.write_snapshot(snap, self.snapshot_path)
                self._last_snapshot = _time.time()
            except OSError:
                log.warning("flux snapshot persist failed; state stays "
                            "in memory", exc_info=True)

    def _tick_locked(self):
        """→ snapshot dict to write after the lock is released, or
        None."""
        closed = self.state.tick()
        if closed and self.tag and self._emitter is not None:
            self._emit_rows(closed, "window")
        self.exporter.refresh(force=bool(closed))
        if not self.snapshot_path:
            return None
        import time as _time

        due = (self.snapshot_interval_sec or 0) > 0 and \
            _time.time() - self._last_snapshot >= self.snapshot_interval_sec
        if not closed and not due:
            return None
        return self.state.snapshot()

    def _emit_rows(self, closed, what: str) -> None:
        rows = self._render_rows(closed)
        buf = bytearray()
        for r in rows:
            buf += encode_event(r, now_event_time())
        try:
            self._emitter.add_record(self.tag, bytes(buf), len(rows))
        except Exception:
            log.exception("flux %s emit failed; rows dropped "
                          "(state already rolled over)", what)

    def _render_rows(self, closed) -> List[dict]:
        spec = self.state.spec
        rows: List[dict] = []
        for key, g in closed:
            row: dict = {"flux": spec.name}
            for fname, part in zip(spec.group_by, key):
                row[fname] = None if part is None \
                    else part.decode("utf-8", "replace")
            row["count"] = g.count
            for f in spec.numeric:
                st = g.cols[f]
                row[f + "_sum"] = st.sum if st.has else 0.0
                row[f + "_min"] = st.min_value()
                row[f + "_max"] = st.max_value()
                row[f + "_avg"] = (st.sum / g.count) if g.count else 0.0
            for f in spec.distinct:
                row[f + "_distinct"] = int(round(g.hlls[f].estimate()))
            if spec.topk_field:
                row["topk"] = [
                    {"value": v.decode("utf-8", "replace"),
                     "estimate": est}
                    for est, v in self.state.topk(key)
                ]
            rows.append(row)
        return rows

    # ---------------------------------------------------- batched path

    def _skip_sources(self) -> list:
        out = []
        if self._sql_mode and self._engine is not None \
                and self._engine.sp is not None \
                and self._engine.sp.emitter_instance is not None:
            out.append(self._engine.sp.emitter_instance)
        if self._emitter_ins is not None:
            out.append(self._emitter_ins)
        return out

    def can_process_batch(self) -> bool:
        return self._batch_ok

    def process_batch(self, chunk):
        from .. import native

        data = chunk.as_bytes()
        skip = self._skip_sources()
        if chunk.src is not None and any(chunk.src is s for s in skip):
            n = chunk.n
            if n is None:
                n = native.count_records(data)
                if n is None:
                    return None
            return (n, data, n)
        spec = self.state.spec
        sfields = spec.string_fields
        strcols = {}
        n = chunk.n
        if not sfields and not spec.numeric:
            n = native.count_records(data) if n is None else n
            if n is None:
                return None
        if sfields and n is None:
            n = native.count_records(data)
            if n is None:
                return None
        for f in sfields:
            # stage straight into caller-owned column buffers: no
            # arena round-trip, so multi-column specs keep every
            # column live without the copy-out of all but the last
            b = np.empty((n, spec.max_len), dtype=np.uint8)
            ln = np.full((n,), -1, dtype=np.int32)
            n2 = native.stage_field_into(data, f.encode("utf-8"),
                                         b, ln, n_hint=n)
            if n2 is None or n2 != n:
                return None
            strcols[f] = (b, ln)
        numcols = {}
        for f in spec.numeric:
            got = native.stage_field_f64(data, f.encode("utf-8"),
                                         n_hint=n)
            if got is None:
                return None
            vals, kinds, n2 = got
            if n is not None and n2 != n:
                return None
            n = n2
            numcols[f] = (vals, kinds)
        # ---- the single commit: nothing below declines ----
        self.state.absorb_batch(n, strcols, numcols)
        try:
            # a raise past the commit would be an implicit decline and
            # the decoded-tail rerun would absorb the chunk AGAIN —
            # the same batch-commit-replay class the analyzer polices
            self.exporter.refresh(force=False)
        except Exception:
            log.exception("flux metrics refresh failed; export deferred")
        return (n, data, n)

    # ------------------------------------------------- per-record twin

    def filter(self, events: list, tag: str, engine) -> tuple:
        src = getattr(engine, "_ingest_src", None) \
            if engine is not None else None
        if src is not None and any(src is s for s in
                                   self._skip_sources()):
            return (FilterResult.NOTOUCH, events)
        self.state.absorb_events(events)
        try:
            self.exporter.refresh(force=False)
        except Exception:
            log.exception("flux metrics refresh failed; export deferred")
        return (FilterResult.NOTOUCH, events)

    def exit(self) -> None:
        # drain semantics belong to the owner: SQL mode drains through
        # SPTask.drain; configured mode emits what the open window holds
        if self._preset_state is None and self.tag \
                and self._emitter is not None:
            closed = self.state.drain()
            if closed:
                self._emit_rows(closed, "drain")
        if self.snapshot_path:
            try:
                self.state.persist(self.snapshot_path)
            except OSError:
                log.warning("flux exit snapshot failed", exc_info=True)
