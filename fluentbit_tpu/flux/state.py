"""Device-resident per-tenant flux state — sketches + window aggregates.

One :class:`FluxState` is the analytics state behind one flux consumer
(a configured ``filter_flux`` instance, or one sketch-eligible
stream-processor query).  Per group key (the tenant/tag labels) it
maintains:

- **HLL cardinality** per distinct-column (``ops.sketch.HyperLogLog``,
  registers device-resident once the backend attaches; cross-chip merge
  is ``lax.pmax`` via ``sharded_hll_update``),
- **count-min top-k** over a state-wide CMS keyed by composite
  ``group␟value`` bytes with a bounded per-group candidate set
  (``sharded_cms_update`` psum merge on a mesh),
- **window aggregates** — count/sum/min/max/avg per numeric column over
  tumbling or hopping windows.  Counts run through the segment
  scatter-add kernel (psum-merged on the mesh lane, integer-exact);
  float sums/mins/maxs accumulate host-side in IEEE doubles.

Exactness model (the differential-test contract, FLUX.md):

- the batched absorb (:meth:`absorb_batch`, fed by the native column
  stagers) and the per-record twin (:meth:`absorb_events`) are
  **bit-identical** — same grouping, same float addition ORDER (the
  running sum is threaded through ``np.bincount``'s sequential
  accumulation, continuing from the pane's stored sum exactly like the
  Python evaluation path's ``sums[n] += v``), same min/max
  representative selection (first row attaining the extremum);
- count/sum/min/max/avg therefore reproduce
  ``stream_processor._Agg`` bit-for-bit for map-bodied records;
- ``COUNT(DISTINCT k)`` is approximate with the standard HLL error
  (σ ≈ 1.04/√(2^p)); top-k estimates carry the count-min
  over-estimation bound (ε ≈ e/width with prob 1-δ, δ = e^-depth).

Windowing matches ``stream_processor.SPTask.tick`` in processing-time
mode (whole-period boundary advance, hopping pane ring of
``round(size/advance)`` panes, drain-on-shutdown).  Event-time tumbling
mode (per-record path only) assigns records to ``floor(ts/size)``
windows, closes on watermark advance, and counts late records instead
of corrupting closed panes.
"""

from __future__ import annotations

import math
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import failpoints as _fp
from ..ops.batch import assemble, bucket_size
from ..ops.sketch import CountMin, HyperLogLog
from . import kernels

__all__ = ["WindowSpec", "FluxSpec", "FluxState", "SNAPSHOT_VERSION"]

SNAPSHOT_VERSION = 1

#: composite separator for top-k keys: group fields join with \x1e,
#: group|value with \x1f (both outside normal label alphabets)
_FIELD_SEP = b"\x1e"
_VALUE_SEP = b"\x1f"

#: cap on distinct group keys tracked for top-k candidates (LRU-ish;
#: the CMS itself is fixed-size — only the nomination sets need a bound)
_MAX_CANDIDATE_GROUPS = 4096


class WindowSpec:
    """Window shape: ``None`` kind = unwindowed running state."""

    __slots__ = ("kind", "size", "advance")

    def __init__(self, kind: Optional[str] = None, size: float = 0.0,
                 advance: Optional[float] = None):
        if kind not in (None, "tumbling", "hopping"):
            raise ValueError(f"unknown window kind {kind!r}")
        if kind is not None and size <= 0:
            raise ValueError("window size must be positive")
        self.kind = kind
        self.size = float(size)
        self.advance = float(advance) if advance else self.size

    @classmethod
    def parse(cls, text: Optional[str]) -> "WindowSpec":
        """``"tumbling 60"`` | ``"hopping 60 10"`` | ``"none"``/empty."""
        if not text or str(text).strip().lower() in ("none", "off"):
            return cls(None)
        parts = str(text).split()
        kind = parts[0].lower()
        size = float(parts[1]) if len(parts) > 1 else 0.0
        advance = float(parts[2]) if len(parts) > 2 else None
        return cls(kind, size, advance)

    @property
    def n_panes(self) -> int:
        if self.kind != "hopping":
            return 1
        return max(1, int(round(self.size / self.advance)))


class FluxSpec:
    """Immutable shape of one flux state."""

    __slots__ = ("name", "group_by", "distinct", "numeric", "topk_field",
                 "topk", "window", "hll_p", "cms_depth", "cms_width",
                 "max_len", "event_time", "mesh")

    def __init__(self, name: str,
                 group_by: Sequence[str] = (),
                 distinct: Sequence[str] = (),
                 numeric: Sequence[str] = (),
                 topk_field: Optional[str] = None,
                 topk: int = 10,
                 window: Optional[WindowSpec] = None,
                 hll_p: int = 12,
                 cms_depth: int = 4,
                 cms_width: int = 16384,
                 max_len: int = 256,
                 event_time: bool = False,
                 mesh: bool = False):
        self.name = name
        self.group_by = tuple(group_by)
        self.distinct = tuple(distinct)
        self.numeric = tuple(numeric)
        self.topk_field = topk_field
        self.topk = int(topk)
        self.window = window or WindowSpec(None)
        self.hll_p = int(hll_p)
        self.cms_depth = int(cms_depth)
        self.cms_width = int(cms_width)
        self.max_len = int(max_len)
        self.event_time = bool(event_time)
        self.mesh = bool(mesh)
        if self.event_time and self.window.kind != "tumbling":
            # fail at CONFIG time: event-time assignment divides by the
            # window size, so a missing/hopping window must not surface
            # as a per-append crash later
            raise ValueError(
                "event-time windows require a tumbling window "
                "(hopping panes are processing-time; see FLUX.md)")

    def shape(self) -> dict:
        """Structural identity for snapshot compatibility checks.
        MUST include the sketch geometry: restoring p=12 registers into
        a p=14 state would hand the C HLL kernel a 4× undersized buffer
        (out-of-bounds write), and a changed CMS width silently hashes
        into the wrong columns. max_len is an exactness parameter too
        (it decides which values leave the sketch)."""
        return {
            "group_by": self.group_by,
            "distinct": self.distinct,
            "numeric": self.numeric,
            "topk_field": self.topk_field,
            "event_time": self.event_time,
            "window": (self.window.kind, self.window.size,
                       self.window.advance),
            "hll_p": self.hll_p,
            "cms_depth": self.cms_depth,
            "cms_width": self.cms_width,
            "max_len": self.max_len,
        }

    @property
    def string_fields(self) -> Tuple[str, ...]:
        """Columns staged as string bytes, in staging order."""
        out: List[str] = list(self.group_by)
        for f in self.distinct:
            if f not in out:
                out.append(f)
        if self.topk_field and self.topk_field not in out:
            out.append(self.topk_field)
        return tuple(out)


class _ColStat:
    """Per-(group, numeric column) running aggregate — the flux twin of
    one column's slice of ``stream_processor._Agg``."""

    __slots__ = ("has", "sum", "min", "max", "min_int", "max_int")

    def __init__(self):
        self.has = False
        self.sum = 0.0
        self.min = 0.0
        self.max = 0.0
        # representative int-ness: the exact path returns the ORIGINAL
        # min/max value (int stays int); kind 1 rows reconstruct as int
        self.min_int = False
        self.max_int = False

    def merge(self, other: "_ColStat") -> None:
        if not other.has:
            return
        if not self.has:
            self.has = True
            # 0.0 + s: same float sequence as _Agg.merge's
            # ``sums.get(n, 0.0) + v``
            self.sum = 0.0 + other.sum
            self.min, self.min_int = other.min, other.min_int
            self.max, self.max_int = other.max, other.max_int
            return
        self.sum = self.sum + other.sum
        if other.min < self.min:
            self.min, self.min_int = other.min, other.min_int
        if other.max > self.max:
            self.max, self.max_int = other.max, other.max_int

    def min_value(self):
        if not self.has:
            return None
        return int(self.min) if self.min_int else self.min

    def max_value(self):
        if not self.has:
            return None
        return int(self.max) if self.max_int else self.max


class _FluxGroup:
    """One group key's accumulators inside one window pane."""

    __slots__ = ("count", "cols", "hlls")

    def __init__(self, spec: FluxSpec):
        self.count = 0
        self.cols: Dict[str, _ColStat] = {f: _ColStat()
                                          for f in spec.numeric}
        self.hlls: Dict[str, HyperLogLog] = {
            f: HyperLogLog(p=spec.hll_p) for f in spec.distinct
        }

    def merge(self, other: "_FluxGroup") -> None:
        self.count += other.count
        for f, st in other.cols.items():
            self.cols[f].merge(st)
        for f, h in other.hlls.items():
            self.hlls[f].merge_registers(
                h.registers if isinstance(h.registers, np.ndarray)
                else np.asarray(h.registers))


def _seq_sum(start: float, values: np.ndarray) -> float:
    """``((start + v0) + v1) + ...`` with C-double sequential adds —
    np.bincount accumulates its weights in input order, which is
    exactly the Python evaluation path's running ``+=``."""
    w = np.concatenate([np.asarray([start], dtype=np.float64),
                        values.astype(np.float64, copy=False)])
    return float(np.bincount(np.zeros(w.size, dtype=np.intp),
                             weights=w, minlength=1)[0])


class FluxState:
    """Mutable analytics state (see module docstring).  All mutation
    happens under the engine's ingest lock — the flux filter is not
    ``thread_safe_raw`` and the SP window tick runs under the same
    lock, so no locking lives here."""

    def __init__(self, spec: FluxSpec, now=None):
        self.spec = spec
        self._now = now or time.time
        self._mesh = kernels.flux_mesh() if spec.mesh else None
        self._lane = None  # fbtpu-armor flux DeviceLane (lazy)
        # processing-time pane machinery (SPTask twin)
        self._groups: Dict[tuple, _FluxGroup] = {}
        self._panes: List[Dict[tuple, _FluxGroup]] = []
        self._window_start = self._now()
        # event-time machinery (tumbling only, per-record path)
        self._event_windows: Dict[int, Dict[tuple, _FluxGroup]] = {}
        self._watermark: Optional[float] = None
        self._pending_closed: List[Tuple[float,
                                         List[Tuple[tuple, _FluxGroup]]]] = []
        # state-lifetime top-k: one CMS + bounded per-group candidates
        self.cms: Optional[CountMin] = None
        self._candidates: Dict[tuple, Dict[bytes, None]] = {}
        if spec.topk_field:
            self.cms = CountMin(depth=spec.cms_depth,
                                width=spec.cms_width)
        # counters (exported as fluentbit_flux_*)
        self.records_total = 0
        self.late_records_total = 0
        self.window_emits_total = 0
        self.batches_total = 0

    # ------------------------------------------------------------ absorb

    def absorb_batch(self, n: int,
                     strcols: Dict[str, Tuple[np.ndarray, np.ndarray]],
                     numcols: Dict[str, Tuple[np.ndarray, np.ndarray]],
                     ) -> int:
        """Absorb one staged chunk (processing-time mode).

        strcols  : field → (batch u8 [n, L], lengths i32 [n]); lengths
                   < 0 = missing/non-string/oversize
        numcols  : field → (values f64 [n], kinds u8 [n]); kind 0 =
                   missing/non-numeric, 1 = integer, 2 = float

        EVERY record counts — the codec coerces non-map bodies to empty
        dicts at decode (codec.events._to_event), so the Python
        evaluation path counts them with all columns missing, and the
        batched path must do exactly the same (the native stagers
        return missing for non-map rows already).
        """
        if self.spec.event_time:
            raise RuntimeError("event-time state has no batched path")
        self.batches_total += 1
        if n <= 0:
            return 0
        self._absorb_rows(self._groups, n, strcols, numcols)
        self.records_total += n
        return n

    def absorb_events(self, events: list) -> int:
        """Per-record twin of :meth:`absorb_batch` — converts decoded
        events to the same column layout and runs the same math, so the
        two paths are bit-identical."""
        n = len(events)
        if n == 0:
            return 0
        # the decode-side coercion: non-dict bodies become empty maps
        # (all columns missing, row still counts) — parity with both
        # the codec's _to_event and the native stagers' non-map rows
        bodies = [ev.body if isinstance(ev.body, dict) else {}
                  for ev in events]
        strcols = {
            f: self._str_column(bodies, f)
            for f in self.spec.string_fields
        }
        numcols = {
            f: self._num_column(bodies, f) for f in self.spec.numeric
        }
        self.batches_total += 1
        if self.spec.event_time:
            ts = np.asarray([ev.ts_float for ev in events],
                            dtype=np.float64)
            absorbed = self._absorb_event_time(ts, strcols, numcols)
        else:
            self._absorb_rows(self._groups, n, strcols, numcols)
            absorbed = n
        self.records_total += absorbed
        return absorbed

    def _str_column(self, bodies: List[dict], field: str):
        vals: List[Optional[bytes]] = []
        for b in bodies:
            v = b.get(field)
            if isinstance(v, str):
                vb = v.encode("utf-8")
                # oversize → missing, exactly like the stager's -2 rows
                vals.append(vb if len(vb) <= self.spec.max_len else None)
            else:
                vals.append(None)
        batch = assemble(vals, self.spec.max_len)
        ln = batch.lengths.copy()
        ln[ln == -2] = -1  # collapse oversize into plain missing
        return batch.batch, ln

    def _num_column(self, bodies: List[dict], field: str):
        vals = np.zeros((len(bodies),), dtype=np.float64)
        kinds = np.zeros((len(bodies),), dtype=np.uint8)
        for i, b in enumerate(bodies):
            v = b.get(field)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            vals[i] = float(v)
            kinds[i] = 1 if isinstance(v, int) else 2
        return vals, kinds

    # -- grouping ------------------------------------------------------

    def _group_rows(self, n_rows: int, strcols
                    ) -> Tuple[np.ndarray, List[tuple]]:
        """Segment ids (first-seen order) + group key tuples."""
        gb = self.spec.group_by
        if not gb:
            return np.zeros((n_rows,), dtype=np.int64), [()]
        mats = []
        for f in gb:
            b, ln = strcols[f]
            L = b.shape[1]
            ln2 = np.where(ln < 0, np.int32(-1), ln)
            bz = np.ascontiguousarray(b, dtype=np.uint8).copy()
            # zero pad bytes so the void view compares by value; the
            # length column disambiguates embedded-NUL prefixes
            mask = np.arange(L)[None, :] >= np.clip(ln2, 0, None)[:, None]
            bz[mask] = 0
            mats.append(bz)
            mats.append(ln2.astype("<i4").view(np.uint8).reshape(-1, 4))
        keyed = np.ascontiguousarray(np.concatenate(mats, axis=1))
        void = keyed.view(f"V{keyed.shape[1]}").reshape(-1)
        _, first_idx, inv = np.unique(void, return_index=True,
                                      return_inverse=True)
        order = np.argsort(first_idx, kind="stable")
        remap = np.empty(order.size, dtype=np.int64)
        remap[order] = np.arange(order.size)
        seg = remap[np.asarray(inv).reshape(-1)]
        keys: List[tuple] = []
        for j in order:
            row = int(first_idx[j])
            key = []
            for f in gb:
                b, ln = strcols[f]
                lni = int(ln[row])
                key.append(b[row, :lni].tobytes() if lni >= 0 else None)
            keys.append(tuple(key))
        return seg, keys

    # -- the shared core ----------------------------------------------

    #: fused-absorb group ceiling: the [Gp, m] register stack scales
    #: with the padded group count, so a pathological high-cardinality
    #: GROUP BY batch (thousands of groups in ONE chunk) absorbs
    #: through the bit-identical host twins instead of staging a
    #: multi-hundred-MB snapshot stack
    _FUSED_MAX_GROUPS = 512

    def _absorb_rows(self, pane: Dict[tuple, _FluxGroup], n_rows: int,
                     strcols, numcols) -> None:
        seg, keys = self._group_rows(n_rows, strcols)
        n_groups = len(keys)
        single = n_groups == 1
        order = bounds = None
        if not single:
            # one stable sort instead of a per-group full-batch scan
            # (O(N log N), not O(groups × rows) — GROUP BY a
            # high-cardinality key must not go quadratic inside the
            # ingest lock); stability keeps each group's row indices
            # ascending, which the sequential-sum exactness needs
            order = np.argsort(seg, kind="stable")
            bounds = np.searchsorted(seg[order],
                                     np.arange(n_groups + 1))

        def gslice(gid, b, ln):
            if single:
                return b, ln
            gidx = order[bounds[gid]:bounds[gid + 1]]
            return b[gidx], ln[gidx]

        groups: List[_FluxGroup] = []
        for key in keys:
            g = pane.get(key)
            if g is None:
                g = pane[key] = _FluxGroup(self.spec)
            groups.append(g)
        # top-k composites: host-built per group (prefix + value) in
        # group order, absorbed below as ONE concatenated batch; the
        # candidate nomination reads the host rows and is independent
        # of how (or whether) the sketch update launches
        comp = comp_len = None
        if self.spec.topk_field:
            tb, tl = strcols[self.spec.topk_field]
            parts = []
            for gid, key in enumerate(keys):
                gb, gl = gslice(gid, tb, tl)
                built = self._topk_composites(key, gb, gl)
                if built is None:
                    continue
                c, cl, plen = built
                self._topk_nominate(key, c, cl, plen)
                parts.append((c, cl))
            if parts:
                W = self.spec.max_len
                comp = np.concatenate([c for c, _ in parts])
                comp_len = np.concatenate([cl for _, cl in parts])
                Bc = bucket_size(comp.shape[0], max_len=W)
                if Bc > comp.shape[0]:
                    comp = np.concatenate(
                        [comp, np.zeros((Bc - comp.shape[0], W),
                                        dtype=np.uint8)])
                    comp_len = np.concatenate(
                        [comp_len, np.full((Bc - comp_len.shape[0],),
                                           -1, dtype=np.int32)])
        fuse = (self._mesh is not None or self._use_device()) \
            and n_groups <= self._FUSED_MAX_GROUPS
        if fuse:
            # ONE device launch for the whole absorb — counts + every
            # group's HLL registers + the count-min table in a single
            # fused program (the cashed fbtpu-fuseplan merge)
            counts = self._fused_absorb(groups, seg, strcols, comp,
                                        comp_len, gslice)
        else:
            if single:
                counts = np.asarray([n_rows], dtype=np.int32)
            else:
                ones = np.ones((seg.shape[0],), dtype=np.int32)
                counts = kernels.host_segment_counts(seg, ones,
                                                     n_groups)
            for f in self.spec.distinct:
                b, ln = strcols[f]
                for gid, g in enumerate(groups):
                    gb, gl = gslice(gid, b, ln)
                    g.hlls[f].host_update(gb, gl)
            if comp is not None:
                self.cms.host_update(comp, comp_len)
        for gid, g in enumerate(groups):
            g.count += int(counts[gid])
            for f in self.spec.numeric:
                vals, kinds = numcols[f]
                if not single:
                    gidx = order[bounds[gid]:bounds[gid + 1]]
                    vals, kinds = vals[gidx], kinds[gidx]
                self._update_col(g.cols[f], vals, kinds)

    def _fused_absorb(self, groups: List[_FluxGroup], seg: np.ndarray,
                      strcols, comp, comp_len, gslice) -> np.ndarray:
        """Dispatch the fused absorb program through the flux lane —
        the snapshot-in/commit-on-finish protocol of
        :meth:`_hll_absorb`, for the whole fused region at once: the
        launch computes counts, the per-group register stacks and the
        count-min table from explicit pre-launch snapshots, and the
        caller commits after ``lane.run`` resolves.  Any failure
        resolves to the bit-identical host twins re-materialized from
        the same snapshots."""
        spec = self.spec
        lane = self._flux_lane()
        mesh_on = self._mesh is not None
        n_groups = len(groups)
        fields = list(spec.distinct)
        regs0 = [[g.hlls[f].registers for g in groups]
                 for f in fields]
        table0 = self.cms.table if comp is not None else None
        n_dev = self._mesh.devices.size if mesh_on else 1
        B = seg.shape[0]
        # bucket the batch axis so jit sees a small set of stable
        # shapes (pad rows: segment 0 with valid 0, lengths -1 — every
        # kernel treats them as no-ops)
        Bp = bucket_size(B, max_len=spec.max_len or 1,
                         multiple_of=n_dev)
        seg32 = seg.astype(np.int32)
        valid = np.ones((B,), dtype=np.int32)
        if Bp > B:
            seg32 = np.concatenate(
                [seg32, np.zeros((Bp - B,), dtype=np.int32)])
            valid = np.concatenate(
                [valid, np.zeros((Bp - B,), dtype=np.int32)])
        fcols = []
        for f in fields:
            b, ln = strcols[f]
            if Bp > b.shape[0]:
                b = np.concatenate(
                    [b, np.zeros((Bp - b.shape[0], b.shape[1]),
                                 dtype=b.dtype)])
                ln = np.concatenate(
                    [ln, np.full((Bp - ln.shape[0],), -1,
                                 dtype=ln.dtype)])
            fcols.append((b, ln))

        def _wait(x):
            return getattr(x, "block_until_ready", lambda: x)()

        def launch():
            if _fp.ACTIVE:
                _fp.fire("flux.device_update")
            m = lane.current_mesh(axis="flux") if mesh_on else None
            if m is not None:
                got = kernels.sharded_fused_absorb(
                    m, seg32, valid, fcols, regs0, comp, comp_len,
                    table0, hll_p=spec.hll_p, cms=self.cms,
                    n_seg=n_groups)
            else:  # mesh shrunk below 2 devices (or none): plain jit
                got = kernels.fused_absorb(
                    seg32, valid, fcols, regs0, comp, comp_len,
                    table0, hll_p=spec.hll_p, cms=self.cms,
                    n_seg=n_groups)
            counts, regs_out, table_out = got
            return (_wait(counts),
                    tuple(_wait(r) for r in regs_out),
                    _wait(table_out) if table_out is not None
                    else None)

        def fallback():
            # device path failed: re-materialize EVERY sketch from its
            # pre-launch snapshot, host-pinned (numpy), and absorb
            # there — bit-identical math (the old-or-new contract of
            # _hll_absorb/_cms_absorb, for the whole fused region)
            ones = np.ones((seg.shape[0],), dtype=np.int32)
            counts = kernels.host_segment_counts(seg, ones, n_groups)
            for fi, f in enumerate(fields):
                b, ln = strcols[f]
                for gid, g in enumerate(groups):
                    hll = g.hlls[f]
                    hll.registers = np.asarray(regs0[fi][gid])
                    gb, gl = gslice(gid, b, ln)
                    hll.host_update(gb, gl)
            if comp is not None:
                self.cms.table = np.asarray(table0)
                self.cms.host_update(comp, comp_len)
            return counts, None, None

        counts, regs_out, table_out = lane.run(launch, fallback)
        if regs_out is not None:
            for fi, f in enumerate(fields):
                for gid, g in enumerate(groups):
                    g.hlls[f].registers = regs_out[fi][gid]
        if table_out is not None:
            self.cms.table = table_out
        return np.asarray(counts)

    @staticmethod
    def _update_col(st: _ColStat, vals: np.ndarray,
                    kinds: np.ndarray) -> None:
        valid = kinds > 0
        if not valid.any():
            return
        vv = vals[valid]
        kk = kinds[valid]
        if np.isnan(vv).any():
            # NaN ordering is path-dependent under vectorized min/max;
            # run the exact per-value semantics instead (rare)
            for v, k in zip(vv.tolist(), kk.tolist()):
                is_int = k == 1
                if not st.has:
                    st.has = True
                    st.sum = 0.0 + v
                    st.min, st.min_int = v, is_int
                    st.max, st.max_int = v, is_int
                    continue
                st.sum = st.sum + v
                if v < st.min:
                    st.min, st.min_int = v, is_int
                if v > st.max:
                    st.max, st.max_int = v, is_int
            return
        start = st.sum if st.has else 0.0
        new_sum = _seq_sum(start, vv)
        gmin = float(vv.min())
        gmax = float(vv.max())
        min_int = bool(kk[int(np.argmax(vv == gmin))] == 1)
        max_int = bool(kk[int(np.argmax(vv == gmax))] == 1)
        if not st.has:
            st.has = True
            st.min, st.min_int = gmin, min_int
            st.max, st.max_int = gmax, max_int
        else:
            if gmin < st.min:
                st.min, st.min_int = gmin, min_int
            if gmax > st.max:
                st.max, st.max_int = gmax, max_int
        st.sum = new_sum

    def _use_device(self) -> bool:
        from ..ops import device

        return device.ready() and device.platform() not in (None, "cpu")

    def _flux_lane(self):
        """The flux plane's device fault domain (fbtpu-armor): sketch
        and count launches run on its watched worker with a deadline
        and breaker; failures resolve to the bit-identical host twins,
        and device sketch state re-materializes host-side (FAULTS.md
        "fbtpu-armor")."""
        lane = self._lane
        if lane is None:
            from ..ops import fault

            lane = self._lane = fault.lane("flux")
        return lane

    def _topk_composites(self, key: tuple, batch: np.ndarray,
                         lengths: np.ndarray):
        """Build one group's top-k composite rows (``prefix + value``)
        host-side — ``(comp, comp_len, plen)`` over the group's VALID
        rows, or None when the group contributes nothing.  The sketch
        update itself happens once for the whole batch (fused launch or
        host twin) on the concatenation of every group's rows."""
        prefix = self._group_prefix(key)
        W = self.spec.max_len
        valid = np.nonzero(lengths >= 0)[0]
        if valid.size == 0:
            return None
        plen = len(prefix)
        if plen > W:
            # the group prefix alone exceeds the composite width: no
            # value can fit, and the broadcast below would raise AFTER
            # earlier groups committed (a partial absorb = the
            # batch-exactness violation). Skip identically on both
            # paths — this group simply has no top-k.
            return None
        comp = np.zeros((valid.size, W), dtype=np.uint8)
        comp_len = np.full((valid.size,), -1, dtype=np.int32)
        if plen:
            comp[:, :plen] = np.frombuffer(prefix, dtype=np.uint8)
        vl = lengths[valid]
        fits = plen + vl <= W
        span = min(W - plen, batch.shape[1])
        comp[:, plen:plen + span] = batch[valid, :span]
        # oversize composites are excluded on BOTH paths (comp_len -1)
        comp_len[fits] = (plen + vl[fits]).astype(np.int32)
        # zero pad bytes past each composite's length (the batch slice
        # above copied arena garbage); candidate extraction below walks
        # by length so only the staged device batch needs the zeroing
        pad = np.arange(W)[None, :] >= np.clip(comp_len, 0, None)[:, None]
        comp[pad] = 0
        return comp, comp_len, plen

    def _topk_nominate(self, key: tuple, comp: np.ndarray,
                       comp_len: np.ndarray, plen: int) -> None:
        """Candidate set: a BOUNDED sample of this chunk's values (the
        CMS holds the counts; candidates only nominate keys for the
        top-k read). Stride-sampling rows instead of uniquing the
        whole chunk caps per-chunk work at O(limit) — hot keys appear
        in most chunks, so they enter the set with high probability,
        and the estimates themselves always come from the sketch."""
        cand = self._candidates.pop(key, None)
        if cand is None:
            cand = {}
        # re-insert at the END: the candidate-group map is bounded
        # LRU-ish (hot groups stay, historical group keys age out) —
        # per-group panes clear on window rollover but top-k is
        # state-lifetime, so without this a high-cardinality GROUP BY
        # grows candidate memory and exporter-refresh cost forever
        self._candidates[key] = cand
        if len(self._candidates) > _MAX_CANDIDATE_GROUPS:
            for stale in list(self._candidates)[
                    : len(self._candidates) - _MAX_CANDIDATE_GROUPS]:
                del self._candidates[stale]
        ok = np.nonzero(comp_len >= 0)[0]
        limit = max(64, 8 * self.spec.topk)
        if ok.size > limit:
            ok = ok[:: max(1, int(ok.size) // limit)][:limit]
        lens = comp_len[ok].tolist()
        for i, clen in zip(ok.tolist(), lens):
            vb = comp[i, plen:clen].tobytes()
            cand.pop(vb, None)
            cand[vb] = None
        if len(cand) > limit:
            for k in list(cand)[: len(cand) - limit]:
                del cand[k]

    def _group_prefix(self, key: tuple) -> bytes:
        if not key:
            return b""
        return _FIELD_SEP.join(
            b"\x00" if part is None else part for part in key
        ) + _VALUE_SEP

    # -- event-time (per-record path only) ----------------------------

    def _absorb_event_time(self, ts: np.ndarray, strcols,
                           numcols) -> int:
        size = self.spec.window.size
        wid = np.floor(ts / size).astype(np.int64)
        wm = self._watermark
        absorbed = 0
        min_open = None
        if wm is not None:
            min_open = int(math.floor(wm / size))
        uniq, first_idx = np.unique(wid, return_index=True)
        for j in np.argsort(first_idx, kind="stable"):
            w = int(uniq[j])
            rows = np.nonzero(wid == w)[0]
            if min_open is not None and w < min_open:
                self.late_records_total += int(rows.size)
                continue
            pane = self._event_windows.get(w)
            if pane is None:
                pane = self._event_windows[w] = {}
            sc = {f: (b[rows], ln[rows]) for f, (b, ln) in strcols.items()}
            nc = {f: (v[rows], k[rows]) for f, (v, k) in numcols.items()}
            self._absorb_rows(pane, int(rows.size), sc, nc)
            absorbed += int(rows.size)
        new_wm = float(ts.max())
        if wm is None or new_wm > wm:
            self._watermark = new_wm
        self._close_event_windows()
        return absorbed

    def _close_event_windows(self) -> None:
        if self._watermark is None:
            return
        size = self.spec.window.size
        done = int(math.floor(self._watermark / size))
        for w in sorted(k for k in self._event_windows if k < done):
            pane = self._event_windows.pop(w)
            if pane:
                self._pending_closed.append(
                    ((w + 1) * size, list(pane.items())))
                self.window_emits_total += 1

    # ------------------------------------------------------------ window

    def tick(self, now: Optional[float] = None
             ) -> List[Tuple[tuple, _FluxGroup]]:
        """Close expired windows; returns the closed window's groups in
        first-seen order (empty list = nothing to emit).  Mirrors
        ``SPTask.tick`` arithmetic exactly in processing-time mode."""
        w = self.spec.window
        if self.spec.event_time:
            out: List[Tuple[tuple, _FluxGroup]] = []
            for _, items in self._pending_closed:
                out.extend(items)
            self._pending_closed = []
            return out
        if w.kind is None:
            return []
        now = self._now() if now is None else now
        if w.kind == "tumbling":
            if now - self._window_start < w.size:
                return []
            self._window_start += w.size * (
                (now - self._window_start) // w.size)
            closed = list(self._groups.items())
            self._groups = {}
            if closed:
                self.window_emits_total += 1
            return closed
        # hopping
        if now - self._window_start < w.advance:
            return []
        self._window_start += w.advance * (
            (now - self._window_start) // w.advance)
        self._panes.append(self._groups)
        self._groups = {}
        self._panes = self._panes[-w.n_panes:]
        merged: Dict[tuple, _FluxGroup] = {}
        for pane in self._panes:
            for key, g in pane.items():
                m = merged.get(key)
                if m is None:
                    m = merged[key] = _FluxGroup(self.spec)
                m.merge(g)
        out = list(merged.items())
        if out:
            self.window_emits_total += 1
        return out

    def drain(self) -> List[Tuple[tuple, _FluxGroup]]:
        """Shutdown: whatever the open window(s) accumulated (SPTask
        drain semantics for processing-time; all open event windows)."""
        if self.spec.event_time:
            for w in sorted(self._event_windows):
                pane = self._event_windows.pop(w)
                if pane:
                    self._pending_closed.append(
                        ((w + 1) * self.spec.window.size,
                         list(pane.items())))
            return self.tick()
        if self.spec.window.kind is None:
            return list(self._groups.items())
        for pane in self._panes:
            for key, g in pane.items():
                cur = self._groups.get(key)
                if cur is None:
                    self._groups[key] = g
                else:
                    cur.merge(g)
        self._panes = []
        closed = list(self._groups.items())
        self._groups = {}
        return closed

    def live_groups(self) -> List[Tuple[tuple, _FluxGroup]]:
        """The OPEN pane's groups (metrics exporter reads; does not
        disturb window accounting)."""
        if self.spec.event_time:
            merged: Dict[tuple, _FluxGroup] = {}
            for w in sorted(self._event_windows):
                for key, g in self._event_windows[w].items():
                    m = merged.get(key)
                    if m is None:
                        m = merged[key] = _FluxGroup(self.spec)
                    m.merge(g)
            return list(merged.items())
        return list(self._groups.items())

    # ------------------------------------------------------------- top-k

    def topk(self, key: tuple) -> List[Tuple[int, bytes]]:
        """Current hottest values for one group: (estimate, value),
        highest first — CMS point queries over the candidate set, one
        device→host table copy for the whole set."""
        if self.cms is None:
            return []
        cand = list(self._candidates.get(key, ()))
        if not cand:
            return []
        prefix = self._group_prefix(key)
        ests = self.cms.query_many([prefix + v for v in cand])
        top = sorted(zip(ests, cand),
                     key=lambda t: (-t[0], t[1]))[: self.spec.topk]
        return [(int(e), v) for e, v in top]

    # ------------------------------------------------------ snapshot/restore

    def snapshot(self) -> dict:
        """Read-only structural snapshot (window accounting untouched —
        rollover under a concurrent snapshot stays correct)."""

        def enc_pane(pane):
            out = []
            for key, g in pane.items():
                out.append({
                    "key": key,
                    "count": g.count,
                    "cols": {
                        f: (st.has, st.sum, st.min, st.max,
                            st.min_int, st.max_int)
                        for f, st in g.cols.items()
                    },
                    "hlls": {
                        f: np.asarray(h.registers).copy()
                        for f, h in g.hlls.items()
                    },
                })
            return out

        snap = {
            "version": SNAPSHOT_VERSION,
            "name": self.spec.name,
            "shape": self.spec.shape(),
            "window_start": self._window_start,
            "groups": enc_pane(self._groups),
            "panes": [enc_pane(p) for p in self._panes],
            "event_windows": {
                w: enc_pane(p) for w, p in self._event_windows.items()
            },
            "watermark": self._watermark,
            "cms": (np.asarray(self.cms.table).copy()
                    if self.cms is not None else None),
            "candidates": {k: list(v) for k, v in
                           self._candidates.items()},
            "counters": (self.records_total, self.late_records_total,
                         self.window_emits_total, self.batches_total),
        }
        return snap

    def restore(self, snap: dict) -> None:
        if snap.get("version") != SNAPSHOT_VERSION:
            raise ValueError(
                f"flux snapshot version {snap.get('version')!r} "
                f"unsupported (want {SNAPSHOT_VERSION})")
        # a snapshot persisted under a different config must not
        # silently restore: group keys would have the wrong arity and
        # columns/sketches would misalign (window rows with missing or
        # shifted labels) — reject and let the caller start fresh
        if snap.get("name") != self.spec.name:
            raise ValueError(
                f"flux snapshot belongs to state "
                f"{snap.get('name')!r}, not {self.spec.name!r}")
        if snap.get("shape") != self.spec.shape():
            raise ValueError(
                f"flux snapshot shape {snap.get('shape')!r} does not "
                f"match this state's spec {self.spec.shape()!r}")

        def dec_pane(items):
            pane: Dict[tuple, _FluxGroup] = {}
            for it in items:
                g = _FluxGroup(self.spec)
                g.count = it["count"]
                for f, (has, s, mn, mx, mni, mxi) in it["cols"].items():
                    if f in g.cols:
                        st = g.cols[f]
                        st.has, st.sum = has, s
                        st.min, st.max = mn, mx
                        st.min_int, st.max_int = mni, mxi
                for f, regs in it["hlls"].items():
                    if f in g.hlls:
                        arr = np.asarray(regs).astype(np.int32).copy()
                        # belt-and-braces behind the shape() check: a
                        # wrong-sized register array would be an
                        # out-of-bounds write in the C kernel
                        if arr.shape != (g.hlls[f].m,):
                            raise ValueError(
                                f"flux snapshot HLL register shape "
                                f"{arr.shape} != ({g.hlls[f].m},)")
                        g.hlls[f].registers = arr
                pane[it["key"]] = g
            return pane

        # decode EVERYTHING into locals before touching self: a decode
        # failure mid-way must leave the state exactly as it was (the
        # old-or-new recovery contract; load() falls back to fresh)
        groups = dec_pane(snap["groups"])
        panes = [dec_pane(p) for p in snap["panes"]]
        event_windows = {
            w: dec_pane(p) for w, p in snap["event_windows"].items()
        }
        cms_table = None
        if self.cms is not None and snap.get("cms") is not None:
            cms_table = np.asarray(snap["cms"]).astype(
                np.asarray(self.cms.table).dtype).copy()
            want = (self.cms.depth, self.cms.width)
            if cms_table.shape != want:
                raise ValueError(
                    f"flux snapshot CMS table shape {cms_table.shape} "
                    f"!= {want}")
        candidates = {
            k: {v: None for v in vs}
            for k, vs in snap.get("candidates", {}).items()
        }
        (records, late, emits, batches) = snap["counters"]
        self._groups = groups
        self._panes = panes
        self._event_windows = event_windows
        self._watermark = snap["watermark"]
        self._window_start = snap["window_start"]
        if cms_table is not None:
            self.cms.table = cms_table
        self._candidates = candidates
        self.records_total = records
        self.late_records_total = late
        self.window_emits_total = emits
        self.batches_total = batches

    def persist(self, path: str) -> None:
        """Atomic snapshot write: tmp + fsync + rename — a crash at the
        armed ``flux.snapshot`` failpoint leaves the previous file
        intact (old-or-new, never torn)."""
        self.write_snapshot(self.snapshot(), path)

    @staticmethod
    def write_snapshot(snap: dict, path: str) -> None:
        """Write an already-built snapshot dict (see :meth:`persist`).
        Split out so callers holding the engine ingest lock can build
        the (read-only, in-memory) snapshot under the lock and do the
        pickle/write/fsync OUTSIDE it — a slow disk must not stall
        every input's append."""
        payload = pickle.dumps(snap, protocol=4)
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".flux-snap-", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            if _fp.ACTIVE:
                _fp.fire("flux.snapshot")
            os.replace(tmp, path)
        finally:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass  # best-effort tmp cleanup; the snapshot landed

    def load(self, path: str) -> bool:
        """Restore from a persisted snapshot; False = no/corrupt file
        (fresh state — the recovery contract is old-or-new)."""
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except OSError:
            return False
        try:
            snap = pickle.loads(payload)
        except Exception:
            # numpy/format upgrades surface as AttributeError /
            # ImportError / TypeError from array reconstruction — the
            # recovery contract is "unusable snapshot → fresh state",
            # never "pipeline fails to start"
            import logging

            logging.getLogger("flb.flux").warning(
                "flux snapshot %s undecodable; starting fresh", path,
                exc_info=True)
            return False
        try:
            self.restore(snap)
        except (KeyError, ValueError, TypeError):
            import logging

            logging.getLogger("flb.flux").warning(
                "flux snapshot %s unusable; starting fresh", path)
            return False
        return True
