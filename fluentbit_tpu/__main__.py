"""CLI — ``python -m fluentbit_tpu``.

Reference: src/fluent-bit.c (long-option parsing :1038, signal handlers
:704-716: SIGINT/SIGTERM graceful stop, SIGHUP hot reload). Argument
order matters the same way: ``-p`` properties apply to the most recent
``-i``/``-F``/``-o`` instance.

Usage examples::

    python -m fluentbit_tpu -i dummy -o stdout -f 1
    python -m fluentbit_tpu -i tail -p path=/var/log/syslog -o null
    python -m fluentbit_tpu -c pipeline.conf
"""

from __future__ import annotations

import os
import signal
import sys
import threading

from fluentbit_tpu import __version__

USAGE = """\
fluentbit_tpu — TPU-native telemetry pipeline

Options:
  -c, --config FILE     load a configuration file (classic INI or YAML)
  -R, --parser FILE     load a parsers file
  -e, --plugin FILE     load a dynamic (.so) plugin (C ABI, see
                        native/fbtpu_plugin.h)
  -i, --input NAME      add an input plugin instance
  -F, --filter NAME     add a filter plugin instance
  -o, --output NAME     add an output plugin instance
  -p, --prop K=V        set a property on the last added instance
  -t, --tag TAG         set the tag on the last added input
  -m, --match PATTERN   set the match rule on the last filter/output
  -f, --flush SECONDS   flush interval
  -g, --grace SECONDS   shutdown grace period
  -H, --http            enable the HTTP admin server
  -P, --port PORT       HTTP admin server port (default 2020)
  -D, --define K=V      set a config variable for ${K} interpolation
  -v, --verbose         increase log verbosity (repeatable)
  -q, --quiet           decrease log verbosity
  --supervisor          run under a supervising parent that restarts
                        the worker on crash
  --dry-run             validate configuration and exit
  -V, --version         print version and exit
  -h, --help            this message
"""


def build_context(argv):
    import fluentbit_tpu as flb
    from fluentbit_tpu.config_format import apply_to_context, load_config_file

    ctx = flb.create()
    env = {}
    last = None  # (kind, ffd)
    verbosity = 0
    dry_run = False
    config_path = None
    i = 0

    def need_arg(flag):
        nonlocal i
        i += 1
        if i >= len(argv):
            raise SystemExit(f"option {flag} requires an argument")
        return argv[i]

    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(USAGE)
            raise SystemExit(0)
        elif a in ("-V", "--version"):
            print(f"fluentbit_tpu v{__version__}")
            raise SystemExit(0)
        elif a in ("-c", "--config"):
            config_path = need_arg(a)
            cf = load_config_file(config_path, env=env)
            apply_to_context(
                ctx, cf, os.path.dirname(os.path.abspath(config_path))
            )
        elif a in ("-R", "--parser"):
            path = need_arg(a)
            from fluentbit_tpu.config_format import _apply_parsers

            _apply_parsers(ctx, load_config_file(path, env=env))
        elif a in ("-e", "--plugin"):
            # dynamic .so plugin (flb_plugin_load, src/flb_plugin.c)
            from fluentbit_tpu.core.dso import load_dso_plugin

            load_dso_plugin(need_arg(a))
        elif a in ("-i", "--input"):
            last = ("input", ctx.input(need_arg(a)))
        elif a in ("-F", "--filter"):
            last = ("filter", ctx.filter(need_arg(a)))
        elif a in ("-o", "--output"):
            last = ("output", ctx.output(need_arg(a)))
        elif a in ("-p", "--prop"):
            kv = need_arg(a)
            if "=" not in kv or last is None:
                raise SystemExit(f"bad -p usage: {kv!r}")
            k, v = kv.split("=", 1)
            ctx.set(last[1], **{k: v})
        elif a in ("-t", "--tag"):
            if last is None or last[0] != "input":
                raise SystemExit("-t requires a preceding -i")
            ctx.set(last[1], tag=need_arg(a))
        elif a in ("-m", "--match"):
            if last is None or last[0] == "input":
                raise SystemExit("-m requires a preceding -F/-o")
            ctx.set(last[1], match=need_arg(a))
        elif a in ("-f", "--flush"):
            ctx.service_set(flush=need_arg(a))
        elif a in ("-g", "--grace"):
            ctx.service_set(grace=need_arg(a))
        elif a in ("-H", "--http"):
            ctx.service_set(http_server="on")
        elif a in ("-P", "--port"):
            ctx.service_set(http_port=need_arg(a))
        elif a in ("-D", "--define"):
            kv = need_arg(a)
            if "=" in kv:
                k, v = kv.split("=", 1)
                env[k] = v
        elif a in ("-v", "--verbose"):
            verbosity += 1
        elif a in ("-q", "--quiet"):
            verbosity -= 1
        elif a == "--dry-run":
            dry_run = True
        else:
            raise SystemExit(f"unknown option {a!r} (see --help)")
        i += 1

    return ctx, verbosity, dry_run, config_path, env


def main(argv=None) -> int:
    import logging

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(USAGE)
        return 1
    if "--supervisor" in argv:
        # flb_supervisor_run: parent forks + restarts the worker
        from .supervisor import run_supervised

        worker_argv = [a for a in argv if a != "--supervisor"]
        logging.basicConfig(level=logging.INFO,
                            format="[%(asctime)s] [%(levelname)5s] %(message)s")
        return run_supervised(lambda: main(worker_argv))
    ctx, verbosity, dry_run, config_path, env = build_context(argv)
    level = {-1: logging.ERROR, 0: logging.INFO, 1: logging.DEBUG}.get(
        max(-1, min(1, verbosity)), logging.INFO
    )
    logging.basicConfig(
        level=level, format="[%(asctime)s] [%(levelname)5s] %(message)s"
    )
    log = logging.getLogger("flb.cli")

    if not ctx.engine.inputs or not ctx.engine.outputs:
        log.error("configuration needs at least one input and one output")
        return 1
    if dry_run:
        print("configuration test is successful")
        return 0

    stop_evt = threading.Event()
    reload_req = threading.Event()

    def reload_enabled() -> bool:
        # reload is gated (reference: -Y / [SERVICE] Hot_Reload On)
        return bool(config_path) and ctx.engine.service.hot_reload

    def on_stop(signum, frame):
        stop_evt.set()

    def on_hup(signum, frame):
        if reload_enabled():
            reload_req.set()
            stop_evt.set()
        else:
            log.warning("SIGHUP ignored (hot_reload off or no config file)")

    signal.signal(signal.SIGINT, on_stop)
    signal.signal(signal.SIGTERM, on_stop)
    signal.signal(signal.SIGHUP, on_hup)

    reloads = 0
    while True:
        if reload_enabled():
            # POST /api/v2/reload triggers the same path as SIGHUP
            def _http_reload():
                reload_req.set()
                stop_evt.set()

            ctx.engine.reload_callback = _http_reload
        ctx.engine.reload_count = reloads
        ctx.start()
        log.info("fluentbit_tpu v%s started (pid %d)", __version__, os.getpid())
        while True:
            while not stop_evt.is_set() and ctx.engine.running:
                stop_evt.wait(0.2)
            if not reload_req.is_set():
                log.info("stopping (grace %ss)...", ctx.engine.service.grace)
                ctx.stop()
                return 0
            # hot reload (flb_reload, src/flb_reload.c:461): validate the
            # NEW configuration with the full original argv BEFORE the
            # old pipeline is torn down — a broken edit must not kill a
            # working service
            # in_calyptia_fleet hands the engine a NEW config path to
            # reload onto (reference do_reload swaps conf_path_file,
            # in_calyptia_fleet.c:610-628)
            override = getattr(ctx.engine, "reload_config_path", None)
            # consume the override: a failed fleet revision must not
            # hijack later operator-initiated reloads
            ctx.engine.reload_config_path = None
            reload_argv = argv
            if override:
                reload_argv = list(argv)
                slots = [j + 1 for j, a in enumerate(reload_argv)
                         if a in ("-c", "--config")
                         and j + 1 < len(reload_argv)]
                if len(slots) == 1:
                    reload_argv[slots[0]] = override
                else:
                    # -c applies cumulatively: substituting a fleet
                    # path into several slots would double-apply it
                    log.warning(
                        "fleet config %s ignored: need exactly one "
                        "-c/--config on the command line (found %d)",
                        override, len(slots))
                    override = None
                    reload_argv = argv
            if not override and ctx.engine.service.hot_reload_diff:
                # diff-mode reload (core/reload_diff.py): apply only
                # the file's delta through one ReloadTxn generation
                # swap — untouched inputs keep tail offsets / sockets,
                # in-flight chunks drain normally. Anything the
                # transaction model can't express falls through to
                # the validated full-restart path below.
                from fluentbit_tpu.core.reload_diff import (
                    ReloadDiffUnsupported, reload_from_file)

                try:
                    gen, _summary = reload_from_file(
                        ctx.engine, config_path, env=env)
                except ReloadDiffUnsupported as e:
                    log.info("reload diff: %s; falling back to full "
                             "restart", e)
                except Exception as e:  # noqa: BLE001
                    log.error("reload diff failed (%s); falling back "
                              "to full restart", e)
                else:
                    if gen is not None:
                        log.info("configuration reloaded in place "
                                 "(generation %d)", gen)
                    # keep the local counter in sync: the txn bumps
                    # engine.reload_count itself, and a LATER full
                    # restart seeds the new engine from `reloads`
                    reloads = ctx.engine.reload_count
                    reload_req.clear()
                    stop_evt.clear()
                    continue  # old engine still running, now current
            log.info("reloading configuration %s", override or config_path)
            reload_req.clear()
            stop_evt.clear()
            try:
                new_ctx, *_ = build_context(reload_argv)
                ok = bool(new_ctx.engine.inputs and new_ctx.engine.outputs)
            except (SystemExit, Exception) as e:  # noqa: BLE001
                log.error("reload failed, keeping current pipeline: %s", e)
                continue  # old engine still running
            if not ok:
                log.error("reload failed, keeping current pipeline: "
                          "needs at least one input and one output")
                continue
            # commit the fleet override only once it VALIDATED — a
            # broken fleet revision must not hijack later reloads of
            # the operator's known-good config
            if override:
                argv = reload_argv
                config_path = override
            log.info("stopping old pipeline (grace %ss)...",
                     ctx.engine.service.grace)
            ctx.stop()
            ctx = new_ctx
            reloads += 1
            break  # outer loop starts the new context


if __name__ == "__main__":
    sys.exit(main())
