"""Stream processor — inline SQL over the log stream.

Reference: src/stream_processor/ (flb_sp.c task runtime, sql.y grammar
:37-65 CREATE STREAM, :108-160 select/keys, :253-276 windows,
flb_sp_window.c tumbling/hopping, flb_sp_groupby.c,
flb_sp_aggregate_func.c AVG/SUM/COUNT/MIN/MAX + TIMESERIES_FORECAST,
flb_sp_snapshot.c). Invoked synchronously post-filter at ingest
(flb_sp_do call, src/flb_input_chunk.c:3155); results re-enter the
pipeline through a hidden emitter (the in_stream_processor pattern).

This is a hand-written recursive-descent parser + evaluator over the
same grammar subset (no flex/bison):

    CREATE STREAM name [WITH (tag='x')] AS
      SELECT *|keys|AGG(key)[ AS alias] FROM STREAM:name|TAG:'pattern'
      [WHERE cond] [WINDOW TUMBLING (N SECOND)
                   |WINDOW HOPPING (N SECOND, ADVANCE BY M SECOND)]
      [GROUP BY keys];

Aggregates: AVG, SUM, COUNT, MIN, MAX, TIMESERIES_FORECAST(key, N).
Conditions: comparisons, AND/OR/NOT, parentheses, IS [NOT] NULL,
@record.time() and @record.contains(key).

Device mapping note (SURVEY §5): tumbling windows are scan-reductions
over device-resident state; the aggregation math here is the CPU
reference semantics those kernels must reproduce.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core.router import Route

# ----------------------------------------------------------------- lexer

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<num>-?\d+(?:\.\d+)?)
      | '(?P<str>(?:[^'\\]|\\.)*)'
      | (?P<id>[A-Za-z_@][A-Za-z0-9_.\-]*)
      | (?P<op><=|>=|!=|<>|[(),;*=<>:])
    )""",
    re.VERBOSE,
)

KEYWORDS = {
    "create", "stream", "snapshot", "flush", "with", "as", "select",
    "from", "where", "window", "tumbling", "hopping", "advance", "by",
    "second", "minute", "hour", "group", "and", "or", "not", "is",
    "null", "tag", "limit", "distinct",
}

AGG_FUNCS = ("avg", "sum", "count", "min", "max", "timeseries_forecast")


class SQLError(ValueError):
    pass


def _tokenize(text: str) -> List[Tuple[str, Any]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip():
                raise SQLError(f"bad SQL near {text[pos:pos+20]!r}")
            break
        pos = m.end()
        if m.group("num") is not None:
            v = float(m.group("num"))
            out.append(("num", int(v) if v.is_integer() else v))
        elif m.group("str") is not None:
            out.append(("str", re.sub(r"\\(.)", r"\1", m.group("str"))))
        elif m.group("id") is not None:
            word = m.group("id")
            out.append(("kw", word.lower()) if word.lower() in KEYWORDS
                       else ("id", word))
        else:
            out.append(("op", m.group("op")))
    return out


# ------------------------------------------------------------------- AST

@dataclass
class SelectKey:
    name: Optional[str]          # None = *
    func: Optional[str] = None   # aggregate function
    alias: Optional[str] = None
    forecast_secs: int = 0       # TIMESERIES_FORECAST horizon

    @property
    def out_name(self) -> str:
        if self.alias:
            return self.alias
        if self.func == "count_distinct":
            return f"COUNT(DISTINCT {self.name})"
        if self.func:
            return f"{self.func.upper()}({self.name or '*'})"
        return self.name or "*"


@dataclass
class Query:
    stream_name: Optional[str]
    props: Dict[str, str]
    keys: List[SelectKey]
    source_type: str             # 'stream' | 'tag'
    source: str
    where: Optional[object]
    window: Optional[Tuple[str, float, float]]  # (kind, size_s, advance_s)
    group_by: List[str]
    # 'stream' | 'snapshot' | 'flush_snapshot' (FLB_SP_CREATE_STREAM /
    # CREATE_SNAPSHOT / FLUSH_SNAPSHOT command types, sql.y:108-146)
    kind: str = "stream"
    limit: int = 0               # CREATE SNAPSHOT ... LIMIT n

    @property
    def has_aggregates(self) -> bool:
        return any(k.func for k in self.keys)


class _Parser:
    def __init__(self, tokens: List[Tuple[str, Any]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, kind, value=None):
        k, v = self.next()
        if k != kind or (value is not None and v != value):
            raise SQLError(f"expected {value or kind}, got {v!r}")
        return v

    def accept(self, kind, value=None) -> bool:
        k, v = self.peek()
        if k == kind and (value is None or v == value):
            self.i += 1
            return True
        return False

    # CREATE STREAM name [WITH (...)] AS SELECT ... | SELECT ...
    def _parse_with(self, props: Dict[str, str]) -> None:
        if self.accept("kw", "with"):
            self.expect("op", "(")
            while True:
                k = self.next()[1]
                self.expect("op", "=")
                props[str(k)] = self.next()[1]
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")

    def parse(self) -> Query:
        name = None
        kind = "stream"
        props: Dict[str, str] = {}
        if self.accept("kw", "create"):
            if self.accept("kw", "snapshot"):
                # CREATE SNAPSHOT name [WITH(seconds=N)] AS SELECT *
                # FROM source [LIMIT n]  (sql.y:122-132)
                kind = "snapshot"
            else:
                self.expect("kw", "stream")
            name = self.expect("id")
            self._parse_with(props)
            self.expect("kw", "as")
        elif self.accept("kw", "flush"):
            # FLUSH SNAPSHOT name AS SELECT * FROM source WHERE cond
            # (sql.y:134-146)
            self.expect("kw", "snapshot")
            kind = "flush_snapshot"
            name = self.expect("id")
            self._parse_with(props)
            self.expect("kw", "as")
        q = self.parse_select()
        q.stream_name = name
        q.props = props
        q.kind = kind
        if kind == "snapshot" and q.limit == 0 and \
                not str(props.get("seconds", "")).strip():
            raise SQLError(
                f"snapshot {name!r}: size is not defined "
                "(use LIMIT n and/or WITH(seconds=N))")
        if kind != "snapshot" and q.limit:
            raise SQLError("LIMIT is only valid on CREATE SNAPSHOT")
        self.accept("op", ";")
        return q

    def parse_select(self) -> Query:
        self.expect("kw", "select")
        keys = [self.parse_select_key()]
        while self.accept("op", ","):
            keys.append(self.parse_select_key())
        self.expect("kw", "from")
        kind, v = self.next()
        low = str(v).lower()
        if low == "stream":
            source_type = "stream"
            self.expect("op", ":")
            source = str(self.expect("id"))
        elif low == "tag":
            source_type = "tag"
            self.expect("op", ":")
            source = str(self.next()[1])
        else:
            raise SQLError(
                f"expected STREAM:name or TAG:'pattern', got {v!r}"
            )
        where = None
        if self.accept("kw", "where"):
            where = self.parse_or()
        window = None
        if self.accept("kw", "window"):
            window = self.parse_window()
        group_by: List[str] = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.expect("id"))
            while self.accept("op", ","):
                group_by.append(self.expect("id"))
        limit = 0
        if self.accept("kw", "limit"):
            k, v = self.next()
            if k != "num":
                raise SQLError(f"LIMIT needs a number, got {v!r}")
            limit = int(v)
        return Query(None, {}, keys, source_type, source, where, window,
                     group_by, limit=limit)

    def parse_select_key(self) -> SelectKey:
        k, v = self.next()
        if k == "op" and v == "*":
            return SelectKey(None)
        if k != "id":
            raise SQLError(f"bad select key {v!r}")
        name = str(v)
        if name.lower() in AGG_FUNCS and self.accept("op", "("):
            func = name.lower()
            if self.accept("op", "*"):
                arg = None
            elif func == "count" and self.accept("kw", "distinct"):
                # COUNT(DISTINCT key) — the cardinality aggregate the
                # flux plane answers with an HLL (exact evaluation
                # keeps a per-group value set)
                func = "count_distinct"
                arg = self.expect("id")
            else:
                arg = self.expect("id")
            horizon = 0
            if self.accept("op", ","):
                horizon = int(self.next()[1])
            self.expect("op", ")")
            alias = self.expect("id") if self.accept("kw", "as") else None
            return SelectKey(arg, func, alias, horizon)
        alias = self.expect("id") if self.accept("kw", "as") else None
        return SelectKey(name, None, alias)

    def parse_window(self) -> Tuple[str, float, float]:
        k, v = self.next()
        kind = str(v).lower()
        if kind not in ("tumbling", "hopping"):
            raise SQLError(f"unknown window kind {v!r}")
        self.expect("op", "(")
        size = float(self.next()[1]) * self._unit()
        advance = size
        if kind == "hopping":
            self.expect("op", ",")
            self.expect("kw", "advance")
            self.expect("kw", "by")
            advance = float(self.next()[1]) * self._unit()
        self.expect("op", ")")
        return (kind, size, advance)

    def _unit(self) -> float:
        k, v = self.next()
        unit = {"second": 1.0, "minute": 60.0, "hour": 3600.0}.get(v)
        if unit is None:
            raise SQLError(f"unknown time unit {v!r} "
                           f"(SECOND/MINUTE/HOUR)")
        return unit

    # -- conditions --

    def parse_or(self):
        left = self.parse_and()
        while self.accept("kw", "or"):
            left = ("or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept("kw", "and"):
            left = ("and", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept("kw", "not"):
            return ("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self):
        if self.accept("op", "("):
            inner = self.parse_or()
            self.expect("op", ")")
            return inner
        left = self.parse_value()
        k, v = self.peek()
        if k == "op" and v in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            right = self.parse_value()
            return ("cmp", v, left, right)
        if k == "kw" and v == "is":
            self.next()
            negate = self.accept("kw", "not")
            self.expect("kw", "null")
            node = ("isnull", left)
            return ("not", node) if negate else node
        return ("truthy", left)

    def parse_value(self):
        k, v = self.next()
        if k == "num" or k == "str":
            return ("lit", v)
        if k == "kw" and v == "null":
            return ("lit", None)
        if k == "id":
            name = str(v)
            if name.startswith("@record."):
                fn = name[len("@record."):]
                self.expect("op", "(")
                arg = None
                if not self.accept("op", ")"):
                    arg = self.next()[1]
                    self.expect("op", ")")
                return ("recfn", fn, arg)
            if name.lower() in ("true", "false"):
                return ("lit", name.lower() == "true")
            return ("key", name)
        raise SQLError(f"bad value {v!r}")


def parse_sql(text: str) -> Query:
    return _Parser(_tokenize(text)).parse()


# -------------------------------------------------------------- evaluate

def _get_key(body: dict, name: str):
    if name in body:
        return body[name]
    cur = body
    for part in name.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def eval_cond(node, body: dict, ts: float) -> bool:
    kind = node[0]
    if kind == "or":
        return eval_cond(node[1], body, ts) or eval_cond(node[2], body, ts)
    if kind == "and":
        return eval_cond(node[1], body, ts) and eval_cond(node[2], body, ts)
    if kind == "not":
        return not eval_cond(node[1], body, ts)
    if kind == "isnull":
        return eval_value(node[1], body, ts) is None
    if kind == "truthy":
        return bool(eval_value(node[1], body, ts))
    if kind == "cmp":
        _, op, ln, rn = node
        lv = eval_value(ln, body, ts)
        rv = eval_value(rn, body, ts)
        if op in ("=",):
            return lv == rv
        if op in ("!=", "<>"):
            return lv != rv
        try:
            if op == "<":
                return lv < rv
            if op == "<=":
                return lv <= rv
            if op == ">":
                return lv > rv
            if op == ">=":
                return lv >= rv
        except TypeError:
            return False
    return False


def eval_value(node, body: dict, ts: float):
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "key":
        return _get_key(body, node[1])
    if kind == "recfn":
        fn, arg = node[1], node[2]
        if fn == "time":
            return ts
        if fn == "contains":
            return arg in body if isinstance(body, dict) else False
        raise SQLError(f"unknown @record function {fn!r}")
    return None


# ------------------------------------------------------------ aggregation

class _Agg:
    """Accumulator for one group (flb_sp_aggregate_func.c semantics)."""

    __slots__ = ("count", "sums", "mins", "maxs", "series", "distincts")

    def __init__(self):
        self.count = 0
        self.sums: Dict[str, float] = {}
        self.mins: Dict[str, Any] = {}
        self.maxs: Dict[str, Any] = {}
        self.series: Dict[str, List[Tuple[float, float]]] = {}
        # COUNT(DISTINCT key): exact per-group value sets — the
        # reference semantics the flux HLL approximates
        self.distincts: Dict[str, set] = {}

    def merge(self, other: "_Agg") -> None:
        """Union of two accumulators (hopping-window pane merge)."""
        self.count += other.count
        for n, v in other.sums.items():
            self.sums[n] = self.sums.get(n, 0.0) + v
        for n, v in other.mins.items():
            if n not in self.mins or v < self.mins[n]:
                self.mins[n] = v
        for n, v in other.maxs.items():
            if n not in self.maxs or v > self.maxs[n]:
                self.maxs[n] = v
        for n, s in other.series.items():
            self.series.setdefault(n, []).extend(s)
        for n, s in other.distincts.items():
            self.distincts.setdefault(n, set()).update(s)

    def add(self, body: dict, ts: float, keys: List[SelectKey]) -> None:
        self.count += 1
        seen = set()  # several aggregates may reference the same field
        for k in keys:
            if not k.func or k.name is None:
                continue
            n = k.name
            v = _get_key(body, n)
            if k.func == "count_distinct":
                if v is not None:
                    try:
                        self.distincts.setdefault(n, set()).add(v)
                    except TypeError:
                        pass  # unhashable (list/dict) values don't count
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if n not in seen:
                seen.add(n)
                self.sums[n] = self.sums.get(n, 0.0) + v
                if n not in self.mins or v < self.mins[n]:
                    self.mins[n] = v
                if n not in self.maxs or v > self.maxs[n]:
                    self.maxs[n] = v
            if k.func == "timeseries_forecast":
                self.series.setdefault(n, []).append((ts, float(v)))

    def result(self, key: SelectKey):
        n = key.name
        if key.func == "count":
            return self.count
        if key.func == "count_distinct":
            return len(self.distincts.get(n, ()))
        if key.func == "sum":
            return self.sums.get(n, 0.0)
        if key.func == "avg":
            return self.sums.get(n, 0.0) / self.count if self.count else 0.0
        if key.func == "min":
            return self.mins.get(n)
        if key.func == "max":
            return self.maxs.get(n)
        if key.func == "timeseries_forecast":
            return self._forecast(self.series.get(n, []),
                                  key.forecast_secs)
        return None

    @staticmethod
    def _forecast(series: List[Tuple[float, float]], horizon: float):
        """Simple linear regression forecast (the reference's
        TIMESERIES_FORECAST is least-squares over the window)."""
        n = len(series)
        if n < 2:
            return series[-1][1] if series else None
        t0 = series[0][0]
        xs = [t - t0 for t, _ in series]
        ys = [v for _, v in series]
        mx = sum(xs) / n
        my = sum(ys) / n
        denom = sum((x - mx) ** 2 for x in xs)
        slope = (sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
                 if denom else 0.0)
        intercept = my - slope * mx
        x_pred = xs[-1] + horizon
        return intercept + slope * x_pred


def project(body: dict, keys: List[SelectKey]) -> dict:
    """SELECT projection of one record (shared by SPTask and the sql
    processor)."""
    out: Dict[str, Any] = {}
    for k in keys:
        if k.name is None and not k.func:
            out.update(body)
        else:
            out[k.out_name] = _get_key(body, k.name)
    return out


class SPTask:
    """One registered query (struct flb_sp_task)."""

    def __init__(self, sql: str, emit, now=None):
        self.query = parse_sql(sql)
        self.sql = sql
        self.emit = emit  # emit(tag, list_of_bodies)
        q = self.query
        self.out_tag = q.props.get("tag") or q.stream_name or "sp.results"
        self._route = (Route(match=q.source) if q.source_type == "tag"
                       else None)
        self._groups: Dict[tuple, _Agg] = {}
        # hopping windows: closed panes, newest last (size/advance many)
        self._panes: List[Dict[tuple, _Agg]] = []
        self._window_start = (now or time.time)()
        self._now = now or time.time
        # CREATE SNAPSHOT ring: (ts, body) bounded by LIMIT records
        # and/or WITH(seconds=N) age (flb_sp_snapshot.c pages)
        self._snap: List[tuple] = []
        self._snap_seconds = float(q.props.get("seconds", 0) or 0)
        # FLUSH SNAPSHOT looks its CREATE twin up through this hook
        # (flb_sp_snapshot_flush walks sp->tasks the same way)
        self.find_snapshot = lambda name: None
        # sketch-eligible queries resolve against flux state instead of
        # the per-event evaluation below (flux.query.attach_flux flips
        # this to a FluxBinding): the hidden flux filter absorbs the
        # records inside the filter pass, this task just reads windows
        self.flux = None

    def matches(self, tag: str, stream_name: Optional[str] = None) -> bool:
        if self.query.source_type == "tag":
            return self._route.matches(tag)
        return stream_name == self.query.source

    # -- ingest-side processing --

    # -- snapshots (flb_sp_snapshot.c) --

    def snapshot_update(self, ts: float, body: dict) -> None:
        self._snap.append((ts, body))
        if self.query.limit:
            del self._snap[:max(0, len(self._snap) - self.query.limit)]
        if self._snap_seconds > 0:
            cutoff = self._now() - self._snap_seconds
            i = 0
            while i < len(self._snap) and self._snap[i][0] < cutoff:
                i += 1
            if i:
                del self._snap[:i]

    def snapshot_take(self) -> List[tuple]:
        taken, self._snap = self._snap, []
        return taken

    def process(self, events: list, tag: str) -> None:
        q = self.query
        if self.flux is not None:
            # flux-backed: state was already updated inside the filter
            # chain (batched or per-record twin) — aggregating here
            # again would double-count
            return
        if q.kind == "snapshot":
            # WHERE and the SELECT projection apply to what gets
            # buffered, same as any other query kind
            for ev in events:
                if not isinstance(ev.body, dict):
                    continue
                if q.where is not None and \
                        not eval_cond(q.where, ev.body, ev.ts_float):
                    continue
                self.snapshot_update(ev.ts_float, self._project(ev.body))
            return
        if q.kind == "flush_snapshot":
            fire = any(
                isinstance(ev.body, dict)
                and (q.where is None
                     or eval_cond(q.where, ev.body, ev.ts_float))
                for ev in events)
            if not fire:
                return
            snap_task = self.find_snapshot(q.stream_name)
            if snap_task is None:
                return
            taken = snap_task.snapshot_take()
            if taken:
                # emit preserves the buffered records' own timestamps
                self.emit(self.out_tag, taken)
            return
        immediate: List[dict] = []
        for ev in events:
            body = ev.body
            if not isinstance(body, dict):
                continue
            ts = ev.ts_float
            if q.where is not None and not eval_cond(q.where, body, ts):
                continue
            if q.has_aggregates:
                gkey = tuple(_get_key(body, g) for g in q.group_by)
                agg = self._groups.get(gkey)
                if agg is None:
                    agg = self._groups[gkey] = _Agg()
                agg.add(body, ts, q.keys)
            else:
                immediate.append(self._project(body))
        if immediate:
            self.emit(self.out_tag, immediate)
        if q.has_aggregates and q.window is None:
            # no window: aggregates emit per processed chunk then reset
            self._emit_aggregates()

    def _project(self, body: dict) -> dict:
        return project(body, self.query.keys)

    def _rows_of(self, groups: Dict[tuple, _Agg]) -> List[dict]:
        q = self.query
        results = []
        for gkey, agg in groups.items():
            row: Dict[str, Any] = {}
            for gname, gval in zip(q.group_by, gkey):
                row[gname] = gval
            for k in q.keys:
                if k.func:
                    row[k.out_name] = agg.result(k)
                elif k.name is not None:
                    row.setdefault(k.out_name, None)
            results.append(row)
        return results

    def _emit_aggregates(self) -> None:
        results = self._rows_of(self._groups)
        self._groups.clear()
        if results:
            self.emit(self.out_tag, results)

    # -- window timer --

    def tick(self) -> None:
        """Close expired windows (flb_sp_window semantics). Tumbling:
        emit+reset every ``size``. Hopping: every ``advance`` the live
        pane closes and the emission aggregates the union of the last
        ``size/advance`` panes (a true sliding window over panes)."""
        q = self.query
        if self.flux is not None:
            rows = self.flux.rows_on_tick(self._now())
            if rows:
                self.emit(self.out_tag, rows)
            return
        if q.window is None or not q.has_aggregates:
            return
        kind, size, advance = q.window
        now = self._now()
        if kind == "tumbling":
            if now - self._window_start >= size:
                # advance by whole periods so tick latency never drifts
                # the window boundaries
                self._window_start += size * ((now - self._window_start)
                                              // size)
                self._emit_aggregates()
            return
        if now - self._window_start < advance:
            return
        self._window_start += advance * ((now - self._window_start)
                                         // advance)
        self._panes.append(self._groups)
        self._groups = {}
        n_panes = max(1, int(round(size / advance)))
        self._panes = self._panes[-n_panes:]
        merged: Dict[tuple, _Agg] = {}
        for pane in self._panes:
            for gkey, agg in pane.items():
                if gkey in merged:
                    merged[gkey].merge(agg)
                else:
                    m = _Agg()
                    m.merge(agg)
                    merged[gkey] = m
        results = self._rows_of(merged)
        if results:
            self.emit(self.out_tag, results)

    def drain(self) -> None:
        """Shutdown: emit whatever the open window accumulated."""
        if self.flux is not None:
            rows = self.flux.rows_on_drain()
            if rows:
                self.emit(self.out_tag, rows)
            return
        if self.query.window is not None and self.query.has_aggregates:
            for pane in self._panes:
                for gkey, agg in pane.items():
                    if gkey in self._groups:
                        self._groups[gkey].merge(agg)
                    else:
                        self._groups[gkey] = agg
            self._panes = []
            self._emit_aggregates()


class StreamProcessor:
    """flb_sp: the set of tasks + chunk hook + result re-ingestion."""

    def __init__(self, engine):
        self.engine = engine
        self.tasks: List[SPTask] = []
        # both set by Engine.sp_task (single place that also wires the
        # window-tick collector)
        self._emitter = None
        self.emitter_instance = None

    def create_task(self, sql: str) -> SPTask:
        task = SPTask(sql, lambda tag, bodies: self._emit(task, tag, bodies))
        task.find_snapshot = self._find_snapshot
        self.tasks.append(task)
        return task

    def _find_snapshot(self, name: str):
        for t in self.tasks:
            if t.query.kind == "snapshot" and t.query.stream_name == name:
                return t
        return None

    def _emit(self, src_task: SPTask, tag: str, bodies: List[dict]) -> None:
        from ..codec.events import decode_events, encode_event, now_event_time

        buf = bytearray()
        for b in bodies:
            if isinstance(b, tuple):  # snapshot flush: (orig_ts, body)
                ts, body = b
            else:
                ts, body = now_event_time(), b
            buf += encode_event(body, ts)
        data = bytes(buf)
        if self._emitter is None:
            raise RuntimeError(
                "stream processor emitter not wired — create tasks via "
                "Engine.sp_task"
            )
        self._emitter.add_record(tag, data, len(bodies))
        # stream-to-stream chaining: FROM STREAM:<name> consumes the
        # named stream's RESULTS (flb_sp_stream.c). Depth-bounded so a
        # cycle of streams (a←b, b←a) terminates instead of recursing
        name = src_task.query.stream_name
        if name:
            self._chain_depth = getattr(self, "_chain_depth", 0) + 1
            try:
                if self._chain_depth > 16:
                    import logging

                    logging.getLogger("flb.sp").warning(
                        "stream chain depth exceeded — cycle between "
                        "CREATE STREAM tasks? dropping further chaining"
                    )
                    return
                chained = decode_events(data)
                for t2 in self.tasks:
                    if t2 is not src_task and t2.matches(tag, name):
                        t2.process(chained, tag)
            finally:
                self._chain_depth -= 1

    def do(self, events: list, tag: str,
           stream_name: Optional[str] = None) -> None:
        """flb_sp_do — run every matching task over the filtered events
        (called at ingest, post-filter)."""
        for task in self.tasks:
            if task.matches(tag, stream_name):
                task.process(events, tag)

    def tick(self) -> None:
        for task in self.tasks:
            task.tick()

    def drain(self) -> None:
        """Shutdown: flush open windows so counted records are not lost."""
        for task in self.tasks:
            task.drain()
