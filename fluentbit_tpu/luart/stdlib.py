"""Lua 5.1 standard library subset: base, string, table, math, os.

The functions filter scripts actually lean on — string mangling
(incl. full pattern-based find/match/gmatch/gsub/format), table
manipulation, math, os.time/date/clock. Reference scope: what LuaJIT
exposes to filter_lua scripts via src/flb_lua.c.
"""

from __future__ import annotations

import math
import time as _time
from typing import Any, List

from . import patterns
from .interp import (
    LuaError,
    LuaFunction,
    LuaTable,
    adjust,
    call_value,
    fmt_number,
    lua_eq,
    lua_tostring,
    lua_type,
    tonumber,
    truthy,
)


def _s(v, fn: str, arg: int = 1) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, float):
        return fmt_number(v)
    raise LuaError(f"bad argument #{arg} to '{fn}' "
                   f"(string expected, got {lua_type(v)})")


def _n(v, fn: str, arg: int = 1) -> float:
    x = tonumber(v)
    if x is None:
        raise LuaError(f"bad argument #{arg} to '{fn}' "
                       f"(number expected, got {lua_type(v)})")
    return x


def _t(v, fn: str, arg: int = 1) -> LuaTable:
    if not isinstance(v, LuaTable):
        raise LuaError(f"bad argument #{arg} to '{fn}' "
                       f"(table expected, got {lua_type(v)})")
    return v


def _str_index(s: str, i: float, default: int) -> int:
    """Lua string index → Python offset (1-based, negatives from end)."""
    i = int(i) if i is not None else default
    if i < 0:
        i = max(len(s) + i + 1, 1)
    elif i == 0:
        i = 1
    return i


# ------------------------------------------------------------ string

def _string_sub(s, i=1.0, j=-1.0):
    s = _s(s, "sub")
    start = _str_index(s, i, 1)
    jj = int(j) if j is not None else -1
    if jj < 0:
        jj = len(s) + jj + 1
    jj = min(jj, len(s))
    if start > jj:
        return ""
    return s[start - 1:jj]


def _string_find(s, pat, init=1.0, plain=None):
    s = _s(s, "find")
    pat = _s(pat, "find", 2)
    start = _str_index(s, init, 1) - 1
    if start > len(s):
        return None
    if truthy(plain):
        idx = s.find(pat, start)
        if idx < 0:
            return None
        return [float(idx + 1), float(idx + len(pat))]
    m = patterns.find(s, pat, start)
    if m is None:
        return None
    st, en, caps = m
    return [float(st + 1), float(en)] + caps


def _string_match(s, pat, init=1.0):
    s = _s(s, "match")
    pat = _s(pat, "match", 2)
    start = _str_index(s, init, 1) - 1
    m = patterns.find(s, pat, start)
    if m is None:
        return None
    st, en, caps = m
    return caps if caps else s[st:en]


def _string_gmatch(s, pat):
    s = _s(s, "gmatch")
    pat = _s(pat, "gmatch", 2)
    pos = [0]

    def it(*_args):
        while pos[0] <= len(s):
            m = patterns.find(s, pat, pos[0])
            if m is None:
                return None
            st, en, caps = m
            pos[0] = en + 1 if en == st else en  # empty match advances
            return caps if caps else s[st:en]
        return None

    return it


def _gsub_value(repl_out, orig: str):
    if repl_out is None or repl_out is False:
        return orig
    if isinstance(repl_out, (str, float)):
        return lua_tostring(repl_out)
    raise LuaError("invalid replacement value (a "
                   f"{lua_type(repl_out)})")


def _string_gsub(s, pat, repl, n=None):
    s = _s(s, "gsub")
    pat = _s(pat, "gsub", 2)
    limit = int(_n(n, "gsub", 4)) if n is not None else -1
    out: List[str] = []
    pos = 0
    count = 0
    while (limit < 0 or count < limit) and pos <= len(s):
        m = patterns.find(s, pat, pos)
        if m is None:
            break
        st, en, caps = m
        out.append(s[pos:st])
        whole = s[st:en]
        eff_caps = caps if caps else [whole]
        if isinstance(repl, str) or isinstance(repl, float):
            rs = lua_tostring(repl)
            buf = []
            i = 0
            while i < len(rs):
                c = rs[i]
                if c == "%" and i + 1 < len(rs):
                    d = rs[i + 1]
                    if d == "0":
                        buf.append(whole)
                    elif d.isdigit():
                        idx = int(d) - 1
                        if idx >= len(eff_caps):
                            raise LuaError(
                                f"invalid capture index %{d} in "
                                "replacement string")
                        buf.append(lua_tostring(eff_caps[idx]))
                    else:
                        buf.append(d)
                    i += 2
                else:
                    buf.append(c)
                    i += 1
            out.append("".join(buf))
        elif isinstance(repl, LuaTable):
            out.append(_gsub_value(repl.get(eff_caps[0]), whole))
        elif callable(repl) or isinstance(repl, LuaFunction):
            r = adjust(call_value(repl, list(eff_caps)))
            out.append(_gsub_value(r, whole))
        else:
            raise LuaError("bad argument #3 to 'gsub' "
                           "(string/function/table expected)")
        count += 1
        if en == st:  # empty match: copy one char and advance
            if st < len(s):
                out.append(s[st])
            pos = st + 1
        else:
            pos = en
    out.append(s[pos:])
    return ["".join(out), float(count)]


def _string_format(fmt, *args):
    fmt = _s(fmt, "format")
    out = []
    i = 0
    ai = 0
    args = list(args)
    while i < len(fmt):
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        j = i + 1
        while j < len(fmt) and fmt[j] in "-+ #0123456789.":
            j += 1
        if j >= len(fmt):
            raise LuaError("invalid format string to 'format'")
        conv = fmt[j]
        spec = fmt[i:j + 1]
        i = j + 1
        if conv == "%":
            out.append("%")
            continue
        arg = args[ai] if ai < len(args) else None
        ai += 1
        if conv in "di":
            out.append((spec[:-1] + "d") % int(_n(arg, "format", ai)))
        elif conv == "u":
            out.append((spec[:-1] + "d") % int(_n(arg, "format", ai)))
        elif conv in "fFgGeE":
            out.append(spec % _n(arg, "format", ai))
        elif conv in "xXo":
            out.append(spec % int(_n(arg, "format", ai)))
        elif conv == "c":
            out.append(chr(int(_n(arg, "format", ai))))
        elif conv == "s":
            out.append(spec % lua_tostring(arg))
        elif conv == "q":
            q = lua_tostring(arg)
            esc = q.replace("\\", "\\\\").replace('"', '\\"') \
                   .replace("\n", "\\n").replace("\r", "\\r") \
                   .replace("\0", "\\0")
            out.append(f'"{esc}"')
        else:
            raise LuaError(
                f"invalid option '%{conv}' to 'format'")
    return "".join(out)


STRING_LIB = {
    "len": lambda s=None: float(len(_s(s, "len"))),
    "sub": _string_sub,
    "upper": lambda s=None: _s(s, "upper").upper(),
    "lower": lambda s=None: _s(s, "lower").lower(),
    "rep": lambda s=None, n=0.0: _s(s, "rep") * int(_n(n, "rep", 2)),
    "reverse": lambda s=None: _s(s, "reverse")[::-1],
    "byte": lambda s=None, i=1.0, j=None: [
        float(ord(ch)) for ch in _string_sub(
            s, i, j if j is not None else i)],
    "char": lambda *a: "".join(chr(int(_n(x, "char", k + 1)))
                               for k, x in enumerate(a)),
    "find": _string_find,
    "match": _string_match,
    "gmatch": _string_gmatch,
    "gsub": _string_gsub,
    "format": _string_format,
}


# ------------------------------------------------------------- table

def _table_insert(t, a=None, b=None):
    t = _t(t, "insert")
    n = t.length()
    if b is None:
        t.set(float(n + 1), a)
    else:
        pos = int(_n(a, "insert", 2))
        for k in range(n, pos - 1, -1):
            t.set(float(k + 1), t.get(float(k)))
        t.set(float(pos), b)


def _table_remove(t, pos=None):
    t = _t(t, "remove")
    n = t.length()
    if n == 0:
        return None
    p = int(_n(pos, "remove", 2)) if pos is not None else n
    v = t.get(float(p))
    for k in range(p, n):
        t.set(float(k), t.get(float(k + 1)))
    t.set(float(n), None)
    return v


def _table_concat(t, sep="", i=1.0, j=None):
    t = _t(t, "concat")
    sep = _s(sep, "concat", 2) if sep != "" else ""
    jj = int(_n(j, "concat", 4)) if j is not None else t.length()
    parts = []
    for k in range(int(_n(i, "concat", 3)), jj + 1):
        v = t.get(float(k))
        if not isinstance(v, (str, float)):
            raise LuaError(f"invalid value (at index {k}) in table "
                           "for 'concat'")
        parts.append(lua_tostring(v))
    return sep.join(parts)


def _table_sort(t, comp=None):
    t = _t(t, "sort")
    n = t.length()
    items = [t.get(float(k)) for k in range(1, n + 1)]
    if comp is not None:
        import functools

        def cmp(a, b):
            if truthy(adjust(call_value(comp, [a, b]))):
                return -1
            if truthy(adjust(call_value(comp, [b, a]))):
                return 1
            return 0

        items.sort(key=functools.cmp_to_key(cmp))
    else:
        try:
            items.sort()
        except TypeError:
            raise LuaError("attempt to compare incompatible values in "
                           "'sort'")
    for k, v in enumerate(items):
        t.set(float(k + 1), v)


# -------------------------------------------------------------- base

def _next(t, key=None):
    t = _t(t, "next")
    keys = list(t.hash.keys())
    if key is None:
        idx = 0
    else:
        from .interp import _normkey
        try:
            idx = keys.index(_normkey(key)) + 1
        except ValueError:
            raise LuaError("invalid key to 'next'")
    if idx >= len(keys):
        return None
    k = keys[idx]
    out_k = float(k) if isinstance(k, int) else (
        k[1] if isinstance(k, tuple) else k)
    return [out_k, t.hash[k]]


def _pairs(t, *_):
    """Stateful iterator closure: O(1) per step (the standalone `next`
    global keeps stateless semantics for explicit callers, but pairs()
    iteration is on the filter hot path)."""
    t = _t(t, "pairs")
    it = iter(list(t.hash.items()))

    def step(*_a):
        for k, v in it:
            out_k = float(k) if isinstance(k, int) else (
                k[1] if isinstance(k, tuple) else k)
            return [out_k, v]
        return None

    return [step, t, None]


def _ipairs_iter(t, i):
    i = (i or 0.0) + 1
    v = t.get(i)
    if v is None:
        return None
    return [float(i), v]


def _ipairs(t, *_):
    return [_ipairs_iter, _t(t, "ipairs"), 0.0]


def _pcall(f=None, *args):
    try:
        return [True] + call_value(f, list(args))
    except LuaError as e:
        return [False, e.value]
    except (ZeroDivisionError, RecursionError, TypeError,
            ValueError, AttributeError, IndexError, KeyError) as e:
        return [False, f"runtime error: {e}"]


def _xpcall(f=None, handler=None, *args):
    try:
        return [True] + call_value(f, list(args))
    except LuaError as e:
        return [False] + call_value(handler, [e.value])


def _error(msg=None, _level=None):
    if isinstance(msg, str):
        raise LuaError("script: " + msg)
    raise LuaError(msg)


def _assert(v=None, msg=None, *rest):
    if not truthy(v):
        _error(msg if msg is not None else "assertion failed!")
    return [v, msg] + list(rest) if msg is not None else [v]


def _select(n=None, *args):
    if n == "#":
        return float(len(args))
    i = int(_n(n, "select"))
    if i < 0:
        i = len(args) + i + 1
    if i < 1:
        raise LuaError("bad argument #1 to 'select' (index out of range)")
    return list(args[i - 1:])


def _unpack(t, i=1.0, j=None):
    t = _t(t, "unpack")
    jj = int(_n(j, "unpack", 3)) if j is not None else t.length()
    return [t.get(float(k)) for k in range(int(_n(i, "unpack", 2)),
                                           jj + 1)]


def _setmetatable(t=None, mt=None):
    t = _t(t, "setmetatable")
    if mt is not None and not isinstance(mt, LuaTable):
        raise LuaError("bad argument #2 to 'setmetatable' "
                       "(nil or table expected)")
    t.metatable = mt
    return t


def _getmetatable(t=None):
    if isinstance(t, LuaTable) and t.metatable is not None:
        return t.metatable.hash.get("__metatable", t.metatable)
    return None


def _rawget(t=None, k=None):
    from .interp import _normkey
    return _t(t, "rawget").hash.get(_normkey(k))


def _rawset(t=None, k=None, v=None):
    _t(t, "rawset").set(k, v)
    return t


def _rawequal(a=None, b=None):
    return lua_eq(a, b)


def _print(*args):
    print("\t".join(lua_tostring(a) for a in args))


# ---------------------------------------------------------------- os

def _os_time(t=None):
    if isinstance(t, LuaTable):
        import calendar
        def g(k, d=None):
            v = t.get(k)
            return int(v) if v is not None else d
        try:
            return float(_time.mktime((
                g("year"), g("month"), g("day"),
                g("hour", 12), g("min", 0), g("sec", 0), 0, 0,
                -1 if t.get("isdst") is None else int(truthy(t.get("isdst"))),
            )))
        except (ValueError, OverflowError):
            return None
    return float(int(_time.time()))


def _os_date(fmt="%c", t=None):
    fmt = _s(fmt, "date") if fmt is not None else "%c"
    when = _n(t, "date", 2) if t is not None else _time.time()
    utc = fmt.startswith("!")
    if utc:
        fmt = fmt[1:]
    st = _time.gmtime(when) if utc else _time.localtime(when)
    if fmt.startswith("*t"):
        out = LuaTable()
        out.set("year", float(st.tm_year))
        out.set("month", float(st.tm_mon))
        out.set("day", float(st.tm_mday))
        out.set("hour", float(st.tm_hour))
        out.set("min", float(st.tm_min))
        out.set("sec", float(st.tm_sec))
        out.set("wday", float(st.tm_wday + 2 if st.tm_wday < 6 else 1.0))
        out.set("yday", float(st.tm_yday))
        out.set("isdst", st.tm_isdst > 0)
        return out
    return _time.strftime(fmt, st)


# ------------------------------------------------------------ export

def _lib(d: dict) -> LuaTable:
    t = LuaTable()
    for k, v in d.items():
        t.set(k, v)
    return t


def make_globals() -> dict:
    import random as _random
    math_lib = {
        "floor": lambda x=None: float(math.floor(_n(x, "floor"))),
        "ceil": lambda x=None: float(math.ceil(_n(x, "ceil"))),
        "abs": lambda x=None: abs(_n(x, "abs")),
        "max": lambda *a: max(_n(x, "max", i + 1)
                              for i, x in enumerate(a)),
        "min": lambda *a: min(_n(x, "min", i + 1)
                              for i, x in enumerate(a)),
        "sqrt": lambda x=None: math.sqrt(_n(x, "sqrt")),
        "pow": lambda x=None, y=None: float(_n(x, "pow")
                                            ** _n(y, "pow", 2)),
        "exp": lambda x=None: math.exp(_n(x, "exp")),
        "log": lambda x=None, b=None: (
            math.log(_n(x, "log"), _n(b, "log", 2)) if b is not None
            else math.log(_n(x, "log"))),
        "log10": lambda x=None: math.log10(_n(x, "log10")),
        "sin": lambda x=None: math.sin(_n(x, "sin")),
        "cos": lambda x=None: math.cos(_n(x, "cos")),
        "tan": lambda x=None: math.tan(_n(x, "tan")),
        "fmod": lambda x=None, y=None: math.fmod(_n(x, "fmod"),
                                                 _n(y, "fmod", 2)),
        "modf": lambda x=None: list(
            (lambda f: [float(int(f)) if f >= 0 else -float(int(-f)),
                        f - (float(int(f)) if f >= 0
                             else -float(int(-f)))])(_n(x, "modf"))),
        "huge": math.inf,
        "pi": math.pi,
        "random": lambda m=None, n=None: (
            _random.random() if m is None else
            float(_random.randint(1, int(_n(m, "random")))) if n is None
            else float(_random.randint(int(_n(m, "random")),
                                       int(_n(n, "random", 2))))),
        "randomseed": lambda x=None: _random.seed(
            _n(x, "randomseed") if x is not None else None),
    }
    os_lib = {
        "time": _os_time,
        "date": _os_date,
        "clock": lambda: _time.process_time(),
        "getenv": lambda k=None: __import__("os").environ.get(
            _s(k, "getenv")),
    }
    table_lib = {
        "insert": _table_insert,
        "remove": _table_remove,
        "concat": _table_concat,
        "sort": _table_sort,
        "getn": lambda t=None: float(_t(t, "getn").length()),
    }
    g = {
        "print": _print,
        "type": lambda v=None: lua_type(v),
        "tostring": lambda v=None: lua_tostring(v),
        "tonumber": lambda v=None, b=None: tonumber(v, b),
        "pairs": _pairs,
        "ipairs": _ipairs,
        "next": _next,
        "select": _select,
        "unpack": _unpack,
        "error": _error,
        "assert": _assert,
        "pcall": _pcall,
        "xpcall": _xpcall,
        "setmetatable": _setmetatable,
        "getmetatable": _getmetatable,
        "rawget": _rawget,
        "rawset": _rawset,
        "rawequal": _rawequal,
        "rawlen": lambda t=None: float(_t(t, "rawlen").length()),
        "string": _lib(STRING_LIB),
        "table": _lib(table_lib),
        "math": _lib(math_lib),
        "os": _lib(os_lib),
        "_VERSION": "Lua 5.1",
    }
    return g
