"""Lua 5.1 tree-walking interpreter.

Values map: nil→None, boolean→bool, number→float, string→str,
table→LuaTable, function→LuaFunction | Python callable. Multiple
returns travel as Python lists at call/return boundaries; expression
contexts truncate to the first value (adjust()).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from .parser import parse


class LuaError(Exception):
    """error() / runtime faults; .value is the Lua error value."""

    def __init__(self, value):
        super().__init__(lua_tostring(value) if not isinstance(value, str)
                         else value)
        self.value = value


class _Break(Exception):
    pass


class _Return(Exception):
    def __init__(self, values: List[Any]):
        self.values = values


def _normkey(k):
    """Table keys: float with integral value folds to int (Lua numbers
    are doubles; 1 and 1.0 are the same key)."""
    if isinstance(k, float) and k.is_integer():
        return int(k)
    if isinstance(k, bool):  # True is not 1 in Lua tables
        return ("bool", k)
    return k


class LuaTable:
    __slots__ = ("hash", "metatable")

    def __init__(self):
        self.hash: Dict[Any, Any] = {}
        self.metatable: Optional["LuaTable"] = None

    def get(self, key):
        v = self.hash.get(_normkey(key))
        if v is None and self.metatable is not None:
            idx = self.metatable.hash.get("__index")
            if isinstance(idx, LuaTable):
                return idx.get(key)
            if callable(idx) or isinstance(idx, LuaFunction):
                return adjust(call_value(idx, [self, key]))
        return v

    def set(self, key, value):
        if key is None:
            raise LuaError("table index is nil")
        if isinstance(key, float) and math.isnan(key):
            raise LuaError("table index is NaN")
        k = _normkey(key)
        if value is None:
            self.hash.pop(k, None)
        else:
            self.hash[k] = value

    def length(self) -> int:
        """'#': a border — count consecutive integer keys from 1."""
        n = 0
        while (n + 1) in self.hash:
            n += 1
        return n

    def py_items(self):
        return self.hash.items()


class LuaFunction:
    __slots__ = ("params", "is_vararg", "body", "scope", "name")

    def __init__(self, params, is_vararg, body, scope, name="?"):
        self.params = params
        self.is_vararg = is_vararg
        self.body = body
        self.scope = scope
        self.name = name


class Scope:
    __slots__ = ("vars", "parent")

    def __init__(self, parent: Optional["Scope"]):
        self.vars: Dict[str, Any] = {}
        self.parent = parent

    def lookup(self, name: str) -> Optional["Scope"]:
        s = self
        while s is not None:
            if name in s.vars:
                return s
            s = s.parent
        return None


def truthy(v) -> bool:
    return v is not None and v is not False


def adjust(values) -> Any:
    """Multi-value → single value."""
    if isinstance(values, list):
        return values[0] if values else None
    return values


def lua_tostring(v) -> str:
    if v is None:
        return "nil"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float):
        return fmt_number(v)
    if isinstance(v, str):
        return v
    if isinstance(v, LuaTable):
        if v.metatable is not None:
            ts = v.metatable.hash.get("__tostring")
            if ts is not None:
                return adjust(call_value(ts, [v]))
        return f"table: 0x{id(v):012x}"
    if isinstance(v, LuaFunction) or callable(v):
        return f"function: 0x{id(v):012x}"
    return str(v)


def fmt_number(v: float) -> str:
    """Lua's %.14g number formatting."""
    if v != v:
        return "nan" if not repr(v).startswith("-") else "-nan"
    if v == math.inf:
        return "inf"
    if v == -math.inf:
        return "-inf"
    if v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.14g}"


def lua_type(v) -> str:
    if v is None:
        return "nil"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, float):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, LuaTable):
        return "table"
    return "function"


def tonumber(v, base=None):
    if base is not None:
        try:
            return float(int(str(v).strip(), int(base)))
        except (ValueError, TypeError):
            return None
    if isinstance(v, float):
        return v
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, str):
        s = v.strip()
        try:
            if s.lower().startswith(("0x", "-0x")):
                return float(int(s, 16))
            return float(s)
        except ValueError:
            return None
    return None


def _arith_num(v, op):
    n = tonumber(v)
    if n is None:
        raise LuaError(
            f"attempt to perform arithmetic ({op}) on a {lua_type(v)} value")
    return n


def call_value(fn, args: List[Any]) -> List[Any]:
    """Invoke a Lua or Python function with a Lua argument list; always
    returns a Python list of return values."""
    if isinstance(fn, LuaFunction):
        scope = Scope(fn.scope)
        for i, p in enumerate(fn.params):
            scope.vars[p] = args[i] if i < len(args) else None
        if fn.is_vararg:
            scope.vars["..."] = args[len(fn.params):]
        try:
            exec_block(fn.body, scope)
        except _Return as r:
            return r.values
        return []
    if callable(fn):
        res = fn(*args)
        if isinstance(res, list):
            return res
        if res is None:
            return []
        return [res]
    if isinstance(fn, LuaTable) and fn.metatable is not None:
        call = fn.metatable.hash.get("__call")
        if call is not None:
            return call_value(call, [fn] + args)
    raise LuaError(f"attempt to call a {lua_type(fn)} value")


# ------------------------------------------------------- interpreter


def exec_block(block: list, scope: Scope) -> None:
    for st in block:
        exec_stmt(st, scope)


def exec_stmt(st: tuple, scope: Scope) -> None:
    op = st[0]
    if op == "callstat":
        eval_multi(st[1], scope)
    elif op == "local":
        _names, exprs = st[1], st[2]
        vals = eval_exprlist(exprs, scope)
        for i, name in enumerate(_names):
            scope.vars[name] = vals[i] if i < len(vals) else None
    elif op == "assign":
        targets, exprs = st[1], st[2]
        vals = eval_exprlist(exprs, scope)
        for i, tg in enumerate(targets):
            v = vals[i] if i < len(vals) else None
            if tg[0] == "name":
                s = scope.lookup(tg[1])
                if s is None:
                    g = scope
                    while g.parent is not None:
                        g = g.parent
                    g.vars[tg[1]] = v
                else:
                    s.vars[tg[1]] = v
            else:  # index
                obj = eval_expr(tg[1], scope)
                key = eval_expr(tg[2], scope)
                settable(obj, key, v)
    elif op == "if":
        for cond, body in st[1]:
            if truthy(eval_expr(cond, scope)):
                exec_block(body, Scope(scope))
                return
        exec_block(st[2], Scope(scope))
    elif op == "while":
        while truthy(eval_expr(st[1], scope)):
            try:
                exec_block(st[2], Scope(scope))
            except _Break:
                break
    elif op == "repeat":
        while True:
            inner = Scope(scope)
            try:
                exec_block(st[1], inner)
            except _Break:
                break
            # until sees the body's locals (manual §2.4.4)
            if truthy(eval_expr(st[2], inner)):
                break
    elif op == "fornum":
        _, var, e1, e2, e3, body, _line = st
        i = _arith_num(eval_expr(e1, scope), "for")
        stop = _arith_num(eval_expr(e2, scope), "for")
        step = _arith_num(eval_expr(e3, scope), "for")
        if step == 0:
            raise LuaError("'for' step is zero")
        while (step > 0 and i <= stop) or (step < 0 and i >= stop):
            inner = Scope(scope)
            inner.vars[var] = i
            try:
                exec_block(body, inner)
            except _Break:
                break
            i += step
    elif op == "forin":
        _, names, exprs, body, _line = st
        vals = eval_exprlist(exprs, scope)
        f = vals[0] if len(vals) > 0 else None
        s = vals[1] if len(vals) > 1 else None
        ctrl = vals[2] if len(vals) > 2 else None
        while True:
            rets = call_value(f, [s, ctrl])
            first = rets[0] if rets else None
            if first is None:
                break
            ctrl = first
            inner = Scope(scope)
            for i, name in enumerate(names):
                inner.vars[name] = rets[i] if i < len(rets) else None
            try:
                exec_block(body, inner)
            except _Break:
                break
    elif op == "do":
        exec_block(st[1], Scope(scope))
    elif op == "return":
        raise _Return(eval_exprlist(st[1], scope))
    elif op == "break":
        raise _Break()
    elif op == "localfunc":
        _, name, fnexpr, _line = st
        scope.vars[name] = None  # visible to itself (recursion)
        fn = eval_expr(fnexpr, scope)
        fn.name = name
        scope.vars[name] = fn
    else:  # pragma: no cover
        raise LuaError(f"unknown statement {op}")


def settable(obj, key, value) -> None:
    if isinstance(obj, LuaTable):
        if obj.metatable is not None and _normkey(key) not in obj.hash:
            ni = obj.metatable.hash.get("__newindex")
            if isinstance(ni, LuaTable):
                return settable(ni, key, value)
            if ni is not None:
                call_value(ni, [obj, key, value])
                return
        obj.set(key, value)
        return
    raise LuaError(f"attempt to index a {lua_type(obj)} value")


def gettable(obj, key):
    if isinstance(obj, LuaTable):
        return obj.get(key)
    if isinstance(obj, str):
        # strings carry the string library as methods (s:upper())
        from .stdlib import STRING_LIB
        return STRING_LIB.get(key)
    raise LuaError(f"attempt to index a {lua_type(obj)} value")


def eval_exprlist(exprs: List[tuple], scope: Scope) -> List[Any]:
    """Lua adjustment: every expr but the last yields one value; the
    last expands if it is a call/vararg."""
    vals: List[Any] = []
    for i, e in enumerate(exprs):
        if i == len(exprs) - 1:
            last = eval_multi(e, scope)
            vals.extend(last if isinstance(last, list) else [last])
        else:
            vals.append(eval_expr(e, scope))
    return vals


def eval_multi(e: tuple, scope: Scope):
    """Evaluate where multiple values are allowed (returns list for
    calls/varargs, scalar otherwise)."""
    op = e[0]
    if op == "call":
        fn = eval_expr(e[1], scope)
        args = eval_exprlist(e[2], scope)
        return call_value(fn, args)
    if op == "method":
        obj = eval_expr(e[1], scope)
        fn = gettable(obj, e[2])
        args = [obj] + eval_exprlist(e[3], scope)
        return call_value(fn, args)
    if op == "vararg":
        s = scope.lookup("...")
        return list(s.vars["..."]) if s else []
    return eval_expr(e, scope)


def eval_expr(e: tuple, scope: Scope) -> Any:
    op = e[0]
    if op == "num":
        return e[1]
    if op == "str":
        return e[1]
    if op == "nil":
        return None
    if op == "true":
        return True
    if op == "false":
        return False
    if op == "name":
        s = scope.lookup(e[1])
        return s.vars[e[1]] if s else None
    if op == "paren":
        return adjust(eval_multi(e[1], scope))
    if op == "index":
        return gettable(eval_expr(e[1], scope), eval_expr(e[2], scope))
    if op in ("call", "method", "vararg"):
        return adjust(eval_multi(e, scope))
    if op == "func":
        return LuaFunction(e[1], e[2], e[3], scope)
    if op == "table":
        t = LuaTable()
        _, array, hash_ = e
        idx = 1
        for i, item in enumerate(array):
            if i == len(array) - 1:
                last = eval_multi(item, scope)
                if isinstance(last, list):
                    for v in last:
                        t.set(float(idx), v)
                        idx += 1
                    continue
                t.set(float(idx), last)
            else:
                t.set(float(idx), eval_expr(item, scope))
            idx += 1
        for k, v in hash_:
            t.set(eval_expr(k, scope), eval_expr(v, scope))
        return t
    if op == "binop":
        return eval_binop(e, scope)
    if op == "unop":
        o, v = e[1], eval_expr(e[2], scope)
        if o == "-":
            return -_arith_num(v, "unm")
        if o == "not":
            return not truthy(v)
        if o == "#":
            if isinstance(v, str):
                return float(len(v))
            if isinstance(v, LuaTable):
                return float(v.length())
            raise LuaError(f"attempt to get length of a {lua_type(v)} value")
    raise LuaError(f"unknown expression {op}")  # pragma: no cover


_NUM_OPS = {"+", "-", "*", "/", "%", "^"}
_CMP_OPS = {"<", ">", "<=", ">="}


def eval_binop(e: tuple, scope: Scope) -> Any:
    op = e[1]
    if op == "and":
        left = eval_expr(e[2], scope)
        return eval_expr(e[3], scope) if truthy(left) else left
    if op == "or":
        left = eval_expr(e[2], scope)
        return left if truthy(left) else eval_expr(e[3], scope)
    left = eval_expr(e[2], scope)
    right = eval_expr(e[3], scope)
    if op in _NUM_OPS:
        ln = _arith_num(left, op)
        rn = _arith_num(right, op)
        if op == "+":
            return ln + rn
        if op == "-":
            return ln - rn
        if op == "*":
            return ln * rn
        if op == "/":
            if rn == 0:
                return math.inf if ln > 0 else (-math.inf if ln < 0
                                                else math.nan)
            return ln / rn
        if op == "%":
            if rn == 0:
                return math.nan
            return ln - math.floor(ln / rn) * rn
        if op == "^":
            return float(ln ** rn)
    if op == "..":
        for v in (left, right):
            if not isinstance(v, (str, float)):
                raise LuaError(
                    f"attempt to concatenate a {lua_type(v)} value")
        ls = fmt_number(left) if isinstance(left, float) else left
        rs = fmt_number(right) if isinstance(right, float) else right
        return ls + rs
    if op == "==":
        return lua_eq(left, right)
    if op == "~=":
        return not lua_eq(left, right)
    if op in _CMP_OPS:
        if isinstance(left, float) and isinstance(right, float):
            pass
        elif isinstance(left, str) and isinstance(right, str):
            pass
        else:
            raise LuaError(
                f"attempt to compare {lua_type(left)} with "
                f"{lua_type(right)}")
        if op == "<":
            return left < right
        if op == ">":
            return left > right
        if op == "<=":
            return left <= right
        return left >= right
    raise LuaError(f"unknown operator {op}")  # pragma: no cover


def lua_eq(a, b) -> bool:
    if a is None and b is None:
        return True
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, float) and isinstance(b, float):
        return a == b
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    return a is b


# --------------------------------------------------------- public API


class LuaRuntime:
    """One Lua state: load scripts into a shared global scope, call
    global functions (the flb_luajit_load_script + lua_pcall surface)."""

    def __init__(self):
        from .stdlib import make_globals
        self.globals = Scope(None)
        self.globals.vars.update(make_globals())
        # _G shares the global scope's dict: assignments through either
        # surface are visible to both
        gt = LuaTable()
        gt.hash = self.globals.vars
        self.globals.vars["_G"] = gt

    def load(self, src: str, name: str = "script") -> None:
        """Parse + run a chunk at global scope (function definitions
        land in globals)."""
        try:
            block = parse(src)
        except SyntaxError as e:
            raise LuaError(f"{name}: {e}")
        try:
            exec_block(block, self.globals)
        except _Return:
            pass

    def call(self, name: str, args: List[Any]) -> List[Any]:
        fn = self.globals.vars.get(name)
        if fn is None:
            raise LuaError(f"attempt to call a nil value (global '{name}')")
        return call_value(fn, list(args))

    def eval(self, src: str):
        """Convenience for tests: evaluate 'return <expr>'."""
        block = parse(src)
        try:
            exec_block(block, self.globals)
        except _Return as r:
            return r.values
        return []
