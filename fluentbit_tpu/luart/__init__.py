"""From-scratch Lua 5.1 runtime for filter_lua.

The reference embeds LuaJIT (lib/luajit-7152e154 via src/flb_luajit.c);
this package interprets the language directly — lexer/parser
(lexer.py, parser.py), tree-walking evaluator (interp.py), the stdlib
subset scripts rely on (stdlib.py) including full Lua pattern matching
(patterns.py). Python↔Lua value bridging mirrors flb_lua.c's
msgpack↔lua conversions (flb_lua_pushmsgpack / flb_lua_tomsgpack).
"""

from __future__ import annotations

from typing import Any

from .interp import (  # noqa: F401
    LuaError,
    LuaFunction,
    LuaRuntime,
    LuaTable,
    lua_tostring,
)


def py_to_lua(v: Any):
    """Python (decoded msgpack record) → Lua value (flb_lua_pushmsgpack,
    src/flb_lua.c). Dicts/lists become tables; numbers become Lua
    numbers (doubles); bytes decode as UTF-8 with replacement."""
    if v is None or isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        return v
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, dict):
        t = LuaTable()
        for k, val in v.items():
            t.set(py_to_lua(k), py_to_lua(val))
        return t
    if isinstance(v, (list, tuple)):
        t = LuaTable()
        for i, val in enumerate(v):
            t.set(float(i + 1), py_to_lua(val))
        return t
    return str(v)


def lua_to_py(v: Any):
    """Lua value → Python (flb_lua_tomsgpack): a table whose keys are
    exactly 1..n becomes a list, otherwise a dict; integral floats
    become ints (so msgpack re-encodes them compactly, matching the
    reference's dual int/double packing)."""
    if v is None or isinstance(v, bool) or isinstance(v, str):
        return v
    if isinstance(v, float):
        return int(v) if v.is_integer() and abs(v) < 2 ** 63 else v
    if isinstance(v, LuaTable):
        keys = list(v.hash.keys())
        n = v.length()
        if keys and n == len(keys):
            return [lua_to_py(v.hash[i]) for i in range(1, n + 1)]
        out = {}
        for k, val in v.hash.items():
            if isinstance(k, tuple):  # normalized bool key
                k = k[1]
            if isinstance(k, int):
                key = k
            elif isinstance(k, float):
                key = int(k) if k.is_integer() else k
            else:
                key = k
            out[key if isinstance(key, str) else str(key)] = lua_to_py(val)
        return out
    return lua_tostring(v)
