"""Lua 5.1 pattern matching (the lstrlib.c match machine).

Not regex: classes %a %d %s %w etc., sets [], captures () incl.
position captures, anchors ^/$, quantifiers * + - ?, %b balanced
match, %f frontier. Powers string.find/match/gmatch/gsub in stdlib.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


class PatternError(ValueError):
    pass


_CLASS = {
    "a": lambda c: c.isalpha(),
    "c": lambda c: ord(c) < 32 or ord(c) == 127,
    "d": lambda c: c.isdigit(),
    "l": lambda c: c.islower(),
    "p": lambda c: 32 < ord(c) < 127 and not c.isalnum(),
    "s": lambda c: c in " \t\n\r\f\v",
    "u": lambda c: c.isupper(),
    "w": lambda c: c.isalnum(),
    "x": lambda c: c in "0123456789abcdefABCDEF",
}


def _match_class(c: str, cl: str) -> bool:
    f = _CLASS.get(cl.lower())
    if f is None:
        return c == cl  # escaped literal (%%, %., %()
    res = f(c)
    return res if cl.islower() else not res


def _class_end(p: str, pi: int) -> int:
    """Index just past the single pattern item starting at pi."""
    c = p[pi]
    pi += 1
    if c == "%":
        if pi >= len(p):
            raise PatternError("malformed pattern (ends with '%')")
        return pi + 1
    if c == "[":
        if pi < len(p) and p[pi] == "^":
            pi += 1
        # first ']' is literal
        first = True
        while True:
            if pi >= len(p):
                raise PatternError("malformed pattern (missing ']')")
            c = p[pi]
            pi += 1
            if c == "%":
                pi += 1
            elif c == "]" and not first:
                return pi
            first = False
    return pi


def _match_set(c: str, p: str, pi: int, ep: int) -> bool:
    """c against the set p[pi:ep] where p[pi]=='[' and p[ep-1]==']'."""
    pi += 1
    negate = False
    if p[pi] == "^":
        negate = True
        pi += 1
    found = False
    while pi < ep - 1:
        if p[pi] == "%":
            pi += 1
            if _match_class(c, p[pi]):
                found = True
            pi += 1
        elif pi + 2 < ep - 1 and p[pi + 1] == "-":
            if p[pi] <= c <= p[pi + 2]:
                found = True
            pi += 3
        else:
            if p[pi] == c:
                found = True
            pi += 1
    return found != negate


def _single_match(s: str, si: int, p: str, pi: int, ep: int) -> bool:
    if si >= len(s):
        return False
    c = s[si]
    pc = p[pi]
    if pc == ".":
        return True
    if pc == "%":
        return _match_class(c, p[pi + 1])
    if pc == "[":
        return _match_set(c, p, pi, ep)
    return pc == c


class _MatchState:
    __slots__ = ("s", "p", "caps")

    def __init__(self, s: str, p: str):
        self.s = s
        self.p = p
        # (start, len) — len == -1 while open, -2 for position capture
        self.caps: List[List[int]] = []


def _do_match(ms: _MatchState, si: int, pi: int) -> Optional[int]:
    s, p = ms.s, ms.p
    while True:
        if pi >= len(p):
            return si
        pc = p[pi]
        if pc == "(":
            if pi + 1 < len(p) and p[pi + 1] == ")":  # position capture
                ms.caps.append([si, -2])
                r = _do_match(ms, si, pi + 2)
                if r is None:
                    ms.caps.pop()
                return r
            ms.caps.append([si, -1])
            r = _do_match(ms, si, pi + 1)
            if r is None:
                ms.caps.pop()
            return r
        if pc == ")":
            for cap in reversed(ms.caps):
                if cap[1] == -1:
                    cap[1] = si - cap[0]
                    r = _do_match(ms, si, pi + 1)
                    if r is None:
                        cap[1] = -1
                    return r
            raise PatternError("invalid pattern capture")
        if pc == "$" and pi + 1 == len(p):
            return si if si == len(s) else None
        if pc == "%":
            nxt = p[pi + 1] if pi + 1 < len(p) else ""
            if nxt == "b":
                if pi + 3 >= len(p):
                    raise PatternError("missing arguments to %b")
                o, cch = p[pi + 2], p[pi + 3]
                if si >= len(s) or s[si] != o:
                    return None
                depth = 1
                j = si + 1
                while j < len(s):
                    if s[j] == cch:
                        depth -= 1
                        if depth == 0:
                            # tail continues after the balanced span
                            pi2 = pi + 4
                            r = _do_match(ms, j + 1, pi2)
                            return r
                    elif s[j] == o:
                        depth += 1
                    j += 1
                return None
            if nxt == "f":
                if pi + 2 >= len(p) or p[pi + 2] != "[":
                    raise PatternError("missing '[' after %f")
                ep = _class_end(p, pi + 2)
                prev = s[si - 1] if si > 0 else "\0"
                cur = s[si] if si < len(s) else "\0"
                if (not _match_set(prev, p, pi + 2, ep)
                        and _match_set(cur, p, pi + 2, ep)):
                    pi = ep
                    continue
                return None
            if nxt.isdigit():  # back-reference %1-%9
                idx = int(nxt) - 1
                if idx >= len(ms.caps) or ms.caps[idx][1] < 0:
                    raise PatternError(f"invalid capture index %{nxt}")
                cs, cl = ms.caps[idx]
                cap = s[cs:cs + cl]
                if s.startswith(cap, si):
                    si += len(cap)
                    pi += 2
                    continue
                return None
        ep = _class_end(p, pi)
        quant = p[ep] if ep < len(p) else ""
        if quant == "?":
            if _single_match(s, si, p, pi, ep):
                r = _do_match(ms, si + 1, ep + 1)
                if r is not None:
                    return r
            pi = ep + 1
            continue
        if quant == "*":
            count = 0
            while _single_match(s, si + count, p, pi, ep):
                count += 1
            while count >= 0:
                r = _do_match(ms, si + count, ep + 1)
                if r is not None:
                    return r
                count -= 1
            return None
        if quant == "+":
            count = 0
            while _single_match(s, si + count, p, pi, ep):
                count += 1
            while count >= 1:
                r = _do_match(ms, si + count, ep + 1)
                if r is not None:
                    return r
                count -= 1
            return None
        if quant == "-":
            while True:
                r = _do_match(ms, si, ep + 1)
                if r is not None:
                    return r
                if _single_match(s, si, p, pi, ep):
                    si += 1
                else:
                    return None
        if not _single_match(s, si, p, pi, ep):
            return None
        si += 1
        pi = ep


def find(s: str, pattern: str, init: int = 0):
    """→ (start, end, captures) with 0-based start, end-exclusive; or
    None. Captures are strings, or 1-based int for position captures."""
    anchored = pattern.startswith("^")
    p = pattern[1:] if anchored else pattern
    si = init
    while si <= len(s):
        ms = _MatchState(s, p)
        e = _do_match(ms, si, 0)
        if e is not None:
            caps = []
            for cs, cl in ms.caps:
                caps.append(float(cs + 1) if cl == -2 else s[cs:cs + cl])
            return si, e, caps
        if anchored:
            return None
        si += 1
    return None
