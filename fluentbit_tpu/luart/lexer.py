"""Lua 5.1 lexer.

Part of the from-scratch Lua runtime that backs plugins/filter_lua
(reference embeds LuaJIT via src/flb_luajit.c + lib/luajit-7152e154;
this build interprets the language directly — same stance as the regex
engine replacing Onigmo)."""

from __future__ import annotations

from typing import List, NamedTuple, Optional


class LuaSyntaxError(SyntaxError):
    pass


KEYWORDS = {
    "and", "break", "do", "else", "elseif", "end", "false", "for",
    "function", "if", "in", "local", "nil", "not", "or", "repeat",
    "return", "then", "true", "until", "while",
}

# longest-first so '..' beats '.' and '...' beats '..'
SYMBOLS = [
    "...", "..", "==", "~=", "<=", ">=", "+", "-", "*", "/", "%", "^",
    "#", "<", ">", "=", "(", ")", "{", "}", "[", "]", ";", ":", ",", ".",
]


class Token(NamedTuple):
    kind: str       # 'name' | 'number' | 'string' | 'keyword' | 'sym' | 'eof'
    value: object
    line: int


_ESCAPES = {"a": "\a", "b": "\b", "f": "\f", "n": "\n", "r": "\r",
            "t": "\t", "v": "\v", "\\": "\\", '"': '"', "'": "'",
            "\n": "\n"}


def _long_bracket_level(src: str, pos: int) -> Optional[int]:
    """At '[': return level if '[===[' style opener, else None."""
    if src[pos] != "[":
        return None
    i = pos + 1
    level = 0
    while i < len(src) and src[i] == "=":
        level += 1
        i += 1
    if i < len(src) and src[i] == "[":
        return level
    return None


def tokenize(src: str) -> List[Token]:
    toks: List[Token] = []
    i = 0
    n = len(src)
    line = 1
    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        # comments
        if src.startswith("--", i):
            i += 2
            level = _long_bracket_level(src, i) if i < n else None
            if level is not None:
                close = "]" + "=" * level + "]"
                end = src.find(close, i)
                if end < 0:
                    raise LuaSyntaxError(f"unfinished long comment at line {line}")
                line += src.count("\n", i, end)
                i = end + len(close)
            else:
                while i < n and src[i] != "\n":
                    i += 1
            continue
        # long strings
        level = _long_bracket_level(src, i)
        if level is not None:
            open_len = level + 2
            close = "]" + "=" * level + "]"
            start = i + open_len
            if start < n and src[start] == "\n":
                start += 1  # spec: leading newline dropped
                line += 1
            end = src.find(close, start)
            if end < 0:
                raise LuaSyntaxError(f"unfinished long string at line {line}")
            s = src[start:end]
            line += s.count("\n")
            toks.append(Token("string", s, line))
            i = end + len(close)
            continue
        # quoted strings
        if c in "'\"":
            quote = c
            i += 1
            buf = []
            while True:
                if i >= n:
                    raise LuaSyntaxError(f"unfinished string at line {line}")
                ch = src[i]
                if ch == quote:
                    i += 1
                    break
                if ch == "\n":
                    raise LuaSyntaxError(f"unfinished string at line {line}")
                if ch == "\\":
                    i += 1
                    if i >= n:
                        raise LuaSyntaxError(f"unfinished string at line {line}")
                    e = src[i]
                    if e in _ESCAPES:
                        buf.append(_ESCAPES[e])
                        if e == "\n":
                            line += 1
                        i += 1
                    elif e.isdigit():
                        num = e
                        i += 1
                        for _ in range(2):
                            if i < n and src[i].isdigit():
                                num += src[i]
                                i += 1
                            else:
                                break
                        code = int(num)
                        if code > 255:
                            raise LuaSyntaxError(
                                f"escape too large at line {line}")
                        buf.append(chr(code))
                    elif e == "x":  # 5.2 extension, commonly used
                        hexd = src[i + 1:i + 3]
                        try:
                            buf.append(chr(int(hexd, 16)))
                        except ValueError:
                            raise LuaSyntaxError(
                                f"hexadecimal digit expected at line {line}")
                        i += 3
                    else:
                        raise LuaSyntaxError(
                            f"invalid escape '\\{e}' at line {line}")
                else:
                    buf.append(ch)
                    i += 1
            toks.append(Token("string", "".join(buf), line))
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            start = i
            if src.startswith(("0x", "0X"), i):
                i += 2
                while i < n and (src[i] in "0123456789abcdefABCDEF"):
                    i += 1
                try:
                    num = float(int(src[start:i], 16))
                except ValueError:
                    raise LuaSyntaxError(
                        f"malformed number near '{src[start:i]}' "
                        f"line {line}")
                toks.append(Token("number", num, line))
                continue
            while i < n and src[i].isdigit():
                i += 1
            if i < n and src[i] == ".":
                i += 1
                while i < n and src[i].isdigit():
                    i += 1
            if i < n and src[i] in "eE":
                i += 1
                if i < n and src[i] in "+-":
                    i += 1
                while i < n and src[i].isdigit():
                    i += 1
            try:
                toks.append(Token("number", float(src[start:i]), line))
            except ValueError:
                raise LuaSyntaxError(
                    f"malformed number near '{src[start:i]}' line {line}")
            continue
        # names / keywords
        if c.isalpha() or c == "_":
            start = i
            while i < n and (src[i].isalnum() or src[i] == "_"):
                i += 1
            word = src[start:i]
            toks.append(Token("keyword" if word in KEYWORDS else "name",
                              word, line))
            continue
        # symbols
        for sym in SYMBOLS:
            if src.startswith(sym, i):
                toks.append(Token("sym", sym, line))
                i += len(sym)
                break
        else:
            raise LuaSyntaxError(
                f"unexpected character {c!r} at line {line}")
    toks.append(Token("eof", None, line))
    return toks
