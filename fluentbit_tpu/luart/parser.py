"""Lua 5.1 recursive-descent parser → tuple AST.

Grammar per the Lua 5.1 manual §8. AST nodes are plain tuples with a
string head — the interpreter (interp.py) dispatches on it.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .lexer import LuaSyntaxError, Token, tokenize

# binary precedence (left, right) — right > left for right-assoc ('..', '^')
_BINPREC = {
    "or": (1, 1), "and": (2, 2),
    "<": (3, 3), ">": (3, 3), "<=": (3, 3), ">=": (3, 3),
    "~=": (3, 3), "==": (3, 3),
    "..": (9, 8),  # right associative
    "+": (10, 10), "-": (10, 10),
    "*": (11, 11), "/": (11, 11), "%": (11, 11),
    "^": (14, 13),  # right associative, above unary
}
_UNARY_PREC = 12


class Parser:
    def __init__(self, src: str):
        self.toks = tokenize(src)
        self.pos = 0

    # ------------------------------------------------------- helpers

    @property
    def tok(self) -> Token:
        return self.toks[self.pos]

    def next(self) -> Token:
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def check(self, kind: str, value=None) -> bool:
        t = self.tok
        return t.kind == kind and (value is None or t.value == value)

    def accept(self, kind: str, value=None) -> Optional[Token]:
        if self.check(kind, value):
            return self.next()
        return None

    def expect(self, kind: str, value=None) -> Token:
        if not self.check(kind, value):
            t = self.tok
            raise LuaSyntaxError(
                f"line {t.line}: expected {value or kind}, got "
                f"{t.value!r}")
        return self.next()

    # --------------------------------------------------------- entry

    def parse_chunk(self) -> list:
        block = self.parse_block()
        self.expect("eof")
        return block

    _BLOCK_END = {"end", "else", "elseif", "until"}

    def parse_block(self) -> list:
        stmts = []
        while True:
            t = self.tok
            if t.kind == "eof" or (t.kind == "keyword"
                                   and t.value in self._BLOCK_END):
                return stmts
            if t.kind == "keyword" and t.value == "return":
                self.next()
                exprs = []
                if not (self.tok.kind == "eof"
                        or (self.tok.kind == "keyword"
                            and self.tok.value in self._BLOCK_END)
                        or self.check("sym", ";")):
                    exprs = self.parse_exprlist()
                self.accept("sym", ";")
                stmts.append(("return", exprs, t.line))
                return stmts
            st = self.parse_statement()
            if st is not None:
                stmts.append(st)

    # ---------------------------------------------------- statements

    def parse_statement(self):
        t = self.tok
        if self.accept("sym", ";"):
            return None
        if t.kind == "keyword":
            kw = t.value
            if kw == "break":
                self.next()
                return ("break", t.line)
            if kw == "do":
                self.next()
                body = self.parse_block()
                self.expect("keyword", "end")
                return ("do", body, t.line)
            if kw == "while":
                self.next()
                cond = self.parse_expr()
                self.expect("keyword", "do")
                body = self.parse_block()
                self.expect("keyword", "end")
                return ("while", cond, body, t.line)
            if kw == "repeat":
                self.next()
                body = self.parse_block()
                self.expect("keyword", "until")
                cond = self.parse_expr()
                return ("repeat", body, cond, t.line)
            if kw == "if":
                return self.parse_if()
            if kw == "for":
                return self.parse_for()
            if kw == "function":
                return self.parse_funcstat()
            if kw == "local":
                return self.parse_local()
            raise LuaSyntaxError(f"line {t.line}: unexpected '{kw}'")
        # exprstat: assignment or call
        expr = self.parse_suffixed()
        if self.check("sym", "=") or self.check("sym", ","):
            targets = [expr]
            while self.accept("sym", ","):
                targets.append(self.parse_suffixed())
            self.expect("sym", "=")
            exprs = self.parse_exprlist()
            for tg in targets:
                if tg[0] not in ("name", "index"):
                    raise LuaSyntaxError(
                        f"line {t.line}: cannot assign to this expression")
            return ("assign", targets, exprs, t.line)
        if expr[0] not in ("call", "method"):
            raise LuaSyntaxError(f"line {t.line}: syntax error near "
                                 f"{self.tok.value!r}")
        return ("callstat", expr, t.line)

    def parse_if(self):
        line = self.expect("keyword", "if").line
        arms = []
        cond = self.parse_expr()
        self.expect("keyword", "then")
        arms.append((cond, self.parse_block()))
        els: list = []
        while True:
            if self.accept("keyword", "elseif"):
                c = self.parse_expr()
                self.expect("keyword", "then")
                arms.append((c, self.parse_block()))
            elif self.accept("keyword", "else"):
                els = self.parse_block()
                self.expect("keyword", "end")
                break
            else:
                self.expect("keyword", "end")
                break
        return ("if", arms, els, line)

    def parse_for(self):
        line = self.expect("keyword", "for").line
        name1 = self.expect("name").value
        if self.accept("sym", "="):
            e1 = self.parse_expr()
            self.expect("sym", ",")
            e2 = self.parse_expr()
            e3 = ("num", 1.0) if not self.accept("sym", ",") \
                else self.parse_expr()
            self.expect("keyword", "do")
            body = self.parse_block()
            self.expect("keyword", "end")
            return ("fornum", name1, e1, e2, e3, body, line)
        names = [name1]
        while self.accept("sym", ","):
            names.append(self.expect("name").value)
        self.expect("keyword", "in")
        exprs = self.parse_exprlist()
        self.expect("keyword", "do")
        body = self.parse_block()
        self.expect("keyword", "end")
        return ("forin", names, exprs, body, line)

    def parse_funcstat(self):
        line = self.expect("keyword", "function").line
        target = ("name", self.expect("name").value)
        is_method = False
        while True:
            if self.accept("sym", "."):
                target = ("index", target, ("str",
                                            self.expect("name").value))
            elif self.accept("sym", ":"):
                target = ("index", target, ("str",
                                            self.expect("name").value))
                is_method = True
                break
            else:
                break
        fn = self.parse_funcbody(is_method)
        return ("assign", [target], [fn], line)

    def parse_local(self):
        line = self.expect("keyword", "local").line
        if self.accept("keyword", "function"):
            name = self.expect("name").value
            fn = self.parse_funcbody(False)
            return ("localfunc", name, fn, line)
        names = [self.expect("name").value]
        while self.accept("sym", ","):
            names.append(self.expect("name").value)
        exprs = self.parse_exprlist() if self.accept("sym", "=") else []
        return ("local", names, exprs, line)

    def parse_funcbody(self, is_method: bool):
        self.expect("sym", "(")
        params = ["self"] if is_method else []
        is_vararg = False
        if not self.check("sym", ")"):
            while True:
                if self.accept("sym", "..."):
                    is_vararg = True
                    break
                params.append(self.expect("name").value)
                if not self.accept("sym", ","):
                    break
        self.expect("sym", ")")
        body = self.parse_block()
        self.expect("keyword", "end")
        return ("func", params, is_vararg, body)

    # --------------------------------------------------- expressions

    def parse_exprlist(self) -> List[tuple]:
        exprs = [self.parse_expr()]
        while self.accept("sym", ","):
            exprs.append(self.parse_expr())
        return exprs

    def parse_expr(self, limit: int = 0):
        t = self.tok
        if (t.kind == "sym" and t.value in ("-", "#")) or \
                (t.kind == "keyword" and t.value == "not"):
            op = self.next().value
            operand = self.parse_expr(_UNARY_PREC)
            left = ("unop", op, operand)
        else:
            left = self.parse_simple()
        while True:
            t = self.tok
            op = t.value if (t.kind == "sym" or t.kind == "keyword") else None
            prec = _BINPREC.get(op)
            if prec is None or prec[0] <= limit:
                return left
            self.next()
            right = self.parse_expr(prec[1])
            left = ("binop", op, left, right)

    def parse_simple(self):
        t = self.tok
        if t.kind == "number":
            self.next()
            return ("num", t.value)
        if t.kind == "string":
            self.next()
            return ("str", t.value)
        if t.kind == "keyword":
            if t.value == "nil":
                self.next()
                return ("nil",)
            if t.value == "true":
                self.next()
                return ("true",)
            if t.value == "false":
                self.next()
                return ("false",)
            if t.value == "function":
                self.next()
                return self.parse_funcbody(False)
        if self.check("sym", "..."):
            self.next()
            return ("vararg",)
        if self.check("sym", "{"):
            return self.parse_table()
        return self.parse_suffixed()

    def parse_primary(self):
        t = self.tok
        if t.kind == "name":
            self.next()
            return ("name", t.value)
        if self.accept("sym", "("):
            e = self.parse_expr()
            self.expect("sym", ")")
            return ("paren", e)  # truncates multiple returns to one
        raise LuaSyntaxError(
            f"line {t.line}: unexpected symbol near {t.value!r}")

    def parse_suffixed(self):
        e = self.parse_primary()
        while True:
            t = self.tok
            if self.accept("sym", "."):
                e = ("index", e, ("str", self.expect("name").value))
            elif self.accept("sym", "["):
                k = self.parse_expr()
                self.expect("sym", "]")
                e = ("index", e, k)
            elif self.accept("sym", ":"):
                name = self.expect("name").value
                args = self.parse_callargs()
                e = ("method", e, name, args)
            elif t.kind == "string" or self.check("sym", "(") \
                    or self.check("sym", "{"):
                e = ("call", e, self.parse_callargs())
            else:
                return e

    def parse_callargs(self) -> List[tuple]:
        t = self.tok
        if t.kind == "string":
            self.next()
            return [("str", t.value)]
        if self.check("sym", "{"):
            return [self.parse_table()]
        self.expect("sym", "(")
        args = [] if self.check("sym", ")") else self.parse_exprlist()
        self.expect("sym", ")")
        return args

    def parse_table(self):
        self.expect("sym", "{")
        array: List[tuple] = []
        hash_: List[Tuple[tuple, tuple]] = []
        while not self.check("sym", "}"):
            if self.check("sym", "["):
                self.next()
                k = self.parse_expr()
                self.expect("sym", "]")
                self.expect("sym", "=")
                hash_.append((k, self.parse_expr()))
            elif self.tok.kind == "name" \
                    and self.toks[self.pos + 1].kind == "sym" \
                    and self.toks[self.pos + 1].value == "=":
                name = self.next().value
                self.next()  # '='
                hash_.append((("str", name), self.parse_expr()))
            else:
                array.append(self.parse_expr())
            if not (self.accept("sym", ",") or self.accept("sym", ";")):
                break
        self.expect("sym", "}")
        return ("table", array, hash_)


def parse(src: str) -> list:
    return Parser(src).parse_chunk()
