"""fluentbit_tpu — a TPU-native telemetry-pipeline framework.

Capabilities of fluent/fluent-bit (collect/process/route logs, metrics,
traces through a tagged-chunk pipeline), with the record-processing stage
(regex grep, parser extraction, tag rewriting, log-to-metrics aggregation)
executed as vectorized JAX kernels across TPU cores.

Public embedding API mirrors the reference's library mode
(include/fluent-bit/flb_lib.h): create/input/filter/output/start/push/stop.
"""

__version__ = "0.2.0"

from .lib import FLBContext, create  # noqa: F401
from .core.plugin import (  # noqa: F401
    FilterPlugin,
    FilterResult,
    FlushResult,
    InputPlugin,
    OutputPlugin,
    ProcessorPlugin,
    registry,
)
