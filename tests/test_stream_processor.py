"""Stream processor SQL: grammar, projection, WHERE, aggregates,
windows, GROUP BY, stream chaining, engine integration.

Reference: src/stream_processor/ (sql.y grammar, flb_sp.c,
flb_sp_window.c, flb_sp_aggregate_func.c).
"""

import json
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events, encode_event
from fluentbit_tpu.stream_processor import SQLError, SPTask, parse_sql


def ev(body, ts=1.0):
    return decode_events(encode_event(body, ts))[0]


# ------------------------------------------------------------------ parse

def test_parse_create_stream():
    q = parse_sql(
        "CREATE STREAM errors WITH (tag='app.errors') AS "
        "SELECT code, msg AS message FROM TAG:'app.*' "
        "WHERE level = 'error' AND code >= 500;"
    )
    assert q.stream_name == "errors"
    assert q.props == {"tag": "app.errors"}
    assert [k.out_name for k in q.keys] == ["code", "message"]
    assert q.source_type == "tag" and q.source == "app.*"
    assert q.window is None and q.group_by == []


def test_parse_window_group_by():
    q = parse_sql(
        "CREATE STREAM s AS SELECT COUNT(*), AVG(size) AS a "
        "FROM STREAM:base WINDOW TUMBLING (5 SECOND) GROUP BY host;"
    )
    assert q.source_type == "stream" and q.source == "base"
    assert q.window == ("tumbling", 5.0, 5.0)
    assert q.group_by == ["host"]
    assert q.keys[0].func == "count"
    assert q.keys[1].alias == "a"


def test_parse_hopping_window():
    q = parse_sql("SELECT COUNT(*) FROM TAG:'x' "
                  "WINDOW HOPPING (10 SECOND, ADVANCE BY 2 SECOND);")
    assert q.window == ("hopping", 10.0, 2.0)


def test_parse_errors():
    with pytest.raises(SQLError):
        parse_sql("SELECT FROM TAG:'x';")
    with pytest.raises(SQLError):
        parse_sql("SELECT * FROM NOWHERE:'x';")


# -------------------------------------------------------------- semantics

def run_task(sql, events, ticks=0, now=None):
    got = []
    task = SPTask(sql, lambda tag, bodies: got.append((tag, bodies)),
                  now=now)
    task.process(events, "app.log")
    for _ in range(ticks):
        task.tick()
    return got


def test_projection_and_where():
    events = [
        ev({"level": "error", "code": 500, "msg": "boom"}),
        ev({"level": "info", "code": 200, "msg": "fine"}),
        ev({"level": "error", "code": 404, "msg": "gone"}),
    ]
    got = run_task(
        "SELECT code, msg FROM TAG:'app.*' WHERE level = 'error';", events
    )
    assert got == [("sp.results", [{"code": 500, "msg": "boom"},
                                   {"code": 404, "msg": "gone"}])]


def test_select_star_and_record_functions():
    events = [ev({"a": 1, "b": 2}), ev({"a": 3})]
    got = run_task(
        "SELECT * FROM TAG:'app.*' WHERE @record.contains(b);", events
    )
    assert got[0][1] == [{"a": 1, "b": 2}]


def test_aggregates_per_chunk():
    events = [ev({"size": 10, "host": "a"}), ev({"size": 20, "host": "a"}),
              ev({"size": 60, "host": "b"})]
    got = run_task(
        "CREATE STREAM s WITH (tag='agg') AS SELECT COUNT(*) AS n, "
        "AVG(size) AS avg, MIN(size) AS lo, MAX(size) AS hi, "
        "SUM(size) AS total FROM TAG:'app.*';",
        events,
    )
    (tag, rows), = got
    assert tag == "agg"
    assert rows == [{"n": 3, "avg": 30.0, "lo": 10, "hi": 60, "total": 90.0}]


def test_group_by():
    events = [ev({"size": 10, "host": "a"}), ev({"size": 20, "host": "a"}),
              ev({"size": 60, "host": "b"})]
    got = run_task(
        "SELECT COUNT(*) AS n, SUM(size) AS s FROM TAG:'app.*' "
        "GROUP BY host;",
        events,
    )
    rows = {r["host"]: r for r in got[0][1]}
    assert rows["a"] == {"host": "a", "n": 2, "s": 30.0}
    assert rows["b"] == {"host": "b", "n": 1, "s": 60.0}


def test_tumbling_window_emits_on_tick():
    clock = [100.0]
    got = []
    task = SPTask(
        "SELECT COUNT(*) AS n FROM TAG:'app.*' WINDOW TUMBLING (5 SECOND);",
        lambda tag, bodies: got.append(bodies), now=lambda: clock[0],
    )
    task.process([ev({"x": 1}), ev({"x": 2})], "app.log")
    task.tick()
    assert got == []  # window still open
    clock[0] = 105.5
    task.tick()
    assert got == [[{"n": 2}]]
    # next window accumulates fresh
    task.process([ev({"x": 3})], "app.log")
    clock[0] = 111.0
    task.tick()
    assert got[-1] == [{"n": 1}]


def test_timeseries_forecast():
    events = [ev({"v": float(i)}, ts=float(i)) for i in range(10)]
    got = run_task(
        "SELECT TIMESERIES_FORECAST(v, 5) AS f FROM TAG:'app.*';", events
    )
    # linear series v=t → forecast at t=9+5 is 14
    assert got[0][1][0]["f"] == pytest.approx(14.0, abs=1e-6)


def test_is_null_and_not():
    events = [ev({"a": 1}), ev({"a": 1, "b": 2})]
    got = run_task(
        "SELECT a FROM TAG:'app.*' WHERE b IS NULL;", events
    )
    assert len(got[0][1]) == 1


# ------------------------------------------------------------ integration

def test_engine_integration_and_reingest():
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="sales")
    ctx.sp_task(
        "CREATE STREAM bigsales WITH (tag='sales.big') AS "
        "SELECT * FROM TAG:'sales' WHERE amount >= 100;"
    )
    got = {}
    ctx.output("lib", match="*",
               callback=lambda d, t: got.setdefault(t, []).extend(
                   decode_events(d)))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"amount": 250, "sku": "x"}))
        ctx.push(in_ffd, json.dumps({"amount": 5, "sku": "y"}))
        ctx.flush_now()
    finally:
        ctx.stop()
    assert [e.body["sku"] for e in got["sales"]] == ["x", "y"]
    assert [e.body["sku"] for e in got["sales.big"]] == ["x"]


def test_stream_chaining():
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.sp_task("CREATE STREAM s1 WITH (tag='s1.out') AS "
                "SELECT code FROM TAG:'t' WHERE code >= 400;")
    ctx.sp_task("CREATE STREAM s2 WITH (tag='s2.out') AS "
                "SELECT COUNT(*) AS n FROM STREAM:s1;")
    got = {}
    ctx.output("lib", match="s*",
               callback=lambda d, t: got.setdefault(t, []).extend(
                   decode_events(d)))
    ctx.start()
    try:
        for code in [200, 404, 500, 301]:
            ctx.push(in_ffd, json.dumps({"code": code}))
        ctx.flush_now()
    finally:
        ctx.stop()
    assert [e.body["code"] for e in got["s1.out"]] == [404, 500]
    # each chunk of s1 results aggregates per chunk
    assert sum(e.body["n"] for e in got["s2.out"]) == 2


def test_streams_file_config(tmp_path):
    streams = tmp_path / "streams.conf"
    streams.write_text("""
[STREAM_TASK]
    Name  t1
    Exec  CREATE STREAM s WITH (tag='out') AS SELECT * FROM TAG:'in';
""")
    conf = tmp_path / "main.conf"
    conf.write_text(f"""
[SERVICE]
    Flush        0.05
    Streams_File {streams}

[INPUT]
    Name lib
    Tag  in

[OUTPUT]
    Name  lib
    Match *
""")
    from fluentbit_tpu.config_format import apply_to_context, load_config_file

    ctx = flb.create(grace="1")
    apply_to_context(ctx, load_config_file(str(conf)), str(tmp_path))
    assert ctx.engine.sp is not None and len(ctx.engine.sp.tasks) == 1


def test_sql_processor_projection():
    """processor_sql: per-instance projection/WHERE (distinct from the
    engine-level SP)."""
    from fluentbit_tpu.core.plugin import registry

    proc = registry.create_processor("sql")
    proc.set("query", "SELECT code, path FROM TAG:'x' WHERE code >= 400;")
    proc.configure()
    proc.plugin.init(proc, None)
    events = [ev({"code": 200, "path": "/a", "junk": 1}),
              ev({"code": 404, "path": "/b", "junk": 2})]
    out = proc.plugin.process_logs(events, "t", None)
    assert len(out) == 1
    assert out[0].body == {"code": 404, "path": "/b"}


def test_sql_processor_rejects_aggregates():
    from fluentbit_tpu.core.plugin import registry

    proc = registry.create_processor("sql")
    proc.set("query", "SELECT COUNT(*) FROM TAG:'x';")
    proc.configure()
    with pytest.raises(ValueError):
        proc.plugin.init(proc, None)


def test_hopping_window_slides_over_panes():
    """HOPPING (4s, ADVANCE 2s): each emission aggregates the union of
    the last size/advance panes, not just the newest advance."""
    clock = [100.0]
    got = []
    task = SPTask(
        "SELECT COUNT(*) AS n FROM TAG:'t' "
        "WINDOW HOPPING (4 SECOND, ADVANCE BY 2 SECOND);",
        lambda tag, bodies: got.append(bodies[0]["n"]), now=lambda: clock[0],
    )
    task.process([ev({"x": 1}), ev({"x": 2})], "t")  # pane 1: 2 events
    clock[0] = 102.1
    task.tick()
    assert got[-1] == 2
    task.process([ev({"x": 3})], "t")  # pane 2: 1 event
    clock[0] = 104.2
    task.tick()
    assert got[-1] == 3  # union of last two panes
    clock[0] = 106.3
    task.tick()  # pane 1 slid out; only pane 2 remains
    assert got[-1] == 1


def test_windowed_task_registered_after_start_ticks():
    """sp_task after ctx.start(): the window collector must still be
    scheduled and the window close must emit."""
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    got = {}
    ctx.output("lib", match="*",
               callback=lambda d, t: got.setdefault(t, []).extend(
                   decode_events(d)))
    ctx.start()
    try:
        ctx.sp_task("CREATE STREAM w WITH (tag='w.out') AS "
                    "SELECT COUNT(*) AS n FROM TAG:'t' "
                    "WINDOW TUMBLING (1 SECOND);")
        ctx.push(in_ffd, json.dumps({"a": 1}))
        ctx.push(in_ffd, json.dumps({"a": 2}))
        deadline = time.time() + 6
        while time.time() < deadline and "w.out" not in got:
            time.sleep(0.05)
    finally:
        ctx.stop()
    assert sum(e.body["n"] for e in got.get("w.out", [])) == 2


def test_window_drained_at_shutdown():
    """An open 60s window is flushed at engine stop, not dropped."""
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.sp_task("CREATE STREAM w WITH (tag='w.out') AS "
                "SELECT COUNT(*) AS n FROM TAG:'t' "
                "WINDOW TUMBLING (60 SECOND);")
    got = {}
    ctx.output("lib", match="*",
               callback=lambda d, t: got.setdefault(t, []).extend(
                   decode_events(d)))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"a": 1}))
        ctx.push(in_ffd, json.dumps({"a": 2}))
        ctx.flush_now()
    finally:
        ctx.stop()
    assert [e.body["n"] for e in got.get("w.out", [])] == [2]


def test_no_self_feedback_loop():
    """A task whose pattern matches its own output tag must not recurse."""
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="anything")
    ctx.sp_task("SELECT * FROM TAG:'*';")  # out_tag sp.results matches '*'
    got = {}
    ctx.output("lib", match="*",
               callback=lambda d, t: got.setdefault(t, []).extend(
                   decode_events(d)))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"a": 1}))
        ctx.flush_now()
    finally:
        ctx.stop()
    assert len(got.get("sp.results", [])) == 1  # exactly one, no loop


def test_snapshot_create_and_flush(monkeypatch):
    """CREATE SNAPSHOT buffers the recent past; FLUSH SNAPSHOT replays
    it when the anomaly condition fires (flb_sp_snapshot.c)."""
    import time as _time

    import fluentbit_tpu as flb
    from fluentbit_tpu.codec.events import decode_events

    got = []
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="logs")
    ctx.sp_task("CREATE SNAPSHOT recent AS SELECT * "
                "FROM TAG:'logs' LIMIT 3;")
    ctx.sp_task("FLUSH SNAPSHOT recent AS SELECT * "
                "FROM TAG:'logs' WHERE level = 'error';")
    ctx.output("lib", match="recent",
               callback=lambda d, tag: got.extend(decode_events(d)))
    ctx.start()
    try:
        for i in range(5):  # 5 normal records; ring keeps the last 3
            ctx.push(in_ffd, f'{{"level": "info", "n": {i}}}')
        ctx.flush_now()
        _time.sleep(0.1)
        assert got == []  # nothing flushed yet
        ctx.push(in_ffd, '{"level": "error", "n": 99}')
        ctx.flush_now()
        deadline = _time.time() + 5
        while len(got) < 3 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        ctx.stop()
    # the flushed snapshot = the 3 records before the anomaly... plus
    # the error record itself if it entered the ring first (snapshot
    # task registered before the flush task, same order as reference
    # task list iteration)
    ns = [ev.body["n"] for ev in got]
    assert ns == [3, 4, 99] or ns == [2, 3, 4], ns
    # ring is purged after a flush
    got.clear()
    ctx2 = None


def test_snapshot_requires_size():
    from fluentbit_tpu.stream_processor import SQLError, parse_sql

    with pytest.raises(SQLError, match="size is not defined"):
        parse_sql("CREATE SNAPSHOT s AS SELECT * FROM TAG:'x';")
    q = parse_sql("CREATE SNAPSHOT s WITH(seconds=5) AS SELECT * "
                  "FROM TAG:'x';")
    assert q.kind == "snapshot" and q.props["seconds"] == 5
    q2 = parse_sql("FLUSH SNAPSHOT s AS SELECT * FROM TAG:'x' "
                   "WHERE a = 1;")
    assert q2.kind == "flush_snapshot" and q2.stream_name == "s"


def test_snapshot_time_limit(monkeypatch):
    from fluentbit_tpu.stream_processor import SPTask

    clock = [1000.0]
    task = SPTask("CREATE SNAPSHOT t WITH(seconds=10) AS SELECT * "
                  "FROM TAG:'x';", emit=lambda *a: None,
                  now=lambda: clock[0])
    for i in range(5):
        task.snapshot_update(clock[0], {"n": i})
        clock[0] += 4.0
    # aging runs at update time (like the reference's cleanup inside
    # flb_sp_snapshot_update): last update at t=1016, cutoff 1006
    assert [b["n"] for _, b in task._snap] == [2, 3, 4]


def test_snapshot_where_projection_and_limit_validation():
    from fluentbit_tpu.stream_processor import SQLError, SPTask, parse_sql

    with pytest.raises(SQLError, match="LIMIT is only valid"):
        parse_sql("CREATE STREAM s AS SELECT * FROM TAG:'x' LIMIT 5;")

    class Ev:
        def __init__(self, body, ts=1.0):
            self.body = body
            self.ts_float = ts

    task = SPTask("CREATE SNAPSHOT s AS SELECT msg FROM TAG:'x' "
                  "WHERE level = 'debug' LIMIT 10;",
                  emit=lambda *a: None)
    task.process([Ev({"level": "debug", "msg": "a", "extra": 1}),
                  Ev({"level": "info", "msg": "b"})], "x")
    assert [b for _, b in task._snap] == [{"msg": "a"}]
