"""Upstream keepalive pools + HA weighted failover.

Reference: src/flb_upstream.c (net.keepalive* pools),
src/flb_upstream_ha.c + flb_upstream_node.c (weighted [NODE] files
consumed by out_forward).
"""

import asyncio
import socket
import threading
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.core.upstream import (UpstreamHA, UpstreamNode,
                                         parse_upstream_file)


def test_ha_weighted_round_robin():
    a = UpstreamNode("a", "h1", 1, weight=3)
    b = UpstreamNode("b", "h2", 2, weight=1)
    ha = UpstreamHA("up", [a, b])
    picks = [ha.pick().name for _ in range(8)]
    assert picks.count("a") == 6 and picks.count("b") == 2


def test_ha_failover_and_recovery():
    a = UpstreamNode("a", "h1", 1)
    b = UpstreamNode("b", "h2", 2)
    ha = UpstreamHA("up", [a, b], retry_window=0.2)
    ha.mark_down(a)
    assert {ha.pick().name for _ in range(4)} == {"b"}
    time.sleep(0.25)
    assert "a" in {ha.pick().name for _ in range(4)}
    # all down: picks still return (caller surfaces the error)
    ha.mark_down(a)
    ha.mark_down(b)
    assert ha.pick() is not None


def test_ha_flapping_nodes_readmitted_with_weights():
    """Nodes flapping unhealthy→healthy: smooth weighted round-robin
    must re-admit recovered nodes with their weights intact, whether
    recovery is explicit (mark_up) or cooldown-driven."""
    a = UpstreamNode("a", "h1", 1, weight=3)
    b = UpstreamNode("b", "h2", 2, weight=1)
    ha = UpstreamHA("up", [a, b], retry_window=0.05)
    for _cycle in range(3):
        ha.mark_down(a)
        assert {ha.pick().name for _ in range(4)} == {"b"}
        time.sleep(0.08)  # cooldown lapses: a is probe-ready again
        picks = [ha.pick().name for _ in range(8)]
        assert picks.count("a") >= 5, picks  # weight 3:1 re-applies
        assert picks.count("b") >= 1, picks
        ha.mark_up(a)  # explicit recovery closes the node's breaker
        assert a.breaker.state_name() == "closed"
    # a node that keeps failing past its cooldown stays excluded: the
    # re-failure re-arms the window (no lapsed-timer re-admission)
    ha.mark_down(b)
    time.sleep(0.08)
    ha.mark_down(b)  # probe failed again
    assert {ha.pick().name for _ in range(4)} == {"a"}
    # every node down: picks still proceed (caller surfaces the error)
    ha.mark_down(a)
    assert ha.pick() is not None


def test_parse_upstream_file(tmp_path):
    p = tmp_path / "up.conf"
    p.write_text(
        "[UPSTREAM]\n    name forward-balancing\n"
        "[NODE]\n    name n1\n    host 127.0.0.1\n    port 10001\n"
        "    weight 2\n"
        "[NODE]\n    name n2\n    host 127.0.0.1\n    port 10002\n"
    )
    ha = parse_upstream_file(str(p))
    assert ha.name == "forward-balancing"
    assert [(n.name, n.port, n.weight) for n in ha.nodes] == [
        ("n1", 10001, 2), ("n2", 10002, 1)]


class _CountingHttpServer:
    """HTTP/1.1 keep-alive server counting connections + requests."""

    def __init__(self):
        self.connections = 0
        self.requests = 0
        self.port = None
        self._loop = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        deadline = time.time() + 5
        while self.port is None and time.time() < deadline:
            time.sleep(0.02)
        return self

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)

    def _run(self):
        async def on_conn(reader, writer):
            self.connections += 1
            try:
                while True:
                    head = bytearray()
                    while not head.endswith(b"\r\n\r\n"):
                        b = await reader.readexactly(1)
                        head += b
                    length = 0
                    for line in head.decode("latin-1").split("\r\n"):
                        if line.lower().startswith("content-length:"):
                            length = int(line.split(":", 1)[1])
                    if length:
                        await reader.readexactly(length)
                    self.requests += 1
                    writer.write(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Length: 2\r\n\r\nok")
                    await writer.drain()
            except (asyncio.IncompleteReadError, ConnectionError):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def main():
            server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
            self.port = server.sockets[0].getsockname()[1]

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(main())
        self._loop.run_forever()


def test_http_output_reuses_keepalive_connection():
    srv = _CountingHttpServer().start()
    try:
        ctx = flb.create(flush="40ms", grace="1")
        in_ffd = ctx.input("lib")
        ctx.output("http", match="*", host="127.0.0.1",
                   port=str(srv.port))
        ctx.start()
        try:
            for i in range(5):
                ctx.push(in_ffd, '{"n": %d}' % i)
                time.sleep(0.15)  # separate chunks → separate flushes
            deadline = time.time() + 5
            while srv.requests < 5 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            ctx.stop()
    finally:
        srv.stop()
    assert srv.requests >= 5
    # the pool reuses connections: far fewer dials than requests
    assert srv.connections < srv.requests, (
        srv.connections, srv.requests)


def test_forward_output_ha_failover():
    """Two forward endpoints; only one is alive — records must land
    there via HA failover."""
    from fluentbit_tpu.codec.msgpack import Unpacker

    received = []
    alive_port = {}
    loop_holder = {}

    def run_server():
        async def on_conn(reader, writer):
            u = Unpacker()
            try:
                while True:
                    data = await reader.read(65536)
                    if not data:
                        break
                    u.feed(data)
                    while True:
                        try:
                            received.append(u.unpack())
                        except Exception:
                            break
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def main():
            server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
            alive_port["port"] = server.sockets[0].getsockname()[1]

        loop = asyncio.new_event_loop()
        loop_holder["loop"] = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(main())
        loop.run_forever()

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    deadline = time.time() + 5
    while "port" not in alive_port and time.time() < deadline:
        time.sleep(0.02)

    # a dead port: bind+close to get a port nothing listens on
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()

    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".conf",
                                     delete=False) as f:
        f.write(
            "[UPSTREAM]\n    name ha\n"
            f"[NODE]\n    name dead\n    host 127.0.0.1\n"
            f"    port {dead_port}\n    weight 10\n"
            f"[NODE]\n    name live\n    host 127.0.0.1\n"
            f"    port {alive_port['port']}\n"
        )
        up_file = f.name

    ctx = flb.create(flush="40ms", grace="1")
    ctx.service_set(**{"scheduler.base": "0.05", "scheduler.cap": "0.1"})
    in_ffd = ctx.input("lib")
    ctx.output("forward", match="*", upstream=up_file)
    ctx.start()
    try:
        ctx.push(in_ffd, '{"via": "ha"}')
        deadline = time.time() + 10
        while not received and time.time() < deadline:
            time.sleep(0.05)
    finally:
        ctx.stop()
        loop_holder["loop"].call_soon_threadsafe(
            loop_holder["loop"].stop)
    assert received, "no forward message reached the live node"
    tag, blob, option = received[0]
    assert tag == "lib.0"
    evs = list(Unpacker(blob))
    assert evs[0][1] == {"via": "ha"}
