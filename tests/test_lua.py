"""Lua runtime + filter_lua tests.

Language/stdlib cases mirror what LuaJIT guarantees filter scripts
(reference plugins/filter_lua + src/flb_lua.c); filter tests mirror
tests/runtime/filter_lua.c scenarios (modify record, drop, split,
timestamp handling, protected mode)."""

import json

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.luart import (
    LuaError,
    LuaRuntime,
    lua_to_py,
    py_to_lua,
)


def run(src, *names):
    rt = LuaRuntime()
    rt.load(src)
    vals = [lua_to_py(rt.globals.vars.get(n)) for n in names]
    return vals[0] if len(vals) == 1 else vals


# ----------------------------------------------------------- language

def test_arith_and_precedence():
    assert run("x = 2 + 3 * 4", "x") == 14
    assert run("x = -2 ^ 2", "x") == -4          # ^ binds above unary -
    assert run("x = 2 ^ 3 ^ 2", "x") == 512      # right assoc
    assert run("x = 7 % 3", "x") == 1
    assert run("x = -7 % 3", "x") == 2           # Lua floor-mod
    assert run("x = 10 / 4", "x") == 2.5
    assert run("x = 1 .. 2", "x") == "12"


def test_string_number_coercion():
    assert run('x = "10" + 5', "x") == 15
    assert run('x = "3" * "4"', "x") == 12


def test_comparison_and_logic():
    assert run("x = 1 < 2 and 'yes' or 'no'", "x") == "yes"
    assert run("x = nil and 1 or 2", "x") == 2
    assert run('x = "a" < "b"', "x") is True
    with pytest.raises(LuaError):
        run('x = 1 < "2"', "x")


def test_multiple_assignment_and_returns():
    assert run("""
function two() return 1, 2 end
a, b, c = two()
d = (two())            -- parens truncate
t = {two()}            -- expands at tail
u = {two(), 10}        -- truncated mid-list
""", "a", "b", "c", "d", "t", "u") == [1, 2, None, 1, [1, 2], [1, 10]]


def test_closures_and_upvalues():
    assert run("""
local function make()
  local c = 0
  return function() c = c + 1 return c end
end
f = make()
f(); f()
x = f()
""", "x") == 3


def test_varargs():
    assert run("""
function f(...)
  local t = {...}
  return #t, select("#", ...), select(2, ...)
end
a, b, c = f("x", "y", "z")
""", "a", "b", "c") == [3, 3, "y"]


def test_loops_and_break():
    assert run("""
s = 0
for i = 1, 10 do if i > 5 then break end s = s + i end
r = 0
local i = 0
repeat i = i + 1 r = r + i until i >= 3
w = 0
while w < 7 do w = w + 2 end
""", "s", "r", "w") == [15, 6, 8]


def test_generic_for_pairs():
    assert run("""
t = {a = 1, b = 2}
ks = {}
for k, v in pairs(t) do ks[k] = v * 10 end
arr = {5, 6, 7}
sum = 0
for i, v in ipairs(arr) do sum = sum + i * v end
""", "ks", "sum") == [{"a": 10, "b": 20}, 38]


def test_table_methods_and_length():
    assert run("""
t = {}
table.insert(t, "a"); table.insert(t, "c"); table.insert(t, 2, "b")
removed = table.remove(t, 1)
n = #t
joined = table.concat({"x", "y", "z"}, "-")
nested = {list = {1, 2, {deep = true}}}
""", "removed", "n", "joined", "nested") == [
        "a", 2, "x-y-z", {"list": [1, 2, {"deep": True}]}]


def test_table_sort():
    assert run("""
t = {3, 1, 2}
table.sort(t)
u = {"b", "c", "a"}
table.sort(u, function(a, b) return a > b end)
""", "t", "u") == [[1, 2, 3], ["c", "b", "a"]]


def test_metatables_index():
    assert run("""
Base = {greet = function(self) return "hi " .. self.name end}
obj = setmetatable({name = "bob"}, {__index = Base})
x = obj:greet()
""", "x") == "hi bob"


def test_method_definition_colon():
    assert run("""
Account = {}
Account.__index = Account
function Account.new(b)
  return setmetatable({balance = b}, Account)
end
function Account:deposit(v) self.balance = self.balance + v end
a = Account.new(100)
a:deposit(50)
x = a.balance
""", "x") == 150


def test_pcall_error():
    assert run("""
ok, err = pcall(function() error("kaboom") end)
ok2, v = pcall(function() return 42 end)
""", "ok", "ok2", "v") == [False, True, 42]
    assert "kaboom" in run("ok, err = pcall(error, 'kaboom')", "err")


def test_tostring_tonumber():
    assert run("x = tostring(42)", "x") == "42"
    assert run("x = tostring(1.5)", "x") == "1.5"
    assert run("x = tonumber('0x1F')", "x") == 31
    assert run("x = tonumber('1e2')", "x") == 100
    assert run("x = tonumber('zz')", "x") is None
    assert run("x = tonumber('ff', 16)", "x") == 255


def test_string_library():
    assert run('x = string.format("%d-%s-%.1f", 7, "a", 2.25)', "x") \
        == "7-a-2.2"
    assert run('x = ("log"):rep(2)', "x") == "loglog"
    assert run('x = string.byte("A")', "x") == 65
    assert run('x = string.char(104, 105)', "x") == "hi"
    assert run('x = string.sub("abcdef", -3)', "x") == "def"
    assert run('x = #"hello"', "x") == 5


def test_lua_patterns():
    assert run('x = string.match("2024-01-15", "(%d+)-(%d+)")',
               "x") == "2024"
    assert run("""
k, v = string.match("level=error", "(%w+)=(%w+)")
""", "k", "v") == ["level", "error"]
    assert run('x, n = string.gsub("a.b.c", "%.", "/")', "x") == "a/b/c"
    assert run("""
t = {}
for k, v in string.gmatch("a=1, b=2", "(%w+)=(%w+)") do t[k] = v end
""", "t") == {"a": "1", "b": "2"}
    assert run('x = string.find("hello", "l+")', "x") == 3
    assert run('x = string.match("  trim  ", "^%s*(.-)%s*$")', "x") \
        == "trim"
    assert run('x = string.gsub("<a><b>", "%b<>", "T")', "x") == "TT"


def test_os_and_math():
    assert run("x = math.floor(3.7)", "x") == 3
    assert run("x = math.max(1, 9, 4)", "x") == 9
    assert run("x = math.huge > 1e300", "x") is True
    assert isinstance(run("x = os.time()", "x"), int)
    assert run('x = os.date("!%Y-%m-%d", 86400)', "x") == "1970-01-02"


def test_conversion_roundtrip():
    rec = {"msg": "x", "count": 3, "pi": 3.5, "ok": True,
           "tags": ["a", "b"], "meta": {"k": None}}
    back = lua_to_py(py_to_lua(rec))
    rec["meta"] = {}  # nil value deletes the key — Lua semantics
    assert back == rec


def test_global_g_table():
    assert run('_G["via_g"] = 5; x = via_g + 1', "x") == 6


# --------------------------------------------------------- filter_lua

def lua_pipeline(code, records, call="cb", **props):
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("lua", match="t", code=code, call=call, **props)
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        for r in records:
            ctx.push(in_ffd, json.dumps(r))
        ctx.flush_now()
        import time
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
    finally:
        ctx.stop()
    return [e for d in got for e in decode_events(d)]


def test_filter_lua_modify():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  record["seen"] = tag .. "!"
  record["n"] = (record["n"] or 0) + 1
  return 2, ts, record
end
""", [{"n": 1}, {"msg": "x"}])
    assert [e.body for e in evs] == [
        {"n": 2, "seen": "t!"}, {"msg": "x", "n": 1, "seen": "t!"}]


def test_filter_lua_drop_and_keep():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  if record.level == "debug" then return -1, ts, record end
  return 0, ts, record
end
""", [{"level": "debug"}, {"level": "error"}])
    assert [e.body for e in evs] == [{"level": "error"}]


def test_filter_lua_split_array():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  return 1, ts, {{part = 1}, {part = 2}}
end
""", [{"x": "y"}])
    assert [e.body for e in evs] == [{"part": 1}, {"part": 2}]


def test_filter_lua_code1_timestamp_override():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  return 1, 1700000000.25, record
end
""", [{"a": 1}])
    assert abs(evs[0].ts_float - 1700000000.25) < 1e-6


def test_filter_lua_code2_keeps_timestamp():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  record.touched = true
  return 2, 12345.0, record
end
""", [{"a": 1}])
    assert evs[0].body["touched"] is True
    assert evs[0].ts_float > 1e9  # original ingest time, not 12345


def test_filter_lua_time_as_table():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  record.sec = ts.sec
  ts.sec = 1600000000
  ts.nsec = 500000000
  return 1, ts, record
end
""", [{"a": 1}], time_as_table="on")
    assert evs[0].body["sec"] > 1e9
    assert abs(evs[0].ts_float - 1600000000.5) < 1e-6


def test_filter_lua_protected_mode():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  if record.bad then error("nope") end
  return 0, ts, record
end
""", [{"bad": True}, {"ok": 1}])
    # errored record kept (protected_mode default on)
    assert [e.body for e in evs] == [{"bad": True}, {"ok": 1}]


def test_filter_lua_type_int_key():
    evs = lua_pipeline("""
function cb(tag, ts, record)
  record.count = "42"
  return 2, ts, record
end
""", [{"a": 1}], type_int_key="count")
    assert evs[0].body["count"] == 42


def test_filter_lua_script_file(tmp_path):
    f = tmp_path / "script.lua"
    f.write_text("""
-- classic k8s-style log mangler
function mangle(tag, ts, record)
  local log = record.log
  if log then
    local level = string.match(log, "%[(%u+)%]")
    if level then record.level = string.lower(level) end
  end
  return 2, ts, record
end
""")
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("lua", match="t", script=str(f), call="mangle")
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"log": "[ERROR] disk full"}))
        ctx.flush_now()
        import time
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
    finally:
        ctx.stop()
    evs = [e for d in got for e in decode_events(d)]
    assert evs[0].body["level"] == "error"


def test_filter_lua_requires_call():
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("dummy", tag="t")
    ctx.filter("lua", match="t", code="x = 1")
    ctx.output("null", match="*")
    with pytest.raises(Exception):
        ctx.start()
    ctx.stop()
