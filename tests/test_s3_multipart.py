"""out_s3 multipart mode against a local S3 stub: create/upload-part/
complete sequencing, part boundaries at upload_chunk_size, restart
resume from fstore metadata, and drain completion (reference
plugins/out_s3/s3.c:82-123, s3_multipart.c)."""

import json
import os
import re
import socket
import threading
import time

import fluentbit_tpu as flb


class S3Stub:
    """Minimal multipart-aware S3 endpoint: answers ?uploads= with an
    UploadId, parts with an ETag header, and records everything."""

    def __init__(self):
        self.requests = []  # (method, path, body)
        self.upload_ids = 0
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while True:
            try:
                c, _ = self.srv.accept()
            except OSError:
                return
            c.settimeout(3)
            try:
                data = b""
                while b"\r\n\r\n" not in data:
                    data += c.recv(65536)
                head, _, body = data.partition(b"\r\n\r\n")
                m = re.search(rb"Content-Length: (\d+)", head)
                cl = int(m.group(1)) if m else 0
                while len(body) < cl:
                    body += c.recv(65536)
                req = head.split(b"\r\n")[0].decode()
                method, path, _ = req.split(" ", 2)
                self.requests.append((method, path, body))
                if path.endswith("?uploads="):
                    self.upload_ids += 1
                    resp = (f"<InitiateMultipartUploadResult>"
                            f"<UploadId>UP{self.upload_ids}</UploadId>"
                            f"</InitiateMultipartUploadResult>").encode()
                    c.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                              + str(len(resp)).encode()
                              + b"\r\n\r\n" + resp)
                elif "partNumber=" in path:
                    n = re.search(r"partNumber=(\d+)", path).group(1)
                    c.sendall(b"HTTP/1.1 200 OK\r\nETag: \"etag-"
                              + n.encode()
                              + b"\"\r\nContent-Length: 0\r\n\r\n")
                else:
                    c.sendall(b"HTTP/1.1 200 OK\r\n"
                              b"Content-Length: 0\r\n\r\n")
            except OSError:
                pass
            c.close()

    def close(self):
        self.srv.close()

    def by_kind(self):
        creates = [r for r in self.requests if r[1].endswith("?uploads=")]
        parts = [r for r in self.requests if "partNumber=" in r[1]]
        completes = [r for r in self.requests
                     if "uploadId=" in r[1] and "partNumber" not in r[1]
                     and not r[1].endswith("?uploads=")]
        return creates, parts, completes


def run_pipeline(stub, store_dir, n_messages, msg_size=40, **extra):
    ctx = flb.create(flush="50ms", grace="3")
    in_ffd = ctx.input("lib", tag="app")
    ctx.output("s3", match="app", bucket="logs",
               endpoint=f"127.0.0.1:{stub.port}",
               use_put_object="off",
               store_dir=str(store_dir),
               s3_key_format="/mp/$TAG/obj", **extra)
    ctx.start()
    try:
        for i in range(n_messages):
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "x" * msg_size}))
            ctx.flush_now()
        deadline = time.time() + 8
        while time.time() < deadline:
            creates, parts, completes = stub.by_kind()
            if completes:
                break
            time.sleep(0.05)
    finally:
        ctx.stop()
    return ctx


def test_multipart_create_part_complete(tmp_path, monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
    stub = S3Stub()
    try:
        # ~55 bytes/record: chunk=128 → part every ~3 records;
        # total=384 → complete after ~2-3 parts
        run_pipeline(stub, tmp_path / "st", 12,
                     upload_chunk_size="128", total_file_size="384")
    finally:
        stub.close()
    creates, parts, completes = stub.by_kind()
    # reaching total_file_size completes an object; later records open
    # the next upload — every create must be matched by a complete
    assert creates and len(completes) == len(creates)
    assert creates[0][1] == "/logs/mp/app/obj?uploads="
    assert len(parts) >= 2
    # part numbers sequential from 1 WITHIN each upload
    by_upload = {}
    for p in parts:
        uid = re.search(r"uploadId=(\w+)", p[1]).group(1)
        by_upload.setdefault(uid, []).append(
            int(re.search(r"partNumber=(\d+)", p[1]).group(1)))
    for uid, nums in by_upload.items():
        assert nums == list(range(1, len(nums) + 1)), (uid, nums)
    # each complete's manifest lists exactly its upload's parts
    for _, path, body in completes:
        uid = re.search(r"uploadId=(\w+)", path).group(1)
        manifest = body.decode()
        for n in by_upload[uid]:
            assert f"<PartNumber>{n}</PartNumber>" in manifest
            assert f'"etag-{n}"' in manifest
    # every record delivered exactly once, in order, across all parts
    seen = []
    for _, _, body in parts:
        seen += [json.loads(l)["i"]
                 for l in body.decode().strip().splitlines()]
    assert seen == list(range(12))


def test_multipart_drain_completes_open_upload(tmp_path, monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
    stub = S3Stub()
    ctx = flb.create(flush="50ms", grace="3")
    in_ffd = ctx.input("lib", tag="app")
    ctx.output("s3", match="app", bucket="logs",
               endpoint=f"127.0.0.1:{stub.port}",
               use_put_object="off",
               upload_chunk_size="64",
               total_file_size="100M",  # size trigger never fires
               store_dir=str(tmp_path / "st2"))
    ctx.start()
    try:
        for i in range(4):
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "y" * 30}))
            ctx.flush_now()
        time.sleep(0.3)
    finally:
        ctx.stop()  # drain must upload the tail part AND complete
    stub.close()
    creates, parts, completes = stub.by_kind()
    assert len(creates) == 1
    assert parts, "no parts uploaded"
    assert len(completes) == 1
    seen = []
    for _, _, body in parts:
        seen += [json.loads(l)["i"]
                 for l in body.decode().strip().splitlines()]
    assert seen == list(range(4))


def test_multipart_restart_resumes_upload(tmp_path, monkeypatch):
    """Kill the pipeline mid-upload; a fresh instance over the same
    store_dir must resume the SAME UploadId and complete with all
    parts (s3.c get_upload/create_upload resume contract)."""
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
    stub = S3Stub()
    store = tmp_path / "st3"
    # phase 1: enough records for one part, then hard-stop (no drain
    # completion: simulate by NOT letting total_file_size trigger and
    # removing the drain via direct engine teardown)
    ctx = flb.create(flush="50ms", grace="3")
    in_ffd = ctx.input("lib", tag="app")
    ctx.output("s3", match="app", bucket="logs",
               endpoint=f"127.0.0.1:{stub.port}",
               use_put_object="off",
               upload_chunk_size="64", total_file_size="100M",
               store_dir=str(store), s3_key_format="/mp/$TAG/obj")
    ctx.start()
    try:
        for i in range(3):
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "z" * 30}))
            ctx.flush_now()
        deadline = time.time() + 6
        while time.time() < deadline and not stub.by_kind()[1]:
            time.sleep(0.05)
        # simulate a crash: drop the drain hook so stop() leaves the
        # upload open with its fstore state on disk
        s3_plugin = ctx.engine.outputs[0].plugin
        s3_plugin.drain = lambda engine: None
    finally:
        ctx.stop()
    creates1, parts1, completes1 = stub.by_kind()
    assert len(creates1) == 1 and parts1 and not completes1
    # phase 2: new pipeline, same store_dir — push one more record and
    # stop; drain completes the RESUMED upload
    ctx2 = flb.create(flush="50ms", grace="3")
    in_ffd = ctx2.input("lib", tag="app")
    ctx2.output("s3", match="app", bucket="logs",
                endpoint=f"127.0.0.1:{stub.port}",
                use_put_object="off",
                upload_chunk_size="64", total_file_size="100M",
                store_dir=str(store), s3_key_format="/mp/$TAG/obj")
    ctx2.start()
    try:
        ctx2.push(in_ffd, json.dumps({"i": 99, "pad": "w" * 30}))
        ctx2.flush_now()
        time.sleep(0.3)
    finally:
        ctx2.stop()
    stub.close()
    creates, parts, completes = stub.by_kind()
    assert len(creates) == 1, "resume must NOT create a second upload"
    assert len(completes) == 1
    assert "uploadId=UP1" in completes[0][1]
    nums = [int(re.search(r"partNumber=(\d+)", p[1]).group(1))
            for p in parts]
    assert nums == list(range(1, len(parts) + 1))
    manifest = completes[0][2].decode()
    assert f"<PartNumber>{len(parts)}</PartNumber>" in manifest
    seen = []
    for _, _, body in parts:
        seen += [json.loads(l)["i"]
                 for l in body.decode().strip().splitlines()]
    assert seen == [0, 1, 2, 99]


def test_multipart_retry_redelivery_no_duplicate_staging(tmp_path,
                                                         monkeypatch):
    """ADVICE.md (medium): flush staged the chunk, the part upload
    failed (failpoint on the part-upload site), the engine redelivered
    the same chunk — staging must be idempotent: every record appears
    exactly once across the uploaded parts."""
    from fluentbit_tpu import failpoints

    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
    failpoints.reset()
    failpoints.enable("s3.upload_part", "1*return(part-lost)")
    stub = S3Stub()
    ctx = flb.create(flush="50ms", grace="3")
    ctx.service_set(**{"scheduler.base": "0.05", "scheduler.cap": "0.1"})
    in_ffd = ctx.input("lib", tag="app")
    ctx.output("s3", match="app", bucket="logs",
               endpoint=f"127.0.0.1:{stub.port}",
               use_put_object="off",
               upload_chunk_size="64", total_file_size="100M",
               store_dir=str(tmp_path / "st4"),
               s3_key_format="/mp/$TAG/obj")
    ctx.start()
    try:
        # one chunk big enough to trip upload_chunk_size on its flush
        for i in range(3):
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "q" * 30}))
        ctx.flush_now()
        deadline = time.time() + 8
        while time.time() < deadline and not stub.by_kind()[1]:
            time.sleep(0.05)
        time.sleep(0.3)
    finally:
        ctx.stop()
        failpoints.reset()
    stub.close()
    _creates, parts, _completes = stub.by_kind()
    assert parts, "the retried flush must eventually upload the part"
    seen = []
    for _, _, body in parts:
        seen += [json.loads(l)["i"]
                 for l in body.decode().strip().splitlines()]
    assert seen == list(range(3)), (
        f"RETRY redelivery duplicated staged records: {seen}")


def test_multipart_interleaved_chunk_then_retry_dedup(tmp_path,
                                                      monkeypatch):
    """A second chunk for the same tag flushing WHILE the first is in
    RETRY backoff must not defeat staging idempotence: the first
    chunk's redelivery still dedups (per-tag digest SET, not a single
    last-staged marker)."""
    from fluentbit_tpu import failpoints

    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
    failpoints.reset()
    # first part-upload attempt fails; every later one succeeds
    failpoints.enable("s3.upload_part", "1*return(part-lost)")
    stub = S3Stub()
    ctx = flb.create(flush="40ms", grace="3")
    # slow retry: chunk B flushes (and uploads) while A is backing off
    ctx.service_set(**{"scheduler.base": "0.5", "scheduler.cap": "0.6"})
    in_ffd = ctx.input("lib", tag="app")
    ctx.output("s3", match="app", bucket="logs",
               endpoint=f"127.0.0.1:{stub.port}",
               use_put_object="off",
               upload_chunk_size="64", total_file_size="100M",
               store_dir=str(tmp_path / "st5"),
               s3_key_format="/mp/$TAG/obj")
    ctx.start()
    try:
        for i in range(3):  # chunk A: staged, part upload fails → RETRY
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "a" * 30}))
        ctx.flush_now()
        time.sleep(0.15)  # A now parked in backoff
        for i in range(3, 6):  # chunk B: flushes while A backs off
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "b" * 30}))
        ctx.flush_now()
        deadline = time.time() + 8
        while time.time() < deadline and len(stub.by_kind()[1]) < 1:
            time.sleep(0.05)
        time.sleep(1.2)  # let A's retry fire and settle
    finally:
        ctx.stop()
        failpoints.reset()
    stub.close()
    _creates, parts, _completes = stub.by_kind()
    seen = []
    for _, _, body in parts:
        seen += [json.loads(l)["i"]
                 for l in body.decode().strip().splitlines()]
    assert sorted(seen) == list(range(6)), (
        f"interleaved flush defeated staging idempotence: {sorted(seen)}")


def test_multipart_restart_redelivery_no_duplicate_staging(tmp_path,
                                                           monkeypatch):
    """The staged-digest map is persisted in the staging file's fstore
    meta: a filesystem-storage chunk redelivered after a hard restart
    must still dedup against the surviving staging file (in-memory
    tracking alone would resurrect the duplication across a crash)."""
    from fluentbit_tpu import failpoints

    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
    failpoints.reset()
    stub = S3Stub()
    store = tmp_path / "st6"

    def make_ctx():
        c = flb.create(flush="50ms", grace="2",
                       **{"storage.path": str(tmp_path / "chunks")})
        c.service_set(**{"scheduler.base": "30", "scheduler.cap": "30"})
        ffd = c.input("lib", tag="app", **{"storage.type": "filesystem"})
        c.output("s3", match="app", bucket="logs",
                 endpoint=f"127.0.0.1:{stub.port}",
                 use_put_object="off", retry_limit="5",
                 upload_chunk_size="64", total_file_size="100M",
                 store_dir=str(store), s3_key_format="/mp/$TAG/obj")
        return c, ffd

    # phase 1: part upload fails after staging; hard-stop mid-backoff
    failpoints.enable("s3.upload_part", "return(down)")
    ctx, in_ffd = make_ctx()
    ctx.start()
    try:
        for i in range(3):
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "r" * 30}))
        ctx.flush_now()  # stages + fails the part → RETRY parked 30 s
        ctx.engine.outputs[0].plugin.drain = lambda engine: None
    finally:
        ctx.engine.request_stop()
        ctx.stop()
    failpoints.reset()
    assert not stub.by_kind()[1], "phase 1 must not upload any part"

    # phase 2: restart recovers the chunk from disk and redelivers it
    ctx2, _ = make_ctx()
    ctx2.start()
    try:
        deadline = time.time() + 8
        while time.time() < deadline and not stub.by_kind()[1]:
            time.sleep(0.05)
        time.sleep(0.3)
    finally:
        ctx2.stop()
    stub.close()
    _creates, parts, _completes = stub.by_kind()
    assert parts, "restart redelivery must upload the staged part"
    seen = []
    for _, _, body in parts:
        seen += [json.loads(l)["i"]
                 for l in body.decode().strip().splitlines()]
    assert sorted(seen) == list(range(3)), (
        f"restart redelivery duplicated staged records: {sorted(seen)}")


def test_multipart_completed_object_then_retry_dedup(tmp_path,
                                                     monkeypatch):
    """A RETRY-parked chunk whose staged bytes were swept into an
    object that since COMPLETED (staging file deleted) must still
    dedup when its retry lands: the digest map lives in its own
    per-tag sidecar, not the staging file's meta."""
    from fluentbit_tpu import failpoints

    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
    failpoints.reset()
    failpoints.enable("s3.upload_part", "1*return(part-lost)")
    stub = S3Stub()
    store = tmp_path / "st7"
    ctx = flb.create(flush="40ms", grace="3")
    ctx.service_set(**{"scheduler.base": "0.3", "scheduler.cap": "0.4"})
    in_ffd = ctx.input("lib", tag="app")
    # A (~165B) trips upload_chunk_size=64 and FAILS → RETRY; B's later
    # flush pushes the staged total past total_file_size=190 → final
    # part (carrying A+B) + complete + staging-file delete — all while
    # A is still parked in backoff
    ctx.output("s3", match="app", bucket="logs",
               endpoint=f"127.0.0.1:{stub.port}",
               use_put_object="off",
               upload_chunk_size="64", total_file_size="190",
               store_dir=str(store), s3_key_format="/mp/$TAG/obj")
    ctx.start()
    try:
        for i in range(3):  # chunk A
            ctx.push(in_ffd, json.dumps({"i": i, "pad": "c" * 30}))
        ctx.flush_now()
        time.sleep(0.1)
        ctx.push(in_ffd, json.dumps({"i": 3, "pad": "d" * 30}))  # chunk B
        ctx.flush_now()
        deadline = time.time() + 8
        while time.time() < deadline and not stub.by_kind()[2]:
            time.sleep(0.05)
        time.sleep(1.2)  # A's retry fires into the post-complete world
    finally:
        ctx.stop()
        failpoints.reset()
    stub.close()
    _creates, parts, completes = stub.by_kind()
    assert completes, "the object must have completed"
    seen = []
    for _, _, body in parts:
        seen += [json.loads(l)["i"]
                 for l in body.decode().strip().splitlines()]
    assert sorted(seen) == list(range(4)), (
        f"retry after object completion duplicated records: {sorted(seen)}")
    # nothing left staged: A's redelivery deduped instead of re-staging
    leftover = [f for f in os.listdir(store / "s3-s3.0") if
                not f.endswith(".meta")] if (store / "s3-s3.0").exists() \
        else []
    for name in leftover:
        assert os.path.getsize(store / "s3-s3.0" / name) == 0, (
            f"records re-staged after dedup should not exist: {name}")
