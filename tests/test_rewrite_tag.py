"""filter_rewrite_tag + in_emitter: first-match-wins, tag templates,
keep/drop, full pipeline re-entry (reference
plugins/filter_rewrite_tag/rewrite_tag.c:356-407), and the BASELINE
config 3 shape (8 regex rules → out_null).
"""

import json

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events


def run_pipeline(rules, records, extra=None, tag="orig", props=None):
    """in_lib → rewrite_tag(rules) → collect everything per tag."""
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag=tag)
    kw = {"match": tag}
    if props:
        kw.update(props)
    f = ctx.filter("rewrite_tag", **kw)
    for r in rules:
        ctx.set(f, rule=r)
    if extra:
        extra(ctx)
    got = {}
    ctx.output(
        "lib", match="*",
        callback=lambda d, t: got.setdefault(t, []).extend(decode_events(d)),
    )
    ctx.start()
    try:
        for rec in records:
            ctx.push(in_ffd, json.dumps(rec))
        ctx.flush_now()
    finally:
        ctx.stop()
    return got


def test_first_match_wins_and_drop():
    got = run_pipeline(
        ["$level error new.$level false", "$level .* catchall true"],
        [{"level": "error", "m": 1}, {"level": "warn", "m": 2}],
    )
    # error → rule 1 only (first match wins), dropped from orig;
    # warn → rule 2, kept in orig
    assert [e.body["m"] for e in got.get("new.error", [])] == [1]
    assert [e.body["m"] for e in got.get("catchall", [])] == [2]
    assert [e.body["m"] for e in got.get("orig", [])] == [2]


def test_tag_template_captures_tag_parts_fields():
    # unnamed-group capture numbering: $1/$2
    got = run_pipeline(
        [r"$log ([a-z]+)-(\d+) $TAG[1].$1.$2.$kind true"],
        [{"log": "api-42 hello", "kind": "k"}],
        tag="a.b",
    )
    assert "b.api.42.k" in got, sorted(got)


def test_tag_template_ruby_named_capture_numbering():
    # Ruby semantics: with named groups present, unnamed groups do not
    # capture — $1 is the first NAMED group
    got = run_pipeline(
        [r"$log (?<svc>[a-z]+)-(\d+) new.$1 true"],
        [{"log": "api-42 hello"}],
    )
    assert "new.api" in got, sorted(got)


def test_no_match_notouch():
    got = run_pipeline(
        ["$log ^ERROR et false"],
        [{"log": "fine"}, {"log": "also fine"}],
    )
    assert len(got.get("orig", [])) == 2
    assert "et" not in got


def test_reemitted_records_pass_through_filters():
    """Re-emitted records re-enter the FULL chain: a grep filter matching
    the new tag must filter them."""
    def add_grep(ctx):
        ctx.filter("grep", match="rt.*", regex="log keepme")

    got = run_pipeline(
        ["$log .* rt.stream false"],
        [{"log": "keepme 1"}, {"log": "dropme 2"}],
        extra=add_grep,
    )
    logs = [e.body["log"] for e in got.get("rt.stream", [])]
    assert logs == ["keepme 1"]
    assert got.get("orig") is None


def test_emitted_bytes_identical():
    got = run_pipeline(
        ["$log .* moved false"],
        [{"log": "x", "n": 7}],
    )
    evs = got["moved"]
    assert len(evs) == 1
    assert evs[0].body == {"log": "x", "n": 7}


def test_device_path_equivalence_config3(monkeypatch):
    """BASELINE config 3 shape: 8 regex rules, syslog-ish corpus; device
    and CPU paths must produce identical routing. The platform gate is
    forced open (it keeps the kernel off CPU backends in prod)."""
    from fluentbit_tpu.ops import device

    monkeypatch.setattr(device, "platform", lambda: "tpu")
    rules = [
        r"$log sshd sec.ssh false",
        r"$log kernel: sys.kernel false",
        r"$log systemd\[1\] sys.init false",
        r"$log ERROR app.error false",
        r"$log WARN app.warn false",
        r"$log nginx web.nginx false",
        r"$log cron\[\d+\] sys.cron false",
        r"$log .*OOM.* sys.oom false",
    ]
    corpus = []
    for i in range(150):
        which = i % 10
        line = {
            0: f"sshd[{i}]: Accepted publickey",
            1: "kernel: eth0 up",
            2: "systemd[1]: Started unit",
            3: "app ERROR boom",
            4: "app WARN slow",
            5: "nginx 200 GET /",
            6: f"cron[{i}]: job ran",
            7: "invoked OOM killer",
        }.get(which, f"plain message {i}")
        corpus.append({"log": line, "i": i})

    got_dev = run_pipeline(rules, corpus, props={"tpu_batch_records": "1"})
    got_cpu = run_pipeline(rules, corpus, props={"tpu.enable": "off"})
    assert set(got_dev) == set(got_cpu)
    for t in got_cpu:
        assert [e.body for e in got_dev[t]] == [e.body for e in got_cpu[t]], t
    assert "sec.ssh" in got_cpu and "sys.oom" in got_cpu


def test_emit_metric_counted():
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    f = ctx.filter("rewrite_tag", match="t", rule="$log .* moved true")
    ctx.output("null", match="*")
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"log": "a"}))
        ctx.push(in_ffd, json.dumps({"log": "b"}))
        ctx.flush_now()
    finally:
        ctx.stop()
    fam = ctx.metrics.to_prometheus()
    assert 'fluentbit_filter_emit_records_total{name="rewrite_tag.0"} 2' in fam


def test_non_string_field_never_matches():
    """flb_ra_key_regex_match: non-STR values are no-match
    (src/flb_ra_key.c:418)."""
    got = run_pipeline(
        ["$n \\d+ moved false"],
        [{"n": 42, "m": 1}, {"n": "42", "m": 2}],
    )
    assert [e.body["m"] for e in got.get("moved", [])] == [2]
    assert [e.body["m"] for e in got.get("orig", [])] == [1]


def test_failed_tag_translation_keeps_record():
    """Rendered-empty tag = failed translation → record kept even with
    keep=false (reference treats translation failure as no-match)."""
    got = run_pipeline(
        ["$log .* $missing false"],
        [{"log": "hello"}],
    )
    assert len(got.get("orig", [])) == 1
