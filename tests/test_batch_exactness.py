"""Exactly-once side effects on the batched filter chain.

The batch-exactness analyzer (fluentbit_tpu.analysis.batch) encodes the
contract statically; these tests pin it dynamically — the ISSUE 3
satellite: interleave DECLINING stages (parser json over corpora with
bin-typed values, outside the C transcode set) with COMMITTING stages
(log_to_metrics counter incs + snapshot emits, rewrite_tag re-emits)
in randomized orders and corpora, and require counters/emits to fire
exactly once whether the chain runs batched, per-record, or batched-
then-declined mid-chain (the decoded-tail continuation).

Also here: the regression tests for the two bugs the analyzer
surfaced — a snapshot-emit raise after the committed counter inc
double-counting via the decoded-tail rerun (filter_log_to_metrics),
and a mid-loop emitter raise replaying already-emitted groups
(filter_rewrite_tag) — plus the decline-swallow fixes (native table
build failures now logged, fast_count_records narrowed).
"""

import logging
import random

import pytest

from fluentbit_tpu.codec.events import encode_event
from fluentbit_tpu.codec.msgpack import Unpacker
from fluentbit_tpu.core.engine import Engine


def _disable_batch(engine):
    for f in engine.filters:
        f.plugin.can_process_batch = lambda: False


def _drain(ins):
    return b"".join(bytes(c.buf) for c in ins.pool.drain())


def _strip_ts(payload):
    out = []
    for obj in Unpacker(payload):
        obj["meta"]["ts"] = 0
        for m in obj["metrics"]:
            m["ts"] = 0
        out.append(obj)
    return out


def _build_chain(order):
    """Engine with a [committing, declining] chain in the given order:
    log_to_metrics (stateful counter + snapshot emit) and parser json
    (declines the batch when a record's log value is bin-typed)."""
    e = Engine()
    e.parser("jp", format="json")
    for kind in order:
        if kind == "metrics":
            lm = e.filter("log_to_metrics")
            lm.set("regex", "log ERROR")
            lm.set("metric_mode", "counter")
            lm.set("metric_name", "errors")
            lm.set("metric_description", "t")
            lm.set("tag", "metrics")
        elif kind == "parser":
            pf = e.filter("parser")
            pf.set("key_name", "log")
            pf.set("parser", "jp")
        else:  # rewrite_tag
            rt = e.filter("rewrite_tag")
            rt.set("rule", "$route ^go moved.out false")
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    return e, ins


def _corpus(rng, n):
    """Records mixing counter hits, JSON parses, re-tag routes, and —
    randomly — bin-typed log values that force the C transcoder to
    decline mid-chain."""
    recs = []
    for i in range(n):
        doc = '{"v": %d, "sev": "ERROR"}' % i if rng.random() < 0.5 \
            else "ERROR plain %d" % i
        body = {"log": doc.encode() if rng.random() < 0.15 else doc}
        if rng.random() < 0.3:
            body["route"] = "go"
        recs.append(encode_event(body, float(i)))
    return b"".join(recs)


def _run(order, buf, disable):
    e, ins = _build_chain(order)
    if disable:
        _disable_batch(e)
    emitters = [
        (f.display_name, f.plugin.emitter.instance)
        for f in e.filters if getattr(f.plugin, "emitter", None) is not None
    ]
    n = e.input_log_append(ins, "t", buf)
    kept = _drain(ins)
    traffic = []
    counters = []
    for name, em in emitters:
        for c in em.pool.drain():
            payload = bytes(c.buf)
            if c.event_type == "metrics":
                traffic.append((name, c.tag, _strip_ts(payload), c.records))
            else:
                traffic.append((name, c.tag, payload, c.records))
    for f in e.filters:
        cmt = getattr(f.plugin, "cmt", None)
        if cmt is not None:
            counters.append([
                (m.fqname, sorted(m.samples())) for m in cmt.metrics()
            ])
    return n, kept, traffic, counters


ORDERS = (
    ("metrics", "parser"),
    ("parser", "metrics"),
    ("metrics", "rewrite", "parser"),
    ("rewrite", "metrics", "parser"),
    ("parser", "rewrite", "metrics"),
)


def test_property_decline_commit_interleavings_exactly_once():
    """Randomized corpora × chain orders: batched output, emitter
    traffic, and final counter state must equal the per-record path's
    bit-for-bit — including when a stateful stage committed before a
    later stage declined (the decoded-tail continuation)."""
    rng = random.Random(11)
    for trial in range(12):
        order = ORDERS[trial % len(ORDERS)]
        buf = _corpus(rng, rng.randrange(40, 160))
        batched = _run(order, buf, disable=False)
        per_record = _run(order, buf, disable=True)
        assert batched == per_record, (trial, order)


def test_counter_after_decline_counts_exactly_once():
    """The specific double-count shape: log_to_metrics incs (batched),
    then parser declines on a bin value — the tail rerun must NOT inc
    again. Counted against the known ERROR population of the corpus."""
    recs = []
    expect = 0
    for i in range(64):
        doc = '{"v": %d}' % i
        body = {"log": doc.encode() if i % 8 == 0 else "ERROR %d" % i}
        if i % 8 != 0:
            expect += 1
        recs.append(encode_event(body, float(i)))
    buf = b"".join(recs)
    # bin-typed values are excluded by the ≥1-keep-rule contract on
    # both paths (non-matching), so only the str ERROR records count
    e, ins = _build_chain(("metrics", "parser"))
    lm = e.filters[0].plugin
    assert lm.can_process_batch()
    n = e.input_log_append(ins, "t", buf)
    assert n == 64
    assert lm.metric.get(()) == expect


def test_snapshot_emit_raise_after_inc_does_not_double_count():
    """Regression (fbtpu-lint batch-commit-replay, filter_log_to_
    metrics): a raise from the snapshot emit AFTER the committed inc
    used to decline the batch, and the decoded-tail rerun inc'd the
    same records a second time."""
    buf = b"".join(encode_event({"log": "ERROR %d" % i}, float(i))
                   for i in range(32))
    e, ins = _build_chain(("metrics",))
    lm = e.filters[0].plugin
    assert lm.can_process_batch()

    def boom(*a, **k):
        raise RuntimeError("emitter down")

    lm.emitter.add_event = boom
    n = e.input_log_append(ins, "t", buf)
    assert n == 32
    assert lm.metric.get(()) == 32  # exactly once, not 64
    assert lm._dirty  # snapshot deferred, not lost


def test_rewrite_emitter_raise_mid_groups_keeps_exactly_once(caplog):
    """Regression (fbtpu-lint batch-commit-replay, filter_rewrite_tag):
    a raise on the SECOND group's append used to propagate, decline the
    batch, and re-emit the first group's records on the rerun. Now the
    failed group degrades to the backpressure outcome (originals kept)
    and committed groups stay single-shot."""
    rules = ["$log ^alpha routed.alpha false",
             "$log ^beta routed.beta false"]
    e = Engine()
    rt = e.filter("rewrite_tag")
    for r in rules:
        rt.set("rule", r)
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    plugin = e.filters[0].plugin
    assert plugin.can_process_batch()
    em = plugin.emitter
    real_add = em.add_record

    def flaky(tag, data, count):
        if tag == "routed.beta":
            raise RuntimeError("emitter down")
        return real_add(tag, data, count)

    em.add_record = flaky
    buf = b"".join(
        encode_event({"log": ("alpha %d" if i % 2 else "beta %d") % i},
                     float(i))
        for i in range(32))
    with caplog.at_level(logging.ERROR, logger="flb"):
        n = e.input_log_append(ins, "t", buf)
    chunks = em.instance.pool.drain()
    emitted = {(c.tag, c.records) for c in chunks}
    assert emitted == {("routed.alpha", 16)}  # once, not twice
    # beta originals kept (backpressure semantics), alphas dropped
    assert n == 16
    assert any("emitter append failed" in r.message for r in caplog.records)


def test_native_table_build_failure_logs_and_declines(caplog, monkeypatch):
    """decline-swallow fix: a native table builder raising is no longer
    an invisible permanent fallback — it logs, and the filter serves
    the per-record path."""
    import fluentbit_tpu.native as native

    if not native.available():
        pytest.skip("native library unavailable")

    class Boom:
        def __init__(self, *a, **k):
            raise RuntimeError("table builder bug")

    monkeypatch.setattr(native, "GrepTables", Boom)
    e = Engine()
    e.parser("rp", format="regex", regex=r"^(?<w>ERROR) (?<n>\d+)$")
    pf = e.filter("parser")
    pf.set("key_name", "log")
    pf.set("parser", "rp")
    ins = e.input("dummy")
    with caplog.at_level(logging.WARNING, logger="flb"):
        for x in e.inputs + e.filters:
            x.configure()
            x.plugin.init(x, e)
    plugin = e.filters[0].plugin
    assert not plugin.can_process_batch()
    assert any("native table build failed" in r.message
               for r in caplog.records)
    # the per-record path still parses
    buf = encode_event({"log": "ERROR 7"}, 1.0)
    n = e.input_log_append(ins, "t", buf)
    assert n == 1
    out = _drain(ins)
    from fluentbit_tpu.codec.events import decode_events

    assert decode_events(out)[0].body == {"w": "ERROR", "n": "7"}


def test_fast_count_records_narrowed_decline():
    """decline-swallow fix: fast_count_records still maps malformed /
    hostile-nesting buffers to None, but an unexpected bug now
    propagates instead of hiding as a silent fallback."""
    from fluentbit_tpu.codec import events as ev

    assert ev.fast_count_records(
        encode_event({"a": 1}, 1.0) + encode_event({"b": 2}, 2.0)) == 2
    # deep hostile nesting: None (not a crash) even without the native
    # scanner
    import fluentbit_tpu.native as native

    real = native.count_records
    try:
        native.count_records = lambda buf: None
        deep = b"\x91" * 5000 + b"\x90"
        assert ev.fast_count_records(deep) is None
        assert ev.fast_count_records(b"\xc1\xc1\xc1") is None

        def raising(buf):
            raise TypeError("real bug")

        real_count = ev.count_records
        try:
            ev.count_records = raising
            with pytest.raises(TypeError):
                ev.fast_count_records(b"\x90")
        finally:
            ev.count_records = real_count
    finally:
        native.count_records = real


def test_engine_batch_decline_metric():
    """The new fluentbit_filter_batch_declines_total counter makes the
    invisible (bit-exact) decline visible in ops."""
    recs = []
    for i in range(16):
        doc = '{"v": %d}' % i
        # one bin value forces the whole-chunk transcoder to decline
        recs.append(encode_event(
            {"log": doc.encode() if i == 3 else doc}, float(i)))
    buf = b"".join(recs)
    e = Engine()
    e.parser("jp", format="json")
    pf = e.filter("parser")
    pf.set("key_name", "log")
    pf.set("parser", "jp")
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    if not e.filters[0].plugin.can_process_batch():
        pytest.skip("native codec unavailable")
    name = e.filters[0].display_name
    n = e.input_log_append(ins, "t", buf)
    assert n == 16
    assert e.m_filter_batch_decline.get((name,)) == 1
