"""Round-3 plugin tail: websocket, pgsql, azure_blob,
kubernetes_events, process_exporter_metrics — each against a local
stub (the reference's runtime-test pattern: start the plugin, point it
at a loopback server, assert the wire payload)."""

import asyncio
import base64
import hashlib
import json
import socket
import struct
import threading
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events


class _StubServer:
    """Threaded asyncio TCP stub; subclass provides handle(reader,
    writer)."""

    def __init__(self, handler):
        self.handler = handler
        self.port = None
        self.received = []
        self._loop = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        deadline = time.time() + 5
        while self.port is None and time.time() < deadline:
            time.sleep(0.02)
        assert self.port is not None
        return self

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)

    def _run(self):
        async def on_conn(reader, writer):
            try:
                await self.handler(self, reader, writer)
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def main():
            server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
            self.port = server.sockets[0].getsockname()[1]

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(main())
        self._loop.run_forever()


# ------------------------------------------------------------ websocket

async def _ws_stub(srv, reader, writer):
    # handshake
    req = bytearray()
    while not req.endswith(b"\r\n\r\n"):
        req += await reader.readexactly(1)
    key = ""
    for line in req.decode().split("\r\n"):
        if line.lower().startswith("sec-websocket-key:"):
            key = line.split(":", 1)[1].strip()
    accept = base64.b64encode(hashlib.sha1(
        (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
    ).digest()).decode()
    writer.write((
        "HTTP/1.1 101 Switching Protocols\r\nUpgrade: websocket\r\n"
        f"Connection: Upgrade\r\nSec-WebSocket-Accept: {accept}\r\n\r\n"
    ).encode())
    await writer.drain()
    # read frames (client frames are masked)
    while True:
        head = await reader.readexactly(2)
        opcode = head[0] & 0x0F
        masked = head[1] & 0x80
        n = head[1] & 0x7F
        if n == 126:
            n = struct.unpack("!H", await reader.readexactly(2))[0]
        elif n == 127:
            n = struct.unpack("!Q", await reader.readexactly(8))[0]
        mask = await reader.readexactly(4) if masked else b"\0\0\0\0"
        payload = bytearray(await reader.readexactly(n))
        for i in range(len(payload)):
            payload[i] ^= mask[i % 4]
        if opcode == 0x8:
            return
        srv.received.append((opcode, bytes(payload)))


def test_websocket_output_delivers_frames():
    srv = _StubServer(_ws_stub).start()
    try:
        ctx = flb.create(flush="50ms", grace="1")
        in_ffd = ctx.input("lib")
        ctx.output("websocket", match="*", host="127.0.0.1",
                   port=str(srv.port), format="json_lines")
        ctx.start()
        try:
            ctx.push(in_ffd, '{"msg": "over ws"}')
            deadline = time.time() + 8
            while not srv.received and time.time() < deadline:
                time.sleep(0.05)
        finally:
            ctx.stop()
    finally:
        srv.stop()
    assert srv.received, "no websocket frame arrived"
    opcode, payload = srv.received[0]
    assert opcode == 0x1  # text frame for json_lines
    assert json.loads(payload)["msg"] == "over ws"


# ------------------------------------------------------------ pgsql

async def _pg_stub(srv, reader, writer):
    # startup message
    (length,) = struct.unpack("!I", await reader.readexactly(4))
    await reader.readexactly(length - 4)
    writer.write(b"R" + struct.pack("!II", 8, 0))       # AuthenticationOk
    writer.write(b"Z" + struct.pack("!I", 5) + b"I")    # ReadyForQuery
    await writer.drain()
    while True:
        tag = await reader.readexactly(1)
        (length,) = struct.unpack("!I", await reader.readexactly(4))
        body = await reader.readexactly(length - 4)
        if tag == b"X":
            return
        if tag == b"Q":
            srv.received.append(body.rstrip(b"\x00").decode())
            # CommandComplete + ReadyForQuery
            writer.write(b"C" + struct.pack("!I", 11) + b"INSERT\x00")
            writer.write(b"Z" + struct.pack("!I", 5) + b"I")
            await writer.drain()


def test_pgsql_output_inserts_rows():
    srv = _StubServer(_pg_stub).start()
    try:
        ctx = flb.create(flush="50ms", grace="1")
        in_ffd = ctx.input("lib")
        ctx.output("pgsql", match="*", host="127.0.0.1",
                   port=str(srv.port), table="logs", user="u",
                   database="db")
        ctx.start()
        try:
            ctx.push(in_ffd, '{"msg": "o\'brien"}')
            deadline = time.time() + 8
            while len(srv.received) < 2 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            ctx.stop()
    finally:
        srv.stop()
    assert any("CREATE TABLE IF NOT EXISTS logs" in q
               for q in srv.received)
    inserts = [q for q in srv.received if q.startswith("INSERT")]
    assert inserts, srv.received
    assert "INSERT INTO logs (time, tag, data) VALUES" in inserts[0]
    # single-quote escaping: o'brien → o''brien inside the literal
    assert "o''brien" in inserts[0]


# ------------------------------------------------------------ azure_blob

async def _http_capture_stub(srv, reader, writer):
    while True:
        req = bytearray()
        while not req.endswith(b"\r\n\r\n"):
            b = await reader.readexactly(1)
            req += b
        head = req.decode("latin-1")
        length = 0
        for line in head.split("\r\n"):
            if line.lower().startswith("content-length:"):
                length = int(line.split(":", 1)[1])
        body = await reader.readexactly(length) if length else b""
        srv.received.append((head.split("\r\n")[0], head, body))
        writer.write(b"HTTP/1.1 201 Created\r\nContent-Length: 0\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        return


def test_azure_blob_appendblob_flow():
    srv = _StubServer(_http_capture_stub).start()
    try:
        ctx = flb.create(flush="50ms", grace="1")
        in_ffd = ctx.input("lib")
        ctx.output("azure_blob", match="*", host="127.0.0.1",
                   port=str(srv.port), account_name="acct",
                   shared_key=base64.b64encode(b"secret").decode(),
                   container_name="logs", blob_type="appendblob",
                   emulator_mode="on", tls="off")
        ctx.start()
        try:
            ctx.push(in_ffd, '{"msg": "to blob"}')
            deadline = time.time() + 8
            while len(srv.received) < 3 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            ctx.stop()
    finally:
        srv.stop()
    lines = [r[0] for r in srv.received]
    # container create → blob create → append block
    assert any("restype=container" in l for l in lines), lines
    assert any("comp=appendblock" in l for l in lines), lines
    for _, head, _ in srv.received:
        assert "Authorization: SharedKey acct:" in head
        assert "x-ms-date:" in head
    append_bodies = [b for l, _, b in srv.received
                     if "comp=appendblock" in l]
    assert append_bodies and b"to blob" in append_bodies[0]


# ------------------------------------------------------ kubernetes_events

K8S_EVENTS = {
    "kind": "EventList",
    "metadata": {"resourceVersion": "100"},
    "items": [
        {"metadata": {"uid": "u1", "resourceVersion": "90",
                      "name": "pod-x.1"},
         "reason": "Scheduled", "message": "ok",
         "involvedObject": {"kind": "Pod", "name": "pod-x"},
         "lastTimestamp": "2026-07-29T01:02:03Z"},
        {"metadata": {"uid": "u2", "resourceVersion": "95",
                      "name": "pod-y.1"},
         "reason": "BackOff", "message": "restarting",
         "involvedObject": {"kind": "Pod", "name": "pod-y"},
         "eventTime": "2026-07-29T02:03:04.123456Z"},
    ],
}


async def _k8s_stub(srv, reader, writer):
    req = bytearray()
    while not req.endswith(b"\r\n\r\n"):
        req += await reader.readexactly(1)
    srv.received.append(req.decode("latin-1"))
    body = json.dumps(K8S_EVENTS).encode()
    writer.write((f"HTTP/1.1 200 OK\r\nContent-Length: {len(body)}\r\n"
                  f"Connection: close\r\n\r\n").encode() + body)
    await writer.drain()


def test_kubernetes_events_input_polls_and_dedups():
    srv = _StubServer(_k8s_stub).start()
    got = []
    try:
        ctx = flb.create(flush="50ms", grace="1")
        ctx.input("kubernetes_events", tag="k8s",
                  kube_url=f"http://127.0.0.1:{srv.port}",
                  kube_token_file="/nonexistent", interval_sec="1")
        ctx.output("lib", match="*",
                   callback=lambda d, tag: got.extend(decode_events(d)))
        ctx.start()
        try:
            deadline = time.time() + 8
            while len(srv.received) < 2 and time.time() < deadline:
                time.sleep(0.05)  # at least two polls happened
            time.sleep(0.3)
        finally:
            ctx.stop()
    finally:
        srv.stop()
    assert len(srv.received) >= 2
    # dedup: two Event objects total despite repeated polls
    assert len(got) == 2
    reasons = {ev.body["reason"] for ev in got}
    assert reasons == {"Scheduled", "BackOff"}
    # timestamp came from lastTimestamp, not receive time
    ts = [ev for ev in got if ev.body["reason"] == "Scheduled"][0]
    assert abs(ts.ts_float - 1785286923.0) < 1.0


# -------------------------------------------------- process_exporter

def test_process_exporter_metrics_scrapes_procfs():
    from fluentbit_tpu.core.plugin import registry as reg

    ins = reg.create_input("process_exporter_metrics")
    ins.configure()
    ins.plugin.init(ins, None)

    captured = {}

    class _Eng:
        def input_event_append(self, instance, tag, payload, etype,
                               n_records=1):
            captured["payload"] = payload
            captured["etype"] = etype
            captured["n"] = n_records
            return n_records

    ins.plugin.collect(_Eng())
    assert captured, "no metrics emitted"
    from fluentbit_tpu.codec.msgpack import unpackb

    obj = unpackb(captured["payload"])
    names = {m["name"] for m in obj["metrics"]}
    assert "process_cpu_seconds_total" in names
    assert "process_resident_memory_bytes" in names
    assert "process_count" in names
    # this very python process appears
    counts = [m for m in obj["metrics"]
              if m["name"] == "process_count"][0]
    all_names = {tuple(v["labels"])[0] for v in counts["values"]}
    assert any("python" in n for n in all_names)
