"""Output worker OS threads (flb_output_thread.c equivalent).

`workers N` must run flush callbacks on dedicated threads with their
own event loops (round-robin), keep keepalive connections loop-affine,
invoke worker_init/exit hooks, and tear down cleanly at stop."""

import json
import socket
import threading
import time

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events


def wait_for(cond, timeout=8.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.05)
    raise TimeoutError()


def test_lib_output_callback_runs_on_worker_thread():
    got = []

    def cb(data, tag):
        got.append((threading.current_thread().name, data))

    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("lib", match="t", callback=cb, workers="2")
    out_ins = ctx.engine.outputs[0]
    ctx.start()
    try:
        for i in range(6):
            ctx.push(in_ffd, json.dumps({"i": i}))
            ctx.flush_now()
            time.sleep(0.08)
        wait_for(lambda: len(got) >= 2)
        assert out_ins.worker_pool is not None
    finally:
        ctx.stop()
    names = {name for name, _ in got}
    assert all(name.startswith("flb-out-") for name in names), names
    # pool torn down at stop
    assert out_ins.worker_pool is None
    # records intact across the thread hop
    bodies = [e.body for _, d in got for e in decode_events(d)]
    assert {"i": 0} in bodies


def test_http_delivery_with_workers_and_keepalive():
    """Several flushes through `workers 2` against a keep-alive server:
    exercises the per-loop connection buckets in core.upstream."""
    reqs = []
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    port = srv.getsockname()[1]
    stop = threading.Event()

    def serve():
        srv.settimeout(0.2)
        conns = []
        while not stop.is_set():
            try:
                c, _ = srv.accept()
                c.settimeout(0.2)
                conns.append(c)
            except socket.timeout:
                pass
            for c in conns:
                try:
                    data = c.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    continue
                if data:
                    reqs.append(data)
                    try:
                        c.sendall(b"HTTP/1.1 200 OK\r\n"
                                  b"Content-Length: 0\r\n\r\n")
                    except OSError:
                        pass
        for c in conns:
            c.close()

    thr = threading.Thread(target=serve, daemon=True)
    thr.start()

    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("http", match="t", host="127.0.0.1", port=str(port),
               workers="2", format="json")
    ctx.start()
    try:
        for i in range(5):
            ctx.push(in_ffd, json.dumps({"seq": i}))
            ctx.flush_now()
            time.sleep(0.06)
        wait_for(lambda: len(reqs) >= 3)
    finally:
        ctx.stop()
        stop.set()
        thr.join(timeout=3)
        srv.close()
    assert any(b"POST / HTTP/1.1" in r for r in reqs)


def test_worker_init_exit_hooks():
    from fluentbit_tpu.core.output_thread import OutputWorkerPool

    events = []

    class Hooked:
        synchronous = False

        def worker_init(self, i):
            events.append(("init", i))

        def worker_exit(self, i):
            events.append(("exit", i))

    pool = OutputWorkerPool("hooked", 2, Hooked())
    ran = []

    async def job(n):
        ran.append((n, threading.current_thread().name))
        return n * 2

    import asyncio

    async def driver():
        return [await pool.submit(job(i)) for i in range(4)]

    results = asyncio.run(driver())
    pool.stop()
    assert results == [0, 2, 4, 6]
    assert {e for e in events if e[0] == "init"} == {("init", 0),
                                                     ("init", 1)}
    assert {e for e in events if e[0] == "exit"} == {("exit", 0),
                                                     ("exit", 1)}
    # round-robin across both workers
    assert len({name for _, name in ran}) == 2
