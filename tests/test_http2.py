"""HTTP/2 (h2c) — HPACK, framing, in_http server, OTLP h2 export.

Reference: src/flb_http_client_http2.c (nghttp2 client) and in_http's
HTTP/2 support. Done-criteria: in_http accepts an HTTP/2 POST;
out_opentelemetry speaks h2c to a test server.
"""

import asyncio
import json
import socket
import threading
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.core.http2 import (PREFACE, Http2Client, HpackCodec,
                                      _HUFF, grpc_unwrap, grpc_wrap,
                                      huffman_decode, serve_h2c)


def _huffman_encode(data: bytes) -> bytes:
    """Test-side encoder (the codec itself only decodes)."""
    bits = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, length = _HUFF[b]
        bits = (bits << length) | code
        nbits += length
        while nbits >= 8:
            out.append((bits >> (nbits - 8)) & 0xFF)
            nbits -= 8
    if nbits:
        out.append(((bits << (8 - nbits)) | ((1 << (8 - nbits)) - 1))
                   & 0xFF)
    return bytes(out)


def test_hpack_round_trip_and_dynamic_table():
    enc = HpackCodec()
    dec = HpackCodec()
    headers = [(":method", "POST"), (":path", "/v1/logs"),
               ("content-type", "application/json"),
               ("x-custom", "abc123"), ("authorization", "Bearer tok")]
    block = enc.encode(headers)
    assert dec.decode(block) == [(k.lower(), v) for k, v in headers]
    # second block reuses the decoder state without corruption
    block2 = enc.encode(headers)
    assert dec.decode(block2) == [(k.lower(), v) for k, v in headers]


def test_hpack_huffman_decode():
    for s in (b"www.example.com", b"/custom/path?q=1",
              b"no-cache", bytes(range(32, 127))):
        assert huffman_decode(_huffman_encode(s)) == s
    # huffman-coded literal header (as curl sends): flag bit 0x80 set
    val = _huffman_encode(b"hello-world")
    block = bytes([0x00]) + bytes([0x01]) + b"x" \
        + bytes([0x80 | len(val)]) + val
    assert HpackCodec().decode(block) == [("x", "hello-world")]


def test_grpc_framing():
    msgs = [b"abc", b"", b"x" * 1000]
    data = b"".join(grpc_wrap(m) for m in msgs)
    assert grpc_unwrap(data) == msgs


def _h2_post(port, path, body, content_type="application/json"):
    """Blocking helper: one h2c POST from a worker thread."""
    result = {}

    async def run():
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        client = Http2Client(reader, writer)
        status, resp = await client.request(
            "POST", f"127.0.0.1:{port}", path,
            [("content-type", content_type)], body, timeout=10)
        result["status"] = status
        result["resp"] = resp
        client.close()

    asyncio.run(run())
    return result


def test_in_http_accepts_http2_post():
    got = []
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("http", listen="127.0.0.1", port="0")
    ctx.output("lib", match="*",
               callback=lambda d, tag: got.extend(decode_events(d)))
    ctx.start()
    try:
        plugin = ctx.engine.inputs[0].plugin
        deadline = time.time() + 5
        while plugin.bound_port is None and time.time() < deadline:
            time.sleep(0.02)
        res = _h2_post(plugin.bound_port, "/app.log",
                       json.dumps({"k": "v", "n": 7}).encode())
        assert res["status"] == 201
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx.stop()
    assert got and got[0].body == {"k": "v", "n": 7}
    # HTTP/1.1 on the same listener still works after the h2 upgrade path
    ctx2 = flb.create(flush="50ms", grace="1")
    ctx2.input("http", listen="127.0.0.1", port="0")
    got2 = []
    ctx2.output("lib", match="*",
                callback=lambda d, tag: got2.extend(decode_events(d)))
    ctx2.start()
    try:
        plugin = ctx2.engine.inputs[0].plugin
        deadline = time.time() + 5
        while plugin.bound_port is None and time.time() < deadline:
            time.sleep(0.02)
        body = b'{"a": 1}'
        with socket.create_connection(("127.0.0.1", plugin.bound_port),
                                      timeout=5) as s:
            s.sendall(b"POST /t HTTP/1.1\r\nHost: x\r\n"
                      b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
            assert b" 201 " in s.recv(1024)
        deadline = time.time() + 5
        while not got2 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx2.stop()
    assert got2 and got2[0].body == {"a": 1}


class _H2TestServer:
    """Minimal h2c collector server running on its own thread."""

    def __init__(self):
        self.requests = []
        self.port = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._loop = None

    def start(self):
        self._thread.start()
        deadline = time.time() + 5
        while self.port is None and time.time() < deadline:
            time.sleep(0.02)
        assert self.port is not None

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)

    def _run(self):
        async def handler(method, path, headers, body):
            self.requests.append((method, path, body))
            return 200, b"{}", "application/json"

        async def on_conn(reader, writer):
            try:
                await serve_h2c(reader, writer, handler)
            except Exception:
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def main():
            server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
            self.port = server.sockets[0].getsockname()[1]

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(main())
        self._loop.run_forever()


def test_out_opentelemetry_speaks_h2c():
    srv = _H2TestServer()
    srv.start()
    try:
        ctx = flb.create(flush="50ms", grace="1")
        in_ffd = ctx.input("lib")
        ctx.output("opentelemetry", match="*", host="127.0.0.1",
                   port=str(srv.port), http2="on")
        ctx.start()
        try:
            ctx.push(in_ffd, '{"message": "over h2"}')
            deadline = time.time() + 8
            while not srv.requests and time.time() < deadline:
                time.sleep(0.05)
        finally:
            ctx.stop()
    finally:
        srv.stop()
    assert srv.requests, "h2c server never saw the OTLP export"
    method, path, body = srv.requests[0]
    assert method == "POST" and path == "/v1/logs"
    wire = json.loads(body)
    rec = wire["resourceLogs"][0]["scopeLogs"][0]["logRecords"][0]
    assert rec["body"]["stringValue"] == "over h2"


def test_h2_large_body_flow_control():
    """A body well past the 65535-byte default send window must deliver
    intact — the client waits for WINDOW_UPDATEs instead of blasting
    past the peer's window (RFC 7540 §5.2)."""
    srv = _H2TestServer()
    srv.start()
    try:
        big = json.dumps({"data": "x" * 300_000}).encode()
        res = _h2_post(srv.port, "/big", big)
        assert res["status"] == 200
        deadline = time.time() + 5
        while not srv.requests and time.time() < deadline:
            time.sleep(0.02)
    finally:
        srv.stop()
    method, path, body = srv.requests[0]
    assert path == "/big" and body == big
