"""fbtpu-armor: the device fault domain (ops/fault.py + the retry-world
attach controller in ops/device.py).

Covers: attach retry/backoff lifecycle (attempt counting, exhaustion
semantics, re-attach generations, status() reporting), the DeviceLane
launch guard (bit-exact CPU fallback on injected failures, deadline
soft-kill of hung launches, breaker open → short-circuit → half-open →
closed), mesh shrink on device loss + regrow on recovery, the
donated-buffer re-stage regression (a retry after a launch that
consumed its donated staged lengths buffer must re-stage from host
arrays, never touch the deleted aval), the grep mesh lane's re-attach
generation swap-in, and flux sketch re-materialization from the
host-pinned twins after device faults.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from fluentbit_tpu import failpoints
from fluentbit_tpu.ops import device, fault
from fluentbit_tpu.ops import mesh as om
from fluentbit_tpu.ops.batch import assemble
from fluentbit_tpu.ops.grep import program_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.mesh


@pytest.fixture(autouse=True)
def _clean_plane():
    failpoints.reset()
    fault.reset()
    yield
    failpoints.reset()
    fault.reset()


def _subproc(code: str, env_extra: dict, timeout: float = 90):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_extra)
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, cwd=REPO,
                          timeout=timeout)


# ------------------------------------------- attach retry lifecycle


def test_attach_retries_then_succeeds():
    """Two injected refusals, third attempt lands: the device swaps in
    live (state ready) and status() records the retry history."""
    code = (
        "from fluentbit_tpu.ops import device\n"
        "assert device.wait(60), device.status()\n"
        "st = device.status()\n"
        "assert st['state'] == 'ready', st\n"
        "assert st['attempts'] == 3, st\n"
        "assert len(st['retry_history']) == 2, st\n"
        "assert st['generation'] == 1, st\n"
    )
    proc = _subproc(code, {
        "FBTPU_FAILPOINTS": "device.attach=2*return(flaky-terminal)",
        "FBTPU_ATTACH_RETRIES": "4",
        "FBTPU_ATTACH_BACKOFF_S": "0.05",
    })
    assert proc.returncode == 0, proc.stderr


def test_attach_exhausts_then_reattach_swaps_in():
    """failed() means EXHAUSTED (all attempts burned), the history
    names every attempt — and reattach_async() re-arms a fresh budget
    that can succeed later (a new attach generation)."""
    code = (
        "from fluentbit_tpu import failpoints\n"
        "from fluentbit_tpu.ops import device\n"
        "assert not device.wait(30)\n"
        "assert device.failed(), device.status()\n"
        "st = device.status()\n"
        "assert st['attempts'] == 2, st\n"
        "assert len(st['retry_history']) == 2, st\n"
        "assert st['next_retry_eta_s'] is None, st\n"
        "assert st['generation'] == 0, st\n"
        "failpoints.reset()\n"
        "assert device.reattach_async()\n"
        "assert device.wait(60), device.status()\n"
        "assert device.generation() == 1, device.status()\n"
    )
    proc = _subproc(code, {
        "FBTPU_FAILPOINTS": "device.attach=return(refused)",
        "FBTPU_ATTACH_RETRIES": "2",
        "FBTPU_ATTACH_BACKOFF_S": "0.05",
    })
    assert proc.returncode == 0, proc.stderr


def test_attach_status_mid_retry_reports_eta():
    """Between attempts the controller is ATTACHING (not failed) and
    status() exposes the next-retry ETA — the bench heartbeat's
    diagnosable block."""
    code = (
        "import time\n"
        "from fluentbit_tpu.ops import device\n"
        "device.attach_async()\n"
        "time.sleep(1.0)\n"  # first attempt failed; long backoff running
        "st = device.status()\n"
        "assert st['state'] == 'attaching', st\n"
        "assert not device.failed()\n"
        "assert st['attempts'] == 1, st\n"
        "assert st['next_retry_eta_s'] is not None, st\n"
    )
    proc = _subproc(code, {
        "FBTPU_FAILPOINTS": "device.attach=1*return(flaky)",
        "FBTPU_ATTACH_RETRIES": "2",
        "FBTPU_ATTACH_BACKOFF_S": "30",
    })
    assert proc.returncode == 0, proc.stderr


# ------------------------------------------------- lane fundamentals


PATTERNS = ("GET|POST", "^kernel:")
VALS = [b"GET /a HTTP/1.1", b"kernel: oops", None, b"POST /b",
        b"zzz", b""] * 5


def _staged(L=96):
    b = assemble(VALS, L)
    return (np.stack([b.batch] * len(PATTERNS)),
            np.stack([b.lengths] * len(PATTERNS)))


def _ref_mask(batch, lengths, cnt):
    from fluentbit_tpu.regex import FlbRegex

    out = np.zeros((len(PATTERNS), cnt), dtype=bool)
    for r, p in enumerate(PATTERNS):
        rx = FlbRegex(p)
        for i in range(cnt):
            li = int(lengths[r, i])
            if li >= 0:
                out[r, i] = rx.match(
                    bytes(batch[r, i, :li]).decode("utf-8"))
    return out


def _mesh_or_skip(n=8):
    assert device.wait(60), device.status()
    if len(jax.devices()) < n:
        pytest.skip(f"need {n} devices")
    return om.build_mesh(n)


def _grep_launch(prog, mesh, batch, lengths):
    def launch():
        m_i32, _, _b, _bp = prog.dispatch_mesh(
            mesh, batch, lengths, with_counts=False)
        return np.asarray(m_i32).astype(bool)

    return launch


def test_lane_fallback_bit_exact_after_post_donation_failure():
    """The donated-buffer regression: device.dispatch fires at the
    POST-launch boundary, i.e. after dispatch_mesh consumed the donated
    staged lengths buffer. The lane's fallback must produce the
    bit-exact verdict from the HOST arrays (re-stage, not the deleted
    aval), and the next launch (fresh device_put) must succeed."""
    mesh = _mesh_or_skip()
    prog = program_for(PATTERNS, 96)
    batch, lengths = _staged()
    cnt = batch.shape[1]
    ref = _ref_mask(batch, lengths, cnt)
    lane = fault.DeviceLane("t-donate", failures=5)
    launch = _grep_launch(prog, mesh, batch, lengths)
    fb = lambda: _ref_mask(batch, lengths, cnt)  # noqa: E731

    clean = lane.run(launch, fb)
    assert np.array_equal(clean[:, :cnt], ref)

    failpoints.enable("device.dispatch", "1*return(post-donation)")
    got = lane.run(launch, fb)
    assert np.array_equal(got[:, :cnt], ref), \
        "fallback verdict must be bit-exact"
    st = lane.stats()
    assert st["failures"] == 1 and st["fallback_segments"] == 1

    failpoints.reset()
    again = lane.run(launch, fb)  # retry re-stages: no deleted-aval read
    assert np.array_equal(again[:, :cnt], ref)
    assert lane.stats()["ok"] == 2


def test_donation_consumed_buffer_would_raise_without_restage():
    """The hazard the lane's re-stage protocol avoids, demonstrated
    directly: after one dispatch the donated lengths device buffer is
    deleted; re-launching against the SAME buffers raises instead of
    silently reading verdict bytes. (The launch closures re-device_put
    from host arrays on every attempt, so they never hit this.)"""
    mesh = _mesh_or_skip()
    prog = program_for(PATTERNS, 96)
    batch, lengths = _staged()
    h = prog._mesh_handle(mesh, "auto", False)
    Bp = om.pad_to_devices(batch.shape[1], h.n_devices)
    if Bp != batch.shape[1]:
        pad = Bp - batch.shape[1]
        batch = np.concatenate(
            [batch, np.zeros((2, pad, 96), dtype=np.uint8)], axis=1)
        lengths = np.concatenate(
            [lengths, np.full((2, pad), -1, dtype=np.int32)], axis=1)
    bd = jax.device_put(np.ascontiguousarray(batch), h.sh_b)
    ld = jax.device_put(np.ascontiguousarray(lengths), h.sh_l)
    np.asarray(h.fn(h.tables, bd, ld))
    assert ld.is_deleted(), "donation must consume the staged buffer"
    with pytest.raises(Exception):
        np.asarray(h.fn(h.tables, bd, ld))


def test_lane_deadline_soft_kills_hung_launch():
    """An armed device.launch_hang wedges the launch worker; the lane
    soft-kills at its deadline, the segment completes on the fallback,
    and the late worker's result is discarded (commit-on-finish)."""
    mesh = _mesh_or_skip()
    prog = program_for(PATTERNS, 96)
    batch, lengths = _staged()
    cnt = batch.shape[1]
    ref = _ref_mask(batch, lengths, cnt)
    lane = fault.DeviceLane("t-hang", deadline=0.4)
    failpoints.enable("device.launch_hang", "1*hang(3000)")
    t0 = time.time()
    got = lane.run(_grep_launch(prog, mesh, batch, lengths),
                   lambda: _ref_mask(batch, lengths, cnt))
    took = time.time() - t0
    assert took < 2.5, f"soft-kill did not engage ({took:.1f}s)"
    assert np.array_equal(got[:, :cnt], ref)
    st = lane.stats()
    assert st["timeouts"] == 1 and st["abandoned"] == 1


def test_lane_breaker_opens_short_circuits_and_recovers():
    """Consecutive failures open the breaker; open short-circuits
    straight to the fallback (no device touch); after the cooldown one
    half-open probe closes it on success."""
    lane = fault.DeviceLane("t-breaker", failures=2, cooldown=0.2)
    boom = lambda: (_ for _ in ()).throw(RuntimeError("xla boom"))  # noqa: E731
    fb = lambda: "cpu"  # noqa: E731
    assert lane.run(boom, fb) == "cpu"
    assert lane.run(boom, fb) == "cpu"
    assert lane.breaker.state_name() == "open"
    assert lane.stats()["breaker_trips"] == 1
    # open: the launch is never attempted (device untouched)
    ran = []
    assert lane.run(lambda: ran.append(1), fb) == "cpu"
    assert not ran and lane.stats()["short_circuits"] == 1
    time.sleep(0.25)
    assert lane.run(lambda: "device", fb) == "device"  # half-open probe
    assert lane.breaker.state_name() == "closed"


def test_lane_device_lost_shrinks_then_regrows():
    """mesh.device_lost shrinks the lane's mesh to the survivors
    (bit-exact verdicts continue); when the breaker re-closes the mesh
    regrows to the full device set."""
    mesh = _mesh_or_skip()
    prog = program_for(PATTERNS, 96)
    batch, lengths = _staged()
    cnt = batch.shape[1]
    ref = _ref_mask(batch, lengths, cnt)
    lane = fault.DeviceLane("t-lost", failures=1, cooldown=0.1)
    assert lane.current_mesh().devices.size == 8

    def launch():
        m = lane.current_mesh()
        m_i32, _, _b, _bp = prog.dispatch_mesh(
            m, batch, lengths, with_counts=False)
        return np.asarray(m_i32).astype(bool)

    fb = lambda: _ref_mask(batch, lengths, cnt)  # noqa: E731
    failpoints.enable("mesh.device_lost", "1*return(lost)")
    got = lane.run(launch, fb)
    assert np.array_equal(got[:, :cnt], ref)
    assert lane.stats()["device_lost"] == 1
    assert lane.current_mesh().devices.size == 7, \
        "mesh must shrink to the survivors"
    assert lane.breaker.state_name() == "open"  # failures=1
    # the shrunk mesh serves bit-exactly while the breaker recovers
    time.sleep(0.15)
    got2 = lane.run(launch, fb)  # half-open probe on the 7-device mesh
    assert np.array_equal(got2[:, :cnt], ref)
    assert lane.breaker.state_name() == "closed"
    assert lane.current_mesh().devices.size == 8, \
        "breaker re-close must regrow the mesh"


def test_lane_regrows_after_healthy_launches_without_breaker_trip():
    """A one-off device loss that never opens the breaker must not pin
    the shrunk mesh forever: after regrow_after consecutive healthy
    launches on the survivors, the lane probes the full set again."""
    _mesh_or_skip()
    lane = fault.DeviceLane("t-regrow", failures=5, regrow_after=3)
    assert lane.current_mesh().devices.size == 8
    failpoints.enable("mesh.device_lost", "1*return(lost)")
    lane.run(lambda: "dev", lambda: "cpu")
    failpoints.reset()
    assert lane.current_mesh().devices.size == 7
    assert lane.breaker.state_name() == "closed"  # one failure < 5
    for _ in range(3):
        assert lane.current_mesh().devices.size == 7
        assert lane.run(lambda: "dev", lambda: "cpu") == "dev"
    assert lane.current_mesh().devices.size == 8, \
        "healthy launches must probe a regrow"


def test_real_runtime_device_loss_is_classified():
    """A real loss surfaces as an XlaRuntimeError-shaped message, not
    our DeviceLostError — the classifier must map it to a shrink, and
    a transient kernel error must NOT."""
    class FakeXla(RuntimeError):
        pass

    assert fault.is_device_loss(FakeXla("DEVICE_LOST: tpu:3 went away"))
    assert fault.is_device_loss(fault.DeviceLostError("injected"))
    assert not fault.is_device_loss(FakeXla("RESOURCE_EXHAUSTED: hbm"))
    _mesh_or_skip()
    lane = fault.DeviceLane("t-realloss", failures=5)
    lane.run(lambda: (_ for _ in ()).throw(
        FakeXla("device_lost: link down")), lambda: "cpu")
    assert lane.stats()["device_lost"] == 1
    assert lane.current_mesh().devices.size == 7


def test_device_compute_variants_never_mutate_sketch_state():
    """The watched-worker protocol's foundation: computing from an
    explicit snapshot must not touch live sketch state (an abandoned
    worker resuming later would otherwise race the fallback's
    host-pinned commit)."""
    from fluentbit_tpu.ops.sketch import (CountMin, HyperLogLog,
                                          sharded_hll_registers)

    mesh = _mesh_or_skip()
    b = assemble([b"a", b"bb", None, b"ccc"] * 4, 32)
    hll = HyperLogLog(p=8)
    snap = hll.registers
    assert isinstance(snap, np.ndarray)
    got = hll.device_registers(b.batch, b.lengths, wait=True,
                               registers=snap)
    assert got is not None
    assert hll.registers is snap, "compute must not commit or convert"
    got2 = sharded_hll_registers(hll, mesh, b.batch, b.lengths,
                                 registers=snap)
    assert hll.registers is snap
    assert np.array_equal(np.asarray(got), np.asarray(got2))
    cms = CountMin(depth=2, width=64)
    tsnap = cms.table
    gott = cms.device_table(b.batch, b.lengths, wait=True, table=tsnap)
    assert gott is not None and cms.table is tsnap


def test_attach_retry_history_is_bounded():
    """A permanently-absent backend re-attached across many cycles
    must not grow the history (and every health/status copy)
    forever."""
    code = (
        "from fluentbit_tpu.ops import device\n"
        "assert not device.wait(60)\n"
        "st = device.status()\n"
        "assert st['attempts'] == 30, st['attempts']\n"
        "assert len(st['retry_history']) == 20, "
        "len(st['retry_history'])\n"
        "assert st['retry_history'][-1]['attempt'] == 30\n"
    )
    proc = _subproc(code, {
        "FBTPU_FAILPOINTS": "device.attach=return(refused)",
        "FBTPU_ATTACH_RETRIES": "30",
        "FBTPU_ATTACH_BACKOFF_S": "0",
    })
    assert proc.returncode == 0, proc.stderr


# ------------------------------------- grep mesh lane: re-attach swap


def test_grep_mesh_swaps_in_on_new_attach_generation(monkeypatch):
    """A plugin whose mesh resolution pinned OFF after an exhausted
    attach must re-resolve when a later attach generation lands
    (reattach_async / a retry attempt succeeding) — the mesh lane
    swaps in live instead of staying pinned for the plugin lifetime."""
    from fluentbit_tpu.ops import device as dev
    from fluentbit_tpu.plugins.filter_grep import GrepFilter

    monkeypatch.setenv("FBTPU_MESH", "force")
    plug = GrepFilter.__new__(GrepFilter)
    plug._program = object()
    plug._mesh = None
    plug._mesh_resolved = False
    plug._mesh_on = False
    plug._mesh_gen = None
    # attach exhausted at generation 0: resolution pins the mesh off
    monkeypatch.setattr(dev, "generation", lambda: 0)
    monkeypatch.setattr(dev, "wait", lambda *a, **k: False)
    monkeypatch.setattr(dev, "failed", lambda: True)
    assert plug._grep_mesh() is None
    assert plug._mesh_resolved is True
    # the same generation stays pinned (no re-probe per chunk)
    assert plug._grep_mesh() is None
    # a re-attach generation lands: resolution re-opens and engages
    monkeypatch.setattr(dev, "generation", lambda: 1)
    monkeypatch.setattr(dev, "wait", lambda *a, **k: True)
    monkeypatch.setattr(dev, "failed", lambda: False)
    assert plug._grep_mesh() is not None, \
        "mesh lane must swap in live on a new attach generation"
    assert plug._mesh_gen == 1 and plug._mesh_on is True


# --------------------------------------- flux: host re-materialization


def test_flux_sketch_failover_rematerializes_host_side():
    """flux.device_update faults force every sketch/count launch onto
    the host twins: the absorbed state is bit-identical to a clean
    mesh run, and the sketch state is re-materialized host-pinned
    (numpy registers/table — the snapshot/restore source)."""
    from fluentbit_tpu.flux.state import FluxSpec, FluxState

    if len(jax.devices()) < 8:
        pytest.skip("need the simulated 8-device mesh")
    bodies = [{"tenant": ["a", "b"][i % 2], "user": f"u{i % 13}",
               "size": float(i)} for i in range(150)]

    def absorb(state):
        strcols = {
            f: state._str_column(bodies, f)
            for f in state.spec.string_fields
        }
        numcols = {f: state._num_column(bodies, f)
                   for f in state.spec.numeric}
        state.absorb_batch(len(bodies), strcols, numcols)

    kw = dict(group_by=("tenant",), distinct=("user",),
              numeric=("size",), topk_field="user", mesh=True)
    clean = FluxState(FluxSpec("t", **kw))
    assert clean._mesh is not None
    absorb(clean)

    faulty = FluxState(FluxSpec("t", **kw))
    failpoints.enable("flux.device_update", "return(chaos)")
    absorb(faulty)
    failpoints.reset()

    lane = faulty._lane
    assert lane is not None and lane.stats()["fallback_segments"] > 0
    for key, g in faulty._groups.items():
        assert isinstance(g.hlls["user"].registers, np.ndarray), \
            "failed-over sketch state must be host-pinned"
        ref = clean._groups[key]
        assert np.array_equal(np.asarray(g.hlls["user"].registers),
                              np.asarray(ref.hlls["user"].registers))
        assert g.count == ref.count
        assert g.cols["size"].sum == ref.cols["size"].sum
    assert np.array_equal(np.asarray(faulty.cms.table),
                          np.asarray(clean.cms.table))


def test_flux_mesh_update_survives_intermittent_faults():
    """30% injected launch failures mid-absorb: the final sketch state
    is STILL bit-identical to a fault-free run (fallback and device
    math are the same math)."""
    from fluentbit_tpu.flux.state import FluxSpec, FluxState

    if len(jax.devices()) < 8:
        pytest.skip("need the simulated 8-device mesh")
    bodies = [{"user": f"u{i % 31}"} for i in range(64)]

    def absorb(state):
        for _ in range(6):
            strcols = {f: state._str_column(bodies, f)
                       for f in state.spec.string_fields}
            state.absorb_batch(len(bodies), strcols, {})

    clean = FluxState(FluxSpec("t", distinct=("user",), mesh=True))
    absorb(clean)
    faulty = FluxState(FluxSpec("t", distinct=("user",), mesh=True))
    failpoints.enable("flux.device_update", "30%return(chaos)")
    absorb(faulty)
    failpoints.reset()
    g1 = clean._groups[()].hlls["user"]
    g2 = faulty._groups[()].hlls["user"]
    assert np.array_equal(np.asarray(g1.registers),
                          np.asarray(g2.registers))
    assert g1.estimate() == g2.estimate()


# ----------------------------------------------- health / introspection


def test_health_block_shape():
    lane = fault.lane("t-health")
    lane.run(lambda: 1, lambda: 0)
    block = fault.health_block()
    assert block["attach"]["state"] in ("unattached", "attaching",
                                        "ready", "failed")
    assert "retries_max" in block["attach"]
    assert block["lanes"]["t-health"]["ok"] == 1
    assert block["lanes"]["t-health"]["breaker"] == "closed"


def test_engine_health_includes_device_block(tmp_path):
    import json

    import fluentbit_tpu as flb

    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("lib", tag="t")
    ctx.output("null", match="t")
    ctx.start()
    try:
        h = ctx.engine.guard.health()
        assert "device" in h
        assert "attach" in h["device"] and "lanes" in h["device"]
        json.dumps(h)  # the admin endpoint must be able to serialize it
    finally:
        ctx.stop()
