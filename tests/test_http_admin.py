"""Admin HTTP server + hot reload.

Reference: src/http_server api/v1 (health/metrics/uptime/plugins/
storage) + api/v2 (reload), src/flb_reload.c.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

import fluentbit_tpu as flb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def http_get(port, path, method="GET"):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
              f"Connection: close\r\n\r\n".encode())
    data = b""
    while True:
        b = s.recv(65536)
        if not b:
            break
        data += b
    s.close()
    head, _, body = data.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    return status, body


@pytest.fixture
def admin_ctx():
    ctx = flb.create(flush="50ms", grace="1", http_server="on", http_port="0")
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("null", match="*")
    ctx.start()
    deadline = time.time() + 5
    while time.time() < deadline:
        srv = ctx.engine.admin_server
        if srv is not None and srv.bound_port:
            break
        time.sleep(0.02)
    yield ctx, ctx.engine.admin_server.bound_port, in_ffd
    ctx.stop()


def test_health_and_banner(admin_ctx):
    ctx, port, _ = admin_ctx
    assert http_get(port, "/api/v1/health") == (200, b"ok\n")
    status, body = http_get(port, "/")
    assert status == 200
    assert json.loads(body)["fluentbit_tpu"]["edition"] == "tpu-native"


def test_metrics_endpoints(admin_ctx):
    ctx, port, in_ffd = admin_ctx
    ctx.push(in_ffd, json.dumps({"x": 1}))
    ctx.flush_now()
    status, body = http_get(port, "/api/v1/metrics/prometheus")
    assert status == 200
    assert b'fluentbit_input_records_total{name="lib.0"} 1' in body
    status, body = http_get(port, "/api/v1/metrics")
    assert status == 200
    names = [m["name"] for m in json.loads(body)["metrics"]]
    assert "fluentbit_input_records_total" in names


def test_uptime_plugins_storage(admin_ctx):
    ctx, port, _ = admin_ctx
    status, body = http_get(port, "/api/v1/uptime")
    assert status == 200 and "uptime_sec" in json.loads(body)
    status, body = http_get(port, "/api/v1/plugins")
    assert json.loads(body)["inputs"] == ["lib.0"]
    status, body = http_get(port, "/api/v1/storage")
    assert status == 200 and "storage_layer" in json.loads(body)


def test_reload_api_get_and_unwired_post(admin_ctx):
    ctx, port, _ = admin_ctx
    status, body = http_get(port, "/api/v2/reload")
    assert status == 200
    assert json.loads(body)["hot_reload_count"] == 0
    status, _ = http_get(port, "/api/v2/reload", method="POST")
    assert status == 400  # no reload_callback wired in lib mode


def test_not_found(admin_ctx):
    ctx, port, _ = admin_ctx
    assert http_get(port, "/nope")[0] == 404


def test_cli_sighup_reload(tmp_path):
    """SIGHUP reloads the config in-process; the pipeline keeps working
    and /api/v2/reload reports the count."""
    conf = tmp_path / "p.conf"
    port = _free_port()
    conf.write_text(f"""
[SERVICE]
    Flush        0.1
    Grace        1
    Hot_Reload   on
    HTTP_Server  on
    HTTP_Port    {port}

[INPUT]
    Name  dummy
    Tag   t
    Rate  20

[OUTPUT]
    Name   file
    Match  t
    Path   {tmp_path}
    File   out.txt
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, "-m", "fluentbit_tpu", "-c", str(conf)],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        _wait_http(port)
        p.send_signal(signal.SIGHUP)
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                status, body = http_get(port, "/api/v2/reload")
                if json.loads(body).get("hot_reload_count") == 1:
                    break
            except OSError:
                pass
            time.sleep(0.1)
        else:
            raise AssertionError("reload not observed")
        # pipeline still flows after reload
        out = tmp_path / "out.txt"
        n0 = out.read_text().count("\n") if out.exists() else 0
        deadline = time.time() + 10
        while time.time() < deadline:
            if out.exists() and out.read_text().count("\n") > n0:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("pipeline stalled after reload")
    finally:
        p.terminate()
        p.wait(timeout=15)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_http(port, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if http_get(port, "/api/v1/health")[0] == 200:
                return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError("admin server not up")


def test_cli_sighup_ignored_without_hot_reload(tmp_path):
    """SIGHUP must not kill a pipeline when hot_reload is off."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, "-m", "fluentbit_tpu",
         "-i", "dummy", "-o", "null", "-f", "0.1", "-g", "1"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        time.sleep(2.5)  # give it time to start
        p.send_signal(signal.SIGHUP)
        time.sleep(1.0)
        assert p.poll() is None, "process died on SIGHUP"
    finally:
        p.terminate()
        p.wait(timeout=15)


def test_cli_reload_with_broken_config_keeps_running(tmp_path):
    conf = tmp_path / "p.conf"
    port = _free_port()
    good = f"""
[SERVICE]
    Flush        0.1
    Grace        1
    Hot_Reload   on
    HTTP_Server  on
    HTTP_Port    {port}

[INPUT]
    Name  dummy
    Tag   t

[OUTPUT]
    Name   null
    Match  *
"""
    conf.write_text(good)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, "-m", "fluentbit_tpu", "-c", str(conf)],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        _wait_http(port)
        conf.write_text("garbage outside any section\n")
        p.send_signal(signal.SIGHUP)
        time.sleep(2.0)
        # the old pipeline survives a broken reload
        assert p.poll() is None
        assert http_get(port, "/api/v1/health")[0] == 200
        assert json.loads(
            http_get(port, "/api/v2/reload")[1]
        )["hot_reload_count"] == 0
    finally:
        p.terminate()
        p.wait(timeout=15)
