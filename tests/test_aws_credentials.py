"""AWS credential providers: STS AssumeRole (signed), web identity
(unsigned), credential_process, ECS/HTTP container creds, expiry
refresh (reference src/aws/flb_aws_credentials_sts.c,
flb_aws_credentials_process.c, flb_aws_credentials_http.c)."""

import json
import os
import re
import socket
import stat
import threading
import time

import pytest

from fluentbit_tpu.utils import aws as _aws


class StubServer:
    def __init__(self, responder):
        self.requests = []
        self.responder = responder
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while True:
            try:
                c, _ = self.srv.accept()
            except OSError:
                return
            c.settimeout(3)
            try:
                data = b""
                while b"\r\n\r\n" not in data:
                    data += c.recv(65536)
                head = data.partition(b"\r\n\r\n")[0]
                self.requests.append(head)
                body = self.responder(head)
                c.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                          + str(len(body)).encode() + b"\r\n\r\n" + body)
            except OSError:
                pass
            c.close()

    def close(self):
        self.srv.close()


STS_XML = (b"<AssumeRoleResponse><AssumeRoleResult><Credentials>"
           b"<AccessKeyId>ASIA123</AccessKeyId>"
           b"<SecretAccessKey>sts-secret</SecretAccessKey>"
           b"<SessionToken>sts-token</SessionToken>"
           b"<Expiration>2099-01-01T00:00:00Z</Expiration>"
           b"</Credentials></AssumeRoleResult></AssumeRoleResponse>")


def test_sts_assume_role(monkeypatch):
    stub = StubServer(lambda head: STS_XML)
    monkeypatch.setenv("AWS_STS_ENDPOINT", f"127.0.0.1:{stub.port}")
    try:
        creds = _aws.sts_assume_role_provider(
            "arn:aws:iam::123:role/r", "sess",
            base=_aws.Credentials("AK", "SK"))
    finally:
        stub.close()
    assert creds is not None
    assert creds.access_key == "ASIA123"
    assert creds.secret_key == "sts-secret"
    assert creds.session_token == "sts-token"
    assert creds.expiration and creds.expiration > time.time()
    assert not creds.expired()
    head = stub.requests[0].decode()
    assert "Action=AssumeRole" in head
    assert "RoleArn=arn%3Aaws%3Aiam%3A%3A123%3Arole%2Fr" in head
    assert "Authorization: AWS4-HMAC-SHA256 Credential=AK/" in head
    assert "/sts/aws4_request" in head


def test_web_identity_provider(monkeypatch, tmp_path):
    tok = tmp_path / "token"
    tok.write_text("the-oidc-token")
    stub = StubServer(lambda head: STS_XML)
    monkeypatch.setenv("AWS_STS_ENDPOINT", f"127.0.0.1:{stub.port}")
    monkeypatch.setenv("AWS_ROLE_ARN", "arn:aws:iam::123:role/web")
    monkeypatch.setenv("AWS_WEB_IDENTITY_TOKEN_FILE", str(tok))
    try:
        creds = _aws.web_identity_provider()
    finally:
        stub.close()
    assert creds is not None and creds.access_key == "ASIA123"
    head = stub.requests[0].decode()
    assert "Action=AssumeRoleWithWebIdentity" in head
    assert "WebIdentityToken=the-oidc-token" in head
    assert "Authorization" not in head  # unsigned by design


def test_process_provider(monkeypatch, tmp_path):
    script = tmp_path / "cred.sh"
    doc = {"Version": 1, "AccessKeyId": "PAK", "SecretAccessKey": "PSK",
           "SessionToken": "PTOK",
           "Expiration": "2099-06-01T00:00:00Z"}
    script.write_text("#!/bin/sh\necho '" + json.dumps(doc) + "'\n")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    cfg = tmp_path / "config"
    cfg.write_text(f"[default]\ncredential_process = {script}\n")
    monkeypatch.setenv("AWS_CONFIG_FILE", str(cfg))
    monkeypatch.delenv("AWS_PROFILE", raising=False)
    creds = _aws.process_provider()
    assert creds is not None
    assert (creds.access_key, creds.secret_key, creds.session_token) == \
        ("PAK", "PSK", "PTOK")
    assert creds.expiration is not None


def test_process_provider_rejects_bad_version(monkeypatch, tmp_path):
    script = tmp_path / "cred.sh"
    script.write_text('#!/bin/sh\necho \'{"Version": 2, '
                      '"AccessKeyId": "x", "SecretAccessKey": "y"}\'\n')
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    cfg = tmp_path / "config"
    cfg.write_text(f"[profile p2]\ncredential_process = {script}\n")
    monkeypatch.setenv("AWS_CONFIG_FILE", str(cfg))
    assert _aws.process_provider("p2") is None


def test_http_provider_full_uri(monkeypatch):
    doc = {"AccessKeyId": "HAK", "SecretAccessKey": "HSK",
           "Token": "HTOK", "Expiration": "2099-01-01T00:00:00Z"}
    stub = StubServer(lambda head: json.dumps(doc).encode())
    monkeypatch.delenv("AWS_CONTAINER_CREDENTIALS_RELATIVE_URI",
                       raising=False)
    monkeypatch.setenv("AWS_CONTAINER_CREDENTIALS_FULL_URI",
                       f"http://127.0.0.1:{stub.port}/v2/creds")
    monkeypatch.setenv("AWS_CONTAINER_AUTHORIZATION_TOKEN", "Bearer abc")
    try:
        creds = _aws.http_provider()
    finally:
        stub.close()
    assert creds is not None
    assert (creds.access_key, creds.session_token) == ("HAK", "HTOK")
    head = stub.requests[0].decode()
    assert head.startswith("GET /v2/creds ")
    assert "Authorization: Bearer abc" in head


def test_current_refreshes_expired(monkeypatch):
    """A credential inside its 5-minute window re-resolves the chain."""
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "NEWAK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "NEWSK")
    stale = _aws.Credentials("OLD", "OLD", expiration=time.time() + 10)
    assert stale.expired()  # inside the 300s window
    got = _aws.current(stale)
    assert got.access_key == "NEWAK"
    fresh = _aws.Credentials("F", "F", expiration=time.time() + 3600)
    assert _aws.current(fresh) is fresh
