"""fbtpu-mesh: the explicitly partitioned pjit/shard_map grep plane.

Tier-1 ``mesh``-marked lane on the simulated 8-device CPU mesh
(conftest forces ``--xla_force_host_platform_device_count=8``). The
contract: the partitioned program's verdicts are BIT-EXACT against
both the single-device kernel and the pure-Python CPU chain, across
adversarial shapes (B not divisible by the mesh, single records, empty
batches, max_states-boundary programs), donation of the staged buffers
actually holds (input→output alias in the lowered module, donated
buffer consumed, zero copy-fallback warnings), and the engine's raw
path under ``FBTPU_MESH=1`` re-emits byte-identical chunks. The full
device-count × kernel matrix rides behind ``slow``.
"""

import os
import warnings

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from fluentbit_tpu.ops.batch import assemble
from fluentbit_tpu.ops.grep import GrepProgram, program_for
from fluentbit_tpu.ops.mesh import (build_mesh, match_partition_rules,
                                    mesh_info, mesh_key, pad_to_devices)
from fluentbit_tpu.regex import FlbRegex
from fluentbit_tpu.regex.dfa import compile_dfa

pytestmark = pytest.mark.mesh

APACHE2 = (
    r'^(?<host>[^ ]*) [^ ]* (?<user>[^ ]*) \[(?<time>[^\]]*)\] '
    r'"(?<method>\S+)(?: +(?<path>[^ ]*) +\S*)?" '
    r'(?<code>[^ ]*) (?<size>[^ ]*)'
    r'(?: "(?<referer>[^\"]*)" "(?<agent>.*)")?$'
)

CORPUS = [
    b'10.0.0.1 - frank [10/Oct/2000:13:55:36 -0700] '
    b'"GET /a HTTP/1.1" 200 23 "http://r" "curl"',
    b"POST /api/v1 500",
    b"kernel: panic at cpu0",
    b"",
    None,  # missing field row
    b"DELETE /x 404",
    b"GET with trailing spaces   ",
]


def _mesh(n=8, axis="batch"):
    if len(jax.devices()) < n:
        pytest.skip(f"need {n} devices, have {len(jax.devices())}")
    return build_mesh(n, axis=axis)


def _stage(vals, R, L=96):
    b = assemble(vals, L)
    return np.stack([b.batch] * R), np.stack([b.lengths] * R)


def _cpu_chain(patterns, vals):
    """The pure-Python reference verdict: per-rule regex over each
    value (None/missing rows never match) — the chain the partitioned
    program must reproduce bit-for-bit."""
    regs = [FlbRegex(p) for p in patterns]
    out = np.zeros((len(patterns), len(vals)), dtype=bool)
    for r, rx in enumerate(regs):
        for i, v in enumerate(vals):
            if v is None:
                continue
            out[r, i] = rx.match(v.decode("utf-8", "surrogateescape"))
    return out


# -- sharded-vs-unsharded bit-exactness, adversarial shapes -----------

@pytest.mark.parametrize("n_rows", [42, 1, 0, 8, 17])
def test_mesh_bit_exact_vs_cpu_chain(n_rows):
    """B not divisible by the mesh (42, 17), a single record, an empty
    batch, and an exact multiple — all bit-exact vs the single-device
    kernel AND the Python chain, with correct global counts."""
    mesh = _mesh()
    patterns = ("GET|POST", "^kernel:", "50[0-9]$")
    vals = (CORPUS * 7)[:n_rows]
    prog = program_for(patterns, 96)
    batch, lengths = _stage(vals, len(patterns))
    ref_chain = _cpu_chain(patterns, vals)
    mask, counts, Bp = prog.match_mesh(mesh, batch, lengths)
    assert Bp % mesh.devices.size == 0
    assert np.array_equal(mask, prog.match(batch, lengths))
    assert np.array_equal(mask, ref_chain)
    assert np.array_equal(counts, ref_chain.sum(axis=1))


def test_mesh_max_states_boundary_programs():
    """The apache2 parser DFA (S=690 — far past the assoc gate, scan
    kernel, k capped by the table budget) and a tiny literal (deep k,
    assoc-eligible S) both survive partitioning bit-exactly."""
    mesh = _mesh()
    vals = (CORPUS * 11)[:59]  # uneven tail on every device
    for patterns in ((APACHE2,), ("panic",), (APACHE2, "panic")):
        prog = program_for(patterns, 128)
        batch, lengths = _stage(vals, len(patterns), L=128)
        mask, counts, _ = prog.match_mesh(mesh, batch, lengths)
        assert np.array_equal(mask, _cpu_chain(patterns, vals))
        assert np.array_equal(counts, mask.sum(axis=1))


def test_mesh_assoc_kernel_bit_exact():
    """The parallel-in-time (assoc) kernel under the partitioned
    program — the shard_map varying-axes tie-in (`+ 0 * lengths`) must
    hold for the compose-tree variant too."""
    mesh = _mesh()
    vals = (CORPUS * 5)[:29]
    prog = GrepProgram([compile_dfa("GET|POST"), compile_dfa("50[0-9]$")],
                       96, kernel="assoc")
    batch, lengths = _stage(vals, 2)
    mask, _, _ = prog.match_mesh(mesh, batch, lengths)
    assert np.array_equal(mask, _cpu_chain(("GET|POST", "50[0-9]$"), vals))


def test_mesh_per_byte_prepass_bit_exact():
    """Force the per-byte classifier (no pair tables) pre-materialize:
    the partitioned program must not depend on the pair-map leaf."""
    mesh = _mesh()
    vals = (CORPUS * 4)[:21]
    prog = GrepProgram([compile_dfa("GET|POST")], 96)
    if prog._np is not None:
        prog._np["pair_maps"] = None
    batch, lengths = _stage(vals, 1)
    mask, _, _ = prog.match_mesh(mesh, batch, lengths)
    assert np.array_equal(mask, _cpu_chain(("GET|POST",), vals))


def test_rule_sharded_variant_bit_exact(monkeypatch):
    """Large-R table sharding: R splits across devices (tables AND the
    per-rule batches), counts come back global, verdicts bit-exact."""
    monkeypatch.setenv("FBTPU_MESH_RULE_SHARD_R", "8")
    mesh = _mesh()
    patterns = ("GET", "POST", "DELETE", "panic", "200", "404",
                "50[0-9]$", "curl")
    prog = GrepProgram([compile_dfa(p) for p in patterns], 96)
    assert prog.mesh_variant(mesh) == "rules"
    vals = (CORPUS * 6)[:37]
    batch, lengths = _stage(vals, len(patterns))
    ref = _cpu_chain(patterns, vals)
    mask, counts, Bp = prog.match_mesh(mesh, batch, lengths)
    assert Bp == 37  # rules variant shards R, B travels unpadded
    assert np.array_equal(mask, ref)
    assert np.array_equal(counts, ref.sum(axis=1))


def test_rule_shard_gate_requires_divisible_R():
    """R that does not divide the mesh falls back to batch sharding
    (a dead-rule pad row would cost a full batch scan)."""
    mesh = _mesh()
    prog = GrepProgram([compile_dfa(p) for p in ("a", "b", "c")], 64)
    os.environ.get("FBTPU_MESH_RULE_SHARD_R")  # default 64 untouched
    assert prog.mesh_variant(mesh) == "batch"


# -- the partition-rules layer ----------------------------------------

def test_match_partition_rules_layer():
    from jax.sharding import PartitionSpec as P

    tree = {
        "trans_flat": np.zeros((4, 128), np.int32),
        "starts": np.zeros((4,), np.int32),
        "scalar": np.zeros((1,), np.int32),
    }
    specs = match_partition_rules(
        ((r"trans_flat", P("batch", None)), (r".*", P("batch"))), tree)
    assert specs["trans_flat"] == P("batch", None)
    assert specs["starts"] == P("batch")
    assert specs["scalar"] == P()  # scalars never partition
    with pytest.raises(ValueError):
        match_partition_rules(((r"^starts$", P()),), tree)


def test_mesh_helpers():
    mesh = _mesh()
    info = mesh_info(mesh)
    assert info["devices"] == 8 and info["axis_names"] == ["batch"]
    assert info["simulated"] is True  # the tier-1 lane IS simulated
    assert mesh_key(mesh) == mesh_key(build_mesh(8))
    assert pad_to_devices(42, 8) == 48 and pad_to_devices(16, 8) == 16
    assert build_mesh(1) is None  # no 1-device mesh: pure overhead


# -- donation ---------------------------------------------------------

def test_donation_declared_and_aliased_in_module():
    """Compile-level half of the donation contract: the staged lengths
    buffer is declared donated and the lowered module carries the
    input→output alias (the i32 verdict lands in the staging buffer)."""
    mesh = _mesh()
    prog = program_for(("GET|POST", "^kernel:"), 96)
    rep = prog.donation_info(mesh, B=42)
    assert rep["declared"] == ["lengths"]
    assert rep["held"] is True and rep["alias_count"] >= 1
    assert rep["variant"] == "batch"
    assert rep["per_device_batch_share"] == pad_to_devices(42, 8) // 8


def test_donation_actually_consumes_buffer_no_warning():
    """Run-time half: after a dispatch the donated staging buffer is
    DELETED (XLA reused it — use-after-donate raises instead of
    silently reading verdict bytes), the un-donatable batch buffer is
    untouched, and no "donated buffers were not usable" copy-fallback
    warning ever fires."""
    mesh = _mesh()
    prog = program_for(("GET|POST", "^kernel:"), 96)
    vals = (CORPUS * 3)[:16]
    batch, lengths = _stage(vals, 2)
    h = prog._mesh_handle(mesh)
    assert h.donate_idx == (2,)  # lengths only: batch has no alias
    bd = jax.device_put(np.ascontiguousarray(batch), h.sh_b)
    ld = jax.device_put(np.ascontiguousarray(lengths), h.sh_l)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mask_i32, counts = h.fn(h.tables, bd, ld)
        np.asarray(mask_i32)
    assert not [x for x in w if "donated" in str(x.message).lower()]
    assert ld.is_deleted()      # donation held: buffer consumed
    assert not bd.is_deleted()  # not declared: still readable
    assert np.array_equal(np.asarray(mask_i32).astype(bool),
                          _cpu_chain(("GET|POST", "^kernel:"), vals))


def test_donation_all_mode_warns_for_unaliasable_batch():
    """The auto policy is load-bearing: force-donating the batch buffer
    (no aliasable u8 output exists) produces exactly the silent-copy
    warning the default set is computed to avoid."""
    mesh = _mesh()
    prog = program_for(("GET|POST",), 96)
    vals = (CORPUS * 3)[:16]
    batch, lengths = _stage(vals, 1)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mask, _, _ = prog.match_mesh(mesh, batch, lengths, donate="all")
    assert np.array_equal(mask, _cpu_chain(("GET|POST",), vals))
    assert [x for x in w if "donated buffers were not usable"
            in str(x.message)]


# -- engine end-to-end (the raw dispatch path) ------------------------

def _build_engine(mesh_on: bool, device: bool = True):
    from fluentbit_tpu.core.engine import Engine

    e = Engine()
    f = e.filter("grep")
    f.set("regex", f"log {APACHE2}")
    f.set("tpu_batch_records", "1")
    if not device:
        f.set("tpu.enable", "off")
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    return e, ins


def _corpus_chunk(n):
    from fluentbit_tpu.codec.events import encode_event

    ok = ('10.0.0.1 - frank [10/Oct/2000:13:55:36 -0700] '
          '"GET /a HTTP/1.1" 200 23 "http://r" "curl"')
    return b"".join(
        encode_event({"log": ok if i % 4 else f"kernel: oom {i}"},
                     float(i))
        for i in range(n))


@pytest.mark.parametrize("seg,n", [(None, 700), (128, 700), (1, 12)])
def test_engine_mesh_raw_path_byte_exact(monkeypatch, seg, n):
    """FBTPU_MESH=1 routes filter_grep's raw path through the
    partitioned matcher (single segment, uneven-tail multi-segment,
    and single-record segments) — surviving records re-emit
    byte-identical to the pure-Python chain."""
    if len(jax.devices()) < 2:
        pytest.skip("need a multi-device mesh")
    monkeypatch.setenv("FBTPU_MESH", "1")
    if seg is not None:
        monkeypatch.setenv("FBTPU_SEGMENT_RECORDS", str(seg))
    chunk = _corpus_chunk(n)
    e1, i1 = _build_engine(mesh_on=True)
    monkeypatch.setenv("FBTPU_MESH", "off")
    e2, i2 = _build_engine(mesh_on=False, device=False)
    monkeypatch.setenv("FBTPU_MESH", "1")
    n1 = e1.input_log_append(i1, "bench", chunk)
    n2 = e2.input_log_append(i2, "bench", chunk)
    o1 = b"".join(bytes(c.buf) for c in i1.pool.drain())
    o2 = b"".join(bytes(c.buf) for c in i2.pool.drain())
    assert e1.filters[0].plugin._mesh is not None  # lane engaged
    assert (n1, o1) == (n2, o2)


def test_mesh_resolution_survives_mid_attach_chunks(monkeypatch):
    """A chunk arriving while the device is still ATTACHING must not
    pin the mesh lane off for the plugin's lifetime: resolution stays
    open until the attach controller reaches ready/failed, then auto
    engages on a real multi-device attach (regression: the first raw
    chunk used to cache None forever)."""
    from fluentbit_tpu.ops import device as dev
    from fluentbit_tpu.plugins.filter_grep import GrepFilter

    monkeypatch.setenv("FBTPU_MESH", "auto")
    plug = GrepFilter.__new__(GrepFilter)
    plug._program = object()  # only truthiness matters here
    plug._mesh = None
    plug._mesh_resolved = False
    # mid-attach: neither ready nor failed — must NOT resolve
    monkeypatch.setattr(dev, "ready", lambda: False)
    monkeypatch.setattr(dev, "failed", lambda: False)
    monkeypatch.setattr(dev, "attach_async", lambda: None)
    assert plug._grep_mesh() is None
    assert plug._mesh_resolved is False  # next chunk re-probes
    # attach lands on a multi-device accelerator: auto engages
    monkeypatch.setattr(dev, "ready", lambda: True)
    monkeypatch.setattr(dev, "platform", lambda: "tpu")
    monkeypatch.setattr(dev, "device_count", lambda: 8)
    assert plug._grep_mesh() is not None
    assert plug._mesh_resolved is True
    # failed attach pins the unsharded path (fresh plugin state)
    plug2 = GrepFilter.__new__(GrepFilter)
    plug2._program = object()
    plug2._mesh = None
    plug2._mesh_resolved = False
    monkeypatch.setattr(dev, "ready", lambda: False)
    monkeypatch.setattr(dev, "failed", lambda: True)
    assert plug2._grep_mesh() is None
    assert plug2._mesh_resolved is True


def test_engine_mesh_auto_stays_off_on_cpu(monkeypatch):
    """auto never shadows the native fused matcher on a CPU backend —
    the 1-core bench hot path must not regress."""
    monkeypatch.delenv("FBTPU_MESH", raising=False)
    e, ins = _build_engine(mesh_on=False)
    e.input_log_append(ins, "bench", _corpus_chunk(64))
    ins.pool.drain()
    assert e.filters[0].plugin._mesh is None


# -- full matrix (slow) -----------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("n_dev", [2, 4, 8])
@pytest.mark.parametrize("kernel", ["scan", "assoc"])
@pytest.mark.parametrize("n_rows", [0, 1, 5, 42, 137])
def test_mesh_full_matrix(n_dev, kernel, n_rows):
    mesh = _mesh(n_dev)
    patterns = ("GET|POST", "^kernel:", "50[0-9]$", "curl")
    prog = GrepProgram([compile_dfa(p) for p in patterns], 96,
                       kernel=kernel)
    vals = (CORPUS * 25)[:n_rows]
    batch, lengths = _stage(vals, len(patterns))
    ref = _cpu_chain(patterns, vals)
    mask, counts, Bp = prog.match_mesh(mesh, batch, lengths)
    assert Bp % n_dev == 0
    assert np.array_equal(mask, ref)
    assert np.array_equal(counts, ref.sum(axis=1))
