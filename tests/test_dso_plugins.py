"""Dynamic (.so) plugin loading — the flb_plugin.c role — with the
C++ demo plugins built live by g++ against native/fbtpu_plugin.h.
Reference: src/flb_plugin.c:200-326, plugins/out_zig_demo (the
native-language plugin proof)."""

import os
import subprocess
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.core.dso import load_dso_plugin, plugin_stem

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build(tmp_path, src_name):
    src = os.path.join(REPO, "native", "demo_plugins", src_name)
    out = str(tmp_path / (src_name.replace(".cpp", "") + ".so"))
    subprocess.run(
        ["g++", "-shared", "-fPIC", "-O2",
         "-I", os.path.join(REPO, "native"), "-o", out, src],
        check=True, capture_output=True)
    return out


@pytest.fixture(scope="module")
def demo_so(tmp_path_factory):
    d = tmp_path_factory.mktemp("dso")
    return {"out": _build(d, "out_demo.cpp"),
            "in": _build(d, "in_demo.cpp")}


def test_stem_derivation():
    assert plugin_stem("/x/out_demo.so") == "out_demo"
    assert plugin_stem("flb-in_foo.so") == "in_foo"


def test_load_rejects_bad_objects(tmp_path, demo_so):
    import shutil

    # stem without an in_/out_ prefix and no proxy register export
    weird = str(tmp_path / "weird.so")
    shutil.copy(demo_so["out"], weird)
    with pytest.raises(ValueError, match="FLBPluginRegister"):
        load_dso_plugin(weird)
    # wrong symbol name for the stem
    bad = str(tmp_path / "out_nosuch.so")
    shutil.copy(demo_so["out"], bad)
    with pytest.raises(ValueError, match="registration structure"):
        load_dso_plugin(bad)
    # missing file
    with pytest.raises(ValueError, match="cannot load"):
        load_dso_plugin(str(tmp_path / "out_absent.so"))


def test_native_output_flush(tmp_path, demo_so):
    load_dso_plugin(demo_so["out"])
    sink = tmp_path / "sink.txt"
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("dummy", tag="t", dummy='{"k": 1}', rate="20",
              samples="3")
    ctx.output("native_demo", match="*", path=str(sink))
    ctx.start()
    try:
        deadline = time.time() + 5
        while (not sink.exists() or not sink.read_text()) and \
                time.time() < deadline:
            time.sleep(0.05)
        time.sleep(0.2)
    finally:
        ctx.stop()
    lines = sink.read_text().strip().splitlines()
    assert lines and all(ln.startswith("t ") for ln in lines)
    total_bytes = sum(int(ln.split()[1]) for ln in lines)
    assert total_bytes > 0


def test_native_input_emits_records(tmp_path, demo_so):
    load_dso_plugin(demo_so["in"])
    got = []
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("native_demo", tag="nat", copies="2")
    ctx.output("lib", match="*",
               callback=lambda d, tag: got.extend(decode_events(d)))
    ctx.start()
    try:
        deadline = time.time() + 5
        while len(got) < 4 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx.stop()
    assert len(got) >= 4
    assert got[0].body["source"] == "native"
    ns = [ev.body["n"] for ev in got[:4]]
    assert ns == sorted(ns)  # counter increments across collects


def test_cli_dash_e_and_plugins_section(tmp_path, demo_so):
    """-e flag AND a [PLUGINS] path both register the plugin in a
    fresh process; records flow through the native output."""
    sink = tmp_path / "cli_sink.txt"
    conf = tmp_path / "p.conf"
    conf.write_text(f"""
[SERVICE]
    flush 0.05
    grace 1

[PLUGINS]
    path {demo_so['out']}

[INPUT]
    name dummy
    tag cli
    rate 20
    samples 2

[OUTPUT]
    name native_demo
    match *
    path {sink}
""")
    proc = subprocess.Popen(
        ["python", "-m", "fluentbit_tpu", "-c", str(conf)],
        cwd=REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if sink.exists() and sink.read_text().strip():
                break
            time.sleep(0.1)
    finally:
        proc.terminate()
        proc.wait(timeout=15)
    assert sink.exists() and sink.read_text().startswith("cli ")


def test_yaml_plugins_key_loads_dso(tmp_path, demo_so):
    sink = tmp_path / "yaml_sink.txt"
    conf = tmp_path / "p.yaml"
    conf.write_text(f"""
service:
  flush: 0.05
  grace: 1
plugins:
  - {demo_so['out']}
pipeline:
  inputs:
    - name: dummy
      tag: y
      rate: 20
      samples: 2
  outputs:
    - name: native_demo
      match: "*"
      path: {sink}
""")
    proc = subprocess.Popen(
        ["python", "-m", "fluentbit_tpu", "-c", str(conf)],
        cwd=REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if sink.exists() and sink.read_text().strip():
                break
            time.sleep(0.1)
    finally:
        proc.terminate()
        proc.wait(timeout=15)
    assert sink.exists() and sink.read_text().startswith("y ")


def test_rejected_object_never_mapped(tmp_path):
    """ADVICE.md: objects without a registration export must be
    rejected BEFORE dlopen — their constructors must never run. The
    probe reads the ELF dynsym instead of loading the object."""
    import subprocess
    import sys

    marker = tmp_path / "ctor_ran"
    src = tmp_path / "evil.c"
    src.write_text(
        '#include <stdio.h>\n'
        '__attribute__((constructor)) static void boom(void) {\n'
        f'    FILE *f = fopen("{marker}", "w");\n'
        '    if (f) { fputs("ran", f); fclose(f); }\n'
        '}\n'
        'int some_unrelated_export(void) { return 1; }\n')
    so = tmp_path / "evil.so"
    subprocess.run(["gcc", "-shared", "-fPIC", "-o", str(so), str(src)],
                   check=True, capture_output=True)
    with pytest.raises(ValueError, match="never ran"):
        load_dso_plugin(str(so))
    assert not marker.exists(), \
        "rejected object's constructor executed (it was dlopen'd)"
    # same invariant for a misnamed in-house object
    so2 = tmp_path / "out_evil.so"
    import shutil

    shutil.copy(str(so), str(so2))
    with pytest.raises(ValueError, match="registration structure"):
        load_dso_plugin(str(so2))
    assert not marker.exists()


def test_elf_probe_finds_real_exports(tmp_path, demo_so):
    from fluentbit_tpu.core.dso import elf_has_export

    assert elf_has_export(demo_so["out"], {"out_demo_plugin"}) is True
    assert elf_has_export(demo_so["out"], {"FLBPluginRegister"}) is False
    # non-ELF input → undecidable (falls back to dlopen-and-check)
    txt = tmp_path / "not_elf.so"
    txt.write_bytes(b"definitely not an object file")
    assert elf_has_export(str(txt), {"x"}) is None


def test_probe_rejects_undefined_reference(tmp_path):
    """An object that merely REFERENCES FLBPluginRegister (undefined
    import in .dynsym) must still be rejected pre-dlopen — only a
    DEFINED export passes the probe."""
    import subprocess

    marker = tmp_path / "ref_ctor_ran"
    src = tmp_path / "ref.c"
    src.write_text(
        '#include <stdio.h>\n'
        'extern int FLBPluginRegister(void *);\n'
        '__attribute__((constructor)) static void boom(void) {\n'
        f'    FILE *f = fopen("{marker}", "w");\n'
        '    if (f) { fputs("ran", f); fclose(f); }\n'
        '}\n'
        'int call_it(void *d) { return FLBPluginRegister(d); }\n')
    so = tmp_path / "ref.so"
    subprocess.run(["gcc", "-shared", "-fPIC", "-o", str(so), str(src)],
                   check=True, capture_output=True)
    from fluentbit_tpu.core.dso import elf_has_export

    assert elf_has_export(str(so), {"FLBPluginRegister"}) is False
    assert elf_has_export(str(so), {"call_it"}) is True
    with pytest.raises(ValueError, match="never ran"):
        load_dso_plugin(str(so))
    assert not marker.exists()
