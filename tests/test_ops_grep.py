"""Device DFA kernel tests — bit-exactness vs the CPU matcher.

Runs on the virtual 8-device CPU backend (conftest). The contract under
test is the north star's: device keep/exclude decisions must be
bit-exact vs the CPU chain."""

import random

import numpy as np
import pytest

from fluentbit_tpu.ops.batch import assemble, bucket_size
from fluentbit_tpu.ops.grep import GrepProgram, choose_k, compose_table, program_for
from fluentbit_tpu.regex.dfa import compile_dfa

APACHE2 = (
    r'^(?<host>[^ ]*) [^ ]* (?<user>[^ ]*) \[(?<time>[^\]]*)\] '
    r'"(?<method>\S+)(?: +(?<path>[^ ]*) +\S*)?" '
    r'(?<code>[^ ]*) (?<size>[^ ]*)'
    r'(?: "(?<referer>[^\"]*)" "(?<agent>.*)")?$'
)


def make_lines(n, rng):
    lines = []
    for i in range(n):
        kind = rng.randrange(4)
        if kind == 0:
            lines.append(
                f'10.0.{rng.randrange(256)}.{rng.randrange(256)} - user{i} '
                f'[10/Oct/2024:13:55:36 -0700] "GET /p{i} HTTP/1.1" '
                f'{rng.choice([200, 404, 500])} {rng.randrange(10000)}'.encode()
            )
        elif kind == 1:
            lines.append(b"random junk line " + str(i).encode())
        elif kind == 2:
            lines.append(b"")
        else:
            lines.append(
                f'host{i} - u [t] "POST /x Z" 201 7 "r" "agent {i}"'.encode()
            )
    return lines


def test_compose_table_equivalence():
    dfa = compile_dfa(r"ab+c")
    t2 = compose_table(dfa.trans, 2)
    S, C = dfa.trans.shape
    for s in (0, 1, dfa.start):
        for c1 in range(C):
            for c2 in range(C):
                assert t2[s, c1 * C + c2] == dfa.trans[dfa.trans[s, c1], c2]


def test_choose_k_budget():
    assert choose_k(10, 4) >= 2
    assert choose_k(100000, 200) == 1


@pytest.mark.parametrize("pattern", ["abc", r"^\d+ GET", APACHE2, r"a*b|c$"])
def test_kernel_vs_cpu(pattern):
    rng = random.Random(7)
    dfa = compile_dfa(pattern)
    lines = make_lines(64, rng)
    b = assemble(lines, max_len=256)
    prog = GrepProgram([dfa], max_len=256)
    got = prog.match(b.batch[None], b.lengths[None])[0]
    expect = np.array([dfa.match_bytes(ln) for ln in lines])
    assert (got == expect).all(), pattern


def test_kernel_multi_rule_different_shapes():
    rng = random.Random(9)
    patterns = ["GET", r"^\d", APACHE2]
    dfas = [compile_dfa(p) for p in patterns]
    lines = make_lines(32, rng)
    b = assemble(lines, max_len=128)
    # rule 1 uses a different field: vary the batch per rule
    other = [ln[::-1] for ln in lines]
    b2 = assemble(other, max_len=128)
    batch = np.stack([b.batch, b2.batch, b.batch])
    lengths = np.stack([b.lengths, b2.lengths, b.lengths])
    prog = GrepProgram(dfas, max_len=128)
    got = prog.match(batch, lengths)
    assert (got[0] == np.array([dfas[0].match_bytes(ln) for ln in lines])).all()
    assert (got[1] == np.array([dfas[1].match_bytes(ln) for ln in other])).all()
    assert (got[2] == np.array([dfas[2].match_bytes(ln) for ln in lines])).all()


def test_invalid_rows_never_match():
    dfa = compile_dfa(r"x*")  # matches everything incl. empty
    b = assemble([b"abc", None, b"x" * 999], max_len=16)
    assert b.overflow == [2]
    prog = GrepProgram([dfa], max_len=16)
    got = prog.match(b.batch[None], b.lengths[None])[0]
    assert got[0]  # valid row matches
    assert not got[1]  # missing field
    assert not got[2]  # overflow → resolved on CPU by caller


def test_padded_batch_rows_inert():
    dfa = compile_dfa("a")
    b = assemble([b"a", b"b"], max_len=8, pad_batch_to=bucket_size(2))
    assert b.batch.shape[0] == 256
    prog = GrepProgram([dfa], max_len=8)
    got = prog.match(b.batch[None], b.lengths[None])[0]
    assert got[0] and not got[1]
    assert not got[2:].any()


def test_apache2_bulk_bit_exact():
    rng = random.Random(1234)
    dfa = compile_dfa(APACHE2)
    lines = make_lines(512, rng)
    b = assemble(lines, max_len=512)
    prog = program_for([APACHE2], max_len=512)
    got = prog.match(b.batch[None], b.lengths[None])[0]
    expect = dfa.match_batch_np(
        b.batch, np.where(b.lengths < 0, 0, b.lengths)
    ) & (b.lengths >= 0)
    assert (got == expect).all()
    scalar = np.array([dfa.match_bytes(ln) for ln in lines])
    assert (got == scalar).all()


def test_assoc_kernel_bit_exact_vs_scan():
    """The parallel-in-time (function-composition) kernel must be
    bit-identical to the sequential scan kernel on every input class:
    matches, misses, empty, padding-only, overflow rows."""
    rng = random.Random(4242)
    patterns = ["GET", r"^\d+$", APACHE2]
    dfas = [compile_dfa(p) for p in patterns]
    lines = make_lines(97, rng) + [b"", b"x" * 999, None]
    b = assemble(lines, max_len=192)
    batch = np.stack([b.batch] * 3)
    lengths = np.stack([b.lengths] * 3)
    scan_prog = GrepProgram(dfas, max_len=192, kernel="scan")
    for seg in (2, 8, 32, 1024):  # incl. seg > Lk (single segment)
        assoc_prog = GrepProgram(dfas, max_len=192, kernel="assoc",
                                 segment=seg)
        got_scan = scan_prog.match(batch, lengths)
        got_assoc = assoc_prog.match(batch, lengths)
        assert (got_scan == got_assoc).all(), f"segment={seg}"
    # and vs the ground-truth CPU matcher on the valid rows
    expect = np.array([dfas[0].match_bytes(ln)
                       if isinstance(ln, bytes) and len(ln) <= 192
                       else False for ln in lines])
    assert (got_assoc[0] == expect).all()


def test_assoc_kernel_sharded_matches_single_device():
    import jax
    from jax.sharding import Mesh

    rng = random.Random(77)
    dfas = [compile_dfa("GET"), compile_dfa(APACHE2)]
    lines = make_lines(41, rng)
    b = assemble(lines, max_len=128)
    batch = np.stack([b.batch] * 2)
    lengths = np.stack([b.lengths] * 2)
    prog = GrepProgram(dfas, max_len=128, kernel="assoc", segment=8)
    devs = jax.devices()
    mesh = Mesh(np.asarray(devs[:8]), ("batch",))
    mask, counts, _ = prog.match_sharded(mesh, batch, lengths)
    single = prog.match(batch, lengths)
    assert (mask == single).all()
    assert (counts == single.sum(axis=1)).all()
