"""Device-path filter_grep: bit-exact equivalence vs the CPU verdict path.

The north star contract (BASELINE.md): surviving records byte-identical to
the CPU chain. We run the same event list through GrepFilter with the
device path forced on and forced off and require identical surviving raw
bytes, across legacy/AND/OR modes, missing fields, and overflow rows.
"""

import random

import pytest

from fluentbit_tpu.codec.events import decode_events, encode_event
from fluentbit_tpu.core.plugin import registry

APACHE_HOSTISH = r"^(?<host>[^ ]*) [^ ]* (?<user>[^ ]*) \[(?<time>[^\]]*)\]"


def make_filter(props):
    ins = registry.create_filter("grep")
    for k, v in props:
        ins.set(k, v)
    ins.configure()
    ins.plugin.init(ins, None)
    return ins.plugin


def make_events(n, seed=0, long_every=None):
    rng = random.Random(seed)
    events = []
    for i in range(n):
        method = rng.choice(["GET", "POST", "PUT", "DELETE"])
        code = rng.choice(["200", "404", "500"])
        body = {"log": f"{method} /path/{i} HTTP/1.1 {code}", "n": i}
        if rng.random() < 0.1:
            body.pop("log")  # missing field rows
        if long_every and i % long_every == 0:
            body["log"] = "x" * 2000 + " GET /long 200"
        buf = encode_event(body, float(i))
        events.append(decode_events(buf)[0])
    return events


def run_both(props, events):
    f_dev = make_filter(props)
    if f_dev._program is None:
        pytest.skip("device program unavailable for these rules")
    f_cpu = make_filter(props + [("tpu.enable", "off")])
    assert f_cpu._program is None
    _, kept_dev = f_dev.filter(list(events), "t", None)
    _, kept_cpu = f_cpu.filter(list(events), "t", None)
    assert [e.raw for e in kept_dev] == [e.raw for e in kept_cpu]
    return kept_dev


@pytest.mark.parametrize("props", [
    [("regex", "log GET"), ("tpu_batch_records", "1")],
    [("exclude", "log 500$"), ("tpu_batch_records", "1")],
    [("regex", "log ^(GET|POST)"), ("exclude", "log 404"),
     ("tpu_batch_records", "1")],
    [("exclude", "log 404"), ("regex", "log ^(GET|POST)"),
     ("tpu_batch_records", "1")],
    [("regex", "log GET"), ("regex", "log 200"), ("logical_op", "AND"),
     ("tpu_batch_records", "1")],
    [("regex", "log GET"), ("regex", "log 500"), ("logical_op", "OR"),
     ("tpu_batch_records", "1")],
    [("exclude", "log GET"), ("exclude", "log 500"), ("logical_op", "OR"),
     ("tpu_batch_records", "1")],
    [("exclude", "log GET"), ("exclude", "log POST"), ("logical_op", "AND"),
     ("tpu_batch_records", "1")],
])
def test_device_equals_cpu(props):
    events = make_events(257, seed=hash(str(props)) & 0xFFFF)
    run_both(props, events)


def test_overflow_rows_resolve_on_cpu():
    events = make_events(200, seed=7, long_every=13)
    kept = run_both(
        [("regex", "log GET"), ("tpu_batch_records", "1"),
         ("tpu_max_record_len", "256")], events)
    # some long rows match "GET" and must survive via the CPU fallback
    assert any(len(e.body.get("log", "")) > 256 for e in kept)


def test_small_batches_use_cpu_path():
    f = make_filter([("regex", "log GET"), ("tpu_batch_records", "64")])
    events = make_events(8)
    _, kept = f.filter(list(events), "t", None)
    expected = [e for e in events if f.keep_record(e.body)]
    assert [e.raw for e in kept] == [e.raw for e in expected]


def test_program_built_only_when_capable():
    # backreference-free rules → program; lookahead rule → CPU only
    f = make_filter([("regex", "log GET")])
    assert f._program is not None
    f2 = make_filter([("regex", r"log (?=G)GET")])
    assert f2._program is None


def test_staged_multi_key_rules_raw_path():
    """Rules over TWO different field heads through the staged raw path:
    stage_field returns per-thread arena views, so the per-key staging
    loop must copy each key's batch out before staging the next key
    (regression: the second call overwrote the first key's bytes and
    every rule matched against the last key's field)."""
    from fluentbit_tpu import native

    if not native.available():
        pytest.skip("native unavailable")
    f = make_filter([
        ("regex", "log GET"), ("exclude", "stream stderr"),
        ("tpu_batch_records", "1"),
    ])
    if f._program is None or not f._program.try_ready():
        pytest.skip("device program unavailable")
    # force the staged (by_key) path: no fused/native tables
    f._native_filter = None
    f._native_tables = None
    rng = random.Random(5)
    buf = bytearray()
    bodies = []
    for i in range(300):
        body = {
            "log": f"{rng.choice(['GET', 'POST'])} /x/{i} 200",
            "stream": rng.choice(["stdout", "stderr"]),
        }
        if rng.random() < 0.1:
            body.pop("log")
        bodies.append(body)
        buf += encode_event(body, float(i))
    got = f.filter_raw(bytes(buf), "t", None, n_records=len(bodies))
    assert got is not None
    n_keep, out = got
    kept = decode_events(bytes(out))
    expected = [b for b in bodies if f.keep_record(b)]
    assert n_keep == len(expected)
    assert [e.body for e in kept] == expected
    # sanity: the expectation itself must depend on BOTH fields
    assert any(b.get("stream") == "stderr" for b in bodies)
    assert 0 < len(expected) < len(bodies)


def test_non_string_values_never_match():
    """String-only matching (src/flb_ra_key.c:418): ints don't match."""
    f = make_filter([("regex", r"n \d+")])
    events = make_events(4, seed=3)
    for ev in events:
        ev.body["n"] = 123  # int field
    _, kept = f.filter(list(events), "t", None)
    assert kept == []  # Regex-miss ⇒ EXCLUDE in legacy mode
