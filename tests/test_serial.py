"""in_serial over a pty pair (a real tty, so the termios raw-mode path
runs). Reference: plugins/in_serial/in_serial.c."""

import os
import pty
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.core.plugin import registry


class _Sink:
    def __init__(self):
        self.events = []

    def __call__(self, data, tag):
        self.events.extend(decode_events(data))


def run_serial(writes, deadline_records, **props):
    master, slave = pty.openpty()
    sink = _Sink()
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("serial", tag="ser", file=os.ttyname(slave),
              bitrate="9600", **props)
    ctx.output("lib", match="*", callback=sink)
    ctx.start()
    try:
        for w in writes:
            os.write(master, w)
            time.sleep(0.08)
        stop = time.time() + 5
        while len(sink.events) < deadline_records and time.time() < stop:
            time.sleep(0.02)
    finally:
        ctx.stop()
        os.close(master)
        os.close(slave)
    return sink.events


def test_separator_mode_splits_records():
    events = run_serial([b"alpha\nbeta\n", b"gam", b"ma\n"], 3,
                        separator="\n")
    assert [ev.body["msg"] for ev in events[:3]] == [
        "alpha", "beta", "gamma"]


def test_json_mode_parses_concatenated_values():
    events = run_serial([b'{"a": 1}{"b"', b': 2} 3 '], 3, format="json")
    bodies = [ev.body["msg"] for ev in events[:3]]
    assert bodies == [{"a": 1}, {"b": 2}, 3]


def test_raw_mode_whole_read_is_one_record():
    events = run_serial([b"hello serial"], 1)
    assert events and events[0].body["msg"] == "hello serial"


def test_leading_nul_and_crlf_stripped():
    # FTDI handshake NUL and a bare newline ahead of the payload
    events = run_serial([b"\x00\nline one\n"], 1, separator="\n")
    assert events and events[0].body["msg"] == "line one"


def test_bad_config_rejected():
    ins = registry.create_input("serial")
    ins.set("bitrate", "9600")
    ins.configure()
    with pytest.raises(ValueError):
        ins.plugin.init(ins, None)
    ins2 = registry.create_input("serial")
    ins2.set("file", "/dev/null")
    ins2.configure()
    with pytest.raises(ValueError):
        ins2.plugin.init(ins2, None)


def test_json_mode_multibyte_split_across_reads():
    # a multi-byte UTF-8 char split at the read boundary must survive
    payload = '{"msg": "café"}'.encode("utf-8")
    cut = payload.index(b"caf") + 4  # mid-'é'
    events = run_serial([payload[:cut], payload[cut:]], 1, format="json")
    assert events and events[0].body["msg"] == {"msg": "café"}


def test_json_mode_hard_invalid_byte_drops_buffer():
    # a hard-invalid byte mid-buffer: parsed values before it are
    # emitted, the poisoned remainder is dropped, later records flow
    events = run_serial([b'{"a": 1} \xff {"b', b'{"c": 3}'], 2,
                        format="json")
    bodies = [ev.body["msg"] for ev in events[:2]]
    assert bodies == [{"a": 1}, {"c": 3}]


def test_json_mode_garbage_head_resyncs():
    # trailing bad byte retained as a possible truncated tail must not
    # poison the next read's valid records
    events = run_serial([b'{"a":1}\xff', b'{"b":2} '], 2, format="json")
    bodies = [ev.body["msg"] for ev in events[:2]]
    assert bodies == [{"a": 1}, {"b": 2}]
