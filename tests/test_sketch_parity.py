"""HLL/count-min dual-path parity — the drift-risk property test.

The sketches have THREE update paths that must stay bit-identical for
the same byte streams: the device kernel (``update()`` once the backend
attaches — the jax jit), the C batch twin (``host_update`` —
fbtpu_hll_update / fbtpu_cms_update), and the Python per-value loop
(``add_cpu``).  Any drift silently corrupts merged multichip state, so
this suite drives randomized workloads through all of them, including
the ``merge_registers``/``merge_table`` cross-shard merge and the
sharded (mesh) update, and asserts register/table equality.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from fluentbit_tpu import native
from fluentbit_tpu.ops.batch import assemble
from fluentbit_tpu.ops.sketch import (
    CountMin,
    HyperLogLog,
    sharded_cms_update,
    sharded_hll_update,
)


def corpus(seed, n=400, max_len=24, none_rate=0.1):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        if rng.random() < none_rate:
            out.append(None)  # missing field rows must never count
        else:
            out.append(bytes(rng.randrange(256)
                             for _ in range(rng.randrange(0, max_len))))
    return out


def _mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"need {n} devices, have {len(devs)}")
    from jax.sharding import Mesh

    return Mesh(np.asarray(devs[:n]), ("batch",))


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_hll_three_paths_identical(seed):
    vals = corpus(seed)
    staged = assemble(vals, 32)

    h_py = HyperLogLog(p=10)
    for v in vals:
        if v is not None:
            h_py.add_cpu(v)

    h_c = HyperLogLog(p=10)
    h_c.host_update(staged.batch, staged.lengths)
    for i in staged.overflow:
        h_c.add_cpu(vals[i])

    h_dev = HyperLogLog(p=10)
    h_dev.update(staged.batch, staged.lengths)  # device path (cpu jit)
    for i in staged.overflow:
        h_dev.add_cpu(vals[i])

    regs_py = np.asarray(h_py.registers)
    assert np.array_equal(regs_py, np.asarray(h_c.registers))
    assert np.array_equal(regs_py, np.asarray(h_dev.registers))


@pytest.mark.parametrize("seed", [4, 5])
def test_cms_three_paths_identical(seed):
    vals = corpus(seed)
    staged = assemble(vals, 32)

    c_py = CountMin(4, 512)
    for v in vals:
        if v is not None:
            c_py.add_cpu(v)

    c_c = CountMin(4, 512)
    c_c.host_update(staged.batch, staged.lengths)
    for i in staged.overflow:
        c_c.add_cpu(vals[i])

    c_dev = CountMin(4, 512)
    c_dev.update(staged.batch, staged.lengths)
    for i in staged.overflow:
        c_dev.add_cpu(vals[i])

    t_py = np.asarray(c_py.table)
    assert np.array_equal(t_py, np.asarray(c_c.table))
    assert np.array_equal(t_py, np.asarray(c_dev.table))


def test_cross_shard_merge_is_union():
    """merge_registers/merge_table over disjoint halves == one sketch
    over the whole stream (the multichip merge contract)."""
    vals = corpus(7, n=600, none_rate=0.0)
    half = len(vals) // 2

    whole_h = HyperLogLog(p=10)
    whole_c = CountMin(4, 512)
    for v in vals:
        whole_h.add_cpu(v)
        whole_c.add_cpu(v)

    a_h, b_h = HyperLogLog(p=10), HyperLogLog(p=10)
    a_c, b_c = CountMin(4, 512), CountMin(4, 512)
    sa = assemble(vals[:half], 32)
    sb = assemble(vals[half:], 32)
    a_h.host_update(sa.batch, sa.lengths)
    b_h.host_update(sb.batch, sb.lengths)
    a_c.host_update(sa.batch, sa.lengths)
    b_c.host_update(sb.batch, sb.lengths)
    for i in sa.overflow:
        a_h.add_cpu(vals[i])
        a_c.add_cpu(vals[i])
    for i in sb.overflow:
        b_h.add_cpu(vals[half + i])
        b_c.add_cpu(vals[half + i])
    a_h.merge_registers(np.asarray(b_h.registers))
    a_c.merge_table(np.asarray(b_c.table))

    assert np.array_equal(np.asarray(whole_h.registers),
                          np.asarray(a_h.registers))
    assert np.array_equal(np.asarray(whole_c.table),
                          np.asarray(a_c.table))


@pytest.mark.mesh
def test_sharded_hll_matches_host():
    """The mesh (pmax-merged) HLL update is bit-identical to the host
    twin — sharding must not change a single register."""
    vals = corpus(9, n=333, none_rate=0.05)  # not divisible by 8
    staged = assemble(vals, 32)
    host = HyperLogLog(p=10)
    host.host_update(staged.batch, staged.lengths)

    mesh = _mesh(8)
    sh = HyperLogLog(p=10)
    sharded_hll_update(sh, mesh, staged.batch, staged.lengths)
    assert np.array_equal(np.asarray(host.registers),
                          np.asarray(sh.registers))


@pytest.mark.mesh
def test_sharded_cms_matches_host():
    vals = corpus(10, n=333, none_rate=0.05)
    staged = assemble(vals, 32)
    host = CountMin(4, 512)
    host.host_update(staged.batch, staged.lengths)

    mesh = _mesh(8)
    sh = CountMin(4, 512)
    sharded_cms_update(sh, mesh, staged.batch, staged.lengths)
    assert np.array_equal(np.asarray(host.table), np.asarray(sh.table))


@pytest.mark.mesh
def test_segment_counts_three_paths_identical():
    """flux window counts: host bincount == device scatter-add ==
    mesh psum merge (integers — exact everywhere)."""
    from fluentbit_tpu.flux import kernels

    rng = np.random.default_rng(3)
    seg = rng.integers(0, 13, size=401).astype(np.int64)
    valid = (rng.random(401) < 0.8).astype(np.int32)
    host = kernels.host_segment_counts(seg, valid, 13)
    dev = kernels.segment_counts(seg, valid, 13)
    assert np.array_equal(host, dev)
    mesh = kernels.flux_mesh()
    if mesh is not None:
        sh = kernels.sharded_segment_counts(mesh, seg, valid, 13)
        assert np.array_equal(host, sh)


def test_native_twins_present():
    """The C batch kernels exist in this build (a stale prebuilt .so
    would silently fall back to the Python loop — still correct, but
    the flux ingest-rate path wants the C twins)."""
    if not native.available():
        pytest.skip("native plane unavailable")
    regs = np.zeros(1 << 8, dtype=np.int32)
    staged = assemble([b"x", b"y"], 8)
    assert native.hll_update(regs, staged.batch, staged.lengths, 8)
    table = np.zeros((2, 64), dtype=np.int64)
    assert native.cms_update(table, staged.batch, staged.lengths)
