"""filter_nightfall against a local stub of the Nightfall scan API.

Reference semantics: plugins/filter_nightfall/nightfall.c (DFS field
extraction, key-context joining, byteRange star-redaction)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from fluentbit_tpu.codec.events import decode_events, encode_event
from fluentbit_tpu.core.plugin import FilterResult, registry


class _StubNightfall(BaseHTTPRequestHandler):
    # class-level: last request payload + a rule function set per test
    requests = []
    rule = staticmethod(lambda items: [[] for _ in items])

    def do_POST(self):
        body = self.rfile.read(int(self.headers["Content-Length"]))
        req = json.loads(body)
        type(self).requests.append(
            {"req": req, "auth": self.headers.get("Authorization")})
        findings = []
        for per_item in self.rule(req["payload"]):
            findings.append([
                {"location": {"byteRange": {"start": s, "end": e}}}
                for s, e in per_item
            ])
        resp = json.dumps({"findings": findings}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)

    def log_message(self, *a):
        pass


@pytest.fixture
def stub():
    _StubNightfall.requests = []
    srv = HTTPServer(("127.0.0.1", 0), _StubNightfall)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def make_filter(port, **props):
    ins = registry.create_filter("nightfall")
    ins.set("nightfall_api_key", "test-key-123")
    ins.set("policy_id", "11111111-2222-3333-4444-555555555555")
    ins.set("api_url", f"http://127.0.0.1:{port}")
    for k, v in props.items():
        ins.set(k, v)
    ins.configure()
    ins.plugin.init(ins, None)
    return ins.plugin


def make_events(bodies):
    return [decode_events(encode_event(b, float(i)))[0]
            for i, b in enumerate(bodies)]


def test_payload_shape_and_auth(stub):
    port = stub.server_address[1]
    plug = make_filter(port)
    events = make_events([
        {"msg": "hello world", "count": 7,
         "nested": {"inner": "secret"}, "arr": ["a", 1, {"b": "c"}]},
    ])
    res, out = plug.filter(events, "t", None)
    assert res == FilterResult.NOTOUCH
    (req,) = _StubNightfall.requests
    assert req["auth"] == "Bearer test-key-123"
    body = req["req"]
    assert body["policyUUIDs"] == ["11111111-2222-3333-4444-555555555555"]
    # DFS order: map keys then values, key-context joined for scalar
    # values under string keys, nested objects walked in place
    assert body["payload"] == [
        "msg", "msg hello world", "count", "count 7",
        "nested", "inner", "inner secret",
        "arr", "a", "1", "b", "b c",
    ]


def test_string_range_redaction(stub):
    port = stub.server_address[1]

    def rule(items):
        out = []
        for it in items:
            if it.startswith("card "):
                # finding over the card number inside "card <16 digits>"
                out.append([(5, 5 + 16)])
            else:
                out.append([])
        return out

    _StubNightfall.rule = staticmethod(rule)
    plug = make_filter(port)
    events = make_events([{"card": "4242424242424242", "ok": "fine"}])
    res, out = plug.filter(events, "t", None)
    assert res == FilterResult.MODIFIED
    # byteRange applies to the joined "card <value>" string; the filter
    # subtracts len("card ")==5 and stars the value alone
    assert out[0].body == {"card": "*" * 16, "ok": "fine"}


def test_integer_and_key_redaction(stub):
    port = stub.server_address[1]

    def rule(items):
        out = []
        for it in items:
            if it == "ssn 78051120":  # int under context key
                out.append([(4, 12)])
            elif it == "topsecretkey":  # a map key itself
                out.append([(0, 3)])
            else:
                out.append([])
        return out

    _StubNightfall.rule = staticmethod(rule)
    plug = make_filter(port)
    events = make_events([{"ssn": 78051120, "topsecretkey": "v"}])
    res, out = plug.filter(events, "t", None)
    assert res == FilterResult.MODIFIED
    # integers with findings are replaced whole; string keys star-fill
    assert out[0].body == {"ssn": "******", "***secretkey": "v"}


def test_partial_range_clamping(stub):
    port = stub.server_address[1]
    _StubNightfall.rule = staticmethod(
        lambda items: [[(4, 99)] for _ in items])
    plug = make_filter(port)
    events = make_events([{"m": "abcdefgh"}])
    res, out = plug.filter(events, "t", None)
    assert res == FilterResult.MODIFIED
    # offset len("m ")==2: start 4-2=2, end clamped to len
    assert out[0].body == {"m": "ab******"}


def test_no_findings_passthrough_and_raw_identity(stub):
    port = stub.server_address[1]
    _StubNightfall.rule = staticmethod(lambda items: [[] for _ in items])
    plug = make_filter(port)
    events = make_events([{"a": "b"}, {"c": 5}])
    res, out = plug.filter(events, "t", None)
    assert res == FilterResult.NOTOUCH
    assert out is events


def test_api_down_is_notouch():
    # connect refused → scan error → records pass through untouched
    plug = make_filter(1)  # port 1: nothing listening
    events = make_events([{"a": "b"}])
    res, out = plug.filter(events, "t", None)
    assert res == FilterResult.NOTOUCH


def test_bad_config_rejected():
    with pytest.raises(ValueError):
        make_filter(80, sampling_rate="0")
    ins = registry.create_filter("nightfall")
    ins.set("policy_id", "x")
    ins.configure()
    with pytest.raises(ValueError):
        ins.plugin.init(ins, None)


def test_sync_http_request_chunked_response():
    import socket
    import threading

    from fluentbit_tpu.utils import sync_http_request

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve():
        conn, _ = srv.accept()
        conn.recv(65536)
        conn.sendall(b"HTTP/1.1 200 OK\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n"
                     b"5\r\nhello\r\n6\r\n world\r\n0\r\n\r\n")
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    got = sync_http_request("127.0.0.1", port, "GET", "/")
    srv.close()
    assert got is not None
    status, headers, body = got
    assert status == 200 and body == b"hello world"


def test_colliding_redacted_keys_both_survive(stub):
    port = stub.server_address[1]
    _StubNightfall.rule = staticmethod(
        lambda items: [[(0, 16)] if len(it) == 16 and it.isdigit()
                       else [] for it in items])
    plug = make_filter(port)
    events = make_events([
        {"4111111111111111": "a", "4242424242424242": "b"}])
    res, out = plug.filter(events, "t", None)
    assert res == FilterResult.MODIFIED
    # both fields survive with disambiguated star keys
    assert sorted(out[0].body.values()) == ["a", "b"]
    assert all(k.startswith("*" * 16) for k in out[0].body)


def test_batched_single_request_per_chunk(stub):
    port = stub.server_address[1]
    _StubNightfall.rule = staticmethod(lambda items: [[] for _ in items])
    plug = make_filter(port)
    events = make_events([{"a": "x"}, {"b": "y"}, {"c": "z"}])
    plug.filter(events, "t", None)
    # 3 records, ONE API round trip carrying all fields in DFS order
    assert len(_StubNightfall.requests) == 1
    assert _StubNightfall.requests[0]["req"]["payload"] == [
        "a", "a x", "b", "b y", "c", "c z"]
