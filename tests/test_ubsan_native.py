"""UndefinedBehaviorSanitizer pass over the native data plane.

ASan/TSan cover memory safety and races; this lane isolates UB —
signed overflow in offset math, misaligned loads in the byte-pair
staging, shift overflows in the msgpack width packing, invalid bool
loads — with ``-fsanitize=undefined`` alone and
``-fno-sanitize-recover`` so the FIRST report aborts the driver (an
ASan+UBSan combined build, as in test_asan_native.py, keeps UBSan in
recovering mode and a report there only prints). Drives the scanner
trio + fused filter over byte soup AND the whole-chunk JSON transcoder
(``parser_json_batch``), which the ASan driver predates.

Shares the ``sanitizer`` marker (tests/conftest.py) with the other
lanes: ``-m sanitizer`` selects, ``-m 'not sanitizer'`` sheds.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.sanitizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DRIVER = r"""
import os, random, sys
sys.path.insert(0, %(repo)r)
import fluentbit_tpu.native as native
native._SO = %(so)r
native._tried = False
native._lib = None
os.environ.pop("FBTPU_NO_NATIVE", None)
from fluentbit_tpu.codec.events import encode_event
from fluentbit_tpu.regex.dfa import compile_dfa

assert native.available(), "ubsan .so failed to load"
tables = native.GrepFilterTables(
    [(b"log", compile_dfa("GET|time?out"), False)], "legacy")
rng = random.Random(23)
for n in (1, 3, 16, 257, 4097):
    buf = bytearray()
    for i in range(n):
        buf += encode_event(
            {"log": ("GET /x " if i %% 2 else "zzz ") + "a" * (i %% 97)},
            float(i))
    raw = bytes(buf)
    assert native.grep_filter(raw, tables) is not None
    native.stage_field(raw, b"log", 96, n_hint=n)
    native.count_records(raw)
    native.scan_offsets(raw)
    for _ in range(15):
        mut = bytearray(raw)
        for _ in range(rng.randrange(1, 8)):
            mut[rng.randrange(len(mut))] = rng.randrange(256)
        cut = bytes(mut[: rng.randrange(1, len(mut) + 1)])
        native.grep_filter(cut, tables)
        native.stage_field(cut, b"log", 64)
        native.count_records(cut)
        native.scan_offsets(cut)

# --- codec extension: decode/pack + the JSON transcoder ---
import fluentbit_tpu.codec._native_codec as nc
nc._SO = %(codec_so)r
nc._mod, nc._tried = None, False
mod = nc.load()
assert mod is not None, "ubsan codec extension failed to load"
from fluentbit_tpu.codec.msgpack import EventTime

docs = [
    '{"a": 1, "wide": 5000000000, "neg": -2147483649}',
    '{"f": 1e308, "tiny": -1e-308, "nan": NaN, "inf": -Infinity}',
    '{"esc": "\\u00e9\\ud834\\udd1e\\n", "nest": {"x": [1, 2.5]}}',
    '{"dup": 1, "dup": {"last": true}}',
    'not json', '[]', '{}',
]
good = b"".join(
    encode_event({"log": docs[i %% len(docs)], "n": i},
                 EventTime(1700000000 + i, 7) if i %% 2 else float(i))
    for i in range(256))
out, n, parsed = mod.parser_json_batch(good, b"log")
assert n == 256 and parsed > 0, (n, parsed)
assert mod.decode_events(out)
for _ in range(200):
    mut = bytearray(good)
    for _ in range(rng.randrange(1, 10)):
        mut[rng.randrange(len(mut))] = rng.randrange(256)
    cut = bytes(mut[: rng.randrange(1, len(mut) + 1)])
    for fn in (lambda b: mod.parser_json_batch(b, b"log"),
               mod.decode_events):
        try:
            fn(cut)
        except ValueError:
            pass  # malformed/declined is fine; UB is not
for _ in range(60):
    body = {"s": "y" * rng.randrange(300), "l": [1, {"k": (2, 3)}],
            "i": rng.randrange(-2**63, 2**64 - 1)}
    mod.pack_event(EventTime(1, 2), {}, body)
print("UBSAN_DRIVER_OK")
"""


@pytest.mark.skipif(sys.platform != "linux", reason="linux toolchain")
def test_native_data_plane_under_ubsan(tmp_path):
    libubsan = subprocess.run(
        ["g++", "-print-file-name=libubsan.so"],
        capture_output=True, text=True).stdout.strip()
    if not libubsan or not os.path.exists(libubsan):
        pytest.skip("libubsan unavailable")
    so = str(tmp_path / "fbtpu_ubsan.so")
    build = subprocess.run(
        ["g++", "-O1", "-g", "-fPIC", "-shared", "-std=c++17",
         "-pthread", "-fsanitize=undefined",
         "-fno-sanitize-recover=undefined",
         os.path.join(REPO, "native", "fbtpu_native.cpp"), "-o", so],
        capture_output=True, text=True, timeout=300)
    if build.returncode != 0:
        pytest.skip(f"ubsan build failed: {build.stderr[-400:]}")
    import sysconfig

    include = sysconfig.get_paths().get("include")
    codec_so = str(tmp_path / "fbtpu_codec_ubsan.so")
    cbuild = subprocess.run(
        ["gcc", "-O1", "-g", "-fPIC", "-shared",
         "-fsanitize=undefined", "-fno-sanitize-recover=undefined",
         "-I", include or ".",
         os.path.join(REPO, "native", "fbtpu_codec.c"),
         "-o", codec_so],
        capture_output=True, text=True, timeout=300)
    if cbuild.returncode != 0:
        pytest.skip(f"ubsan codec build failed: {cbuild.stderr[-400:]}")
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": libubsan,
        "UBSAN_OPTIONS": "print_stacktrace=1:halt_on_error=1",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "FBTPU_THREADS_NO_HW_CAP": "1",
        "FBTPU_DFA_THREADS": "2",
    })
    proc = subprocess.run(
        [sys.executable, "-c",
         DRIVER % {"repo": REPO, "so": so, "codec_so": codec_so}],
        capture_output=True, text=True, timeout=420, env=env)
    assert proc.returncode == 0, (
        f"ubsan report (rc={proc.returncode}):\n"
        f"{proc.stdout[-1000:]}\n{proc.stderr[-3000:]}")
    assert "UBSAN_DRIVER_OK" in proc.stdout
    assert "runtime error:" not in proc.stderr
