"""Filesystem storage: write-through persistence, crash recovery
(backlog re-ingest), CRC corruption handling, DLQ quarantine.

Reference: lib/chunkio (src/cio_file.c:49-104 CRC chunks),
src/flb_storage.c:530-556, plugins/in_storage_backlog.
"""

import glob
import json
import os

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.chunk import Chunk
from fluentbit_tpu.codec.events import decode_events, encode_event
from fluentbit_tpu.core.storage import Storage


def make_ctx(tmp_path, outputs=("null",), checksum=True):
    ctx = flb.create(flush="60ms", grace="1")
    ctx.service_set(**{"storage.path": str(tmp_path / "st"),
                       "storage.checksum": "on" if checksum else "off"})
    return ctx


# ------------------------------------------------------------- unit level

def test_write_finalize_scan_roundtrip(tmp_path):
    st = Storage(str(tmp_path), checksum=True)
    c = Chunk("app.log", in_name="lib.0")
    data = encode_event({"m": 1}, 1.0) + encode_event({"m": 2}, 2.0)
    c.append(data, 2)
    st.write_through(c, data)
    st.finalize(c)
    st2 = Storage(str(tmp_path), checksum=True)
    got = st2.scan_backlog()
    assert len(got) == 1
    assert got[0].tag == "app.log"
    assert got[0].records == 2
    assert [e.body for e in got[0].decode()] == [{"m": 1}, {"m": 2}]


def test_unfinalized_chunk_recovered(tmp_path):
    """A crash before finalize leaves state=open, crc=0 — payload still
    recovered."""
    st = Storage(str(tmp_path))
    c = Chunk("t", in_name="i")
    data = encode_event({"x": 1}, 1.0)
    st.write_through(c, data)  # no finalize: simulated crash
    got = Storage(str(tmp_path)).scan_backlog()
    assert len(got) == 1 and got[0].records == 1


def test_corrupt_crc_skipped_and_renamed(tmp_path):
    st = Storage(str(tmp_path), checksum=True)
    c = Chunk("t", in_name="i")
    data = encode_event({"x": 1}, 1.0)
    c.append(data, 1)
    st.write_through(c, data)
    st.finalize(c)
    (path,) = glob.glob(str(tmp_path / "streams" / "*" / "*.flb"))
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        last = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last[0] ^ 0xFF]))  # flip payload byte
    got = Storage(str(tmp_path), checksum=True).scan_backlog()
    assert got == []
    # corrupt chunks quarantine into the DLQ dir (FAULTS.md contract):
    # operators find every rejected payload in one place
    assert glob.glob(str(tmp_path / "dlq" / "*.corrupt"))
    assert not glob.glob(str(tmp_path / "streams" / "*" / "*.corrupt"))


def test_delete_removes_file(tmp_path):
    st = Storage(str(tmp_path))
    c = Chunk("t", in_name="i")
    data = encode_event({"x": 1}, 1.0)
    st.write_through(c, data)
    st.finalize(c)
    st.delete(c)
    assert not glob.glob(str(tmp_path / "streams" / "*" / "*.flb"))


# ------------------------------------------------------------ engine level

def test_kill_and_restart_no_data_loss(tmp_path):
    """Records persisted before a hard stop are redelivered after
    restart (the checkpoint/resume contract)."""
    ctx = make_ctx(tmp_path)
    in_ffd = ctx.input("lib", tag="t", **{"storage.type": "filesystem"})
    ctx.output("retry", match="t")  # never succeeds → chunks stay on disk
    ctx.start()
    try:
        for i in range(5):
            ctx.push(in_ffd, json.dumps({"i": i}))
    finally:
        # hard "crash": abandon without graceful drain
        ctx.engine.request_stop()
        ctx.stop()
    files = glob.glob(str(tmp_path / "st" / "streams" / "*" / "*.flb"))
    assert files, "chunk files must survive the stop"

    # restart: recovered chunks re-dispatch to the (now healthy) output
    ctx2 = make_ctx(tmp_path)
    got = []
    ctx2.output("lib", match="t", callback=lambda d, t: got.append(d))
    ctx2.start()
    try:
        ctx2.flush_now()
    finally:
        ctx2.stop()
    events = [e for d in got for e in decode_events(d)]
    assert sorted(e.body["i"] for e in events) == [0, 1, 2, 3, 4]
    # delivered → files gone
    assert not glob.glob(str(tmp_path / "st" / "streams" / "*" / "*.flb"))


def test_dlq_on_exhausted_retries(tmp_path):
    ctx = make_ctx(tmp_path)
    ctx.service_set(**{"scheduler.base": "0.01", "scheduler.cap": "0.02"})
    in_ffd = ctx.input("lib", tag="t", **{"storage.type": "filesystem"})
    ctx.output("retry", match="t", retry_limit="1")
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"x": "doomed"}))
        import time

        deadline = time.time() + 8
        while time.time() < deadline:
            if glob.glob(str(tmp_path / "st" / "dlq" / "*.flb")):
                break
            time.sleep(0.05)
    finally:
        ctx.stop()
    st = Storage(str(tmp_path / "st"))
    dlq = st.dlq_chunks()
    assert len(dlq) == 1
    assert dlq[0].decode()[0].body == {"x": "doomed"}


def test_memory_inputs_not_persisted(tmp_path):
    ctx = make_ctx(tmp_path)
    in_ffd = ctx.input("lib", tag="t")  # default storage.type=memory
    ctx.output("null", match="t")
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"x": 1}))
        ctx.flush_now()
    finally:
        ctx.stop()
    assert not glob.glob(str(tmp_path / "st" / "streams" / "*" / "*.flb"))
