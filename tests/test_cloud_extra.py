"""Round-3 tail part 2: charset conversion + plot/vivo/skywalking/
chronicle/kusto/logs_ingestion/oracle outputs."""

import asyncio
import json
import socket
import threading
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events, encode_event


def _make_output(name, **props):
    from fluentbit_tpu.core.plugin import registry

    ins = registry.create_output(name)
    for k, v in props.items():
        ins.set(k, v)
    ins.configure()
    ins.plugin.init(ins, None)
    return ins.plugin


# ------------------------------------------------------- charset

def test_tail_generic_encoding_sjis(tmp_path):
    logf = tmp_path / "sjis.log"
    text = "こんにちは世界\nさようなら\n"
    logf.write_bytes(text.encode("shift_jis"))
    got = []
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("tail", tag="t", path=str(logf), read_from_head="on",
              refresh_interval="1", **{"generic.encoding": "ShiftJIS"})
    ctx.output("lib", match="*",
               callback=lambda d, tag: got.extend(decode_events(d)))
    ctx.start()
    try:
        deadline = time.time() + 5
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx.stop()
    assert [ev.body["log"] for ev in got] == ["こんにちは世界", "さようなら"]


def test_tail_unicode_encoding_utf16le(tmp_path):
    logf = tmp_path / "u16.log"
    logf.write_bytes("first π\nsecond ∑\n".encode("utf-16-le"))
    got = []
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("tail", tag="t", path=str(logf), read_from_head="on",
              refresh_interval="1", **{"unicode.encoding": "UTF-16LE"})
    ctx.output("lib", match="*",
               callback=lambda d, tag: got.extend(decode_events(d)))
    ctx.start()
    try:
        deadline = time.time() + 5
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx.stop()
    assert [ev.body["log"] for ev in got] == ["first π", "second ∑"]


def test_tail_gbk_big5_supported():
    from fluentbit_tpu.core.plugin import registry

    for enc, codec_text in (("GBK", "gbk"), ("Big5", "big5"),
                            ("Win1251", "cp1251")):
        ins = registry.create_input("tail")
        ins.set("path", "/tmp/nope*")
        ins.set("generic.encoding", enc)
        ins.configure()
        ins.plugin.init(ins, None)  # must not raise


# ------------------------------------------------------- plot

def test_plot_output_writes_gnuplot_rows(tmp_path):
    out = tmp_path / "plot.dat"
    p = _make_output("plot", file=str(out), key="v")
    data = encode_event({"v": 1.5}, 10.0) + encode_event(
        {"v": 2}, 11.0) + encode_event({"other": "x"}, 12.0)
    asyncio.run(p.flush(bytes(data), "t", None))
    lines = out.read_text().strip().splitlines()
    assert len(lines) == 2
    ts, v = lines[0].split()
    assert float(ts) == 10.0 and float(v) == 1.5


# ------------------------------------------------------- formatters

def test_skywalking_format():
    p = _make_output("skywalking", svc_name="svc", svc_inst_name="i1")
    body = p.format(encode_event({"log": "x"}, 5.0), "t")
    arr = json.loads(body)
    assert arr[0]["service"] == "svc"
    assert arr[0]["timestamp"] == 5000
    assert json.loads(arr[0]["body"]["json"]["json"]) == {"log": "x"}


def test_azure_kusto_format():
    p = _make_output(
        "azure_kusto", tenant_id="t", client_id="c", client_secret="s",
        ingestion_endpoint="http://127.0.0.1:9999",
        database_name="db", table_name="tbl")
    body = p.format(encode_event({"a": 1}, 5.0), "mytag")
    row = json.loads(body.decode().splitlines()[0])
    assert row["a"] == 1 and row["tag"] == "mytag"
    assert p._uri().startswith("/v1/rest/ingest/db/tbl")


def test_azure_logs_ingestion_format():
    p = _make_output(
        "azure_logs_ingestion", tenant_id="t", client_id="c",
        client_secret="s", dce_url="http://127.0.0.1:9999",
        dcr_id="dcr-123", table_name="MyTable")
    rows = json.loads(p.format(encode_event({"a": 1}, 5.0), "t"))
    assert rows[0]["a"] == 1 and "TimeGenerated" in rows[0]
    assert "/dataCollectionRules/dcr-123/streams/Custom-MyTable" \
        in p._uri()


def test_chronicle_format(tmp_path):
    sa = tmp_path / "sa.json"
    sa.write_text(json.dumps({
        "client_email": "x@y", "private_key": "nope",
        "token_uri": "http://127.0.0.1:9/token"}))
    p = _make_output("chronicle", google_service_credentials=str(sa),
                     customer_id="cust-1", log_type="NIX_SYSTEM")
    payload = json.loads(p.format(encode_event({"m": "hi"}, 5.0), "t"))
    assert payload["customerId"] == "cust-1"
    assert payload["logType"] == "NIX_SYSTEM"
    assert json.loads(payload["entries"][0]["logText"]) == {"m": "hi"}


# ------------------------------------------------------- vivo

def test_vivo_exporter_serves_buffered_logs():
    ctx = flb.create(flush="40ms", grace="1")
    in_ffd = ctx.input("lib")
    ctx.output("vivo_exporter", match="*", listen="127.0.0.1", port="0")
    ctx.start()
    try:
        ctx.push(in_ffd, '{"msg": "vivo"}')
        plugin = ctx.engine.outputs[0].plugin
        deadline = time.time() + 5
        while plugin.bound_port is None and time.time() < deadline:
            time.sleep(0.05)
        assert plugin.bound_port is not None
        with socket.create_connection(
                ("127.0.0.1", plugin.bound_port), timeout=5) as s:
            s.sendall(b"GET /logs HTTP/1.1\r\nHost: x\r\n"
                      b"Connection: close\r\n\r\n")
            resp = b""
            while True:
                b = s.recv(4096)
                if not b:
                    break
                resp += b
    finally:
        ctx.stop()
    body = resp.split(b"\r\n\r\n", 1)[1]
    ts, tag, rec = json.loads(body.splitlines()[0])
    assert rec == {"msg": "vivo"} and tag == "lib.0"


# ------------------------------------------------------- kusto runtime

def test_azure_kusto_streaming_ingest_runtime():
    """AAD token exchange + streaming ingest against local stubs."""
    requests = []
    port_box = {}
    loop_box = {}

    def run():
        async def handle(reader, writer):
            try:
                head = bytearray()
                while not head.endswith(b"\r\n\r\n"):
                    head += await reader.readexactly(1)
                length = 0
                for line in head.decode("latin-1").split("\r\n"):
                    if line.lower().startswith("content-length:"):
                        length = int(line.split(":", 1)[1])
                body = await reader.readexactly(length) if length else b""
                first = head.decode("latin-1").split("\r\n")[0]
                requests.append((first, head.decode("latin-1"), body))
                if "/oauth2/" in first or "/token" in first:
                    resp = json.dumps({"access_token": "tok-1",
                                       "expires_in": 3600}).encode()
                else:
                    resp = b"{}"
                writer.write(
                    b"HTTP/1.1 200 OK\r\nContent-Length: %d\r\n"
                    b"Connection: close\r\n\r\n%s" % (len(resp), resp))
                await writer.drain()
            except (asyncio.IncompleteReadError, ConnectionError):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def main():
            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            port_box["port"] = server.sockets[0].getsockname()[1]

        loop = asyncio.new_event_loop()
        loop_box["loop"] = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(main())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 5
    while "port" not in port_box and time.time() < deadline:
        time.sleep(0.02)
    port = port_box["port"]

    ctx = flb.create(flush="40ms", grace="1")
    in_ffd = ctx.input("lib")
    ctx.output("azure_kusto", match="*", tenant_id="tid",
               client_id="cid", client_secret="sec",
               ingestion_endpoint=f"http://127.0.0.1:{port}",
               database_name="db", table_name="tbl",
               oauth_endpoint=f"http://127.0.0.1:{port}/tid/oauth2"
                              f"/v2.0/token")
    ctx.start()
    try:
        ctx.push(in_ffd, '{"k": "kusto"}')
        deadline = time.time() + 8
        while len(requests) < 2 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        ctx.stop()
        loop_box["loop"].call_soon_threadsafe(loop_box["loop"].stop)
    ingest = [r for r in requests
              if "/v1/rest/ingest/db/tbl" in r[0]]
    assert ingest, requests
    assert "Authorization: Bearer tok-1" in ingest[0][1]
    assert json.loads(ingest[0][2].splitlines()[0])["k"] == "kusto"
