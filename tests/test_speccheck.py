"""fbtpu-speccheck: the abstract sharding/shape/dtype interpreter.

Three layers of proof:

1. Rule fixtures — every one of the six rules has a red (fires), a
   green (sanctioned pattern stays quiet), and an ``fbtpu-lint:
   allow()`` suppression case, driven through ``SpecCheckRules`` with
   injected synthetic ProgramSpecs (the registry-driven rules) or
   plain source fixtures (the source-driven rules).
2. Soundness — the ``pad_to_devices`` discharge is exactly the real
   mesh's divisibility contract: the checker never accepts a dim the
   mesh rejects (property-tested numerically, spot-checked against a
   real ``NamedSharding`` on the simulated mesh).
3. Static == dynamic — for every shipped device program (grep
   batch-sharded, grep rule-sharded, the flux hll/cms/counts kernels)
   the checker's predicted per-leaf PartitionSpecs and donation set
   equal the LOWERED program's actual compiled shardings and
   ``donation_report`` on the simulated 8-device mesh. The abstraction
   is pinned to ground truth, not to its own mirror.
"""

import numpy as np
import pytest

from fluentbit_tpu.analysis import Module, lint_source
from fluentbit_tpu.analysis.speccheck import (
    REPLICATE_BUDGET, Aval, ProgramSpec, SpecCheckRules, dim_divisible,
    predict_donations, program_env, program_shardings,
    shardings_snapshot, shipped_programs)
from fluentbit_tpu.ops.mesh import AXIS, PARTITION_RULES, pad_to_devices

APACHE2 = (
    r'^(?<host>[^ ]*) [^ ]* (?<user>[^ ]*) \[(?<time>[^\]]*)\] '
    r'"(?<method>\S+)(?: +(?<path>[^ ]*) +\S*)?" '
    r'(?<code>[^ ]*) (?<size>[^ ]*)'
    r'(?: "(?<referer>[^\"]*)" "(?<agent>.*)")?$'
)


def rule_names(findings):
    return [f.rule for f in findings]


def check(programs, path, source):
    return SpecCheckRules(programs=programs).check(Module(path, source))


# ---------------------------------------------------------------------
# registry-driven rules: synthetic ProgramSpecs
# ---------------------------------------------------------------------

GO_SRC = "def go(x):\n    return x\n"

GO_ALLOW_SRC = (
    "def go(x):  # fbtpu-lint: allow({rule}) reviewed\n"
    "    return x\n"
)


def _prog(monkeypatch, rules, *, tables=(), inputs=(), outputs=(),
          donate=(), discharge=None, env=None):
    monkeypatch.setitem(PARTITION_RULES, "__test", rules)
    return ProgramSpec(
        name="t", module="x/mod.py", entry="go",
        axes=(("m", "n_dev"),), rules_key="__test",
        tables=tuple(tables), inputs=tuple(inputs),
        outputs=tuple(outputs), donate=tuple(donate),
        discharge=dict(discharge or {}), env=dict(env or {}))


def test_unmatched_leaf_fires(monkeypatch):
    p = _prog(monkeypatch, ((r"^named$", (AXIS,)),),
              tables=(Aval("named", ("8*n_dev",), "int32"),
                      Aval("orphan", (4,), "int32")))
    f = check([p], "x/mod.py", GO_SRC)
    assert "shard-unmatched-leaf" in rule_names(f)
    assert any("orphan" in x.message for x in f)
    # the named leaf itself is fine
    assert not any("`named`" in x.message
                   and x.rule == "shard-unmatched-leaf" for x in f)


def test_unmatched_leaf_catchall_over_budget(monkeypatch):
    big = REPLICATE_BUDGET + 4  # bytes of int8
    p = _prog(monkeypatch, ((r".*", ()),),
              tables=(Aval("huge", (big,), "int8"),
                      Aval("tiny", (8,), "int8")))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "shard-unmatched-leaf"]
    assert len(f) == 1 and "huge" in f[0].message
    assert "replication" in f[0].message


def test_unmatched_leaf_explicit_replicate_green(monkeypatch):
    # an explicit (named) replicate rule is a declared decision
    big = REPLICATE_BUDGET + 4
    p = _prog(monkeypatch, ((r"^huge$", ()),),
              tables=(Aval("huge", (big,), "int8"),))
    assert check([p], "x/mod.py", GO_SRC) == []


def test_unmatched_leaf_allow(monkeypatch):
    p = _prog(monkeypatch, ((r"^named$", (AXIS,)),),
              tables=(Aval("named", ("8*n_dev",), "int32"),
                      Aval("orphan", (4,), "int32")))
    src = GO_ALLOW_SRC.format(rule="shard-unmatched-leaf")
    assert "shard-unmatched-leaf" not in rule_names(
        check([p], "x/mod.py", src))


def test_shadowed_rule_subsumed(monkeypatch):
    p = _prog(monkeypatch, ((r"^tab", ("8*n_dev",) and (AXIS,)),
                            (r"^table$", ())),
              tables=(Aval("table", ("8*n_dev",), "int32"),))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "shard-shadowed-rule"]
    assert len(f) == 1 and "never fire" in f[0].message


def test_shadowed_rule_dead(monkeypatch):
    p = _prog(monkeypatch, ((r"^table$", (AXIS,)),
                            (r"^gone$", ())),
              tables=(Aval("table", ("8*n_dev",), "int32"),))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "shard-shadowed-rule"]
    assert len(f) == 1 and "matches no leaf" in f[0].message


def test_shadowed_rule_green(monkeypatch):
    p = _prog(monkeypatch, ((r"^a$", (AXIS,)), (r"^b$", ())),
              tables=(Aval("a", ("8*n_dev",), "int32"),
                      Aval("b", (4,), "int32")))
    assert check([p], "x/mod.py", GO_SRC) == []


def test_shadowed_rule_allow(monkeypatch):
    p = _prog(monkeypatch, ((r"^table$", (AXIS,)), (r"^gone$", ())),
              tables=(Aval("table", ("8*n_dev",), "int32"),))
    src = GO_ALLOW_SRC.format(rule="shard-shadowed-rule")
    assert "shard-shadowed-rule" not in rule_names(
        check([p], "x/mod.py", src))


def test_indivisible_axis_symbolic_requires_proof(monkeypatch):
    # "B" evaluates to a divisible value at canonical params — still
    # rejected: canonical luck is not a proof
    p = _prog(monkeypatch, ((r"^t$", (AXIS,)),),
              tables=(Aval("t", ("B",), "int32"),))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "shard-indivisible-axis"]
    assert len(f) == 1 and "not provably divisible" in f[0].message


def test_indivisible_axis_int_dim(monkeypatch):
    p = _prog(monkeypatch, ((r"^good$", (AXIS,)), (r"^bad$", (AXIS,))),
              tables=(Aval("good", (64,), "int32"),
                      Aval("bad", (12,), "int32")))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "shard-indivisible-axis"]
    assert len(f) == 1 and "`bad`" in f[0].message


def test_indivisible_axis_factor_green(monkeypatch):
    # a dim with the axis size as a literal factor is structurally safe
    p = _prog(monkeypatch, ((r"^t$", (AXIS,)),),
              tables=(Aval("t", ("8*n_dev",), "int32"),))
    assert check([p], "x/mod.py", GO_SRC) == []


PAD_SRC = (
    "def go(x):\n"
    "    Bp = pad_to_devices(B, n_dev)\n"
    "    return x\n"
)

GUARD_SRC = (
    "def go(x):\n"
    "    if R % n_dev != 0:\n"
    "        return None\n"
    "    return x\n"
)


def test_indivisible_axis_pad_discharge(monkeypatch):
    p = _prog(monkeypatch, ((r"^t$", (AXIS,)),),
              tables=(Aval("t", ("Bp",), "int32"),),
              discharge={"Bp": ("pad", "go")})
    assert check([p], "x/mod.py", PAD_SRC) == []


def test_indivisible_axis_guard_discharge(monkeypatch):
    # the 2-D rule-shard gate: R % n_dev == 0 proven by its own guard
    p = _prog(monkeypatch, ((r"^t$", (AXIS, None)),),
              tables=(Aval("t", ("R", 257), "int32"),),
              discharge={"R": ("guard", "go")})
    assert check([p], "x/mod.py", GUARD_SRC) == []


def test_indivisible_axis_stale_claim_fires(monkeypatch):
    # the claim names a function that no longer pads: the proof is
    # gone, the finding comes back
    p = _prog(monkeypatch, ((r"^t$", (AXIS,)),),
              tables=(Aval("t", ("Bp",), "int32"),),
              discharge={"Bp": ("pad", "go")})
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "shard-indivisible-axis"]
    assert len(f) == 1 and "no longer verifies" in f[0].message


def test_indivisible_axis_allow(monkeypatch):
    p = _prog(monkeypatch, ((r"^t$", (AXIS,)),),
              tables=(Aval("t", ("B",), "int32"),))
    src = GO_ALLOW_SRC.format(rule="shard-indivisible-axis")
    assert "shard-indivisible-axis" not in rule_names(
        check([p], "x/mod.py", src))


def test_donation_mismatch_fires(monkeypatch):
    # donated u8 input has no u8 output to alias
    p = _prog(monkeypatch, ((r"^t$", ()),),
              tables=(Aval("t", (8,), "int32"),),
              inputs=(Aval("x", ("B", "L"), "uint8", ("m", None),
                           donatable=True),),
              outputs=(Aval("y", ("B",), "int32", ("m",)),),
              donate=("x",))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "donation-aval-mismatch"]
    assert len(f) == 1 and "silent copy" in f[0].message


def test_donation_match_green(monkeypatch):
    p = _prog(monkeypatch, ((r"^t$", ()),),
              tables=(Aval("t", (8,), "int32"),),
              inputs=(Aval("x", ("8*n_dev",), "int32", ("m",),
                           donatable=True),),
              outputs=(Aval("y", ("8*n_dev",), "int32", ("m",)),),
              donate=("x",))
    assert check([p], "x/mod.py", GO_SRC) == []
    assert predict_donations(p) == ["x"]


def test_donation_unknown_input_fires(monkeypatch):
    p = _prog(monkeypatch, ((r"^t$", ()),),
              tables=(Aval("t", (8,), "int32"),),
              donate=("ghost",))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "donation-aval-mismatch"]
    assert len(f) == 1 and "names no input" in f[0].message


def test_donation_sharding_breaks_alias(monkeypatch):
    # same global shape but DIFFERENT sharding: per-device avals
    # differ, the alias cannot hold — the symbolic twin of
    # aliasable_donations' sharded-shape match
    p = _prog(monkeypatch, ((r"^t$", ()),),
              tables=(Aval("t", (8,), "int32"),),
              inputs=(Aval("x", ("8*n_dev",), "int32", ("m",),
                           donatable=True),),
              outputs=(Aval("y", ("8*n_dev",), "int32", ()),),
              donate=("x",))
    f = [x for x in check([p], "x/mod.py", GO_SRC)
         if x.rule == "donation-aval-mismatch"]
    assert len(f) == 1


def test_donation_allow(monkeypatch):
    p = _prog(monkeypatch, ((r"^t$", ()),),
              tables=(Aval("t", (8,), "int32"),),
              donate=("ghost",))
    src = GO_ALLOW_SRC.format(rule="donation-aval-mismatch")
    assert "donation-aval-mismatch" not in rule_names(
        check([p], "x/mod.py", src))


# ---------------------------------------------------------------------
# source-driven rules: shard_map bodies, literal rule tuples, jit
# boundaries
# ---------------------------------------------------------------------

RESHARD_RED = '''
from jax.sharding import PartitionSpec as P
def step(a, b):
    return a + b
fn = shard_map(step, mesh=m, in_specs=(P("x", None), P("y", None)),
               out_specs=P())
'''

RESHARD_GREEN_PSUM = '''
from jax.sharding import PartitionSpec as P
def step(a, b):
    bb = lax.psum(b, axis_name="y")
    return a + bb
fn = shard_map(step, mesh=m, in_specs=(P("x", None), P("y", None)),
               out_specs=P())
'''

RESHARD_GREEN_SAME = '''
from jax.sharding import PartitionSpec as P
def step(a, b):
    return a + b
fn = shard_map(step, mesh=m, in_specs=(P("x", None), P("x", None)),
               out_specs=P("x", None))
'''

RESHARD_GREEN_REDUCED = '''
from jax.sharding import PartitionSpec as P
def step(a, b):
    return a + jnp.sum(b, axis=0)
fn = shard_map(step, mesh=m, in_specs=(P(None, "x"), P("y", None)),
               out_specs=P())
'''


def test_implicit_reshard_fires():
    f = check([], "x/m.py", RESHARD_RED)
    assert rule_names(f) == ["shard-implicit-reshard"]
    assert "'x'" in f[0].message and "'y'" in f[0].message


def test_implicit_reshard_collective_green():
    assert check([], "x/m.py", RESHARD_GREEN_PSUM) == []


def test_implicit_reshard_same_axis_green():
    assert check([], "x/m.py", RESHARD_GREEN_SAME) == []


def test_implicit_reshard_reduction_drops_dim():
    # sum(axis=0) removes b's 'y' dim; what remains broadcasts against
    # a's trailing dim — rank mismatch degrades to unknown, no finding
    assert check([], "x/m.py", RESHARD_GREEN_REDUCED) == []


def test_implicit_reshard_allow():
    src = RESHARD_RED.replace(
        "return a + b",
        "return a + b  # fbtpu-lint: allow(shard-implicit-reshard) ok")
    assert check([], "x/m.py", src) == []


LITERAL_SHADOW = '''
specs = match_partition_rules(((".*", P()), ("^table$", P("x"))), tree)
'''


def test_literal_shadowed_rule():
    f = check([], "x/m.py", LITERAL_SHADOW)
    assert rule_names(f) == ["shard-shadowed-rule"]
    assert "first-match" in f[0].message


def test_literal_rules_ordered_green():
    src = ('specs = match_partition_rules((("^table$", P("x")), '
           '(".*", P())), tree)\n')
    assert check([], "x/m.py", src) == []


RETRACE_RED = '''
import jax, jax.numpy as jnp
def f(x, n):
    return x + jnp.zeros((n,), dtype=jnp.int32)
g = jax.jit(f)
'''

RETRACE_TRANSITIVE = '''
import jax, jax.numpy as jnp
def _impl(s, n_pad):
    return jnp.zeros((n_pad,), jnp.int32).at[s].add(1)
def f(s, n):
    return _impl(s, n)
g = jax.jit(f)
'''

RETRACE_GREEN_CLOSURE = '''
import jax, jax.numpy as jnp
def _impl(s, v, n_pad):
    return jnp.zeros((n_pad,), jnp.int32).at[s].add(v)
def build(n_pad):
    return jax.jit(lambda s, v: _impl(s, v, n_pad))
'''


def test_retrace_fires():
    f = check([], "x/m.py", RETRACE_RED)
    assert rule_names(f) == ["jit-dynamic-shape-retrace"]
    assert "`n`" in f[0].message


def test_retrace_transitive_fires():
    # n flows through f into _impl's shape position: still a dynamic
    # shape at the jit boundary
    f = check([], "x/m.py", RETRACE_TRANSITIVE)
    assert rule_names(f) == ["jit-dynamic-shape-retrace"]


def test_retrace_static_argnums_green():
    src = RETRACE_RED.replace("jax.jit(f)",
                              "jax.jit(f, static_argnums=(1,))")
    assert check([], "x/m.py", src) == []


def test_retrace_static_argnames_green():
    src = RETRACE_RED.replace(
        "jax.jit(f)", 'jax.jit(f, static_argnames=("n",))')
    assert check([], "x/m.py", src) == []


def test_retrace_closure_cache_green():
    # the sanctioned pattern: the dim is closed over and the compiled
    # fn cached per dim (flux.kernels.segment_counts)
    assert check([], "x/m.py", RETRACE_GREEN_CLOSURE) == []


def test_retrace_allow():
    src = RETRACE_RED.replace(
        "g = jax.jit(f)",
        "g = jax.jit(f)  # fbtpu-lint: allow(jit-dynamic-shape-retrace)")
    assert check([], "x/m.py", src) == []


def test_lint_source_integration():
    # the default rule set carries the pack: source fixtures fire
    # through the shared lint_source entry point too
    f = [x for x in lint_source(RETRACE_RED, "x/m.py")
         if x.rule == "jit-dynamic-shape-retrace"]
    assert len(f) == 1


# ---------------------------------------------------------------------
# match_partition_rules dead-rule bugfix (ops.mesh)
# ---------------------------------------------------------------------

def _dead_rule_setup():
    jax = pytest.importorskip("jax")
    from jax.sharding import PartitionSpec as P

    tree = {"table": np.zeros((8,), np.int32)}
    rules = ((r"^table$", P()), (r"^gone$", P("x")))
    return tree, rules


def test_match_partition_rules_dead_rule_raises():
    from fluentbit_tpu.ops.mesh import match_partition_rules

    tree, rules = _dead_rule_setup()
    with pytest.raises(ValueError, match="matched no leaf"):
        match_partition_rules(rules, tree)


def test_match_partition_rules_dead_rule_warns():
    from fluentbit_tpu.ops.mesh import match_partition_rules

    tree, rules = _dead_rule_setup()
    with pytest.warns(UserWarning, match="matched no leaf"):
        specs = match_partition_rules(rules, tree, dead_rules="warn")
    assert set(specs) == {"table"}


def test_match_partition_rules_dead_rule_ignore():
    from fluentbit_tpu.ops.mesh import match_partition_rules

    tree, rules = _dead_rule_setup()
    specs = match_partition_rules(rules, tree, dead_rules="ignore")
    assert set(specs) == {"table"}


# ---------------------------------------------------------------------
# pad_to_devices discharge soundness (property)
# ---------------------------------------------------------------------

def test_pad_discharge_sound_property():
    # the checker's int-dim acceptance is EXACTLY the mesh's
    # divisibility contract: accept ⇔ n_dev | dim. pad_to_devices
    # output always lands on the accept side.
    rng = np.random.RandomState(20260805)
    for _ in range(500):
        B = int(rng.randint(0, 1 << 14))
        n = int(rng.randint(1, 64))
        Bp = pad_to_devices(B, n)
        assert Bp >= max(B, 1) and Bp % n == 0
        assert dim_divisible(Bp, "n", {"n": n}) is True
    for _ in range(500):
        d = int(rng.randint(1, 1 << 14))
        n = int(rng.randint(1, 64))
        assert dim_divisible(d, "n", {"n": n}) is (d % n == 0)


def test_pad_discharge_sound_on_real_mesh():
    # spot-check the property against the real thing: a dim the
    # checker accepts device_puts cleanly; one it proves indivisible
    # is rejected by the mesh
    jax = pytest.importorskip("jax")
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from fluentbit_tpu.ops.mesh import build_mesh

    mesh = build_mesh(8, axis="m")
    if mesh is None:
        pytest.skip("needs the simulated 8-device mesh")
    sh = NamedSharding(mesh, P("m"))
    for B in (8, 24, 4096):
        assert dim_divisible(B, "n_dev", {"n_dev": 8}) is True
        out = jax.device_put(np.zeros((B,), np.int32), sh)
        assert out.shape == (B,)
    for B in (4, 12, 1001):
        assert dim_divisible(B, "n_dev", {"n_dev": 8}) is False
        with pytest.raises(Exception):
            jax.device_put(np.zeros((B,), np.int32), sh)


# ---------------------------------------------------------------------
# static == dynamic: predicted specs/donation vs the lowered programs
# ---------------------------------------------------------------------

def _mesh8(axis):
    jax = pytest.importorskip("jax")
    from fluentbit_tpu.ops.mesh import build_mesh

    mesh = build_mesh(8, axis=axis)
    if mesh is None or mesh.devices.size != 8:
        pytest.skip("needs the simulated 8-device mesh")
    return mesh


def _registry(name):
    progs = {p.name: p for p in shipped_programs()}
    if name not in progs:
        pytest.skip("shipped-program registry unavailable (no jax)")
    return progs[name]


def _assert_spec(mesh, actual, predicted, ndim):
    """predicted is the JSON-shaped spec (list entries / None);
    equality is sharding equivalence on the mesh — NamedSharding and
    GSPMDSharding actuals both compare."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    ent = tuple(tuple(e) if isinstance(e, list) else e
                for e in (predicted or ()))
    want = NamedSharding(mesh, P(*ent))
    assert actual.is_equivalent_to(want, ndim), (actual, predicted)


@pytest.mark.mesh
@pytest.mark.parametrize("variant", ["batch", "rules"])
def test_crosscheck_grep(variant, monkeypatch):
    jax = pytest.importorskip("jax")
    from fluentbit_tpu.ops.grep import GrepProgram
    from fluentbit_tpu.regex.dfa import compile_dfa

    mesh = _mesh8("batch")
    if variant == "rules":
        # drop the rule-shard threshold so R=8 enters the variant the
        # registry models (the gate itself is mesh_variant's R % n_dev
        # guard — the discharge speccheck verifies)
        monkeypatch.setenv("FBTPU_MESH_RULE_SHARD_R", "2")
        R = 8
    else:
        R = 2
    prog = GrepProgram([compile_dfa(APACHE2)] * R, max_len=64)
    assert prog.mesh_variant(mesh) == variant

    h = prog._mesh_handle(mesh)
    Bp = 16
    batch = np.zeros((R, Bp, 64), np.uint8)
    lengths = np.full((R, Bp), -1, np.int32)
    bd = jax.device_put(batch, h.sh_b)
    ld = jax.device_put(lengths, h.sh_l)
    compiled = h.fn.lower(h.tables, bd, ld).compile()
    tbl_sh, b_sh, l_sh = compiled.input_shardings[0]

    pred = program_shardings(_registry(f"grep.mesh[{variant}]"))
    assert set(pred["tables"]) == set(tbl_sh), \
        "registry leaves drifted from the built table pytree"
    for leaf, sh in tbl_sh.items():
        _assert_spec(mesh, sh, pred["tables"][leaf],
                     np.asarray(h.tables[leaf]).ndim)
    _assert_spec(mesh, b_sh, pred["inputs"]["batch"], 3)
    _assert_spec(mesh, l_sh, pred["inputs"]["lengths"], 2)
    mask_sh, counts_sh = compiled.output_shardings
    _assert_spec(mesh, mask_sh, pred["outputs"]["mask"], 2)
    _assert_spec(mesh, counts_sh, pred["outputs"]["counts"], 1)

    # predicted donation set == the lowered module's held aliases
    rep = prog.donation_info(mesh, B=Bp)
    assert rep["variant"] == variant
    assert pred["donate_predicted"] == rep["declared"] == ["lengths"]
    assert rep["held"] is True


@pytest.mark.mesh
def test_crosscheck_flux_kernels():
    jax = pytest.importorskip("jax")
    from fluentbit_tpu.flux.kernels import build_sharded_counts
    from fluentbit_tpu.ops.sketch import (CountMin, HyperLogLog,
                                          build_sharded_cms,
                                          build_sharded_hll)

    mesh = _mesh8("flux")
    Bp, L = 16, 8
    batch = np.zeros((Bp, L), np.uint8)
    lens = np.ones((Bp,), np.int32)

    hll = HyperLogLog(p=12)
    regs = np.asarray(hll.registers)
    comp = build_sharded_hll(hll, mesh).lower(regs, batch, lens).compile()
    pred = program_shardings(_registry("flux.hll"))
    r_sh, b_sh, l_sh = comp.input_shardings[0]
    _assert_spec(mesh, r_sh, pred["tables"]["registers"], regs.ndim)
    _assert_spec(mesh, b_sh, pred["inputs"]["batch"], 2)
    _assert_spec(mesh, l_sh, pred["inputs"]["lengths"], 1)
    (out_sh,) = jax.tree_util.tree_leaves(comp.output_shardings)
    _assert_spec(mesh, out_sh, pred["outputs"]["registers_out"],
                 regs.ndim)
    assert pred["donate_predicted"] == []

    cms = CountMin()
    table = np.asarray(cms.table)
    w = np.ones((Bp,), np.int32)
    comp = build_sharded_cms(cms, mesh).lower(
        table, batch, lens, w).compile()
    pred = program_shardings(_registry("flux.cms"))
    t_sh, b_sh, l_sh, w_sh = comp.input_shardings[0]
    _assert_spec(mesh, t_sh, pred["tables"]["table"], table.ndim)
    _assert_spec(mesh, b_sh, pred["inputs"]["batch"], 2)
    _assert_spec(mesh, l_sh, pred["inputs"]["lengths"], 1)
    _assert_spec(mesh, w_sh, pred["inputs"]["weights"], 1)
    (out_sh,) = jax.tree_util.tree_leaves(comp.output_shardings)
    _assert_spec(mesh, out_sh, pred["outputs"]["table_out"], table.ndim)

    seg = np.zeros((Bp,), np.int32)
    comp = build_sharded_counts(mesh, 8).lower(seg, lens).compile()
    pred = program_shardings(_registry("flux.counts"))
    s_sh, v_sh = comp.input_shardings[0]
    _assert_spec(mesh, s_sh, pred["inputs"]["seg"], 1)
    _assert_spec(mesh, v_sh, pred["inputs"]["valid"], 1)
    (out_sh,) = jax.tree_util.tree_leaves(comp.output_shardings)
    _assert_spec(mesh, out_sh, pred["outputs"]["counts"], 1)


@pytest.mark.mesh
def test_shipped_tree_speccheck_clean():
    # the acceptance gate in miniature: zero unbaselined speccheck
    # findings on the shipped package (the tree gate in test_lint.py
    # asserts the same through the full rule set)
    import os

    from fluentbit_tpu.analysis import lint_paths

    pkg = os.path.dirname(
        os.path.abspath(__import__("fluentbit_tpu").__file__))
    names = set(SpecCheckRules.RULE_NAMES)
    hits = [f for f in lint_paths([pkg]) if f.rule in names]
    assert hits == [], [f"{f.path}:{f.line} {f.rule}" for f in hits]


# ---------------------------------------------------------------------
# budget plumbing: shardings snapshot + spec-change regression
# ---------------------------------------------------------------------

def test_shardings_snapshot_shape():
    snap = shardings_snapshot()
    if not snap:
        pytest.skip("shipped-program registry unavailable (no jax)")
    assert set(snap) == {"grep.jit", "grep.mesh[batch]",
                        "grep.mesh[rules]", "flux.hll", "flux.cms",
                        "flux.counts", "flux.fused"}
    gr = snap["grep.mesh[rules]"]
    assert gr["tables"]["trans_flat"] == ["batch", None]
    assert gr["donate_predicted"] == ["lengths"]
    assert snap["flux.hll"]["tables"]["registers"] == []
    assert snap["flux.counts"]["inputs"]["seg"] == ["flux"]
    fu = snap["flux.fused"]
    assert fu["inputs"]["seg"] == ["flux"]
    assert fu["inputs"]["registers"] == []
    assert fu["donate_predicted"] == ["registers"]


def _sharding_budgets():
    base = {"chains": {}, "shardings": {
        "p": {"tables": {"t": ["m", None]}, "inputs": {}, "outputs": {},
              "donate_predicted": ["x"]}}}
    cur = {"chains": {}, "shardings": {
        "p": {"tables": {"t": ["m", None]}, "inputs": {}, "outputs": {},
              "donate_predicted": ["x"]}}}
    return base, cur


def test_budget_spec_change_regression():
    from fluentbit_tpu.analysis.launchgraph import compare_budget

    base, cur = _sharding_budgets()
    reg, _ = compare_budget(cur, base)
    assert reg == []
    cur["shardings"]["p"]["tables"]["t"] = [None, "m"]
    reg, _ = compare_budget(cur, base)
    assert len(reg) == 1 and "sharding changed" in reg[0]


def test_budget_donation_change_regression():
    from fluentbit_tpu.analysis.launchgraph import compare_budget

    base, cur = _sharding_budgets()
    cur["shardings"]["p"]["donate_predicted"] = []
    reg, _ = compare_budget(cur, base)
    assert len(reg) == 1 and "donation set changed" in reg[0]


def test_budget_new_program_regression():
    from fluentbit_tpu.analysis.launchgraph import compare_budget

    base, cur = _sharding_budgets()
    cur["shardings"]["q"] = {"tables": {}, "inputs": {}, "outputs": {},
                             "donate_predicted": []}
    reg, _ = compare_budget(cur, base)
    assert len(reg) == 1 and "new device program" in reg[0]


def test_budget_old_baseline_gates_nothing():
    # a pre-speccheck baseline (no shardings block) must not fail —
    # old synthetic baselines in tests and mid-upgrade CI stay valid
    from fluentbit_tpu.analysis.launchgraph import compare_budget

    _, cur = _sharding_budgets()
    reg, _ = compare_budget(cur, {"chains": {}})
    assert reg == []


def test_committed_budget_carries_shardings():
    import json

    from fluentbit_tpu.analysis.registry import budget_path

    with open(budget_path(), "r", encoding="utf-8") as fh:
        budget = json.load(fh)["budget"]
    snap = shardings_snapshot()
    if not snap:
        pytest.skip("shipped-program registry unavailable (no jax)")
    assert budget.get("shardings") == snap


# ---------------------------------------------------------------------
# qos defer-hint collector pacing (carried-over satellite)
# ---------------------------------------------------------------------

def test_collector_delay_paces_deferred_input():
    from fluentbit_tpu.core.engine import Engine

    e = Engine()
    ins = e.input("dummy")
    # not qos-paused: the configured interval rules
    assert e._collector_delay(ins, 0.5) == 0.5
    ins.paused_by_qos = True
    ins._qos_defer_cost = 4096
    e.qos.defer_hint = lambda i, n: 12.0
    assert e._collector_delay(ins, 0.5) == 12.0
    # never below the interval, capped at 30s
    e.qos.defer_hint = lambda i, n: 0.01
    assert e._collector_delay(ins, 0.5) == 0.5
    e.qos.defer_hint = lambda i, n: 1e9
    assert e._collector_delay(ins, 0.5) == 30.0
    # a hint failure degrades to the plain interval, never raises
    def boom(i, n):
        raise RuntimeError("bucket gone")
    e.qos.defer_hint = boom
    assert e._collector_delay(ins, 0.5) == 0.5


def test_collector_delay_uses_real_hint():
    from fluentbit_tpu.core.engine import Engine

    e = Engine()
    ins = e.input("dummy")
    ins.paused_by_qos = True
    ins._qos_defer_cost = 128
    got = e._collector_delay(ins, 0.25)
    assert isinstance(got, float) and 0.25 <= got <= 30.0
