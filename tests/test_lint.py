"""fbtpu-lint: the analyzer gates the package tree, and the analyzer
itself is pinned by fixtures — every rule must fire on a known-bad
snippet, stay quiet on the known-good twin, and honor the
``# fbtpu-lint: allow(...)`` suppression path.

The fixture paths matter: guarded-by findings key off the registry's
module paths, so the bad snippets are linted *as if* they lived in
core/engine.py etc. — a deliberately-introduced guarded-attribute
access, an await-under-lock, or a host-sync-in-traced-code would fail
this file exactly like it fails `python -m fluentbit_tpu.analysis`.
"""

import os
import subprocess
import sys

from fluentbit_tpu.analysis import lint_paths, lint_source
from fluentbit_tpu.analysis.registry import GuardEntry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "fluentbit_tpu")


def rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------
# the gate: the shipped tree must be clean
# ---------------------------------------------------------------------

def test_package_tree_clean():
    # the committed launch/transfer budget (analysis/launch_budget.json)
    # is the one sanctioned baseline: its recorded launch-graph debt
    # (ROADMAP item 1) is subtracted exactly — anything else fails, and
    # a stale baseline entry that no longer matches the tree fails too
    # ... and since the locksmith pack, analysis/lock_baseline.json is
    # the second sanctioned baseline, since the memscope pack,
    # analysis/copy_budget.json the third, and since the fuseplan
    # pack, analysis/fusion_plan.json the fourth — all are subtracted
    # EXACTLY
    import json

    from fluentbit_tpu.analysis.__main__ import _canon
    from fluentbit_tpu.analysis.registry import budget_path, \
        copy_budget_path, fusion_plan_path, lock_baseline_path

    recorded = set()
    for bpath in (budget_path(), lock_baseline_path(),
                  copy_budget_path(), fusion_plan_path()):
        with open(bpath, "r", encoding="utf-8") as fh:
            recorded |= {(d["path"], d["rule"], d["message"])
                         for d in json.load(fh)["findings"]}
    findings = lint_paths([PKG])
    keys = {(_canon(f.path), f.rule, f.message) for f in findings}
    fresh = [f for f in findings
             if (_canon(f.path), f.rule, f.message) not in recorded]
    assert not fresh, "\n".join(f.render() for f in fresh)
    stale = recorded - keys
    assert not stale, f"stale baseline entries: {stale}"


def test_cli_exit_codes(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "fluentbit_tpu.analysis", PKG],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bad = tmp_path / "fluentbit_tpu" / "plugins"
    bad.mkdir(parents=True)
    (bad / "x.py").write_text(
        "try:\n    f()\nexcept Exception:\n    pass\n")
    proc = subprocess.run(
        [sys.executable, "-m", "fluentbit_tpu.analysis", str(bad)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    assert "swallowed-error" in proc.stdout


def test_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "fluentbit_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0
    for name in ("guarded-by", "await-in-lock", "swallowed-error",
                 "batch-decline-after-commit", "batch-commit-replay",
                 "batch-no-fallback", "batch-unordered-emit",
                 "decline-swallow", "dtype-narrowing",
                 "await-no-deadline",
                 "device-multi-launch-chain", "device-undonated-buffer",
                 "device-host-roundtrip", "device-sync-in-staging-loop",
                 "stage-redundant-copy",
                 "shard-unmatched-leaf", "shard-shadowed-rule",
                 "shard-indivisible-axis", "donation-aval-mismatch",
                 "shard-implicit-reshard", "jit-dynamic-shape-retrace",
                 "codec-balance", "codec-bounds", "codec-leak",
                 "untrusted-bounds",
                 "lock-order-cycle", "guarded-field-unlocked",
                 "guarded-by-missing", "atomicity-check-then-act",
                 "lock-held-across-dispatch", "cow-swap-aliasing",
                 "host-redundant-copy", "host-decode-then-restage",
                 "host-mutable-view-escape", "mmap-lifetime-escape",
                 "fusable-unfused-boundary",
                 "fusion-blocked-by-host-compact",
                 "cross-launch-restage", "fused-effect-violation",
                 "fusion-plan-regression", "stale-suppression"):
        assert name in proc.stdout


# ---------------------------------------------------------------------
# guarded-by (lock discipline)
# ---------------------------------------------------------------------

BAD_GUARDED = """
class Engine:
    def park(self, chunks):
        self._backlog.extend(chunks)
"""

GOOD_GUARDED = """
class Engine:
    def park(self, chunks):
        with self._ingest_lock:
            self._backlog.extend(chunks)
"""


def test_guarded_attr_fires_off_lock():
    got = lint_source(BAD_GUARDED, "fluentbit_tpu/core/engine.py")
    assert rules(got) == ["guarded-by"]
    assert "_ingest_lock" in got[0].message


def test_guarded_attr_quiet_under_lock():
    assert lint_source(GOOD_GUARDED, "fluentbit_tpu/core/engine.py") == []


def test_guarded_attr_suppression():
    src = BAD_GUARDED.replace(
        "self._backlog.extend(chunks)",
        "self._backlog.extend(chunks)  # fbtpu-lint: allow(guarded-by)")
    assert lint_source(src, "fluentbit_tpu/core/engine.py") == []


def test_guarded_attr_init_exempt_and_alias():
    src = """
class Engine:
    def __init__(self):
        self._backlog = []

    def drain(self, ins, parallel):
        lock = ins.ingest_lock if parallel else self._ingest_lock
        with lock:
            self._backlog.append(1)
"""
    assert lint_source(src, "fluentbit_tpu/core/engine.py") == []


def test_guarded_closure_under_lock_still_flagged():
    # a closure born inside the lock runs later, without it
    src = """
class Engine:
    def sched(self):
        with self._ingest_lock:
            def later():
                self._backlog.append(1)
        return later
"""
    got = lint_source(src, "fluentbit_tpu/core/engine.py")
    assert rules(got) == ["guarded-by"]


def test_alias_is_function_scoped():
    # an alias minted in one function must not legitimize `with lock:`
    # in a sibling that bound the same NAME to a different lock
    src = """
class Engine:
    def a(self):
        lock = self._ingest_lock
        with lock:
            self._backlog.append(1)

    def b(self):
        lock = self._other_mutex
        with lock:
            self._task_map.clear()
"""
    got = lint_source(src, "fluentbit_tpu/core/engine.py")
    assert rules(got) == ["guarded-by"]
    assert len(got) == 1 and "_task_map" in got[0].message  # b() only


def test_lambda_under_lock_still_flagged():
    # a lambda born under the lock runs later, without it
    src = """
class Engine:
    def sched(self):
        with self._ingest_lock:
            cb = lambda: self._task_map.pop(1, None)
        return cb
"""
    got = lint_source(src, "fluentbit_tpu/core/engine.py")
    assert rules(got) == ["guarded-by"]


def test_cli_bad_path_fails_loudly():
    proc = subprocess.run(
        [sys.executable, "-m", "fluentbit_tpu.analysis",
         "fluentbit_tpu/core/engine.pyy"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2
    assert "not a directory or .py file" in proc.stderr


def test_guarded_global_and_writes_only():
    guards = (GuardEntry("mod.py", "_lock", ("_state",),
                         writes_only=True, kind="global"),)
    bad = "def f():\n    global _state\n    _state = 'x'\n"
    good = ("import threading\n_lock = threading.Lock()\n_state = None\n"
            "def probe():\n    return _state\n"
            "def set_it(v):\n    global _state\n"
            "    with _lock:\n        _state = v\n")
    assert rules(lint_source(bad, "mod.py", guards)) == ["guarded-by"]
    assert lint_source(good, "mod.py", guards) == []


# ---------------------------------------------------------------------
# await-in-lock
# ---------------------------------------------------------------------

BAD_AWAIT = """
import asyncio
class E:
    async def main(self):
        with self._ingest_lock:
            await asyncio.sleep(0.1)
"""

GOOD_AWAIT = """
import asyncio
class E:
    async def main(self):
        with self._ingest_lock:
            x = 1
        await asyncio.sleep(0.1)
        async with self._aio_lock:
            await asyncio.sleep(0.1)
"""


def test_await_under_threading_lock_fires():
    got = lint_source(BAD_AWAIT, "fluentbit_tpu/core/engine.py")
    assert rules(got) == ["await-in-lock"]


def test_await_outside_lock_and_async_with_quiet():
    assert lint_source(GOOD_AWAIT, "fluentbit_tpu/core/engine.py") == []


def test_await_in_nested_def_not_attributed_to_outer_lock():
    src = """
import asyncio
class E:
    def make(self):
        with self._ingest_lock:
            async def later():
                await asyncio.sleep(0)
        return later
"""
    assert lint_source(src, "fluentbit_tpu/core/engine.py") == []


# ---------------------------------------------------------------------
# jax purity / retrace
# ---------------------------------------------------------------------

BAD_HOST_SYNC = """
import jax
import numpy as np

@jax.jit
def kernel(batch):
    host = np.asarray(batch)
    return batch + host.sum()
"""

BAD_TRACED_CHAIN = """
import jax
from jax import lax

class P:
    def _materialize(self):
        impl = self._assoc if self.kernel else self._scan
        self._jit = jax.jit(impl)

    def _scan(self, batch, lengths):
        def step(s, c):
            print("tracing")
            return s, None
        out, _ = lax.scan(step, batch, lengths)
        return out.block_until_ready()

    def _assoc(self, batch, lengths):
        if batch.shape[0] > 128:
            return batch
        return lengths
"""

GOOD_KERNEL = """
import jax
import jax.numpy as jnp
from jax import lax

@jax.jit
def kernel(batch, lengths):
    pad = jnp.arange(batch.shape[1]) >= lengths[:, None]
    cls = jnp.where(pad, 0, batch)

    def step(s, c):
        return s + c.sum(), None

    out, _ = lax.scan(step, jnp.zeros(()), cls.T)
    return out


def host_wrapper(batch, lengths):
    import numpy as np
    return np.asarray(kernel(batch, lengths))
"""


def test_host_sync_in_jitted_fn_fires():
    got = lint_source(BAD_HOST_SYNC, "fluentbit_tpu/ops/fixture.py")
    assert rules(got) == ["jax-host-sync"]


def test_traced_chain_through_alias_scan_and_shape_branch():
    got = lint_source(BAD_TRACED_CHAIN, "fluentbit_tpu/ops/fixture.py")
    assert rules(got) == ["jax-host-sync", "jax-retrace",
                          "jax-side-effect"]


def test_pure_kernel_quiet_and_host_wrapper_untraced():
    # np.asarray is fine OUTSIDE traced code (host_wrapper)
    assert lint_source(GOOD_KERNEL, "fluentbit_tpu/ops/fixture.py") == []


def test_purity_suppression():
    src = BAD_HOST_SYNC.replace(
        "host = np.asarray(batch)",
        "host = np.asarray(batch)  # fbtpu-lint: allow(jax-host-sync)")
    assert lint_source(src, "fluentbit_tpu/ops/fixture.py") == []


# batched filter entry points (process_batch): the retrace rule fires
# on shape branches even though the def itself is not traced — a shape
# branch there re-specializes every kernel the batch feeds

BAD_PROCESS_BATCH = """
import numpy as np

class F:
    def process_batch(self, chunk):
        staged = self._stage(chunk)
        if staged.shape[0] > 128:
            return self._kernel_big(staged)
        return self._kernel_small(staged)
"""

GOOD_PROCESS_BATCH = """
import numpy as np

class F:
    def process_batch(self, chunk):
        staged = self._stage(chunk)           # bucketed upstream
        host = np.asarray(staged)             # host sync is legal here
        if chunk.n is None:
            return None
        return self._kernel(host)
"""


def test_process_batch_shape_branch_fires():
    got = lint_source(BAD_PROCESS_BATCH,
                      "fluentbit_tpu/plugins/filter_x.py")
    assert rules(got) == ["jax-retrace"]
    assert "process_batch" in got[0].message


def test_process_batch_host_code_quiet():
    # host syncs and branches on plain ints stay legal in batched
    # entries — only array-shape branches re-specialize kernels
    assert lint_source(GOOD_PROCESS_BATCH,
                       "fluentbit_tpu/plugins/filter_x.py") == []


def test_process_batch_suppression():
    src = BAD_PROCESS_BATCH.replace(
        "if staged.shape[0] > 128:",
        "if staged.shape[0] > 128:  # fbtpu-lint: allow(jax-retrace)")
    assert lint_source(src, "fluentbit_tpu/plugins/filter_x.py") == []


# pjit / shard_map coverage (the partitioned mesh plane): decorated and
# call-arg forms both seed tracing, and host-callback escapes fire —
# a callback inside a sharded program blocks every device's step

BAD_PJIT_DECORATED = """
import jax
import numpy as np
from jax.experimental.pjit import pjit

@pjit
def kernel(tables, batch):
    host = np.asarray(batch)
    return batch + host.sum()
"""

BAD_SHARD_MAP_CALLBACK = """
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

def build(mesh, specs):
    def step(t, batch, lengths):
        extra = jax.pure_callback(lambda x: x + 1, batch, batch)
        return extra + t["starts"]

    return jax.jit(shard_map(step, mesh=mesh, in_specs=specs,
                             out_specs=specs))
"""

GOOD_MESH_PROGRAM = """
import re
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

def match_partition_rules(rules, tree):
    # host-side partition-rules layer: np use is legal here (untraced)
    def pick(name, leaf):
        if np.prod(getattr(leaf, "shape", ())) == 1:
            return P()
        for rule, spec in rules:
            if re.search(rule, name):
                return spec
        raise ValueError(name)
    return {k: pick(k, v) for k, v in tree.items()}

def build(mesh, tspecs, axis):
    def step(t, batch, lengths):
        # pytree-structure membership is static per jit cache entry,
        # not tracer boolification — must stay quiet
        if "pair_maps" in t:
            base = t["pair_maps"]
        else:
            base = t["starts"]
        mask = (batch.sum(axis=2) + base[:, None] > 0) & (lengths >= 0)
        return mask.astype(jnp.int32)

    return jax.jit(shard_map(step, mesh=mesh,
                             in_specs=(tspecs, P(None, axis, None),
                                       P(None, axis)),
                             out_specs=P(None, axis)))
"""


def test_pjit_decorated_host_sync_fires():
    got = lint_source(BAD_PJIT_DECORATED, "fluentbit_tpu/ops/fixture.py")
    assert rules(got) == ["jax-host-sync"]


def test_shard_map_arg_callback_fires():
    got = lint_source(BAD_SHARD_MAP_CALLBACK,
                      "fluentbit_tpu/ops/fixture.py")
    assert rules(got) == ["jax-host-sync"]
    assert "callback" in got[0].message


def test_mesh_program_with_partition_rules_quiet():
    # the partition-rules layer is host code (np/re legal); the
    # shard_map'd step's dict-membership branch is pytree structure
    assert lint_source(GOOD_MESH_PROGRAM,
                       "fluentbit_tpu/ops/fixture.py") == []


def test_membership_over_traced_array_param_still_fires():
    # the pytree-membership exemption is scoped to params the kernel
    # also string-subscripts (dict pytrees); `"GET" in batch` over a
    # traced ARRAY iterates the tracer at trace time and must fire
    src = """
import jax

@jax.jit
def kernel(batch, lengths):
    if "GET" in batch:
        return lengths
    return batch
"""
    got = lint_source(src, "fluentbit_tpu/ops/fixture.py")
    assert rules(got) == ["jax-retrace"]


# ---------------------------------------------------------------------
# swallowed-error
# ---------------------------------------------------------------------

BAD_SWALLOW = """
def flush(x):
    try:
        send(x)
    except Exception:
        pass
"""


def test_broad_swallow_fires_on_data_path():
    got = lint_source(BAD_SWALLOW, "fluentbit_tpu/plugins/out_x.py")
    assert rules(got) == ["swallowed-error"]


def test_narrow_or_observable_handlers_quiet():
    src = """
def flush(x, m):
    try:
        send(x)
    except OSError:
        pass
    try:
        send(x)
    except Exception:
        m.inc(1)
"""
    assert lint_source(src, "fluentbit_tpu/plugins/out_x.py") == []


def test_swallow_off_data_path_quiet():
    assert lint_source(BAD_SWALLOW, "fluentbit_tpu/luart/interp.py") == []


def test_swallow_suppression_on_pass_line():
    src = BAD_SWALLOW.replace(
        "        pass",
        "        pass  # fbtpu-lint: allow(swallowed-error)")
    assert lint_source(src, "fluentbit_tpu/plugins/out_x.py") == []


def test_bare_and_tuple_broad_excepts_fire():
    src = """
def a(x):
    try:
        go(x)
    except:
        pass

def b(x):
    try:
        go(x)
    except (ValueError, Exception):
        pass
"""
    got = lint_source(src, "fluentbit_tpu/core/x.py")
    assert len(got) == 2 and rules(got) == ["swallowed-error"]


# ---------------------------------------------------------------------
# batch exactness (process_batch contract dataflow)
# ---------------------------------------------------------------------

BAD_DECLINE_AFTER_COMMIT = """
class F:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        n = chunk.n
        self.metric.inc(n, ())
        if n > 100:
            return None
        return (n, chunk.data, n)
"""

GOOD_DECLINE_BEFORE_COMMIT = """
class F:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        n = chunk.n
        if n is None:
            return None
        self.metric.inc(n, ())
        return (n, chunk.data, n)
"""


def test_decline_after_commit_fires():
    got = lint_source(BAD_DECLINE_AFTER_COMMIT,
                      "fluentbit_tpu/plugins/filter_x.py")
    assert "batch-decline-after-commit" in rules(got)


def test_decline_before_commit_quiet():
    assert lint_source(GOOD_DECLINE_BEFORE_COMMIT,
                       "fluentbit_tpu/plugins/filter_x.py") == []


def test_decline_after_commit_interprocedural():
    # the commit hides inside a self-method, the decline in a tail call
    src = """
class F:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def _bump(self, n):
        self.metric.inc(n, ())

    def _finish(self, chunk):
        if chunk.n is None:
            return None
        return (chunk.n, chunk.data, chunk.n)

    def process_batch(self, chunk):
        self._bump(chunk.n)
        return self._finish(chunk)
"""
    got = lint_source(src, "fluentbit_tpu/plugins/filter_x.py")
    assert "batch-decline-after-commit" in rules(got)


def test_fallback_error_raise_after_commit_fires():
    src = BAD_DECLINE_AFTER_COMMIT.replace(
        "return None", "raise FallbackError('decline')")
    got = lint_source(src, "fluentbit_tpu/plugins/filter_x.py")
    assert "batch-decline-after-commit" in rules(got)


def test_tail_call_decline_before_commit_quiet():
    # the GOOD pattern refactored into a helper: the tail callee
    # declines BEFORE committing — must not be double-inlined into a
    # false decline-after-commit
    src = """
class F:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def _impl(self, chunk):
        if chunk.n is None:
            return None
        self.metric.inc(chunk.n, ())
        return (chunk.n, chunk.data, chunk.n)

    def process_batch(self, chunk):
        return self._impl(chunk)
"""
    assert lint_source(src, "fluentbit_tpu/plugins/filter_x.py") == []


BAD_COMMIT_REPLAY = """
class F:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        if chunk.n is None:
            return None
        for tag, payload in chunk.groups:
            self.emitter.add_record(tag, payload, 1)
        return (chunk.n, chunk.data, chunk.n)
"""


def test_unguarded_emit_loop_replay_fires():
    # iteration N+1's add_record raising replays iteration N's emit
    got = lint_source(BAD_COMMIT_REPLAY,
                      "fluentbit_tpu/plugins/filter_x.py")
    assert "batch-commit-replay" in rules(got)


def test_guarded_emit_loop_quiet():
    src = BAD_COMMIT_REPLAY.replace(
        "            self.emitter.add_record(tag, payload, 1)",
        "            try:\n"
        "                self.emitter.add_record(tag, payload, 1)\n"
        "            except Exception:\n"
        "                log.exception('append failed')")
    assert lint_source(src, "fluentbit_tpu/plugins/filter_x.py") == []


def test_stateful_unmarked_fires():
    src = BAD_COMMIT_REPLAY.replace("    stateful_batch = True\n", "")
    got = lint_source(src, "fluentbit_tpu/plugins/filter_x.py")
    assert "batch-stateful-unmarked" in rules(got)


def test_no_fallback_fires_only_with_can_process_batch():
    src = """
class F:
    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        return (chunk.n, chunk.data, chunk.n)
"""
    got = lint_source(src, "fluentbit_tpu/plugins/filter_x.py")
    assert rules(got) == ["batch-no-fallback"]
    # without the advertisement the hook is inert: no contract to break
    src2 = src.replace("    def can_process_batch(self):\n"
                       "        return True\n\n", "")
    assert lint_source(src2, "fluentbit_tpu/plugins/filter_x.py") == []


def test_unordered_emit_fires_and_sorted_groups_quiet():
    bad = """
class F:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        if chunk.n is None:
            return None
        for tag in set(chunk.tags):
            try:
                self.emitter.add_record(tag, b"", 1)
            except Exception:
                log.exception("x")
        return (chunk.n, chunk.data, chunk.n)
"""
    got = lint_source(bad, "fluentbit_tpu/plugins/filter_x.py")
    assert "batch-unordered-emit" in rules(got)
    good = bad.replace(
        "set(chunk.tags)",
        "sorted(groups.items(), key=lambda kv: kv[1]['first'])")
    assert lint_source(good, "fluentbit_tpu/plugins/filter_x.py") == []
    # output-buffer concatenation over a set is flagged too...
    concat = """
class F:
    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        if chunk.n is None:
            return None
        out = bytearray()
        for tag in set(chunk.tags):
            out += chunk.spans[tag]
        return (chunk.n, bytes(out), chunk.n)
"""
    got = lint_source(concat, "fluentbit_tpu/plugins/filter_x.py")
    assert "batch-unordered-emit" in rules(got)
    # ...but an order-INDEPENDENT reduction over a set is not
    reduction = concat.replace(
        "        out = bytearray()\n", "        total = 0\n").replace(
        "            out += chunk.spans[tag]",
        "            total += chunk.counts[tag]").replace(
        "        return (chunk.n, bytes(out), chunk.n)",
        "        return (chunk.n, chunk.data, chunk.n)")
    assert lint_source(reduction,
                       "fluentbit_tpu/plugins/filter_x.py") == []


def test_batch_rule_suppression():
    src = BAD_DECLINE_AFTER_COMMIT.replace(
        "            return None",
        "            return None  "
        "# fbtpu-lint: allow(batch-decline-after-commit)")
    assert lint_source(src, "fluentbit_tpu/plugins/filter_x.py") == []


# ---------------------------------------------------------------------
# decline-swallow
# ---------------------------------------------------------------------

BAD_DECLINE_SWALLOW = """
class F:
    def init(self):
        try:
            self._tables = build()
        except Exception:
            self._tables = None
"""


def test_decline_swallow_fires_on_data_path():
    got = lint_source(BAD_DECLINE_SWALLOW,
                      "fluentbit_tpu/plugins/filter_x.py")
    assert rules(got) == ["decline-swallow"]
    assert got[0].severity == "warning"


def test_decline_swallow_quiet_when_logged_or_narrow():
    logged = BAD_DECLINE_SWALLOW.replace(
        "            self._tables = None",
        "            log.warning('fast path disabled', exc_info=True)\n"
        "            self._tables = None")
    assert lint_source(logged, "fluentbit_tpu/plugins/filter_x.py") == []
    narrow = BAD_DECLINE_SWALLOW.replace("except Exception:",
                                         "except ValueError:")
    assert lint_source(narrow, "fluentbit_tpu/plugins/filter_x.py") == []


def test_decline_swallow_off_data_path_quiet():
    assert lint_source(BAD_DECLINE_SWALLOW,
                       "fluentbit_tpu/luart/interp.py") == []


def test_decline_swallow_does_not_double_report_pass_bodies():
    # pass-only bodies stay swallowed-error territory
    got = lint_source(BAD_SWALLOW, "fluentbit_tpu/plugins/out_x.py")
    assert rules(got) == ["swallowed-error"]


# ---------------------------------------------------------------------
# await-no-deadline (flush-path I/O deadlines)
# ---------------------------------------------------------------------

BAD_NO_DEADLINE = """
class FooOutput(OutputPlugin):
    async def _connect(self):
        self._reader, self._writer = await open_connection(
            self.instance, self.host, self.port)

    async def flush(self, data, tag, engine):
        self._writer.write(data)
        await self._writer.drain()
        return FlushResult.OK
"""

GOOD_DEADLINE = """
class FooOutput(OutputPlugin):
    async def _connect(self):
        self._reader, self._writer = await open_connection(
            self.instance, self.host, self.port, timeout=10)

    async def flush(self, data, tag, engine):
        self._writer.write(data)
        await io_deadline(self._writer.drain())
        line = await asyncio.wait_for(self._reader.readline(), 5.0)
        return FlushResult.OK
"""


def test_await_no_deadline_fires_on_raw_flush_io():
    got = lint_source(BAD_NO_DEADLINE, "fluentbit_tpu/plugins/out_x.py")
    assert rules(got) == ["await-no-deadline"]
    assert len(got) == 2  # unbounded dial + raw drain
    assert all(f.severity == "warning" for f in got)
    assert "task-map slot" in got[1].message


def test_await_no_deadline_quiet_when_wrapped():
    assert lint_source(GOOD_DEADLINE,
                       "fluentbit_tpu/plugins/out_x.py") == []


def test_await_no_deadline_scope_and_suppression():
    # off the data path → quiet
    assert lint_source(BAD_NO_DEADLINE,
                       "fluentbit_tpu/luart/interp.py") == []
    # a non-output class's reader loop → out of scope (functions NAMED
    # flush/_flush* stay in scope wherever they live)
    reader = BAD_NO_DEADLINE.replace(
        "class FooOutput(OutputPlugin):", "class FooReader:").replace(
        "async def flush(self, data, tag, engine):",
        "async def serve(self, data, tag, engine):")
    assert lint_source(reader, "fluentbit_tpu/plugins/in_x.py") == []
    # a justified unbounded await (long-poll reader) → suppressible
    src = BAD_NO_DEADLINE.replace(
        "        await self._writer.drain()",
        "        # server-push loop: unbounded by design\n"
        "        await self._writer.drain()"
        "  # fbtpu-lint: allow(await-no-deadline)")
    got = lint_source(src, "fluentbit_tpu/plugins/out_x.py")
    assert [f.rule for f in got] == ["await-no-deadline"]  # dial only


def test_await_no_deadline_module_level_flush_helpers():
    src = """
async def _flush_stream(writer, data):
    writer.write(data)
    await writer.drain()
"""
    got = lint_source(src, "fluentbit_tpu/plugins/out_y.py")
    assert rules(got) == ["await-no-deadline"]


# ---------------------------------------------------------------------
# dtype-narrowing
# ---------------------------------------------------------------------

def test_dtype_narrowing_fires_on_offsets():
    src = """
import numpy as np

def pack(offsets, lens):
    a = np.asarray(offsets, dtype=np.int32)
    b = offsets.astype(np.int32)
    c = np.cumsum(lens, dtype=np.int32)
    return a, b, c
"""
    got = lint_source(src, "fluentbit_tpu/plugins/filter_x.py")
    assert rules(got) == ["dtype-narrowing"] and len(got) == 3


def test_dtype_narrowing_quiet_on_bounded_values():
    src = """
import numpy as np

def pack(offsets, verdict, class_map):
    a = np.asarray(offsets, dtype=np.int64)   # wide is fine
    b = class_map.astype(np.int32)            # bounded domain
    c = verdict.astype(np.uint8)              # not offset-flavored
    return a, b, c
"""
    assert lint_source(src, "fluentbit_tpu/plugins/filter_x.py") == []


def test_dtype_narrowing_suppression():
    src = """
import numpy as np

def pack(offsets):
    # fbtpu-lint: allow(dtype-narrowing)
    return np.asarray(offsets, dtype=np.int32)
"""
    assert lint_source(src, "fluentbit_tpu/plugins/filter_x.py") == []


# ---------------------------------------------------------------------
# severity + JSON plumbing
# ---------------------------------------------------------------------

def test_findings_carry_severity_and_json_mode(tmp_path):
    bad = tmp_path / "fluentbit_tpu" / "plugins"
    bad.mkdir(parents=True)
    (bad / "x.py").write_text(BAD_DECLINE_SWALLOW)
    proc = subprocess.run(
        [sys.executable, "-m", "fluentbit_tpu.analysis", "--json",
         str(bad)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    import json as _json

    data = _json.loads(proc.stdout)
    assert data and data[0]["rule"] == "decline-swallow"
    assert data[0]["severity"] == "warning"


# ---------------------------------------------------------------------
# batch-exactness: the fbtpu-flux commit surface (absorb_batch /
# absorb_events are state commits — a decline after them makes the
# decoded rerun double-aggregate the same records)
# ---------------------------------------------------------------------

BAD_FLUX_DECLINE_AFTER_ABSORB = """
class FluxLike:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        data = chunk.as_bytes()
        self.state.absorb_batch(chunk.n, self.mm, {}, {})
        cols = stage(data)
        if cols is None:
            return None
        return (chunk.n, data, chunk.n)
"""

GOOD_FLUX_COMMIT_LAST = """
class FluxLike:
    stateful_batch = True

    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        data = chunk.as_bytes()
        cols = stage(data)
        if cols is None:
            return None
        self.state.absorb_batch(chunk.n, self.mm, cols, {})
        return (chunk.n, data, chunk.n)
"""

BAD_FLUX_UNMARKED_STATEFUL = """
class FluxLike:
    def can_process_batch(self):
        return True

    def process_batch(self, chunk):
        cols = stage(chunk.as_bytes())
        if cols is None:
            return None
        self.state.absorb_events(cols)
        return (chunk.n, chunk.data, chunk.n)
"""


def test_flux_absorb_is_a_commit():
    got = lint_source(BAD_FLUX_DECLINE_AFTER_ABSORB,
                      "fluentbit_tpu/flux/fixture.py")
    assert "batch-decline-after-commit" in rules(got)


def test_flux_commit_last_quiet():
    assert lint_source(GOOD_FLUX_COMMIT_LAST,
                       "fluentbit_tpu/flux/fixture.py") == []


def test_flux_unmarked_stateful_fires():
    got = lint_source(BAD_FLUX_UNMARKED_STATEFUL,
                      "fluentbit_tpu/flux/fixture.py")
    assert "batch-stateful-unmarked" in rules(got)


def test_shipped_flux_plugin_passes_the_gate():
    # the real filter_flux must satisfy its own contract
    import fluentbit_tpu.flux.plugin as fp

    assert lint_paths([fp.__file__]) == []


# ---------------------------------------------------------------------
# qos-unmetered-ingest (fbtpu-qos metered-ingest invariant)
# ---------------------------------------------------------------------

_QOS_PATH = "fluentbit_tpu/core/ingest_fixture.py"

BAD_UNMETERED = """
class Engine:
    def ingest_fast(self, ins, tag, data):
        with ins.ingest_lock:
            return ins.pool.append(tag, data, 1)
"""

GOOD_METERED = """
class Engine:
    def ingest_fast(self, ins, tag, data):
        if self.qos.admit(ins, len(data)):
            return -1
        with ins.ingest_lock:
            return ins.pool.append(tag, data, 1)
"""


def test_unmetered_ingest_fires():
    got = lint_source(BAD_UNMETERED, _QOS_PATH)
    assert "qos-unmetered-ingest" in rules(got)


def test_metered_ingest_quiet():
    assert lint_source(GOOD_METERED, _QOS_PATH) == []


BAD_UNMETERED_INTERPROC = """
class Engine:
    def ingest_fast(self, ins, tag, data):
        return self._write(ins, tag, data)

    def _write(self, ins, tag, data):
        with ins.ingest_lock:
            return ins.pool.append(tag, data, 1)
"""

GOOD_METERED_INTERPROC = """
class Engine:
    def ingest_fast(self, ins, tag, data):
        if self.qos.admit(ins, len(data)):
            return -1
        return self._write(ins, tag, data)

    def _write(self, ins, tag, data):
        with ins.ingest_lock:
            return ins.pool.append(tag, data, 1)
"""


def test_unmetered_ingest_interprocedural():
    got = lint_source(BAD_UNMETERED_INTERPROC, _QOS_PATH)
    assert [f.rule for f in got] == ["qos-unmetered-ingest"]
    # the finding lands on the PUBLIC entry point, not the helper
    assert got[0].line == 3
    assert lint_source(GOOD_METERED_INTERPROC, _QOS_PATH) == []


def test_unmetered_ingest_private_only_quiet():
    # a private helper with no public caller is reachable only through
    # an admitted entry point in some other module — not flagged here
    helper_only = """
class Engine:
    def _write(self, ins, tag, data):
        with ins.ingest_lock:
            return ins.pool.append(tag, data, 1)
"""
    assert lint_source(helper_only, _QOS_PATH) == []


def test_unmetered_ingest_scope_and_suppression():
    # plugins ingest through Engine.input_*_append (already metered):
    # out of scope
    assert lint_source(BAD_UNMETERED,
                       "fluentbit_tpu/plugins/fixture.py") == []
    suppressed = BAD_UNMETERED.replace(
        "def ingest_fast(self, ins, tag, data):",
        "def ingest_fast(self, ins, tag, data):  "
        "# fbtpu-lint: allow(qos-unmetered-ingest) replay path, "
        "admitted at first ingest")
    assert lint_source(suppressed, _QOS_PATH) == []


def test_shipped_engine_ingest_is_metered():
    # the real entry points must keep calling qos.admit — deleting the
    # admission from input_log_append would fail THIS, not just the
    # behavior suite
    import fluentbit_tpu.core.engine as eng

    assert "qos-unmetered-ingest" not in rules(lint_paths([eng.__file__]))


NESTED_CLOSURE_METERED = """
class Engine:
    def ingest_batched(self, ins, tag, data):
        if self.qos.admit(ins, len(data)):
            return -1
        def flush(chunk):
            return ins.pool.append(tag, data, 1)
        return flush(data)
"""


def test_qos_rule_ignores_nested_closures():
    """A non-underscore closure inside a metered public function must
    not be flagged as its own unmetered entry point — the admit call
    lives in its container."""
    got = lint_source(NESTED_CLOSURE_METERED, _QOS_PATH)
    assert "qos-unmetered-ingest" not in rules(got), [
        f.message for f in got]


# ---------------------------------------------------------------------
# device-unguarded-dispatch (fbtpu-armor DeviceLane invariant)
# ---------------------------------------------------------------------

_DEV_PATH = "fluentbit_tpu/plugins/filter_fixture.py"

BAD_UNGUARDED_DISPATCH = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        mask = self._program.dispatch_mesh(self._mesh, data, n_records)
        return mask
"""

GOOD_GUARDED_DISPATCH = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        return lane.run(
            lambda: self._program.dispatch_mesh(self._mesh, data,
                                                n_records),
            lambda: self._host_mask(data, n_records),
        )
"""


def test_unguarded_dispatch_fires():
    got = lint_source(BAD_UNGUARDED_DISPATCH, _DEV_PATH)
    assert "device-unguarded-dispatch" in rules(got)


def test_guarded_dispatch_quiet():
    assert "device-unguarded-dispatch" not in rules(
        lint_source(GOOD_GUARDED_DISPATCH, _DEV_PATH))


BAD_UNGUARDED_INTERPROC = """
class F:
    def filter(self, events, tag, engine):
        return self._match(events)

    def _match(self, events):
        return self._program.match(self._batch, self._lengths)
"""

GOOD_GUARDED_INTERPROC = """
class F:
    def filter(self, events, tag, engine):
        return self._match(events)

    def _match(self, events):
        lane = self._lane()
        return lane.run(
            lambda: self._program.match(self._batch, self._lengths),
            lambda: self._host(events),
        )
"""


def test_unguarded_dispatch_interprocedural():
    got = lint_source(BAD_UNGUARDED_INTERPROC, _DEV_PATH)
    assert [f.rule for f in got] == ["device-unguarded-dispatch"]
    # the finding lands on the PUBLIC entry point, not the helper
    assert got[0].line == 3
    assert lint_source(GOOD_GUARDED_INTERPROC, _DEV_PATH) == []


def test_unguarded_dispatch_sharded_sketch_names():
    bad = """
def absorb(state, batch, lengths):
    sharded_hll_update(state.hll, state.mesh, batch, lengths)
"""
    got = lint_source(bad, "fluentbit_tpu/flux/fixture.py")
    assert "device-unguarded-dispatch" in rules(got)
    guarded = """
def absorb(lane, state, batch, lengths):
    return lane.run(
        lambda: sharded_hll_update(state.hll, state.mesh, batch,
                                   lengths),
        lambda: state.hll.host_update(batch, lengths),
    )
"""
    assert lint_source(guarded, "fluentbit_tpu/flux/fixture.py") == []


def test_unguarded_dispatch_scope_and_suppression():
    # ops/ is the kernel layer the lanes wrap: out of scope
    assert lint_source(BAD_UNGUARDED_DISPATCH,
                       "fluentbit_tpu/ops/fixture.py") == []
    suppressed = BAD_UNGUARDED_DISPATCH.replace(
        "def filter_raw(self, data, tag, engine, n_records=None):",
        "def filter_raw(self, data, tag, engine, n_records=None):  "
        "# fbtpu-lint: allow(device-unguarded-dispatch) bench-only "
        "diagnostic path, raw failure wanted")
    # (the launch-graph pack's structural undonated-buffer warning on
    # the bare dispatch_mesh site is a different rule and stays)
    assert "device-unguarded-dispatch" not in rules(
        lint_source(suppressed, _DEV_PATH))


def test_unguarded_dispatch_plain_match_needs_program_chain():
    # .match( on a non-program chain (a regex, a dict) is not a device
    # dispatch — the rule must not fire on everyday string matching
    benign = """
class F:
    def filter(self, events, tag, engine):
        return [e for e in events if self.regex.match(e.body)]
"""
    assert lint_source(benign, _DEV_PATH) == []


def test_shipped_device_planes_are_lane_guarded():
    # the real grep/rewrite_tag/flux device paths must keep their lane
    # wrapping — stripping DeviceLane from filter_grep would fail THIS,
    # not just the chaos suite
    import fluentbit_tpu.flux.kernels as fk
    import fluentbit_tpu.flux.state as fs
    import fluentbit_tpu.plugins.filter_grep as fg
    import fluentbit_tpu.plugins.filter_rewrite_tag as frt

    for mod in (fg, frt, fs, fk):
        assert "device-unguarded-dispatch" not in rules(
            lint_paths([mod.__file__])), mod.__name__


# ---------------------------------------------------------------------
# grep-unminimized-dfa (fbtpu-shrink minimizer invariant)
# ---------------------------------------------------------------------

_SHRINK_PATH = "fluentbit_tpu/plugins/filter_fixture.py"

BAD_RAW_DFA_TO_TABLES = """
import numpy as np


class F:
    def init(self, instance, engine):
        dfa = DFA(trans=np.zeros((2, 2), np.int32),
                  class_map=np.zeros(257, np.uint8),
                  start=0, n_states=2, n_classes=2, pattern="x")
        self._tables = GrepTables([(b"log", dfa)])
"""

BAD_UNMINIMIZED_COMPILE = """
class F:
    def init(self, instance, engine):
        self._program = GrepProgram(
            [compile_dfa(p, minimize=False) for p in self.patterns], 512)
"""

GOOD_MINIMIZED_COMPILE = """
class F:
    def init(self, instance, engine):
        self._program = GrepProgram(
            [compile_dfa(p) for p in self.patterns], 512)
        self._tables = GrepTables(
            [(b"log", compile_dfa(p)) for p in self.patterns])
"""


def test_unminimized_dfa_raw_construction_fires():
    got = lint_source(BAD_RAW_DFA_TO_TABLES, _SHRINK_PATH)
    assert "grep-unminimized-dfa" in rules(got)


def test_unminimized_dfa_minimize_false_fires():
    got = lint_source(BAD_UNMINIMIZED_COMPILE, _SHRINK_PATH)
    assert "grep-unminimized-dfa" in rules(got)


def test_minimized_compile_quiet():
    assert "grep-unminimized-dfa" not in rules(
        lint_source(GOOD_MINIMIZED_COMPILE, _SHRINK_PATH))


def test_unminimized_dfa_interprocedural():
    # the source hides in a same-module helper; the sink lives in the
    # caller — the closure still connects them
    bad = """
class F:
    def init(self, instance, engine):
        self._tables = GrepTables(self._rules())

    def _rules(self):
        return [(b"log", compile_dfa("x", minimize=False))]
"""
    got = lint_source(bad, _SHRINK_PATH)
    assert "grep-unminimized-dfa" in rules(got)


def test_unminimized_dfa_scope_and_suppression():
    # regex/ is the definition site (the minimizer builds raw tables)
    assert lint_source(BAD_RAW_DFA_TO_TABLES,
                       "fluentbit_tpu/regex/fixture.py") == []
    suppressed = BAD_UNMINIMIZED_COMPILE.replace(
        "[compile_dfa(p, minimize=False) for p in self.patterns], 512)",
        "[compile_dfa(p, minimize=False)  "
        "# fbtpu-lint: allow(grep-unminimized-dfa) differential\n"
        "             for p in self.patterns], 512)")
    assert "grep-unminimized-dfa" not in rules(
        lint_source(suppressed, _SHRINK_PATH))


def test_unminimized_dfa_source_without_sink_quiet():
    # compiling an unminimized DFA for a NON-kernel purpose (a property
    # test oracle, a doc example) is not the bug class
    benign = """
def oracle(pattern):
    return compile_dfa(pattern, minimize=False)
"""
    assert "grep-unminimized-dfa" not in rules(
        lint_source(benign, _SHRINK_PATH))


def test_shipped_kernel_paths_use_minimized_dfas():
    # the real program/table builders must stay on the compile_dfa
    # default path — wiring minimize=False into filter_grep would fail
    # THIS, not just a bench round three PRs later
    import fluentbit_tpu.ops.grep as og
    import fluentbit_tpu.plugins.filter_grep as fg
    import fluentbit_tpu.plugins.filter_parser as fp

    for mod in (og, fg, fp):
        assert "grep-unminimized-dfa" not in rules(
            lint_paths([mod.__file__])), mod.__name__
