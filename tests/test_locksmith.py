"""fbtpu-locksmith: the interprocedural lock-order & lockset analyzer
(analysis/locksmith.py) — red/green fixtures per rule, shipped-tree
graph pins, baseline round-trip, and the static ⊇ dynamic witness
crosscheck that keeps the model honest (core/lockorder.py).

Fixture paths live OUTSIDE the package scopes ("fixtures/mod.py") so
the scope gate analyzes them as test snippets; registry-dependent
rules get a purpose-built GuardEntry tuple for that path.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from fluentbit_tpu.analysis import lint_source
from fluentbit_tpu.analysis.locksmith import (
    build_lock_graph, graph_cycle_findings, static_order_edges)
from fluentbit_tpu.analysis.registry import GuardEntry, lock_baseline_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "fluentbit_tpu")
FIX = "fixtures/mod.py"


def rules(findings):
    return sorted({f.rule for f in findings})


def smith(findings):
    from fluentbit_tpu.analysis.locksmith import LocksmithRules
    names = set(LocksmithRules.RULE_NAMES)
    return [f for f in findings if f.rule in names]


# ---------------------------------------------------------------------
# lock-order-cycle: interprocedural acquisition-order inversions
# ---------------------------------------------------------------------

CYCLE_BAD = """
class Foo:
    def alpha(self):
        with self._lock_a:
            self._helper()

    def _helper(self):
        with self._lock_b:
            pass

    def beta(self):
        with self._lock_b:
            with self._lock_a:
                pass
"""

CYCLE_GOOD = """
class Foo:
    def alpha(self):
        with self._lock_a:
            self._helper()

    def _helper(self):
        with self._lock_b:
            pass

    def beta(self):
        with self._lock_a:
            with self._lock_b:
                pass
"""


def test_lock_order_cycle_interprocedural():
    got = smith(lint_source(CYCLE_BAD, FIX))
    assert rules(got) == ["lock-order-cycle"]
    # the witness path names both sides of the inversion
    assert "Foo._lock_a" in got[0].message
    assert "Foo._lock_b" in got[0].message
    assert smith(lint_source(CYCLE_GOOD, FIX)) == []


# the PR-15 shipped-tree inversion, reduced: the raw append path held
# the input's lock while its decline continuation re-entered the
# decode path's global lock; the collector tick nests the opposite way
INVERSION_BAD = """
class Engine:
    def input_log_append(self, ins, data):
        with ins.ingest_lock:
            got = self._ingest_raw(ins, data)
        return got

    def _ingest_raw(self, ins, data):
        if data is None:
            return self._raw_tail(data)
        return 1

    def _raw_tail(self, data):
        with self._ingest_lock:
            return 0

    def _tick(self, ins):
        with self._ingest_lock:
            with ins.ingest_lock:
                pass
"""

INVERSION_GOOD = """
class Engine:
    def input_log_append(self, ins, data):
        with ins.ingest_lock:
            got = self._ingest_raw(ins, data)
        if got is None:
            got = self._raw_tail(data)
        return got

    def _ingest_raw(self, ins, data):
        if data is None:
            return None
        return 1

    def _raw_tail(self, data):
        with self._ingest_lock:
            return 0

    def _tick(self, ins):
        with self._ingest_lock:
            with ins.ingest_lock:
                pass
"""


def test_raw_path_inversion_regression():
    """Red on the pre-fix engine shape (ingest_lock held across the
    tail continuation), green on the continuation-after-release
    restructure the PR ships."""
    got = smith(lint_source(INVERSION_BAD, FIX))
    assert "lock-order-cycle" in rules(got)
    assert any("InputInstance.ingest_lock" in f.message
               and "Engine._ingest_lock" in f.message for f in got)
    assert smith(lint_source(INVERSION_GOOD, FIX)) == []


SELF_DEADLOCK = """
class Qos:
    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""


def test_self_deadlock_on_nonreentrant_lock():
    got = smith(lint_source(SELF_DEADLOCK, FIX))
    assert rules(got) == ["lock-order-cycle"]
    assert "Qos._lock" in got[0].message


def test_reentrant_reacquire_is_clean():
    # Engine._ingest_lock is in the analyzer's REENTRANT set
    src = SELF_DEADLOCK.replace("Qos", "Engine").replace(
        "_lock", "_ingest_lock")
    assert smith(lint_source(src, FIX)) == []


# ---------------------------------------------------------------------
# guarded-field-unlocked: writes_only registry entries, in-place
# mutation IS a write
# ---------------------------------------------------------------------

FIELD_GUARDS = (GuardEntry(FIX, "_lock", ("_items",), writes_only=True),)

FIELD_BAD = """
class Foo:
    def probe(self):
        return len(self._items)

    def bad(self, x):
        self._items.append(x)
"""

FIELD_GOOD = """
class Foo:
    def probe(self):
        return len(self._items)

    def good(self, x):
        with self._lock:
            self._items.append(x)
"""

FIELD_ALLOWED = """
class Foo:
    def bad(self, x):
        # fbtpu-lint: allow(guarded-field-unlocked) test justification
        self._items.append(x)
"""


def test_guarded_field_unlocked_red_green():
    got = smith(lint_source(FIELD_BAD, FIX, FIELD_GUARDS))
    assert rules(got) == ["guarded-field-unlocked"]
    assert smith(lint_source(FIELD_GOOD, FIX, FIELD_GUARDS)) == []
    assert smith(lint_source(FIELD_ALLOWED, FIX, FIELD_GUARDS)) == []


# ---------------------------------------------------------------------
# guarded-by-missing: the Eraser-style lockset arm (attrs) and the
# module-global arm
# ---------------------------------------------------------------------

ERASER_BAD = """
class Foo:
    def __init__(self):
        self._curr = 0

    def bump(self):
        with self._lock:
            self._curr += 1

    def reset(self):
        self._curr = 0
"""

ERASER_GOOD = """
class Foo:
    def __init__(self):
        self._curr = 0

    def bump(self):
        with self._lock:
            self._curr += 1

    def reset(self):
        with self._lock:
            self._curr = 0
"""

# interprocedural: the unlocked-looking helper is ONLY called with the
# lock already held — must-hold propagation keeps it quiet
ERASER_HELPER_GOOD = """
class Foo:
    def bump(self):
        with self._lock:
            self._bump_locked()

    def shrink(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self._curr += 1
"""


def test_eraser_lockset_red_green():
    got = smith(lint_source(ERASER_BAD, FIX))
    assert rules(got) == ["guarded-by-missing"]
    assert "_curr" in got[0].message
    assert smith(lint_source(ERASER_GOOD, FIX)) == []


def test_eraser_must_hold_interprocedural():
    assert smith(lint_source(ERASER_HELPER_GOOD, FIX)) == []


GLOBAL_BAD = """
import threading

_lock = threading.Lock()
_cache = {}


def put(k, v):
    _cache[k] = v


def get(k):
    with _lock:
        return _cache.get(k)
"""

GLOBAL_GOOD = """
import threading

_lock = threading.Lock()
_cache = {}


def put(k, v):
    with _lock:
        _cache[k] = v


def get(k):
    with _lock:
        return _cache.get(k)
"""

GLOBAL_LOCALS_ONLY = """
import threading

_lock = threading.Lock()


def tally(xs):
    counts = {}
    for x in xs:
        counts[x] = counts.get(x, 0) + 1
    with _lock:
        return len(counts)
"""


GLOBAL_GUARDS = (GuardEntry(FIX, "_lock", ("_cache",),
                            writes_only=True, kind="global"),)


def test_global_lockset_red_green():
    # unregistered global mutated in a lock-owning module: the module
    # owes the registry an entry — even if this mutation site happens
    # to hold the lock, nothing binds future call paths to it
    got = smith(lint_source(GLOBAL_BAD, FIX))
    assert rules(got) == ["guarded-by-missing"]
    assert "_cache" in got[0].message
    # registered + mutated under the registered lock: clean
    assert smith(lint_source(GLOBAL_GOOD, FIX, GLOBAL_GUARDS)) == []
    # registered + mutated OFF the lock: the lockset rule takes over
    got = smith(lint_source(GLOBAL_BAD, FIX, GLOBAL_GUARDS))
    assert rules(got) == ["guarded-field-unlocked"]


def test_global_arm_ignores_locals():
    # a local dict mutated inside a module that owns a lock is not a
    # shared-state violation (the shadowing gate)
    assert smith(lint_source(GLOBAL_LOCALS_ONLY, FIX)) == []


# ---------------------------------------------------------------------
# atomicity-check-then-act
# ---------------------------------------------------------------------

ATOM_GUARDS = (GuardEntry(FIX, "_lock", ("_state",)),)

ATOM_BAD = """
class Foo:
    def flip(self):
        with self._lock:
            cur = self._state
        with self._lock:
            self._state = cur + 1
"""

ATOM_DOUBLE_CHECK = """
class Foo:
    def flip(self):
        with self._lock:
            cur = self._state
        new = cur + 1
        with self._lock:
            if self._state == cur:
                self._state = new
"""

ATOM_BRANCHES = """
class Foo:
    def flip(self, fast):
        with self._lock:
            cur = self._state
        if fast:
            return cur
        with self._lock:
            self._state = cur + 1
            return cur
"""


def test_atomicity_red():
    got = smith(lint_source(ATOM_BAD, FIX, ATOM_GUARDS))
    assert rules(got) == ["atomicity-check-then-act"]


def test_atomicity_validated_double_check_is_green():
    # the act re-reads guarded state under the re-acquired lock (the
    # ops/fault.py current_mesh shape): a correct double-check
    assert smith(lint_source(ATOM_DOUBLE_CHECK, FIX, ATOM_GUARDS)) == []


def test_atomicity_alternative_branches_are_green():
    # a return between the two blocks means they are alternatives,
    # not a released-and-reacquired sequence
    assert smith(lint_source(ATOM_BRANCHES, FIX, ATOM_GUARDS)) == []


# ---------------------------------------------------------------------
# lock-held-across-dispatch
# ---------------------------------------------------------------------

DISPATCH_BAD = """
class Engine:
    def flush(self, lane, fn, batch):
        with self._ingest_lock:
            lane.run(fn, batch)
"""

DISPATCH_BAD_INTERPROC = """
class Engine:
    def flush(self, lane, fn, batch):
        with self._ingest_lock:
            self._go(lane, fn, batch)

    def _go(self, lane, fn, batch):
        lane.run(fn, batch)
"""

DISPATCH_GOOD = """
class Engine:
    def flush(self, lane, fn, batch):
        with self._ingest_lock:
            staged = list(batch)
        lane.run(fn, staged)
"""


def test_dispatch_under_ingest_lock():
    got = smith(lint_source(DISPATCH_BAD, FIX))
    assert rules(got) == ["lock-held-across-dispatch"]
    got = smith(lint_source(DISPATCH_BAD_INTERPROC, FIX))
    assert rules(got) == ["lock-held-across-dispatch"]
    assert smith(lint_source(DISPATCH_GOOD, FIX)) == []


# ---------------------------------------------------------------------
# cow-swap-aliasing
# ---------------------------------------------------------------------

COW_BAD = """
class Engine:
    def add(self, ins):
        self.filters.append(ins)
"""

COW_GOOD = """
class Engine:
    def add(self, ins):
        with self._ingest_lock:
            self.filters = self.filters + [ins]
"""

COW_OTHER_CLASS = """
class Registry:
    def register(self, name, plugin):
        self.inputs[name] = plugin
"""


def test_cow_swap_red_green():
    got = smith(lint_source(COW_BAD, FIX))
    assert rules(got) == ["cow-swap-aliasing"]
    assert smith(lint_source(COW_GOOD, FIX)) == []
    # a same-named dict on a NON-COW class (the plugin-type registry)
    # is not the engine's reader-snapshot contract
    assert smith(lint_source(COW_OTHER_CLASS, FIX)) == []


# ---------------------------------------------------------------------
# the shipped tree: graph pins, acyclicity, baseline round-trip
# ---------------------------------------------------------------------

def test_shipped_graph_is_acyclic_and_pinned():
    graph = build_lock_graph()
    assert graph["cycles"] == []
    assert list(graph_cycle_findings()) == []
    # the committed baseline records the same shape the live walk sees
    with open(lock_baseline_path(), "r", encoding="utf-8") as fh:
        recorded = json.load(fh)
    assert recorded["graph"]["nodes"] == len(graph["nodes"])
    assert recorded["graph"]["edges"] == len(graph["edges"])
    assert recorded["graph"]["cycles"] == 0
    # structure pins: the canonical engine-plane orderings must exist
    edges = {(e["from"], e["to"]) for e in graph["edges"]}
    assert ("Engine._ingest_lock", "InputInstance.ingest_lock") in edges
    assert ("Engine._reload_lock", "Engine._ingest_lock") in edges
    # ... and the inversions this PR fixed must NOT
    assert ("InputInstance.ingest_lock", "Engine._ingest_lock") not in edges
    assert ("Engine._ingest_lock", "Engine._reload_lock") not in edges


def test_baseline_stale_entry_detection(tmp_path, monkeypatch):
    from fluentbit_tpu.analysis.__main__ import _lock_findings

    # a pristine baseline yields nothing on a clean tree
    assert [f for f in _lock_findings([])
            if f.rule == "lock-baseline-stale"] == []
    # a baseline entry matching no live finding is flagged stale
    fake = tmp_path / "lock_baseline.json"
    with open(lock_baseline_path(), "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    payload["findings"].append({
        "path": "fluentbit_tpu/core/engine.py", "rule": "cow-swap-aliasing",
        "message": "long-fixed debt", "severity": "error"})
    fake.write_text(json.dumps(payload))
    monkeypatch.setattr(
        "fluentbit_tpu.analysis.registry.lock_baseline_path",
        lambda: str(fake))
    got = [f for f in _lock_findings([]) if f.rule == "lock-baseline-stale"]
    assert len(got) == 1 and "long-fixed debt" in got[0].message


def test_missing_baseline_is_an_error(monkeypatch, tmp_path):
    from fluentbit_tpu.analysis.__main__ import _lock_findings

    monkeypatch.setattr(
        "fluentbit_tpu.analysis.registry.lock_baseline_path",
        lambda: str(tmp_path / "nope.json"))
    got = _lock_findings([])
    assert any(f.rule == "lock-baseline-stale" and f.severity == "error"
               for f in got)


def test_graph_cli_renders():
    for mode in ("lock", "lock-dot"):
        proc = subprocess.run(
            [sys.executable, "-m", "fluentbit_tpu.analysis",
             "--graph", mode],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        if mode == "lock":
            g = json.loads(proc.stdout)
            assert g["cycles"] == [] and g["nodes"]
        else:
            assert proc.stdout.startswith("digraph lock_order")


# ---------------------------------------------------------------------
# ground truth: static ⊇ dynamic (the witness recorder)
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_static_graph_covers_witnessed_edges(monkeypatch):
    """Drive a representative workload under FBTPU_LOCK_WITNESS and
    assert every dynamically recorded acquisition edge exists in the
    static order graph. A missing edge means the analyzer's call walk
    lost a path — this test fails loudly instead of the model rotting."""
    import fluentbit_tpu as flb
    from fluentbit_tpu.core import lockorder

    monkeypatch.setenv("FBTPU_LOCK_WITNESS", "1")
    lockorder.witness_reset()

    ctx = flb.create(flush="40ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("grep", match="t", regex="log keep")
    got = []
    ctx.output("lib", match="t", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        for k in range(12):
            ctx.push(in_ffd, json.dumps(
                [k, {"log": f"keep-{k}", "k": k}]))
        ctx.flush_now()
        # a reload commit exercises _reload_lock → _ingest_lock →
        # per-input locks
        txn = ctx.engine.reload_txn()
        txn.replace_filter("grep.0")
        assert txn.commit() == 1
        for k in range(12, 18):
            ctx.push(in_ffd, json.dumps(
                [k, {"log": f"keep-{k}", "k": k}]))
        ctx.flush_now()
        deadline = time.time() + 8.0
        while not got and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx.stop()

    dynamic = set(lockorder.witness_edges())
    assert dynamic, "witness recorded nothing — recorder not engaged?"
    static = set(static_order_edges())
    missing = dynamic - static
    assert not missing, (
        f"dynamic edges missing from the static order graph: "
        f"{sorted(missing)}")
    # and the static graph itself stays acyclic (cheap re-assert here
    # so THIS test's failure output carries both halves of the story)
    assert build_lock_graph()["cycles"] == []
