"""wasmrt interpreter + filter_wasm tests.

Modules are hand-assembled by an independent binary encoder below (the
spec's binary grammar), so interpreter bugs can't self-confirm.
Filter scenarios mirror the reference filter_wasm contract
(plugins/filter_wasm/filter_wasm.c: replace / drop / trap-keeps)."""

import json
import struct

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.wasmrt import Module, Trap, WasmError

# ------------------------------------------------- binary assembler


def leb(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def sleb(n):
    out = bytearray()
    more = True
    while more:
        b = n & 0x7F
        n >>= 7
        if (n == 0 and not b & 0x40) or (n == -1 and b & 0x40):
            more = False
        else:
            b |= 0x80
        out.append(b)
    return bytes(out)


def sec(sid, body):
    return bytes([sid]) + leb(len(body)) + body


def vec(items):
    return leb(len(items)) + b"".join(items)


I32 = 0x7F


def functype(params, results):
    return b"\x60" + vec([bytes([p]) for p in params]) \
        + vec([bytes([r]) for r in results])


def module(types, funcs, exports, memory_pages=1, data=(), tables=None,
           elems=(), globals_=()):
    """funcs: [(type_idx, locals:[(count, type)], body_bytes)]"""
    out = bytearray(b"\0asm\x01\0\0\0")
    out += sec(1, vec([functype(p, r) for p, r in types]))
    out += sec(3, vec([leb(t) for t, _l, _b in funcs]))
    if tables is not None:
        out += sec(4, vec([b"\x70\x00" + leb(tables)]))
    if memory_pages:
        out += sec(5, vec([b"\x00" + leb(memory_pages)]))
    if globals_:
        out += sec(6, vec([bytes([vt, mut]) + init + b"\x0b"
                           for vt, mut, init in globals_]))
    out += sec(7, vec([leb(len(n)) + n.encode() + bytes([kind]) + leb(i)
                       for n, kind, i in exports]))
    if elems:
        out += sec(9, vec([b"\x00\x41" + sleb(off) + b"\x0b"
                           + vec([leb(f) for f in idxs])
                           for off, idxs in elems]))
    bodies = []
    for _t, locals_, body in funcs:
        lb = vec([leb(c) + bytes([vt]) for c, vt in locals_]) + body \
            + b"\x0b"
        bodies.append(leb(len(lb)) + lb)
    out += sec(10, vec(bodies))
    if data:
        out += sec(11, vec([b"\x00\x41" + sleb(off) + b"\x0b"
                            + leb(len(d)) + d for off, d in data]))
    return bytes(out)


# opcodes used below
LOCAL_GET, LOCAL_SET = b"\x20", b"\x21"
I32_CONST = b"\x41"
I32_ADD, I32_SUB, I32_MUL = b"\x6a", b"\x6b", b"\x6c"
I32_EQ, I32_LT_S, I32_GE_U, I32_EQZ = b"\x46", b"\x48", b"\x4f", b"\x45"
CALL = b"\x10"
IF_I32, IF_VOID, ELSE, END = b"\x04\x7f", b"\x04\x40", b"\x05", b"\x0b"
BLOCK_VOID, LOOP_VOID = b"\x02\x40", b"\x03\x40"
BR, BR_IF, RETURN = b"\x0c", b"\x0d", b"\x0f"
I32_LOAD8_U = b"\x2d\x00\x00"  # align=0 offset=0
I32_STORE8 = b"\x3a\x00\x00"


def l(i):
    return LOCAL_GET + leb(i)


# ------------------------------------------------------ interpreter


def test_add_function():
    m = Module(module(
        [([I32, I32], [I32])],
        [(0, [], l(0) + l(1) + I32_ADD)],
        [("add", 0, 0)], memory_pages=0))
    assert m.call("add", [2, 3]) == [5]
    assert m.call("add", [0xFFFFFFFF, 1]) == [0]  # i32 wraps


def test_factorial_recursion():
    # fac(n) = n<1 ? 1 : n*fac(n-1)
    body = (l(0) + I32_CONST + sleb(1) + I32_LT_S
            + IF_I32 + I32_CONST + sleb(1)
            + ELSE + l(0) + l(0) + I32_CONST + sleb(1) + I32_SUB
            + CALL + leb(0) + I32_MUL + END)
    m = Module(module([([I32], [I32])], [(0, [], body)],
                      [("fac", 0, 0)], memory_pages=0))
    assert m.call("fac", [10]) == [3628800]


def test_loop_sum():
    # sum 1..n with a loop: local1 = acc
    body = (
        BLOCK_VOID
        + LOOP_VOID
        + l(0) + I32_EQZ + BR_IF + leb(1)          # exit when n == 0
        + l(1) + l(0) + I32_ADD + LOCAL_SET + leb(1)
        + l(0) + I32_CONST + sleb(1) + I32_SUB + LOCAL_SET + leb(0)
        + BR + leb(0)
        + END + END
        + l(1)
    )
    m = Module(module([([I32], [I32])], [(0, [(1, I32)], body)],
                      [("sum", 0, 0)], memory_pages=0))
    assert m.call("sum", [100]) == [5050]


def test_memory_and_data_segment():
    # byte_at(i) -> mem[i]; data "hi!" at offset 8
    m = Module(module(
        [([I32], [I32])],
        [(0, [], l(0) + I32_LOAD8_U)],
        [("byte_at", 0, 0), ("memory", 2, 0)],
        data=[(8, b"hi!")]))
    assert m.call("byte_at", [8]) == [ord("h")]
    assert m.call("byte_at", [10]) == [ord("!")]
    assert m.call("byte_at", [11]) == [0]


def test_store_and_trap_oob():
    # poke(addr, v): mem[addr] = v
    body = l(0) + l(1) + I32_STORE8
    m = Module(module([([I32, I32], [])], [(0, [], body)],
                      [("poke", 0, 0)]))
    m.call("poke", [5, 65])
    assert m.memory[5] == 65
    with pytest.raises(Trap):
        m.call("poke", [1 << 20, 1])  # beyond the single page


def test_globals_and_call_indirect():
    # two funcs f0()->10, f1()->20 in a table; pick(i) calls table[i]
    g_init = I32_CONST + sleb(7)
    m = Module(module(
        [([], [I32]), ([I32], [I32])],
        [(0, [], I32_CONST + sleb(10)),
         (0, [], I32_CONST + sleb(20) + b"\x23\x00" + I32_ADD),  # +g0
         (1, [], l(0) + b"\x11" + leb(0) + leb(0))],  # call_indirect
        [("pick", 0, 2)], memory_pages=0, tables=2,
        elems=[(0, [0, 1])], globals_=[(I32, 0, g_init)]))
    assert m.call("pick", [0]) == [10]
    assert m.call("pick", [1]) == [27]
    with pytest.raises(Trap):
        m.call("pick", [5])


def test_div_by_zero_traps():
    body = l(0) + l(1) + b"\x6d"  # i32.div_s
    m = Module(module([([I32, I32], [I32])], [(0, [], body)],
                      [("div", 0, 0)], memory_pages=0))
    assert m.call("div", [7, 2]) == [3]
    assert m.call("div", [(-7) & 0xFFFFFFFF, 2]) == [(-3) & 0xFFFFFFFF]
    with pytest.raises(Trap):
        m.call("div", [1, 0])


def test_imports_rejected():
    broken = bytearray(b"\0asm\x01\0\0\0")
    broken += sec(2, vec([leb(3) + b"env" + leb(1) + b"f" + b"\x00\x00"]))
    with pytest.raises(WasmError, match="import"):
        Module(bytes(broken))


# ------------------------------------------------------- filter_wasm


def filter_module():
    """The reference filter signature:
    f(tag_ptr, tag_len, sec, nsec, rec_ptr, rec_len) -> i32 (cstr ptr).

    Behavior: scan the record for the byte 'X' — found: return 0 (drop);
    else if rec_len > 60: return ptr to '{"flag":"long"}'; else echo
    the record back (rec_ptr)."""
    drop_scan = (
        # local6 = i (loop index)
        BLOCK_VOID
        + LOOP_VOID
        + l(6) + l(5) + I32_GE_U + BR_IF + leb(1)   # i >= rec_len → exit
        + l(4) + l(6) + I32_ADD + I32_LOAD8_U
        + I32_CONST + sleb(ord("X")) + I32_EQ
        + IF_VOID + I32_CONST + sleb(0) + RETURN + END
        + l(6) + I32_CONST + sleb(1) + I32_ADD + LOCAL_SET + leb(6)
        + BR + leb(0)
        + END + END
    )
    tail = (
        l(5) + I32_CONST + sleb(60) + b"\x4b"        # rec_len > 60 (gt_u)
        + IF_I32 + I32_CONST + sleb(16)              # ptr to static JSON
        + ELSE + l(4) + END
    )
    return module(
        [([I32] * 6, [I32])],
        [(0, [(1, I32)], drop_scan + tail)],
        [("go", 0, 0)],
        data=[(16, b'{"flag":"long"}\0')])


def run_wasm_filter(records, tmp_path, **props):
    path = tmp_path / "filter.wasm"
    path.write_bytes(filter_module())
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("wasm", match="t", wasm_path=str(path),
               function_name="go", **props)
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        for r in records:
            ctx.push(in_ffd, json.dumps(r))
        ctx.flush_now()
        import time
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
    finally:
        ctx.stop()
    return [e.body for d in got for e in decode_events(d)]


def test_filter_wasm_drop_replace_echo(tmp_path):
    bodies = run_wasm_filter(
        [{"msg": "contains X marker"},                   # dropped
         {"msg": "a" * 80},                              # replaced
         {"msg": "short"}],                              # echoed
        tmp_path)
    assert bodies == [{"flag": "long"}, {"msg": "short"}]


def test_filter_wasm_missing_function(tmp_path):
    path = tmp_path / "f.wasm"
    path.write_bytes(filter_module())
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("dummy", tag="t")
    ctx.filter("wasm", match="t", wasm_path=str(path),
               function_name="nope")
    ctx.output("null", match="*")
    with pytest.raises(Exception):
        ctx.start()
    ctx.stop()


def test_void_block_branch_preserves_operands():
    """br out of a VOID block must not duplicate pending operands from
    the enclosing frame (blocktype 0x40 decodes as SLEB -64)."""
    body = (l(0) + l(1)
            + BLOCK_VOID + BR + leb(0) + END
            + I32_ADD)
    m = Module(module([([I32, I32], [I32])], [(0, [], body)],
                      [("f", 0, 0)], memory_pages=0))
    assert m.call("f", [10, 20]) == [30]


def test_br_to_function_frame_is_return():
    """A br whose label is the function frame itself is a return."""
    body = I32_CONST + sleb(7) + BR + leb(0)
    m = Module(module([([], [I32])], [(0, [], body)],
                      [("f", 0, 0)], memory_pages=0))
    assert m.call("f", []) == [7]


def test_dup_data_uses_exported_malloc():
    """Modules exporting malloc get dup_data through THEIR allocator
    (WAMR's wasm_runtime_module_malloc behavior) — no collision with a
    guest-managed heap."""
    # malloc(n): bump global 0 by n, return old value; free: no-op
    g_init = I32_CONST + sleb(1024)
    malloc_body = (b"\x23\x00"            # global.get 0
                   + b"\x23\x00" + l(0) + I32_ADD
                   + b"\x24\x00")         # global.set 0
    free_body = b""
    m = Module(module(
        [([I32], [I32]), ([I32], [])],
        [(0, [], malloc_body), (1, [], free_body)],
        [("malloc", 0, 0), ("free", 0, 1)],
        globals_=[(I32, 1, g_init)]))
    p1 = m.dup_data(b"abc")
    p2 = m.dup_data(b"defg")
    assert p1 == 1024 and p2 == 1028  # allocated BY the guest malloc
    assert bytes(m.memory[p1:p1 + 4]) == b"abc\0"
    assert bytes(m.memory[p2:p2 + 5]) == b"defg\0"
    m.reset_heap()
    assert m._mallocs == []


def test_filter_wasm_reinstantiates_after_trap(tmp_path):
    """A trapping record must not poison guest state for later records:
    the module reinstantiates (global resets to its init value)."""
    # f(...6 args) -> i32: bump global; if rec_len == 1 trap (div 0);
    # else return ptr to static json only when global == 1 (fresh)
    body = (b"\x23\x00" + I32_CONST + sleb(1) + I32_ADD + b"\x24\x00"
            + l(5) + I32_CONST + sleb(1) + I32_EQ
            + IF_VOID + I32_CONST + sleb(1) + I32_CONST + sleb(0)
            + b"\x6d" + b"\x1a" + END     # div_s by zero → trap
            + b"\x23\x00" + I32_CONST + sleb(1) + I32_EQ
            + IF_I32 + I32_CONST + sleb(32)
            + ELSE + I32_CONST + sleb(48) + END)
    mod_bytes = module(
        [([I32] * 6, [I32])],
        [(0, [], body)],
        [("go", 0, 0)],
        data=[(32, b'{"fresh":1}\0'), (48, b'{"stale":1}\0')],
        globals_=[(I32, 1, I32_CONST + sleb(0))])
    path = tmp_path / "trap.wasm"
    path.write_bytes(mod_bytes)
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("wasm", match="t", wasm_path=str(path),
               function_name="go")
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(in_ffd, "0")          # rec_len 1 → traps, kept as-is
        ctx.push(in_ffd, json.dumps({"a": 1}))  # must see a FRESH module
        ctx.flush_now()
        import time
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
    finally:
        ctx.stop()
    bodies = [e.body for d in got for e in decode_events(d)]
    assert 0 in bodies or {"0": 0} not in bodies  # trapped record kept raw
    assert {"fresh": 1} in bodies, bodies


def test_saturating_trunc_and_memory_fill():
    # sat(x f64) -> i32.trunc_sat_f64_s ; fill(d, v, n) via 0xFC 11
    F64 = 0x7C
    sat_body = l(0) + b"\xfc\x02"          # i32.trunc_sat_f64_s
    fill_body = l(0) + l(1) + l(2) + b"\xfc\x0b\x00"
    m = Module(module(
        [([F64], [I32]), ([I32, I32, I32], [])],
        [(0, [], sat_body), (1, [], fill_body)],
        [("sat", 0, 0), ("fill", 0, 1)]))
    assert m.call("sat", [3.9]) == [3]
    assert m.call("sat", [float("nan")]) == [0]
    assert m.call("sat", [1e300]) == [0x7FFFFFFF]
    assert m.call("sat", [-1e300]) == [0x80000000]
    m.call("fill", [10, 0x41, 5])
    assert bytes(m.memory[10:16]) == b"AAAAA\0"


def test_simd_prefix_rejected_at_load():
    bad = module([([], [])], [(0, [], b"\xfd\x00")], [("f", 0, 0)],
                 memory_pages=0)
    with pytest.raises(WasmError, match="SIMD"):
        Module(bad)


def test_memory_limit_enforced():
    # grow(n) -> memory.grow result
    body = l(0) + b"\x40\x00"
    m = Module(module([([I32], [I32])], [(0, [], body)],
                      [("grow", 0, 0)]), max_memory_bytes=3 * 65536)
    assert m.call("grow", [1]) == [1]     # 1 page → 2, under the cap
    assert m.call("grow", [10]) == [0xFFFFFFFF]  # over the 3-page cap


def test_filter_wasm_survives_stack_underflow(tmp_path):
    """An invalid module raising a raw Python error (drop on empty
    stack) must keep the record, not leak the exception."""
    bad_body = b"\x1a"  # drop with nothing on the stack → IndexError
    mod_bytes = module([([I32] * 6, [I32])], [(0, [], bad_body)],
                       [("go", 0, 0)])
    path = tmp_path / "bad.wasm"
    path.write_bytes(mod_bytes)
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("wasm", match="t", wasm_path=str(path),
               function_name="go")
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"keep": "me"}))
        ctx.flush_now()
        import time
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.02)
    finally:
        ctx.stop()
    bodies = [e.body for d in got for e in decode_events(d)]
    assert bodies == [{"keep": "me"}]
