"""Sketch-eligible stream-processor SQL ↔ flux plane.

The acceptance contract: sketch-eligible queries return results
bit-identical (exact aggregates — COUNT/SUM/MIN/MAX/AVG, including
Python number types) or within documented HLL error bounds
(COUNT(DISTINCT ...)) versus the existing exact Python evaluation
path, over randomized workloads; ineligible shapes fall back to the
exact path untouched; and the raw (no-decode) ingest fast path stays
ON for flux-backed tags.
"""

import json
import math
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import fluentbit_tpu  # noqa: F401
from fluentbit_tpu.codec.events import encode_event
from fluentbit_tpu.core.engine import Engine
from fluentbit_tpu.flux.query import eligible
from fluentbit_tpu.stream_processor import parse_sql

SQL_WINDOWED = (
    "CREATE STREAM s WITH (tag='out') AS "
    "SELECT tenant, COUNT(*), COUNT(DISTINCT user) AS uniq, "
    "SUM(size) AS sz, MIN(size), MAX(size), AVG(size) "
    "FROM TAG:'app.*' WINDOW TUMBLING (60 SECOND) GROUP BY tenant;"
)


# ------------------------------------------------------------- grammar

def test_count_distinct_parses():
    q = parse_sql("SELECT COUNT(DISTINCT user) FROM TAG:'x';")
    k = q.keys[0]
    assert k.func == "count_distinct" and k.name == "user"
    assert k.out_name == "COUNT(DISTINCT user)"
    assert q.has_aggregates


def test_count_distinct_exact_evaluation():
    """The exact path (no flux) counts a per-group value set."""
    e = Engine()
    task = e.sp_task("CREATE STREAM s WITH (tag='o') AS "
                     "SELECT COUNT(DISTINCT u) AS c FROM TAG:'t';",
                     allow_flux=False)
    out = []
    task.emit = lambda tag, rows: out.append(rows)
    from fluentbit_tpu.codec.events import decode_events

    buf = b"".join(encode_event({"u": f"x{i % 3}"}, 1.0)
                   for i in range(10))
    task.process(decode_events(buf), "t")
    assert out[0][0]["c"] == 3


# --------------------------------------------------------- eligibility

ELIGIBILITY = [
    (SQL_WINDOWED, True),
    # no window → exact path
    ("CREATE STREAM s AS SELECT COUNT(*) FROM TAG:'a' GROUP BY t;",
     False),
    # WHERE → exact path
    ("CREATE STREAM s AS SELECT COUNT(*) FROM TAG:'a' "
     "WHERE x = 1 WINDOW TUMBLING (5 SECOND);", False),
    # forecast needs the raw series
    ("CREATE STREAM s AS SELECT TIMESERIES_FORECAST(v, 10) "
     "FROM TAG:'a' WINDOW TUMBLING (5 SECOND);", False),
    # stream source → exact path
    ("CREATE STREAM s AS SELECT COUNT(*) FROM STREAM:base "
     "WINDOW TUMBLING (5 SECOND);", False),
    # per-query opt-out
    ("CREATE STREAM s WITH (flux='off') AS SELECT COUNT(*) "
     "FROM TAG:'a' WINDOW TUMBLING (5 SECOND);", False),
    # projection-only (no aggregates) → exact path
    ("CREATE STREAM s AS SELECT a, b FROM TAG:'a' "
     "WINDOW TUMBLING (5 SECOND);", False),
    # hopping windows are eligible
    ("CREATE STREAM s AS SELECT COUNT(*) FROM TAG:'a' "
     "WINDOW HOPPING (10 SECOND, ADVANCE BY 2 SECOND);", True),
    # dotted (nested-accessor) fields resolve through nested maps on
    # the exact path only — flux stagers see literal top-level keys,
    # so these shapes must stay exact (silently-wrong otherwise)
    ("CREATE STREAM s AS SELECT AVG(http.status) FROM TAG:'a' "
     "WINDOW TUMBLING (5 SECOND);", False),
    ("CREATE STREAM s AS SELECT COUNT(*) FROM TAG:'a' "
     "WINDOW TUMBLING (5 SECOND) GROUP BY k8s.pod;", False),
]


@pytest.mark.parametrize("sql,want", ELIGIBILITY)
def test_eligibility_matrix(sql, want):
    assert eligible(parse_sql(sql)) is want


def test_ineligible_query_stays_exact():
    e = Engine()
    task = e.sp_task("CREATE STREAM s AS SELECT COUNT(*) FROM TAG:'a' "
                     "WHERE x = 1 WINDOW TUMBLING (5 SECOND);")
    assert task.flux is None
    assert not any(f.plugin.name == "flux" for f in e.filters)


def test_eligible_query_gets_flux_and_hidden_filter():
    e = Engine()
    task = e.sp_task(SQL_WINDOWED)
    assert task.flux is not None
    hidden = [f for f in e.filters if f.plugin.name == "flux"]
    assert len(hidden) == 1
    assert hidden[0].route.matches("app.x")
    assert not hidden[0].route.matches("db.y")


# ------------------------------------------------------- differential

def make_engine(sql, allow_flux, mesh=False):
    t = [1000.0]
    e = Engine()
    task = e.sp_task(sql, allow_flux=allow_flux)
    task._now = lambda: t[0]
    task._window_start = 1000.0
    if task.flux is not None:
        st = task.flux.state
        st._now = task._now
        st._window_start = 1000.0
        if mesh:
            from fluentbit_tpu.flux import kernels

            st._mesh = kernels.flux_mesh()
    out = []
    task.emit = lambda tag, rows: out.append((tag, rows))
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    return e, ins, task, out, t


def same_value(a, b) -> bool:
    """Bit-identity for row values: types match and values are equal —
    with NaN == NaN (both paths legitimately produce NaN when a window
    sums +inf and -inf; that IS agreement)."""
    if type(a) is not type(b):
        return False
    if isinstance(a, float) and math.isnan(a):
        return isinstance(b, float) and math.isnan(b)
    return a == b


def corpus(rng, n):
    buf = bytearray()
    for i in range(n):
        body = {}
        if rng.random() > 0.05:
            body["tenant"] = rng.choice(["acme", "globex", "init"])
        if rng.random() > 0.05:
            body["user"] = f"u{rng.randrange(60)}"
        r = rng.random()
        if r < 0.3:
            body["size"] = rng.randrange(-10**12, 10**12)
        elif r < 0.6:
            body["size"] = rng.uniform(-1e6, 1e6)
        elif r < 0.7:
            body["size"] = rng.choice(
                [float("inf"), -float("inf"), 0.0, -0.0, True, None,
                 "123", [1]])
        buf += encode_event(body, 1000.0 + i)
    return bytes(buf)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_differential_exact_bit_identical_hll_bounded(seed):
    rng1, rng2 = random.Random(seed), random.Random(seed)
    e1, ins1, t1, out1, clk1 = make_engine(SQL_WINDOWED, True)
    e2, ins2, t2, out2, clk2 = make_engine(SQL_WINDOWED, False)
    assert t1.flux is not None and t2.flux is None
    for step in range(4):
        raw = corpus(rng1, 250)
        assert corpus(rng2, 250) == raw
        e1.input_log_append(ins1, "app.x", raw)
        e2.input_log_append(ins2, "app.x", raw)
        clk1[0] = clk2[0] = 1000.0 + 61 * (step + 1)
        t1.tick()
        t2.tick()
    assert len(out1) == len(out2) > 0
    for (tag1, rows1), (tag2, rows2) in zip(out1, out2):
        assert tag1 == tag2 and len(rows1) == len(rows2)
        for r1, r2 in zip(rows1, rows2):
            assert list(r1.keys()) == list(r2.keys())
            for k in r2:
                if k == "uniq":
                    exact = r2[k]
                    est = r1[k]
                    # p=12 HLL: σ ≈ 1.04/√4096 ≈ 1.6%; 5σ + small-n
                    # slack is far beyond any observable deviation
                    bound = max(3.0, 0.10 * exact)
                    assert abs(est - exact) <= bound, (k, est, exact)
                else:
                    assert same_value(r1[k], r2[k]), (k, r1[k], r2[k])


def test_differential_survives_decline_to_per_record():
    """Forcing the flux hook to decline (per-record twin) must not
    change a single emitted byte."""
    rng = random.Random(77)
    raws = [corpus(rng, 150) for _ in range(3)]

    def run(force_decline):
        e, ins, task, out, clk = make_engine(SQL_WINDOWED, True)
        if force_decline:
            for f in e.filters:
                if f.plugin.name == "flux":
                    f.plugin._batch_ok = False
        for i, raw in enumerate(raws):
            e.input_log_append(ins, "app.x", raw)
            clk[0] = 1000.0 + 61 * (i + 1)
            task.tick()
        return out

    a, b = run(False), run(True)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_filters_registered_after_sp_task_run_before_flux():
    """Config files apply [STREAM_TASK] before [FILTER]: a user filter
    registered AFTER the query must still run before the hidden flux
    filter (the SP aggregates POST-filter), so records the chain drops
    never reach flux state."""
    e, ins, task, out, clk = make_engine(SQL_WINDOWED, True)
    g = e.filter("grep")            # registered AFTER sp_task
    g.set("exclude", "user ^drop")
    g.configure()
    g.plugin.init(g, e)
    assert [f.plugin.name for f in e.filters] == ["grep", "flux"]
    raw = b"".join(encode_event(
        {"tenant": "a", "user": ("drop" if i % 2 else "keep"),
         "size": 1}, 1000.0) for i in range(20))
    e.input_log_append(ins, "app.x", raw)
    clk[0] = 1061.0
    task.tick()
    # exact-path twin: same order, same verdict
    e2, ins2, task2, out2, clk2 = make_engine(SQL_WINDOWED, False)
    g2 = e2.filter("grep")
    g2.set("exclude", "user ^drop")
    g2.configure()
    g2.plugin.init(g2, e2)
    e2.input_log_append(ins2, "app.x", raw)
    clk2[0] = 1061.0
    task2.tick()
    assert out[0][1][0]["COUNT(*)"] == 10
    assert out[0][1][0]["COUNT(*)"] == out2[0][1][0]["COUNT(*)"]


# ----------------------------------------------------- raw path stays on

def test_raw_fast_path_stays_on_for_flux_backed_tag():
    """The whole point: a flux-backed query must NOT force the decode
    path. The raw chain handles the append (no batch declines) and the
    window still aggregates."""
    e, ins, task, out, clk = make_engine(SQL_WINDOWED, True)
    raw = b"".join(encode_event(
        {"tenant": "a", "user": f"u{i}", "size": i}, 1000.0)
        for i in range(50))
    n = e.input_log_append(ins, "app.x", raw)
    assert n == 50
    assert sum(v for _, v in e.m_filter_batch_decline.samples()) == 0
    clk[0] = 1061.0
    task.tick()
    assert out and out[0][1][0]["COUNT(*)"] == 50


def test_exact_sp_still_forces_decode_path():
    """Non-flux tasks keep the pre-existing behavior (sp_active)."""
    e, ins, task, out, clk = make_engine(
        "CREATE STREAM s AS SELECT COUNT(*) FROM TAG:'app.*' "
        "WHERE tenant = 'a' WINDOW TUMBLING (60 SECOND);", True)
    assert task.flux is None
    raw = b"".join(encode_event({"tenant": "a"}, 1000.0)
                   for i in range(10))
    e.input_log_append(ins, "app.x", raw)
    clk[0] = 1061.0
    task.tick()
    assert out[0][1][0]["COUNT(*)"] == 10


def test_drain_emits_open_flux_window():
    e, ins, task, out, clk = make_engine(SQL_WINDOWED, True)
    raw = b"".join(encode_event(
        {"tenant": "a", "user": "u", "size": 1}, 1000.0)
        for _ in range(5))
    e.input_log_append(ins, "app.x", raw)
    task.drain()
    assert out and out[0][1][0]["COUNT(*)"] == 5


# -------------------------------------------------------------- mesh

@pytest.mark.mesh
def test_sql_on_simulated_mesh_bit_identical():
    """The tier-1 mesh acceptance: the same differential with the flux
    state sharded across the simulated 8-device mesh."""
    if len(jax.devices()) < 8:
        pytest.skip("need the simulated 8-device mesh")
    rng1, rng2 = random.Random(21), random.Random(21)
    e1, ins1, t1, out1, clk1 = make_engine(SQL_WINDOWED, True,
                                           mesh=True)
    assert t1.flux.state._mesh is not None
    e2, ins2, t2, out2, clk2 = make_engine(SQL_WINDOWED, False)
    raw = corpus(rng1, 200)
    assert corpus(rng2, 200) == raw
    e1.input_log_append(ins1, "app.x", raw)
    e2.input_log_append(ins2, "app.x", raw)
    clk1[0] = clk2[0] = 1061.0
    t1.tick()
    t2.tick()
    (tag1, rows1), (tag2, rows2) = out1[0], out2[0]
    for r1, r2 in zip(rows1, rows2):
        for k in r2:
            if k == "uniq":
                assert abs(r1[k] - r2[k]) <= max(3.0, 0.10 * r2[k])
            else:
                assert same_value(r1[k], r2[k]), (k, r1[k], r2[k])


@pytest.mark.mesh
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_sql_mesh_matrix_slow(seed):
    """Full mesh matrix (slow lane): more seeds, hopping windows."""
    if len(jax.devices()) < 8:
        pytest.skip("need the simulated 8-device mesh")
    sql = ("CREATE STREAM s AS SELECT tenant, COUNT(*), "
           "SUM(size) AS sz, COUNT(DISTINCT user) AS uniq "
           "FROM TAG:'app.*' "
           "WINDOW HOPPING (60 SECOND, ADVANCE BY 20 SECOND) "
           "GROUP BY tenant;")
    rng1, rng2 = random.Random(seed), random.Random(seed)
    e1, ins1, t1, out1, clk1 = make_engine(sql, True, mesh=True)
    e2, ins2, t2, out2, clk2 = make_engine(sql, False)
    for step in range(5):
        raw = corpus(rng1, 120)
        assert corpus(rng2, 120) == raw
        e1.input_log_append(ins1, "app.x", raw)
        e2.input_log_append(ins2, "app.x", raw)
        clk1[0] = clk2[0] = 1000.0 + 21 * (step + 1)
        t1.tick()
        t2.tick()
    assert len(out1) == len(out2) > 0
    for (_, rows1), (_, rows2) in zip(out1, out2):
        for r1, r2 in zip(rows1, rows2):
            for k in r2:
                if k == "uniq":
                    assert abs(r1[k] - r2[k]) <= max(3.0, 0.10 * r2[k])
                else:
                    assert same_value(r1[k], r2[k]), (k, r1[k], r2[k])
