"""HTTP forward-proxy delivery tests.

Reference: flb_http_client.c proxy_parse + fmt_proxy (absolute-form
requests with Proxy-Connection for plain http) and the CONNECT tunnel
form for TLS origins. The proxy stubs here assert the exact wire shape
a real forward proxy (squid/envoy) would see."""

import asyncio
import json
import socket
import ssl
import subprocess
import threading
import time

import pytest

import fluentbit_tpu as flb


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("proxycerts")
    crt, key = str(d / "srv.crt"), str(d / "srv.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", crt, "-days", "2",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    return crt, key


class PlainProxyStub:
    """Accepts absolute-form requests, answers 200, records them."""

    def __init__(self):
        self.requests = []
        self.port = None
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thr = threading.Thread(target=self._serve, daemon=True)
        self._thr.start()

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            with conn:
                conn.settimeout(3)
                data = b""
                try:
                    while b"\r\n\r\n" not in data:
                        data += conn.recv(65536)
                    head, _, rest = data.partition(b"\r\n\r\n")
                    clen = 0
                    for line in head.split(b"\r\n"):
                        if line.lower().startswith(b"content-length:"):
                            clen = int(line.split(b":")[1])
                    while len(rest) < clen:
                        rest += conn.recv(65536)
                    self.requests.append((head.decode(), rest))
                    conn.sendall(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Length: 2\r\n\r\nok")
                except (socket.timeout, OSError):
                    pass

    def close(self):
        self._stop = True
        self._thr.join(timeout=2)
        self._sock.close()


def wait_for(cond, timeout=8.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.05)
    raise TimeoutError()


def test_plain_http_via_proxy():
    stub = PlainProxyStub()
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    # backend.invalid is never resolved — the proxy is dialed instead
    ctx.output("http", match="t", host="backend.invalid", port="8080",
               uri="/ingest", proxy=f"http://127.0.0.1:{stub.port}",
               format="json")
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"m": 1}))
        ctx.flush_now()
        wait_for(lambda: stub.requests)
    finally:
        ctx.stop()
        stub.close()
    head, body = stub.requests[0]
    lines = head.split("\r\n")
    # absolute-form request line naming the ORIGIN, not the proxy
    assert lines[0] == "POST http://backend.invalid:8080/ingest HTTP/1.1"
    assert "Proxy-Connection: Keep-Alive" in lines
    assert any(line == "Host: backend.invalid:8080" for line in lines)
    assert b'"m": 1' in body or b'"m":1' in body


def test_connect_tunnel_for_tls(certs):
    crt, key = certs
    # TLS origin
    origin_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    origin_ctx.load_cert_chain(crt, key)
    origin = socket.socket()
    origin.bind(("127.0.0.1", 0))
    origin.listen(2)
    oport = origin.getsockname()[1]
    got = {}

    def origin_serve():
        origin.settimeout(8)
        try:
            conn, _ = origin.accept()
        except socket.timeout:
            return
        with origin_ctx.wrap_socket(conn, server_side=True) as tls:
            tls.settimeout(5)
            data = b""
            try:
                while b"\r\n\r\n" not in data:
                    data += tls.recv(65536)
                got["head"] = data.partition(b"\r\n\r\n")[0].decode()
                tls.sendall(b"HTTP/1.1 200 OK\r\n"
                            b"Content-Length: 0\r\n\r\n")
            except (socket.timeout, OSError):
                pass

    # CONNECT proxy: replies 200 then tunnels bytes to the origin
    proxy = socket.socket()
    proxy.bind(("127.0.0.1", 0))
    proxy.listen(2)
    pport = proxy.getsockname()[1]
    connect_line = {}

    def proxy_serve():
        proxy.settimeout(8)
        try:
            conn, _ = proxy.accept()
        except socket.timeout:
            return
        conn.settimeout(5)
        data = b""
        while b"\r\n\r\n" not in data:
            data += conn.recv(65536)
        connect_line["line"] = data.split(b"\r\n")[0].decode()
        upstream = socket.create_connection(("127.0.0.1", oport))
        conn.sendall(b"HTTP/1.1 200 Connection established\r\n\r\n")

        def pump(a, b):
            try:
                while True:
                    chunk = a.recv(65536)
                    if not chunk:
                        break
                    b.sendall(chunk)
            except OSError:
                pass
            finally:
                try:
                    b.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t1 = threading.Thread(target=pump, args=(conn, upstream),
                              daemon=True)
        t2 = threading.Thread(target=pump, args=(upstream, conn),
                              daemon=True)
        t1.start()
        t2.start()
        t1.join(timeout=8)
        t2.join(timeout=8)

    to = threading.Thread(target=origin_serve, daemon=True)
    tp = threading.Thread(target=proxy_serve, daemon=True)
    to.start()
    tp.start()

    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("http", match="t", host="localhost", port=str(oport),
               uri="/tls-ingest", proxy=f"http://127.0.0.1:{pport}",
               tls="on", **{"tls.verify": "off"}, format="json")
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"secure": True}))
        ctx.flush_now()
        wait_for(lambda: "head" in got)
    finally:
        ctx.stop()
        proxy.close()
        origin.close()
    assert connect_line["line"] == f"CONNECT localhost:{oport} HTTP/1.1"
    # origin sees a normal origin-form request THROUGH the tunnel
    assert got["head"].split("\r\n")[0] == "POST /tls-ingest HTTP/1.1"


def test_proxy_rejects_https_scheme():
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("dummy", tag="t")
    ctx.output("http", match="t", host="h", proxy="https://secure-proxy:3128")
    with pytest.raises(Exception):
        ctx.start()
    ctx.stop()
