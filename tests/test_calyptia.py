"""Calyptia control plane (out_calyptia / custom_calyptia /
in_calyptia_fleet) against a local stub of the Cloud API.

Reference: plugins/out_calyptia/calyptia.c,
plugins/custom_calyptia/calyptia.c,
plugins/in_calyptia_fleet/in_calyptia_fleet.c."""

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.core.plugin import registry


class _StubCloud(BaseHTTPRequestHandler):
    log = []
    fleet_config = "[INPUT]\n    name dummy\n"
    fleet_last_modified = "Mon, 02 Jan 2006 15:04:05 GMT"

    def _reply(self, code, body=b"", headers=None):
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _record(self, body=b""):
        type(self).log.append({
            "method": self.command, "path": self.path,
            "project": self.headers.get("X-Project-Token"),
            "agent_token": self.headers.get("X-Agent-Token"),
            "ctype": self.headers.get("Content-Type"),
            "body": body,
        })

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self._record(body)
        if self.path == "/v1/agents":
            self._reply(200, json.dumps(
                {"id": "agent-1", "token": "tok-1"}).encode())
        elif self.path.startswith("/v1/agents/") and \
                self.path.endswith("/metrics"):
            self._reply(200)
        else:
            self._reply(404)

    def do_PATCH(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self._record(body)
        self._reply(204)

    def do_GET(self):
        self._record()
        if self.path.startswith("/v1/search"):
            self._reply(200, json.dumps([{"id": "fleet-42"}]).encode())
        elif "/config" in self.path and self.path.startswith("/v1/fleets/"):
            self._reply(200, self.fleet_config.encode(),
                        {"Last-Modified": self.fleet_last_modified})
        else:
            self._reply(404)

    def log_message(self, *a):
        pass


@pytest.fixture
def cloud():
    _StubCloud.log = []
    srv = HTTPServer(("127.0.0.1", 0), _StubCloud)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _make_output(port, **props):
    ins = registry.create_output("calyptia")
    ins.set("api_key", "proj-token")
    ins.set("machine_id", "m-1")
    ins.set("cloud_host", "127.0.0.1")
    ins.set("cloud_port", str(port))
    for k, v in props.items():
        ins.set(k, v)
    ins.configure()
    ins.plugin.init(ins, None)
    return ins.plugin


def test_agent_registration_on_init(cloud):
    port = cloud.server_address[1]
    plug = _make_output(port)
    assert plug.agent_id == "agent-1" and plug.agent_token == "tok-1"
    (reg,) = _StubCloud.log
    assert reg["method"] == "POST" and reg["path"] == "/v1/agents"
    assert reg["project"] == "proj-token"
    meta = json.loads(reg["body"])
    assert meta["type"] == "fluentbit" and meta["machineID"] == "m-1"
    assert meta["edition"] == "community" and meta["os"] == "linux"


def test_session_reuse_patches_instead_of_registering(cloud, tmp_path):
    port = cloud.server_address[1]
    _make_output(port, store_path=str(tmp_path))
    assert (tmp_path / "session.CALYPTIA").is_file()
    _StubCloud.log = []
    plug2 = _make_output(port, store_path=str(tmp_path))
    assert plug2.agent_id == "agent-1"
    (patch,) = _StubCloud.log
    assert patch["method"] == "PATCH"
    assert patch["path"] == "/v1/agents/agent-1"


def test_metrics_flush_carries_agent_token(cloud):
    import asyncio

    from fluentbit_tpu.codec.msgpack import packb
    from fluentbit_tpu.core.plugin import FlushResult

    port = cloud.server_address[1]
    plug = _make_output(port)
    plug.instance.set("add_label", "pipeline main")
    plug.instance.configure()
    plug._labels = [("pipeline", "main")]
    payload = packb({"meta": {"ts": 1.0}, "metrics": [
        {"name": "m", "type": "counter", "desc": "", "labels": [],
         "ts": 1.0, "values": [{"labels": [], "value": 3.0}]}]})
    res = asyncio.run(plug.flush(payload, "_calyptia_cloud", None))
    assert res == FlushResult.OK
    push = _StubCloud.log[-1]
    assert push["path"] == "/v1/agents/agent-1/metrics"
    assert push["agent_token"] == "tok-1"
    assert push["ctype"] == "application/x-msgpack"
    from fluentbit_tpu.codec.msgpack import unpackb
    sent = unpackb(push["body"])
    m = sent["metrics"][0]
    assert m["labels"] == ["pipeline"]
    assert m["values"][0]["labels"] == ["main"]


def _fleet_api_key():
    head = base64.b64encode(
        json.dumps({"ProjectID": "p-9"}).encode()).decode().rstrip("=")
    return head + ".signature"


class _FakeEngine:
    def __init__(self):
        self.reload_config_path = None
        self.reloaded = 0

        def cb():
            self.reloaded += 1
        self.reload_callback = cb


def _make_fleet(port, tmp_path, **props):
    ins = registry.create_input("calyptia_fleet")
    ins.set("api_key", _fleet_api_key())
    ins.set("host", "127.0.0.1")
    ins.set("port", str(port))
    ins.set("config_dir", str(tmp_path))
    for k, v in props.items():
        ins.set(k, v)
    ins.configure()
    ins.plugin.init(ins, None)
    return ins.plugin


def test_fleet_name_resolution_and_reload(cloud, tmp_path):
    port = cloud.server_address[1]
    plug = _make_fleet(port, tmp_path, fleet_name="prod",
                       machine_id="m-1")
    eng = _FakeEngine()
    plug.collect(eng)
    # name → id via /v1/search with the ProjectID from the api_key
    search = [e for e in _StubCloud.log if e["path"].startswith("/v1/search")]
    assert search and "project_id=p-9" in search[0]["path"]
    assert "term=prod" in search[0]["path"]
    assert plug.fleet_id == "fleet-42"
    # config fetched, written under config_dir, reload fired
    assert eng.reloaded == 1
    assert eng.reload_config_path and eng.reload_config_path.endswith(".conf")
    with open(eng.reload_config_path) as f:
        assert f.read() == _StubCloud.fleet_config
    # same config again → no second reload
    plug.collect(eng)
    assert eng.reloaded == 1


def test_custom_wires_hidden_pipeline(cloud, tmp_path):
    port = cloud.server_address[1]
    ctx = flb.create(flush="100ms", grace="1")
    ctx.custom("calyptia", api_key=_fleet_api_key(),
               calyptia_host="127.0.0.1", calyptia_port=str(port),
               calyptia_tls="off", fleet_id="fleet-42",
               store_path=str(tmp_path / "store"),
               fleet_config_dir=str(tmp_path / "fleet"))
    ctx.output("null", match="nothing")
    ctx.start()
    try:
        deadline = time.time() + 6
        while time.time() < deadline:
            if any(e["path"] == "/v1/agents/agent-1/metrics"
                   for e in _StubCloud.log):
                break
            time.sleep(0.05)
    finally:
        ctx.stop()
    paths = [e["path"] for e in _StubCloud.log]
    assert "/v1/agents" in paths  # registration happened
    assert any(p == "/v1/agents/agent-1/metrics" for p in paths)
    # machine-id was provisioned and persisted
    assert (tmp_path / "store" / "machine-id").is_file()
