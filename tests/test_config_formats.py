"""Config formats (classic INI + YAML) and the CLI.

Reference: src/config_format/flb_cf_fluentbit.c (classic), flb_cf_yaml.c
(YAML pipelines), src/flb_env.c (${VAR} interpolation), src/fluent-bit.c
(CLI argument semantics).
"""

import json
import os
import subprocess
import sys
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.config_format import (
    apply_to_context,
    load_config_file,
    parse_classic,
    parse_yaml,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_classic_sections_and_properties(tmp_path):
    cf = parse_classic(
        """
# comment
[SERVICE]
    Flush  2
    Grace  7

[INPUT]
    Name  dummy
    Tag   t
    Rate  5

[OUTPUT]
    Name   stdout
    Match  *
""")
    assert [s.name for s in cf.sections] == ["service", "input", "output"]
    assert cf.sections[1].get("Name") == "dummy"
    assert cf.sections[1].get("rate") == "5"


def test_classic_env_interpolation_and_set(monkeypatch):
    monkeypatch.setenv("MYTAG", "fromenv")
    cf = parse_classic(
        "@SET RATE=9\n[INPUT]\n Name dummy\n Tag ${MYTAG}\n Rate ${RATE}\n"
    )
    sec = cf.sections[0]
    assert sec.get("Tag") == "fromenv"
    assert sec.get("Rate") == "9"


def test_classic_include(tmp_path):
    (tmp_path / "extra.conf").write_text("[OUTPUT]\n Name null\n Match *\n")
    main = tmp_path / "main.conf"
    main.write_text("[INPUT]\n Name dummy\n@INCLUDE extra.conf\n")
    cf = load_config_file(str(main))
    assert [s.name for s in cf.sections] == ["input", "output"]


def test_yaml_pipeline(tmp_path):
    cf = parse_yaml(
        """
service:
  flush: 0.5
env:
  TOPIC: apps
pipeline:
  inputs:
    - name: dummy
      tag: ${TOPIC}.x
  filters:
    - name: grep
      match: "*"
      regex: log hi
  outputs:
    - name: "null"
      match: "*"
""")
    names = [(s.name, s.get("name")) for s in cf.sections]
    assert ("input", "dummy") in names
    assert ("filter", "grep") in names
    inp = [s for s in cf.sections if s.name == "input"][0]
    assert inp.get("tag") == "apps.x"


def test_apply_to_context_runs_pipeline(tmp_path):
    conf = tmp_path / "p.conf"
    conf.write_text("""
[SERVICE]
    Flush  0.05
    Grace  1

[INPUT]
    Name  lib
    Tag   t

[FILTER]
    Name   grep
    Match  t
    Regex  log keep

[OUTPUT]
    Name     lib
    Match    t
""")
    ctx = flb.create()
    apply_to_context(ctx, load_config_file(str(conf)), str(tmp_path))
    got = []
    ctx.engine.outputs[0].set("callback", lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(0, json.dumps({"log": "keep me"}))
        ctx.push(0, json.dumps({"log": "drop me"}))
        ctx.flush_now()
    finally:
        ctx.stop()
    evs = [e for d in got for e in decode_events(d)]
    assert [e.body["log"] for e in evs] == ["keep me"]


def test_parsers_file_loaded_via_service(tmp_path):
    ctx = flb.create()
    conf = tmp_path / "c.conf"
    conf.write_text(f"""
[SERVICE]
    Parsers_File {REPO}/conf/parsers.conf

[INPUT]
    Name lib

[OUTPUT]
    Name null
    Match *
""")
    apply_to_context(ctx, load_config_file(str(conf)), str(tmp_path))
    assert "apache2" in ctx.engine.parsers
    assert ctx.engine.parsers["apache2"].types  # Types parsed


@pytest.mark.parametrize("conf", [
    "baseline1-grep.conf",
    "baseline2-parser.yaml",
    "baseline3-rewrite.conf",
    "baseline4-metrics.yaml",
])
def test_baseline_configs_constructible(conf, tmp_path):
    """Every shipped BASELINE config parses and materializes (dry run)."""
    path = os.path.join(REPO, "conf", conf)
    ctx = flb.create()
    apply_to_context(ctx, load_config_file(path), os.path.join(REPO, "conf"))
    assert ctx.engine.inputs and ctx.engine.outputs


def test_baseline5_constructible_or_skipped():
    path = os.path.join(REPO, "conf", "baseline5-k8s.conf")
    ctx = flb.create()
    try:
        apply_to_context(ctx, load_config_file(path),
                         os.path.join(REPO, "conf"))
    except ValueError as e:
        pytest.skip(f"kubernetes filter not yet available: {e}")
    assert ctx.engine.inputs and ctx.engine.outputs


# --------------------------------------------------------------------- CLI

def run_cli(args, timeout=30):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "fluentbit_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def test_cli_help_and_version():
    assert "Options:" in run_cli(["--help"]).stdout
    assert "fluentbit_tpu v" in run_cli(["--version"]).stdout


def test_cli_dry_run():
    r = run_cli(["-i", "dummy", "-o", "null", "--dry-run"])
    assert r.returncode == 0
    assert "configuration test is successful" in r.stdout


def test_cli_dry_run_missing_output():
    assert run_cli(["-i", "dummy", "--dry-run"]).returncode == 1


def test_cli_pipeline_runs_and_sigterm(tmp_path):
    out_file = tmp_path / "out.txt"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, "-m", "fluentbit_tpu",
         "-i", "dummy", "-t", "t", "-p", 'dummy={"m": 1}', "-p", "rate=50",
         "-o", "file", "-m", "t", "-p", f"path={tmp_path}", "-p", "file=out.txt",
         "-p", "format=json_lines", "-f", "0.1", "-g", "1"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if out_file.exists() and out_file.read_text().count("\n") >= 3:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("no output produced")
    finally:
        p.terminate()
        p.wait(timeout=15)
    assert p.returncode == 0
    line = out_file.read_text().splitlines()[0]
    assert json.loads(line)["m"] == 1
