"""node_exporter_metrics collectors + collectd binary protocol."""

import socket
import struct
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.codec.msgpack import Unpacker
from fluentbit_tpu.plugins.inputs_exporters import parse_collectd_packet


def test_node_exporter_collectors():
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("node_exporter_metrics", tag="node", scrape_interval="0.2")
    payloads = []
    ctx.output("lib", match="node", callback=lambda d, t: payloads.append(d))
    ctx.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and not payloads:
            time.sleep(0.05)
    finally:
        ctx.stop()
    assert payloads
    obj = next(iter(Unpacker(payloads[0])))
    by_name = {m["name"]: m for m in obj["metrics"]}
    cpu = by_name["node_cpu_seconds_total"]
    assert cpu["type"] == "counter"
    assert cpu["labels"] == ["cpu", "mode"]
    modes = {s["labels"][1] for s in cpu["values"]}
    assert {"user", "system", "idle"} <= modes
    assert by_name["node_memory_MemTotal_bytes"]["values"][0]["value"] > 0
    assert "node_load1" in by_name
    assert by_name["node_uname_info"]["values"][0]["value"] == 1.0
    fs = by_name["node_filesystem_size_bytes"]
    assert fs["labels"] == ["device", "mountpoint", "fstype"]


def collectd_packet():
    def part_str(ptype, s):
        b = s.encode() + b"\x00"
        return struct.pack(">HH", ptype, 4 + len(b)) + b

    def part_u64(ptype, v):
        return struct.pack(">HHQ", ptype, 12, v)

    values = struct.pack(">HH", 0x0006, 4 + 2 + 2 * 9)  # 2 values
    values += struct.pack(">H", 2)
    values += bytes([1, 0])                  # gauge, counter
    values += struct.pack("<d", 36.5)        # gauge is little-endian
    values += struct.pack(">Q", 12345)       # counter is u64 BE
    return (part_str(0x0000, "web01")
            + part_u64(0x0008, int(1700000000 * (2 ** 30)))  # time_hr
            + part_str(0x0002, "cpu")
            + part_str(0x0003, "0")
            + part_str(0x0004, "cpu")
            + part_str(0x0005, "user")
            + values)


def test_parse_collectd_packet():
    records = parse_collectd_packet(collectd_packet())
    assert len(records) == 1
    r = records[0]
    assert r["host"] == "web01"
    assert r["plugin"] == "cpu" and r["plugin_instance"] == "0"
    assert r["type"] == "cpu" and r["type_instance"] == "user"
    assert r["values"] == [36.5, 12345]
    assert abs(r["time"] - 1700000000) < 1


def test_collectd_udp_pipeline():
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("collectd", tag="cd", port="0")
    ins = ctx.engine.inputs[0]
    got = []
    ctx.output("lib", match="cd", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and not getattr(ins.plugin,
                                                     "bound_port", None):
            time.sleep(0.02)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.sendto(collectd_packet(), ("127.0.0.1", ins.plugin.bound_port))
        s.close()
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.05)
    finally:
        ctx.stop()
    ev = decode_events(got[0])[0]
    assert ev.body["host"] == "web01"
    assert ev.body["values"] == [36.5, 12345]
    assert abs(ev.ts_float - 1700000000) < 1
