"""node_exporter_metrics collectors + collectd binary protocol."""

import socket
import struct
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.codec.msgpack import Unpacker
from fluentbit_tpu.plugins.inputs_exporters import parse_collectd_packet


def test_node_exporter_collectors():
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("node_exporter_metrics", tag="node", scrape_interval="0.2")
    payloads = []
    ctx.output("lib", match="node", callback=lambda d, t: payloads.append(d))
    ctx.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and not payloads:
            time.sleep(0.05)
    finally:
        ctx.stop()
    assert payloads
    obj = next(iter(Unpacker(payloads[0])))
    by_name = {m["name"]: m for m in obj["metrics"]}
    cpu = by_name["node_cpu_seconds_total"]
    assert cpu["type"] == "counter"
    assert cpu["labels"] == ["cpu", "mode"]
    modes = {s["labels"][1] for s in cpu["values"]}
    assert {"user", "system", "idle"} <= modes
    assert by_name["node_memory_MemTotal_bytes"]["values"][0]["value"] > 0
    assert "node_load1" in by_name
    assert by_name["node_uname_info"]["values"][0]["value"] == 1.0
    fs = by_name["node_filesystem_size_bytes"]
    assert fs["labels"] == ["device", "mountpoint", "fstype"]


def test_node_exporter_extended_collectors(tmp_path):
    """diskstats / vmstat / stat / filefd / cpufreq / hwmon / time /
    uptime / textfile against a synthetic procfs+sysfs tree
    (reference in_node_exporter_metrics/ne.c:34-49 collector set)."""
    proc = tmp_path / "proc"
    sys_ = tmp_path / "sys"
    (proc / "sys/fs").mkdir(parents=True)
    (proc / "diskstats").write_text(
        "   8  0 sda 100 0 2048 50 200 0 4096 80 0 30 1500\n"
        "   8  1 sda1 10 0 16 5 20 0 64 8 0 3 150\n")
    (proc / "vmstat").write_text(
        "nr_free_pages 100\npgpgin 555\npgpgout 666\npswpin 7\n"
        "pgfault 888\npgmajfault 99\noom_kill 2\n")
    (proc / "stat").write_text(
        "cpu  10 0 20 300 0 0 0 0\ncpu0 10 0 20 300 0 0 0 0\n"
        "intr 12345 1 2 3\nctxt 99999\nbtime 1700000000\n"
        "processes 4321\nprocs_running 3\nprocs_blocked 1\n")
    (proc / "sys/fs/file-nr").write_text("1234\t0\t808348\n")
    (proc / "uptime").write_text("5000.5 9000.0\n")
    cf = sys_ / "devices/system/cpu/cpu0/cpufreq"
    cf.mkdir(parents=True)
    (cf / "scaling_cur_freq").write_text("2200000\n")
    (cf / "scaling_min_freq").write_text("800000\n")
    (cf / "scaling_max_freq").write_text("3400000\n")
    hw = sys_ / "class/hwmon/hwmon0"
    hw.mkdir(parents=True)
    (hw / "name").write_text("coretemp\n")
    (hw / "temp1_input").write_text("45500\n")
    tfd = tmp_path / "textfile"
    tfd.mkdir()
    (tfd / "job.prom").write_text(
        "# HELP my_job_last_success Last success.\n"
        "# TYPE my_job_last_success gauge\n"
        "my_job_last_success 1700000001\n")

    from fluentbit_tpu.core.plugin import registry

    ins = registry.create_input("node_exporter_metrics")
    ins.set("path.procfs", str(proc))
    ins.set("path.sysfs", str(sys_))
    ins.set("collectors",
            "diskstats,vmstat,stat,filefd,cpufreq,hwmon,time,uptime")
    ins.set("textfile.directory", str(tfd))
    ins.configure()
    ins.plugin.init(ins, None)

    captured = {}

    class Eng:
        def input_event_append(self, instance, tag, data, etype,
                               n_records=1):
            captured["data"] = data
            captured["n"] = n_records

    ins.plugin.collect(Eng())
    obj = next(iter(Unpacker(captured["data"])))
    by_name = {m["name"]: m for m in obj["metrics"]}

    disk = by_name["node_disk_read_bytes_total"]
    vals = {tuple(s["labels"]): s["value"] for s in disk["values"]}
    assert vals[("sda",)] == 2048 * 512
    assert by_name["node_disk_io_time_seconds_total"]["values"][0][
        "value"] == pytest.approx(0.03)  # field 13 (ms doing I/O) / 1000
    assert by_name["node_vmstat_oom_kill"]["values"][0]["value"] == 2
    assert by_name["node_vmstat_pgfault"]["values"][0]["value"] == 888
    assert "node_vmstat_nr_free_pages" not in by_name  # filtered set
    assert by_name["node_context_switches_total"]["values"][0][
        "value"] == 99999
    assert by_name["node_forks_total"]["values"][0]["value"] == 4321
    assert by_name["node_procs_running"]["values"][0]["value"] == 3
    assert by_name["node_filefd_allocated"]["values"][0]["value"] == 1234
    assert by_name["node_filefd_maximum"]["values"][0]["value"] == 808348
    freq = by_name["node_cpu_scaling_frequency_hertz"]
    assert freq["values"][0]["value"] == 2200000 * 1000
    temp = by_name["node_hwmon_temp_celsius"]
    assert temp["values"][0]["labels"] == ["coretemp", "temp1"]
    assert temp["values"][0]["value"] == pytest.approx(45.5)
    assert by_name["node_uptime_seconds_total"]["values"][0][
        "value"] == pytest.approx(5000.5)
    assert by_name["node_time_seconds"]["values"][0]["value"] > 1e9
    assert by_name["my_job_last_success"]["values"][0][
        "value"] == 1700000001


def collectd_packet():
    def part_str(ptype, s):
        b = s.encode() + b"\x00"
        return struct.pack(">HH", ptype, 4 + len(b)) + b

    def part_u64(ptype, v):
        return struct.pack(">HHQ", ptype, 12, v)

    values = struct.pack(">HH", 0x0006, 4 + 2 + 2 * 9)  # 2 values
    values += struct.pack(">H", 2)
    values += bytes([1, 0])                  # gauge, counter
    values += struct.pack("<d", 36.5)        # gauge is little-endian
    values += struct.pack(">Q", 12345)       # counter is u64 BE
    return (part_str(0x0000, "web01")
            + part_u64(0x0008, int(1700000000 * (2 ** 30)))  # time_hr
            + part_str(0x0002, "cpu")
            + part_str(0x0003, "0")
            + part_str(0x0004, "cpu")
            + part_str(0x0005, "user")
            + values)


def test_parse_collectd_packet():
    records = parse_collectd_packet(collectd_packet())
    assert len(records) == 1
    r = records[0]
    assert r["host"] == "web01"
    assert r["plugin"] == "cpu" and r["plugin_instance"] == "0"
    assert r["type"] == "cpu" and r["type_instance"] == "user"
    assert r["values"] == [36.5, 12345]
    assert abs(r["time"] - 1700000000) < 1


def test_collectd_udp_pipeline():
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("collectd", tag="cd", port="0")
    ins = ctx.engine.inputs[0]
    got = []
    ctx.output("lib", match="cd", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and not getattr(ins.plugin,
                                                     "bound_port", None):
            time.sleep(0.02)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.sendto(collectd_packet(), ("127.0.0.1", ins.plugin.bound_port))
        s.close()
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            time.sleep(0.05)
    finally:
        ctx.stop()
    ev = decode_events(got[0])[0]
    assert ev.body["host"] == "web01"
    assert ev.body["values"] == [36.5, 12345]
    assert abs(ev.ts_float - 1700000000) < 1
