"""Ingest-time conditional routing + memrb eviction.

Reference: split_and_append_route_payloads (src/flb_input_log.c:1495) —
per-record route conditions split payloads into per-route-mask chunks
at ingest; memrb storage evicts oldest chunks with drop metrics
(src/flb_input_chunk.c:2936-2966).
"""

import time

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events, encode_event


def test_route_condition_splits_records():
    """errors output receives ONLY level=error records; the
    unconditional output receives everything."""
    all_recs, err_recs = [], []
    ctx = flb.create(flush="40ms", grace="1")
    in_ffd = ctx.input("lib")
    ctx.output("lib", match="*",
               callback=lambda d, tag: all_recs.extend(decode_events(d)))
    ctx.output("lib", match="*", route_condition="$level eq error",
               callback=lambda d, tag: err_recs.extend(decode_events(d)))
    ctx.start()
    try:
        ctx.push(in_ffd, '{"level": "info", "n": 1}')
        ctx.push(in_ffd, '{"level": "error", "n": 2}')
        ctx.push(in_ffd, '{"level": "error", "n": 3}')
        ctx.push(in_ffd, '{"level": "warn", "n": 4}')
        deadline = time.time() + 5
        while (len(all_recs) < 4 or len(err_recs) < 2) and \
                time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx.stop()
    assert sorted(ev.body["n"] for ev in all_recs) == [1, 2, 3, 4]
    assert sorted(ev.body["n"] for ev in err_recs) == [2, 3]


def test_route_condition_numeric_comparison():
    """route_condition coerces numeric literals: $status gte 500."""
    errs = []
    ctx = flb.create(flush="40ms", grace="1")
    in_ffd = ctx.input("lib")
    ctx.output("lib", match="*", route_condition="$status gte 500",
               callback=lambda d, tag: errs.extend(decode_events(d)))
    ctx.start()
    try:
        ctx.push(in_ffd, '{"status": 200}')
        ctx.push(in_ffd, '{"status": 503}')
        ctx.push(in_ffd, '{"status": 404}')
        deadline = time.time() + 5
        while not errs and time.time() < deadline:
            time.sleep(0.02)
        time.sleep(0.2)
    finally:
        ctx.stop()
    assert [ev.body["status"] for ev in errs] == [503]


def test_memrb_evicts_oldest_with_metrics():
    """memrb storage: appends never pause; over the limit, oldest
    chunks drop and the memrb metrics count them."""
    from fluentbit_tpu.core.engine import Engine

    e = Engine()
    ins = e.input("dummy", **{"storage.type": "memrb",
                              "mem_buf_limit": "8k"})
    for x in e.inputs:
        x.configure()
        x.plugin.init(x, e)
    payload = encode_event({"log": "x" * 900}, 1.0)
    accepted = 0
    for i in range(40):
        got = e.input_log_append(ins, "t", payload, n_records=1)
        assert got == 1, "memrb must never reject an append"
        accepted += 1
    assert accepted == 40
    # buffer stayed bounded and the oldest records were evicted
    assert ins.pool.pending_bytes <= 8 * 1024
    dropped = e.m_memrb_dropped_chunks.get((ins.display_name,))
    assert dropped > 0
    assert e.m_memrb_dropped_bytes.get((ins.display_name,)) > 0
    assert not ins.paused  # memrb never pauses the input
