"""fbtpu-memscope: host copy-census rules, the committed copy-budget
gate, the FBTPU_COPY_WITNESS runtime crosscheck (tier-1 static ⊇
dynamic), and the offset-sidecar replay differential (bit-exact vs the
decode walk).

Reference: ANALYSIS.md "Host-memory pack"; analysis/memscope.py;
core/copywitness.py; core/sidecar.py.
"""

import copy
import glob
import json
import os
import textwrap

import pytest

from fluentbit_tpu.analysis import lint_source
from fluentbit_tpu.analysis.memscope import (
    ELIMINATED, INGEST_ENTRIES, WITNESS_SHAPES, MemscopeRules,
    build_copy_census, census_snapshot, compare_copy_budget,
    witness_crosscheck)
from fluentbit_tpu.analysis.registry import copy_budget_path
from fluentbit_tpu.codec.chunk import Chunk
from fluentbit_tpu.codec.events import encode_event
from fluentbit_tpu.core import copywitness, sidecar
from fluentbit_tpu.core.storage import Storage

# a census-scope module path: the memscope rules key off SCOPES
MOD = "fluentbit_tpu/core/engine.py"


def memscope_findings(src, path=MOD):
    """Lint a fixture and keep only the memscope pack's findings (the
    same source also runs under the guard/locksmith rules)."""
    src = textwrap.dedent(src)
    return [f for f in lint_source(src, path)
            if f.rule in MemscopeRules.RULE_NAMES]


def rules_of(findings):
    return sorted(f.rule for f in findings)


# --------------------------------------------------- host-redundant-copy

def test_redundant_copy_fires():
    fs = memscope_findings("""
        def f(data):
            a = bytes(data)
            b = bytes(data)
            return a, b
    """)
    assert rules_of(fs) == ["host-redundant-copy"]
    assert fs[0].severity == "warning"


def test_redundant_copy_quiet_on_rebind_between():
    fs = memscope_findings("""
        def f(data):
            a = bytes(data)
            data = transform(data)
            b = bytes(data)
            return a, b
    """)
    assert fs == []


def test_redundant_copy_quiet_on_sibling_if_arms():
    # exclusive arms materialize at most once per execution
    fs = memscope_findings("""
        def f(data, cond):
            if cond:
                a = bytes(data)
            else:
                a = bytes(data)
            return a
    """)
    assert fs == []


def test_redundant_copy_suppressed_by_allow():
    fs = memscope_findings("""
        def f(data):
            a = bytes(data)
            # fbtpu-lint: allow(host-redundant-copy)
            b = bytes(data)
            return a, b
    """)
    assert fs == []


# ----------------------------------------------- host-decode-then-restage

def test_decode_restage_fires_on_unpackb_to_packb():
    fs = memscope_findings("""
        def f(raw):
            recs = unpackb(raw)
            return packb(recs)
    """)
    assert rules_of(fs) == ["host-decode-then-restage"]
    assert fs[0].severity == "warning"


def test_decode_restage_fires_on_unpacker_loop():
    fs = memscope_findings("""
        def f(raw):
            out = []
            for rec in Unpacker(raw):
                out.append(packb(rec))
            return out
    """)
    assert rules_of(fs) == ["host-decode-then-restage"]


def test_decode_restage_quiet_without_taint():
    fs = memscope_findings("""
        def f(raw, other):
            recs = unpackb(raw)
            use(recs)
            return packb(other)
    """)
    assert fs == []


# ----------------------------------------------- host-mutable-view-escape

def test_view_escape_fires_on_arena_view_return():
    fs = memscope_findings("""
        def f():
            view = memoryview(_tls.arena)[:64]
            return view
    """)
    assert rules_of(fs) == ["host-mutable-view-escape"]
    assert fs[0].severity == "error"


def test_view_escape_quiet_when_materialized():
    fs = memscope_findings("""
        def f():
            view = memoryview(_tls.arena)[:64]
            return bytes(view)
    """)
    assert fs == []


def test_view_escape_fires_on_stage_field_attr_store():
    fs = memscope_findings("""
        def f(self, data):
            out = stage_field(data)
            self.cache = out
    """)
    assert rules_of(fs) == ["host-mutable-view-escape"]


# -------------------------------------------------- mmap-lifetime-escape

def test_mmap_escape_fires_on_view_attr_store():
    fs = memscope_findings("""
        def f(self, fd):
            mm = mmap.mmap(fd, 0)
            view = memoryview(mm)
            self.cache = view[10:20]
    """)
    assert rules_of(fs) == ["mmap-lifetime-escape"]
    assert fs[0].severity == "error"


def test_mmap_escape_quiet_when_bytes_taken():
    fs = memscope_findings("""
        def f(self, fd):
            mm = mmap.mmap(fd, 0)
            view = memoryview(mm)
            try:
                self.cache = bytes(view[10:20])
            finally:
                view.release()
                mm.close()
    """)
    assert fs == []


# -------------------------------------------------------------- census

def test_census_covers_every_ingest_entry():
    census = build_copy_census()
    entries = {cid.rsplit(".", 1)[-1] for cid in census["chains"]}
    assert entries == set(INGEST_ENTRIES)


def test_census_sites_all_budgeted_and_fresh():
    census = build_copy_census()
    # every instrumented site in source carries a WITNESS_SHAPES budget
    assert not [s for s, d in census["witness_sites"].items()
                if d.get("unbudgeted")]
    # every budget entry still exists in source
    assert census["stale_shapes"] == []
    # and the two sides are exactly the same site set
    assert set(census["witness_sites"]) == set(WITNESS_SHAPES)


def test_committed_copy_budget_is_fresh():
    """analysis/copy_budget.json must match the source of truth — the
    same contract test_lint.py applies to the launch budget."""
    with open(copy_budget_path(), "r", encoding="utf-8") as fh:
        committed = json.load(fh)
    assert committed["census"] == census_snapshot(build_copy_census())
    # the zero-copy work the census paid for stays on the books
    assert committed["eliminated"] == list(ELIMINATED)
    assert len(committed["eliminated"]) >= 2


# ------------------------------------------------------- budget compare

def _snapshot():
    return census_snapshot(build_copy_census())


def test_compare_flags_copy_pass_growth():
    cur, base = _snapshot(), _snapshot()
    cid = next(iter(cur["chains"]))
    cur["chains"][cid]["copy_passes"] += 1
    regressions, notes = compare_copy_budget(cur, base)
    assert any("copy_passes grew" in r for r in regressions)


def test_compare_notes_improvement():
    cur, base = _snapshot(), _snapshot()
    cid = max(cur["chains"],
              key=lambda c: cur["chains"][c]["copy_passes"])
    cur["chains"][cid]["copy_passes"] -= 1
    regressions, notes = compare_copy_budget(cur, base)
    assert regressions == []
    assert any("improved" in n for n in notes)


def test_compare_flags_new_site_and_unbudgeted_site():
    cur, base = _snapshot(), _snapshot()
    cur["witness_sites"]["engine.new.materialize"] = {
        "kind": "copy", "bytes_per_record": 256}
    cur["witness_sites"]["engine.mystery.materialize"] = {
        "kind": "copy", "bytes_per_record": -1}  # unbudgeted marker
    regressions, _ = compare_copy_budget(cur, base)
    assert any("engine.new.materialize" in r and "new" in r
               for r in regressions)
    assert any("engine.mystery.materialize" in r for r in regressions)


def test_compare_notes_vanished_entries():
    cur, base = _snapshot(), _snapshot()
    gone_chain = next(iter(cur["chains"]))
    gone_site = next(iter(cur["witness_sites"]))
    del cur["chains"][gone_chain]
    del cur["witness_sites"][gone_site]
    regressions, notes = compare_copy_budget(cur, base)
    assert regressions == []
    assert any(gone_chain in n for n in notes)
    assert any(gone_site in n for n in notes)


def test_identical_snapshots_compare_clean():
    cur = _snapshot()
    assert compare_copy_budget(cur, copy.deepcopy(cur)) == ([], [])


# ------------------------------------- runtime witness (tier-1 crosscheck)

def _witness_on():
    os.environ["FBTPU_COPY_WITNESS"] = "1"
    copywitness.refresh()
    copywitness.witness_reset()


def _witness_off():
    os.environ.pop("FBTPU_COPY_WITNESS", None)
    copywitness.refresh()
    copywitness.witness_reset()


def test_witness_disabled_records_nothing():
    _witness_off()
    copywitness.count("chunk.append.materialize", 64)
    assert copywitness.witness_counts() == {}


def test_witness_crosscheck_static_superset_of_dynamic(tmp_path):
    """Tier-1: drive a representative ingest + crash-recovery workload
    under FBTPU_COPY_WITNESS and assert every copy the runtime actually
    performed is a budgeted site in the static census."""
    _witness_on()
    try:
        st = Storage(str(tmp_path), checksum=True)
        c = Chunk("app.log", in_name="lib.0")
        data = encode_event({"m": 1}, 1.0) + encode_event({"m": 2}, 2.0)
        # a non-bytes span exercises the chunk-owned-copy site
        c.append(bytearray(data), 2)
        st.write_through(c, data)
        st.finalize(c)
        st.close()
        # recovery: the sidecar fast path materializes the payload once
        got = Storage(str(tmp_path), checksum=True).scan_backlog()
        assert len(got) == 1 and got[0].records == 2
        counts = copywitness.witness_counts()
        assert counts, "workload exercised no instrumented site"
        assert witness_crosscheck(counts) == []
    finally:
        _witness_off()


def test_witness_crosscheck_flags_unknown_site():
    msgs = witness_crosscheck({"engine.rogue.materialize": (3, 768)})
    assert len(msgs) == 1 and "engine.rogue.materialize" in msgs[0]


# -------------------------------------- sidecar replay vs decode replay

def _write_chunk(tmp_path, n_events=3, finalize=True):
    st = Storage(str(tmp_path), checksum=True)
    c = Chunk("app.log", in_name="lib.0")
    data = b"".join(encode_event({"m": i, "pad": "x" * 40}, float(i))
                    for i in range(n_events))
    c.append(data, n_events)
    st.write_through(c, data)
    if finalize:
        st.finalize(c)
    st.close()
    (path,) = glob.glob(str(tmp_path / "streams" / "*" / "*.flb"))
    return path


def _replay(tmp_path, sidecars=True):
    st = Storage(str(tmp_path), checksum=True)
    if not sidecars:
        st.sidecars = False
    got = st.scan_backlog()
    return st, got


def test_sidecar_written_next_to_chunk(tmp_path):
    path = _write_chunk(tmp_path)
    assert os.path.exists(sidecar.sidecar_path(path))


def test_no_sidecar_env_disables_writer(tmp_path, monkeypatch):
    monkeypatch.setenv("FBTPU_NO_SIDECAR", "1")
    path = _write_chunk(tmp_path)
    assert not os.path.exists(sidecar.sidecar_path(path))


def test_sidecar_replay_bit_exact_vs_decode(tmp_path):
    _write_chunk(tmp_path, n_events=5)
    fast_st, fast = _replay(tmp_path, sidecars=True)
    slow_st, slow = _replay(tmp_path, sidecars=False)
    assert fast_st.replay_sidecar_hits == 1
    assert fast_st.replay_decode_walks == 0
    # FINAL chunk + FINAL sidecar with both CRCs valid: believed
    # outright, no walk of any kind
    assert fast_st.replay_sidecar_trusted == 1
    assert slow_st.replay_decode_walks == 1
    assert len(fast) == len(slow) == 1
    assert fast[0].buf == slow[0].buf
    assert fast[0].records == slow[0].records == 5
    assert fast[0].tag == slow[0].tag


def test_unfinalized_replay_validates_and_stays_bit_exact(tmp_path):
    """An open (crash) chunk is never trusted outright: the covered
    region re-counts in C, and the result still matches the walk."""
    _write_chunk(tmp_path, n_events=4, finalize=False)
    fast_st, fast = _replay(tmp_path, sidecars=True)
    slow_st, slow = _replay(tmp_path, sidecars=False)
    assert fast_st.replay_sidecar_hits == 1
    assert fast_st.replay_sidecar_trusted == 0
    assert fast[0].buf == slow[0].buf
    assert fast[0].records == slow[0].records == 4


def test_torn_tail_replay_bit_exact(tmp_path):
    """Truncate mid-record (torn final write): the sidecar path must
    quarantine the torn tail exactly like the decode walk does."""
    path = _write_chunk(tmp_path, n_events=4, finalize=False)
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        f.truncate(f.tell() - 7)  # tear the last record
    fast_st, fast = _replay(tmp_path, sidecars=True)
    slow_st, slow = _replay(tmp_path, sidecars=False)
    assert fast[0].buf == slow[0].buf
    assert fast[0].records == slow[0].records == 3
    # the torn fragment itself never survives into the payload
    assert fast[0].decode()[-1].body["m"] == 2


def test_dropped_sidecar_falls_back_to_decode(tmp_path):
    path = _write_chunk(tmp_path)
    Storage._drop_sidecar(path)
    st, got = _replay(tmp_path, sidecars=True)
    assert st.replay_sidecar_hits == 0
    assert st.replay_decode_walks == 1
    assert got[0].records == 3
