"""fbtpu-xray: the interprocedural device launch-graph analyzer.

Three layers of pinning, mirroring test_lint.py's contract for every
other rule pack:

- **fixtures** — each of the five launch-graph rules fires on a
  known-bad snippet, stays quiet on the good twin, and honors
  ``# fbtpu-lint: allow(...)``;
- **the shipped tree** — the graph's per-chain launch counts, scatter
  passes, and canonical transfer bytes are pinned to today's reality
  (the numbers the committed ``analysis/launch_budget.json`` gates,
  and the numbers the fusion PR — ROADMAP item 1 — must improve);
- **static == dynamic** — the analyzer's launches-per-segment must
  equal the DeviceLane launch counters observed on the simulated
  8-device mesh for the grep, flux, parser-regex, and rewrite_tag
  chains.  A walker bug that over- or under-counts a chain fails HERE,
  not three PRs later when the budget gate lies.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

from fluentbit_tpu.analysis import lint_paths, lint_source
from fluentbit_tpu.analysis.launchgraph import (LaunchGraphRules,
                                                budget_snapshot,
                                                build_launch_graph,
                                                canonical_env,
                                                compare_budget,
                                                graph_to_dot)
from fluentbit_tpu.analysis.registry import BUDGET_PARAMS, budget_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "fluentbit_tpu")

_FIX = "fluentbit_tpu/plugins/filter_fixture.py"


def rules(findings):
    return sorted({f.rule for f in findings})


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------
# device-multi-launch-chain
# ---------------------------------------------------------------------

BAD_MULTI_LAUNCH = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        mask = lane.run(
            lambda: self._program.dispatch_mesh(self._mesh, data,
                                                n_records),
            lambda: self._host(data),
        )
        extra = lane.run(
            lambda: self._counts.dispatch_mesh(self._mesh, data,
                                               n_records),
            lambda: self._host_counts(data),
        )
        return mask, extra
"""

GOOD_SINGLE_LAUNCH = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        return lane.run(
            lambda: self._program.dispatch_mesh(self._mesh, data,
                                                n_records),
            lambda: self._host(data),
        )
"""


def test_multi_launch_chain_fires():
    got = lint_source(BAD_MULTI_LAUNCH, _FIX)
    hits = by_rule(got, "device-multi-launch-chain")
    assert len(hits) == 1
    assert "2 device launches per staged segment" in hits[0].message
    assert hits[0].severity == "warning"


def test_single_launch_chain_quiet():
    got = lint_source(GOOD_SINGLE_LAUNCH, _FIX)
    assert "device-multi-launch-chain" not in rules(got)


def test_multi_launch_interprocedural():
    # the second launch hides two calls deep — the walker must chain
    # through self-method edges to find it
    src = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        mask = self._match(data, n_records)
        return self._sketch(mask)

    def _match(self, data, n):
        lane = self._lane()
        return lane.run(
            lambda: self._program.dispatch_mesh(self._mesh, data, n),
            lambda: self._host(data),
        )

    def _sketch(self, mask):
        lane = self._lane()
        return lane.run(
            lambda: self._counts.dispatch_mesh(self._mesh, mask, 0),
            lambda: self._host_counts(mask),
        )
"""
    got = lint_source(src, _FIX)
    assert "device-multi-launch-chain" in rules(got)


def test_multi_launch_branches_take_max_not_sum():
    # an if/else picking ONE of two launch paths is still a one-launch
    # chain; a branch that returns must not chain into the fallthrough
    src = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        if self._mesh is not None:
            return lane.run(
                lambda: self._program.dispatch_mesh(self._mesh, data,
                                                    n_records),
                lambda: self._host(data),
            )
        return lane.run(
            lambda: self._program.dispatch_jit(data, n_records),
            lambda: self._host(data),
        )
"""
    got = lint_source(src, _FIX)
    assert "device-multi-launch-chain" not in rules(got)


def test_multi_launch_suppression():
    src = BAD_MULTI_LAUNCH.replace(
        "    def filter_raw(self, data, tag, engine, n_records=None):",
        "    # fbtpu-lint: allow(device-multi-launch-chain)\n"
        "    def filter_raw(self, data, tag, engine, n_records=None):")
    got = lint_source(src, _FIX)
    assert "device-multi-launch-chain" not in rules(got)


# ---------------------------------------------------------------------
# device-undonated-buffer
# ---------------------------------------------------------------------

BAD_DONATE_OFF = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        return lane.run(
            lambda: self._program.dispatch_mesh(self._mesh, data,
                                                n_records, donate="off"),
            lambda: self._host(data),
        )
"""


def test_undonated_donate_off_is_an_error():
    got = by_rule(lint_source(BAD_DONATE_OFF, _FIX),
                  "device-undonated-buffer")
    assert len(got) == 1
    assert got[0].severity == "error"
    assert "donation disabled" in got[0].message


def test_undonated_structural_gap_is_a_warning():
    # the default donate set still cannot alias the u8 batch (no
    # same-aval output exists) — a warning pointing at the fusion fix
    got = by_rule(lint_source(GOOD_SINGLE_LAUNCH, _FIX),
                  "device-undonated-buffer")
    assert len(got) == 1
    assert got[0].severity == "warning"
    assert "R*Bp*L" in got[0].message


def test_undonated_suppression():
    src = BAD_DONATE_OFF.replace(
        "            lambda: self._program.dispatch_mesh(self._mesh, "
        "data,\n",
        "            # fbtpu-lint: allow(device-undonated-buffer)\n"
        "            lambda: self._program.dispatch_mesh(self._mesh, "
        "data,\n")
    assert "device-undonated-buffer" not in rules(lint_source(src, _FIX))


# ---------------------------------------------------------------------
# device-host-roundtrip
# ---------------------------------------------------------------------

BAD_ROUNDTRIP = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        mask = lane.run(
            lambda: self._program.dispatch_mesh(self._mesh, data,
                                                n_records),
            lambda: self._host(data),
        )
        keep, n_kept = native.compact(data, mask)
        return keep
"""

GOOD_MASK_ONLY = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        mask = lane.run(
            lambda: self._program.dispatch_mesh(self._mesh, data,
                                                n_records),
            lambda: self._host(data),
        )
        return mask
"""


def test_host_roundtrip_fires_on_compact_after_launch():
    got = by_rule(lint_source(BAD_ROUNDTRIP, _FIX),
                  "device-host-roundtrip")
    assert len(got) == 1
    assert "compact" in got[0].message
    assert got[0].severity == "warning"


def test_host_roundtrip_quiet_without_scatter():
    assert "device-host-roundtrip" not in rules(
        lint_source(GOOD_MASK_ONLY, _FIX))


def test_host_roundtrip_quiet_without_launch():
    # compact on a host-computed mask is not a PCIe roundtrip
    src = """
class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        mask = self._host(data)
        keep, n_kept = native.compact(data, mask)
        return keep
"""
    assert "device-host-roundtrip" not in rules(lint_source(src, _FIX))


def test_host_roundtrip_suppression():
    src = BAD_ROUNDTRIP.replace(
        "        keep, n_kept = native.compact(data, mask)",
        "        # fbtpu-lint: allow(device-host-roundtrip)\n"
        "        keep, n_kept = native.compact(data, mask)")
    assert "device-host-roundtrip" not in rules(lint_source(src, _FIX))


# ---------------------------------------------------------------------
# device-sync-in-staging-loop
# ---------------------------------------------------------------------

BAD_SYNC_IN_LOOP = """
import numpy as np

class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        out = []
        for lo, hi in segment_bounds(n_records, 4096):
            out.append(np.asarray(lane.run(
                lambda: self._program.dispatch_mesh(self._mesh, data,
                                                    hi - lo),
                lambda: self._host(data),
            )))
        return out
"""

GOOD_FORCE_AFTER_LOOP = """
import numpy as np

class F:
    def filter_raw(self, data, tag, engine, n_records=None):
        lane = self._lane()
        flights = []
        for lo, hi in segment_bounds(n_records, 4096):
            flights.append(lane.run(
                lambda: self._program.dispatch_mesh(self._mesh, data,
                                                    hi - lo),
                lambda: self._host(data),
            ))
        return np.asarray(flights)
"""


def test_sync_in_staging_loop_fires():
    got = by_rule(lint_source(BAD_SYNC_IN_LOOP, _FIX),
                  "device-sync-in-staging-loop")
    assert len(got) == 1
    assert got[0].severity == "error"
    assert "asarray" in got[0].message


def test_sync_after_loop_quiet():
    assert "device-sync-in-staging-loop" not in rules(
        lint_source(GOOD_FORCE_AFTER_LOOP, _FIX))


def test_sync_suppression():
    src = BAD_SYNC_IN_LOOP.replace(
        "            out.append(np.asarray(lane.run(",
        "            # fbtpu-lint: allow(device-sync-in-staging-loop)\n"
        "            out.append(np.asarray(lane.run(")
    assert "device-sync-in-staging-loop" not in rules(
        lint_source(src, _FIX))


# ---------------------------------------------------------------------
# stage-redundant-copy
# ---------------------------------------------------------------------

BAD_ARENA_COPY = """
class F:
    def _stage(self, span, key):
        got = native.stage_field(span, key, 96, 8)
        b, ln, offs, n = got
        b = b.copy()
        return b, ln, offs, n
"""

GOOD_STAGE_INTO = """
import numpy as np

class F:
    def _stage(self, span, key, cnt):
        wide = np.empty((cnt, 96), dtype=np.uint8)
        wlen = np.full((cnt,), -1, dtype=np.int32)
        count = native.stage_field_into(span, key, wide, wlen,
                                        n_hint=cnt)
        return wide, wlen, count
"""


def test_arena_copy_fires():
    got = by_rule(lint_source(BAD_ARENA_COPY, _FIX),
                  "stage-redundant-copy")
    assert len(got) == 1
    assert got[0].severity == "error"
    assert "stage_field_into" in got[0].message


def test_stage_into_quiet():
    assert "stage-redundant-copy" not in rules(
        lint_source(GOOD_STAGE_INTO, _FIX))


def test_arena_copy_through_subscript_fires():
    # `.copy()` on a subscript of the tainted arena view still fires
    src = """
class F:
    def _stage(self, span, key):
        b, ln, offs, n = native.stage_field(span, key, 96, 8)
        return b[0].copy()
"""
    assert "stage-redundant-copy" in rules(lint_source(src, _FIX))


def test_arena_copy_suppression():
    src = BAD_ARENA_COPY.replace(
        "        b = b.copy()",
        "        # fbtpu-lint: allow(stage-redundant-copy)\n"
        "        b = b.copy()")
    assert "stage-redundant-copy" not in rules(lint_source(src, _FIX))


def test_copy_on_untainted_buffer_quiet():
    src = """
class F:
    def _stage(self, span, key):
        b = self._scratch
        return b.copy()
"""
    assert "stage-redundant-copy" not in rules(lint_source(src, _FIX))


# ---------------------------------------------------------------------
# scope: the rules live on the plugin/flux planes only
# ---------------------------------------------------------------------

def test_rules_scoped_to_device_planes():
    for src in (BAD_MULTI_LAUNCH, BAD_ROUNDTRIP, BAD_ARENA_COPY):
        assert lint_source(src, "fluentbit_tpu/ops/fixture.py") == []


# ---------------------------------------------------------------------
# the shipped tree: today's launch-graph reality, pinned
# ---------------------------------------------------------------------

def _chain(graph, suffix):
    hits = [c for cid, c in graph["chains"].items()
            if cid.endswith(suffix)]
    assert len(hits) == 1, sorted(graph["chains"])
    return hits[0]


@pytest.fixture(scope="module")
def graph():
    return build_launch_graph()


def test_shipped_grep_chain(graph):
    ch = _chain(graph, "filter_grep.py::GrepFilter.filter_raw")
    assert ch["launches_per_segment"] == 1
    assert ch["staged"] is True
    assert ch["sync_hits"] == []          # overlap intact
    (site,) = [s for s in ch["sites"] if s["kind"] == "grep-mesh"]
    assert site["lane"] is True           # armor-guarded
    # the exact-path compact is the one true roundtrip; the two
    # approx-branch compacts are suppressed in source, not counted out
    assert ch["scatter_passes"] == 3


def test_shipped_flux_chain(graph):
    # post-fuseplan: the counts→hll→cms chain is one fused shard_map
    # program — a single launch, no per-group loop (the per-group HLL
    # and CMS absorbs now ride a masked [Gp, ...] lane inside it)
    ch = _chain(graph, "flux/state.py::FluxState.absorb_batch")
    assert ch["launches_per_segment"] == 1
    kinds = sorted(s["kind"] for s in ch["sites"])
    assert kinds == ["flux-fused"]
    assert not ch["sites"][0]["in_loop"]


def test_shipped_host_only_entries(graph):
    for suffix in ("filter_parser.py::ParserFilter.process_batch",
                   "filter_rewrite_tag.py::RewriteTagFilter"
                   ".process_batch",
                   "flux/plugin.py::FluxFilter.process_batch",
                   "filter_log_to_metrics.py::LogToMetricsFilter"
                   ".process_batch"):
        ch = _chain(graph, suffix)
        assert ch["launches_per_segment"] == 0, suffix
        assert ch["sync_hits"] == [], suffix


def test_shipped_transfer_budget_numbers(graph):
    env = canonical_env()
    assert env["Bp"] == 4096 and env["R"] == 2 and env["L"] == 512
    grep = _chain(graph, "GrepFilter.filter_raw")["transfers"]
    # batch u8 [R,Bp,L] un-donated + lengths i32 [R,Bp] aliased
    assert grep["undonated_h2d_bytes_canonical"] == \
        env["R"] * env["Bp"] * env["L"]
    assert grep["d2h_bytes_canonical"] == 4 * env["R"] * env["Bp"]
    donated = {t["buffer"]: t["donated"] for t in grep["h2d"]}
    assert donated == {"batch": False, "lengths": True}
    flux = _chain(graph, "FluxState.absorb_batch")["transfers"]
    # fused program: seg/valid/lengths/comp_len 4*Bp i32 each, batch +
    # comp Bp*L u8, cms table 8*M_cms — registers are donated; d2h
    # returns counts [Gp] + registers [Gp, M_hll] + table
    assert flux["undonated_h2d_bytes_canonical"] == 4784128
    assert flux["d2h_bytes_canonical"] == 557088


def test_shipped_donation_crosscheck(graph):
    d = graph["donation"]
    # static expectation == live aliasable_donations on the 8-device
    # mesh: only lengths aliases the mask; the u8 batch has no
    # same-aval output to alias (the undonated-buffer warning's basis)
    assert d["lengths_donated"] is True
    assert d["batch_donated"] is False


def test_shipped_table_bytes(graph):
    tables = graph["tables"]
    apache2 = tables["filter_grep[apache2]"]
    # the minimized apache2 DFA: shrink already ran (the carried-over
    # ROADMAP item — rewrite_tag/log_to_metrics compile through the
    # same reducer, reported via m_shrink_* at init)
    assert apache2["rules"][0]["states_eliminated"] > 0
    assert apache2["bytes"] == tables["filter_rewrite_tag[apache2]"][
        "bytes"]
    assert apache2["replicated_bytes"] == \
        apache2["bytes"] * BUDGET_PARAMS["n_dev"]
    assert tables["filter_log_to_metrics[5xx]"]["bytes"] < 1024


# ---------------------------------------------------------------------
# the budget file: round-trip + regression gate
# ---------------------------------------------------------------------

def _committed():
    with open(budget_path(), "r", encoding="utf-8") as fh:
        return json.load(fh)


def test_budget_file_matches_the_tree(graph):
    # `--write-budget` run today must reproduce the committed file
    # byte-for-byte in content: the budget snapshot...
    committed = _committed()
    assert budget_snapshot(graph) == committed["budget"]
    # ...and the findings baseline (the recorded launch-graph debt)
    from fluentbit_tpu.analysis.__main__ import _canon

    names = set(LaunchGraphRules.RULE_NAMES)
    live = {(_canon(f.path), f.rule, f.message)
            for f in lint_paths([PKG]) if f.rule in names}
    recorded = {(d["path"], d["rule"], d["message"])
                for d in committed["findings"]}
    assert live == recorded, "stale launch_budget.json — regenerate " \
        "with: python -m fluentbit_tpu.analysis --write-budget"


def test_budget_self_comparison_clean(graph):
    current = budget_snapshot(graph)
    regressions, notes = compare_budget(current, _committed()["budget"])
    assert regressions == []


def test_budget_catches_regressions(graph):
    current = budget_snapshot(graph)
    key = next(k for k in current["chains"] if "GrepFilter" in k)
    # more launches than the baseline → regression
    base = copy.deepcopy(current)
    base["chains"][key]["launches_per_segment"] = 0
    regs, _ = compare_budget(current, base)
    assert any("launches" in r for r in regs)
    # more un-donated bytes → regression
    base = copy.deepcopy(current)
    base["chains"][key]["undonated_h2d_bytes"] = 1
    regs, _ = compare_budget(current, base)
    assert any("donated" in r for r in regs)
    # a brand-new device chain → regression (no silent growth)
    base = copy.deepcopy(current)
    del base["chains"][key]
    regs, _ = compare_budget(current, base)
    assert regs
    # fewer launches than the baseline → a note, not a failure
    base = copy.deepcopy(current)
    base["chains"][key]["launches_per_segment"] = 9
    regs, notes = compare_budget(current, base)
    assert regs == [] and notes


# ---------------------------------------------------------------------
# CLI plumbing: --graph / --changed / the implicit baseline / --all
# ---------------------------------------------------------------------

def _cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "fluentbit_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, timeout=timeout)


def test_cli_graph_json():
    proc = _cli("--graph", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert "GrepFilter.filter_raw" in "".join(data["chains"])
    assert data["budget_regressions"] == []
    assert data["budget"] == _committed()["budget"]


def test_cli_graph_dot():
    proc = _cli("--graph", "dot")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.lstrip().startswith("digraph")
    assert "grep-mesh" in proc.stdout


def test_cli_default_gate_is_zero_findings_with_baseline():
    # the committed launch_budget.json acts as the implicit baseline:
    # the recorded multi-launch/roundtrip/undonated debt is subtracted,
    # the default invocation stays a zero-findings gate
    proc = _cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 findings" in proc.stdout
    assert "baselined" in proc.stdout


def test_cli_changed_smoke():
    # git-diff-scoped pre-commit run: whatever the tree state, the
    # shipped files must come back clean (baselined debt subtracted)
    proc = _cli("--changed")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_full_gate_budget_comparison():
    # `--all` adds the launch/transfer budget comparison to the PR
    # gate: zero un-baselined findings on the shipped tree (native
    # layers may individually skip, but never silently)
    proc = _cli("--all", "--json", timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["findings"] == []


def test_cli_missing_budget_file_is_a_finding(tmp_path, monkeypatch):
    # the gate must never silently lose its baseline: point the
    # registry at a nonexistent budget file and --all must fail
    import fluentbit_tpu.analysis.__main__ as cli

    monkeypatch.setattr("fluentbit_tpu.analysis.registry.budget_path",
                        lambda: str(tmp_path / "nope.json"))
    findings, notes = cli._budget_findings()
    assert [f.rule for f in findings] == ["launch-budget-regression"]
    assert "missing" in findings[0].message


# ---------------------------------------------------------------------
# static == dynamic: the launch counts must match the lane counters
# on the simulated 8-device mesh
# ---------------------------------------------------------------------

APACHE2 = (
    r'^(?<host>[^ ]*) [^ ]* (?<user>[^ ]*) \[(?<time>[^\]]*)\] '
    r'"(?<method>\S+)(?: +(?<path>[^ ]*) +\S*)?" '
    r'(?<code>[^ ]*) (?<size>[^ ]*)'
    r'(?: "(?<referer>[^\"]*)" "(?<agent>.*)")?$'
)


def _grep_engine():
    from fluentbit_tpu.core.engine import Engine

    e = Engine()
    f = e.filter("grep")
    f.set("regex", f"log {APACHE2}")
    f.set("tpu_batch_records", "1")
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    return e, ins


def _log_chunk(n):
    from fluentbit_tpu.codec.events import encode_event

    ok = ('10.0.0.1 - frank [10/Oct/2000:13:55:36 -0700] '
          '"GET /a HTTP/1.1" 200 23 "http://r" "curl"')
    return b"".join(
        encode_event({"log": ok if i % 4 else f"kernel: oom {i}"},
                     float(i))
        for i in range(n))


def _lane_launches(name):
    from fluentbit_tpu.ops import fault

    return fault.lane(name).stats()["launches"]


@pytest.mark.mesh
def test_static_matches_dynamic_grep_chain(graph, monkeypatch):
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("need a multi-device mesh")
    static = _chain(graph, "GrepFilter.filter_raw")[
        "launches_per_segment"]
    monkeypatch.setenv("FBTPU_MESH", "1")
    monkeypatch.setenv("FBTPU_SEGMENT_RECORDS", "128")
    n, seg = 700, 128
    n_segments = -(-n // seg)
    e, ins = _grep_engine()
    before = _lane_launches("grep")
    e.input_log_append(ins, "bench", _log_chunk(n))
    ins.pool.drain()
    assert e.filters[0].plugin._mesh is not None  # lane engaged
    observed = _lane_launches("grep") - before
    assert observed == n_segments * static, (
        f"analyzer says {static} launch(es)/segment × {n_segments} "
        f"segments, the lane counted {observed}")


@pytest.mark.mesh
def test_static_matches_dynamic_flux_chain(graph, monkeypatch):
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("need a multi-device mesh")
    from fluentbit_tpu.codec.events import encode_event
    from fluentbit_tpu.core.engine import Engine

    static = _chain(graph, "FluxState.absorb_batch")[
        "launches_per_segment"]
    e = Engine()
    f = e.filter("flux")
    for k, v in {"group_by": "tenant", "distinct_field": "user",
                 "topk_field": "user", "window": "tumbling 60",
                 "export_interval_sec": "0", "mesh": "on"}.items():
        f.set(k, v)
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    assert e.filters[0].plugin.state._mesh is not None
    # one tenant → one group → the ×G loops run once; one chunk → one
    # absorbed segment
    raw = b"".join(
        encode_event({"tenant": "a", "user": f"u{i % 13}", "size": i},
                     float(i))
        for i in range(256))
    before = _lane_launches("flux")
    e.input_log_append(ins, "t", raw)
    observed = _lane_launches("flux") - before
    assert observed == static, (
        f"analyzer says {static} launches per absorbed segment, the "
        f"flux lane counted {observed}")


@pytest.mark.mesh
def test_static_matches_dynamic_host_only_chains():
    # parser-regex and rewrite_tag: the analyzer says ZERO device
    # launches — no lane anywhere may tick while they process a batch
    pytest.importorskip("jax")
    from fluentbit_tpu.codec.events import encode_event
    from fluentbit_tpu.core.engine import Engine
    from fluentbit_tpu.ops import fault

    def total_launches():
        return sum(ln.stats()["launches"]
                   for ln in fault.lanes().values())

    e = Engine()
    e.parser("rp", format="regex", regex=r"^(?<w>ERROR) (?<n>\d+)$")
    pf = e.filter("parser")
    pf.set("key_name", "log")
    pf.set("parser", "rp")
    rt = e.filter("rewrite_tag")
    rt.set("rule", "$log ^alpha routed.alpha false")
    ins = e.input("dummy")
    for x in e.inputs + e.filters:
        x.configure()
        x.plugin.init(x, e)
    raw = b"".join(
        encode_event({"log": f"ERROR {i}" if i % 2 else f"alpha {i}"},
                     float(i))
        for i in range(64))
    before = total_launches()
    e.input_log_append(ins, "t", raw)
    ins.pool.drain()
    assert total_launches() - before == 0
