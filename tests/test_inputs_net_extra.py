"""unix_socket input, prometheus text parser + scrape input,
nginx_exporter_metrics, storage.pause_on_chunks_overlimit.
"""

import json
import socket
import threading
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.codec.msgpack import Unpacker
from fluentbit_tpu.plugins.inputs_net_extra import parse_prometheus_text


def wait_for(cond, timeout=6.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.02)
    raise TimeoutError


def test_unix_socket_stream(tmp_path):
    path = str(tmp_path / "flb.sock")
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("unix_socket", tag="t", path=path)
    ins = ctx.engine.inputs[0]
    got = []
    ctx.output("lib", match="t", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        wait_for(lambda: ins.plugin.ready)
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(b'{"via": "unix"}\n')
        s.close()
        wait_for(lambda: got)
    finally:
        ctx.stop()
    assert decode_events(got[0])[0].body == {"via": "unix"}


PROM_TEXT = """\
# HELP http_requests_total Total requests.
# TYPE http_requests_total counter
http_requests_total{method="get",code="200"} 1027 1700000000000
http_requests_total{method="post",code="200"} 3
# TYPE temp_celsius gauge
temp_celsius 36.6
# TYPE rpc_seconds histogram
rpc_seconds_bucket{le="0.1"} 2
rpc_seconds_bucket{le="+Inf"} 5
rpc_seconds_sum 1.5
rpc_seconds_count 5
# a comment
malformed line without value
"""


def test_parse_prometheus_text():
    entries = {e["name"]: e for e in parse_prometheus_text(PROM_TEXT)}
    reqs = entries["http_requests_total"]
    assert reqs["type"] == "counter"
    assert reqs["desc"] == "Total requests."
    assert reqs["labels"] == ["method", "code"]
    vals = {tuple(s["labels"]): s["value"] for s in reqs["values"]}
    assert vals == {("get", "200"): 1027.0, ("post", "200"): 3.0}
    assert entries["temp_celsius"]["values"][0]["value"] == 36.6
    # histogram series inherit the family type
    assert entries["rpc_seconds_bucket"]["type"] == "histogram"
    assert entries["rpc_seconds_count"]["values"][0]["value"] == 5.0


def test_prometheus_scrape_pipeline():
    # stub /metrics endpoint
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)

    def serve():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return
            try:
                c.settimeout(2)
                data = b""
                while b"\r\n\r\n" not in data:
                    data += c.recv(65536)
                body = PROM_TEXT.encode()
                c.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                          + str(len(body)).encode() + b"\r\n\r\n" + body)
            except OSError:
                pass
            c.close()

    threading.Thread(target=serve, daemon=True).start()
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("prometheus_scrape", tag="prom", host="127.0.0.1",
              port=str(srv.getsockname()[1]), scrape_interval="0.2")
    payloads = []
    ctx.output("lib", match="prom", callback=lambda d, t: payloads.append(d))
    ctx.start()
    try:
        wait_for(lambda: payloads)
    finally:
        ctx.stop()
        srv.close()
    obj = next(iter(Unpacker(payloads[0])))
    names = {m["name"] for m in obj["metrics"]}
    assert "http_requests_total" in names and "temp_celsius" in names


def test_pause_on_chunks_overlimit():
    ctx = flb.create(flush="10", grace="1")  # slow flush: chunks pile up
    ctx.service_set(**{"storage.max_chunks_up": "2"})
    in_ffd = ctx.input("lib", tag="t",
                       **{"storage.pause_on_chunks_overlimit": "on"})
    ctx.output("null", match="t")
    ctx.start()
    try:
        accepted = 0
        for i in range(10):
            # big appends: each locks a fresh chunk (2MB target)
            big = json.dumps({"pad": "x" * (2 * 1024 * 1024)})
            if ctx.push(in_ffd, big) > 0:
                accepted += 1
        ins = ctx.engine.inputs[0]
        assert ins.paused
        assert accepted <= 3  # limit 2 chunks (+1 in-flight append)
    finally:
        ctx.stop()
