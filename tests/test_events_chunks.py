"""Log event V2 codec + chunk pool tests
(mirrors tests/internal/log_event_encoder.c / input_chunk coverage)."""

from fluentbit_tpu.codec import (
    CHUNK_TARGET_SIZE,
    Chunk,
    ChunkPool,
    EventTime,
    count_records,
    decode_events,
    encode_event,
    encode_events,
    packb,
    reencode_event,
)


def test_v2_roundtrip():
    buf = encode_event({"log": "hello"}, EventTime(100, 5), {"source": "t"})
    evs = decode_events(buf)
    assert len(evs) == 1
    ev = evs[0]
    assert ev.body == {"log": "hello"}
    assert ev.metadata == {"source": "t"}
    assert ev.timestamp == EventTime(100, 5)
    assert ev.raw == buf
    assert reencode_event(ev) == buf


def test_legacy_v1_decode():
    buf = packb([1234.5, {"msg": "legacy"}])
    evs = decode_events(buf)
    assert evs[0].body == {"msg": "legacy"}
    assert evs[0].ts_float == 1234.5
    assert evs[0].metadata == {}


def test_multiple_events_raw_spans():
    a = encode_event({"i": 1}, 1)
    b = encode_event({"i": 2}, 2)
    c = encode_event({"i": 3}, 3)
    evs = decode_events(a + b + c)
    assert [e.body["i"] for e in evs] == [1, 2, 3]
    assert [e.raw for e in evs] == [a, b, c]
    assert count_records(a + b + c) == 3


def test_group_markers():
    buf = encode_event({}, -1, {"resource": {"x": 1}}) + encode_event(
        {"log": "in group"}, 5
    ) + encode_event({}, -2)
    evs = decode_events(buf)
    assert evs[0].is_group_start()
    assert not evs[1].is_group_start() and not evs[1].is_group_end()
    assert evs[2].is_group_end()


def test_encode_events_batch():
    buf = encode_events([(1, {"a": 1}), (2, {"b": 2})])
    assert count_records(buf) == 2


def test_chunk_pool_tag_keying():
    pool = ChunkPool("in_test")
    c1 = pool.append("app.a", encode_event({"x": 1}), 1)
    c2 = pool.append("app.b", encode_event({"x": 2}), 1)
    c3 = pool.append("app.a", encode_event({"x": 3}), 1)
    assert c1 is c3 and c1 is not c2
    assert c1.records == 2 and c2.records == 1
    drained = pool.drain()
    assert {c.tag for c in drained} == {"app.a", "app.b"}
    assert pool.drain() == []


def test_chunk_lock_at_target_size():
    pool = ChunkPool()
    big = b"\x00" * (CHUNK_TARGET_SIZE // 2 + 1)
    ca = pool.append("t", big, 10)
    assert not ca.locked
    cb = pool.append("t", big, 10)
    assert cb is ca and ca.locked
    cc = pool.append("t", b"\x01", 1)
    assert cc is not ca and not cc.locked
    drained = pool.drain()
    assert ca in drained and cc in drained


def test_chunk_decode():
    pool = ChunkPool()
    pool.append("t", encode_events([(1, {"n": i}) for i in range(5)]), 5)
    (chunk,) = pool.drain()
    assert [e.body["n"] for e in chunk.decode()] == list(range(5))
