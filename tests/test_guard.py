"""fbtpu-guard: flush deadlines, per-output circuit breakers, watchdog
+ load shedding (core/guard.py), plus the satellite hardening — worker
pool startup failover, stuck-shutdown stack dumps, the
``/api/v1/health`` readiness verdict, and the seeded backoff-jitter
property suite.

The fast breaker state-machine suite runs on a fake clock; the engine
integration cases use sub-second deadlines/cooldowns so the whole file
stays tier-1 friendly.
"""

import asyncio
import json
import random
import socket
import threading
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu import failpoints
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.core.guard import (CircuitBreaker, Guard, cancel_requested,
                                      io_deadline)
from fluentbit_tpu.core.plugin import FlushResult, OutputPlugin, registry
from fluentbit_tpu.core.scheduler import backoff_full_jitter


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def wait_for(cond, timeout=8.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise TimeoutError(f"condition not met within {timeout}s")


# ---------------------------------------------------------------------
# CircuitBreaker state machine (fake clock: deterministic + instant)
# ---------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _breaker(**kw):
    clock = _Clock()
    transitions = []
    br = CircuitBreaker(
        "out", on_transition=lambda n, o, new: transitions.append((o, new)),
        clock=clock, **kw)
    return br, clock, transitions


def test_breaker_opens_on_consecutive_failures():
    br, _clock, transitions = _breaker(failures=3, cooldown=5.0)
    for _ in range(2):
        br.record_failure()
    assert br.state_name() == "closed" and br.allow()
    br.record_failure()
    assert br.state_name() == "open"
    assert not br.allow() and not br.available()
    assert transitions == [("closed", "open")]


def test_breaker_opens_on_windowed_error_rate():
    br, _clock, _t = _breaker(failures=100, error_rate=0.5, window=10)
    # alternate: never 100 consecutive, but 50% of the window fails
    for i in range(10):
        (br.record_failure if i % 2 else br.record_ok)()
    assert br.state_name() == "open"


def test_breaker_ok_resets_consecutive_count():
    br, _c, _t = _breaker(failures=3)
    br.record_failure()
    br.record_failure()
    br.record_ok()
    br.record_failure()
    br.record_failure()
    assert br.state_name() == "closed"


def test_breaker_half_open_single_probe_and_recovery():
    br, clock, transitions = _breaker(failures=1, cooldown=5.0)
    br.record_failure()
    assert br.state_name() == "open"
    clock.t = 4.9
    assert not br.allow()
    clock.t = 5.1
    assert br.available()          # non-consuming view
    assert br.allow()              # THE probe
    assert br.state_name() == "half-open"
    assert not br.allow(), "half-open admits exactly one probe"
    br.record_ok()
    assert br.state_name() == "closed"
    assert transitions == [("closed", "open"), ("open", "half-open"),
                           ("half-open", "closed")]


def test_breaker_probe_failure_reopens_with_fresh_cooldown():
    br, clock, _t = _breaker(failures=1, cooldown=5.0)
    br.record_failure()
    clock.t = 6.0
    assert br.allow()
    br.record_failure()            # probe failed: hysteresis
    assert br.state_name() == "open"
    clock.t = 10.0                 # 4s into the FRESH cooldown
    assert not br.allow()
    clock.t = 11.1
    assert br.allow()


def test_breaker_failure_while_open_rearms_cooldown():
    """An HA node re-picked after its cooldown but still failing must
    not be re-admitted on the lapsed timer (mark_down while open)."""
    br, clock, _t = _breaker(failures=1, cooldown=5.0)
    br.record_failure()
    clock.t = 6.0
    assert br.available()
    br.record_failure()            # still sick
    assert not br.available()
    clock.t = 10.0
    assert not br.available()      # cooldown re-armed at t=6
    clock.t = 11.1
    assert br.available()


def test_breaker_probes_threshold_and_reset():
    br, clock, _t = _breaker(failures=1, cooldown=1.0, probes=2)
    br.record_failure()
    clock.t = 1.5
    assert br.allow()
    br.record_ok()
    assert br.state_name() == "half-open", "needs 2 probe successes"
    assert br.allow()
    br.record_ok()
    assert br.state_name() == "closed"
    br.record_failure()
    assert br.state_name() == "open"
    br.reset()                     # HA mark_up semantics
    assert br.state_name() == "closed" and br.allow()


# ---------------------------------------------------------------------
# scheduler backoff: seeded jitter property suite (satellite)
# ---------------------------------------------------------------------


def test_backoff_full_jitter_seeded_properties():
    """Monotone cap + the never-before-base+1 invariant, over seeded
    draws — breaker-driven retry storms are provably bounded."""
    rng = random.Random(1234)
    for base, cap in [(0.05, 0.1), (5.0, 2000.0), (1.0, 1.0),
                      (2.0, 1000.0), (10.0, 5.0)]:
        for attempt in range(1, 48):
            exp = min(cap, base * (2 ** attempt))
            d = backoff_full_jitter(base, cap, attempt, rng)
            # never fires before min(base, cap)+1 (the reference adds
            # one second after drawing from [base, exp])
            assert d >= min(base, exp) + 1.0 - 1e-9, (base, cap, attempt)
            # capped: the draw's upper bound is min(cap, base*2^n)
            assert d <= cap + 1.0 + 1e-9, (base, cap, attempt)
        # the envelope itself is monotone in the attempt number
        exps = [min(cap, base * (2 ** a)) for a in range(1, 48)]
        assert exps == sorted(exps)
    # same seed → same schedule (determinism for soak replays)
    a = [backoff_full_jitter(5, 2000, k, random.Random(7))
         for k in range(1, 24)]
    b = [backoff_full_jitter(5, 2000, k, random.Random(7))
         for k in range(1, 24)]
    assert a == b


# ---------------------------------------------------------------------
# unarmed/disabled guard overhead: zero per-record work at ingest
# ---------------------------------------------------------------------


def test_guard_no_work_on_ingest_hot_path(monkeypatch):
    """Guard checks ride the housekeeping timer and the flush paths —
    the per-record ingest path must never touch the guard."""
    from fluentbit_tpu.core.engine import Engine

    calls = []
    for name in ("housekeeping", "maybe_shed", "track",
                 "short_circuit_delay", "on_result", "breaker",
                 "flight", "consume_timeout"):
        real = getattr(Guard, name)
        monkeypatch.setattr(
            Guard, name,
            (lambda real_fn, nm: lambda self, *a, **kw: (
                calls.append(nm), real_fn(self, *a, **kw))[1])(real, name))

    e = Engine()
    ins = e.input("dummy")
    for x in e.inputs:
        x.configure()
        x.plugin.init(x, e)
    from fluentbit_tpu.codec.events import encode_event

    for i in range(50):
        e.input_log_append(ins, "t", encode_event({"seq": i}, 1.0 + i))
    assert calls == [], f"guard touched on the ingest hot path: {calls}"


def test_guard_disabled_is_inert():
    ctx = flb.create(flush="50ms", grace="1", **{"guard.enable": "off"})
    got = []
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("lib", match="t", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(in_ffd, '{"x": 1}')
        wait_for(lambda: got)
    finally:
        ctx.stop()
    g = ctx.engine.guard
    assert g._breakers == {} and g._flights == {}
    assert g.health() == {"status": "ok", "guard": "disabled"}


# ---------------------------------------------------------------------
# flush deadlines: soft-kill → RETRY; leaked worker threads
# ---------------------------------------------------------------------


def _register_test_outputs():
    from fluentbit_tpu.core.config import ConfigMapEntry

    if "guard_hang" in registry.outputs:
        return

    @registry.register
    class GuardHangOutput(OutputPlugin):
        """Hangs (async) for the first `hang_n` flushes, then delivers."""

        name = "guard_hang"
        config_map = [ConfigMapEntry("hang_n", "int", default=1)]

        def init(self, instance, engine) -> None:
            self.calls = 0
            self.delivered = []

        async def flush(self, data, tag, engine):
            self.calls += 1
            if self.calls <= self.hang_n:
                await asyncio.sleep(60)
            self.delivered.extend(
                ev.body["seq"] for ev in decode_events(data))
            return FlushResult.OK

    @registry.register
    class GuardBlockOutput(OutputPlugin):
        """Blocks its worker thread in SYNC code once (a wedged flush
        the event loop cannot cancel), then delivers; also exercises
        the cooperative cancel flag."""

        name = "guard_block"
        config_map = [ConfigMapEntry("block_s", "double", default=1.0)]

        def init(self, instance, engine) -> None:
            self.calls = 0
            self.delivered = []
            self.saw_cancel_flag = False

        async def flush(self, data, tag, engine):
            self.calls += 1
            if self.calls == 1:
                time.sleep(self.block_s)
                # the soft-kill could not land as a CancelledError
                # while we were in sync code — but the cooperative
                # flag is visible here
                self.saw_cancel_flag = cancel_requested()
            self.delivered.extend(
                ev.body["seq"] for ev in decode_events(data))
            return FlushResult.OK

    @registry.register
    class GuardFlakyOutput(OutputPlugin):
        """RETRY until .ok is flipped, then delivers."""

        name = "guard_flaky"

        def init(self, instance, engine) -> None:
            self.calls = 0
            self.ok = False
            self.delivered = []

        async def flush(self, data, tag, engine):
            self.calls += 1
            if not self.ok:
                return FlushResult.RETRY
            self.delivered.extend(
                ev.body["seq"] for ev in decode_events(data))
            return FlushResult.OK


_register_test_outputs()


def test_flush_deadline_soft_kills_and_requeues_as_retry():
    ctx = flb.create(flush="50ms", grace="1", **{
        "scheduler.base": "0.05", "scheduler.cap": "0.1",
        "guard.breaker_failures": "50",  # deadline path, not the breaker
    })
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("guard_hang", match="t", alias="hang", hang_n="1",
               flush_timeout="0.2s", retry_limit="no_limits")
    plugin = ctx.engine.outputs[0].plugin
    ctx.start()
    try:
        ctx.push(in_ffd, '{"seq": 1}')
        wait_for(lambda: plugin.delivered == [1], timeout=6)
        g = ctx.engine.guard
        assert g.m_timeouts.get(("hang",)) >= 1
        # the slot was reclaimed: task map drains once delivered
        wait_for(lambda: not ctx.engine._task_map, timeout=4)
        # the soft-kill was accounted as a normal RETRY
        assert ctx.engine.m_out_retries.get(("hang",)) >= 1
    finally:
        ctx.stop()


def test_worker_flush_hard_abandon_counts_leaked_thread():
    ctx = flb.create(flush="50ms", grace="2", **{
        "scheduler.base": "0.05", "scheduler.cap": "0.1",
        "guard.leak_grace": "0.1",
        "guard.breaker_failures": "50",
    })
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("guard_block", match="t", alias="blocky", workers="1",
               block_s="1.0", flush_timeout="0.2s",
               retry_limit="no_limits")
    plugin = ctx.engine.outputs[0].plugin
    ctx.start()
    try:
        ctx.push(in_ffd, '{"seq": 9}')
        g = ctx.engine.guard
        # the wedged worker ignores its soft-kill → hard abandon
        wait_for(lambda: g.m_abandoned.get(("blocky",)) >= 1, timeout=4)
        wait_for(lambda: 9 in plugin.delivered, timeout=6)
        assert plugin.saw_cancel_flag, \
            "cooperative cancel flag not visible to the wedged worker"
    finally:
        ctx.stop()


# ---------------------------------------------------------------------
# breaker integration: open → short-circuit → probe → recovery
# ---------------------------------------------------------------------


def test_breaker_short_circuits_and_recovers_via_probe():
    # retry timers fire at backoff+1s (the reference's jitter floor),
    # so a 2s cooldown guarantees the first post-open retry lands
    # INSIDE the open window and must short-circuit
    ctx = flb.create(flush="50ms", grace="1", **{
        "scheduler.base": "0.05", "scheduler.cap": "0.1",
        "guard.breaker_failures": "2", "guard.breaker_cooldown": "2",
    })
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("guard_flaky", match="t", alias="flaky",
               retry_limit="no_limits")
    plugin = ctx.engine.outputs[0].plugin
    ctx.start()
    try:
        g = ctx.engine.guard
        ctx.push(in_ffd, '{"seq": 5}')
        wait_for(lambda: g.breaker("flaky").state_name() == "open",
                 timeout=5)
        calls_at_open = plugin.calls
        # while open, dispatch short-circuits: scheduled retries, no
        # flush attempts (no probe before the 2s cooldown)
        wait_for(lambda: g.m_short_circuit.get(("flaky",)) >= 1,
                 timeout=4)
        assert plugin.calls == calls_at_open, \
            "open breaker must not burn flush attempts"
        plugin.ok = True  # destination recovers
        wait_for(lambda: plugin.delivered == [5], timeout=8)
        wait_for(lambda: g.breaker("flaky").state_name() == "closed",
                 timeout=5)
        assert g.m_transitions.get(("flaky", "open")) >= 1
        assert g.m_transitions.get(("flaky", "closed")) >= 1
    finally:
        ctx.stop()


# ---------------------------------------------------------------------
# load shedding: open-breaker chunks spill, readmit on recovery
# ---------------------------------------------------------------------


def test_dispatch_sheds_open_breaker_routes_and_readmits():
    ctx = flb.create(flush="50ms", grace="1", **{
        "task_map_size": "4", "guard.shed_watermark": "0.5",
        "guard.breaker_failures": "1", "guard.breaker_cooldown": "30",
        "scheduler.base": "0.05", "scheduler.cap": "0.1",
    })
    got = []
    in_ffds = [ctx.input("lib", tag=f"t{i}") for i in range(4)]
    ctx.output("lib", match="t*", alias="sink",
               callback=lambda d, t: got.extend(
                   ev.body["seq"] for ev in decode_events(d)))
    ctx.start()
    try:
        g = ctx.engine.guard
        # force the sink's breaker open (cooldown 30s: stays open)
        g.breaker("sink").record_failure()
        assert g.breaker("sink").state_name() == "open"
        for i, ffd in enumerate(in_ffds):
            ctx.push(ffd, json.dumps({"seq": i}))
        # watermark = 2 of 4 slots: the first chunks park as
        # short-circuited retries, the rest shed; the watchdog then
        # reclaims the retry-held slots too
        wait_for(lambda: g.shed_count() >= 3, timeout=4)
        wait_for(lambda: sum(g.m_shed.get((n,))
                             for n in ("sink",)) >= 3, timeout=2)
        assert not got, "open breaker must not deliver"
        # recovery: close the breaker → shed chunks readmit + deliver
        g.breaker("sink").reset()
        wait_for(lambda: sorted(got) == [0, 1, 2, 3], timeout=6)
        wait_for(lambda: not ctx.engine._task_map, timeout=4)
        assert g.shed_count() == 0
    finally:
        ctx.stop()


# ---------------------------------------------------------------------
# watchdog health verdict + admin endpoint
# ---------------------------------------------------------------------


def _http_get(port, path):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n"
              f"Connection: close\r\n\r\n".encode())
    data = b""
    while True:
        b = s.recv(65536)
        if not b:
            break
        data += b
    s.close()
    head, _, body = data.partition(b"\r\n\r\n")
    return int(head.split(b" ", 2)[1]), body


def test_health_verdicts_ok_degraded_stalled():
    # flush=10s: housekeeping will not refresh the heartbeat mid-test
    ctx = flb.create(flush="10s", grace="1", http_server="on",
                     http_port="0")
    ctx.input("lib", tag="t")
    ctx.output("lib", match="t", callback=lambda d, t: None)
    ctx.start()
    try:
        port = wait_for(
            lambda: ctx.engine.admin_server
            and ctx.engine.admin_server.bound_port)
        status, body = _http_get(port, "/api/v1/health")
        assert (status, body) == (200, b"ok\n")
        status, body = _http_get(port, "/api/v1/health/guard")
        assert status == 200
        obj = json.loads(body)
        assert obj["status"] == "ok" and obj["breakers"] == {}
        assert obj["task_map"]["size"] == 2048

        # degraded: a breaker left closed state
        g = ctx.engine.guard
        for _ in range(ctx.engine.service.guard_breaker_failures):
            g.breaker("sick.0").record_failure()
        status, body = _http_get(port, "/api/v1/health")
        assert status == 200
        obj = json.loads(body)
        assert obj["status"] == "degraded"
        assert obj["breakers"]["sick.0"] == "open"

        # stalled: heartbeat far older than guard.stall_after
        g.breaker("sick.0").reset()
        g.heartbeat = time.time() - 100
        status, body = _http_get(port, "/api/v1/health")
        assert status == 503
        assert json.loads(body)["status"] == "stalled"
        g.heartbeat = time.time()
    finally:
        ctx.stop()


def test_deadline_resolution_order():
    ctx = flb.create(grace="3", **{"guard.flush_timeout": "7s"})
    out_ffd = ctx.output("lib", callback=lambda d, t: None)
    out = ctx.engine.outputs[0]
    out.set("flush_timeout", "2s")
    out.configure()
    g = ctx.engine.guard
    assert g.deadline_for(out) == 2.0          # per-output wins
    out.flush_timeout = None
    assert g.deadline_for(out) == 7.0          # service-level next
    ctx.engine.service.guard_flush_timeout = 0.0
    assert g.deadline_for(out) == 6.0          # 2 × grace default


# ---------------------------------------------------------------------
# satellite: worker pool startup failure → inline failover
# ---------------------------------------------------------------------


def test_worker_start_timeout_fails_over_to_inline_flush():
    failpoints.enable("output.worker_start", "delay(1000)")
    got = []
    ctx = flb.create(flush="50ms", grace="1",
                     **{"guard.worker_start_timeout": "0.3s"})
    in_ffd = ctx.input("lib", tag="t")
    ctx.output("lib", match="t", alias="w", workers="1",
               callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        failpoints.reset()
        out = ctx.engine.outputs[0]
        assert out.worker_pool is None, \
            "a pool whose workers never started must not be installed"
        assert ctx.engine.guard.m_worker_start_fail.get(("w",)) == 1
        ctx.push(in_ffd, '{"seq": 3}')
        wait_for(lambda: got)  # delivery fell over to inline flushes
        bodies = [ev.body for d in got for ev in decode_events(d)]
        assert {"seq": 3} in bodies
    finally:
        ctx.stop()


def test_worker_start_injected_death_aborts_fast():
    from fluentbit_tpu.core.output_thread import OutputWorkerPool

    failpoints.enable("output.worker_start", "return(dead)")
    t0 = time.time()
    pool = OutputWorkerPool("dead-test", 1, None, start_timeout=10.0)
    try:
        assert pool.failed
        assert time.time() - t0 < 5, "abort must beat the timeout"
        with pytest.raises(RuntimeError, match="never started"):
            async def noop():
                return 1

            pool.submit(noop())
    finally:
        pool.stop()


# ---------------------------------------------------------------------
# satellite: stuck shutdown dumps thread stacks
# ---------------------------------------------------------------------


def test_stuck_shutdown_warns_and_dumps_stacks(caplog, capfd):
    import logging

    from fluentbit_tpu.core.engine import Engine

    class _WedgedThread:
        def join(self, timeout=None):
            pass  # "times out": returns with the thread still alive

        def is_alive(self):
            return True

    e = Engine()
    e._thread = _WedgedThread()
    with caplog.at_level(logging.WARNING, logger="flb.engine"):
        e.stop()
    assert any("shutdown is stuck" in r.message for r in caplog.records)
    err = capfd.readouterr().err
    assert "Current thread" in err or "Thread" in err, \
        "faulthandler stack dump missing from stderr"
    assert e._thread is None


# ---------------------------------------------------------------------
# io_deadline helper (the await-no-deadline escape hatch)
# ---------------------------------------------------------------------


def test_io_deadline_raises_oserror_compatible_timeout():
    async def run():
        with pytest.raises(OSError):
            await io_deadline(asyncio.sleep(5), 0.01)
        return await io_deadline(_value(), 1.0)

    async def _value():
        return 42

    assert asyncio.run(run()) == 42


def test_shed_readmission_is_priority_ordered():
    """Regression (fbtpu-qos satellite): probe-ready shed chunks used
    to readmit in FIFO shed order regardless of priority; they must
    re-enter the backlog highest-priority-first so recovery bandwidth
    goes to the classes that matter."""
    from fluentbit_tpu.codec.chunk import Chunk
    from fluentbit_tpu.codec.events import encode_event

    ctx = flb.create()
    e = ctx.engine
    g = e.guard
    # breaker-shed entries in deliberately unsorted FIFO shed order; no
    # breaker exists for the route, which counts as probe-ready
    for prio in (5, 0, 7, 2):
        c = Chunk("t")
        c.append(encode_event({"p": prio}, None), 1)
        c.priority = prio
        c.route_names = ("out.0",)
        with g._lock:
            g._shed.append((c, "breaker"))
    g._shed_pass(time.time(), occupancy=0, on_loop=False)
    assert g.shed_count() == 0
    assert [c.priority for c in e._backlog] == [0, 2, 5, 7]
