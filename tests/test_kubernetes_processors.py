"""filter_kubernetes (BASELINE config 5), processors
(content_modifier / labels / metrics_selector), and the extra filters
(type_converter / checklist / alter_size / throttle_size / sysinfo).
"""

import json
import os

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.codec.msgpack import Unpacker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

K8S_TAG = ("kube.var.log.containers."
           "web-5c7f9_prod_nginx-0123456789abcdef0123456789abcdef"
           "0123456789abcdef0123456789abcdef.log")


def write_meta(tmp_path, namespace="prod", pod="web-5c7f9", **kw):
    meta = {
        "metadata": {
            "uid": "pod-uid-1",
            "labels": {"app": "web"},
            "annotations": kw.get("annotations", {"team": "core"}),
        },
        "spec": {"nodeName": "node-7"},
    }
    d = tmp_path / "cache"
    d.mkdir(exist_ok=True)
    (d / f"{namespace}_{pod}.meta").write_text(json.dumps(meta))
    return str(d)


def run_k8s(tmp_path, records, tag=K8S_TAG, **props):
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag=tag)
    ctx.filter("kubernetes", match="kube.*",
               kube_meta_preload_cache_dir=write_meta(tmp_path), **props)
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        for r in records:
            ctx.push(in_ffd, json.dumps(r))
        ctx.flush_now()
    finally:
        ctx.stop()
    return [e for d in got for e in decode_events(d)]


def test_k8s_enrichment_from_cache(tmp_path):
    evs = run_k8s(tmp_path, [{"log": "hello"}])
    k8s = evs[0].body["kubernetes"]
    assert k8s["pod_name"] == "web-5c7f9"
    assert k8s["namespace_name"] == "prod"
    assert k8s["container_name"] == "nginx"
    assert k8s["pod_id"] == "pod-uid-1"
    assert k8s["host"] == "node-7"
    assert k8s["labels"] == {"app": "web"}


def test_k8s_merge_log_json(tmp_path):
    evs = run_k8s(tmp_path, [{"log": '{"level": "info", "msg": "m"}'}],
                  merge_log="on")
    body = evs[0].body
    assert body["level"] == "info" and body["msg"] == "m"
    assert "log" in body  # keep_log default on
    evs2 = run_k8s(tmp_path, [{"log": '{"a": 1}'}], merge_log="on",
                   keep_log="off")
    assert "log" not in evs2[0].body and evs2[0].body["a"] == 1


def test_k8s_non_matching_tag_untouched(tmp_path):
    evs = run_k8s(tmp_path, [{"log": "x"}], tag="other.tag")
    assert "kubernetes" not in evs[0].body


def test_k8s_exclude_annotation(tmp_path):
    cache = write_meta(
        tmp_path, annotations={"fluentbit.io/exclude": "true"})
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag=K8S_TAG)
    ctx.filter("kubernetes", match="kube.*",
               kube_meta_preload_cache_dir=cache,
               **{"k8s-logging.exclude": "on"})
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"log": "x"}))
        ctx.flush_now()
    finally:
        ctx.stop()
    assert got == []


def test_baseline5_constructible():
    from fluentbit_tpu.config_format import apply_to_context, load_config_file

    ctx = flb.create()
    apply_to_context(
        ctx,
        load_config_file(os.path.join(REPO, "conf", "baseline5-k8s.conf")),
        os.path.join(REPO, "conf"),
    )
    assert [i.plugin.name for i in ctx.engine.inputs] == ["forward"]
    assert [f.plugin.name for f in ctx.engine.filters] == ["kubernetes", "grep"]


# ------------------------------------------------------------- processors

def make_processor(name, **props):
    from fluentbit_tpu.core.plugin import registry

    proc = registry.create_processor(name)
    for k, v in props.items():
        proc.set(k, v)
    proc.configure()
    proc.plugin.init(proc, None)
    return proc.plugin


def ev_of(body, ts=1.0):
    from fluentbit_tpu.codec.events import encode_event

    return decode_events(encode_event(body, ts))[0]


def test_content_modifier_actions():
    p = make_processor("content_modifier", action="upsert", key="env",
                       value="prod")
    out = p.process_logs([ev_of({"a": 1})], "t", None)
    assert out[0].body == {"a": 1, "env": "prod"}

    p2 = make_processor("content_modifier", action="rename", key="old",
                        value="new")
    assert p2.process_logs([ev_of({"old": 5})], "t", None)[0].body == {"new": 5}

    p3 = make_processor("content_modifier", action="hash", key="secret")
    hashed = p3.process_logs([ev_of({"secret": "x"})], "t", None)[0].body
    assert len(hashed["secret"]) == 64

    p4 = make_processor("content_modifier", action="extract", key="log",
                        pattern=r"(?<verb>\w+) (?<path>/\S*)")
    out4 = p4.process_logs([ev_of({"log": "GET /x HTTP"})], "t", None)
    assert out4[0].body["verb"] == "GET" and out4[0].body["path"] == "/x"

    p5 = make_processor("content_modifier", action="convert", key="n",
                        converted_type="int")
    assert p5.process_logs([ev_of({"n": "42"})], "t", None)[0].body["n"] == 42


def test_yaml_processors_wired(tmp_path):
    conf = tmp_path / "p.yaml"
    conf.write_text("""
service:
  flush: 0.05
  grace: 1
pipeline:
  inputs:
    - name: lib
      tag: t
      processors:
        logs:
          - name: content_modifier
            action: upsert
            key: stamped
            value: "yes"
  outputs:
    - name: lib
      match: "*"
""")
    from fluentbit_tpu.config_format import apply_to_context, load_config_file

    ctx = flb.create()
    apply_to_context(ctx, load_config_file(str(conf)), str(tmp_path))
    got = []
    ctx.engine.outputs[0].set("callback", lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(0, json.dumps({"m": 1}))
        ctx.flush_now()
    finally:
        ctx.stop()
    evs = [e for d in got for e in decode_events(d)]
    assert evs[0].body == {"m": 1, "stamped": "yes"}


def test_labels_and_selector_processors():
    payload = {"meta": {}, "metrics": [
        {"name": "a_hits", "labels": ["svc"],
         "values": [{"labels": ["api"], "value": 2}]},
        {"name": "b_errs", "labels": [],
         "values": [{"labels": [], "value": 1}]},
    ]}
    lp = make_processor("labels", insert="env prod")
    (out,) = lp.process_metrics([payload], "t", None)
    m = out["metrics"][0]
    assert m["labels"] == ["svc", "env"]
    assert m["values"][0]["labels"] == ["api", "prod"]

    sel = make_processor("metrics_selector", metric_name="hits")
    (out2,) = sel.process_metrics([out], "t", None)
    assert [m["name"] for m in out2["metrics"]] == ["a_hits"]


# ------------------------------------------------------------ extra filters

def run_filter(name, records, **props):
    from fluentbit_tpu.core.plugin import registry

    ins = registry.create_filter(name)
    for k, v in props.items():
        if isinstance(v, list):
            for item in v:
                ins.set(k, item)
        else:
            ins.set(k, v)
    ins.configure()
    ins.plugin.init(ins, None)
    events = [ev_of(r) for r in records]
    _, out = ins.plugin.filter(events, "t", None)
    return out


def test_type_converter():
    out = run_filter("type_converter", [{"code": "200", "f": "1.5"}],
                     int_key="code code_n", float_key="f f_n")
    assert out[0].body["code_n"] == 200
    assert out[0].body["f_n"] == 1.5


def test_checklist(tmp_path):
    lst = tmp_path / "bad.txt"
    lst.write_text("10.0.0.9\n# comment\n10.0.0.1\n")
    out = run_filter("checklist",
                     [{"ip": "10.0.0.1"}, {"ip": "8.8.8.8"}],
                     file=str(lst), lookup_key="ip",
                     record=["flagged true"])
    assert out[0].body["flagged"] == "true"
    assert "flagged" not in out[1].body


def test_alter_size():
    out = run_filter("alter_size", [{"i": i} for i in range(5)], remove="2")
    assert [e.body["i"] for e in out] == [2, 3, 4]
    out2 = run_filter("alter_size", [{"i": 0}], add="2")
    assert len(out2) == 3


def test_throttle_size():
    out = run_filter("throttle_size",
                     [{"log": "x" * 100} for _ in range(10)],
                     rate="350", window="60")
    assert len(out) == 3  # 3 × 100 bytes fit the 350-byte budget


def test_sysinfo():
    out = run_filter("sysinfo", [{"m": 1}], hostname_key="host",
                     os_name_key="os")
    assert out[0].body["os"] == "linux"
    assert out[0].body["host"]
