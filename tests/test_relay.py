"""fbtpu-relay: the fault-hardened fluent-forward fan-in tier.

Covers the hop's effectively-once machinery (stable chunk-ids, the
durable dedup ledger, ack-lost redelivery absorbing once), the armored
client (breaker/HA/backoff, partition spool + heal replay,
CompressedPackedForward), tenant/priority stamp propagation across the
wire, backpressure-as-withheld-ack, the ``forward`` health block +
metric family, the new failpoint site inventory, and the tier-1 slice
of the multi-process chaos soak (``failpoints/soak.py``
``run_relay_scenario``) — the full 3-seed matrix rides the
``slow``/``soak`` markers.
"""

import gzip
import json
import os
import socket
import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu import failpoints
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.core.relay import (DedupLedger, ForwardSpool,
                                      load_ledger_counts,
                                      stable_chunk_id)
from fluentbit_tpu.failpoints import soak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    failpoints.reset()
    yield
    failpoints.reset()


def wait_for(cond, timeout=8.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise TimeoutError("condition not met")


def events_of(got):
    return [(t, e) for t, d in got for e in decode_events(d)]


def collect_ctx(tmp_path=None, **props):
    """One aggregator-side ctx: forward input → lib collector."""
    svc = {"flush": "50ms", "grace": "1"}
    if tmp_path is not None:
        svc["storage.path"] = str(tmp_path / "agg-storage")
    ctx = flb.create(**svc)
    ctx.input("forward", tag="t", listen="127.0.0.1", port="0", **props)
    ins = ctx.engine.inputs[0]
    got = []
    ctx.output("lib", match="*", callback=lambda d, t: got.append((t, d)))
    ctx.start()
    port = wait_for(lambda: getattr(ins.plugin, "bound_port", None))
    return ctx, port, got


def client_ctx(port, in_props=None, **out_props):
    ctx = flb.create(flush="50ms", grace="1")
    ffd = ctx.input("lib", tag="fwd.test", **(in_props or {}))
    ctx.output("forward", match="*", host="127.0.0.1", port=str(port),
               **out_props)
    ctx.start()
    return ctx, ffd


# ----------------------------------------------------- chunk identity


def test_stable_chunk_id_is_content_addressed():
    a = stable_chunk_id("tag.a", b"payload")
    assert a == stable_chunk_id("tag.a", b"payload")  # resend-stable
    assert a != stable_chunk_id("tag.b", b"payload")
    assert a != stable_chunk_id("tag.a", b"payload2")
    # (tag, payload) boundary is framed, not concatenated
    assert stable_chunk_id("x", b"yz") != stable_chunk_id("xy", b"z")
    assert len(a) == 32


# ----------------------------------------------------- dedup ledger


def test_ledger_dedup_and_ttl(tmp_path):
    t = [100.0]
    led = DedupLedger(str(tmp_path), ttl=10.0, clock=lambda: t[0])
    assert not led.seen("c1")
    led.record("c1")
    assert led.seen("c1")
    assert led.dedup_hits == 1
    t[0] += 11.0  # past the retry window: the entry expires
    assert not led.seen("c1")
    assert led.size() == 0


def test_ledger_survives_restart(tmp_path):
    led = DedupLedger(str(tmp_path), ttl=300.0)
    led.record("c-restart")
    # a new process over the same storage root sees the absorb
    led2 = DedupLedger(str(tmp_path), ttl=300.0)
    assert led2.seen("c-restart")
    counts = load_ledger_counts(str(tmp_path))
    assert counts == {"c-restart": 1}


def test_ledger_double_absorb_stays_visible(tmp_path):
    led = DedupLedger(str(tmp_path), ttl=300.0)
    led.record("c2")
    led.record("c2")  # a bug upstream: the ledger must not hide it
    assert led.snapshot()["c2"] == 2
    assert load_ledger_counts(str(tmp_path))["c2"] == 2


def test_forward_spool_roundtrip(tmp_path):
    sp = ForwardSpool(str(tmp_path))
    blob = b"\x92\x01\x02" * 5
    f = sp.put("t.x", blob, [3, 9, 15], {"tag": "t.x", "chunk": "cid1"})
    assert [p.name for p in sp.pending()] == [f.name]
    got = ForwardSpool.load(f)
    assert got is not None
    payload, n, meta = got
    assert payload == blob and n == 3
    assert meta["chunk"] == "cid1" and meta["tag"] == "t.x"
    # sequence resumes past existing files after a restart
    sp2 = ForwardSpool(str(tmp_path))
    f2 = sp2.put("t.x", blob, [15], {})
    assert int(f2.name) == int(f.name) + 1
    ForwardSpool.drop(f)
    ForwardSpool.drop(f2)
    assert sp2.pending() == []


# ------------------------------------------- effectively-once over the wire


def test_ack_lost_redelivery_absorbs_once(tmp_path):
    """forward.ack_drop swallows the first ack: the client's ack
    timeout forces a resend of the SAME chunk (same content digest) —
    the aggregator's ledger absorbs it exactly once and acks the
    redelivery from the dedup path."""
    ctx_srv, port, got = collect_ctx(tmp_path)
    failpoints.enable("forward.ack_drop", "1*return")
    ctx_cli, ffd = client_ctx(port, require_ack_response="true",
                              ack_timeout="0.4")
    try:
        ctx_cli.push(ffd, json.dumps({"seq": 1}))
        ctx_cli.flush_now()
        srv = ctx_srv.engine.inputs[0].plugin
        # the redelivery must hit the ledger, not the engine
        wait_for(lambda: srv._ledger.dedup_hits >= 1)
        assert srv.n_absorbed == 1
        wait_for(lambda: events_of(got))
        assert [e.body["seq"] for _, e in events_of(got)] == [1]
        # the armed site is pinned in the ledger meta: exactly one absorb
        counts = srv._ledger.snapshot()
        assert list(counts.values()) == [1]
    finally:
        ctx_cli.stop()
        ctx_srv.stop()
    # delivery stayed single even though the wire saw the chunk twice
    assert [e.body["seq"] for _, e in events_of(got)] == [1]


def test_dup_delivery_failpoint_dedups(tmp_path):
    """forward.dup_delivery makes the CLIENT send every chunk twice on
    the same connection — the second copy must ack from the ledger."""
    ctx_srv, port, got = collect_ctx(tmp_path)
    failpoints.enable("forward.dup_delivery", "1*return")
    ctx_cli, ffd = client_ctx(port, require_ack_response="true",
                              ack_timeout="2")
    try:
        ctx_cli.push(ffd, json.dumps({"seq": 7}))
        ctx_cli.flush_now()
        srv = ctx_srv.engine.inputs[0].plugin
        wait_for(lambda: srv._ledger.dedup_hits >= 1)
        assert srv.n_absorbed == 1
        wait_for(lambda: events_of(got))
        assert [e.body["seq"] for _, e in events_of(got)] == [7]
    finally:
        ctx_cli.stop()
        ctx_srv.stop()


# ------------------------------------------------- satellite: stamps


def test_tenant_priority_stamps_cross_the_hop(tmp_path):
    """The chunk's qos_tenant/priority stamps ride the option map and
    are restored onto the chunk the AGGREGATOR builds, so storage
    quotas and shed-by-priority keep acting on the original tenant."""
    from fluentbit_tpu.core.config import ConfigMapEntry
    from fluentbit_tpu.core.plugin import (FLUSH_CHUNK, FlushResult,
                                           OutputPlugin, registry)

    seen = []
    if "stamp_spy" not in registry.outputs:
        @registry.register
        class StampSpy(OutputPlugin):
            name = "stamp_spy"
            description = "records the flushed chunk's QoS stamps"
            config_map = [ConfigMapEntry("sink", "str")]

            async def flush(self, data, tag, engine) -> FlushResult:
                ch = FLUSH_CHUNK.get()
                engine._stamp_spy.append(
                    (getattr(ch, "qos_tenant", None),
                     getattr(ch, "priority", None)))
                return FlushResult.OK

    ctx_srv = flb.create(flush="50ms", grace="1",
                         **{"storage.path": str(tmp_path / "s")})
    ctx_srv.input("forward", tag="t", listen="127.0.0.1", port="0")
    ctx_srv.output("stamp_spy", match="*")
    ctx_srv.engine._stamp_spy = seen
    ctx_srv.start()
    port = wait_for(
        lambda: ctx_srv.engine.inputs[0].plugin.bound_port)
    ctx_cli, ffd = client_ctx(
        port,
        in_props={"tenant": "acme", "tenant.priority": "2"},
        require_ack_response="true")
    try:
        ctx_cli.push(ffd, json.dumps({"seq": 1}))
        ctx_cli.flush_now()
        wait_for(lambda: seen)
        assert ("acme", 2) in seen
    finally:
        ctx_cli.stop()
        ctx_srv.stop()


# --------------------------------------------- satellite: compression


def test_compressed_packedforward_roundtrip(tmp_path):
    """``compress gzip`` → CompressedPackedForward on the wire; the
    decoded record stream is bit-exact against the uncompressed path."""
    ctx_srv, port, got = collect_ctx(tmp_path)
    ctx_cli, ffd = client_ctx(port, compress="gzip",
                              require_ack_response="true")
    try:
        bodies = [{"seq": i, "blob": "x" * 100} for i in range(20)]
        for b in bodies:
            ctx_cli.push(ffd, json.dumps(b))
        ctx_cli.flush_now()
        wait_for(lambda: len(events_of(got)) >= len(bodies))
    finally:
        ctx_cli.stop()
        ctx_srv.stop()
    assert [e.body for _, e in events_of(got)] == bodies


def test_frame_gzip_is_bit_exact_and_id_stable():
    """Unit-level: the frame's entry stream gunzips back to the exact
    packed bytes, and the stable chunk-id is computed over the
    UNCOMPRESSED entries (compression settings don't change identity)."""
    from fluentbit_tpu.codec.msgpack import Unpacker
    from fluentbit_tpu.plugins.net_forward import ForwardOutput

    blob = b"\x93\x01\x02\x03" * 40
    cid = stable_chunk_id("t.gz", blob)
    plain = object.__new__(ForwardOutput)
    plain.compress = None
    plain.time_as_integer = False
    gz = object.__new__(ForwardOutput)
    gz.compress = "gzip"
    gz.time_as_integer = False
    u1, u2 = Unpacker(), Unpacker()
    u1.feed(plain._frame("t.gz", blob, 40, cid, None, None))
    u2.feed(gz._frame("t.gz", blob, 40, cid, "acme", 3))
    (ptag, pents, popt), = list(u1)
    (gtag, gents, gopt), = list(u2)
    assert pents == blob
    assert gopt["compressed"] == "gzip"
    assert gzip.decompress(gents) == blob
    # identity follows the uncompressed bytes on both paths
    assert popt["chunk"] == gopt["chunk"] == cid
    assert gopt["tenant"] == "acme" and gopt["priority"] == 3
    assert popt["size"] == gopt["size"] == 40


# -------------------------------------------- satellite: backpressure


def test_backpressure_withholds_ack(tmp_path):
    """A remote chunk whose tenant is over quota (overflow=defer) must
    NOT be acked unconditionally: the ack is delayed up to
    defer_ack_window, then withheld — the peer's own ack timeout is the
    backpressure signal."""
    ctx_srv = flb.create(flush="50ms", grace="1",
                         **{"storage.path": str(tmp_path / "s")})
    ctx_srv.input("forward", tag="t", listen="127.0.0.1", port="0",
                  defer_ack_window="0.3")
    got = []
    ctx_srv.output("lib", match="*", callback=lambda d, t: got.append(d))
    ctx_srv.start()
    # declare the tenant's (tiny) contract up front and push its token
    # bucket deep into debt (try_take's oversized-cost rule admits one
    # full-bucket take, so a fresh bucket would admit the first chunk)
    t = ctx_srv.engine.qos.tenant("slow", rate=1.0, overflow="defer")
    assert t.bucket.try_take(100_000)
    port = wait_for(
        lambda: ctx_srv.engine.inputs[0].plugin.bound_port)
    ctx_cli, ffd = client_ctx(
        port, in_props={"tenant": "slow"},
        require_ack_response="true", ack_timeout="0.5")
    try:
        ctx_cli.push(ffd, json.dumps({"seq": 1, "pad": "y" * 200}))
        ctx_cli.flush_now()
        srv = ctx_srv.engine.inputs[0].plugin
        wait_for(lambda: srv.n_withheld_acks >= 1)
        assert srv.n_deferred_acks >= 1
        assert got == []  # nothing entered the engine
        # the client saw the timeout as a lost ack (will retry/spool)
        cli = ctx_cli.engine.outputs[0].plugin
        wait_for(lambda: cli.n_acks_lost >= 1)
    finally:
        ctx_cli.stop()
        ctx_srv.stop()


# ------------------------------------------- spool + heal replay


def test_partition_spools_then_replays_on_heal(tmp_path):
    """Every upstream down → the flush degrades to the fstore spool
    (OK, not RETRY); when the aggregator appears the replay task drains
    the spool with the ORIGINAL chunk-ids."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # reserved-then-released: nothing listens yet
    ctx_cli, ffd = client_ctx(
        port, require_ack_response="true", ack_timeout="0.5",
        storage_spool=str(tmp_path / "spool"))
    cli = ctx_cli.engine.outputs[0].plugin
    try:
        ctx_cli.push(ffd, json.dumps({"seq": 42}))
        ctx_cli.flush_now()
        wait_for(lambda: cli.n_spooled >= 1)
        assert cli._spool.pending()
        # the heal: an aggregator appears on the reserved port
        ctx_srv = flb.create(flush="50ms", grace="1",
                             **{"storage.path": str(tmp_path / "s")})
        ctx_srv.input("forward", tag="t", listen="127.0.0.1",
                      port=str(port))
        got = []
        ctx_srv.output("lib", match="*",
                       callback=lambda d, t: got.append((t, d)))
        ctx_srv.start()
        try:
            wait_for(lambda: cli.n_replayed >= 1, timeout=15)
            wait_for(lambda: events_of(got))
            assert [e.body["seq"] for _, e in events_of(got)] == [42]
            assert cli._spool.pending() == []
        finally:
            ctx_srv.stop()
    finally:
        ctx_cli.stop()


# ------------------------------------- satellite: metrics + health


def test_forward_metric_family_and_health_block(tmp_path):
    ctx_srv, port, got = collect_ctx(tmp_path)
    ctx_cli, ffd = client_ctx(port, require_ack_response="true")
    try:
        ctx_cli.push(ffd, json.dumps({"seq": 1}))
        ctx_cli.flush_now()
        wait_for(lambda: events_of(got))
        met_srv = ctx_srv.metrics.to_prometheus()
        met_cli = ctx_cli.metrics.to_prometheus()
        assert "fluentbit_forward_absorbed_chunks_total" in met_srv
        assert "fluentbit_forward_dedup_hits_total" in met_srv
        assert "fluentbit_forward_acks_waited_total" in met_cli
        assert "fluentbit_forward_ack_rtt_seconds" in met_cli
        assert "fluentbit_forward_breaker_state" in met_cli
        # /api/v1/health carries a "forward" block on both roles
        h_srv = ctx_srv.engine.guard.health()
        h_cli = ctx_cli.engine.guard.health()
        srv_block = next(iter(h_srv["forward"].values()))
        cli_block = next(iter(h_cli["forward"].values()))
        assert srv_block["role"] == "server"
        assert srv_block["absorbed"] >= 1
        assert cli_block["role"] == "client"
        assert cli_block["acks_waited"] >= 1
        assert "upstreams" in cli_block
    finally:
        ctx_cli.stop()
        ctx_srv.stop()


# ------------------------------------- satellite: site inventory


def test_new_failpoint_sites_pinned():
    """The five relay sites are registered in the inventory AND their
    literal names appear at fire() call sites in the forward plugin —
    a renamed/removed site must fail here, not silently stop firing."""
    new = ("forward.handshake", "forward.conn_reset",
           "forward.partial_write", "forward.dup_delivery",
           "forward.ack_drop")
    for name in new:
        assert name in failpoints.SITES, name
    src = open(os.path.join(
        REPO, "fluentbit_tpu", "plugins", "net_forward.py"),
        encoding="utf-8").read()
    for name in new:
        assert f'"{name}"' in src, f"{name} has no call site"
    assert len(set(failpoints.SITES)) == len(failpoints.SITES)


# --------------------------------------------------- the chaos soak


def test_relay_soak_tier1(tmp_path):
    """Tier-1 slice of the tentpole proof: one seed, small corpus —
    black-hole aggregator SIGKILLed, partition + heal, 35%-class edge
    faults; flux dumps bit-identical, ledger absorbs ≤ once."""
    art = soak.run_relay_scenario(str(tmp_path), records=24, tags=2,
                                  seed=1, settle=25.0)
    assert art["baseline"] == art["faulted"]
    assert art["ledger"] and all(c == 1 for c in art["ledger"].values())


@pytest.mark.slow
@pytest.mark.soak
class TestRelaySoakMatrix:
    @pytest.mark.parametrize("seed", [2, 3, 4])
    def test_seed(self, tmp_path, seed):
        art = soak.run_relay_scenario(str(tmp_path), records=48,
                                      tags=3, seed=seed, settle=35.0)
        assert art["baseline"] == art["faulted"]
        assert all(c == 1 for c in art["ledger"].values())
