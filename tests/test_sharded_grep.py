"""Multi-device grep: shard_map over the virtual 8-device CPU mesh.

Validates the SPMD path (batch-dim sharding + psum match counts) against
the single-device kernel, including the non-divisible-batch pad path.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jax.sharding import Mesh

from fluentbit_tpu.ops.batch import assemble
from fluentbit_tpu.ops.grep import program_for


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"need {n} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n]), ("batch",))


def _stage(patterns, vals, L=64, pad_to=None):
    prog = program_for(tuple(patterns), L)
    b = assemble(vals, L, pad_to)
    R = len(patterns)
    return prog, np.stack([b.batch] * R), np.stack([b.lengths] * R)


CORPUS = [
    b"GET /index.html 200",
    b"POST /api/v1 500",
    b"kernel: panic",
    b"",
    None,  # missing field row
    b"DELETE /x 404",
] * 7  # 42 rows — not divisible by 8


@pytest.mark.parametrize("n_dev", [2, 8])
def test_sharded_matches_single_device(n_dev):
    mesh = _mesh(n_dev)
    prog, batch, lengths = _stage(["GET|POST", "^kernel:", "50[0-9]$"], CORPUS)
    mask, counts, padded = prog.match_sharded(mesh, batch, lengths)
    ref = prog.match(batch, lengths)
    assert padded % n_dev == 0
    assert np.array_equal(mask, ref)
    assert np.array_equal(counts, ref.sum(axis=1))


def test_sharded_counts_are_global():
    mesh = _mesh(8)
    vals = [b"hit"] * 16 + [b"miss"] * 16
    prog, batch, lengths = _stage(["hit"], vals)
    _, counts, _ = prog.match_sharded(mesh, batch, lengths)
    assert counts.tolist() == [16]
