"""Device UTF-8 validation kernel, compression/crypto utils, and the
script extension-runtime filter.
"""

import json

import numpy as np
import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.ops.batch import assemble
from fluentbit_tpu.ops.utf8 import Utf8Validator, validate_bytes
from fluentbit_tpu import utils


# ------------------------------------------------------------------ utf8

GOOD = [
    b"plain ascii",
    "héllo wörld".encode(),
    "日本語テキスト".encode(),
    "🎉🚀 emoji".encode(),
    "\U0010FFFF".encode(),  # max code point
    b"",
]
BAD = [
    b"\x80midstream",            # lone continuation
    b"\xc0\xaf",                 # overlong '/'
    b"\xc1\xbf",                 # C1 always invalid
    b"\xe0\x80\x80",             # overlong 3-byte
    b"\xed\xa0\x80",             # UTF-16 surrogate D800
    b"\xf0\x80\x80\x80",         # overlong 4-byte
    b"\xf4\x90\x80\x80",         # > U+10FFFF
    b"\xf5\x80\x80\x80",         # F5 lead invalid
    b"truncated \xe6\x97",       # cut sequence
    b"\xff",
]


def test_cpu_oracle():
    for g in GOOD:
        assert validate_bytes(g), g
    for b in BAD:
        assert not validate_bytes(b), b


def test_device_kernel_matches_oracle():
    vals = GOOD + BAD
    staged = assemble(vals, 64)
    got = Utf8Validator().validate(staged.batch, staged.lengths)
    want = [validate_bytes(v) for v in vals]
    assert got.tolist() == want


def test_device_kernel_python_stdlib_differential():
    import random

    rng = random.Random(1)
    vals = []
    for _ in range(300):
        raw = bytes(rng.randrange(256) for _ in range(rng.randrange(24)))
        vals.append(raw)
    staged = assemble(vals, 32)
    got = Utf8Validator().validate(staged.batch, staged.lengths)
    for v, g in zip(vals, got):
        try:
            v.decode("utf-8")
            ok = True
        except UnicodeDecodeError:
            ok = False
        assert bool(g) == ok, v


# ----------------------------------------------------------------- utils

def test_compression_roundtrip_and_gates():
    data = b"payload " * 100
    algos = ["gzip", "zlib", "snappy"]
    from fluentbit_tpu.utils import lz4 as _lz4
    from fluentbit_tpu.utils import zstd as _zstd
    if _zstd.available():  # ctypes binding over the system libzstd
        algos.append("zstd")
    if _lz4.available():   # ctypes binding over the system liblz4
        algos.append("lz4")
    for algo in algos:
        assert utils.decompress(algo, utils.compress(algo, data)) == data
    with pytest.raises(utils.CompressionError):
        utils.compress("nope", data)
    with pytest.raises(utils.CompressionError):
        utils.decompress("lz4", b"not an lz4 frame")


def test_crypto_encoding():
    assert utils.digest("sha256", b"x").hex().startswith("2d711642")
    assert utils.hmac_sign("sha256", b"k", b"m")
    assert utils.base64_decode(utils.base64_encode(b"abc")) == b"abc"
    assert utils.uri_decode(utils.uri_encode("a b/c")) == "a b/c"
    assert utils.uri_field("/api/v1/metrics", 2) == "v1"
    assert utils.uri_field("/api", 9) is None
    assert utils.crc32(b"123456789") == 0xCBF43926  # CRC-32 check value


# ---------------------------------------------------------------- script

SCRIPT = """
def cb_filter(tag, ts, record):
    if record.get("drop"):
        return -1, ts, record
    if record.get("split"):
        return 1, ts, [{"part": 1}, {"part": 2}]
    if "n" in record:
        record["n2"] = record["n"] * 2
        return 1, ts, record
    return 0, ts, record
"""


def test_script_filter_contract(tmp_path):
    path = tmp_path / "cb.py"
    path.write_text(SCRIPT)
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("script", match="t", script=str(path))
    got = []
    ctx.output("lib", match="t", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"n": 21}))
        ctx.push(in_ffd, json.dumps({"drop": True}))
        ctx.push(in_ffd, json.dumps({"keep": "as-is"}))
        ctx.push(in_ffd, json.dumps({"split": True}))
        ctx.flush_now()
    finally:
        ctx.stop()
    bodies = [e.body for d in got for e in decode_events(d)]
    assert {"n": 21, "n2": 42} in bodies
    assert {"keep": "as-is"} in bodies
    assert {"part": 1} in bodies and {"part": 2} in bodies
    assert not any(b.get("drop") for b in bodies)


def test_script_inline_code_and_protected_mode():
    ctx = flb.create(flush="50ms", grace="1")
    in_ffd = ctx.input("lib", tag="t")
    ctx.filter("script", match="t",
               code="def cb_filter(tag, ts, r):\n    raise RuntimeError('x')")
    got = []
    ctx.output("lib", match="t", callback=lambda d, t: got.append(d))
    ctx.start()
    try:
        ctx.push(in_ffd, json.dumps({"survives": 1}))
        ctx.flush_now()
    finally:
        ctx.stop()
    bodies = [e.body for d in got for e in decode_events(d)]
    assert bodies == [{"survives": 1}]  # protected mode keeps the record


def test_wasm_requires_module_path():
    from fluentbit_tpu.core.plugin import registry

    ins = registry.create_filter("wasm")
    ins.configure()
    with pytest.raises(ValueError, match="wasm_path"):
        ins.plugin.init(ins, None)


def test_lz4_truncated_frame_rejected():
    from fluentbit_tpu.utils import lz4 as _lz4
    if not _lz4.available():
        pytest.skip("liblz4 absent")
    comp = utils.compress("lz4", b"payload " * 100)
    with pytest.raises(utils.CompressionError):
        utils.decompress("lz4", comp[:18])
    with pytest.raises(utils.CompressionError):
        utils.decompress("lz4", b"")
