"""zstd codec (ctypes binding over the system libzstd — the
src/flb_zstd.c role) + Content-Encoding paths through out_http and
the in_http server base."""

import time

import pytest

import fluentbit_tpu as flb
from fluentbit_tpu.codec.events import decode_events
from fluentbit_tpu.utils import CompressionError, compress, decompress
from fluentbit_tpu.utils import zstd as zstd_mod


pytestmark = pytest.mark.skipif(not zstd_mod.available(),
                                reason="libzstd not present")


def test_roundtrip_and_magic():
    data = b"the quick brown fox " * 500
    comp = compress("zstd", data)
    assert comp[:4] == b"\x28\xb5\x2f\xfd"  # zstd frame magic
    assert len(comp) < len(data)
    assert decompress("zstd", comp) == data


def test_empty_and_incompressible():
    assert decompress("zstd", compress("zstd", b"")) == b""
    import os
    blob = os.urandom(4096)
    assert decompress("zstd", compress("zstd", blob)) == blob


def test_bad_frame_rejected():
    with pytest.raises(CompressionError):
        decompress("zstd", b"not a zstd frame at all")


def test_content_size_limit():
    comp = compress("zstd", b"x" * 100000)
    with pytest.raises(ValueError):
        zstd_mod.decompress(comp, max_output=1024)


def test_out_http_zstd_body():
    from fluentbit_tpu.codec.events import encode_event
    from fluentbit_tpu.core.plugin import registry

    ins = registry.create_output("http")
    ins.set("format", "json")
    ins.set("compress", "zstd")
    ins.configure()
    ins.plugin.init(ins, None)
    body = ins.plugin._build(encode_event({"a": 1}, 5.0), "t")
    assert body[:4] == b"\x28\xb5\x2f\xfd"
    assert b'"a":1' in decompress("zstd", body)
    assert any("Content-Encoding: zstd" in h
               for h in ins.plugin._headers())


def test_in_http_accepts_zstd_and_gzip_bodies():
    import json
    import socket

    got = []
    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("http", tag="h", listen="127.0.0.1", port="0")
    in_ins = ctx.engine.inputs[-1]
    ctx.output("lib", match="*",
               callback=lambda d, tag: got.extend(decode_events(d)))
    ctx.start()
    try:
        deadline = time.time() + 5
        while in_ins.plugin.bound_port is None and \
                time.time() < deadline:
            time.sleep(0.02)
        port = in_ins.plugin.bound_port
        for algo in ("zstd", "gzip"):
            payload = compress(
                algo, json.dumps({"via": algo}).encode())
            s = socket.create_connection(("127.0.0.1", port), timeout=3)
            s.sendall((f"POST /t HTTP/1.1\r\nHost: x\r\n"
                       f"Content-Encoding: {algo}\r\n"
                       f"Content-Length: {len(payload)}\r\n"
                       "Connection: close\r\n\r\n").encode() + payload)
            resp = s.recv(4096)
            s.close()
            assert b" 201" in resp.split(b"\r\n", 1)[0]
        deadline = time.time() + 5
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        ctx.stop()
    assert sorted(ev.body["via"] for ev in got[:2]) == ["gzip", "zstd"]


def test_in_http_rejects_corrupt_encoding():
    import socket

    ctx = flb.create(flush="50ms", grace="1")
    ctx.input("http", tag="h", listen="127.0.0.1", port="0")
    in_ins = ctx.engine.inputs[-1]
    ctx.output("null", match="*")
    ctx.start()
    try:
        deadline = time.time() + 5
        while in_ins.plugin.bound_port is None and \
                time.time() < deadline:
            time.sleep(0.02)
        port = in_ins.plugin.bound_port
        s = socket.create_connection(("127.0.0.1", port), timeout=3)
        s.sendall(b"POST /t HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Encoding: zstd\r\n"
                  b"Content-Length: 7\r\n"
                  b"Connection: close\r\n\r\ngarbage")
        resp = s.recv(4096)
        s.close()
        assert b" 400" in resp.split(b"\r\n", 1)[0]
    finally:
        ctx.stop()
